"""Alignment-phase driver: batches CIGAR-less overlaps onto the device
banded global aligner, installs CIGARs, and lets the host finish whatever the
device rejects (too long / too divergent), mirroring the reference's
cudaaligner orchestration (/root/reference/src/cuda/cudapolisher.cpp:74-214,
rejection statuses src/cuda/cudaaligner.cpp:63-71).
"""

from __future__ import annotations

import sys
import time

from .. import config, obs


def _on_tpu() -> bool:
    import jax
    return jax.devices()[0].platform == "tpu"


def _engine() -> str:
    """Which aligner serves phase 1.

    Default 'auto': the Hirschberg engine (Pallas distance kernels +
    host-orchestrated splitting, O(band) memory — covers full-length
    reads) on a TPU backend, the host Myers aligner elsewhere — the same
    device-on-TPU posture as the consensus path (_use_pallas) and the
    reference, whose accelerator serves phase 1 whenever CUDA devices
    exist (/root/reference/src/cuda/cudapolisher.cpp:74-214). Explicit
    overrides: '0'/'host', 'hirschberg', '1'/'xla' (the moves-matrix
    kernel, small pairs only). A device-engine failure degrades to the
    host aligner for the remaining jobs (see run_alignment_phase).
    """
    env = config.get_str("RACON_TPU_DEVICE_ALIGNER")
    if env in ("auto", ""):
        return "hirschberg" if _on_tpu() else "host"
    if env in ("0", "host"):
        return "host"
    if env in ("1", "xla"):
        return "xla"
    if env == "hirschberg":
        return "hirschberg"
    print(f"[racon_tpu::align] WARNING: unknown RACON_TPU_DEVICE_ALIGNER="
          f"{env!r}; using the host aligner "
          f"(valid: auto, 0/host, 1/xla, hirschberg)", file=sys.stderr)
    return "host"


def run_alignment_phase(pipeline, progress: bool = False,
                        journal=None) -> dict:
    """Device alignment for every eligible CIGAR-less overlap; host for
    the rest.  Device failures run through the degradation lattice inside
    the engines' run_jobs (per-cohort retry, bisection-quarantine, engine
    death -> host for the remainder); already-installed CIGARs are kept
    and the served count survives a mid-phase engine failure.

    With `journal` armed, device-served CIGARs journaled by a previous
    run are replayed (and excluded from device batching — the native
    host pass skips any job whose CIGAR is already set), and fresh
    device results are journaled through a CigarTap as the engines
    install them.  Host-computed CIGARs are not journaled: the native
    engine recomputes them deterministically on resume.

    Returns stats {device:…, host:…, report: PhaseReport} — the report's
    per-tier served counts sum to the job count, clean or
    fault-injected."""
    from ..analysis import sanitize
    from ..resilience import faults
    from ..resilience import lattice as rl
    from ..resilience.journal import CigarTap, replay_cigars
    from ..resilience.report import PhaseReport

    report = PhaseReport("alignment", rl.ALIGN_TIERS + ("journal",))
    # guard_stats is a no-op passthrough unless RACON_TPU_SANITIZE=1.
    stats = sanitize.guard_stats({"device": 0, "host": 0, "report": report},
                                 "align_driver.run_alignment_phase")
    n = pipeline.num_align_jobs()
    report.total = n
    # Bulk-FFI lengths array, fetched ONCE and threaded through the cells
    # counter, per-engine eligibility, and the engines' own bucketing
    # (each used to refetch it independently).
    lengths = (pipeline.align_job_lengths()
               if n and hasattr(pipeline, "align_job_lengths") else None)
    if lengths is not None and obs.enabled():
        # Total need-band DP cells over ALL phase-1 jobs (host share
        # included) for the cost model (obs/costmodel.py): per pair,
        # max(n, m) rows x the 10%-rule band the aligner actually needs.
        import numpy as np

        L = np.asarray(lengths, dtype=np.int64)[:n]
        if L.size:
            mx = L.max(axis=1)
            need = np.abs(L[:, 1] - L[:, 0]) + mx // 10 + 2
            obs.count("align.cells.total", int((mx * need).sum()))
    replayed = replay_cigars(pipeline, journal, n, report)
    if n:
        # engine resolution inside the guard AND the try: with no align
        # jobs (SAM input) phase 1 must not touch the JAX backend at all,
        # and a backend-init failure under 'auto' must degrade to host,
        # not abort the polish.
        engine = "auto"
        try:
            engine = _engine()
            if engine == "host":
                pass
            elif engine == "hirschberg":
                faults.check("align.compile")
                from . import align_pallas

                # duck-typed pipelines without the lengths table raise
                # AttributeError here -> outer catch -> host degrade,
                # same as the per-engine fetch used to
                ln = (lengths if lengths is not None
                      else pipeline.align_job_lengths())
                jobs = [i for i in range(n) if i not in replayed
                        and align_pallas.band_for(int(ln[i, 0]),
                                                  int(ln[i, 1])) > 0]
                if jobs:
                    sink = (CigarTap(pipeline, journal, "hirschberg")
                            if journal is not None else pipeline)
                    # stats["device"] accumulates INSIDE run_jobs, per
                    # installed CIGAR: an exception escaping run_jobs
                    # after partial installs (kernel build, sanitizer,
                    # install failure) must not zero the device count —
                    # the host-served figure below is derived from it.
                    align_pallas.run_jobs(sink, jobs, report=report,
                                          stats=stats, lengths=ln)
            else:
                faults.check("align.compile")
                from . import align

                ln = (lengths if lengths is not None
                      else pipeline.align_job_lengths())
                jobs = [i for i in range(n) if i not in replayed
                        and align.device_eligible(ln[i, 0], ln[i, 1])]
                if jobs:
                    sink = (CigarTap(pipeline, journal, "xla")
                            if journal is not None else pipeline)
                    align.run_jobs(sink, jobs, report=report, stats=stats,
                                   lengths=ln)
        except Exception as e:  # noqa: BLE001 — engine/backend init
            print(f"[racon_tpu::align] WARNING: device aligner "
                  f"'{engine}' failed ({type(e).__name__}: {e}); "
                  f"finishing the alignment phase on the host",
                  file=sys.stderr)
            report.record_failure(engine, e)
            report.record_degrade(engine, "host", e)
    # Host finishes everything still CIGAR-less (device-rejected or
    # ineligible).
    t0 = time.perf_counter()
    with obs.span("align.host") as sp:
        pipeline.align_jobs_cpu()
        sp.set(jobs=n - stats["device"] - len(replayed))
    report.add_wall("host", time.perf_counter() - t0)
    stats["host"] = n - stats["device"] - len(replayed)
    report.record_served("host", stats["host"])
    return stats
