"""Alignment-phase driver: batches CIGAR-less overlaps onto the device
banded global aligner, installs CIGARs, and lets the host finish whatever the
device rejects (too long / too divergent), mirroring the reference's
cudaaligner orchestration (/root/reference/src/cuda/cudapolisher.cpp:74-214,
rejection statuses src/cuda/cudaaligner.cpp:63-71).
"""

from __future__ import annotations

import os

import numpy as np


def _use_device() -> bool:
    # Off by default: the host banded block-Myers aligner (bit-parallel,
    # ~64 cells/op) measures faster than the lane-per-cell device kernel for
    # this phase, on-chip included (58s vs ~1s on the lambda workload). The
    # device aligner remains available for experimentation and as the base
    # for a future wavefront kernel.
    return os.environ.get("RACON_TPU_DEVICE_ALIGNER", "0") == "1"


def run_alignment_phase(pipeline, progress: bool = False) -> dict:
    stats = {"device": 0, "host": 0}
    n = pipeline.num_align_jobs()
    if n and _use_device():
        from . import align

        lengths = pipeline.align_job_lengths()
        jobs = [i for i in range(n)
                if align.device_eligible(lengths[i, 0], lengths[i, 1])]
        if jobs:
            stats["device"] = align.run_jobs(pipeline, jobs)
    # Host finishes everything still CIGAR-less (device-rejected or
    # ineligible).
    pipeline.align_jobs_cpu()
    stats["host"] = n - stats["device"]
    return stats
