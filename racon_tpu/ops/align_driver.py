"""Alignment-phase driver: batches CIGAR-less overlaps onto the device
banded global aligner, installs CIGARs, and lets the host finish whatever the
device rejects (too long / too divergent), mirroring the reference's
cudaaligner orchestration (/root/reference/src/cuda/cudapolisher.cpp:74-214,
rejection statuses src/cuda/cudaaligner.cpp:63-71).
"""

from __future__ import annotations

import os
import sys


def _on_tpu() -> bool:
    import jax
    return jax.devices()[0].platform == "tpu"


def _engine() -> str:
    """Which aligner serves phase 1.

    Default 'auto': the Hirschberg engine (Pallas distance kernels +
    host-orchestrated splitting, O(band) memory — covers full-length
    reads) on a TPU backend, the host Myers aligner elsewhere — the same
    device-on-TPU posture as the consensus path (_use_pallas) and the
    reference, whose accelerator serves phase 1 whenever CUDA devices
    exist (/root/reference/src/cuda/cudapolisher.cpp:74-214). Explicit
    overrides: '0'/'host', 'hirschberg', '1'/'xla' (the moves-matrix
    kernel, small pairs only). A device-engine failure degrades to the
    host aligner for the remaining jobs (see run_alignment_phase).
    """
    env = os.environ.get("RACON_TPU_DEVICE_ALIGNER", "auto")
    if env in ("auto", ""):
        return "hirschberg" if _on_tpu() else "host"
    if env in ("0", "host"):
        return "host"
    if env in ("1", "xla"):
        return "xla"
    if env == "hirschberg":
        return "hirschberg"
    print(f"[racon_tpu::align] WARNING: unknown RACON_TPU_DEVICE_ALIGNER="
          f"{env!r}; using the host aligner "
          f"(valid: auto, 0/host, 1/xla, hirschberg)", file=sys.stderr)
    return "host"


def run_alignment_phase(pipeline, progress: bool = False) -> dict:
    """Device alignment for every eligible CIGAR-less overlap; host for
    the rest. Any device-engine failure (Mosaic compile/runtime) degrades
    to the host aligner for the remaining jobs — the phase-1 analogue of
    the consensus driver's kernel-tier lattice; already-installed CIGARs
    are kept."""
    stats = {"device": 0, "host": 0}
    n = pipeline.num_align_jobs()
    if n:
        # engine resolution inside the guard AND the try: with no align
        # jobs (SAM input) phase 1 must not touch the JAX backend at all,
        # and a backend-init failure under 'auto' must degrade to host,
        # not abort the polish.
        engine = "auto"
        try:
            engine = _engine()
            if engine == "host":
                pass
            elif engine == "hirschberg":
                from . import align_pallas

                lengths = pipeline.align_job_lengths()
                jobs = [i for i in range(n)
                        if align_pallas.band_for(int(lengths[i, 0]),
                                                 int(lengths[i, 1])) > 0]
                if jobs:
                    stats["device"] = align_pallas.run_jobs(pipeline, jobs)
            else:
                from . import align

                lengths = pipeline.align_job_lengths()
                jobs = [i for i in range(n)
                        if align.device_eligible(lengths[i, 0],
                                                 lengths[i, 1])]
                if jobs:
                    stats["device"] = align.run_jobs(pipeline, jobs)
        except Exception as e:  # noqa: BLE001
            print(f"[racon_tpu::align] WARNING: device aligner "
                  f"'{engine}' failed ({type(e).__name__}: {e}); "
                  f"finishing the alignment phase on the host",
                  file=sys.stderr)
    # Host finishes everything still CIGAR-less (device-rejected or
    # ineligible).
    pipeline.align_jobs_cpu()
    stats["host"] = n - stats["device"]
    return stats
