"""Alignment-phase driver: batches CIGAR-less overlaps onto the device
banded global aligner, installs CIGARs, and lets the host finish whatever the
device rejects (too long / too divergent), mirroring the reference's
cudaaligner orchestration (/root/reference/src/cuda/cudapolisher.cpp:74-214,
rejection statuses src/cuda/cudaaligner.cpp:63-71).
"""

from __future__ import annotations

import os

import numpy as np


def _engine() -> str:
    """Which aligner serves phase 1: 'host' (default), 'hirschberg'
    (Pallas distance kernels + host-orchestrated splitting — covers
    full-length reads in O(band) memory), or 'xla' (the moves-matrix
    kernel, small pairs only).

    Host stays the default until the Pallas engine has an on-hardware win
    recorded (docs/benchmarks.md); the reference makes the same call the
    other way because its GPU aligner is proven
    (/root/reference/src/cuda/cudapolisher.cpp:74-214).
    """
    env = os.environ.get("RACON_TPU_DEVICE_ALIGNER", "0")
    if env in ("0", ""):
        return "host"
    if env in ("1", "xla"):
        return "xla"
    if env == "hirschberg":
        return "hirschberg"
    import sys
    print(f"[racon_tpu::align] WARNING: unknown RACON_TPU_DEVICE_ALIGNER="
          f"{env!r}; using the host aligner (valid: 0, 1/xla, hirschberg)",
          file=sys.stderr)
    return "host"


def run_alignment_phase(pipeline, progress: bool = False) -> dict:
    stats = {"device": 0, "host": 0}
    n = pipeline.num_align_jobs()
    engine = _engine()
    if n and engine != "host":
        if engine == "hirschberg":
            from . import align_pallas

            lengths = pipeline.align_job_lengths()
            jobs = [i for i in range(n)
                    if align_pallas.band_for(int(lengths[i, 0]),
                                             int(lengths[i, 1])) > 0]
            if jobs:
                stats["device"] = align_pallas.run_jobs(pipeline, jobs)
        else:
            from . import align

            lengths = pipeline.align_job_lengths()
            jobs = [i for i in range(n)
                    if align.device_eligible(lengths[i, 0], lengths[i, 1])]
            if jobs:
                stats["device"] = align.run_jobs(pipeline, jobs)
    # Host finishes everything still CIGAR-less (device-rejected or
    # ineligible).
    pipeline.align_jobs_cpu()
    stats["host"] = n - stats["device"]
    return stats
