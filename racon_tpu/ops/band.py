"""Banded-DP subsystem: Ukkonen band planning + the verify-and-widen
ladder shared by all three hot DP kernels.

The reference racon gets most of its aligner speed from edlib's
score-bounded banded DP, and the window pipeline pre-localizes every
read segment to its window — the optimal path hugs the backbone
diagonal, so a band of width ``w₀ = |m - n| + slack`` contains it almost
always.  This module owns the machinery that makes banding *safe*:

* **band plan** — per-job initial band width from the length delta plus
  the ``RACON_TPU_BAND_SLACK`` knob, bucketed to the kernels' lane
  grids (``BAND_BUCKETS``);
* **exact verify (aligner)** — for the unit-cost Hirschberg engine the
  Ukkonen bound is exact: with the band placed symmetrically around the
  main-diagonal corridor, any path that leaves the band costs at least
  ``|m - n| + 2·(min_pad + 1)`` edits, so a banded terminal distance
  ``D <= |m - n| + 2·min_pad`` proves every optimal AND co-optimal path
  stays strictly inside the band — midpoints, tie-breaks and traceback
  coincide with the flat kernel's, i.e. the banded CIGAR is
  byte-identical (``ukkonen_ok``);
* **composite hit signal (POA)** — sequence-to-graph scoring has no
  clean unit-cost bound (graph jump edges move the diagonal for free),
  so the banded POA kernels emit a conservative ``band_hit`` flag:
  the optimum touched the band boundary, or the terminal score's
  deficit exceeds the gap-cost bound for the band width (the kernels
  compute it; ``poa_deficit_bound`` is the host-side mirror);
* **widening ladder** — a hit job is re-dispatched at ``2w`` up to
  ``RACON_TPU_BAND_MAX_WIDENINGS`` times, then falls back to the flat
  kernel through the ``banded -> flat`` lattice edge
  (``record_band_fallback``) — the flat kernel IS today's oracle, so
  the ladder's floor never changes output.

Counters (racon_tpu.obs): ``band.jobs`` (banded dispatches),
``band.hits``, ``band.widenings``, ``band.fallbacks`` — bench.py derives
``band_hit_rate`` from them and ``cells_banded`` from the
``align.cells.banded`` / ``poa.cells.banded`` cell counters.
"""

from __future__ import annotations

from .. import config, obs

#: Band buckets the banded kernels compile under.  128 is the TPU lane
#: width — the narrowest band a lane-parallel DP row can iterate — and
#: the wider rungs coincide with the flat aligner's BANDS so the ladder
#: tops out exactly where the flat kernel starts.
BAND_BUCKETS = (128, 256, 512, 1024, 2048)


class Hit:
    """Sentinel result for a banded attempt whose verify failed: the
    optimum touched the band boundary or the terminal score exceeded
    the Ukkonen bound.  Flows through the lattice like any opaque
    result; install() treats it as 'not served yet — widen'."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debugging aid
        return "<band.Hit>"


HIT = Hit()


def enabled() -> bool:
    """Banded DP master switch (RACON_TPU_BAND, default off)."""
    return config.get_bool("RACON_TPU_BAND")


def slack() -> int:
    """Extra half-band beyond the length delta (RACON_TPU_BAND_SLACK)."""
    return max(0, config.get_int("RACON_TPU_BAND_SLACK"))


def max_widenings() -> int:
    """Bounded doublings before the flat fallback."""
    return max(0, config.get_int("RACON_TPU_BAND_MAX_WIDENINGS"))


def initial_width(n: int, m: int) -> int:
    """w₀: the length delta plus the slack knob."""
    return abs(m - n) + slack()


def bucket_for(width: int):
    """Smallest band bucket covering `width`; None = no bucket (flat)."""
    for b in BAND_BUCKETS:
        if width <= b:
            return b
    return None


def plan_align_band(n: int, m: int, flat_k: int, widenings: int = 0):
    """Banded K for an aligner job after `widenings` doublings, or None
    when banding cannot beat the flat band (ladder exhausted, w₀ already
    at or beyond the flat bucket, or job not device-eligible)."""
    if flat_k <= 0 or flat_k <= BAND_BUCKETS[0]:
        return None  # flat band is already minimal
    k = bucket_for(initial_width(n, m) << widenings)
    return k if k is not None and k < flat_k else None


def ukkonen_ok(n: int, m: int, k: int, gdmin: int, dist) -> bool:
    """Exact in-band certificate for the unit-cost aligner.

    The band covers global diagonals ``[gdmin, gdmin + k - 1]``; the
    optimal corridor spans diagonals ``[min(0, m-n), max(0, m-n)]``.
    ``min_pad`` is the narrower margin between corridor and band edge.
    A path using a diagonal outside the band costs at least
    ``|m - n| + 2*(min_pad + 1)`` edits (each extra diagonal excursion
    costs one insertion AND one deletion), so ``dist <= |m-n| +
    2*min_pad`` proves strict in-band optimality — including every
    co-optimal path, hence identical midpoint argmin tie-breaks and an
    identical traceback vs the flat kernel."""
    if dist is None:
        return False
    pad_low = min(0, m - n) - gdmin
    pad_high = (gdmin + k - 1) - max(0, m - n)
    min_pad = min(pad_low, pad_high)
    if min_pad < 0:
        return False  # band misplaced: corridor not covered
    return dist <= abs(m - n) + 2 * min_pad


def poa_deficit_bound(gap: int, w: int) -> int:
    """Host-side mirror of the banded POA kernels' score-deficit bound:
    a path that strays more than ``w`` columns off the backbone diagonal
    pays at least ``2 * |gap| * (w // 2)`` in gap penalties over the
    in-band alternative (the other half of the band absorbs legitimate
    drift).  Terminal deficit above this => band_hit."""
    return 2 * abs(gap) * max(1, w // 2)


class BandState:
    """Per-job ladder state threaded through an executor ops object."""

    __slots__ = ("k", "widenings", "pending", "exhausted")

    def __init__(self, k):
        self.k = k                # current banded bucket; None = flat
        self.widenings = 0
        self.pending = False      # hit recorded, awaiting re-dispatch
        self.exhausted = False    # fell back to the flat kernel

    def widen(self, n: int, m: int, flat_k: int, report=None,
              tier: str = "banded", cells_counter: str = None) -> None:
        """Advance the ladder after a hit: double (bounded), else flat."""
        obs.count("band.hits")
        self.pending = True
        if self.widenings < max_widenings():
            self.widenings += 1
            nxt = plan_align_band(n, m, flat_k, self.widenings)
            if nxt is not None and nxt > self.k:
                self.k = nxt
                obs.count("band.widenings")
                if cells_counter and obs.enabled():
                    obs.count(cells_counter, 2 * max(n, m) * self.k)
                return
        # ladder exhausted (or next rung >= flat): the banded -> flat
        # lattice edge
        self.k = None
        self.exhausted = True
        record_band_fallback(report, tier)

    def widen_width(self, cap: int, report=None,
                    tier: str = "banded") -> None:
        """POA flavor of `widen`: `k` is a runtime half-band width (not a
        compiled bucket — the banded POA kernels take it as data), so the
        ladder is a plain bounded doubling, with the flat kernel
        (wband = 0 through the same compiled build) as the floor."""
        obs.count("band.hits")
        self.pending = True
        if (self.widenings < max_widenings() and self.k
                and 2 * self.k < cap):
            self.widenings += 1
            self.k *= 2
            obs.count("band.widenings")
            return
        self.k = None
        self.exhausted = True
        record_band_fallback(report, tier)


def record_band_fallback(report, tier: str, cause=None) -> None:
    """The ``banded -> flat`` lattice edge (resilience/lattice.py owns
    the canonical recorder; re-exported here so kernel-side callers
    don't import the lattice)."""
    from ..resilience.lattice import record_band_fallback as _rec

    _rec(report, tier, cause)
