"""Consensus-phase driver: packs windows into padded, depth-bucketed device
batches, runs the batched POA kernel, trims and installs results, and
re-runs anything the device rejected on the host POA engine.

Mirrors the reference's CUDA polish orchestration
(/root/reference/src/cuda/cudapolisher.cpp:216-378): depth cap per window
(MAX_DEPTH_PER_WINDOW=200, :226), per-entry rejection of oversized layers
(cudabatch.cpp:141-160), failed windows re-polished on the host
(:354-378), and the host-side trim identical to the CPU path
(cudabatch.cpp:230-256).
"""

from __future__ import annotations

import functools
import os
import sys
from collections import deque
from typing import List

import numpy as np

from . import poa
from .encoding import decode, encode

DEPTH_CAP = 200                    # reference: MAX_DEPTH_PER_WINDOW
DEPTH_BUCKETS = (8, 32, DEPTH_CAP)


def _pipeline_depth() -> int:
    """How many packed chunks may be in flight on the device at once."""
    return max(1, int(os.environ.get("RACON_TPU_PIPELINE_DEPTH", "2")))


def _batch_size() -> int:
    env = os.environ.get("RACON_TPU_BATCH_WINDOWS")
    if env:
        return max(1, int(env))
    import jax
    return 64 if jax.devices()[0].platform == "tpu" else 4


def _kernel_kind() -> str:
    """Which fused Pallas kernel serves consensus batches.

    'ls' (default) — v3 lane-lockstep, 8 windows per program
    (poa_pallas_ls.py); 'v2' — one window per program (poa_pallas.py).
    Either degrades v2 -> XLA (and ls -> v2 -> XLA) through the same
    lattice on Mosaic failure.
    """
    k = os.environ.get("RACON_TPU_POA_KERNEL", "ls")
    if k not in ("ls", "v2"):
        raise ValueError(
            f"RACON_TPU_POA_KERNEL must be 'ls' or 'v2', got {k!r}")
    return k


def _device_batch(n_dev: int, kind: str) -> int:
    """Batch size divisible over the mesh; the lockstep kernel additionally
    needs the per-device batch to be a multiple of its sublane group G."""
    from ..parallel.mesh import divisible_batch

    B = divisible_batch(n_dev, _batch_size())
    if kind == "ls":
        from .poa_pallas_ls import G
        q = G * n_dev
        B = max(1, (B + q - 1) // q) * q
    return B


def _node_factor() -> int:
    """max_nodes = factor * window_length. The default 3 matches the
    geometry every recorded pin was measured under; repeat-dense windows
    (4 of λ's 96) overflow it and fall back to the host, so hw_session
    measures factor 4 (VMEM fits per docs/roadmap.md) for a same-session
    pin refresh — the reference's per-entry capacity rejection is the
    analogous knob (/root/reference/src/cuda/cudabatch.cpp:141-160)."""
    return max(1, int(os.environ.get("RACON_TPU_NODE_FACTOR", "3")))


def window_class(bb_len: int) -> int:
    """Kernel-geometry class for a backbone length: ceil to the 128-lane
    grid. Windows bucket by (depth, class) so one long-window target in a
    mixed run no longer inflates every bucket's geometry — short windows
    pay their own class's DP ranges, not the global maximum's."""
    return max(128, (bb_len + 127) // 128 * 128)


def make_config(window_length: int, depth: int, match: int, mismatch: int,
                gap: int) -> poa.PoaConfig:
    def ceil128(x):
        return (x + 127) // 128 * 128

    max_backbone = ceil128(window_length)
    max_len = ceil128(window_length + window_length // 2)
    max_nodes = ceil128(_node_factor() * window_length)
    return poa.PoaConfig(max_nodes=max_nodes, max_len=max_len,
                         max_backbone=max_backbone, max_edges=12,
                         depth=depth, match=match, mismatch=mismatch,
                         gap=gap)


def tgs_trim(codes: np.ndarray, cov: np.ndarray, n_seqs: int):
    """Low-coverage end trim (reference: src/window.cpp:125-146)."""
    avg = (n_seqs - 1) // 2
    n = len(codes)
    begin = 0
    while begin < n and cov[begin] < avg:
        begin += 1
    end = n - 1
    while end >= 0 and cov[end] < avg:
        end -= 1
    if begin >= end:
        return codes  # chimeric suspicion: keep untrimmed
    return codes[begin:end + 1]


def run_consensus_phase(pipeline, *, match: int, mismatch: int, gap: int,
                        trim: bool, progress: bool = False) -> dict:
    """Device consensus for every eligible window; host for the rest.

    Streaming: a cheap metadata pass (window_info — no bases copied) sizes
    the geometry and buckets windows by depth; window bases are exported
    chunk-by-chunk at pack time, so driver memory is O(batch). Packing of
    chunk N+1 overlaps device execution of chunk N through JAX async
    dispatch — the analogue of the reference's greedy batch fill running
    concurrently with kernel execution
    (/root/reference/src/cuda/cudapolisher.cpp:83-145).

    Returns stats {device:…, host_fallback:…, backbone:…}.
    """
    n = pipeline.num_windows()
    stats = {"device": 0, "host_fallback": 0, "backbone": 0, "failed": 0}

    fallback: List[int] = []

    # Metadata pass: geometry + depth buckets, no layer bytes touched.
    jobs = []          # (window_idx, estimated depth, backbone len)
    for i in range(n):
        n_seqs, bb_len, _rank, _is_tgs, _bytes, _tid = pipeline.window_info(i)
        k = n_seqs - 1
        if k < 2:
            # <3 sequences incl. backbone: backbone passthrough
            # (reference: src/window.cpp:68-71)
            wx = pipeline.export_window(i)
            pipeline.set_consensus(i, wx.backbone.tobytes(), False)
            stats["backbone"] += 1
            continue
        jobs.append((i, min(k, DEPTH_CAP), bb_len))

    if jobs:
        n_dev = _n_devices()
        kind = _kernel_kind()
        B = _device_batch(n_dev, kind)
        use_pallas = _use_pallas()
        # Bucket by (depth, backbone class) to bound padding waste in BOTH
        # dims: layers dropped at pack time (oversized/empty) only shrink
        # a window's true depth, so a window always fits the bucket its
        # estimate chose; and short windows run in their own 128-grid
        # geometry class instead of the dataset-max geometry (one long
        # target in a mixed run used to inflate every bucket's DP ranges).
        buckets = {}
        for i, depth, bb in jobs:
            bucket = next(b for b in DEPTH_BUCKETS if depth <= b)
            buckets.setdefault((bucket, window_class(bb)),
                               []).append((i, depth, bb))

        # In-flight chunks: (chunk, packed, outs, cfg, pallas, kind).
        # JAX dispatch is async, so with depth Q the host packs/exports
        # chunks N+1..N+Q while chunk N executes — the analogue of the
        # reference's continuous batch fill running concurrently with
        # kernel execution (cudapolisher.cpp:83-145). Depth >= 2 keeps the
        # device busy across the host's pack gap even when pack time
        # fluctuates; more mostly adds host memory (Q packed batches).
        pending = deque()
        q_depth = _pipeline_depth()
        # geometries (cfg, kind) whose pallas kernel already failed —
        # seeded from warm-up failures so the measured run never retries
        # a kernel the warm-up proved dead
        dead_geoms = set(_WARM_DEAD)
        for (depth_bucket, wl_class), bucket_jobs in sorted(buckets.items()):
            cfg = make_config(wl_class, depth_bucket, match, mismatch, gap)
            # Large window geometries (e.g. -w 1000) overflow the fused
            # kernel's VMEM budget; the flag must flip HERE so _submit and
            # _unpack agree with the kernel _build_kernel actually returns.
            bucket_pallas, bucket_kind = _pick_tier(cfg, use_pallas, kind)
            # (Per-bucket depth is kept deliberately: the fused kernel's
            # VMEM footprint is depth-independent now, but packing and
            # host->device transfer scale with the padded depth — a single
            # DEPTH_CAP geometry would ship ~25x zeros for the shallow
            # buckets on every chunk to save compiles that the lru +
            # persistent compilation caches already amortize.)
            kernel = _build_kernel(cfg, B, bucket_pallas, bucket_kind)
            # Sequential loops run lock-step across the batch, so keep
            # batches depth-homogeneous — and length-homogeneous within
            # equal depth: a lockstep program's DP range is the union
            # over its 8 windows, so mixing a short window into a long
            # group bills it the long group's ranks.
            bucket_jobs.sort(key=lambda job: (job[1], job[2]))
            for off in range(0, len(bucket_jobs), B):
                while bucket_pallas and (cfg, bucket_kind) in dead_geoms:
                    # an earlier chunk (or the warm-up) proved this tier
                    # dead for this geometry: step down before dispatching
                    bucket_pallas, kernel, bucket_kind = _step_down(
                        cfg, B, bucket_kind, dead_geoms)
                idxs = [i for i, _, _ in bucket_jobs[off:off + B]]
                # Always pad to B: a dataset-size-dependent final-chunk
                # shape would force an extra jit compile per distinct
                # remainder (padded windows are 1-base/0-layer — free).
                pad = B
                chunk = _export_chunk(pipeline, idxs, cfg, fallback)
                if not chunk:
                    continue
                packed = _pack(chunk, cfg, pad)
                while True:
                    try:
                        outs = _submit(kernel, packed, bucket_pallas)
                        break
                    except Exception as e:  # noqa: BLE001
                        if not bucket_pallas:
                            raise
                        dead_geoms.add((cfg, bucket_kind))
                        bucket_pallas, kernel, bucket_kind = _degrade(
                            e, cfg, B, bucket_kind, dead_geoms)
                pending.append((chunk, packed, outs, cfg, bucket_pallas,
                                bucket_kind))
                if len(pending) >= q_depth:
                    _drain(pipeline, pending.popleft(), trim, stats,
                           fallback, B, dead_geoms)
            if progress:
                print(f"[racon_tpu::poa] bucket depth<={depth_bucket} "
                      f"len<={wl_class}: {len(bucket_jobs)} windows",
                      file=sys.stderr)
        while pending:
            _drain(pipeline, pending.popleft(), trim, stats, fallback, B,
                   dead_geoms)

    for i in fallback:
        pipeline.consensus_cpu_one(i)
        stats["host_fallback"] += 1

    return stats


# (cfg, kind) pairs whose pallas kernel failed during warm-up; consulted by
# run_consensus_phase so the measured run dispatches straight to the tier
# the warm-up landed on instead of re-paying a compile-and-fail.
_WARM_DEAD: set = set()


def warm_geometries(window_lengths, match: int, mismatch: int,
                    gap: int) -> None:
    """Compile (or load from the persistent cache) every kernel geometry
    the consensus phase can pick for these window lengths (an int or an
    iterable of observed backbone lengths — each maps to its 128-grid
    class, exactly as run_consensus_phase buckets them).

    One all-padding batch per (depth bucket, class) runs in milliseconds
    but forces the full compile — so a benchmark's measured pass never
    pays compile time, whatever depth/length mix the real dataset
    produces. Tiers that fail here are recorded in _WARM_DEAD so the
    measured run skips them."""
    if isinstance(window_lengths, int):
        window_lengths = [window_lengths]
    classes = sorted({window_class(max(w, 1)) for w in window_lengths})
    n_dev = _n_devices()
    kind = _kernel_kind()
    B = _device_batch(n_dev, kind)
    use_pallas = _use_pallas()
    import itertools
    for depth_bucket, wl_class in itertools.product(DEPTH_BUCKETS, classes):
        cfg = make_config(wl_class, depth_bucket, match, mismatch, gap)
        bucket_pallas, bucket_kind = _pick_tier(cfg, use_pallas, kind)
        kernel = _build_kernel(cfg, B, bucket_pallas, bucket_kind)
        packed = _pack([], cfg, B)
        while True:
            try:
                _unpack(_submit(kernel, packed, bucket_pallas),
                        bucket_pallas)
                break
            except Exception as e:  # noqa: BLE001
                # same degrade philosophy as run_consensus_phase: a Mosaic
                # failure on one geometry must not abort the caller — warm
                # the tier it will actually fall back to, and remember the
                # failure so the measured run doesn't retry it
                if not bucket_pallas:
                    raise
                _WARM_DEAD.add((cfg, bucket_kind))
                bucket_pallas, kernel, bucket_kind = _degrade(
                    e, cfg, B, bucket_kind, _WARM_DEAD)


def _pick_tier(cfg, use_pallas: bool, kind: str):
    """(bucket_pallas, bucket_kind) after VMEM-fit checks: the requested
    pallas tier if it fits, else the next tier down."""
    if not use_pallas:
        return False, kind
    if _fits_vmem(cfg, kind):
        return True, kind
    if kind == "ls" and _fits_vmem(cfg, "v2"):
        return True, "v2"
    return False, kind


def _step_down(cfg, B, kind, dead_geoms=()):
    """Next LIVE tier below (pallas `kind`) for this geometry:
    ls -> v2 (if it fits and isn't already proven dead) -> XLA.
    Returns (use_pallas, kernel, kind)."""
    if (kind == "ls" and _fits_vmem(cfg, "v2")
            and (cfg, "v2") not in dead_geoms):
        return True, _build_kernel(cfg, B, True, "v2"), "v2"
    return False, _build_kernel(cfg, B, False, kind), kind


def _degrade(e, cfg, B, kind, dead_geoms=()):
    """Mosaic compile/runtime failure: fall back to the next live kernel
    tier for this geometry (same philosophy as the per-window host
    fallback). Tiers already in dead_geoms are skipped so a drain-time ls
    failure doesn't pay a doomed submit through an already-dead v2."""
    use_p, kernel, new_kind = _step_down(cfg, B, kind, dead_geoms)
    tier = f"pallas '{new_kind}'" if use_p else "XLA"
    print("[racon_tpu::poa] WARNING: pallas kernel failed "
          f"({type(e).__name__}: {e}); falling back to the {tier} kernel",
          file=sys.stderr)
    return use_p, kernel, new_kind


def _drain(pipeline, pending, trim, stats, fallback, B, dead_geoms):
    """Block on an in-flight chunk's device results and install them.

    If the pallas kernel failed at runtime (error surfaces at the blocking
    transfer), re-run the chunk through the next tier down — the packed
    arrays are still on hand, so no re-export is needed — and mark the
    geometry dead so the bucket loop stops dispatching through the broken
    kernel.
    """
    chunk, packed, outs, cfg, was_pallas, kind = pending
    kernel = None
    while True:
        try:
            if outs is None:
                outs = _submit(kernel, packed, was_pallas)
            results = _unpack(outs, was_pallas)
            break
        except Exception as e:  # noqa: BLE001
            if not was_pallas:
                raise
            dead_geoms.add((cfg, kind))
            was_pallas, kernel, kind = _degrade(e, cfg, B, kind, dead_geoms)
            outs = None  # re-submit inside the try: a synchronous failure
            # of the intermediate v2 tier must also degrade, not escape
    _install(pipeline, chunk, results, trim, stats, fallback)


def _use_pallas() -> bool:
    env = os.environ.get("RACON_TPU_PALLAS")
    if env is not None:
        return env == "1"
    import jax
    return jax.devices()[0].platform == "tpu"


def _n_devices() -> int:
    import jax
    return len(jax.devices())


def _fits_vmem(cfg, kind: str = "v2", budget_bytes: int = 14 << 20) -> bool:
    """Whether the fused Pallas kernel's VMEM scratch fits the core budget.

    v2 mirrors poa_pallas.py's blocked layout: layer arrays live in HBM
    and stream through two DMA slots, so depth does not appear. ls mirrors
    poa_pallas_ls.py's scratch_shapes: a 128-row H ring instead of the full
    H matrix, plus rank-space graph arrays and per-layer DMA slots.
    """
    if kind == "ls":
        from .poa_pallas_ls import G, RING, _round_up

        NC = cfg.max_nodes // 128
        JC = _round_up(cfg.max_len + 1, 128) // 128
        lane_bytes = G * 128 * 4
        ring = RING * JC * lane_bytes
        j_rows = (1 + 2 + 2 * 2) * JC * lane_bytes   # H0, nkey/runrem, scr
        n_rows = (9 + 2 * cfg.max_edges) * NC * lane_bytes
        io = 4 * NC * lane_bytes                      # bb/bbw in, cons out
        return ring + j_rows + n_rows + io < budget_bytes
    from .poa_pallas import blocked_width

    jw8 = 8 * blocked_width(cfg.max_len + 1)
    nw8 = 8 * blocked_width(cfg.max_nodes)
    h = (cfg.max_nodes + 1) * jw8 * 4
    mv = (cfg.max_nodes + 1) * jw8 * 4  # move records, i32 (Mosaic tiling)
    layer_slots = 2 * 2 * jw8 * 4       # double-buffered seq + weight rows
    graph = nw8 * (10 * 4 + 2 * cfg.max_edges * 4)
    return h + mv + layer_slots + graph < budget_bytes


def _build_kernel(cfg, B, use_pallas, kind: str = "v2"):
    """Memoization front for _build_kernel_cached: the XLA twin ignores
    `kind`, so normalize it out of the key — a warm-up that degraded to
    the twin under 'v2' must hit the same cache entry as a measured-run
    request arriving via the 'ls' step-down (and as __graft_entry__'s
    default-argument call)."""
    if not use_pallas:
        kind = "xla"
    return _build_kernel_cached(cfg, B, use_pallas, kind)


@functools.lru_cache(maxsize=64)
def _build_kernel_cached(cfg, B, use_pallas, kind):
    """Single- or multi-device kernel for a B-window batch.

    Multi-device: batch dim sharded over the 1-D `windows` mesh — the
    production analogue of the reference's multi-GPU batch striping
    (src/cuda/cudapolisher.cpp:228-240), with no collectives.

    Memoized on the full geometry key: the warm-up's compiled kernel IS
    the measured run's function object, so the in-process jit cache hits
    even when the persistent disk cache can't serve (observed: AOT
    entries compiled under different machine features fail to load and
    silently recompile — minutes per geometry on the CPU twin).
    """
    import jax

    n_dev = _n_devices()
    assert not (use_pallas and not _fits_vmem(cfg, kind)), (
        "caller must check _fits_vmem before requesting the pallas kernel")
    if use_pallas:
        if kind == "ls":
            from .poa_pallas_ls import build_lockstep_poa_kernel as build
        else:
            from .poa_pallas import build_pallas_poa_kernel as build
        interp = jax.devices()[0].platform != "tpu"
        if n_dev == 1:
            return build(cfg, interpret=interp)(B)
        from ..parallel.mesh import shard_batch_build
        sharded = shard_batch_build(
            lambda b: build(cfg, interpret=interp)(b), B, 9, 5)
        assert sharded is not None, (B, n_dev)  # _device_batch divides B
        return sharded
    kernel = poa.build_poa_kernel(cfg)
    if n_dev == 1:
        return kernel
    from ..parallel.mesh import device_mesh, shard_batch_kernel
    return shard_batch_kernel(kernel, device_mesh(), 9)


def _export_chunk(pipeline, idxs, cfg, fallback):
    """Export window bases for one chunk; apply per-layer admission.

    Returns [(window_idx, export, kept layer indices)] — windows the device
    can't represent go straight to the host fallback list.
    """
    chunk = []
    for i in idxs:
        wx = pipeline.export_window(i)
        k = len(wx.lens)
        keep = [j for j in range(k) if 0 < wx.lens[j] <= cfg.max_len]
        if len(keep) < len(wx.lens[:DEPTH_CAP]) and len(keep) < 2:
            fallback.append(i)
            continue
        chunk.append((i, wx, keep[:DEPTH_CAP]))
    return chunk


def _pack(chunk, cfg, pad_to=None):
    B = pad_to if pad_to is not None else len(chunk)
    bb = np.zeros((B, cfg.max_backbone), dtype=np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), dtype=np.int32)
    bb_len = np.ones(B, dtype=np.int32)   # padded windows: 1-base backbone
    n_layers = np.zeros(B, dtype=np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.int32)
    lens = np.zeros((B, cfg.depth), dtype=np.int32)
    begins = np.zeros((B, cfg.depth), dtype=np.int32)
    ends = np.zeros((B, cfg.depth), dtype=np.int32)

    for bi, (i, wx, keep) in enumerate(chunk):
        L = len(wx.backbone)
        bb[bi, :L] = encode(wx.backbone)
        bbw[bi, :L] = wx.backbone_weights
        bb_len[bi] = L
        K = len(keep)
        n_layers[bi] = K
        if K == 0:
            continue
        # Encode the window's whole layer blob ONCE, then contiguous
        # slice copies into flat row views — ~2x over the per-slice loop
        # with an encode() per layer at production layer sizes (and the
        # measured winner over a fancy-index gather/scatter, whose index
        # arrays cost more memory traffic than the bases themselves).
        # The reference fills batches in tight C++ under a mutex
        # (/root/reference/src/cuda/cudapolisher.cpp:83-145).
        enc = encode(wx.bases)
        w_all = wx.weights
        offsets = np.concatenate([[0], np.cumsum(wx.lens)]).astype(np.int64)
        kp = np.asarray(keep, dtype=np.int64)
        lens_k = wx.lens[kp].astype(np.int64)
        ML = cfg.max_len
        sflat = seqs[bi].reshape(-1)
        wflat = ws[bi].reshape(-1)
        for li in range(K):
            o = offsets[kp[li]]
            ll = lens_k[li]
            sflat[li * ML:li * ML + ll] = enc[o:o + ll]
            wflat[li * ML:li * ML + ll] = w_all[o:o + ll]
        lens[bi, :K] = lens_k
        begins[bi, :K] = wx.begins[kp]
        ends[bi, :K] = wx.ends[kp]
    return (bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends)


def _submit(kernel, packed, use_pallas):
    """Dispatch one packed chunk; returns device futures (async)."""
    bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends = packed
    if use_pallas:
        return kernel(bb_len[:, None], n_layers[:, None], lens, begins,
                      ends, bb.astype(np.int32), bbw, seqs.astype(np.int32),
                      ws)
    return kernel(bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends)


def _unpack(outs, use_pallas):
    """Block on device futures; normalize to host arrays."""
    cb, cc, cl, fl = outs[0], outs[1], outs[2], outs[3]
    cons_base = np.asarray(cb)
    cons_cov = np.asarray(cc)
    cons_len = np.asarray(cl)
    failed = np.asarray(fl)
    if use_pallas:
        cons_len = cons_len[:, 0]
        failed = failed[:, 0]
    return cons_base, cons_cov, cons_len, failed


def _install(pipeline, chunk, results, trim, stats, fallback):
    cons_base, cons_cov, cons_len, failed = results
    for bi, (i, wx, keep) in enumerate(chunk):
        if failed[bi]:
            fallback.append(i)
            stats["failed"] += 1
            continue
        cl = int(cons_len[bi])
        codes = cons_base[bi, :cl]
        cov = cons_cov[bi, :cl]
        out = np.asarray(codes)
        if wx.is_tgs and trim:
            # Threshold on the ADMITTED sequence count (backbone + the
            # layers this driver actually packed), mirroring the
            # reference accelerator's seqs_added_per_window_ rule — it
            # counts only sequences successfully added to the GPU group
            # (src/cuda/cudabatch.cpp:139-163,233), not the window's full
            # layer count. Device coverage can only ever reach the
            # admitted count, so a full-window threshold (the CPU rule,
            # src/window.cpp:125-146) would over-trim between DEPTH_CAP
            # and 2*DEPTH_CAP layers and silently never trim above
            # 2*DEPTH_CAP. Host parity therefore holds exactly where the
            # two counts coincide: depth <= DEPTH_CAP.
            n_admitted_seqs = len(keep) + 1
            kept_codes = tgs_trim(out, np.asarray(cov), n_admitted_seqs)
        else:
            kept_codes = out
        pipeline.set_consensus(i, decode(kept_codes), True)
        stats["device"] += 1
