"""Consensus-phase driver: packs windows into padded, depth-bucketed device
batches, runs the batched POA kernel, trims and installs results, and
re-runs anything the device rejected on the host POA engine.

Mirrors the reference's CUDA polish orchestration
(/root/reference/src/cuda/cudapolisher.cpp:216-378): depth cap per window
(MAX_DEPTH_PER_WINDOW=200, :226), per-entry rejection of oversized layers
(cudabatch.cpp:141-160), failed windows re-polished on the host
(:354-378), and the host-side trim identical to the CPU path
(cudabatch.cpp:230-256).

Failure handling runs through the explicit degradation lattice
(racon_tpu/resilience/lattice.py): tiers ls -> v2 -> xla -> host, with
per-tier bounded retry, a per-device-call watchdog, and batch bisection
so one poisoned window is quarantined to the host instead of demoting the
whole run a tier.  Every seam carries a named fault-injection point
(resilience/faults.py) so each edge is deterministically testable in CI.
"""

from __future__ import annotations

import functools
import sys
import time
from typing import List

import numpy as np

from .. import config, obs
from ..resilience import faults
from ..resilience import lattice as rl
from ..resilience.journal import replay_windows
from ..resilience.report import PhaseReport
from . import band as _band
from . import poa
from .batch_exec import BatchExecutor, pipeline_depth as _pipeline_depth
from .encoding import decode, encode

DEPTH_CAP = 200                    # reference: MAX_DEPTH_PER_WINDOW
DEPTH_BUCKETS = (8, 32, DEPTH_CAP)


def _sanitize():
    """The runtime sanitizer module (lazy: the analysis package must not
    load on the production import path).  Its entry points self-gate on
    RACON_TPU_SANITIZE, so callers just call through."""
    from ..analysis import sanitize
    return sanitize

_PALLAS_KINDS = ("ls", "v2")

#: The window lengths the static jaxpr audit traces the consensus kernel
#: grid at: the CLI default (-w 500) and the large-window scenario
#: (-w 1000).  Each maps to its 128-lane class exactly as
#: run_consensus_phase buckets real windows.
AUDIT_WINDOW_LENGTHS = (500, 1000)

#: Declared compile budget for the audited POA grid: one jit signature
#: per (depth bucket, window class) — len(DEPTH_BUCKETS) x
#: len(AUDIT_WINDOW_LENGTHS) = 6.  A deliberate literal, not a product:
#: widening DEPTH_BUCKETS, the audited window set, or any geometry
#: change that splits signatures must consciously revisit this number or
#: the jaxpr audit (racon_tpu/analysis) fails tier-1 — silent recompile
#: blow-ups are the single biggest TPU serving-latency cliff.
POA_RECOMPILE_BUDGET = 6


def _batch_size() -> int:
    env = config.get_raw("RACON_TPU_BATCH_WINDOWS")
    if env:
        return max(1, int(env))
    import jax
    return 64 if jax.devices()[0].platform == "tpu" else 4


def _kernel_kind() -> str:
    """Which fused Pallas kernel serves consensus batches.

    'ls' (default) — v3 lane-lockstep, 8 windows per program
    (poa_pallas_ls.py); 'v2' — one window per program (poa_pallas.py).
    Either degrades through the lattice (ls -> v2 -> xla -> host) on
    Mosaic failure.
    """
    k = config.get_str("RACON_TPU_POA_KERNEL")
    if k not in _PALLAS_KINDS:
        raise ValueError(
            f"RACON_TPU_POA_KERNEL must be 'ls' or 'v2', got {k!r}")
    return k


def _band_active(kind: str) -> bool:
    """Banded POA dispatch: RACON_TPU_BAND on and a Pallas tier serving
    (the XLA twin and the host floor always run flat — they are the
    byte-identity oracles the verify-and-widen ladder bottoms out on)."""
    return kind in _PALLAS_KINDS and _band.enabled()


def _initial_poa_band(wx, keep, cfg):
    """w₀ (half-band) for a window: the worst admitted layer's
    length-vs-span delta plus the slack knob; None (flat) when the band
    would not be meaningfully narrower than the full DP row."""
    if not keep:
        return None
    delta = max(abs(int(wx.lens[j]) - (int(wx.ends[j]) - int(wx.begins[j])))
                for j in keep)
    w0 = delta + _band.slack()
    return w0 if 2 * w0 + 1 < cfg.max_len // 2 else None


def _shard_n(B: int) -> int:
    """Mesh shards this driver dispatches a B-window batch over (1 =
    single device: sharding off, demoted, batch too small, or a 1-wide
    batch axis)."""
    from ..parallel.partitioner import get_partitioner

    part = get_partitioner()
    return part.batch_axis_size if part.will_shard(B) else 1


def _device_batch(kind: str) -> int:
    """Batch size for the kernel geometry, padded UP to a mesh multiple
    when the batch will shard (the old round-DOWN spilled remainder
    windows to the slow path; pad rows are 1-base/0-layer windows and
    show up in `shard.pad_rows`); the lockstep kernel additionally needs
    the per-shard batch to be a multiple of its sublane group G."""
    B = _batch_size()
    m = _shard_n(B)
    if m > 1:
        B = ((B + m - 1) // m) * m
    if kind == "ls":
        from .poa_pallas_ls import G
        q = G * m
        B = max(1, (B + q - 1) // q) * q
    return B


def _node_factor() -> int:
    """max_nodes = factor * window_length. The default 3 matches the
    geometry every recorded pin was measured under; repeat-dense windows
    (4 of λ's 96) overflow it and fall back to the host, so hw_session
    measures factor 4 (VMEM fits per docs/roadmap.md) for a same-session
    pin refresh — the reference's per-entry capacity rejection is the
    analogous knob (/root/reference/src/cuda/cudabatch.cpp:141-160)."""
    return max(1, config.get_int("RACON_TPU_NODE_FACTOR"))


def window_class(bb_len: int) -> int:
    """Kernel-geometry class for a backbone length: ceil to the 128-lane
    grid. Windows bucket by (depth, class) so one long-window target in a
    mixed run no longer inflates every bucket's geometry — short windows
    pay their own class's DP ranges, not the global maximum's."""
    return max(128, (bb_len + 127) // 128 * 128)


def make_config(window_length: int, depth: int, match: int, mismatch: int,
                gap: int) -> poa.PoaConfig:
    def ceil128(x):
        return (x + 127) // 128 * 128

    max_backbone = ceil128(window_length)
    max_len = ceil128(window_length + window_length // 2)
    max_nodes = ceil128(_node_factor() * window_length)
    return poa.PoaConfig(max_nodes=max_nodes, max_len=max_len,
                         max_backbone=max_backbone, max_edges=12,
                         depth=depth, match=match, mismatch=mismatch,
                         gap=gap)


def tgs_trim(codes: np.ndarray, cov: np.ndarray, n_seqs: int):
    """Low-coverage end trim (reference: src/window.cpp:125-146)."""
    avg = (n_seqs - 1) // 2
    n = len(codes)
    begin = 0
    while begin < n and cov[begin] < avg:
        begin += 1
    end = n - 1
    while end >= 0 and cov[end] < avg:
        end -= 1
    if begin >= end:
        return codes  # chimeric suspicion: keep untrimmed
    return codes[begin:end + 1]


def run_consensus_phase(pipeline, *, match: int, mismatch: int, gap: int,
                        trim: bool, progress: bool = False,
                        journal=None) -> dict:
    """Device consensus for every eligible window; host for the rest.

    Streaming: a cheap metadata pass (window_info — no bases copied) sizes
    the geometry and buckets windows by depth; window bases are exported
    chunk-by-chunk at pack time, so driver memory is O(batch). Packing of
    chunk N+1 overlaps device execution of chunk N through JAX async
    dispatch — the analogue of the reference's greedy batch fill running
    concurrently with kernel execution
    (/root/reference/src/cuda/cudapolisher.cpp:83-145).

    Returns stats {device:…, host_fallback:…, backbone:…, failed:…,
    layers_dropped:…, report: PhaseReport} — the report's per-tier served
    counts sum to the window count, clean or fault-injected.

    With `journal` (resilience/journal.py) armed, windows already in the
    journal are replayed up front (served tier "journal") and every
    freshly served window — device, host fallback, or backbone — is
    appended as it is installed, so a crash loses at most the in-flight
    batch.
    """
    n = pipeline.num_windows()
    report = PhaseReport("consensus",
                         rl.CONSENSUS_TIERS + ("backbone", "journal"))
    report.total = n
    stats = {"device": 0, "host_fallback": 0, "backbone": 0, "failed": 0,
             "layers_dropped": 0, "report": report}
    # Runtime-sanitizer guard (no-op passthrough when unarmed): flags
    # stats mutations from any thread but this driver thread.
    stats = _sanitize().guard_stats(stats, "poa_driver.run_consensus_phase")

    replayed = replay_windows(pipeline, journal, n, report)

    fallback: List[int] = []

    # Metadata pass: geometry + depth buckets, no layer bytes touched.
    jobs = []          # (window_idx, estimated depth, backbone len)
    with obs.span("poa.metadata", windows=n):
        for i in range(n):
            if i in replayed:
                continue
            (n_seqs, bb_len, _rank, _is_tgs, _bytes,
             tid) = pipeline.window_info(i)
            k = n_seqs - 1
            if k < 2:
                # <3 sequences incl. backbone: backbone passthrough
                # (reference: src/window.cpp:68-71)
                try:
                    wx = pipeline.export_window(i)
                except Exception as e:  # noqa: BLE001 — export seam
                    fallback.append(i)
                    report.record_quarantine(i, e)
                    continue
                pipeline.set_consensus(i, wx.backbone.tobytes(), False)
                if journal is not None:
                    journal.append_window(i, tid, wx.rank, "backbone",
                                          wx.backbone.tobytes(), False)
                stats["backbone"] += 1
                continue
            jobs.append((i, min(k, DEPTH_CAP), bb_len))
    report.record_served("backbone", stats["backbone"])

    if jobs:
        requested = _kernel_kind()
        B = _device_batch(requested)
        use_pallas = _use_pallas()
        # Bucket by (depth, backbone class) to bound padding waste in BOTH
        # dims: layers dropped at pack time (oversized/empty) only shrink
        # a window's true depth, so a window always fits the bucket its
        # estimate chose; and short windows run in their own 128-grid
        # geometry class instead of the dataset-max geometry (one long
        # target in a mixed run used to inflate every bucket's DP ranges).
        # Note the layer-admission shift that rides along with per-class
        # geometry: a layer is admitted against ITS WINDOW'S class
        # max_len (cfg.max_len = 2x the 128-ceiled backbone class), not
        # the dataset-wide maximum — so a long stray layer over a short
        # backbone is dropped at pack time where the old single-geometry
        # driver would have admitted it.  Dropped layers only thin the
        # POA coverage (consensus still forms; parity with the reference
        # is kept by the golden tests); the count is surfaced as
        # report.extra["layers_dropped_maxlen"] and the
        # `poa.layers_dropped_maxlen` metrics counter so a serving-mix
        # or accuracy shift on mixed-length datasets is attributable.
        buckets = {}
        for i, depth, bb in jobs:
            bucket = next(b for b in DEPTH_BUCKETS if depth <= b)
            buckets.setdefault((bucket, window_class(bb)),
                               []).append((i, depth, bb))

        # geometries (cfg, kind) whose kernel already failed — seeded from
        # warm-up failures so the measured run never retries a kernel the
        # warm-up proved dead
        dead_geoms = set(_WARM_DEAD)
        # The shared executor (ops/batch_exec.py) owns the in-flight
        # queue: JAX dispatch is async, so with depth Q the host
        # packs/exports chunks N+1..N+Q while chunk N executes — the
        # analogue of the reference's continuous batch fill running
        # concurrently with kernel execution (cudapolisher.cpp:83-145).
        # This driver is only the bucket policy on top of it.
        executor = BatchExecutor(
            _ConsensusOps(pipeline, B, trim, stats, fallback, report,
                          journal, dead_geoms),
            report=report)
        for (depth_bucket, wl_class), bucket_jobs in sorted(buckets.items()):
            obs.count(f"poa.windows.d{depth_bucket}.c{wl_class}",
                      len(bucket_jobs))
            # Measured-cell counter for the cost model (obs/costmodel.py):
            # sum of (admitted depth x class) over the bucket's windows —
            # the serial-step count at graph growth 1.  True depth, not
            # the bucket cap: padding layers are all-zero rows the model
            # must not bill as DP work.
            obs.count(f"poa.cells.d{depth_bucket}.c{wl_class}",
                      sum(d for _, d, _ in bucket_jobs) * wl_class)
            obs.observe("poa.bucket_windows", len(bucket_jobs))
            # Bucket spans cover submit-side work; with pipelining a
            # chunk of bucket X may *drain* inside bucket Y's span — the
            # async-dispatch overlap the trace is there to make visible.
            with obs.span("poa.bucket", depth=depth_bucket,
                          wl_class=wl_class, windows=len(bucket_jobs)):
                cfg = make_config(wl_class, depth_bucket, match, mismatch,
                                  gap)
                # Large window geometries (e.g. -w 1000) overflow the fused
                # kernel's VMEM budget; the entry tier is picked per
                # geometry.
                entry_kind = _pick_tier(cfg, use_pallas, requested)
                # (Per-bucket depth is kept deliberately: the fused
                # kernel's VMEM footprint is depth-independent now, but
                # packing and host->device transfer scale with the padded
                # depth — a single DEPTH_CAP geometry would ship ~25x
                # zeros for the shallow buckets on every chunk to save
                # compiles that the lru + persistent compilation caches
                # already amortize.)
                # Sequential loops run lock-step across the batch, so keep
                # batches depth-homogeneous — and length-homogeneous
                # within equal depth: a lockstep program's DP range is the
                # union over its 8 windows, so mixing a short window into
                # a long group bills it the long group's ranks.
                bucket_jobs.sort(key=lambda job: (job[1], job[2]))
                ctx = _BucketCtx(cfg, entry_kind)
                for off in range(0, len(bucket_jobs), B):
                    executor.submit(
                        ctx, [i for i, _, _ in bucket_jobs[off:off + B]])
                if progress:
                    print(f"[racon_tpu::poa] bucket depth<={depth_bucket} "
                          f"len<={wl_class}: {len(bucket_jobs)} windows",
                          file=sys.stderr)
        executor.flush()
        # feeder split (VERDICT #7): host pack wall vs blocked kernel
        # wall, stamped for bench.py's machine-checkable criterion
        executor.stamp_walls(report)

    t0 = time.perf_counter()
    with obs.span("poa.host_fallback", windows=len(fallback)):
        for i in fallback:
            polished = pipeline.consensus_cpu_one(i)
            if journal is not None:
                _, _, rank, _, _, tid = pipeline.window_info(i)
                journal.append_window(i, tid, rank, "host",
                                      pipeline.get_consensus(i), polished)
            stats["host_fallback"] += 1
    report.add_wall("host", time.perf_counter() - t0)
    report.record_served("host", stats["host_fallback"])
    report.extra["device_rejected"] = stats["failed"]
    # layers dropped by this class's max_len admission (per-class geometry
    # change, ADVICE.md): attributes serving-mix shifts on mixed-length
    # datasets
    report.extra["layers_dropped_maxlen"] = stats["layers_dropped"]
    return stats


def observed_window_lengths(draft_path: str, w: int) -> set:
    """Every window length the consensus phase will actually derive.

    run_consensus_phase buckets kernel geometry by the OBSERVED backbone
    classes, not the nominal -w (the metadata pass above). Windows are
    fixed-size chunks of draft contigs (rt_pipeline.cpp window build), so
    the set is computable from the draft FASTA alone: per contig, w for
    the full chunks plus the tail remainder. Warming only the nominal w
    would leave the tail-class geometries to compile inside the timed
    pass.  Shared by bench.py's prewarm and the pipelined polisher's
    warm-up thread (polisher.py)."""
    import gzip

    lens = set()

    def add(contig_len):
        if contig_len <= 0:
            return
        if contig_len >= w:
            lens.add(w)
        rem = contig_len % w
        if contig_len < w:
            lens.add(contig_len)
        elif rem:
            lens.add(rem)

    opener = gzip.open if draft_path.endswith(".gz") else open
    cur = 0
    with opener(draft_path, "rt") as f:
        for line in f:
            if line.startswith(">"):
                add(cur)
                cur = 0
            else:
                cur += len(line.strip())
    add(cur)
    return lens or {1}


# (cfg, kind) pairs whose kernel failed during warm-up; consulted by
# run_consensus_phase so the measured run dispatches straight to the tier
# the warm-up landed on instead of re-paying a compile-and-fail.
_WARM_DEAD: set = set()


def warm_geometries(window_lengths, match: int, mismatch: int,
                    gap: int) -> None:
    """Compile (or load from the persistent cache) every kernel geometry
    the consensus phase can pick for these window lengths (an int or an
    iterable of observed backbone lengths — each maps to its 128-grid
    class, exactly as run_consensus_phase buckets them).

    One all-padding batch per (depth bucket, class) runs in milliseconds
    but forces the full compile — so a benchmark's measured pass never
    pays compile time, whatever depth/length mix the real dataset
    produces. Tiers that fail here are recorded in _WARM_DEAD so the
    measured run skips them."""
    if isinstance(window_lengths, int):
        window_lengths = [window_lengths]
    classes = sorted({window_class(max(w, 1)) for w in window_lengths})
    requested = _kernel_kind()
    B = _device_batch(requested)
    use_pallas = _use_pallas()
    import itertools
    for depth_bucket, wl_class in itertools.product(DEPTH_BUCKETS, classes):
        cfg = make_config(wl_class, depth_bucket, match, mismatch, gap)
        kind = _pick_tier(cfg, use_pallas, requested)
        with obs.span("poa.warmup", depth=depth_bucket, wl_class=wl_class):
            while kind != "host":
                kernel, kind = _live_tier(cfg, B, kind, _WARM_DEAD)
                if kind == "host":
                    break
                try:
                    faults.check(f"poa.run.{kind}", ())
                    pallas = kind in _PALLAS_KINDS
                    banded = _band_active(kind)
                    _unpack(_submit(kernel, _pack([], cfg, B), pallas,
                                    banded), pallas, banded)
                    break
                except Exception as e:  # noqa: BLE001 — same degrade
                    # philosophy as run_consensus_phase: a Mosaic failure
                    # on one geometry must not abort the caller — warm
                    # the tier it will actually fall back to, and
                    # remember the failure so the measured run doesn't
                    # retry it
                    _WARM_DEAD.add((cfg, kind))
                    nxt = _next_tier(cfg, kind)
                    _warn_degrade(e, nxt)
                    kind = nxt


def _pick_tier(cfg, use_pallas: bool, kind: str) -> str:
    """Entry tier for a geometry after VMEM-fit checks: the requested
    pallas tier if it fits, else the next tier down."""
    if not use_pallas:
        return "xla"
    if _fits_vmem(cfg, kind):
        return kind
    if kind == "ls" and _fits_vmem(cfg, "v2"):
        return "v2"
    return "xla"


def _next_tier(cfg, kind: str) -> str:
    """The lattice tier below `kind` for this geometry (VMEM-aware)."""
    if kind == "ls" and _fits_vmem(cfg, "v2"):
        return "v2"
    if kind in _PALLAS_KINDS:
        return "xla"
    return "host"


def _live_tier(cfg, B, kind, dead_geoms, report=None):
    """Kernel for the best LIVE tier at or below `kind` for this geometry,
    stepping past tiers proven dead and tiers whose kernel build fails
    (compile failures demote exactly like runtime failures).  Returns
    (kernel, kind); kernel is None iff kind == 'host'."""
    while kind != "host":
        if (cfg, kind) in dead_geoms:
            kind = _next_tier(cfg, kind)
            continue
        try:
            return _build_kernel(cfg, B, kind in _PALLAS_KINDS, kind), kind
        except Exception as e:  # noqa: BLE001 — compile seam
            dead_geoms.add((cfg, kind))
            nxt = _next_tier(cfg, kind)
            if report is not None:
                report.record_failure(kind, e)
                report.record_degrade(kind, nxt, e)
            _warn_degrade(e, nxt)
            kind = nxt
    return None, "host"


def _warn_degrade(e, to_kind: str) -> None:
    tier = (f"the pallas '{to_kind}' kernel" if to_kind in _PALLAS_KINDS
            else "the XLA kernel" if to_kind == "xla"
            else "the host engine")
    print(f"[racon_tpu::poa] WARNING: kernel tier failed "
          f"({type(e).__name__}: {e}); falling back to {tier}",
          file=sys.stderr)


class _BucketCtx:
    """Per-(depth, class) bucket context the executor threads through the
    ops hooks: the geometry, its entry tier, and the kernel handle the
    most recent live_tier resolution built."""

    __slots__ = ("cfg", "entry_kind", "kernel")

    def __init__(self, cfg, entry_kind):
        self.cfg = cfg
        self.entry_kind = entry_kind
        self.kernel = None


class _ConsensusOps:
    """poa_driver's hooks for the shared executor (ops/batch_exec.py):
    bucket policy, pack/submit/unpack, and the journal/sanitizer/report
    seams.  Failure semantics are exactly the pre-extraction driver's:
    per tier bounded retry, then batch bisection (a poisoned window is
    quarantined to the host while the rest of the batch stays on the
    device); a batch-independent failure demotes the geometry one tier,
    down to the host floor."""

    span_name = "poa.chunk"
    async_dispatch = True

    def __init__(self, pipeline, B, trim, stats, fallback, report,
                 journal, dead_geoms):
        self.pipeline = pipeline
        self.B = B
        self.trim = trim
        self.stats = stats
        self.fallback = fallback
        self.report = report
        self.journal = journal
        self.dead_geoms = dead_geoms
        # verify-and-widen ladder state (ops/band.py): window idx ->
        # BandState; _band_retry holds hit windows awaiting the
        # executor's widen loop
        self.band = {}
        self._band_retry = []

    def _widths(self, chunk, cfg):
        """Per-window half-band widths for _pack (0 = flat), creating
        ladder state on first touch."""
        if not _band.enabled():
            return None
        widths = {}
        for i, wx, keep in chunk:
            st = self.band.get(i)
            if st is None:
                st = _band.BandState(_initial_poa_band(wx, keep, cfg))
                self.band[i] = st
                if st.k:
                    obs.count("band.jobs")
                    if obs.enabled():
                        obs.count("poa.cells.banded",
                                  len(keep) * (2 * st.k + 1))
            widths[i] = st.k or 0
        return widths

    def live_tier(self, ctx, kind):
        # best LIVE tier for this geometry (earlier chunks or the warm-up
        # may have proven tiers dead)
        ctx.kernel, kind = _live_tier(ctx.cfg, self.B,
                                      kind or ctx.entry_kind,
                                      self.dead_geoms, self.report)
        return kind

    def export(self, ctx, idxs):
        return _export_chunk(self.pipeline, idxs, ctx.cfg, self.fallback,
                             self.stats, self.report)

    def pack(self, ctx, chunk):
        # Always pad to B: a dataset-size-dependent final-chunk shape
        # would force an extra jit compile per distinct remainder (padded
        # windows are 1-base/0-layer — free).
        return _pack(chunk, ctx.cfg, self.B, self._widths(chunk, ctx.cfg))

    def dispatch(self, ctx, kind, packed, chunk):
        faults.check(f"poa.run.{kind}", [i for i, _, _ in chunk])
        return _submit(ctx.kernel, packed, kind in _PALLAS_KINDS,
                       _band_active(kind))

    def attempt(self, ctx, kind, sub):
        pallas = kind in _PALLAS_KINDS
        banded = _band_active(kind)
        faults.check(f"poa.run.{kind}", [i for i, _, _ in sub])
        return _unpack(
            _submit(ctx.kernel,
                    _pack(sub, ctx.cfg, self.B, self._widths(sub, ctx.cfg)),
                    pallas, banded), pallas, banded)

    def unpack(self, ctx, kind, outs):
        return _unpack(outs, kind in _PALLAS_KINDS, _band_active(kind))

    def span_args(self, ctx, chunk, pipelined):
        return {"windows": len(chunk), "pipelined": pipelined}

    def install(self, ctx, kind, sub, results):
        forced = False
        if _band_active(kind):
            # the widening-exhaustion drill: an armed band.hit fault
            # classifies every banded window as a hit instead of raising,
            # driving the ladder deterministically to its flat floor
            try:
                faults.check("band.hit", [i for i, _, _ in sub])
            except faults.InjectedFault:
                forced = True
        retry = _install(self.pipeline, sub, results, self.trim, self.stats,
                         self.fallback, self.report, kind, self.journal,
                         band_states=self.band,
                         band_cap=ctx.cfg.max_len // 2, force_hit=forced)
        if retry:
            self._band_retry.extend(retry)

    def widen(self, ctx, kind):
        # executor widen hook: hit windows re-dispatched at their widened
        # (or flat, wband=0) band through the same tier
        retry, self._band_retry = self._band_retry, []
        return retry

    def surrender(self, ctx, items, exported):
        if exported:
            self.fallback.extend(i for i, _, _ in items)
        else:
            self.fallback.extend(items)

    def quarantine(self, ctx, item, exc):
        self.fallback.append(item[0])
        self.report.record_quarantine(item[0], exc)

    def demote(self, ctx, kind, cause):
        self.dead_geoms.add((ctx.cfg, kind))
        nxt = _next_tier(ctx.cfg, kind)
        self.report.record_degrade(kind, nxt, cause)
        _warn_degrade(cause, nxt)
        return nxt

    # -- sharded dispatch (optional executor hooks) ------------------------
    def shard_multiple(self, ctx, chunk):
        # _pack always pads to B, so the executor's pad-to-multiple is a
        # no-op here; returning m>1 is purely the shard-size accounting
        # (and must match the kernel the last live_tier built — _shard_n
        # re-reads the same partitioner state _build_kernel keyed on)
        m = _shard_n(self.B)
        return m if m > 1 and self.B % m == 0 else 1

    def demote_shard(self, ctx, kind, cause):
        if self.shard_multiple(ctx, None) <= 1:
            return False
        from ..parallel.partitioner import get_partitioner

        if get_partitioner().demote(f"{type(cause).__name__}: {cause}"):
            rl.record_shard_demotion(self.report, kind, cause)
        return True


def _use_pallas() -> bool:
    env = config.get_raw("RACON_TPU_PALLAS")
    if env is not None:
        return env == "1"
    import jax
    return jax.devices()[0].platform == "tpu"


def _n_devices() -> int:
    import jax
    return len(jax.devices())


def _platform() -> str:
    import jax
    return jax.devices()[0].platform


def _fits_vmem(cfg, kind: str = "v2", budget_bytes: int = 14 << 20) -> bool:
    """Whether the fused Pallas kernel's VMEM scratch fits the core budget.

    v2 mirrors poa_pallas.py's blocked layout: layer arrays live in HBM
    and stream through two DMA slots, so depth does not appear. ls mirrors
    poa_pallas_ls.py's scratch_shapes: a 128-row H ring instead of the full
    H matrix, plus rank-space graph arrays and per-layer DMA slots.
    """
    if kind == "ls":
        from .poa_pallas_ls import G, RING, _round_up

        NC = cfg.max_nodes // 128
        JC = _round_up(cfg.max_len + 1, 128) // 128
        lane_bytes = G * 128 * 4
        ring = RING * JC * lane_bytes
        j_rows = (1 + 2 + 2 * 2) * JC * lane_bytes   # H0, nkey/runrem, scr
        n_rows = (9 + 2 * cfg.max_edges) * NC * lane_bytes
        io = 4 * NC * lane_bytes                      # bb/bbw in, cons out
        return ring + j_rows + n_rows + io < budget_bytes
    from .poa_pallas import blocked_width

    jw8 = 8 * blocked_width(cfg.max_len + 1)
    nw8 = 8 * blocked_width(cfg.max_nodes)
    h = (cfg.max_nodes + 1) * jw8 * 4
    mv = (cfg.max_nodes + 1) * jw8 * 4  # move records, i32 (Mosaic tiling)
    layer_slots = 2 * 2 * jw8 * 4       # double-buffered seq + weight rows
    graph = nw8 * (10 * 4 + 2 * cfg.max_edges * 4)
    return h + mv + layer_slots + graph < budget_bytes


def _build_kernel(cfg, B, use_pallas, kind: str = "v2"):
    """Memoization front for _build_kernel_cached: the XLA twin ignores
    `kind`, so normalize it out of the key — a warm-up that degraded to
    the twin under 'v2' must hit the same cache entry as a measured-run
    request arriving via the 'ls' step-down (and as __graft_entry__'s
    default-argument call).  The device topology (count + platform) is
    part of the key: reconfiguring JAX devices after a first build must
    never serve a stale sharded/interpreted kernel (ADVICE.md)."""
    if not use_pallas:
        kind = "xla"
    faults.check(f"poa.compile.{kind}")
    # Column-compressed stepping rides in the cache key: flipping the
    # knob mid-process (hw_session's compressed-vs-flat steps) must not
    # serve a kernel built under the other loop shape.
    colstep = config.get_bool("RACON_TPU_POA_COLSTEP")
    # Banded builds ride the cache key too: the flat and banded variants
    # of a geometry are distinct compiled kernels (extra wband input /
    # band_hit output), and the flat one is the ladder's oracle.
    banded = use_pallas and _band_active(kind)
    # Shard count resolved here (not in the cached builder) so the key
    # is explicit: a will_shard flip — knob, demotion, mesh change —
    # can never serve a kernel wrapped for the wrong dispatch mode.
    shard_n = _shard_n(B)
    if shard_n > 1 and B % shard_n:
        shard_n = 1  # geometry was sized for a different mesh; stay local
    for m in ((shard_n, 1) if shard_n > 1 else (1,)):
        # Same build-observability pattern as
        # kernel_cache.device_keyed_cache: a miss is only known after
        # the call, so the span is retroactive.
        misses0 = _build_kernel_cached.cache_info().misses
        t0 = time.monotonic_ns()
        try:
            built = _build_kernel_cached(cfg, B, use_pallas, kind,
                                         _n_devices(), _platform(),
                                         colstep, m, banded)
        except Exception as e:  # noqa: BLE001 — shard lattice edge
            if m <= 1:
                raise
            # sharded build failed: drop the partitioner to
            # single-device for the rest of the process and rebuild the
            # SAME tier locally (never a tier demotion, never fatal)
            from ..parallel.partitioner import get_partitioner

            if get_partitioner().demote(f"{type(e).__name__}: {e}"):
                rl.record_shard_demotion(None, kind, e)
            continue
        if _build_kernel_cached.cache_info().misses != misses0:
            from . import cost_hooks

            # predicted per-window bill for this geometry/tier, stamped
            # next to the measured build wall (obs/costmodel.py)
            pred = cost_hooks.record_build(
                "build_lockstep_poa_kernel" if kind == "ls"
                else "build_pallas_poa_kernel" if kind == "v2"
                else "build_poa_kernel", (cfg,), {})
            obs.add_complete("kernel.build", t0, time.monotonic_ns(),
                             builder=f"poa.{kind}", B=B, shards=m,
                             max_nodes=cfg.max_nodes, depth=cfg.depth,
                             **pred)
            obs.count(f"kernel.builds.poa.{kind}")
        return built


@functools.lru_cache(maxsize=64)
def _build_kernel_cached(cfg, B, use_pallas, kind, n_dev, platform,
                         colstep=True, shard_n=1, banded=False):
    """Single- or multi-device kernel for a B-window batch.

    shard_n > 1: batch dim sharded over the partitioner's mesh (the
    production analogue of the reference's multi-GPU batch striping,
    src/cuda/cudapolisher.cpp:228-240, with no collectives) — shard_map
    around the per-shard pallas build, pjit sharding constraints around
    the XLA twin (which partitions transparently).

    Memoized on the full geometry key — including the device topology
    (n_dev, platform) and the shard count: the warm-up's compiled kernel
    IS the measured run's function object, so the in-process jit cache
    hits even when the persistent disk cache can't serve (observed: AOT
    entries compiled under different machine features fail to load and
    silently recompile — minutes per geometry on the CPU twin).
    """
    assert not (use_pallas and not _fits_vmem(cfg, kind)), (
        "caller must check _fits_vmem before requesting the pallas kernel")
    if use_pallas:
        if kind == "ls":
            from .poa_pallas_ls import build_lockstep_poa_kernel as build
        else:
            from .poa_pallas import build_pallas_poa_kernel as build
        interp = platform != "tpu"
        if shard_n <= 1:
            return build(cfg, interpret=interp, colstep=colstep,
                         band=banded)(B)
        from ..parallel.partitioner import get_partitioner
        n_in, n_out = (10, 6) if banded else (9, 5)
        sharded = get_partitioner().shard_build(
            lambda b: build(cfg, interpret=interp, colstep=colstep,
                            band=banded)(b),
            B, n_in, n_out)
        assert sharded is not None, (B, shard_n)  # _device_batch divides B
        return sharded
    kernel = poa.build_poa_kernel(cfg)
    if shard_n <= 1:
        return kernel
    from ..parallel.partitioner import get_partitioner
    return get_partitioner().partition(
        kernel, in_axes=[("windows",)] * 9, out_axes=("windows",))


def _export_chunk(pipeline, idxs, cfg, fallback, stats=None, report=None):
    """Export window bases for one chunk; apply per-layer admission.

    Returns [(window_idx, export, kept layer indices)] — windows the device
    can't represent go straight to the host fallback list, and an export
    failure (the `window.export` seam) quarantines just that window.
    """
    chunk = []
    for i in idxs:
        try:
            wx = pipeline.export_window(i)
        except Exception as e:  # noqa: BLE001 — export seam
            fallback.append(i)
            if report is not None:
                report.record_quarantine(i, e)
            continue
        k = len(wx.lens)
        keep = [j for j in range(k) if 0 < wx.lens[j] <= cfg.max_len]
        # Per-class geometry admission (ADVICE.md): a layer longer than
        # THIS class's max_len is dropped here where the old dataset-max
        # geometry admitted it; counted (report.extra + the named
        # `poa.layers_dropped_maxlen` metrics counter) so serving-mix
        # shifts on mixed-length datasets stay attributable.
        if stats is not None:
            dropped = int(
                sum(1 for ln in wx.lens[:DEPTH_CAP] if ln > cfg.max_len))
            stats["layers_dropped"] += dropped
            if dropped:
                obs.count("poa.layers_dropped_maxlen", dropped)
        if len(keep) < len(wx.lens[:DEPTH_CAP]) and len(keep) < 2:
            fallback.append(i)
            continue
        chunk.append((i, wx, keep[:DEPTH_CAP]))
    return chunk


def _pack(chunk, cfg, pad_to=None, band_widths=None):
    B = pad_to if pad_to is not None else len(chunk)
    bb = np.zeros((B, cfg.max_backbone), dtype=np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), dtype=np.int32)
    bb_len = np.ones(B, dtype=np.int32)   # padded windows: 1-base backbone
    n_layers = np.zeros(B, dtype=np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.int32)
    lens = np.zeros((B, cfg.depth), dtype=np.int32)
    begins = np.zeros((B, cfg.depth), dtype=np.int32)
    ends = np.zeros((B, cfg.depth), dtype=np.int32)
    wband = np.zeros(B, dtype=np.int32)   # 0 = flat (padded rows stay 0)

    for bi, (i, wx, keep) in enumerate(chunk):
        if band_widths:
            wband[bi] = band_widths.get(i, 0)
        L = len(wx.backbone)
        bb[bi, :L] = encode(wx.backbone)
        bbw[bi, :L] = wx.backbone_weights
        bb_len[bi] = L
        K = len(keep)
        n_layers[bi] = K
        if K == 0:
            continue
        # Encode the window's whole layer blob ONCE, then contiguous
        # slice copies into flat row views — ~2x over the per-slice loop
        # with an encode() per layer at production layer sizes (and the
        # measured winner over a fancy-index gather/scatter, whose index
        # arrays cost more memory traffic than the bases themselves).
        # The reference fills batches in tight C++ under a mutex
        # (/root/reference/src/cuda/cudapolisher.cpp:83-145).
        enc = encode(wx.bases)
        w_all = wx.weights
        offsets = np.concatenate([[0], np.cumsum(wx.lens)]).astype(np.int64)
        kp = np.asarray(keep, dtype=np.int64)
        lens_k = wx.lens[kp].astype(np.int64)
        ML = cfg.max_len
        sflat = seqs[bi].reshape(-1)
        wflat = ws[bi].reshape(-1)
        for li in range(K):
            o = offsets[kp[li]]
            ll = lens_k[li]
            sflat[li * ML:li * ML + ll] = enc[o:o + ll]
            wflat[li * ML:li * ML + ll] = w_all[o:o + ll]
        lens[bi, :K] = lens_k
        begins[bi, :K] = wx.begins[kp]
        ends[bi, :K] = wx.ends[kp]
    return (bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends, wband)


def _submit(kernel, packed, use_pallas, banded=False):
    """Dispatch one packed chunk; returns device futures (async).
    `packed` is _pack's 10-tuple (trailing per-window half-band row) or
    a legacy 9-tuple from flat-only callers (probes, the multichip
    worker) — the band row is only touched on banded dispatch."""
    bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends = packed[:9]
    if use_pallas:
        args = [bb_len[:, None], n_layers[:, None], lens, begins,
                ends, bb.astype(np.int32), bbw, seqs.astype(np.int32), ws]
        if banded:
            args.append(packed[9])
        return kernel(*args)
    return kernel(bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends)


def _unpack(outs, use_pallas, banded=False):
    """Block on device futures; normalize to host arrays."""
    cb, cc, cl, fl = outs[0], outs[1], outs[2], outs[3]
    cons_base = np.asarray(cb)
    cons_cov = np.asarray(cc)
    cons_len = np.asarray(cl)
    failed = np.asarray(fl)
    if use_pallas:
        cons_len = cons_len[:, 0]
        failed = failed[:, 0]
        if banded:
            return (cons_base, cons_cov, cons_len, failed,
                    np.asarray(outs[5])[:, 0])
    return cons_base, cons_cov, cons_len, failed


def _install(pipeline, chunk, results, trim, stats, fallback, report=None,
             tier=None, journal=None, band_states=None, band_cap=0,
             force_hit=False):
    san = _sanitize()
    sanitizing = san.enabled()
    if sanitizing:
        # Concrete-side invariants (the kernel proxy skips traced calls):
        # in-range lengths/codes, boolean failed flags. The sanitize.nan
        # fault fires in here against a checker-only copy.
        san.check_consensus_outputs(results[:4], [i for i, _, _ in chunk],
                                    where=f"poa._install[{tier or 'device'}]")
    if len(results) == 5:
        cons_base, cons_cov, cons_len, failed, band_hit = results
    else:
        cons_base, cons_cov, cons_len, failed = results
        band_hit = None
    retry = []
    for bi, (i, wx, keep) in enumerate(chunk):
        st = band_states.get(i) if band_states else None
        if st is not None and st.k:
            # banded dispatch: a kernel hit flag — or any failure, which
            # under a band may just mean the masked DP lost the path —
            # advances the verify-and-widen ladder instead of installing
            hit_bi = force_hit or (band_hit is not None
                                   and bool(band_hit[bi]))
            if hit_bi or failed[bi]:
                st.widen_width(band_cap, report, tier=tier or "device")
                if st.k and obs.enabled():
                    obs.count("poa.cells.banded",
                              len(keep) * (2 * st.k + 1))
                retry.append((i, wx, keep))
                continue
            st.pending = False
        if failed[bi]:
            fallback.append(i)
            stats["failed"] += 1
            continue
        cl = int(cons_len[bi])
        codes = cons_base[bi, :cl]
        cov = cons_cov[bi, :cl]
        out = np.asarray(codes)
        if wx.is_tgs and trim:
            # Threshold on the ADMITTED sequence count (backbone + the
            # layers this driver actually packed), mirroring the
            # reference accelerator's seqs_added_per_window_ rule — it
            # counts only sequences successfully added to the GPU group
            # (src/cuda/cudabatch.cpp:139-163,233), not the window's full
            # layer count. Device coverage can only ever reach the
            # admitted count, so a full-window threshold (the CPU rule,
            # src/window.cpp:125-146) would over-trim between DEPTH_CAP
            # and 2*DEPTH_CAP layers and silently never trim above
            # 2*DEPTH_CAP. Host parity therefore holds exactly where the
            # two counts coincide: depth <= DEPTH_CAP.
            n_admitted_seqs = len(keep) + 1
            kept_codes = tgs_trim(out, np.asarray(cov), n_admitted_seqs)
        else:
            kept_codes = out
        payload = decode(kept_codes)
        if sanitizing and san.parity_due(stats["device"]):
            # Sampled host<->device parity. Host trim parity holds exactly
            # when no layers were dropped at admission (see the trim
            # comment above), so deeper windows are skipped. Recompute
            # BEFORE the install below so the device result is what
            # finally lands — an armed run stays byte-identical.
            n_seqs = pipeline.window_info(i)[0]
            if len(keep) + 1 == n_seqs:
                pipeline.consensus_cpu_one(i)
                san.check_parity(payload, pipeline.get_consensus(i), i,
                                 where=f"poa._install[{tier or 'device'}]")
        pipeline.set_consensus(i, payload, True)
        if journal is not None:
            journal.append_window(i, wx.target_id, wx.rank,
                                  tier or "device", payload, True)
        stats["device"] += 1
        if report is not None and tier is not None:
            report.record_served(tier)
    return retry
