"""Consensus-phase driver: packs windows into padded, depth-bucketed device
batches, runs the batched POA kernel, trims and installs results, and
re-runs anything the device rejected on the host POA engine.

Mirrors the reference's CUDA polish orchestration
(/root/reference/src/cuda/cudapolisher.cpp:216-378): depth cap per window
(MAX_DEPTH_PER_WINDOW=200, :226), per-entry rejection of oversized layers
(cudabatch.cpp:141-160), failed windows re-polished on the host
(:354-378), and the host-side trim identical to the CPU path
(cudabatch.cpp:230-256).
"""

from __future__ import annotations

import os
import sys
from typing import List

import numpy as np

from . import poa
from .encoding import decode, encode

DEPTH_CAP = 200                    # reference: MAX_DEPTH_PER_WINDOW
DEPTH_BUCKETS = (8, 32, DEPTH_CAP)


def _batch_size() -> int:
    env = os.environ.get("RACON_TPU_BATCH_WINDOWS")
    if env:
        return max(1, int(env))
    import jax
    return 64 if jax.devices()[0].platform == "tpu" else 4


def make_config(window_length: int, depth: int, match: int, mismatch: int,
                gap: int) -> poa.PoaConfig:
    def ceil128(x):
        return (x + 127) // 128 * 128

    max_backbone = ceil128(window_length)
    max_len = ceil128(window_length + window_length // 2)
    max_nodes = ceil128(3 * window_length)
    return poa.PoaConfig(max_nodes=max_nodes, max_len=max_len,
                         max_backbone=max_backbone, max_edges=12,
                         depth=depth, match=match, mismatch=mismatch,
                         gap=gap)


def tgs_trim(codes: np.ndarray, cov: np.ndarray, n_seqs: int):
    """Low-coverage end trim (reference: src/window.cpp:125-146)."""
    avg = (n_seqs - 1) // 2
    n = len(codes)
    begin = 0
    while begin < n and cov[begin] < avg:
        begin += 1
    end = n - 1
    while end >= 0 and cov[end] < avg:
        end -= 1
    if begin >= end:
        return codes  # chimeric suspicion: keep untrimmed
    return codes[begin:end + 1]


def run_consensus_phase(pipeline, *, match: int, mismatch: int, gap: int,
                        trim: bool, progress: bool = False) -> dict:
    """Device consensus for every eligible window; host for the rest.

    Returns stats {device:…, host_fallback:…, backbone:…}.
    """
    n = pipeline.num_windows()
    stats = {"device": 0, "host_fallback": 0, "backbone": 0, "failed": 0}

    fallback: List[int] = []
    window_length = 0

    # First pass: export everything and find the batch geometry (the layer
    # length cap depends on the final config, which depends on the largest
    # backbone).
    exports = []
    for i in range(n):
        wx = pipeline.export_window(i)
        window_length = max(window_length, len(wx.backbone))
        exports.append(wx)

    max_len = make_config(max(window_length, 1), DEPTH_BUCKETS[0], match,
                          mismatch, gap).max_len

    jobs = []          # (window_idx, export, kept layer indices)
    for i, wx in enumerate(exports):
        k = len(wx.lens)
        if k < 2:
            # <3 sequences incl. backbone: backbone passthrough
            # (reference: src/window.cpp:68-71)
            pipeline.set_consensus(i, wx.backbone.tobytes(), False)
            stats["backbone"] += 1
            continue
        keep = [j for j in range(k) if 0 < wx.lens[j] <= max_len]
        if len(keep) < len(wx.lens[:DEPTH_CAP]) and len(keep) < 2:
            # device can't represent enough of this window: host it
            fallback.append(i)
            continue
        keep = keep[:DEPTH_CAP]
        jobs.append((i, wx, keep))

    if jobs:
        from ..parallel.mesh import divisible_batch

        n_dev = _n_devices()
        B = divisible_batch(n_dev, _batch_size())
        use_pallas = _use_pallas()
        # Bucket by depth to bound padding waste.
        buckets = {}
        for job in jobs:
            depth = len(job[2])
            bucket = next(b for b in DEPTH_BUCKETS if depth <= b)
            buckets.setdefault(bucket, []).append(job)

        for depth_bucket, bucket_jobs in sorted(buckets.items()):
            cfg = make_config(max(window_length, 1), depth_bucket, match,
                              mismatch, gap)
            bucket_pallas = use_pallas
            kernel = _build_kernel(cfg, B, bucket_pallas)
            # Sequential loops run lock-step across the batch, so keep
            # batches depth-homogeneous.
            bucket_jobs.sort(key=lambda job: len(job[2]))
            for off in range(0, len(bucket_jobs), B):
                chunk = bucket_jobs[off:off + B]
                pad = B if (bucket_pallas or n_dev > 1) else None
                try:
                    _run_chunk(pipeline, kernel, cfg, chunk, trim, stats,
                               fallback, use_pallas=bucket_pallas,
                               pad_to=pad)
                except Exception as e:  # noqa: BLE001
                    if not bucket_pallas:
                        raise
                    # Mosaic compile/runtime failure: degrade to the XLA
                    # kernel for the rest of this geometry (same fallback
                    # philosophy as the per-window host fallback).
                    print("[racon_tpu::poa] WARNING: pallas kernel failed "
                          f"({type(e).__name__}: {e}); falling back to the "
                          "XLA kernel", file=sys.stderr)
                    bucket_pallas = False
                    kernel = _build_kernel(cfg, B, bucket_pallas)
                    pad = B if n_dev > 1 else None
                    _run_chunk(pipeline, kernel, cfg, chunk, trim, stats,
                               fallback, use_pallas=bucket_pallas,
                               pad_to=pad)
            if progress:
                print(f"[racon_tpu::poa] bucket depth<={depth_bucket}: "
                      f"{len(bucket_jobs)} windows", file=sys.stderr)

    for i in fallback:
        pipeline.consensus_cpu_one(i)
        stats["host_fallback"] += 1

    return stats


def _use_pallas() -> bool:
    env = os.environ.get("RACON_TPU_PALLAS")
    if env is not None:
        return env == "1"
    import jax
    return jax.devices()[0].platform == "tpu"


def _n_devices() -> int:
    import jax
    return len(jax.devices())


def _fits_vmem(cfg, budget_bytes: int = 12 << 20) -> bool:
    """Whether the fused Pallas kernel's VMEM scratch fits the core budget."""
    lp = (cfg.max_len + 1 + 127) // 128 * 128
    h = (cfg.max_nodes + 1) * lp * 4
    layers = 2 * cfg.depth * cfg.max_len * 4
    graph = cfg.max_nodes * (4 * 4 + 2 * cfg.max_edges * 4)
    return h + layers + graph < budget_bytes


def _build_kernel(cfg, B, use_pallas):
    """Single- or multi-device kernel for a B-window batch.

    Multi-device: batch dim sharded over the 1-D `windows` mesh — the
    production analogue of the reference's multi-GPU batch striping
    (src/cuda/cudapolisher.cpp:228-240), with no collectives.
    """
    import jax

    n_dev = _n_devices()
    if use_pallas and not _fits_vmem(cfg):
        # Large window geometries (e.g. -w 1000) overflow the ~16 MB/core
        # VMEM budget of the fused kernel; use the XLA-scheduled variant.
        use_pallas = False
    if use_pallas:
        from . import poa_pallas
        interp = jax.devices()[0].platform != "tpu"
        if n_dev == 1:
            return poa_pallas.build_pallas_poa_kernel(cfg, interpret=interp)(B)
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import AXIS, device_mesh
        mesh = device_mesh()
        local = poa_pallas.build_pallas_poa_kernel(cfg, interpret=interp)(
            B // n_dev)
        spec = P(AXIS)
        return jax.jit(jax.shard_map(
            lambda *args: local(*args), mesh=mesh,
            in_specs=(spec,) * 9, out_specs=(spec,) * 5,
            check_vma=False))
    kernel = poa.build_poa_kernel(cfg)
    if n_dev == 1:
        return kernel
    from ..parallel.mesh import device_mesh, shard_batch_kernel
    return shard_batch_kernel(kernel, device_mesh(), 9)


def _run_chunk(pipeline, kernel, cfg, chunk, trim, stats, fallback,
               use_pallas=False, pad_to=None):
    B = pad_to if pad_to is not None else len(chunk)
    bb = np.zeros((B, cfg.max_backbone), dtype=np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), dtype=np.int32)
    bb_len = np.ones(B, dtype=np.int32)   # padded windows: 1-base backbone
    n_layers = np.zeros(B, dtype=np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.int32)
    lens = np.zeros((B, cfg.depth), dtype=np.int32)
    begins = np.zeros((B, cfg.depth), dtype=np.int32)
    ends = np.zeros((B, cfg.depth), dtype=np.int32)

    for bi, (i, wx, keep) in enumerate(chunk):
        L = len(wx.backbone)
        bb[bi, :L] = encode(wx.backbone)
        bbw[bi, :L] = wx.backbone_weights
        bb_len[bi] = L
        n_layers[bi] = len(keep)
        offsets = np.concatenate([[0], np.cumsum(wx.lens)]).astype(np.int64)
        for li, j in enumerate(keep):
            ll = int(wx.lens[j])
            seqs[bi, li, :ll] = encode(wx.bases[offsets[j]:offsets[j] + ll])
            ws[bi, li, :ll] = wx.weights[offsets[j]:offsets[j] + ll]
            lens[bi, li] = ll
            begins[bi, li] = wx.begins[j]
            ends[bi, li] = wx.ends[j]

    if use_pallas:
        cb, cc, cl, fl, _ = kernel(
            bb_len[:, None], n_layers[:, None], lens, begins, ends,
            bb.astype(np.int32), bbw, seqs.astype(np.int32), ws)
        cons_base = np.asarray(cb)
        cons_cov = np.asarray(cc)
        cons_len = np.asarray(cl)[:, 0]
        failed = np.asarray(fl)[:, 0]
    else:
        cons_base, cons_cov, cons_len, failed, _ = (
            np.asarray(x) for x in kernel(bb, bbw, bb_len, n_layers, seqs,
                                          ws, lens, begins, ends))

    for bi, (i, wx, keep) in enumerate(chunk):
        if failed[bi]:
            fallback.append(i)
            stats["failed"] += 1
            continue
        cl = int(cons_len[bi])
        codes = cons_base[bi, :cl]
        cov = cons_cov[bi, :cl]
        out = np.asarray(codes)
        if wx.is_tgs and trim:
            keep_mask_len = len(keep) + 1  # incorporated sequences incl. backbone
            kept_codes = tgs_trim(out, np.asarray(cov), keep_mask_len)
        else:
            kept_codes = out
        pipeline.set_consensus(i, decode(kept_codes), True)
        stats["device"] += 1
