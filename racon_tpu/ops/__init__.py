"""Device (JAX/XLA/Pallas) kernels: batched POA consensus and batched banded
global alignment, plus their drivers that claim work from the native pipeline
and fall back to the host for anything outside device limits."""

import os

from .. import config


def enable_compilation_cache() -> None:
    """Persist XLA compilations across processes (kernel geometries are
    stable, so repeated CLI/bench invocations skip the expensive compiles).
    Harmless no-op if the backend doesn't support it."""
    try:
        import jax

        cache_dir = config.get_raw("RACON_TPU_COMPILE_CACHE") or os.path.join(
            os.path.expanduser("~"), ".cache", "racon_tpu_xla")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:  # noqa: BLE001 -- cache is an optimization only
        pass


enable_compilation_cache()
