"""Device (JAX/XLA/Pallas) kernels: batched POA consensus and batched banded
global alignment, plus their drivers that claim work from the native pipeline
and fall back to the host for anything outside device limits."""
