"""Batched banded global (NW) alignment on device.

TPU-native replacement for the reference's edlib call on CIGAR-less overlaps
(/root/reference/src/overlap.cpp:205-224) and its CUDA batch analogue
(/root/reference/src/cuda/cudaaligner.cpp). Unit costs, static band per
size bucket (the reference GPU path also aligns banded: auto band = 10% of
mean overlap length, src/cuda/cudapolisher.cpp:159-163).

Formulation: rows i over the query, each row a K-lane vector over band
offsets o, with cell (i, o) <-> target column j = i + dmin + o. The
horizontal (target-gap) dependency is resolved with the affine-transform
cummin: D[i][o] = o + cummin(V[i][o] - o). A 2-bit move per cell (stored as
u8) supports an exact in-band traceback; ops are RLE'd to a CIGAR on host.

In-band paths are valid alignments but may be suboptimal if the true path
leaves the band — same approximation contract as the reference's banded CUDA
aligner, with accuracy pinned by the golden tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import encode
from .kernel_cache import device_keyed_cache

INF = jnp.int32(1 << 28)

# (max sequence length, band width) buckets; larger pairs go to the host.
BUCKETS = ((1024, 256), (2048, 512), (4096, 1024), (8192, 2048))
MAX_DEVICE_LEN = BUCKETS[-1][0]

#: Declared compile budget for the aligner: one jit signature per
#: (cap, band) bucket at the nominal batch.  A deliberate literal (see
#: POA_RECOMPILE_BUDGET in poa_driver.py): adding a bucket without
#: revisiting this number fails the jaxpr audit.
ALIGN_RECOMPILE_BUDGET = 4


def device_eligible(q_len: int, t_len: int) -> bool:
    n, m = int(q_len), int(t_len)
    if n == 0 or m == 0:
        return False
    size = max(n, m)
    for cap, band in BUCKETS:
        if size <= cap:
            return abs(m - n) + 2 <= band
    return False


def _bucket_for(size: int):
    for cap, band in BUCKETS:
        if size <= cap:
            return cap, band
    raise ValueError(size)


@device_keyed_cache(maxsize=16)
def build_align_kernel(cap: int, band: int, shard_n: int = 1):
    """jit kernel over a batch: returns (moves-free) ops + lengths.

    shard_n > 1 constrains every input/output to shard its leading
    (``query``) batch dim over the partitioner's mesh — the pjit path;
    the vmapped XLA program partitions transparently, no per-shard
    rebuild needed.  Callers pad cohorts to a shard_n multiple (the
    executor's pad seam) before dispatching on the sharded kernel."""
    K = band
    PAD = K + 2

    def one(q, t, n, m):
        # q, t: u8 codes padded to cap; n, m actual lengths.
        diff = m - n
        slack = (K - 1 - jnp.abs(diff)) // 2
        dmin = jnp.minimum(0, diff) - slack

        t_pad = jnp.full(cap + 2 * PAD, 255, dtype=jnp.uint8)
        t_pad = jax.lax.dynamic_update_slice(t_pad, t, (PAD,))

        o_vec = jnp.arange(K, dtype=jnp.int32)

        row0_j = dmin + o_vec
        row0 = jnp.where((row0_j >= 0) & (row0_j <= m), row0_j, INF)

        def row_fn(prev_row, xs):
            qc, i = xs  # i = 1..cap
            j_vec = i + dmin + o_vec
            tsl = jax.lax.dynamic_slice(t_pad, (i + dmin - 1 + PAD,), (K,))
            sub = prev_row + jnp.where(tsl == qc, 0, 1)
            up = jnp.concatenate([prev_row[1:], jnp.array([INF])]) + 1
            V = jnp.minimum(sub, up)
            mv = jnp.where(V == sub, jnp.uint8(0), jnp.uint8(1))
            # boundary column j == 0: only vertical moves
            V = jnp.where(j_vec == 0, i, V)
            mv = jnp.where(j_vec == 0, jnp.uint8(1), mv)
            V = jnp.where((j_vec < 0) | (j_vec > m), INF, V)
            # horizontal pass
            row = jax.lax.cummin(V - o_vec) + o_vec
            mv = jnp.where(row < V, jnp.uint8(2), mv)
            row = jnp.where((j_vec < 0) | (j_vec > m), INF, row)
            return row, mv

        ii = jnp.arange(1, cap + 1, dtype=jnp.int32)
        _, moves = jax.lax.scan(row_fn, row0, (q.astype(jnp.uint8), ii))
        # moves[i-1] is row i

        # Traceback from (n, j=m).
        OPS = 2 * cap

        def cond(c):
            i, j, _, cnt, _ = c
            return ((i > 0) | (j > 0)) & (cnt < OPS)

        def body(c):
            i, j, ops, cnt, ok = c
            o = j - i - dmin
            in_band = (o >= 0) & (o < K)
            mv = jnp.where(i > 0,
                           jnp.where(in_band,
                                     moves[jnp.maximum(i - 1, 0),
                                           jnp.clip(o, 0, K - 1)],
                                     jnp.uint8(3)),
                           jnp.uint8(2))  # row 0: consume target
            ok = ok & (mv != 3)
            # 0=M (diag), 1=I (query), 2=D (target)
            ops = ops.at[cnt].set(mv)
            i = jnp.where(mv == 2, i, i - 1)
            j = jnp.where(mv == 1, j, j - 1)
            return (i, j, ops, cnt + 1, ok)

        ops0 = jnp.zeros(OPS, dtype=jnp.uint8)
        i, j, ops, cnt, ok = jax.lax.while_loop(
            cond, body, (n, m, ops0, jnp.int32(0), jnp.bool_(True)))
        ok = ok & (i == 0) & (j == 0)
        return ops, cnt, ok

    if shard_n > 1:
        from ..parallel.partitioner import get_partitioner

        return get_partitioner().partition(
            jax.vmap(one), in_axes=[("query",)] * 4,
            out_axes=("query",))
    return jax.jit(jax.vmap(one))


class _XlaAlignOps:
    """Executor hooks (ops/batch_exec.py) for the moves-matrix aligner.

    The jit kernel call is a JAX async dispatch, so the shared executor
    keeps depth-Q chunks in flight: the host packs chunk N+1 while chunk
    N executes.  Packing is single-copy — each job's bases land once in
    the chunk's padded buffers; lattice retries and bisection probes
    gather rows from the per-job views instead of re-materializing."""

    span_name = "align.cohort"
    async_dispatch = True

    def __init__(self, pipeline, report, stats, state):
        self.pipeline = pipeline
        self.report = report
        self.stats = stats
        self.state = state        # {"served": int}
        self.rows = {}            # job -> (q_row, t_row, n, m)
        self.dead = False

    def live_tier(self, ctx, kind):
        return "host" if self.dead else "xla"

    def export(self, ctx, chunk):
        return list(chunk)

    def pack(self, ctx, chunk):
        cap = ctx["cap"]
        B = len(chunk)
        q = np.zeros((B, cap), dtype=np.uint8)
        t = np.zeros((B, cap), dtype=np.uint8)
        n = np.zeros(B, dtype=np.int32)
        m = np.zeros(B, dtype=np.int32)
        for bi, job in enumerate(chunk):
            qa, ta = self.pipeline.align_job(job)
            q[bi, :len(qa)] = encode(qa)
            t[bi, :len(ta)] = encode(ta)
            n[bi] = len(qa)
            m[bi] = len(ta)
            self.rows[job] = (q[bi], t[bi], n[bi], m[bi])
        return q, t, n, m

    def dispatch(self, ctx, kind, packed, chunk):
        from ..resilience import faults

        faults.check("align.run", chunk)
        kern = ctx["skernel"] if ctx.get("use_shard") else ctx["kernel"]
        return kern(*packed)

    def attempt(self, ctx, kind, sub):
        from ..resilience import faults

        faults.check("align.run", sub)
        q = np.stack([self.rows[j][0] for j in sub])
        t = np.stack([self.rows[j][1] for j in sub])
        n = np.asarray([self.rows[j][2] for j in sub], dtype=np.int32)
        m = np.asarray([self.rows[j][3] for j in sub], dtype=np.int32)
        return tuple(np.asarray(x) for x in ctx["kernel"](q, t, n, m))

    def unpack(self, ctx, kind, outs):
        return tuple(np.asarray(x) for x in outs)

    def span_args(self, ctx, chunk, pipelined):
        return {"cap": ctx["cap"], "jobs": len(chunk)}

    def install(self, ctx, kind, sub, results):
        from ..analysis import sanitize
        from ..resilience import faults

        ops, cnt, ok = results
        if sanitize.enabled():
            sanitize.check_align_outputs(ops, cnt, ok,
                                         where="align.run_jobs")
        for bi, job in enumerate(sub):
            if not ok[bi]:
                continue  # host will align it
            faults.check("align.install", (job,))
            cigar = ops_to_cigar(ops[bi, :cnt[bi]][::-1])
            self.pipeline.set_job_cigar(job, cigar)
            self.state["served"] += 1
            if self.stats is not None:
                self.stats["device"] = self.stats.get("device", 0) + 1
            if self.report is not None:
                self.report.record_served("xla")

    def surrender(self, ctx, items, exported):
        pass  # CIGAR-less jobs fall to the native host pass

    def quarantine(self, ctx, job, exc):
        if self.report is not None:
            self.report.record_quarantine(job, exc)

    def demote(self, ctx, kind, cause):
        import sys

        self.dead = True
        print(f"[racon_tpu::align] WARNING: xla aligner failed "
              f"({type(cause).__name__}: {cause}); remaining jobs "
              f"fall back to the host aligner", file=sys.stderr)
        if self.report is not None:
            self.report.record_degrade("xla", "host", cause)
        return "host"

    def done(self, ctx, chunk):
        # keep host memory O(depth x batch): rows die with the chunk
        for job in chunk:
            self.rows.pop(job, None)

    # -- sharded dispatch (optional executor hooks) ------------------------
    def shard_multiple(self, ctx, chunk):
        # Decided per cohort: the executor pads the packed buffers to
        # the returned multiple, then dispatch() (same submit call)
        # routes to the sharded kernel.  Tail cohorts below the
        # will_shard floor go single-device unpadded.  install() indexes
        # results by real-row position, so the trailing pad rows
        # (repeats of the last job) are computed and dropped.
        ctx["use_shard"] = False
        m = ctx.get("shard_n", 1)
        if m <= 1 or ctx.get("skernel") is None:
            return 1
        from ..parallel.partitioner import get_partitioner

        if not get_partitioner().will_shard(len(chunk)):
            return 1
        ctx["use_shard"] = True
        return m

    def demote_shard(self, ctx, kind, cause):
        if not ctx.get("use_shard"):
            return False
        ctx["use_shard"] = False
        ctx["shard_n"] = 1
        from ..parallel.partitioner import get_partitioner
        from ..resilience import lattice as rl

        if get_partitioner().demote(f"{type(cause).__name__}: {cause}"):
            rl.record_shard_demotion(self.report, kind, cause)
        return True


def run_jobs(pipeline, jobs, batch: int = 16, report=None,
             stats=None, lengths=None) -> int:
    """Align the given pipeline jobs on device; install CIGARs.
    Returns how many alignments the device served.

    Jobs bucket by padded length (lengths only — bases are packed once
    per chunk into padded buffers at dispatch time), and every chunk runs
    through the degradation lattice via the shared executor
    (ops/batch_exec.py): depth-Q async dispatch, bounded retry, then
    bisection so a poisoned job is quarantined to the host while the rest
    of the chunk stays on the device.  A chunk-independent failure stops
    the engine; the served count stays accurate for whatever was already
    installed.

    `lengths` is the bulk job-lengths array (the driver fetches it once
    and threads it through); without it, one bulk fetch happens here.

    ``stats`` (the driver's accounting dict) has its ``'device'`` entry
    incremented per installed CIGAR, so even an exception that escapes
    this function entirely — a kernel build for a later bucket, a
    sanitizer trip, an install failure — cannot zero out work already
    installed (which the driver's host count is derived from)."""
    import sys

    from ..resilience import lattice as rl
    from .. import obs
    from .batch_exec import BatchExecutor

    if lengths is None and hasattr(pipeline, "align_job_lengths"):
        lengths = pipeline.align_job_lengths()
    if lengths is not None:
        maxlen = {j: int(max(lengths[j, 0], lengths[j, 1])) for j in jobs}
    else:  # duck-typed pipelines without the lengths table
        maxlen = {}
        for job in jobs:
            qa, ta = pipeline.align_job(job)
            maxlen[job] = max(len(qa), len(ta))
    # Group by bucket (lengths only, no bases copied yet).
    grouped = {}
    for job in jobs:
        cap, band = _bucket_for(maxlen[job])
        grouped.setdefault((cap, band), []).append(job)

    state = {"served": 0}
    ops_obj = _XlaAlignOps(pipeline, report, stats, state)
    executor = BatchExecutor(ops_obj, report=report)
    try:
        from ..parallel.partitioner import get_partitioner

        part = get_partitioner()
        shard_n = part.batch_axis_size if part.will_shard(batch) else 1
        for (cap, band), items in sorted(grouped.items()):
            kernel = build_align_kernel(cap, band)
            skernel = None
            if shard_n > 1:
                try:
                    skernel = build_align_kernel(cap, band, shard_n)
                except Exception as e:  # noqa: BLE001 — shard edge
                    # sharded wrap failed to build: single-device for
                    # the rest of the process, same tier (never fatal)
                    if part.demote(f"{type(e).__name__}: {e}"):
                        rl.record_shard_demotion(report, "xla", e)
                    shard_n = 1
            obs.count(f"align.bucket.c{cap}", len(items))
            # Measured-cell counter for the cost model (obs/costmodel.py):
            # every job in a bucket pays the full padded cap x band DP.
            obs.count(f"align.cells.c{cap}", len(items) * cap * band)
            ctx = {"cap": cap, "band": band, "kernel": kernel,
                   "skernel": skernel, "shard_n": shard_n}
            for off in range(0, len(items), batch):
                executor.submit(ctx, items[off:off + batch])
            # drain before the next bucket's kernel build so in-flight
            # futures never outlive their geometry's packed buffers
            executor.flush()
    except Exception as e:  # noqa: BLE001 — lattice boundary
        cause = e.cause if isinstance(e, rl.TierDead) else e
        print(f"[racon_tpu::align] WARNING: xla aligner failed "
              f"({type(cause).__name__}: {cause}); remaining jobs "
              f"fall back to the host aligner", file=sys.stderr)
        if report is not None:
            report.record_degrade("xla", "host", cause)
    if report is not None:
        executor.stamp_walls(report)
    return state["served"]


_OPC = np.frombuffer(b"MID", dtype=np.uint8)


def ops_to_cigar(ops: np.ndarray) -> str:
    """Run-length encode forward-ordered op codes (0=M,1=I,2=D)."""
    if len(ops) == 0:
        return ""
    change = np.nonzero(np.diff(ops))[0]
    starts = np.concatenate([[0], change + 1])
    ends = np.concatenate([change + 1, [len(ops)]])
    out = []
    for s, e in zip(starts, ends):
        out.append(f"{e - s}{chr(_OPC[ops[s]])}")
    return "".join(out)
