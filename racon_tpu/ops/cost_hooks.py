"""Shape/cost extraction for kernel builds — the ops-side half of the
analytic cost model (racon_tpu/obs/costmodel.py).

``device_keyed_cache`` calls :func:`record_build` on every builder cache
miss, and ``poa_driver._build_kernel`` does the same for its
topology-keyed front.  The hook maps the builder's shape arguments onto
the closed-form per-unit estimates, so every retroactive ``kernel.build``
span carries ``pred_flops`` / ``pred_hbm_bytes`` / ``pred_serial_steps``
args — the predicted bill for ONE window/job through that kernel, right
next to the measured build wall in the same trace row.

Gated on ``RACON_TPU_COST_MODEL`` (default on) and a no-op whenever obs
is disarmed; anything unrecognized returns ``{}`` rather than guessing.
The in-process registry (:func:`builds`) is what tests and the hw_session
validation step read back.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import config, obs
from ..obs import costmodel

ENV_COST_MODEL = "RACON_TPU_COST_MODEL"

#: Build records this process accumulated: {builder, shape, estimate}.
_BUILDS: List[dict] = []


def enabled() -> bool:
    return obs.enabled() and config.get_bool(ENV_COST_MODEL)


def reset() -> None:
    del _BUILDS[:]


def builds() -> List[dict]:
    return list(_BUILDS)


def _poa_estimate(cfg, tier: str) -> costmodel.CostEstimate:
    # max_backbone is already the 128-ceiled window class (make_config)
    return costmodel.poa_window_cost(cfg.depth, cfg.max_backbone, tier)


def estimate(builder: str, args: tuple,
             kwargs: dict) -> Optional[costmodel.CostEstimate]:
    """Closed-form per-unit cost for a recognized builder signature, or
    None.  Signatures mirror the @device_keyed_cache builders:

    * ``build_align_kernel(cap, band)`` — xla moves-matrix aligner
    * ``build_poa_kernel(cfg)`` — XLA twin
    * ``build_pallas_poa_kernel(cfg, ...)`` / \
      ``build_lockstep_poa_kernel(cfg, ...)`` — v2 / ls tiers
    * ``_build_edge_kernel(rcap, K, ...)`` / ``_build_base_kernel(K,
      ...)`` — Hirschberg pieces (billed as one hirschberg job at the
      kernel's row capacity and band)
    """
    try:
        if builder == "build_align_kernel":
            return costmodel.align_job_cost(int(args[0]), int(args[1]),
                                            "xla")
        if builder == "build_poa_kernel":
            return _poa_estimate(args[0], "xla")
        if builder == "build_pallas_poa_kernel":
            return _poa_estimate(args[0], "v2")
        if builder == "build_lockstep_poa_kernel":
            return _poa_estimate(args[0], "ls")
        if builder == "_build_edge_kernel":
            return costmodel.align_job_cost(int(args[0]), int(args[1]),
                                            "hirschberg")
        if builder == "_build_base_kernel":
            return costmodel.align_job_cost(int(args[0]), int(args[0]),
                                            "hirschberg")
    except (IndexError, TypeError, ValueError, AttributeError):
        return None
    return None


def record_build(builder: str, args: tuple = (),
                 kwargs: Optional[dict] = None) -> Dict[str, float]:
    """Called by the kernel-cache seams on a build.  Returns the span
    args to stamp onto the ``kernel.build`` event ({} when the cost
    model is off or the builder is unrecognized)."""
    if not enabled():
        return {}
    est = estimate(builder, args, kwargs or {})
    if est is None:
        return {}
    _BUILDS.append({"builder": builder, "estimate": est})
    obs.count(f"cost_model.builds.{builder}")
    return {"pred_flops": round(est.flops),
            "pred_hbm_bytes": round(est.hbm_bytes),
            "pred_serial_steps": round(est.serial_steps)}
