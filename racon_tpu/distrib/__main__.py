"""CLI for the distrib coordinator: `racon-tpu distrib [options]
<sequences> <overlaps> <target>` (also `python -m racon_tpu.distrib`).

Polish flags mirror the main CLI; the polished FASTA goes to stdout
(or ``-o``), byte-identical to the single-process run over the same
inputs.  A one-line summary of the fleet accounting lands on stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu distrib",
        description="polish with a fault-tolerant multi-process "
                    "chunk-worker fleet (leases, heartbeats, journal "
                    "resume, speculative re-dispatch; output is "
                    "byte-identical to the single-process CLI)")
    p.add_argument("sequences")
    p.add_argument("overlaps")
    p.add_argument("targets")
    p.add_argument("-u", "--include-unpolished", action="store_true",
                   help="output unpolished target sequences")
    p.add_argument("-f", "--fragment-correction", action="store_true",
                   help="perform fragment correction instead of contig "
                   "polishing")
    p.add_argument("-w", "--window-length", type=int, default=500)
    p.add_argument("-q", "--quality-threshold", type=float, default=10.0)
    p.add_argument("-e", "--error-threshold", type=float, default=0.3)
    p.add_argument("--no-trimming", action="store_true")
    p.add_argument("-m", "--match", type=int, default=3)
    p.add_argument("-x", "--mismatch", type=int, default=-5)
    p.add_argument("-g", "--gap", type=int, default=-4)
    p.add_argument("-t", "--threads", type=int, default=1)
    p.add_argument("--tpu", action="store_true",
                   help="workers run the accelerated path")
    p.add_argument("--workers", type=int, default=None,
                   help="fleet size (default: RACON_TPU_DISTRIB_WORKERS)")
    p.add_argument("--chunks", type=int, default=None,
                   help="target chunk count hint (default: 2x workers)")
    p.add_argument("-o", "--output", metavar="PATH", default=None,
                   help="write the polished FASTA here instead of stdout")
    p.add_argument("--state-dir", metavar="DIR", default=None,
                   help="coordinator working directory holding chunks, "
                   "journals, and worker logs (default: a fresh temp dir)")
    p.add_argument("--timeout", type=float, default=0.0,
                   help="abort the run after this many seconds "
                   "(0 = no deadline)")
    p.add_argument("--report", metavar="PATH", default=None,
                   help="write the coordinator's JSON run report "
                   "(distrib phase: fleet/local serving mix, "
                   "re-dispatches, degradations) to PATH")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="write a Chrome-trace JSON of the coordinator "
                   "(per-chunk dispatch/done events, distrib.* counters) "
                   "to PATH")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    from ..resilience import faults
    try:
        faults.validate_env()
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1

    from .coordinator import Coordinator

    workdir = args.state_dir or tempfile.mkdtemp(prefix="racon-distrib-")
    out_path = args.output or os.path.join(workdir, "polished.fasta")

    from ..obs import flight

    def _on_sigterm(signum, frame):
        # post-mortem before the default die: the coordinator's ring
        # lands next to the worker dumps it would have swept
        flight.dump("sigterm", dir_path=workdir, signal=int(signum))
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _on_sigterm)
    coord = Coordinator(
        args.sequences, args.overlaps, args.targets, workdir,
        args={
            "window_length": args.window_length,
            "quality_threshold": args.quality_threshold,
            "error_threshold": args.error_threshold,
            "trim": not args.no_trimming,
            "fragment_correction": args.fragment_correction,
            "match": args.match, "mismatch": args.mismatch,
            "gap": args.gap, "num_threads": args.threads,
        },
        include_unpolished=args.include_unpolished,
        backend="tpu" if args.tpu else "cpu",
        workers=args.workers, chunks_hint=args.chunks,
        trace_path=args.trace, report_path=args.report)
    try:
        result = coord.run(out_path, timeout=args.timeout or None)
    except (RuntimeError, TimeoutError, OSError) as e:
        print(f"[racon_tpu::distrib] {e}", file=sys.stderr)
        return 1
    print(f"[racon_tpu::distrib] {json.dumps(result['summary'])}",
          file=sys.stderr)
    if args.output is None:
        with open(out_path) as f:
            sys.stdout.write(f.read())
    return 0


if __name__ == "__main__":
    sys.exit(main())
