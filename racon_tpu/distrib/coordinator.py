"""The `racon-tpu distrib` coordinator: chunk fleet with leases.

The coordinator splits the target FASTA into contiguous contig chunks
(``polisher._split_fasta`` — the same base-balanced split the phase
pipeline uses, so chunked output concatenates byte-identically) and
farms them out to a fleet of worker processes over the serve wire
format (newline-JSON over localhost TCP, serve/protocol.py).  Workers
are clients: they connect, say ``hello``, then loop ``fetch`` →
polish → ``result``; a background thread per in-flight chunk sends
``heartbeat`` renewals on a second connection.

Robustness model (the headline, not an afterthought):

* **Leases.**  Every assignment carries a TTL lease.  A heartbeat renews
  it; a lease that outlives its TTL expires and the chunk re-queues with
  exponential backoff (``RACON_TPU_DISTRIB_RETRY_BASE * 2^n``).  A
  worker connection EOF (crash, SIGKILL) expires all of its leases
  immediately — death is detected at socket speed, not TTL speed.
* **Re-dispatch.**  An expired/failed chunk prefers a worker that has
  not attempted it.  The per-chunk journal lives on the shared
  filesystem, so when the previous holder is *known dead* the re-run
  resumes the journaled prefix instead of recomputing
  (resilience/journal.py); a holder that is merely unresponsive keeps
  journal ownership and the re-run writes a fresh side journal — two
  live writers never share a journal file.
* **Speculation.**  An idle worker with no pending work duplicates the
  longest-running chunk once it exceeds ``RACON_TPU_DISTRIB_SPECULATE``
  × the median completed-chunk wall.  The first result to arrive wins;
  later duplicates are discarded deterministically (the chunk is already
  ``done``) and counted.
* **Fleet → local.**  The degradation lattice's next rung up: a chunk
  that exhausts its retry budget — or every chunk, when the fleet
  shrinks to zero — is executed by the coordinator itself through the
  host-oracle CLI (the same demotion target as the serve host lane),
  recorded as a ``fleet → local`` degradation in the run report.

Ordered gather: results install per chunk index and concatenate in
order, so the polished FASTA is byte-identical to a single-process run
(pinned by tests/test_distrib.py and the CI chaos job's ``cmp`` gate).

The lease/chunk lifecycle and the worker-process pool live in
racon_tpu/fleet (leases.py, pool.py) — the shared core this coordinator
and the elastic multi-job FleetPlane both run on.  The coordinator uses
the pool at a fixed size (min == max == ``--workers``); reclaim of a
dead worker's leases passes through the ``lease.reclaim`` fault point.
"""

from __future__ import annotations

import os
import socket
import statistics
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .. import obs
from ..fleet.leases import (Chunk, Lease,  # noqa: F401 — re-exported;
                            # the classes moved to the shared fleet core
                            fire_reclaim_fault, release_worker_leases)
from ..fleet.pool import ElasticPool
from ..obs import context, flight
from ..polisher import _split_fasta
from ..resilience import faults
from ..resilience.report import PhaseReport, RunReport
from ..serve.protocol import read_message, write_message
from ..serve.session import POLISH_ARG_DEFAULTS
from .common import (SCOPED_KNOBS, distrib_fault_worker,
                     distrib_heartbeat, distrib_lease_ttl,
                     distrib_max_retries, distrib_retry_base,
                     distrib_speculate, distrib_workers)

#: Fleet tiers, lattice order (fleet is the device-analogue; local is
#: the coordinator-run oracle floor).
TIERS = ("fleet", "local")


class Coordinator:
    def __init__(self, sequences: str, overlaps: str, target: str,
                 workdir: str, args: Optional[dict] = None,
                 include_unpolished: bool = False, backend: str = "cpu",
                 workers: Optional[int] = None,
                 chunks_hint: Optional[int] = None,
                 lease_ttl: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 trace_path: Optional[str] = None,
                 report_path: Optional[str] = None):
        self.sequences = sequences
        self.overlaps = overlaps
        self.target = target
        self.workdir = workdir
        self.args = dict(POLISH_ARG_DEFAULTS)
        self.args.update(args or {})
        self.include_unpolished = include_unpolished
        self.backend = backend
        self.n_workers = distrib_workers() if workers is None else workers
        self.chunks_hint = chunks_hint
        self.lease_ttl = (distrib_lease_ttl() if lease_ttl is None
                          else lease_ttl)
        self.max_retries = (distrib_max_retries() if max_retries is None
                            else max_retries)
        self.trace_path = trace_path
        self.report_path = report_path

        self.chunks: List[Chunk] = []
        self.counters: Dict[str, int] = {}
        self.completed_walls: List[float] = []
        self.queue_waits: List[float] = []      # eligible→dispatch, s
        self.worker_stats: Dict[int, dict] = {} # per-worker aggregates
        self._staleness_max = 0.0               # worst heartbeat gap, s
        self._ctx: Optional[dict] = None        # fleet trace context
        self._last_tick = 0.0
        self.report = RunReport()
        self.phase = PhaseReport("distrib", TIERS)
        self.report.attach(self.phase)
        self._cv = threading.Condition()
        self._stopping = False
        self._degraded = False
        self._dead_workers = set()
        self._sock: Optional[socket.socket] = None
        self.port = 0
        # fixed-size use of the shared elastic pool: min == max, filled
        # once by start(); spawn failures shrink it, nothing regrows it
        self.pool = ElasticPool(
            logs_dir=os.path.join(workdir, "workers"),
            min_workers=self.n_workers, max_workers=self.n_workers,
            env_fn=self._worker_env,
            on_spawn=lambda i, pid: obs.event("distrib.spawn",
                                              worker=i, pid=pid),
            on_spawn_failure=self._on_spawn_failure)

    # -- counters (mirrored into obs so the coordinator trace carries
    # -- distrib.* series even though the python dict is the source of
    # -- truth when tracing is disarmed) -----------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        # Condition wraps an RLock, so this is safe (and cheap) from
        # call sites that already hold self._cv.
        with self._cv:
            self.counters[name] = self.counters.get(name, 0) + n
        obs.count(f"distrib.{name}", n)

    # -- setup -------------------------------------------------------------

    def _layout(self) -> None:
        chunks_dir = os.path.join(self.workdir, "chunks")
        os.makedirs(chunks_dir, exist_ok=True)
        paths = _split_fasta(self.target, self.chunks_hint or
                             max(2, 2 * self.n_workers), chunks_dir)
        if paths is None:
            # single contig / non-FASTA: one chunk, the whole target
            paths = [self.target]
        for i, p in enumerate(paths):
            cd = os.path.join(chunks_dir, f"chunk{i:03d}")
            os.makedirs(cd, exist_ok=True)
            self.chunks.append(Chunk(i, p, cd))
        self.phase.total = len(self.chunks)

    def _listen(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        t = threading.Thread(target=self._accept_loop,
                             name="distrib-accept", daemon=True)
        t.start()

    def _worker_env(self, index: int) -> dict:
        env = dict(os.environ)
        for k in SCOPED_KNOBS:
            env.pop(k, None)
        # fault scoping: exactly one worker inherits RACON_TPU_FAULT, so
        # a chaos run kills a known worker instead of the whole fleet
        if "RACON_TPU_FAULT" in env and index != distrib_fault_worker():
            env.pop("RACON_TPU_FAULT", None)
        return env

    def _on_spawn_failure(self, index: int, exc: BaseException) -> None:
        # a spawn failure (injected or real) shrinks the fleet; it must
        # not kill the run, which can still finish on fewer workers or
        # degrade to local.  The pool counts spawn_failures.
        self.phase.record_failure("fleet", exc)  # concurrency: invoked from pool.start() before any worker thread exists
        obs.event("distrib.spawn_failed", worker=index,
                  error=f"{type(exc).__name__}: {exc}")

    def _spawn_fleet(self) -> None:
        with self._cv:
            self.pool.port = self.port
            spawned = self.pool.start()
        if spawned:
            self._count("workers_spawned", spawned)

    # -- connection handling ------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return   # socket closed during shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="distrib-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        worker = -1
        try:
            f = conn.makefile("rwb")
            while True:
                try:
                    req = read_message(f)
                    if req is None:
                        break
                    if "worker" in req:
                        worker = int(req["worker"])
                    resp = self._dispatch(req)
                except (ValueError, KeyError, TypeError) as e:
                    resp = {"ok": False, "error": f"{e}"}
                except Exception as e:  # noqa: BLE001 — one bad request
                    # must not take down the coordinator
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                write_message(f, resp)
        except (OSError, BrokenPipeError, ConnectionResetError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # EOF on any of a worker's connections is the fast death
            # signal: a SIGKILLed worker's kernel-closed sockets get its
            # leases expired right now, not a TTL from now
            if worker >= 0:
                self._worker_dead(worker, "connection lost")

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "hello":
            return {"ok": True, "lease_ttl": self.lease_ttl,
                    "heartbeat": distrib_heartbeat(self.lease_ttl)}
        if op == "fetch":
            return self._fetch(int(req["worker"]))
        if op == "heartbeat":
            return self._heartbeat(int(req["worker"]), int(req["chunk"]),
                                   int(req["attempt"]))
        if op == "result":
            return self._result(req)
        if op == "error":
            return self._chunk_error(req)
        if op == "stats":
            return self._stats()
        raise ValueError(f"unknown op {op!r}")

    # -- assignment ---------------------------------------------------------

    def _fetch(self, worker: int) -> dict:
        with self._cv:
            if self._stopping or all(c.state == "done"
                                     for c in self.chunks):
                return {"ok": True, "drain": True}
            now = time.monotonic()
            eligible = [c for c in self.chunks
                        if c.state == "pending" and not c.local
                        and c.next_eligible <= now]
            if eligible:
                # prefer a chunk this worker has not attempted (the
                # "retry on a different worker" rule), then chunk order
                chunk = min(eligible,
                            key=lambda c: (worker in c.tried, c.index))
                return self._assign(chunk, worker, speculative=False)
            chunk = self._straggler(worker, now)
            if chunk is not None:
                return self._assign(chunk, worker, speculative=True)
            return {"ok": True, "wait": True, "poll_s": 0.2}

    def _straggler(self, worker: int, now: float) -> Optional[Chunk]:
        """The longest-running chunk past the speculation threshold that
        `worker` could duplicate (call with the lock held)."""
        factor = distrib_speculate()
        if factor <= 0 or not self.completed_walls:
            return None
        median = statistics.median(self.completed_walls)
        best, best_elapsed = None, 0.0
        for c in self.chunks:
            if (c.state != "running" or c.local or worker in c.tried
                    or len(c.leases) >= 2 or not c.leases):
                continue
            elapsed = now - min(ls.t_start for ls in c.leases.values())
            if elapsed > factor * median and elapsed > best_elapsed:
                best, best_elapsed = c, elapsed
        return best

    def _assign(self, c: Chunk, worker: int, speculative: bool) -> dict:
        c.attempts += 1
        attempt = c.attempts
        c.state = "running"
        c.tried.add(worker)
        # journal ownership: the canonical per-chunk journal resumes a
        # re-dispatch, but only one live writer may ever hold it — a
        # merely-unresponsive holder keeps it and the new attempt gets a
        # fresh side journal
        canonical = not c.journal_held
        if canonical:
            c.journal_held = True
            journal = c.journal
        else:
            journal = os.path.join(c.dir, f"journal.a{attempt}.jsonl")
        c.leases[attempt] = Lease(worker, attempt, self.lease_ttl,
                                  canonical)
        self.queue_waits.append(max(
            0.0, time.monotonic() - max(c.t_pending, c.next_eligible)))
        self._count("dispatches")
        if speculative:
            self._count("speculative")
        if attempt > 1 and not speculative:
            self._count("redispatches")
        # trace-context propagation: each dispatch gets a fresh span id;
        # the worker stamps it as `parent` on its distrib.chunk span, so
        # the merged timeline parents worker spans under this event
        ctx = context.child(self._ctx)
        obs.event("distrib.dispatch", chunk=c.index, worker=worker,
                  attempt=attempt, speculative=speculative,
                  canonical_journal=canonical,
                  trace_id=(ctx or {}).get("trace_id"),
                  span_id=(ctx or {}).get("parent"))
        return {"ok": True, "chunk": {
            "index": c.index, "attempt": attempt,
            "sequences": self.sequences, "overlaps": self.overlaps,
            "target": c.target, "args": self.args,
            "include_unpolished": self.include_unpolished,
            "backend": self.backend, "journal": journal,
            "output": os.path.join(c.dir, f"out.a{attempt}.fasta"),
            "trace": ctx,
        }}

    # -- worker messages ----------------------------------------------------

    def _heartbeat(self, worker: int, index: int, attempt: int) -> dict:
        with self._cv:
            c = self.chunks[index]
            lease = c.leases.get(attempt)
            if lease is None or c.state == "done":
                # the attempt was superseded (lease expired and the
                # chunk re-dispatched, or another attempt won)
                return {"ok": True, "cancel": True}
            now = time.monotonic()
            self._staleness_max = max(self._staleness_max,
                                      now - lease.last_beat)
            lease.last_beat = now
            lease.deadline = now + self.lease_ttl
            self._count("heartbeats")
            return {"ok": True, "cancel": False}

    def _result(self, req: dict) -> dict:
        index = int(req["chunk"])
        attempt = int(req["attempt"])
        stats = req.get("stats") or {}
        with self._cv:
            c = self.chunks[index]
            lease = c.leases.pop(attempt, None)
            if c.state == "done":
                # first result won already; this duplicate is discarded
                # deterministically (its per-attempt output file is
                # never installed)
                self._count("duplicates")
                obs.event("distrib.duplicate", chunk=index,
                          worker=int(req["worker"]), attempt=attempt)
                return {"ok": True, "accepted": False}
            c.state = "done"
            c.served_by = "fleet"
            c.output = str(req["output"])
            c.stats = stats
            self.phase.record_served("fleet")
            if lease is not None:
                wall = time.monotonic() - lease.t_start
                self.completed_walls.append(wall)
                self.phase.add_wall("fleet", wall)
            replayed = int(stats.get("journal_replayed") or 0)
            if replayed:
                self._count("journal_replayed", replayed)
            self._count("chunks_fleet")
            ws = self.worker_stats.setdefault(
                int(req["worker"]),
                {"chunks": 0, "wall_s": 0.0, "kernel_wall_s": 0.0,
                 "rss_mb": 0.0})
            ws["chunks"] += 1
            ws["wall_s"] = round(
                ws["wall_s"] + float(stats.get("wall_s") or 0.0), 4)
            ws["kernel_wall_s"] = round(
                ws["kernel_wall_s"]
                + float(stats.get("kernel_wall_s") or 0.0), 4)
            ws["rss_mb"] = max(ws.get("rss_mb", 0.0),
                               float(stats.get("rss_mb") or 0.0))
            obs.event("distrib.chunk_done", chunk=index,
                      worker=int(req["worker"]), attempt=attempt,
                      replayed=replayed)
            # fold the worker's shipped span buffer + metrics into the
            # coordinator's tracer: the written trace IS the merged
            # multi-process fleet timeline
            absorbed = obs.absorb(req.get("obs"))
            if absorbed:
                self._count("obs_events_absorbed", absorbed)
            self._cv.notify_all()
            return {"ok": True, "accepted": True}

    def _chunk_error(self, req: dict) -> dict:
        index = int(req["chunk"])
        attempt = int(req["attempt"])
        err = str(req.get("error", "worker error"))
        with self._cv:
            c = self.chunks[index]
            lease = c.leases.pop(attempt, None)
            if lease is not None and lease.canonical:
                # the worker survived to report, so its journal writer
                # is closed: the canonical journal is safe to hand on
                c.journal_held = False
            if c.state != "done":
                self._fail_chunk(c, RuntimeError(err))
            obs.event("distrib.chunk_error", chunk=index,
                      worker=int(req["worker"]), attempt=attempt,
                      error=err)
            return {"ok": True}

    def _stats(self) -> dict:
        """The deepened 'stats' wire verb: live fleet telemetry for a
        poller (queue depth, in-flight leases, per-tier served,
        heartbeat staleness) plus the recent telemetry ring."""
        with self._cv:
            now = time.monotonic()
            states = {"pending": 0, "running": 0, "done": 0}
            for c in self.chunks:
                states[c.state] = states.get(c.state, 0) + 1
            leases = sum(len(c.leases) for c in self.chunks)
            staleness = 0.0
            for c in self.chunks:
                for ls in c.leases.values():
                    staleness = max(staleness, now - ls.last_beat)
            self._staleness_max = max(self._staleness_max, staleness)
            return {"ok": True,
                    "chunks": states,
                    "leases": leases,
                    "workers": {"live": self._live_workers(),
                                "dead": len(self._dead_workers)},
                    "served": dict(self.phase.served),
                    "staleness_s": round(staleness, 3),
                    "counters": dict(self.counters),
                    "telemetry": obs.telemetry(last=8)}

    def _queueing_p95(self) -> Optional[float]:
        """p95 of the eligible→dispatch queue waits (None before the
        first dispatch) — the bench telemetry stamp."""
        waits = sorted(self.queue_waits)
        if not waits:
            return None
        return round(waits[min(len(waits) - 1,
                               int(0.95 * len(waits)))], 4)

    def fleet_telemetry(self) -> dict:
        """The per-run fleet telemetry summary stamped into the run
        result and bench entries."""
        return {
            "workers": {str(w): dict(s)
                        for w, s in sorted(self.worker_stats.items())},
            "queueing_p95_s": self._queueing_p95(),
            "staleness_max_s": round(self._staleness_max, 3),
        }

    # -- failure paths (call with the lock held) ----------------------------

    def _fail_chunk(self, c: Chunk, exc: BaseException) -> None:
        c.failures += 1
        self.phase.record_failure("fleet", exc)
        self.phase.retries += 1
        if not c.leases and c.state != "done":
            c.state = "pending"
            backoff = distrib_retry_base() * (2 ** (c.failures - 1))
            c.next_eligible = time.monotonic() + backoff
            self._cv.notify_all()

    def _worker_dead(self, worker: int, why: str) -> None:
        with self._cv:
            if worker in self._dead_workers:
                return
            if self._stopping or all(c.state == "done"
                                     for c in self.chunks):
                return   # clean drain-and-exit, not a death
            self._dead_workers.add(worker)
            self._count("workers_dead")
            obs.event("distrib.worker_dead", worker=worker, cause=why)
            # the reclaim transition is a named fault point: kill=1
            # crashes the coordinator mid-reclaim, a raise is absorbed
            # and counted — the reclaim itself always proceeds
            if fire_reclaim_fault():
                self._count("reclaim_faults")
            for c in self.chunks:
                # a known-dead writer releases the canonical journal so
                # the re-dispatch resumes it
                popped = release_worker_leases(c, worker)
                if popped:
                    self._count("lease_expired", len(popped))
                    if c.state != "done":
                        self._fail_chunk(
                            c, RuntimeError(f"worker {worker} died "
                                            f"({why}) holding chunk "
                                            f"{c.index}"))

    def _expire_leases(self) -> None:
        now = time.monotonic()
        with self._cv:
            for c in self.chunks:
                expired = [a for a, ls in c.leases.items()
                           if ls.deadline < now]
                for a in expired:
                    lease = c.leases.pop(a)
                    # NOT releasing the canonical journal here: an
                    # unresponsive-but-alive holder may still be writing
                    self._count("lease_expired")
                    obs.event("distrib.lease_expired", chunk=c.index,
                              worker=lease.worker, attempt=a)
                    if c.state != "done":
                        self._fail_chunk(
                            c, TimeoutError(
                                f"lease on chunk {c.index} expired "
                                f"(worker {lease.worker}, attempt {a})"))

    # -- fleet -> local degradation -----------------------------------------

    def _live_workers(self) -> int:
        return sum(1 for i in self.pool.alive_indices()
                   if i not in self._dead_workers)

    def _degrade(self, cause: str) -> None:
        """Record the fleet→local lattice step (once per run)."""
        if not self._degraded:
            self._degraded = True
            self.phase.record_degrade("fleet", "local",
                                      RuntimeError(cause))

    def _run_local(self, c: Chunk) -> None:
        """Execute one chunk in the coordinator through the host-oracle
        CLI — the same demotion target as the serve host lane, so the
        output stays byte-identical.  A free canonical journal (cpu
        fingerprint only) is resumed; otherwise a fresh local journal."""
        with self._cv:
            if c.state == "done":
                return
            c.state = "running"
            resume = (not c.journal_held) and self.backend == "cpu"
        journal = c.journal if resume else os.path.join(
            c.dir, "journal.local.jsonl")
        out_path = os.path.join(c.dir, "out.local.fasta")
        part = out_path + ".part"
        a = self.args
        cmd = [sys.executable, "-m", "racon_tpu.cli",
               "-w", str(a["window_length"]),
               "-q", str(a["quality_threshold"]),
               "-e", str(a["error_threshold"]),
               "-m", str(a["match"]), "-x", str(a["mismatch"]),
               "-g", str(a["gap"]), "-t", str(a["num_threads"]),
               "--resume-journal", journal]
        if not a["trim"]:
            cmd.append("--no-trimming")
        if a["fragment_correction"]:
            cmd.append("-f")
        if self.include_unpolished:
            cmd.append("-u")
        cmd += [self.sequences, self.overlaps, c.target]
        env = dict(os.environ)
        for k in SCOPED_KNOBS:
            env.pop(k, None)
        t0 = time.monotonic()
        with open(part, "w") as out_f, \
                open(os.path.join(c.dir, "local.stderr.log"), "w") as err_f:
            rc = subprocess.call(cmd, stdout=out_f, stderr=err_f, env=env)
        with self._cv:
            if c.state == "done":
                self._count("duplicates")   # a late fleet result won
                return
            if rc != 0:
                # the local rung is the floor: a failure here fails the
                # run (reported by run())
                c.state = "pending"
                c.local = True
                self.phase.record_failure(
                    "local", RuntimeError(f"local chunk {c.index} "
                                          f"exited {rc}"))
                raise RuntimeError(
                    f"chunk {c.index} failed on the local rung "
                    f"(exit {rc}; see {c.dir}/local.stderr.log)")
            os.replace(part, out_path)
            c.state = "done"
            c.served_by = "local"
            c.output = out_path
            self.phase.record_served("local")
            self.phase.add_wall("local", time.monotonic() - t0)
            self._count("chunks_local")
            obs.event("distrib.chunk_local", chunk=c.index)
            self._cv.notify_all()

    # -- main loop ----------------------------------------------------------

    def run(self, output_path: str,
            timeout: Optional[float] = None) -> dict:
        obs.reset()
        obs.set_role("coordinator")
        # fleet trace context: minted fresh per run, activated before
        # configure so the tracer stamps it into the file's provenance;
        # _assign derives one child context per dispatch from it
        context.activate(context.fresh())
        obs.configure(trace_path=self.trace_path)
        self._ctx = context.current() if obs.enabled() else None
        faults.reset()
        os.makedirs(self.workdir, exist_ok=True)
        flight.set_dir(self.workdir)
        deadline = (None if not timeout
                    else time.monotonic() + timeout)
        try:
            with obs.span("distrib.run", workers=self.n_workers,
                          backend=self.backend):
                self._layout()
                self._listen()
                self._spawn_fleet()
                try:
                    self._monitor(deadline)
                finally:
                    self._shutdown_fleet()
                self._gather(output_path)
            self.report.finalize()
            # post-mortem sweep: any flight.<pid>.json a crashed/killed
            # worker left in a chunk dir is referenced from the report
            self.report.flight = flight.scan(self.workdir)
            if self.report.flight:
                self._count("flight_dumps", len(self.report.flight))
            # pool counters (spawn_failures, scale_* fault absorbs)
            # merge under the coordinator's own, which win on overlap
            counters = dict(self.pool.counters)
            counters.update(self.counters)
            self.phase.extra.update(counters)
            if self.report_path:
                self.report.write(self.report_path)
            self.report.write_env()
            replayed = self.counters.get("journal_replayed", 0)
            return {
                "output": output_path,
                "chunks": len(self.chunks),
                "workers": self.n_workers,
                "served": dict(self.phase.served),
                "degradations": list(self.phase.degradations),
                "counters": counters,
                "journal_replayed": replayed,
                "report": self.report_path,
                "trace": self.trace_path,
                "telemetry": self.fleet_telemetry(),
                "pool": {"min": self.pool.min_workers,
                         "max": self.pool.max_workers,
                         "timeline": [list(s) for s in
                                      self.pool.size_timeline]},
                "flight": [d.get("path") for d in self.report.flight],
                "summary": self.report.summary(),
            }
        finally:
            # scoped teardown: write the merged trace, then disarm the
            # process-global tracer and trace context so a second
            # in-process run can never append into this run's file
            obs.release(write=True)
            context.clear()

    def _monitor(self, deadline: Optional[float]) -> None:
        while True:
            with self._cv:
                if all(c.state == "done" for c in self.chunks):
                    return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"distrib run exceeded its deadline with "
                    f"{sum(1 for c in self.chunks if c.state != 'done')} "
                    f"chunk(s) unfinished")
            # reap dead worker processes (second death signal, for a
            # worker that died before ever connecting)
            with self._cv:
                reaped = self.pool.reap()
            for i, rc, _was_draining in reaped:
                self._worker_dead(i, f"exited {rc}")
            self._expire_leases()
            now = time.monotonic()
            if now - self._last_tick >= 1.0:
                self._last_tick = now
                with self._cv:
                    staleness = max(
                        (now - ls.last_beat for c in self.chunks
                         for ls in c.leases.values()), default=0.0)
                    self._staleness_max = max(self._staleness_max,
                                              staleness)
                    obs.telemetry_tick(
                        queue_depth=sum(1 for c in self.chunks
                                        if c.state == "pending"),
                        leases=sum(len(c.leases) for c in self.chunks),
                        workers_live=self._live_workers(),
                        staleness_s=round(staleness, 3))
            local_work = []
            with self._cv:
                live = self._live_workers()
                undone = [c for c in self.chunks if c.state != "done"]
                for c in undone:
                    if (c.failures > self.max_retries and not c.leases
                            and c.state == "pending" and not c.local):
                        c.local = True
                        self._degrade(f"chunk {c.index} exhausted its "
                                      f"retry budget ({c.failures} "
                                      f"failures > {self.max_retries})")
                if live == 0 and undone:
                    # fleet collapse: every remaining chunk falls to the
                    # local rung (leases of dead workers are already
                    # expired by _worker_dead)
                    for c in undone:
                        if c.state == "pending" and not c.local:
                            c.local = True
                    if any(c.local for c in undone):
                        self._degrade("fleet collapse: no live workers")
                local_work = [c for c in self.chunks
                              if c.local and c.state == "pending"]
            for c in local_work:
                self._run_local(c)
            with self._cv:
                self._cv.wait(0.05)

    def _shutdown_fleet(self) -> None:
        with self._cv:
            self._stopping = True
        self.pool.shutdown(timeout=5.0)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _gather(self, output_path: str) -> None:
        """Ordered gather: chunk outputs concatenate in chunk order, so
        the result is byte-identical to an unchunked run."""
        part = output_path + ".part"
        with open(part, "wb") as out:
            for c in self.chunks:
                assert c.state == "done" and c.output, c.index
                with open(c.output, "rb") as f:
                    out.write(f.read())
        os.replace(part, output_path)
