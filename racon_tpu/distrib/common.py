"""Shared pieces of the distrib coordinator/worker pair: the knob
accessors (registered in racon_tpu/config.py; README has the docs rows)
and the blocking request/response helper over the serve wire format
(serve/protocol.py — one JSON object per line)."""

from __future__ import annotations

import socket
from typing import Optional

from .. import config
from ..serve.protocol import read_message, write_message


#: Environment a spawned worker must NOT inherit: per-run artifact
#: knobs would make every worker clobber the controller's
#: trace/report/journal.  Shared by the distrib coordinator and the
#: fleet plane's elastic pool.
SCOPED_KNOBS = ("RACON_TPU_TRACE", "RACON_TPU_TRACE_DEVICE",
                "RACON_TPU_METRICS", "RACON_TPU_REPORT",
                "RACON_TPU_JOURNAL")


class WireError(ConnectionError):
    """The peer closed the connection or answered ``ok: false``."""


def rpc(f, msg: dict) -> dict:
    """One request/response exchange on a buffered socket file; raises
    WireError on EOF or an ``ok: false`` answer."""
    write_message(f, msg)
    resp = read_message(f)
    if resp is None:
        raise WireError(f"peer closed the connection (op "
                        f"{msg.get('op')!r})")
    if not resp.get("ok"):
        raise WireError(str(resp.get("error", "request failed")))
    return resp


def fleet_stats(port: int, host: str = "127.0.0.1",
                timeout: float = 5.0) -> dict:
    """One-shot live-telemetry scrape of a running coordinator: open a
    connection, issue the ``stats`` op, close.  Raises WireError/OSError
    when the coordinator is unreachable."""
    with socket.create_connection((host, port), timeout=timeout) as sock:
        with sock.makefile("rwb") as f:
            return rpc(f, {"op": "stats"})


def distrib_workers() -> int:
    return config.get_int("RACON_TPU_DISTRIB_WORKERS")


def distrib_lease_ttl() -> float:
    return config.get_float("RACON_TPU_DISTRIB_LEASE_TTL")


#: Floor on the heartbeat interval: a lease TTL small enough to push
#: TTL/3 below this would turn the worker's renewal loop into a busy
#: spin (and flood the coordinator with heartbeat RPCs).  A tiny TTL
#: still expires leases fast; it just cannot melt the renewal thread.
HEARTBEAT_FLOOR = 0.05


def distrib_heartbeat(ttl: Optional[float] = None) -> float:
    """Heartbeat interval; defaults to a third of the lease TTL so two
    missed beats still renew before the lease expires.  Clamped to
    HEARTBEAT_FLOOR either way — an explicit RACON_TPU_DISTRIB_HEARTBEAT
    or a tiny RACON_TPU_DISTRIB_LEASE_TTL must not busy-spin the
    renewal loop."""
    raw = config.get_raw("RACON_TPU_DISTRIB_HEARTBEAT")
    if raw:
        return max(HEARTBEAT_FLOOR, float(raw))
    return max(HEARTBEAT_FLOOR,
               (distrib_lease_ttl() if ttl is None else ttl) / 3.0)


def distrib_retry_base() -> float:
    return config.get_float("RACON_TPU_DISTRIB_RETRY_BASE")


def distrib_max_retries() -> int:
    return config.get_int("RACON_TPU_DISTRIB_MAX_RETRIES")


def distrib_speculate() -> float:
    return config.get_float("RACON_TPU_DISTRIB_SPECULATE")


def distrib_fault_worker() -> int:
    return config.get_int("RACON_TPU_DISTRIB_FAULT_WORKER")
