"""One chunk-worker of the `racon-tpu distrib` fleet.

A worker is a client of the coordinator (coordinator.py): it opens two
connections — commands and heartbeats — says ``hello``, then loops
``fetch`` → polish → ``result`` until told to ``drain``.  Each fetched
chunk runs through the normal ``create_polisher`` seam with the
coordinator-assigned journal armed for resume, so a chunk re-dispatched
after a crash replays its predecessor's journaled prefix instead of
recomputing (the ``journal_replayed`` count rides back in the result
stats as the proof).  While a chunk is in flight a daemon thread renews
its lease on the heartbeat connection every interval the coordinator
advertised in the ``hello`` response.

Fault points (resilience/faults.py): ``worker.heartbeat`` fires before
every renewal — ``raise`` silently stops renewing (the heartbeat-loss /
straggler path: the lease expires while the polish keeps running),
``kill=1`` SIGKILLs the worker mid-chunk.  ``worker.result`` fires after
the polish is journaled and written but before delivery — ``kill=1``
there is the chaos suite's canonical crash: the re-dispatched chunk
resumes everything from the journal.  The coordinator scopes
``RACON_TPU_FAULT`` to one worker index (RACON_TPU_DISTRIB_FAULT_WORKER)
so a chaos run kills a known worker, not the fleet.

Workers stay resident across chunks: kernel caches (and, on a TPU
backend, compiled geometries) are paid once per worker, not per chunk —
the same hot-kernel economics as `racon-tpu serve`.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading
import time

from .. import obs
from ..obs import context, flight, ledger
from .common import WireError, rpc


def _polish_chunk(a: dict) -> dict:
    """Run one assigned chunk; returns the result stats."""
    from ..polisher import create_polisher
    from ..resilience import budget, faults

    # the memory seam: kill=1 here is a real OOM-style SIGKILL of this
    # worker mid-chunk (scope with RACON_TPU_DISTRIB_FAULT_WORKER) —
    # the lease/journal machinery resumes the chunk byte-identically;
    # a raise is a modeled allocation failure (chunk error, re-queued)
    faults.check("mem.oom")
    t0 = time.monotonic()
    chunk_dir = os.path.dirname(a["output"]) or "."
    # trace-context propagation: the coordinator's dispatch shipped a
    # {trace_id, parent} pair when it is tracing; activating it BEFORE
    # create_polisher matters because the polisher's reset_run_state
    # re-arms obs, and the fresh tracer stamps the active context.
    # A flight dump from this chunk lands in the chunk directory.
    ctx = a.get("trace")
    context.activate(ctx)
    flight.set_dir(chunk_dir)
    trace_path = (os.path.join(chunk_dir, f"trace.a{a['attempt']}.json")
                  if ctx else None)
    polisher = create_polisher(
        a["sequences"], a["overlaps"], a["target"],
        backend=a.get("backend") or "cpu",
        journal_path=a["journal"], resume_journal=True,
        trace_path=trace_path, **(a.get("args") or {}))
    with obs.span("distrib.chunk", chunk=a["index"], attempt=a["attempt"],
                  trace_id=(ctx or {}).get("trace_id"),
                  parent=(ctx or {}).get("parent")):
        polisher.initialize()
        out = polisher.polish(not a.get("include_unpolished"))
    part = a["output"] + ".part"
    with open(part, "w") as f:
        for name, data in out:
            f.write(f">{name}\n{data}\n")
    os.replace(part, a["output"])
    replayed = sum(rep.served.get("journal", 0)
                   for rep in polisher.report.phases.values())
    # kernel wall: tier-attributed serving wall of the two DP phases —
    # the per-worker number the fleet breakdown and bench telemetry use
    kernel_wall = sum(
        sum(rep.wall_s.values())
        for name, rep in polisher.report.phases.items()
        if name in ("alignment", "consensus"))
    # ledger fragment: per-stage compute seconds off this chunk's own
    # report, plus the build/replay overlays from the span histograms
    # (obs/ledger.py vocabulary) — the fleet plane folds these into the
    # owning job's latency ledger
    stage_s = ledger.stage_seconds(polisher.report.summary())
    stage_s.update(ledger.overlay_seconds(obs.snapshot()))
    # per-worker peak RSS rides back in the stats (the coordinator /
    # fleet plane track the max per worker into fleet_telemetry()) and
    # lands as a trace instant for the `obs fleet` per-pid RSS column
    rss = round(budget.peak_rss_mb(), 1)
    obs.event("mem.rss", rss_mb=rss, chunk=a["index"])
    return {
        "wall_s": round(time.monotonic() - t0, 4),
        "records": len(out),
        "polished_bp": sum(len(data) for _, data in out),
        "journal_replayed": replayed,
        "kernel_wall_s": round(kernel_wall, 4),
        "rss_mb": rss,
        "stage_s": stage_s,
    }


def _heartbeat_loop(hb_f, worker: int, index: int, attempt: int,
                    interval: float, stop: threading.Event) -> None:
    """Renew the chunk lease until told to stop.  Any failure —
    injected (worker.heartbeat) or real — silently ends renewal: the
    coordinator's lease TTL turns heartbeat loss into re-dispatch."""
    from ..resilience import faults

    while not stop.wait(interval):
        try:
            faults.check("worker.heartbeat")
            resp = rpc(hb_f, {"op": "heartbeat", "worker": worker,
                              "chunk": index, "attempt": attempt})
        except Exception:  # noqa: BLE001 — heartbeat loss is a modeled
            # failure mode, not a crash: the lease expires and the
            # coordinator re-dispatches
            return
        if resp.get("cancel"):
            return   # superseded; no point renewing a dead lease


def run_worker(port: int, worker: int, poll_s: float = 0.2) -> int:
    from ..resilience import faults

    main_sock = socket.create_connection(("127.0.0.1", port), timeout=600)
    hb_sock = socket.create_connection(("127.0.0.1", port), timeout=600)
    main_f = main_sock.makefile("rwb")
    hb_f = hb_sock.makefile("rwb")
    hello = rpc(main_f, {"op": "hello", "worker": worker})
    interval = float(hello.get("heartbeat") or 1.0)

    chunks_done = 0
    while True:
        resp = rpc(main_f, {"op": "fetch", "worker": worker})
        if resp.get("drain"):
            break
        if resp.get("wait"):
            time.sleep(float(resp.get("poll_s") or poll_s))
            continue
        a = resp["chunk"]
        stop = threading.Event()
        hb = threading.Thread(
            target=_heartbeat_loop,
            args=(hb_f, worker, a["index"], a["attempt"], interval, stop),
            name="distrib-heartbeat", daemon=True)
        hb.start()
        try:
            stats = _polish_chunk(a)
        except Exception as e:  # noqa: BLE001 — a failed chunk is
            # reported and the worker lives on to fetch the next one
            stop.set()
            hb.join()
            flight.dump("chunk_error", chunk=a["index"],
                        attempt=a["attempt"],
                        error=f"{type(e).__name__}: {e}")
            obs.release(write=False)
            rpc(main_f, {"op": "error", "worker": worker,
                         "chunk": a["index"], "attempt": a["attempt"],
                         "error": f"{type(e).__name__}: {e}"})
            continue
        stop.set()
        hb.join()
        # ship this chunk's span buffer + metrics snapshot with the
        # result (None when tracing is disarmed — the field stays off
        # the wire), then scope the per-chunk tracer out so the next
        # chunk cannot append into this chunk's trace file
        ship = obs.shipment()
        obs.release(write=True)
        # the chaos seam: the chunk is fully journaled and its output
        # written, but the result is not yet delivered — kill=1 here is
        # the canonical mid-chunk crash the resume path must absorb
        faults.check("worker.result")
        msg = {"op": "result", "worker": worker,
               "chunk": a["index"], "attempt": a["attempt"],
               "output": a["output"], "stats": stats}
        if ship is not None:
            msg["obs"] = ship
        rpc(main_f, msg)
        chunks_done += 1
    for f, s in ((main_f, main_sock), (hb_f, hb_sock)):
        try:
            f.close()
            s.close()
        except OSError:
            pass
    return chunks_done


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="racon-tpu distrib worker",
        description="one chunk-worker process of a racon-tpu distrib "
                    "fleet (spawned by the coordinator; not normally "
                    "run by hand)")
    p.add_argument("--port", type=int, required=True,
                   help="coordinator TCP port on 127.0.0.1")
    p.add_argument("--worker", type=int, required=True,
                   help="this worker's index in the fleet")
    args = p.parse_args(argv)
    obs.set_role(f"worker{args.worker}")

    def _on_sigterm(signum, frame):
        # post-mortem before dying: the ring of recent spans/events
        # lands in the current chunk directory (set per fetch)
        flight.dump("sigterm", signal=int(signum))
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        done = run_worker(args.port, args.worker)
    except WireError as e:
        # coordinator went away: exit quietly — the run is over (or the
        # coordinator crashed, which its own caller reports)
        print(f"[racon_tpu::distrib] worker {args.worker}: {e}",
              file=sys.stderr)
        return 1
    print(f"[racon_tpu::distrib] worker {args.worker} drained after "
          f"{done} chunk(s)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
