"""Fault-tolerant multi-process polishing: `racon-tpu distrib`.

A coordinator (coordinator.py) splits the target FASTA into contig
chunks and farms them out to a fleet of worker processes (worker.py)
over the serve wire format, with lease-based assignment, heartbeat
renewal, exponential-backoff re-dispatch, speculative straggler
duplication, per-chunk journal resume, and a fleet→local degradation
rung when the fleet shrinks to zero.  Ordered gather keeps the output
byte-identical to a single-process run.  See docs/architecture.md,
"Distributed polishing".
"""

from .common import WireError
from .coordinator import Chunk, Coordinator, Lease

__all__ = ["Chunk", "Coordinator", "Lease", "WireError"]
