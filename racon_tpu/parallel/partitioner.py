"""Hardware mesh discovery + the Partitioner that shards grid kernels.

This is the multi-chip half of ROADMAP item 2: a real partitioning
subsystem in the T5X mold (SNIPPETS.md [1]-[3]) sized for this repo's
two embarrassingly parallel hot paths.  Three layers:

* **mesh discovery** — ``mesh_shape()`` resolves the
  ``RACON_TPU_MESH_SHAPE`` knob against the live device set;
  ``build_mesh()`` materializes a 2-D ``jax.sharding.Mesh`` over
  ``axes.MESH_AXES``: a hybrid ICI×DCN mesh on multi-host TPU
  topologies (``mesh_utils.create_hybrid_device_mesh``, so the
  data-parallel axis stripes across hosts without tripping over
  non-contiguous device order), a flat reshape of ``jax.devices()``
  everywhere else (CPU, single-host TPU, and the CI
  ``xla_force_host_platform_device_count`` virtual mesh).

* **the Partitioner** — wraps any grid kernel for the mesh two ways:
  ``partition()`` jits with NamedSharding in/out constraints (the pjit
  path; right for XLA-tier kernels, which partition transparently), and
  ``shard_build()`` wraps a per-shard kernel *builder* in shard_map (the
  Pallas path, where each device must trace a kernel of the local batch
  size).  Both resolve dim specs through the logical-axis rules in
  ``parallel/axes.py`` so no kernel ever names a mesh axis.  Padding
  math (``pad_rows``/``pad_packed``) and the ``will_shard`` gate live
  here too so every caller pads identically — the round-DOWN remainder
  spill the old ``divisible_batch`` forced on the consensus driver is
  replaced by round-UP padding accounted in stats.

* **demotion state** — a sharded compile failure or device loss calls
  ``demote(cause)``; the partitioner then answers ``will_shard() ->
  False`` for the rest of the process and every caller falls back to
  its existing single-device build (the ``sharded -> single-device``
  lattice edge; see resilience/lattice.record_shard_demotion).  Output
  stays byte-identical because sharding only ever changes *where* rows
  compute, never what is computed.

``get_partitioner()`` is memoized through the topology-keyed
``ops/kernel_cache.device_keyed_cache`` with the mesh shape and rule
set as explicit key components, so reconfiguring devices, the mesh
knob, or the rules never serves a stale mesh wrap.
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import config
from ..ops.kernel_cache import device_keyed_cache
from . import axes
from .mesh import resolve_shard_map


def _warn(msg: str) -> None:
    print(f"[racon-tpu] {msg}", file=sys.stderr)


# --------------------------------------------------------------------------
# mesh discovery
# --------------------------------------------------------------------------

def mesh_shape(n_devices: Optional[int] = None) -> Tuple[int, int]:
    """(data, model) mesh shape from ``RACON_TPU_MESH_SHAPE``.

    Accepted spellings: ``"8"`` -> (8, 1); ``"4,2"`` / ``"4x2"`` ->
    (4, 2).  Unset defaults to (n_devices, 1) — every device on the
    data-parallel axis.  A shape asking for more devices than exist (or
    unparseable text) falls back to the default with a warning rather
    than failing: mis-set knobs degrade, they don't kill a polish."""
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    raw = config.get_str("RACON_TPU_MESH_SHAPE").strip()
    if not raw:
        return (n_devices, 1)
    try:
        parts = [int(p) for p in raw.replace("x", ",").split(",")
                 if p.strip()]
    except ValueError:
        parts = []
    if len(parts) == 1:
        parts.append(1)
    if (len(parts) != 2 or any(p < 1 for p in parts)
            or parts[0] * parts[1] > n_devices):
        _warn(f"RACON_TPU_MESH_SHAPE={raw!r} invalid for {n_devices} "
              f"device(s); using ({n_devices}, 1)")
        return (n_devices, 1)
    return (parts[0], parts[1])


def build_mesh(shape: Optional[Tuple[int, int]] = None):
    """A 2-D Mesh over ``axes.MESH_AXES`` for the current device set.

    Multi-host TPU topologies get ``create_hybrid_device_mesh`` (ICI
    within a host, DCN across hosts — SNIPPETS.md [1]); anything else
    gets a flat reshape of ``jax.devices()`` in enumeration order, which
    is exactly what the CI forced-host CPU mesh and single-host silicon
    want.  Uses the first data*model devices when the shape deliberately
    under-subscribes the machine."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if shape is None:
        shape = mesh_shape(len(devs))
    data, model = shape
    if (jax.process_count() > 1 and devs[0].platform == "tpu"
            and data % jax.process_count() == 0):
        from jax.experimental import mesh_utils

        try:
            arr = mesh_utils.create_hybrid_device_mesh(
                (data // jax.process_count(), model),
                (jax.process_count(), 1))
            return Mesh(arr, axes.MESH_AXES)
        except Exception as exc:  # noqa: BLE001 — hybrid mesh construction is best-effort; any topology error falls back to the flat mesh
            _warn(f"hybrid mesh ({data},{model}) failed ({exc!r}); "
                  f"using flat device order")
    arr = np.asarray(devs[:data * model], dtype=object).reshape(
        (data, model))
    return Mesh(arr, axes.MESH_AXES)


# --------------------------------------------------------------------------
# the Partitioner
# --------------------------------------------------------------------------

class Partitioner:
    """Shards grid kernels over a concrete mesh via logical-axis rules.

    Not callable on purpose: instances pass through
    ``analysis.sanitize.wrap_kernel`` unchanged when memoized through
    the kernel cache."""

    def __init__(self, mesh, rules: axes.Rules):
        axes.validate_rules(rules, tuple(mesh.shape))
        self.mesh = mesh
        self.rules = rules
        self._disabled: Optional[str] = None  # demotion cause, sticky

    # -- topology ----------------------------------------------------------

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @property
    def batch_axis_size(self) -> int:
        """Shard count along the batch (``windows``) logical axis — the
        device-count multiple every sharded batch must pad to."""
        mesh_axis = dict(self.rules).get("windows")
        if mesh_axis is None:
            return 1
        return int(self.mesh.shape[mesh_axis])

    @property
    def disabled(self) -> Optional[str]:
        return self._disabled

    def demote(self, cause: str) -> bool:
        """Permanently drop to single-device dispatch.  Returns True the
        first time (callers log/record the lattice edge exactly once)."""
        first = self._disabled is None
        self._disabled = str(cause)
        return first

    # -- spec resolution ---------------------------------------------------

    def spec(self, *logical: Optional[str]):
        """PartitionSpec for an array whose dims carry these logical
        axis names (None entries = replicated dims)."""
        return axes.resolve_spec(logical, self.rules, tuple(self.mesh.shape))

    def sharding(self, *logical: Optional[str]):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.spec(*logical))

    # -- kernel wrapping ---------------------------------------------------

    def partition(self, fn, in_axes: Sequence, out_axes):
        """jit ``fn`` with sharding constraints resolved from logical
        axes — the pjit path for XLA-tier kernels.

        ``in_axes`` is one logical-axis tuple per input; ``out_axes`` is
        a single tuple (one output) or a tuple of tuples."""
        import jax

        in_sh = tuple(self.sharding(*a) for a in in_axes)
        if (isinstance(out_axes, (list, tuple)) and out_axes
                and isinstance(out_axes[0], (list, tuple))):
            out_sh = tuple(self.sharding(*a) for a in out_axes)
        else:
            out_sh = self.sharding(*out_axes)
        return jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh)

    def shard_build(self, build_local, batch: int, n_in: int, n_out: int):
        """shard_map wrap of a per-shard kernel *builder* — the Pallas
        path, where each device traces a kernel of the local batch size.
        Every input/output is sharded on the leading ``windows`` dim.
        Returns None when this batch shouldn't shard (caller keeps its
        single-device build)."""
        import jax

        m = self.batch_axis_size
        if self._disabled is not None or m <= 1 or batch % m or batch < m:
            return None
        local = build_local(batch // m)
        spec = self.spec("windows")
        out_specs = (spec,) * n_out if n_out > 1 else spec
        smap, no_check = resolve_shard_map()
        return jax.jit(smap(
            lambda *a: local(*a), mesh=self.mesh,
            in_specs=(spec,) * n_in, out_specs=out_specs, **no_check))

    # -- batch padding (satellite: the one place pad math lives) -----------

    def pad_rows(self, n: int) -> int:
        """Smallest batch >= n that divides over the batch axis — the
        round-UP replacement for mesh.divisible_batch's round-DOWN."""
        m = self.batch_axis_size
        return max(1, (max(n, 1) + m - 1) // m) * m

    def pad_packed(self, packed, pad_to: Optional[int] = None):
        """Pad every array's leading dim to a batch-axis multiple (or to
        ``pad_to``) by repeating the final row — always a valid, already
        computed-for row, so padded lanes do real-but-discarded work and
        can never poison the kernel.  Returns (padded tuple, n_pad)."""
        rows = int(np.asarray(packed[0]).shape[0])
        target = self.pad_rows(rows) if pad_to is None else int(pad_to)
        pad = target - rows
        if pad <= 0:
            return tuple(packed), 0
        out = []
        for a in packed:
            a = np.asarray(a)
            out.append(np.concatenate(
                [a, np.repeat(a[-1:], pad, axis=0)], axis=0))
        return tuple(out), pad

    # -- dispatch gate -----------------------------------------------------

    def will_shard(self, batch: int) -> bool:
        """Whether a batch of this many rows should dispatch over the
        mesh: sharding enabled (``RACON_TPU_SHARD`` != 0), not demoted,
        >1 shard on the batch axis, and batch at least
        ``RACON_TPU_SHARD_MIN_BATCH`` (default: one row per shard) so
        tiny tails aren't padded up just to ship one window per chip."""
        if self._disabled is not None:
            return False
        if config.get_raw("RACON_TPU_SHARD") == "0":
            return False
        m = self.batch_axis_size
        if m <= 1:
            return False
        min_batch = config.get_int("RACON_TPU_SHARD_MIN_BATCH")
        return batch >= (min_batch if min_batch > 0 else m)


# --------------------------------------------------------------------------
# topology-keyed singleton
# --------------------------------------------------------------------------

@device_keyed_cache(maxsize=8)
def _build_partitioner(shape: Tuple[int, int], rules: axes.Rules):
    return Partitioner(build_mesh(shape), rules)


def get_partitioner() -> Partitioner:
    """The process-wide Partitioner for the current topology, mesh-shape
    knob, and rule set.  Demotion state rides on the memoized instance,
    so one sharded compile failure disables sharding for every
    subsequent caller on the same topology (tests reset via
    ``reset_partitioner``)."""
    return _build_partitioner(mesh_shape(), axes.rules_key())


def reset_partitioner() -> None:
    """Drop memoized partitioners (and their demotion state)."""
    _build_partitioner.cache_clear()
