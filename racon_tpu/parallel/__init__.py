"""Multi-chip parallelism: window batches are sharded data-parallel over a
`jax.sharding.Mesh` (windows are independent POA problems — the reference's
multi-GPU batch striping, src/cuda/cudapolisher.cpp:165-180,228-240, maps to
batch-dim sharding over ICI; multi-host scales by sharding contigs/windows
over DCN with an ordered host gather, no collectives needed).

Layout: ``axes`` holds the logical-axis rule registry
(windows/query/depth/lane -> mesh axes), ``partitioner`` the mesh
discovery + Partitioner that wraps kernels via pjit/shard_map, ``mesh``
the jax-version shard_map shim and legacy 1-D helpers."""

from .mesh import (  # noqa: F401
    device_mesh, divisible_batch, resolve_shard_map, shard_batch_kernel)
from .partitioner import (  # noqa: F401
    Partitioner, build_mesh, get_partitioner, mesh_shape,
    reset_partitioner)
