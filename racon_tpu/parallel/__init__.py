"""Multi-chip parallelism: window batches are sharded data-parallel over a
`jax.sharding.Mesh` (windows are independent POA problems — the reference's
multi-GPU batch striping, src/cuda/cudapolisher.cpp:165-180,228-240, maps to
batch-dim sharding over ICI; multi-host scales by sharding contigs/windows
over DCN with an ordered host gather, no collectives needed)."""

from .mesh import (  # noqa: F401
    device_mesh, divisible_batch, shard_batch_kernel)
