"""Mesh construction and batch-dim sharding for the device kernels.

The consensus and alignment workloads are embarrassingly parallel across
windows/overlap pairs, so the natural mesh is 1-D: every kernel input/output
carries a leading batch axis sharded over the `windows` mesh axis; XLA
partitions the program with zero collectives and results gather back to host
in order (the stitch loop is strictly ordered — reference:
src/polisher.cpp:510-537).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "windows"

# jax >= 0.7 promotes shard_map to the public namespace and renames the
# replication-check kwarg check_rep -> check_vma; 0.4.x only has the
# experimental spelling.


def resolve_shard_map(jax_mod=None):
    """(shard_map callable, replication-check-off kwargs) for this jax.

    The version shim, factored out so tests can drive BOTH branches with
    stand-in modules (a jax bump that moves/renames shard_map again must
    fail a test, not silently kill the sharded tier).  ``jax_mod``
    defaults to the real ``jax``."""
    mod = jax if jax_mod is None else jax_mod
    fn = getattr(mod, "shard_map", None)
    if fn is not None:
        return fn, {"check_vma": False}
    sub = getattr(mod.experimental, "shard_map", None)
    if sub is None:
        import importlib
        sub = importlib.import_module(
            mod.__name__ + ".experimental.shard_map")
    return sub.shard_map, {"check_rep": False}


_shard_map, _NO_CHECK = resolve_shard_map()


def device_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    import numpy as np
    return Mesh(np.array(devs), (AXIS,))


def shard_batch_kernel(fn, mesh: Mesh, n_in: int):
    """jit `fn` with every one of its `n_in` array inputs (and all outputs)
    sharded on the leading batch dimension over the mesh."""
    batch = NamedSharding(mesh, P(AXIS))
    return jax.jit(fn, in_shardings=(batch,) * n_in,
                   out_shardings=batch)


def shard_batch_build(build_local, batch, n_in, n_out):
    """Batch-stripe a per-shard kernel BUILD over the 1-D `windows` mesh:
    `build_local(batch // n_devices)` is wrapped in shard_map with every
    input/output sharded on the leading batch dim — zero collectives,
    results gather host-side in order. The shared wrap for both pallas
    drivers (consensus poa_driver._build_kernel, aligner align_pallas);
    reference analogue: per-device accelerator batches
    (src/cuda/cudapolisher.cpp:96-114, 228-240). Returns None when the
    batch doesn't divide over >1 devices and the plain single-device jit
    is the right call."""
    n_dev = len(jax.devices())
    if n_dev <= 1 or batch < n_dev or batch % n_dev:
        return None
    local = build_local(batch // n_dev)
    out_specs = (P(AXIS),) * n_out if n_out > 1 else P(AXIS)
    return jax.jit(_shard_map(
        lambda *a: local(*a), mesh=device_mesh(),
        in_specs=(P(AXIS),) * n_in, out_specs=out_specs,
        **_NO_CHECK))


def divisible_batch(n_devices: int, b: int) -> int:
    """Largest batch size <= max(b, n_devices) that divides evenly over the
    mesh.  LEGACY round-DOWN: remainder windows spilled to the slow path.
    The drivers now round UP via ``partitioner.Partitioner.pad_rows`` and
    count the padding in stats; kept for callers that need the old
    semantics (and for the regression test pinning the difference)."""
    return max(1, b // n_devices) * n_devices
