"""Mesh construction and batch-dim sharding for the device kernels.

The consensus and alignment workloads are embarrassingly parallel across
windows/overlap pairs, so the natural mesh is 1-D: every kernel input/output
carries a leading batch axis sharded over the `windows` mesh axis; XLA
partitions the program with zero collectives and results gather back to host
in order (the stitch loop is strictly ordered — reference:
src/polisher.cpp:510-537).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "windows"

# jax >= 0.7 promotes shard_map to the public namespace and renames the
# replication-check kwarg check_rep -> check_vma; 0.4.x only has the
# experimental spelling.  Resolve once at import so shard_batch_build
# works on both.
try:
    _shard_map = jax.shard_map
    _NO_CHECK = {"check_vma": False}
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map
    _NO_CHECK = {"check_rep": False}


def device_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    import numpy as np
    return Mesh(np.array(devs), (AXIS,))


def shard_batch_kernel(fn, mesh: Mesh, n_in: int):
    """jit `fn` with every one of its `n_in` array inputs (and all outputs)
    sharded on the leading batch dimension over the mesh."""
    batch = NamedSharding(mesh, P(AXIS))
    return jax.jit(fn, in_shardings=(batch,) * n_in,
                   out_shardings=batch)


def shard_batch_build(build_local, batch, n_in, n_out):
    """Batch-stripe a per-shard kernel BUILD over the 1-D `windows` mesh:
    `build_local(batch // n_devices)` is wrapped in shard_map with every
    input/output sharded on the leading batch dim — zero collectives,
    results gather host-side in order. The shared wrap for both pallas
    drivers (consensus poa_driver._build_kernel, aligner align_pallas);
    reference analogue: per-device accelerator batches
    (src/cuda/cudapolisher.cpp:96-114, 228-240). Returns None when the
    batch doesn't divide over >1 devices and the plain single-device jit
    is the right call."""
    n_dev = len(jax.devices())
    if n_dev <= 1 or batch < n_dev or batch % n_dev:
        return None
    local = build_local(batch // n_dev)
    out_specs = (P(AXIS),) * n_out if n_out > 1 else P(AXIS)
    return jax.jit(_shard_map(
        lambda *a: local(*a), mesh=device_mesh(),
        in_specs=(P(AXIS),) * n_in, out_specs=out_specs,
        **_NO_CHECK))


def divisible_batch(n_devices: int, b: int) -> int:
    """Largest batch size <= max(b, n_devices) that divides evenly over the
    mesh (the consensus driver rounds DOWN so per-device memory stays within
    the configured budget)."""
    return max(1, b // n_devices) * n_devices
