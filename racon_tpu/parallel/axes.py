"""Logical axis rules: named kernel-grid axes resolved to mesh axes.

Every device kernel in this repo works on arrays whose dimensions carry
one of four *logical* meanings, independent of which kernel or tier is
running:

* ``windows`` — the batch of independent POA problems (the consensus
  kernels' leading dim; the reference's per-GPU batch striping axis);
* ``query``   — the batch of independent alignment jobs/tasks (the
  aligner kernels' leading dim — same data-parallel role as ``windows``,
  named separately so the two phases can be steered independently);
* ``depth``   — the per-window layer dim (sequences stacked on a
  backbone);
* ``lane``    — the 128-lane base/column dims (backbone positions, DP
  columns, packed words).  Lane dims feed Mosaic tilings and masked
  reductions and must stay whole on every device.

A *rule set* maps each logical axis to a mesh axis name (or ``None`` =
replicated), the T5X ``logical_axis_rules`` pattern (SNIPPETS.md [2]).
``resolve_spec`` turns a tuple of logical names — one per array dim —
into a ``jax.sharding.PartitionSpec`` against a concrete mesh, which is
how the partitioner (parallel/partitioner.py) derives pjit sharding
constraints and shard_map specs without any kernel knowing mesh axis
names.

Only the stdlib + jax.sharding types are imported here; no backend is
touched, so the module is importable before device configuration.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from jax.sharding import PartitionSpec

#: Mesh axis names, in mesh-shape order.  ``data`` carries the
#: embarrassingly parallel batch axes (windows/query); ``model`` exists
#: for rule experiments that split a non-batch dim (depth) — size 1 on
#: the default mesh, so the default rules below are a no-op over it.
MESH_AXES: Tuple[str, ...] = ("data", "model")

#: The logical axis vocabulary.  Unknown names are a hard error in
#: resolve_spec — a typo'd axis must not silently replicate.
LOGICAL_AXES: Tuple[str, ...] = ("windows", "query", "depth", "lane")

#: One (logical axis, mesh axis | None) pair per logical axis.
Rules = Tuple[Tuple[str, Optional[str]], ...]

#: Default rules: both batch axes data-parallel, depth on the (size-1 by
#: default) model axis, lane dims always replicated/whole.
DEFAULT_RULES: Rules = (
    ("windows", "data"),
    ("query", "data"),
    ("depth", "model"),
    ("lane", None),
)

_RULES: Rules = DEFAULT_RULES


def get_rules() -> Rules:
    """The active rule set (module-level registry; DEFAULT_RULES unless
    overridden)."""
    return _RULES


def set_rules(rules: Rules) -> None:
    """Install a new active rule set (validated lazily against the mesh
    by the partitioner).  Used by tests and rule experiments."""
    global _RULES
    _RULES = tuple(rules)


def rules_key() -> Rules:
    """Hashable identity of the active rules — part of the partitioner's
    memoization key so a rule override never serves a stale mesh wrap."""
    return _RULES


def validate_rules(rules: Rules, mesh_axes: Sequence[str]) -> None:
    """Every rule must name a known logical axis and an existing mesh
    axis (or None); duplicate logical names are an error."""
    seen = set()
    for logical, mesh_axis in rules:
        if logical not in LOGICAL_AXES:
            raise ValueError(
                f"unknown logical axis {logical!r}; known: {LOGICAL_AXES}")
        if logical in seen:
            raise ValueError(f"duplicate rule for logical axis {logical!r}")
        seen.add(logical)
        if mesh_axis is not None and mesh_axis not in mesh_axes:
            raise ValueError(
                f"rule {logical!r} -> {mesh_axis!r}: mesh has no such "
                f"axis (axes: {tuple(mesh_axes)})")


def resolve_spec(logical_axes: Sequence[Optional[str]],
                 rules: Rules,
                 mesh_axes: Sequence[str]) -> PartitionSpec:
    """One PartitionSpec entry per array dim from its logical axis names.

    ``None`` entries (and logical axes whose rule maps to ``None``)
    resolve to a replicated dim.  Scalar/0-d arrays pass ``()`` and get
    the empty spec (SNIPPETS.md [3]'s scalar convention)."""
    table = dict(rules)
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        if name not in LOGICAL_AXES:
            raise ValueError(
                f"unknown logical axis {name!r}; known: {LOGICAL_AXES}")
        mesh_axis = table.get(name)
        if mesh_axis is not None and mesh_axis not in mesh_axes:
            raise ValueError(
                f"rule {name!r} -> {mesh_axis!r} names a mesh axis "
                f"absent from this mesh (axes: {tuple(mesh_axes)})")
        out.append(mesh_axis)
    return PartitionSpec(*out)
