"""Polishing-as-a-service: the resident `racon-tpu serve` daemon.

Every CLI invocation pays parse + kernel build + bucket-grid warmup from
scratch; this package keeps the expensive state resident and streams
jobs through it (ROADMAP open item #1 — the SeGraM/gpuPairHMM serving
pattern applied to the polish pipeline):

* ``session``   — PolishSession: one process, many polishes.  Kernels
  stay hot in the topology-keyed ``ops/kernel_cache`` across jobs; the
  consensus geometries are pre-compiled once at startup
  (``poa_driver.warm_geometries``); per-request state (journal, report,
  trace, fault schedule) is isolated per job directory.
* ``scheduler`` — queue-based job scheduler multiplexing N concurrent
  jobs onto one device set: admission control (bounded queue depth +
  per-job window budget), per-submitter round-robin fairness, and the
  degradation lattice extended one level up — a job that overruns its
  budget or fails on the device lane is demoted to a host-lane CLI
  subprocess (byte-identical output) instead of stalling the queue.
* ``server`` / ``client`` — localhost TCP daemon speaking a newline-JSON
  protocol (submit/status/result/cancel/stats/shutdown) and the thin
  client.  Each request carries its own crash-safe journal, so a
  preempted job resumes on daemon restart instead of recomputing.
* ``loadtest``  — concurrent synthetic-job harness reporting throughput
  and p50/p95/p99 latency plus the cold-first-job vs warm-job delta
  (see docs/benchmarks.md and ``bench.py serve``).

Entry points: ``python -m racon_tpu.serve`` or
``python -m racon_tpu.cli serve`` (daemon), ``python -m
racon_tpu.serve.loadtest`` (harness).
"""

from .client import ServeClient, ServeError
from .scheduler import AdmissionError, Scheduler
from .server import ServeDaemon
from .session import JobCancelled, JobSpec, PolishSession

__all__ = [
    "AdmissionError",
    "JobCancelled",
    "JobSpec",
    "PolishSession",
    "Scheduler",
    "ServeClient",
    "ServeDaemon",
    "ServeError",
]
