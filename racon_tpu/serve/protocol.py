"""Newline-JSON wire helpers shared by every localhost TCP surface.

One JSON object per line in each direction — the `racon-tpu serve`
daemon (server.py), its client (client.py), and the `racon-tpu distrib`
coordinator/worker pair (racon_tpu/distrib) all speak the same framing,
so the guards live in one place:

* ``MAX_LINE`` bounds a single message (a line that long without a
  terminating newline is an oversized/garbage frame, not a request);
* ``read_message`` returns the parsed dict, ``None`` on a clean EOF, and
  raises ``ValueError`` on malformed JSON, a non-object payload, or an
  oversized frame — the caller decides whether that kills the
  connection (client) or just the request (server);
* ``write_message`` frames and flushes one object.

Only the stdlib is imported; the helpers operate on any buffered binary
file object (``socket.makefile("rwb")``).
"""

from __future__ import annotations

import json
from typing import Optional

#: Protocol guard: one message line must fit comfortably in memory.
MAX_LINE = 1 << 20


def read_message(f) -> Optional[dict]:
    """Read one newline-framed JSON object.  None = clean EOF."""
    line = f.readline(MAX_LINE)
    if not line:
        return None
    if len(line) >= MAX_LINE and not line.endswith(b"\n"):
        raise ValueError(f"message exceeds MAX_LINE ({MAX_LINE} bytes)")
    msg = json.loads(line)
    if not isinstance(msg, dict):
        raise ValueError("message must be a JSON object")
    return msg


def write_message(f, msg: dict) -> None:
    """Frame and flush one object (the flush is the send)."""
    f.write(json.dumps(msg).encode() + b"\n")
    f.flush()
