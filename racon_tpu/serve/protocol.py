"""Newline-JSON wire helpers shared by every localhost TCP surface.

One JSON object per line in each direction — the `racon-tpu serve`
daemon (server.py), its client (client.py), and the `racon-tpu distrib`
coordinator/worker pair (racon_tpu/distrib) all speak the same framing,
so the guards live in one place:

* ``MAX_LINE`` bounds a single message (a line that long without a
  terminating newline is an oversized/garbage frame, not a request);
* ``read_message`` returns the parsed dict, ``None`` on a clean EOF, and
  raises ``ValueError`` on malformed JSON, a non-object payload, or an
  oversized frame — the caller decides whether that kills the
  connection (client) or just the request (server);
* ``write_message`` frames and flushes one object.

Only the stdlib is imported; the helpers operate on any buffered binary
file object (``socket.makefile("rwb")``).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from .. import obs

#: Protocol guard: one message line must fit comfortably in memory.
MAX_LINE = 1 << 20


def read_message(f) -> Optional[dict]:
    """Read one newline-framed JSON object.  None = clean EOF.

    When tracing is armed the receive is stamped as an ``rpc.recv``
    span with the payload byte size, so queueing vs transport vs
    compute separate cleanly in the merged fleet timeline.  The stamp
    covers the blocking read — on a server connection that includes the
    idle wait for the next request, which is exactly the queueing-gap
    signal the fleet breakdown keys off."""
    t0 = time.monotonic_ns()
    line = f.readline(MAX_LINE)
    if not line:
        return None
    if len(line) >= MAX_LINE and not line.endswith(b"\n"):
        raise ValueError(f"message exceeds MAX_LINE ({MAX_LINE} bytes)")
    msg = json.loads(line)
    if not isinstance(msg, dict):
        raise ValueError("message must be a JSON object")
    obs.add_complete("rpc.recv", t0, time.monotonic_ns(), cat="rpc",
                     bytes=len(line), op=msg.get("op"))
    return msg


def write_message(f, msg: dict) -> None:
    """Frame and flush one object (the flush is the send).  Armed, the
    serialize+flush is stamped as an ``rpc.send`` span with the payload
    byte size (see ``read_message``)."""
    t0 = time.monotonic_ns()
    data = json.dumps(msg).encode() + b"\n"
    f.write(data)
    f.flush()
    obs.add_complete("rpc.send", t0, time.monotonic_ns(), cat="rpc",
                     bytes=len(data), op=msg.get("op"))


# ---------------------------------------------------------------------------
# Declared wire-protocol spec.
#
# The static contract auditor (racon_tpu/analysis/concurrency/contracts)
# extracts every producer's sent fields and every consumer's read fields
# from server.py / client.py / distrib/coordinator.py / distrib/worker.py
# and cross-checks them against these literals, so the four surfaces
# cannot drift apart silently.  Keep the dicts pure literals — they are
# read by `ast.literal_eval`, not imported, when the tree is audited.
#
# Shapes: req = fields a request MUST carry; opt = fields it MAY carry;
# resp = fields an ok-response may carry beyond COMMON_RESP.
# ---------------------------------------------------------------------------

#: Fields every response may carry regardless of op: the ok flag and
#: the error envelope the server attaches on any failure path.
COMMON_RESP = ("ok", "error", "rejected")

PROTOCOL = {
    "serve": {
        "ping": {"req": (), "opt": (),
                 "resp": ("pid", "backend", "port")},
        "submit": {"req": ("sequences", "overlaps", "target"),
                   "opt": ("args", "include_unpolished", "backend",
                           "job_id", "submitter", "window_budget",
                           "priority", "trace"),
                   "resp": ("job_id", "lane", "demotions")},
        "status": {"req": ("job_id",), "opt": (),
                   "resp": ("job_id", "state", "lane", "submitter",
                            "demotions", "error", "queued_s",
                            "running_s")},
        "result": {"req": ("job_id",), "opt": ("wait", "timeout"),
                   "resp": ("job_id", "state", "lane", "submitter",
                            "demotions", "error", "queued_s",
                            "running_s", "result")},
        "cancel": {"req": ("job_id",), "opt": (),
                   "resp": ("job_id", "state", "lane", "submitter",
                            "demotions", "error", "queued_s",
                            "running_s")},
        "stats": {"req": (), "opt": (),
                  "resp": ("jobs", "queued", "queue_depth", "max_jobs",
                           "window_budget", "session", "telemetry",
                           "admission", "fleet")},
        "metrics": {"req": (), "opt": (), "resp": ("text", "slo")},
        "shutdown": {"req": (), "opt": (), "resp": ("bye",)},
    },
    "distrib": {
        "hello": {"req": ("worker",), "opt": (),
                  "resp": ("lease_ttl", "heartbeat")},
        "fetch": {"req": ("worker",), "opt": (),
                  "resp": ("drain", "wait", "poll_s", "chunk")},
        "heartbeat": {"req": ("worker", "chunk", "attempt"), "opt": (),
                      "resp": ("cancel",)},
        "result": {"req": ("worker", "chunk", "attempt", "output"),
                   "opt": ("stats", "obs"), "resp": ("accepted",)},
        "error": {"req": ("worker", "chunk", "attempt"),
                  "opt": ("error",), "resp": ()},
        "stats": {"req": (), "opt": (),
                  "resp": ("chunks", "leases", "workers", "served",
                           "staleness_s", "counters", "telemetry")},
    },
}

#: Nested message payloads: "<surface>.<op>.<field>" -> the exact field
#: set of the nested object.  The producer's literal must match this
#: set exactly; the consumer may only read declared fields.
PAYLOADS = {
    "distrib.fetch.chunk": ("index", "attempt", "sequences", "overlaps",
                            "target", "args", "include_unpolished",
                            "backend", "journal", "output", "trace"),
}
