"""Thin client for the `racon-tpu serve` daemon (newline-JSON over a
localhost TCP socket; protocol documented in server.py)."""

from __future__ import annotations

import json
import os
import socket
from typing import Optional

from .protocol import read_message, write_message


class ServeError(RuntimeError):
    """The daemon answered ``ok: false``; the message is its error."""


class ServeClient:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 600.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._f = self._sock.makefile("rwb")

    @classmethod
    def from_state_dir(cls, state_dir: str,
                       timeout: float = 600.0) -> "ServeClient":
        """Connect to the daemon whose ``serve.json`` lives in
        ``state_dir`` (how port-0 daemons advertise their bound port)."""
        with open(os.path.join(state_dir, "serve.json")) as f:
            info = json.load(f)
        return cls(info["port"], host=info.get("host", "127.0.0.1"),
                   timeout=timeout)

    # -- plumbing ----------------------------------------------------------

    def rpc(self, **req) -> dict:
        """One request/response exchange; raises ServeError on
        ``ok: false`` (the raw response rides on the exception)."""
        write_message(self._f, req)
        try:
            resp = read_message(self._f)
        except (ValueError, json.JSONDecodeError) as e:
            raise ServeError(f"malformed daemon response: {e}") from None
        if resp is None:
            raise ServeError("daemon closed the connection")
        if not resp.get("ok"):
            err = ServeError(resp.get("error", "request failed"))
            err.response = resp
            raise err
        return resp

    def close(self) -> None:
        try:
            self._f.close()
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- operations --------------------------------------------------------

    def ping(self) -> dict:
        return self.rpc(op="ping")

    def submit(self, sequences: str, overlaps: str, target: str,
               args: Optional[dict] = None, include_unpolished: bool = False,
               backend: str = "", job_id: str = "",
               submitter: str = "", window_budget: int = 0,
               priority: int = 0,
               trace: Optional[dict] = None) -> str:
        resp = self.rpc(op="submit", sequences=sequences, overlaps=overlaps,
                        target=target, args=args or {},
                        include_unpolished=include_unpolished,
                        backend=backend, job_id=job_id,
                        submitter=submitter or f"pid{os.getpid()}",
                        window_budget=window_budget,
                        priority=priority,
                        trace=trace)
        return resp["job_id"]

    def status(self, job_id: str) -> dict:
        return self.rpc(op="status", job_id=job_id)

    def result(self, job_id: str, wait: bool = False,
               timeout: Optional[float] = None) -> dict:
        return self.rpc(op="result", job_id=job_id, wait=wait,
                        timeout=timeout)

    def wait(self, job_id: str, timeout: Optional[float] = None) -> dict:
        """Block until the job is terminal; returns the result response.
        Raises ServeError if the job failed/was cancelled/timed out."""
        return self.result(job_id, wait=True, timeout=timeout)

    def cancel(self, job_id: str) -> dict:
        return self.rpc(op="cancel", job_id=job_id)

    def stats(self) -> dict:
        return self.rpc(op="stats")

    def metrics(self) -> dict:
        """Prometheus exposition text + SLO snapshot (``text``/``slo``
        response fields)."""
        return self.rpc(op="metrics")

    def shutdown(self) -> dict:
        return self.rpc(op="shutdown")
