"""Queue-based job scheduler: N concurrent submissions, one device set.

Concurrency model: in-process polishes cannot overlap (the per-run
runtime state the polisher constructors reset is module-global — see
``polisher.reset_run_state``), so the **device lane** is one worker
thread draining a queue through ``PolishSession.run_job``.  The **host
lane** is a second worker running demoted jobs as ``python -m
racon_tpu.cli`` subprocesses — the CPU oracle produces byte-identical
output, so a demotion changes *where* a job runs, never *what* it
returns.  This extends the kernel degradation lattice one level up:
where a window falls ls → v2 → xla → host, a whole job falls
device-lane → host-lane.

Admission control bounds what the daemon will hold: a queue-depth cap on
not-yet-running jobs, a max-jobs cap on everything unfinished, an
optional per-tenant quota (``RACON_TPU_FLEET_TENANT_QUOTA``), and a
window budget enforced in two steps — a job whose estimated window
count exceeds the budget is demoted to the host lane at submit time
(an overloaded tier demotes work, it does not stall the queue), and a
job that fits alone but would push the device lane's *aggregate*
reserved windows over the budget is **shed** to the host lane; when the
host lane itself is saturated the submit is rejected.  The ladder is
always shed → host lane → reject, in that order.  The estimate is file
I/O and runs outside the scheduler lock; the check-and-reserve against
the aggregate happens atomically under it, so concurrent submits cannot
both squeeze into the same budget headroom.

The ladder also has a **memory dimension** (resilience/budget.py): when
``RACON_TPU_MEM_BUDGET_MB`` is set, every submit samples the worst of
the daemon's own RSS and the per-worker RSS the fleet telemetry last
reported.  A soft watermark sheds the job to the host lane
(``shed_memory`` — a subprocess's allocations die with it, unlike the
resident device lane's), a hard watermark rejects outright
(``rejected_memory``): admitting more work under hard pressure makes
every lane worse.  Like the window estimate, the sample runs outside
the scheduler lock (it reads /proc and takes the plane's lock).  Fairness is per-submitter
round-robin with priority lanes (fleet/queues.py): each submitter has
its own FIFOs; the scheduler serves the highest priority present and
rotates submitters within it, so one flooding client cannot starve the
rest and a high-priority job outranks lower lanes without starving
other tenants at its own level.

Elastic fleet: with a ``FleetPlane`` attached (fleet/plane.py;
``RACON_TPU_FLEET_MAX_WORKERS`` > 0), the device lane stops running
jobs in-process and instead feeds them to the plane, which splits each
into chunks dispatched across an autoscaled worker pool — several jobs
in flight at once, so idle workers steal chunks across jobs.  A plane
failure demotes the job to the host lane exactly like an in-process
device failure; output is byte-identical on every path.

Failure handling mirrors the lattice, too: a job that raises on the
device lane is demoted to the host lane (recorded in its
``demotions``); a host-lane failure is final and marks only that job
failed — the daemon and the rest of the queue keep running.

Persistence: the scheduler writes ``spec.json`` into the job directory
at admission and ``result.json`` at any terminal state.  A daemon killed
mid-run leaves specs without results; ``recover()`` re-queues them on
restart, and the per-job journal (session.py) turns the re-run into a
resume.  Graceful ``shutdown()`` finishes the running job, leaves queued
jobs unpersisted-as-terminal, and lets the next daemon pick them up.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .. import fingerprint, obs
from ..fleet import fleet_tenant_quota
from ..obs import ledger as joblog
from ..obs import slo
from ..resilience import budget as membudget
from ..fleet.queues import TenantQueues
from .session import (JobCancelled, JobSpec, PolishSession, serve_max_jobs,
                      serve_queue_depth, serve_window_budget)

LANES = ("device", "host")
TERMINAL = ("done", "failed", "cancelled")


class AdmissionError(RuntimeError):
    """Submission rejected by admission control (queue full / at
    capacity / invalid spec reuse).  The client sees the message; the
    daemon state is untouched."""


def estimate_windows(target_path: str, window_length: int) -> Optional[int]:
    """Estimated window count for a draft: per contig,
    ceil(len / window_length) — the same fixed-size chunking the window
    builder applies.  None when the target cannot be sized cheaply
    (non-FASTA, unreadable) — the budget check then lets it through."""
    import gzip

    opener = (gzip.open if target_path.lower().endswith(".gz") else open)
    lens: List[int] = []
    try:
        with opener(target_path, "rt") as f:
            for line in f:
                if line.startswith(">"):
                    lens.append(0)
                elif line.startswith("@") and not lens:
                    return None   # FASTQ (or garbage): not sized here
                elif lens:
                    lens[-1] += len(line.strip())
    except (OSError, UnicodeDecodeError):
        return None
    if not lens:
        return None
    w = max(1, int(window_length))
    return sum(math.ceil(n / w) for n in lens if n > 0)


class Job:
    """One scheduled job and its lifecycle:
    queued -> running -> done | failed | cancelled."""

    def __init__(self, spec: JobSpec, job_id: str):
        self.spec = spec
        self.id = job_id
        self.state = "queued"
        self.lane = "device"
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.demotions: List[dict] = []
        self.cancel = threading.Event()
        self.done = threading.Event()
        self.t_submit = time.monotonic()
        self.t_start: Optional[float] = None
        self.t_end: Optional[float] = None
        # per-job latency ledger (obs/ledger.py): stamps submit now;
        # the scheduler stamps admit/dispatch/finish/result_ship as the
        # job moves, the compute side ships stage_s fragments back
        self.ledger = joblog.JobLedger(job_id, tenant=spec.submitter)

    def as_status(self) -> dict:
        now = time.monotonic()
        return {
            "job_id": self.id,
            "state": self.state,
            "lane": self.lane,
            "submitter": self.spec.submitter,
            "demotions": list(self.demotions),
            "error": self.error,
            "queued_s": round((self.t_start or now) - self.t_submit, 4),
            "running_s": (None if self.t_start is None else
                          round((self.t_end or now) - self.t_start, 4)),
        }


class Scheduler:
    def __init__(self, session: PolishSession,
                 queue_depth: Optional[int] = None,
                 max_jobs: Optional[int] = None,
                 window_budget: Optional[int] = None,
                 host_lane: bool = True,
                 plane=None,
                 tenant_quota: Optional[int] = None):
        self.session = session
        self.queue_depth = (serve_queue_depth() if queue_depth is None
                            else queue_depth)
        self.max_jobs = serve_max_jobs() if max_jobs is None else max_jobs
        self.window_budget = (serve_window_budget() if window_budget is None
                              else window_budget)
        self.host_lane = host_lane
        self.plane = plane   # FleetPlane, or None for in-process device
        self.tenant_quota = (fleet_tenant_quota() if tenant_quota is None
                             else tenant_quota)
        self._jobs: Dict[str, Job] = {}
        # lane -> per-tenant priority queues (fleet/queues.py)
        self._queues: Dict[str, TenantQueues] = {ln: TenantQueues()
                                                 for ln in LANES}
        # device-lane window reservations by job id: the aggregate the
        # shed check holds against, reserved at admit under _cv and
        # released when the job leaves the device lane
        self._reserved: Dict[str, int] = {}
        self.admission: Dict[str, int] = {}   # demoted/shed/rejected/...
        self._cv = threading.Condition()
        self._stop = False
        self._counter = 0
        self._workers: List[threading.Thread] = []
        # injectable for tests: () -> "ok"|"soft"|"hard" — the memory
        # dimension of the admission ladder (sampled OUTSIDE _cv)
        self.memory_source = self._memory_pressure

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for lane in LANES:
            if lane == "host" and not self.host_lane:
                continue
            t = threading.Thread(target=self._worker, args=(lane,),
                                 name=f"serve-{lane}-lane", daemon=True)
            t.start()
            self._workers.append(t)

    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop accepting work, finish the running job(s), exit the
        workers.  Queued jobs keep their spec.json and get no
        result.json — a restarted daemon re-queues them (recover()) and
        their journals turn the re-run into a resume."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if wait:
            for t in self._workers:
                t.join(timeout)

    def recover(self) -> List[str]:
        """Re-queue every job directory holding a spec.json without a
        result.json — the unfinished work of a previous daemon life.  A
        spec that no longer admits (inputs deleted, invalid) is marked
        failed so it cannot retry forever on every restart.

        Torn files never crash the restart path: a result.json a killed
        daemon left unparseable (or parseable but not an object) is
        discarded so the job counts as unfinished and re-queues from its
        spec; a spec.json torn the same way fails that one job with the
        usual recovery warning.  Either way the daemon comes up — the
        broad per-job except is the lattice-of-last-resort for whatever
        shape mid-write truncation produced."""
        jobs_root = os.path.join(self.session.workdir, "jobs")
        recovered = []
        for job_id in sorted(os.listdir(jobs_root) if
                             os.path.isdir(jobs_root) else ()):
            jd = os.path.join(jobs_root, job_id)
            spec_path = os.path.join(jd, "spec.json")
            if not os.path.isfile(spec_path):
                continue
            result_path = os.path.join(jd, "result.json")
            if os.path.isfile(result_path):
                if self._result_intact(result_path):
                    continue
                try:
                    os.remove(result_path)   # truncate-and-requeue
                except OSError:
                    continue   # unreadable AND undeletable: leave it
                print(f"[racon_tpu::serve] WARNING: discarding torn "
                      f"result.json for job {job_id}; re-queueing",
                      file=sys.stderr)
            try:
                with open(spec_path) as f:
                    doc = json.load(f)
                if not isinstance(doc, dict):
                    raise ValueError(f"spec.json holds "
                                     f"{type(doc).__name__}, not an object")
                spec = JobSpec.from_dict(doc)
                spec.job_id = job_id  # concurrency: single-owner until submit() publishes it
                self.submit(spec)
                recovered.append(job_id)
            except Exception as e:  # noqa: BLE001 — a torn spec.json can
                # decode to anything; one damaged job directory must not
                # take down the restart path
                job = Job(JobSpec("", "", "", job_id=job_id), job_id)
                job.state = "failed"  # concurrency: job is thread-local until published under _cv below
                job.error = f"recovery failed: {type(e).__name__}: {e}"  # concurrency: thread-local, see above
                job.done.set()
                with self._cv:
                    self._jobs[job_id] = job
                self._persist_result(job)
                print(f"[racon_tpu::serve] WARNING: cannot recover job "
                      f"{job_id}: {e}", file=sys.stderr)
        return recovered

    @staticmethod
    def _result_intact(path: str) -> bool:
        """Whether a result.json parses to an object — anything else is
        the torn tail of a write the dying daemon never finished."""
        try:
            with open(path) as f:
                return isinstance(json.load(f), dict)
        except (OSError, ValueError, json.JSONDecodeError):
            return False

    # -- submission / queries ----------------------------------------------

    def submit(self, spec: JobSpec) -> Job:
        spec.validate()
        # the size estimate is file I/O: run it BEFORE taking the lock
        # (a slow disk must not stall every other submit/finish), then
        # check-and-reserve atomically under it — two concurrent submits
        # can never both fit into the same budget headroom
        est = self._estimate(spec)
        # so is the memory sample: it reads /proc and (with a plane)
        # takes the plane's lock — the two condition variables must
        # never nest
        mem = self.memory_source()
        with self._cv:
            if self._stop:
                raise AdmissionError("daemon is shutting down")
            unfinished = sum(1 for j in self._jobs.values()
                             if j.state not in TERMINAL)
            if unfinished >= self.max_jobs:
                self._admission_count("rejected_capacity")
                raise AdmissionError(
                    f"at capacity: {unfinished} unfinished jobs "
                    f"(RACON_TPU_SERVE_MAX_JOBS={self.max_jobs})")
            queued = sum(len(q) for q in self._queues.values())
            if queued >= self.queue_depth:
                self._admission_count("rejected_queue_full")
                raise AdmissionError(
                    f"queue full: {queued} queued jobs "
                    f"(RACON_TPU_SERVE_QUEUE_DEPTH={self.queue_depth})")
            if self.tenant_quota > 0:
                held = sum(1 for j in self._jobs.values()
                           if j.spec.submitter == spec.submitter
                           and j.state not in TERMINAL)
                if held >= self.tenant_quota:
                    self._admission_count("rejected_quota")
                    raise AdmissionError(
                        f"tenant quota: submitter {spec.submitter!r} "
                        f"holds {held} unfinished jobs (RACON_TPU_FLEET_"
                        f"TENANT_QUOTA={self.tenant_quota})")
            job_id = spec.job_id
            if job_id:
                prior = self._jobs.get(job_id)
                if prior is not None and prior.state not in TERMINAL:
                    raise AdmissionError(f"job id {job_id!r} is already "
                                         f"{prior.state}")
            else:
                while True:
                    job_id = f"job{self._counter:04d}"
                    self._counter += 1
                    if job_id not in self._jobs:
                        break
                spec.job_id = job_id
            job = Job(spec, job_id)
            lane = self._admission_lane(job, est, mem)
            job.ledger.mark("admit")
            # instant event: critpath's job-wall anchor in the merged
            # fleet trace (pairs with serve.job.done in _finish)
            obs.event("serve.job.submit", job=job_id,
                      tenant=spec.submitter, lane=lane)
            self._jobs[job_id] = job
            self._enqueue(lane, job)
            self._persist_spec(job)
            self._cv.notify_all()
            return job

    def _estimate(self, spec: JobSpec) -> Optional[int]:
        """Window estimate for budget admission; None when the budget
        machinery does not apply to this spec.  Lock-free (file I/O)."""
        if not self.host_lane:
            return None
        if ((spec.backend or self.session.backend) == "cpu"
                and self.plane is None):
            return None
        if (spec.window_budget or self.window_budget) <= 0:
            return None
        w = spec.polish_args()["window_length"]
        return estimate_windows(spec.target, w)

    def _memory_pressure(self) -> str:
        """Memory-pressure level for admission: the worst of the
        daemon's own RSS (resilience/budget.py watermarks) and the
        per-worker RSS the fleet telemetry last reported.  "ok" when
        unbudgeted.  Lock-free relative to _cv by design — it samples
        /proc and takes the plane's lock."""
        b = membudget.active()
        if b is None or not b.enabled:
            return "ok"
        level = b.poll(fault_check=False)
        if self.plane is not None and not membudget.at_least(level, "hard"):
            tel = self.plane.fleet_telemetry()
            worst = max((float(s.get("rss_mb") or 0.0)
                         for s in tel.get("workers", {}).values()),
                        default=0.0)
            if worst >= b.hard_mb:
                level = "hard"
            elif worst >= b.soft_mb and not membudget.at_least(level,
                                                               "soft"):
                level = "soft"
        return level

    def _admission_count(self, name: str, n: int = 1) -> None:
        # call with self._cv held
        self.admission[name] = self.admission.get(name, 0) + n

    def _admission_lane(self, job: Job, est: Optional[int],
                        mem: str = "ok") -> str:
        """Lane decision + window reservation (call with _cv held).
        The ladder: per-job budget demote, then aggregate shed, then —
        if the host lane cannot absorb the fallout either — reject.
        ``mem`` is the pre-sampled memory-pressure level: soft sheds to
        the host lane, hard rejects outright."""
        spec = job.spec
        if membudget.at_least(mem, "hard"):
            # the memory dimension's bottom rung: under a hard
            # watermark admitting anything degrades every lane
            self._admission_count("rejected_memory")
            raise AdmissionError(
                f"memory pressure: RSS at the hard watermark "
                f"(RACON_TPU_MEM_BUDGET_MB={membudget.budget_mb()}) — "
                f"resubmit later")
        if not self.host_lane:
            return "device"
        if ((spec.backend or self.session.backend) == "cpu"
                and self.plane is None):
            # in-process device lane has nothing to offer a cpu job; a
            # fleet plane does (worker processes), so this shortcut only
            # applies without one
            job.lane = "host"
            return "host"
        budget = spec.window_budget or self.window_budget
        to_host: Optional[str] = None
        if membudget.at_least(mem, "soft"):
            # memory shed: the host-lane subprocess's allocations die
            # with it; the resident device lane's do not
            to_host = (f"shed (memory): RSS over the soft watermark "
                       f"(RACON_TPU_MEM_BUDGET_MB="
                       f"{membudget.budget_mb()})")
            self._admission_count("shed_memory")
        elif slo.engine().should_shed(spec.submitter):
            # SLO shed: the tenant's burn rate exceeds the shedding
            # threshold on both windows — stop piling work onto the
            # lane that is missing its targets (opt-in, default off)
            to_host = (f"shed (slo): burn rate over RACON_TPU_SLO_"
                       f"SHED_BURN={slo.engine().shed_burn:g} on both "
                       f"windows")
            self._admission_count("shed_slo")
        elif budget > 0 and est is not None:
            if est > budget:
                to_host = (f"window budget: ~{est} windows > "
                           f"budget {budget}")
                self._admission_count("demoted_budget")
            else:
                reserved = sum(self._reserved.values())
                if reserved + est > budget:
                    # the job fits alone but not on top of what the
                    # device lane already holds: shed it
                    to_host = (f"shed: ~{est} windows would push the "
                               f"device lane to {reserved + est} "
                               f"reserved > budget {budget}")
                    self._admission_count("shed")
        if to_host is None:
            if est is not None:
                self._reserved[job.id] = est
            return "device"
        if len(self._queues["host"]) >= self.queue_depth:
            # the bottom of the ladder: host lane saturated too
            self._admission_count("rejected_host_saturated")
            raise AdmissionError(
                f"host lane saturated ({len(self._queues['host'])} "
                f"queued) and the device lane is over budget — "
                f"resubmit later ({to_host})")
        job.lane = "host"
        job.demotions.append({"from": "device", "to": "host",
                              "cause": to_host})
        obs.event("serve.shed" if to_host.startswith("shed") else
                  "serve.demote", job=job.id, cause=to_host)
        return "host"

    def get(self, job_id: str) -> Job:
        with self._cv:
            job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job id {job_id!r}")
        return job

    def cancel(self, job_id: str) -> dict:
        """Cancel a job.  Queued: removed immediately.  Running: the
        cancel event is honored at the next phase boundary (device lane)
        or kills the subprocess (host lane); a device job that reaches
        completion first stays done — cancellation is best-effort once
        work is on the device."""
        job = self.get(job_id)
        with self._cv:
            if job.state == "queued":
                for lane in LANES:
                    self._queues[lane].remove(job.spec.submitter, job)
                self._reserved.pop(job.id, None)
                job.state = "cancelled"
                job.error = "cancelled while queued"
                job.t_end = time.monotonic()
                job.done.set()
                self._persist_result(job)
                return job.as_status()
        job.cancel.set()
        # plane jobs: propagate outside _cv (the plane fires on_done ->
        # _finish, which takes _cv itself)
        if self.plane is not None and job.lane == "device":
            self.plane.cancel_job(job_id)
        return job.as_status()

    def stats(self) -> dict:
        # the plane snapshot takes the plane's lock; grab it outside
        # ours so the two condition variables never nest
        fleet = self.plane.snapshot() if self.plane is not None else None
        with self._cv:
            by_state: Dict[str, int] = {}
            for j in self._jobs.values():
                by_state[j.state] = by_state.get(j.state, 0) + 1
            queued = {lane: len(q) for lane, q in self._queues.items()}
            admission = dict(self.admission)
            admission["reserved_windows"] = sum(self._reserved.values())
            admission["by_tenant"] = {
                lane: q.per_tenant() for lane, q in self._queues.items()}
        out = {
            "jobs": by_state,
            "queued": queued,
            "queue_depth": self.queue_depth,
            "max_jobs": self.max_jobs,
            "window_budget": self.window_budget,
            "admission": admission,
            "session": self.session.stats(),
            # recent metrics-snapshot ring (obs.telemetry_tick entries,
            # stamped per finished job) — what `--stats-watch` polls
            "telemetry": obs.telemetry(last=8),
        }
        if fleet is not None:
            out["fleet"] = fleet
        return out

    # -- queue mechanics (call with self._cv held) -------------------------

    def _enqueue(self, lane: str, job: Job) -> None:
        self._queues[lane].push(job.spec.submitter, job,
                                job.spec.priority)

    def _pop(self, lane: str) -> Optional[Job]:
        """Next job for a lane: highest priority present, round-robin
        among the submitters holding it (fleet/queues.py) — bursts from
        one client interleave with everyone else's jobs."""
        return self._queues[lane].pop()

    # -- workers -----------------------------------------------------------

    def _worker(self, lane: str) -> None:
        while True:
            with self._cv:
                job = self._pop(lane)
                while job is None:
                    if self._stop:
                        return
                    self._cv.wait(0.2)
                    job = self._pop(lane)
                job.state = "running"
                job.lane = lane
                job.t_start = time.monotonic()
                job.ledger.mark("dispatch")
            if lane == "device" and self.plane is not None:
                # elastic fleet path: hand the job to the plane and go
                # straight back to the queue — several jobs in flight at
                # once is what makes cross-job stealing possible
                self._dispatch_to_plane(job)
                continue
            try:
                if lane == "device":
                    result = self.session.run_job(job.spec,
                                                  cancel_event=job.cancel)
                else:
                    result = self._run_host(job)
            except JobCancelled:
                self._finish(job, "cancelled", error="cancelled mid-run")
            except Exception as e:  # noqa: BLE001 — the job absorbs the
                # failure (lattice-of-last-resort); the daemon and the
                # rest of the queue keep serving
                if (lane == "device" and self.host_lane
                        and not job.cancel.is_set()):
                    self._demote(job, e)
                else:
                    self._finish(job, "failed",
                                 error=f"{type(e).__name__}: {e}")
            else:
                self._finish(job, "done", result=result)

    def _dispatch_to_plane(self, job: Job) -> None:
        """Submit one popped job to the fleet plane, non-blocking.  The
        plane's on_done callback (fired off its lock, on a fleet thread)
        re-enters _finish/_demote exactly like the in-process path."""
        spec = job.spec

        def on_done(state: str, result: Optional[dict],
                    error: Optional[str]) -> None:
            if state == "done":
                self._finish(job, "done", result=result)
            elif state == "cancelled":
                self._finish(job, "cancelled",
                             error=error or "cancelled mid-run")
            elif self.host_lane and not job.cancel.is_set():
                self._demote(job, RuntimeError(error or "fleet failure"))
            else:
                self._finish(job, "failed",
                             error=error or "fleet failure")

        try:
            self.plane.submit_job(
                job.id, spec.sequences, spec.overlaps, spec.target,
                spec.polish_args(), spec.include_unpolished,
                spec.backend or self.session.backend,
                workdir=self.session.job_dir(job.id),
                tenant=spec.submitter, priority=spec.priority,
                on_done=on_done)
        except Exception as e:  # noqa: BLE001 — a plane that cannot
            # admit (stopping, duplicate id) degrades like any device
            # failure: host lane if there is one, else the job fails
            if self.host_lane and not job.cancel.is_set():
                self._demote(job, e)
            else:
                self._finish(job, "failed",
                             error=f"{type(e).__name__}: {e}")

    def _demote(self, job: Job, exc: BaseException) -> None:
        """Device-lane failure: re-queue on the host lane (the job-level
        degradation step).  Output stays byte-identical — the host lane
        is the oracle path."""
        with self._cv:
            self._reserved.pop(job.id, None)
            job.demotions.append({
                "from": "device", "to": "host",
                "cause": f"{type(exc).__name__}: {exc}"})
            if self._stop:
                job.state = "queued"   # next daemon life recovers it
                self._cv.notify_all()
                return
            job.state = "queued"
            self._enqueue("host", job)
            self._cv.notify_all()

    def _finish(self, job: Job, state: str, result: Optional[dict] = None,
                error: Optional[str] = None) -> None:
        job.ledger.mark("finish")
        if result is not None:
            self._fold_ledger(job, result)
            # the persisted copy cannot time its own write: result.json
            # carries the ledger without the result_ship stage; the wire
            # copy below is re-finalized after the persist
            result["ledger"] = job.ledger.as_dict()
        with self._cv:
            self._reserved.pop(job.id, None)
            job.state = state
            job.result = result
            job.error = error
            job.t_end = time.monotonic()
        # persist before signalling done: a waiter released by done.wait()
        # must find result.json on disk (clients read it immediately)
        self._persist_result(job)
        job.ledger.mark("result_ship")
        if result is not None:
            result["ledger"] = job.ledger.as_dict()
        obs.event("serve.job.done", job=job.id, tenant=job.spec.submitter,
                  state=state)
        if state != "cancelled":
            # SLO ingest: a cancel is a client decision, not a miss
            slo.engine().record(
                job.spec.submitter,
                (job.t_end or time.monotonic()) - job.t_submit,
                ok=(state == "done"))
        with self._cv:
            job.done.set()
            self._cv.notify_all()

    @staticmethod
    def _fold_ledger(job: Job, result: dict) -> None:
        """Absorb the compute side's stage durations into the job
        ledger: a fleet-plane result carries a pre-aggregated
        ``ledger.stage_s`` fragment; an in-process or host-lane result
        carries the run-report summary."""
        frag = result.get("ledger")
        if isinstance(frag, dict) and isinstance(frag.get("stage_s"), dict):
            job.ledger.merge_stage_s(frag["stage_s"])
        elif isinstance(result.get("summary"), dict):
            job.ledger.merge_stage_s(
                joblog.stage_seconds(result["summary"]))

    # -- host lane ---------------------------------------------------------

    def _run_host(self, job: Job) -> dict:
        """Run one job as a host-path CLI subprocess.  Same flags as a
        user-run CLI invocation (byte-identical output), its own
        journal (cpu-fingerprinted) and per-request trace, stdout
        written to a .part file and renamed only on success."""
        spec = job.spec
        a = spec.polish_args()
        # host lane = cpu backend: same `serve_job_dir` fingerprint site
        # as the in-process lane, so a demoted re-run resumes the
        # cpu-keyed journal and never replays device-tier records
        paths = fingerprint.serve_job_paths(self.session.workdir, job.id,
                                            "cpu")
        jd = paths["dir"]
        os.makedirs(jd, exist_ok=True)
        out_path = paths["output"]
        part_path = out_path + ".part"
        report_path = paths["report"]
        stderr_path = os.path.join(jd, "host.stderr.log")
        cmd = [sys.executable, "-m", "racon_tpu.cli",
               "-w", str(a["window_length"]),
               "-q", str(a["quality_threshold"]),
               "-e", str(a["error_threshold"]),
               "-m", str(a["match"]), "-x", str(a["mismatch"]),
               "-g", str(a["gap"]), "-t", str(a["num_threads"]),
               "--report", report_path,
               "--resume-journal", paths["journal"],
               "--trace", paths["trace"]]
        if not a["trim"]:
            cmd.append("--no-trimming")
        if a["fragment_correction"]:
            cmd.append("-f")
        if spec.include_unpolished:
            cmd.append("-u")
        cmd += [spec.sequences, spec.overlaps, spec.target]

        t0 = time.monotonic()
        with open(part_path, "w") as out_f, open(stderr_path, "w") as err_f:
            proc = subprocess.Popen(cmd, stdout=out_f, stderr=err_f)
            while True:
                try:
                    rc = proc.wait(timeout=0.2)
                    break
                except subprocess.TimeoutExpired:
                    if job.cancel.is_set():
                        proc.kill()
                        proc.wait()
                        raise JobCancelled(job.id) from None
        if rc != 0:
            tail = ""
            try:
                with open(stderr_path) as f:
                    tail = f.read()[-400:].strip()
            except OSError:
                pass
            raise RuntimeError(f"host lane exited {rc}: {tail}")
        os.replace(part_path, out_path)

        records = polished_bp = 0
        with open(out_path) as f:
            for line in f:
                if line.startswith(">"):
                    records += 1
                else:
                    polished_bp += len(line.strip())
        replayed = 0
        stage_s: Dict[str, float] = {}
        try:
            with open(report_path) as f:
                rep = json.load(f)
            replayed = sum(ph.get("served", {}).get("journal", 0)
                           for ph in rep.get("phases", {}).values())
            # report phases carry per-tier wall splits — the same shape
            # RunReport.summary() ships, so the ledger fragment comes
            # straight off the subprocess's own report
            stage_s = joblog.stage_seconds(rep.get("phases"))
        except (OSError, json.JSONDecodeError, AttributeError):
            pass
        return {
            "job_id": job.id,
            "backend": "cpu",
            "cold": False,
            "wall_s": round(time.monotonic() - t0, 4),
            "records": records,
            "polished_bp": polished_bp,
            "kernel_builds": 0,
            "journal_replayed": replayed,
            "output": out_path,
            "report": report_path,
            "trace": os.path.join(jd, "trace.json"),
            "summary": None,
            "ledger": {"stage_s": stage_s},
        }

    # -- persistence (job dir = crash-safe source of truth) ----------------

    def _persist_spec(self, job: Job) -> None:
        jd = self.session.job_dir(job.id)
        try:
            os.makedirs(jd, exist_ok=True)
            # tmp + rename, like _persist_result: a daemon killed
            # mid-write must never leave a torn spec.json for recover()
            tmp = os.path.join(jd, "spec.json.tmp")
            with open(tmp, "w") as f:
                json.dump(job.spec.as_dict(), f, indent=1)
                f.write("\n")
            os.replace(tmp, os.path.join(jd, "spec.json"))
        except OSError as e:
            print(f"[racon_tpu::serve] WARNING: cannot persist spec for "
                  f"{job.id}: {e}", file=sys.stderr)

    def _persist_result(self, job: Job) -> None:
        jd = self.session.job_dir(job.id)
        doc = {
            "job_id": job.id,
            "state": job.state,
            "lane": job.lane,
            "result": job.result,
            "error": job.error,
            "demotions": list(job.demotions),
        }
        try:
            os.makedirs(jd, exist_ok=True)
            tmp = os.path.join(jd, "result.json.tmp")
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
            os.replace(tmp, os.path.join(jd, "result.json"))
        except OSError as e:
            print(f"[racon_tpu::serve] WARNING: cannot persist result for "
                  f"{job.id}: {e}", file=sys.stderr)
