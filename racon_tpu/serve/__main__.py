"""`python -m racon_tpu.serve` / `python -m racon_tpu.cli serve` —
run the resident polishing daemon, or (with ``--stats-watch``) poll a
running daemon's live telemetry without starting one."""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

from .server import ServeDaemon


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu serve",
        description="Resident polishing daemon: kernels stay hot across "
        "jobs, a queue-based scheduler multiplexes concurrent submissions "
        "onto one device set, every job journals for preemption-safe "
        "resume (protocol: newline-JSON over localhost TCP; see "
        "docs/architecture.md, 'Serving').")
    p.add_argument("--state-dir", default="./racon-serve",
                   help="daemon state directory: serve.json (bound port) "
                   "plus one subdirectory per job holding its spec, "
                   "journal, trace, report, and polished output "
                   "(default ./racon-serve)")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port to bind on 127.0.0.1 (default: "
                   "RACON_TPU_SERVE_PORT, 0 = ephemeral)")
    p.add_argument("--backend", choices=("tpu", "cpu"), default="tpu",
                   help="session backend for the device lane "
                   "(default tpu)")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="queued-job admission cap (default: "
                   "RACON_TPU_SERVE_QUEUE_DEPTH)")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="unfinished-job admission cap (default: "
                   "RACON_TPU_SERVE_MAX_JOBS)")
    p.add_argument("--window-budget", type=int, default=None,
                   help="per-job window budget; bigger jobs run on the "
                   "host lane (default: RACON_TPU_SERVE_WINDOW_BUDGET, "
                   "0 = unlimited)")
    p.add_argument("--no-warm", action="store_true",
                   help="skip the startup kernel warm-up (first job then "
                   "pays the compiles; RACON_TPU_SERVE_WARMUP=0 is the "
                   "env equivalent)")
    p.add_argument("--warm-window", type=int, action="append", default=None,
                   metavar="W",
                   help="window length(s) to pre-compile geometries for "
                   "(repeatable; default 500 — pass the -w your jobs use)")
    p.add_argument("--no-host-lane", action="store_true",
                   help="disable the host demotion lane (device failures "
                   "then fail the job instead of retrying on the host)")
    p.add_argument("--fleet-max", type=int, default=None,
                   help="elastic fleet worker ceiling; > 0 runs the "
                   "device lane through the chunk-level fleet plane "
                   "with autoscaling and work-stealing (default: "
                   "RACON_TPU_FLEET_MAX_WORKERS, 0 = in-process device "
                   "lane)")
    p.add_argument("--fleet-min", type=int, default=None,
                   help="elastic fleet worker floor (default: "
                   "RACON_TPU_FLEET_MIN_WORKERS)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="Prometheus exposition HTTP port on 127.0.0.1 "
                   "(GET /metrics; default: RACON_TPU_METRICS_PORT, "
                   "0 = disabled — the `metrics` wire op still works)")
    p.add_argument("-m", "--match", type=int, default=3,
                   help="match score to warm kernels for (default 3)")
    p.add_argument("-x", "--mismatch", type=int, default=-5,
                   help="mismatch score to warm kernels for (default -5)")
    p.add_argument("-g", "--gap", type=int, default=-4,
                   help="gap penalty to warm kernels for (default -4)")
    p.add_argument("--stats-watch", action="store_true",
                   help="do not start a daemon: connect to the one whose "
                   "serve.json lives in --state-dir and print its stats "
                   "(one JSON line per poll), then exit")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between --stats-watch polls (default 2)")
    p.add_argument("--count", type=int, default=1,
                   help="number of --stats-watch polls before exiting "
                   "(default 1; 0 = poll until the daemon goes away)")
    return p


def stats_watch(state_dir: str, interval: float, count: int) -> int:
    """Poll a running daemon's ``stats`` op and print one JSON line per
    sample.  Exits 0 after ``count`` polls, 1 if the daemon cannot be
    reached (including when it goes away mid-watch)."""
    from .client import ServeClient, ServeError
    polls = 0
    while True:
        try:
            with ServeClient.from_state_dir(state_dir, timeout=10.0) as c:
                resp = c.stats()
        except (OSError, ValueError, ServeError) as e:
            print(f"[racon_tpu::serve] stats-watch: daemon unreachable: "
                  f"{e}", file=sys.stderr)
            return 1
        resp.pop("ok", None)
        print(json.dumps(resp, sort_keys=True), flush=True)
        polls += 1
        if count > 0 and polls >= count:
            return 0
        time.sleep(max(0.1, interval))


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    if args.stats_watch:
        return stats_watch(args.state_dir, args.interval, args.count)

    from ..resilience import faults
    try:
        faults.validate_env()
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1
    if args.backend == "tpu":
        from ..ops.poa_driver import _kernel_kind
        try:
            _kernel_kind()
        except ValueError as e:
            print(e, file=sys.stderr)
            return 1

    daemon = ServeDaemon(
        args.state_dir, backend=args.backend, port=args.port,
        queue_depth=args.queue_depth, max_jobs=args.max_jobs,
        window_budget=args.window_budget,
        warm=False if args.no_warm else None,
        warm_window_lengths=tuple(args.warm_window or (500,)),
        warm_scores=(args.match, args.mismatch, args.gap),
        host_lane=not args.no_host_lane,
        fleet_min=args.fleet_min, fleet_max=args.fleet_max,
        metrics_port=args.metrics_port)

    from ..obs import flight
    flight.set_role("serve")
    flight.set_dir(args.state_dir)

    def _stop(signum, frame):
        print(f"[racon_tpu::serve] signal {signum}: shutting down "
              f"(queued jobs stay recoverable)", file=sys.stderr)
        flight.dump("sigterm", signal=int(signum))
        daemon.stop(wait=False)

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    daemon.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
