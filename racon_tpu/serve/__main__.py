"""`python -m racon_tpu.serve` / `python -m racon_tpu.cli serve` —
run the resident polishing daemon."""

from __future__ import annotations

import argparse
import signal
import sys

from .server import ServeDaemon


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu serve",
        description="Resident polishing daemon: kernels stay hot across "
        "jobs, a queue-based scheduler multiplexes concurrent submissions "
        "onto one device set, every job journals for preemption-safe "
        "resume (protocol: newline-JSON over localhost TCP; see "
        "docs/architecture.md, 'Serving').")
    p.add_argument("--state-dir", default="./racon-serve",
                   help="daemon state directory: serve.json (bound port) "
                   "plus one subdirectory per job holding its spec, "
                   "journal, trace, report, and polished output "
                   "(default ./racon-serve)")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port to bind on 127.0.0.1 (default: "
                   "RACON_TPU_SERVE_PORT, 0 = ephemeral)")
    p.add_argument("--backend", choices=("tpu", "cpu"), default="tpu",
                   help="session backend for the device lane "
                   "(default tpu)")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="queued-job admission cap (default: "
                   "RACON_TPU_SERVE_QUEUE_DEPTH)")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="unfinished-job admission cap (default: "
                   "RACON_TPU_SERVE_MAX_JOBS)")
    p.add_argument("--window-budget", type=int, default=None,
                   help="per-job window budget; bigger jobs run on the "
                   "host lane (default: RACON_TPU_SERVE_WINDOW_BUDGET, "
                   "0 = unlimited)")
    p.add_argument("--no-warm", action="store_true",
                   help="skip the startup kernel warm-up (first job then "
                   "pays the compiles; RACON_TPU_SERVE_WARMUP=0 is the "
                   "env equivalent)")
    p.add_argument("--warm-window", type=int, action="append", default=None,
                   metavar="W",
                   help="window length(s) to pre-compile geometries for "
                   "(repeatable; default 500 — pass the -w your jobs use)")
    p.add_argument("--no-host-lane", action="store_true",
                   help="disable the host demotion lane (device failures "
                   "then fail the job instead of retrying on the host)")
    p.add_argument("-m", "--match", type=int, default=3,
                   help="match score to warm kernels for (default 3)")
    p.add_argument("-x", "--mismatch", type=int, default=-5,
                   help="mismatch score to warm kernels for (default -5)")
    p.add_argument("-g", "--gap", type=int, default=-4,
                   help="gap penalty to warm kernels for (default -4)")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    from ..resilience import faults
    try:
        faults.validate_env()
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1
    if args.backend == "tpu":
        from ..ops.poa_driver import _kernel_kind
        try:
            _kernel_kind()
        except ValueError as e:
            print(e, file=sys.stderr)
            return 1

    daemon = ServeDaemon(
        args.state_dir, backend=args.backend, port=args.port,
        queue_depth=args.queue_depth, max_jobs=args.max_jobs,
        window_budget=args.window_budget,
        warm=False if args.no_warm else None,
        warm_window_lengths=tuple(args.warm_window or (500,)),
        warm_scores=(args.match, args.mismatch, args.gap),
        host_lane=not args.no_host_lane)

    def _stop(signum, frame):
        print(f"[racon_tpu::serve] signal {signum}: shutting down "
              f"(queued jobs stay recoverable)", file=sys.stderr)
        daemon.stop(wait=False)

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    daemon.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
