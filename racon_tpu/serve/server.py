"""The `racon-tpu serve` daemon: localhost TCP, newline-JSON protocol.

One JSON object per line in each direction.  Requests carry an ``op``:

* ``ping``     -> ``{"ok": true, "pid": ..., "backend": ...}``
* ``submit``   -> admit a job (fields of serve.session.JobSpec);
  response carries the assigned ``job_id``.
* ``status``   -> job lifecycle snapshot (state, lane, demotions).
* ``result``   -> terminal outcome; ``"wait": true`` blocks (this
  connection's thread only) until the job finishes or ``timeout``.
* ``cancel``   -> cancel queued immediately / running best-effort.
* ``stats``    -> scheduler + session counters.
* ``metrics``  -> Prometheus text exposition + SLO engine snapshot
  (the same text ``--metrics-port`` serves over HTTP).
* ``shutdown`` -> acknowledge, then stop the daemon gracefully.

Errors never kill the daemon: a malformed line gets
``{"ok": false, "error": ...}`` on that connection; a client that
disconnects mid-job only loses its socket — the job keeps running and
its result stays queryable by id from any new connection.  The bound
port is written to ``<state_dir>/serve.json`` so clients (and the
load-test harness) can find a daemon started with port 0.

Restart story: on start the daemon re-queues every job directory with a
spec but no result (scheduler.recover) — combined with the per-job
journals, a daemon preempted mid-job resumes the job instead of
recomputing it.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
from typing import Optional

from .. import config, obs
from ..obs import export as obs_export
from ..obs import slo
from .protocol import MAX_LINE, read_message, write_message  # noqa: F401
# (MAX_LINE is re-exported: it is this daemon's documented protocol
# bound and pre-protocol.py importers reference it from here)
from .scheduler import AdmissionError, Scheduler
from .session import JobSpec, PolishSession, serve_port


class ServeDaemon:
    def __init__(self, state_dir: str, backend: str = "tpu",
                 port: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 max_jobs: Optional[int] = None,
                 window_budget: Optional[int] = None,
                 warm: Optional[bool] = None,
                 warm_window_lengths=(500,),
                 warm_scores=(3, -5, -4),
                 host_lane: bool = True,
                 fleet_min: Optional[int] = None,
                 fleet_max: Optional[int] = None,
                 metrics_port: Optional[int] = None):
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self.session = PolishSession(state_dir, backend=backend)
        # elastic fleet: with a worker ceiling > 0 the device lane runs
        # through a FleetPlane (chunk-level control plane with an
        # autoscaled worker pool) instead of in-process
        from ..fleet import fleet_max_workers, fleet_min_workers
        resolved_max = fleet_max_workers() if fleet_max is None else fleet_max
        self.plane = None
        if resolved_max > 0:
            from ..fleet.plane import FleetPlane
            fleet_dir = os.path.join(state_dir, "fleet")
            self.plane = FleetPlane(
                workdir=fleet_dir,
                min_workers=(fleet_min_workers() if fleet_min is None
                             else fleet_min),
                max_workers=resolved_max,
                backend=backend,
                trace_path=os.path.join(fleet_dir, "trace.json"),
                report_path=os.path.join(fleet_dir, "report.json"))
        self.scheduler = Scheduler(self.session, queue_depth=queue_depth,
                                   max_jobs=max_jobs,
                                   window_budget=window_budget,
                                   host_lane=host_lane,
                                   plane=self.plane)
        self._warm = warm
        self._warm_window_lengths = warm_window_lengths
        self._warm_scores = warm_scores
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", serve_port() if port is None
                         else port))
        self.port = self._sock.getsockname()[1]
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        # Prometheus exposition endpoint (obs/export.py): 0 = disabled;
        # the `metrics` wire op serves the same text either way
        self.metrics_port = (config.get_int("RACON_TPU_METRICS_PORT")
                             if metrics_port is None else metrics_port)
        self._httpd = None
        self._httpd_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Warm the kernels, recover unfinished jobs, start accepting."""
        from .session import serve_warmup_enabled

        with open(os.path.join(self.state_dir, "serve.json"), "w") as f:
            json.dump({"host": "127.0.0.1", "port": self.port,
                       "pid": os.getpid(),
                       "backend": self.session.backend}, f)
            f.write("\n")
        warm = serve_warmup_enabled() if self._warm is None else self._warm
        if warm and self.plane is None:
            # with the plane on, device jobs run in worker processes —
            # warming the in-process session would compile kernels
            # nothing ever uses
            m, x, g = self._warm_scores
            wall = self.session.warm(self._warm_window_lengths, m, x, g)
            if wall:
                print(f"[racon_tpu::serve] warmed consensus geometries "
                      f"{sorted(self.session.warmed)} in {wall:.2f}s",
                      file=sys.stderr)
        if self.plane is not None:
            self.plane.start()
            print(f"[racon_tpu::serve] fleet plane up on port "
                  f"{self.plane.port} (workers {self.plane.min_workers}"
                  f"..{self.plane.max_workers})", file=sys.stderr)
        self.scheduler.start()
        recovered = self.scheduler.recover()
        if recovered:
            print(f"[racon_tpu::serve] recovered {len(recovered)} "
                  f"unfinished job(s): {', '.join(recovered)}",
                  file=sys.stderr)
        self._sock.listen(16)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="serve-accept", daemon=True)
        self._accept_thread.start()
        self._start_metrics_http()

    def serve_forever(self) -> None:
        self.start()
        print(f"[racon_tpu::serve] listening on 127.0.0.1:{self.port} "
              f"(state: {self.state_dir}, backend: {self.session.backend})",
              file=sys.stderr)
        self._stopping.wait()
        self.scheduler.shutdown(wait=True)
        self._stop_plane()

    def stop(self, wait: bool = True) -> None:
        if not self._stopping.is_set():
            self._stopping.set()
            try:
                self._sock.close()
            except OSError:
                pass
            self._stop_metrics_http()
        if wait:
            self.scheduler.shutdown(wait=True)
            self._stop_plane()

    def _stop_plane(self) -> None:
        """Drain the fleet plane: stamp the scheduler's admission ledger
        into the fleet report, then stop (writes report + trace)."""
        if self.plane is None:
            return
        self.plane.phase.extra["admission"] = dict(self.scheduler.admission)
        self.plane.stop()

    # -- metrics exposition -------------------------------------------------

    def _metrics_scrape(self) -> dict:
        """One scrape: obs registry snapshot (None when disarmed) + SLO
        engine state + instantaneous queue/fleet gauges, rendered as
        Prometheus text (obs/export.py).  Shared by the `metrics` wire
        op and the --metrics-port HTTP endpoint."""
        st = self.scheduler.stats()   # plane lock + _cv, never nested
        gauges = {
            "serve_queued_jobs": sum(st.get("queued", {}).values()),
            "serve_running_jobs": st.get("jobs", {}).get("running", 0),
        }
        fleet = st.get("fleet")
        if isinstance(fleet, dict):
            workers = fleet.get("workers")
            # plane snapshots expose {"live": n, "active": n, "dead": n}
            if isinstance(workers, dict):
                live = workers.get("live")
                if isinstance(live, (int, float)):
                    gauges["fleet_live_workers"] = live
            elif isinstance(workers, (int, float)):
                gauges["fleet_live_workers"] = workers
        snap = slo.engine().snapshot()
        return {"text": obs_export.prometheus_text(
                    metrics=obs.snapshot(), slo=snap, gauges=gauges),
                "slo": snap}

    def _start_metrics_http(self) -> None:  # concurrency: _httpd set once before the accept loop starts
        """Optional localhost HTTP exposition (`GET /metrics`); the
        stdlib threading server keeps the daemon dependency-free."""
        if not self.metrics_port or self.metrics_port <= 0:
            return
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        daemon = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):              # noqa: N802 — stdlib contract
                if self.path.split("?")[0].rstrip("/") not in ("",
                                                               "/metrics"):
                    self.send_error(404)
                    return
                body = daemon._metrics_scrape()["text"].encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log lines
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.metrics_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.metrics_port = self._httpd.server_address[1]
        self._httpd_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-metrics-http",
            daemon=True)
        self._httpd_thread.start()
        print(f"[racon_tpu::serve] metrics exposition on "
              f"http://127.0.0.1:{self.metrics_port}/metrics",
              file=sys.stderr)

    def _stop_metrics_http(self) -> None:  # concurrency: atomic swap; a double stop gets None and no-ops
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except OSError:
                pass

    # -- accept / connection handling --------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return   # socket closed by stop()
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="serve-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """One thread per connection; a client vanishing mid-exchange
        closes only this socket."""
        try:
            f = conn.makefile("rwb")
            while True:
                try:
                    req = read_message(f)
                    if req is None:
                        return
                    resp = self._dispatch(req)
                except AdmissionError as e:
                    resp = {"ok": False, "error": str(e),
                            "rejected": "admission"}
                except (ValueError, KeyError, TypeError,
                        json.JSONDecodeError) as e:
                    resp = {"ok": False, "error": f"{e}"}
                except Exception as e:  # noqa: BLE001 — one bad request
                    # must not take down the connection (or the daemon)
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                write_message(f, resp)
                if resp.get("bye"):
                    self.stop(wait=False)
                    return
        except (OSError, BrokenPipeError, ConnectionResetError):
            pass   # client went away; the daemon does not care
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- protocol ----------------------------------------------------------

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pid": os.getpid(),
                    "backend": self.session.backend, "port": self.port}
        if op == "submit":
            spec = JobSpec.from_dict(
                {k: v for k, v in req.items() if k != "op"})
            job = self.scheduler.submit(spec)
            return {"ok": True, "job_id": job.id, "lane": job.lane,
                    "demotions": list(job.demotions)}
        if op == "status":
            job = self.scheduler.get(str(req["job_id"]))
            return {"ok": True, **job.as_status()}
        if op == "result":
            job = self.scheduler.get(str(req["job_id"]))
            if req.get("wait"):
                timeout = req.get("timeout")
                if not job.done.wait(None if timeout is None
                                     else float(timeout)):
                    # status last-but-error-wins: as_status()'s error
                    # field is None for a live job and must not clobber
                    # the timeout message
                    return {**job.as_status(), "ok": False,
                            "error": f"timeout waiting for {job.id}"}
            if not job.done.is_set():
                return {**job.as_status(), "ok": False,
                        "error": f"job {job.id} is {job.state}"}
            return {**job.as_status(), "ok": job.state == "done",
                    "result": job.result}
        if op == "cancel":
            return {"ok": True,
                    **self.scheduler.cancel(str(req["job_id"]))}
        if op == "stats":
            return {"ok": True, **self.scheduler.stats()}
        if op == "metrics":
            return {"ok": True, **self._metrics_scrape()}
        if op == "shutdown":
            return {"ok": True, "bye": True}
        raise ValueError(f"unknown op {op!r}; expected one of ping/submit/"
                         f"status/result/cancel/stats/metrics/shutdown")
