"""Resident polishing session: one process, many polish jobs.

A ``PolishSession`` owns what is expensive to build and safe to share —
the process-global kernel caches (``ops/kernel_cache.device_keyed_cache``
and the poa_driver geometry lru are keyed by topology, not by run, so
every compiled kernel outlives the polisher that built it) — and builds
what must be per-request fresh through the normal
``polisher.create_polisher`` seam: journal, run report, trace, fault
schedule (``polisher.reset_run_state``).  ``warm()`` pre-compiles the
consensus geometries once at startup via ``poa_driver.warm_geometries``,
so even the first job pays no kernel builds.

Because the per-run state the constructors reset is module-global,
in-process jobs must not overlap; ``run_job`` holds a lock and the
scheduler (scheduler.py) provides the concurrency by queueing.  Each
job runs inside its own directory (``<workdir>/jobs/<job_id>/``) holding
its journal, trace, report, and polished output — concurrent jobs can
never clobber each other's artifacts because the job id namespaces every
path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from .. import config, fingerprint, obs
from ..obs import context, flight, ledger
from ..polisher import create_polisher

#: Polish parameters a job may override, with the CLI defaults — the
#: same contract as `racon_tpu.cli` flags, so a serve job and a CLI run
#: with equal parameters produce byte-identical output.
POLISH_ARG_DEFAULTS = {
    "window_length": 500,
    "quality_threshold": 10.0,
    "error_threshold": 0.3,
    "trim": True,
    "fragment_correction": False,
    "match": 3,
    "mismatch": -5,
    "gap": -4,
    "num_threads": 1,
}

BACKENDS = ("cpu", "tpu")


class JobCancelled(RuntimeError):
    """Raised inside run_job when the job's cancel event is set."""


@dataclass
class JobSpec:
    """One polish request: input paths + polish parameters.

    ``args`` overrides ``POLISH_ARG_DEFAULTS`` (unknown keys are a
    submit-time error, not a mid-run crash).  ``job_id`` is assigned by
    the scheduler when empty.  ``window_budget`` overrides the daemon's
    ``RACON_TPU_SERVE_WINDOW_BUDGET`` for this job (0 = daemon default).
    """

    sequences: str
    overlaps: str
    target: str
    args: dict = field(default_factory=dict)
    include_unpolished: bool = False
    backend: str = ""
    job_id: str = ""
    submitter: str = "local"
    window_budget: int = 0
    #: Priority lane (higher serves first; fairness still rotates
    #: tenants within a lane — fleet/queues.py).
    priority: int = 0
    #: Optional trace context ({"trace_id", "parent"}) from the
    #: submitter, so the job's spans parent under the caller's timeline
    #: when the traces are merged (obs/context.py).
    trace: Optional[dict] = None

    def validate(self) -> None:
        unknown = sorted(set(self.args) - set(POLISH_ARG_DEFAULTS))
        if unknown:
            raise ValueError(
                f"unknown polish arg(s) {', '.join(unknown)}; allowed: "
                f"{', '.join(sorted(POLISH_ARG_DEFAULTS))}")
        if self.backend and self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r}; allowed: "
                             f"{', '.join(BACKENDS)}")
        for label, path in (("sequences", self.sequences),
                            ("overlaps", self.overlaps),
                            ("target", self.target)):
            if not path or not os.path.isfile(path):
                raise ValueError(f"{label} file not found: {path!r}")
        if self.job_id and ("/" in self.job_id or self.job_id.startswith(".")):
            raise ValueError(f"invalid job id {self.job_id!r}")

    def polish_args(self) -> dict:
        """The full kwargs for create_polisher: defaults + overrides."""
        merged = dict(POLISH_ARG_DEFAULTS)
        merged.update(self.args)
        return merged

    def as_dict(self) -> dict:
        return {
            "sequences": self.sequences,
            "overlaps": self.overlaps,
            "target": self.target,
            "args": dict(self.args),
            "include_unpolished": self.include_unpolished,
            "backend": self.backend,
            "job_id": self.job_id,
            "submitter": self.submitter,
            "window_budget": self.window_budget,
            "priority": self.priority,
            "trace": dict(self.trace) if self.trace else None,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        unknown = sorted(set(d) - {
            "sequences", "overlaps", "target", "args", "include_unpolished",
            "backend", "job_id", "submitter", "window_budget", "priority",
            "trace"})
        if unknown:
            raise ValueError(f"unknown job field(s): {', '.join(unknown)}")
        for key in ("sequences", "overlaps", "target"):
            if not isinstance(d.get(key), str) or not d.get(key):
                raise ValueError(f"job field {key!r} must be a non-empty "
                                 f"path string")
        args = d.get("args") or {}
        if not isinstance(args, dict):
            raise ValueError("job field 'args' must be an object")
        return cls(
            sequences=d["sequences"],
            overlaps=d["overlaps"],
            target=d["target"],
            args=dict(args),
            include_unpolished=bool(d.get("include_unpolished", False)),
            backend=str(d.get("backend") or ""),
            job_id=str(d.get("job_id") or ""),
            submitter=str(d.get("submitter") or "local"),
            window_budget=int(d.get("window_budget") or 0),
            priority=int(d.get("priority") or 0),
            trace=(dict(d.get("trace"))
                   if isinstance(d.get("trace"), dict) else None),
        )


def _journal_replayed(report) -> int:
    """Units the journal replayed across all phases of a resumed run."""
    return sum(rep.served.get("journal", 0)
               for rep in report.phases.values())


class PolishSession:
    """Resident session.  Thread-safe: ``run_job`` serializes in-process
    jobs (the per-run runtime state the polisher constructors reset is
    module-global); the kernel caches are shared across jobs and across
    sessions in the same process — that sharing IS the hot path."""

    def __init__(self, workdir: str, backend: str = "tpu"):
        if backend not in BACKENDS:
            raise ValueError(f"unknown backend {backend!r}")
        self.workdir = workdir
        self.backend = backend
        self.jobs_run = 0
        self.warmed: List[int] = []
        self.warm_wall_s = 0.0
        self._lock = threading.Lock()
        os.makedirs(os.path.join(workdir, "jobs"), exist_ok=True)

    # -- layout ------------------------------------------------------------

    def job_dir(self, job_id: str) -> str:
        # the `serve_job_dir` site of the unified fingerprint registry
        return fingerprint.serve_job_paths(self.workdir, job_id)["dir"]

    # -- startup warm-up ---------------------------------------------------

    def warm(self, window_lengths=(500,), match: int = 3,
             mismatch: int = -5, gap: int = -4) -> float:
        """Pre-compile (or load from the persistent XLA cache) every
        consensus kernel geometry for these window lengths, so the first
        job's consensus phase finds everything hot.  Device backend
        only; returns the wall seconds spent.  Same mechanism as the
        phase pipeline's warm-up thread (polisher.py) and bench.py's
        prewarm — ``poa_driver.warm_geometries``."""
        if self.backend != "tpu":
            return 0.0
        from ..ops import poa_driver

        lens = sorted({int(w) for w in window_lengths})
        t0 = time.monotonic()
        poa_driver.warm_geometries(lens, match, mismatch, gap)
        self.warm_wall_s = round(time.monotonic() - t0, 4)
        self.warmed = lens
        return self.warm_wall_s

    def warm_for_target(self, target_path: str, window_length: int = 500,
                        match: int = 3, mismatch: int = -5,
                        gap: int = -4) -> float:
        """Warm every geometry a specific draft will derive (full chunks
        plus per-contig tail remainders — ``observed_window_lengths``)."""
        if self.backend != "tpu":
            return 0.0
        from ..ops import poa_driver

        lens = poa_driver.observed_window_lengths(target_path,
                                                  int(window_length))
        return self.warm(sorted(lens), match, mismatch, gap)

    # -- job execution -----------------------------------------------------

    def run_job(self, spec: JobSpec,
                cancel_event: Optional[threading.Event] = None) -> dict:
        """Run one polish job to completion inside its job directory.

        Serialized: only one in-process job runs at a time (the
        scheduler queues the rest).  The job's journal is always armed
        with resume semantics — a re-submitted job whose previous run
        was preempted replays the journaled prefix instead of
        recomputing, and still produces byte-identical output."""
        with self._lock:
            return self._run_job_locked(spec, cancel_event)

    def _run_job_locked(self, spec: JobSpec, cancel) -> dict:
        job_id = spec.job_id or f"job{self.jobs_run:04d}"
        backend = spec.backend or self.backend
        paths = fingerprint.serve_job_paths(self.workdir, job_id, backend)
        jd = paths["dir"]
        os.makedirs(jd, exist_ok=True)
        out_path = paths["output"]
        trace_path = paths["trace"]
        journal_path = paths["journal"]
        report_path = paths["report"]

        cold = self.jobs_run == 0
        t0 = time.monotonic()
        if cancel is not None and cancel.is_set():
            raise JobCancelled(job_id)
        # trace-context propagation: a submitter's {trace_id, parent}
        # pair (JobSpec.trace) is activated before create_polisher so
        # the job's fresh tracer stamps it; a flight dump from this job
        # lands in the job directory
        context.activate(spec.trace)
        flight.set_dir(jd)
        try:
            polisher = create_polisher(
                spec.sequences, spec.overlaps, spec.target, backend=backend,
                journal_path=journal_path, resume_journal=True,
                trace_path=trace_path, **spec.polish_args())
            # The constructor armed this request's tracer; the instant
            # event tags the per-request trace with its job id (every
            # span in the file belongs to this job — the trace itself is
            # per-request).
            obs.event("serve.job", job=job_id, backend=backend, cold=cold,
                      submitter=spec.submitter)
            polisher.initialize()
            if cancel is not None and cancel.is_set():
                # Phase boundary: alignment is done and journaled; the
                # consensus phase has not started.  The journal makes the
                # cancellation cheap to undo — a re-run resumes from here.
                raise JobCancelled(job_id)
            out = polisher.polish(not spec.include_unpolished)
            kernel_builds = obs.counter_total("kernel.builds.")

            with open(out_path, "w") as f:
                for name, data in out:
                    f.write(f">{name}\n{data}\n")
            summary = polisher.report.summary()
            # compute-side latency-ledger fragment: per-stage seconds
            # from this run's own report plus the build/replay overlays,
            # persisted with the report and shipped in the result for
            # the scheduler's job ledger
            stage_s = ledger.stage_seconds(summary)
            stage_s.update(ledger.overlay_seconds(obs.snapshot()))
            polisher.report.ledger = {"job": job_id, "stage_s": stage_s}
            report_doc = dict(polisher.report.as_dict())
            report_doc["job_id"] = job_id
            with open(report_path, "w") as f:
                json.dump(report_doc, f, indent=1)
                f.write("\n")

            self.jobs_run += 1
            obs.telemetry_tick(jobs_run=self.jobs_run, job=job_id)
            # bounded span shipment: rides inside the result payload so
            # a tracing submitter can absorb this job's spans into its
            # own merged timeline
            ship = obs.shipment()
            return {
                "job_id": job_id,
                "backend": backend,
                "cold": cold,
                "wall_s": round(time.monotonic() - t0, 4),
                "records": len(out),
                "polished_bp": sum(len(data) for _, data in out),
                "kernel_builds": kernel_builds,
                "journal_replayed": _journal_replayed(polisher.report),
                "output": out_path,
                "report": report_path,
                "trace": trace_path,
                "obs": ship,
                "summary": summary,
                "ledger": {"stage_s": dict(stage_s)},
            }
        except JobCancelled:
            raise
        except Exception as e:  # noqa: BLE001 — post-mortem breadcrumb;
            # the scheduler owns the failure handling
            flight.dump("job_error", job=job_id,
                        error=f"{type(e).__name__}: {e}")
            raise
        finally:
            # scoped teardown: re-write the (now complete) per-job trace
            # and disarm, so the next job — or a bare polisher in the
            # same process — can never append into this job's file
            obs.release(write=True)
            context.clear()

    def stats(self) -> dict:
        return {
            "backend": self.backend,
            "jobs_run": self.jobs_run,
            "warmed_window_lengths": list(self.warmed),
            "warm_wall_s": self.warm_wall_s,
            "workdir": self.workdir,
        }


#: Serve knob accessors (registered in racon_tpu/config.py; README has
#: the docs rows).  Centralized here so scheduler/server share defaults.

def serve_port() -> int:
    return config.get_int("RACON_TPU_SERVE_PORT")


def serve_queue_depth() -> int:
    return config.get_int("RACON_TPU_SERVE_QUEUE_DEPTH")


def serve_max_jobs() -> int:
    return config.get_int("RACON_TPU_SERVE_MAX_JOBS")


def serve_warmup_enabled() -> bool:
    return config.get_bool("RACON_TPU_SERVE_WARMUP")


def serve_window_budget() -> int:
    return config.get_int("RACON_TPU_SERVE_WINDOW_BUDGET")
