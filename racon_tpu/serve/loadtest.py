"""Load-test harness for the `racon-tpu serve` daemon.

Closed-loop load generation: N client threads, each with its own socket,
each looping submit -> wait over its share of synthetic polish jobs
(``tools/simulate.py`` data).  Reports end-to-end latency percentiles
(p50/p95/p99 — queueing included, that is the point), aggregate
throughput over the makespan, per-job service walls, and the
cold-first-job vs warm-job delta that quantifies what the resident
session amortizes (kernel builds happen once, or zero times when the
startup warm-up ran).

``--docs PATH`` rewrites the marked block in docs/benchmarks.md with the
measured numbers; ``bench.py serve`` runs the same harness and stamps a
normalized entry into the bench history so the `obs bench` regression
gate covers the daemon path.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import List, Optional

from ..obs import ledger as joblog
from .client import ServeClient, ServeError

DOCS_BEGIN = "<!-- serve-loadtest:begin -->"
DOCS_END = "<!-- serve-loadtest:end -->"


def percentile(values: List[float], p: float) -> float:
    """Linearly interpolated percentile on a non-empty list — the same
    estimator `obs critpath` uses and `obs.metrics.hist_quantile`
    approximates per bucket, so percentiles agree across the harness,
    the analyzer, and the metrics registry."""
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = (p / 100.0) * (len(vs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    return round(vs[lo] + (pos - lo) * (vs[hi] - vs[lo]), 6)


def spawn_daemon(state_dir: str, backend: str = "tpu",
                 window_length: int = 500,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[dict] = None,
                 timeout: float = 300.0) -> subprocess.Popen:
    """Start a daemon subprocess on an ephemeral port and wait until it
    answers ping (startup includes the kernel warm-up, so the deadline
    is generous).  stderr goes to <state_dir>/daemon.stderr.log."""
    os.makedirs(state_dir, exist_ok=True)
    cmd = [sys.executable, "-m", "racon_tpu.cli", "serve",
           "--state-dir", state_dir, "--port", "0", "--backend", backend,
           "--warm-window", str(window_length)] + (extra_args or [])
    err_f = open(os.path.join(state_dir, "daemon.stderr.log"), "w")
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL, stderr=err_f,
                            env=env)
    deadline = time.monotonic() + timeout
    while True:
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve daemon exited {proc.returncode} during startup "
                f"(see {state_dir}/daemon.stderr.log)")
        try:
            with ServeClient.from_state_dir(state_dir, timeout=5.0) as c:
                c.ping()
            return proc
        except (OSError, ValueError, ServeError):
            if time.monotonic() > deadline:
                proc.kill()
                raise RuntimeError(
                    f"serve daemon not reachable after {timeout}s") from None
            time.sleep(0.2)


def run_loadtest(port: int, paths: dict, jobs: int, clients: int,
                 polish_args: Optional[dict] = None,
                 backend: str = "", timeout: float = 1200.0,
                 tenants: int = 1, priority_levels: int = 1,
                 profiles: Optional[List[dict]] = None) -> dict:
    """Drive an already-running daemon with `jobs` synthetic jobs from
    `clients` concurrent client threads; returns the summary dict (see
    module docstring for the metrics).

    Mixed multi-tenant load: jobs round-robin over `tenants` submitter
    identities and `priority_levels` priority lanes, and `profiles` (a
    list of polish-arg dicts layered over `polish_args`) varies the job
    shape — together they exercise the scheduler's tenant fairness,
    quota, and priority paths, not just its throughput."""
    polish_args = polish_args or {}
    clients = max(1, min(clients, jobs))
    tenants = max(1, tenants)
    priority_levels = max(1, priority_levels)
    per_job: List[Optional[dict]] = [None] * jobs
    errors: List[str] = []
    barrier = threading.Barrier(clients)
    t_start = time.monotonic()

    def client_loop(ci: int) -> None:
        try:
            with ServeClient(port, timeout=timeout) as c:
                barrier.wait()
                for ji in range(ci, jobs, clients):
                    tenant = f"tenant{ji % tenants}"
                    priority = ji % priority_levels
                    args = dict(polish_args)
                    if profiles:
                        args.update(profiles[ji % len(profiles)])
                    t0 = time.monotonic()
                    job_id = c.submit(paths["reads"], paths["overlaps"],
                                      paths["draft"], args=args,
                                      backend=backend,
                                      submitter=tenant, priority=priority)
                    resp = c.wait(job_id, timeout=timeout)
                    res = resp.get("result") or {}
                    per_job[ji] = {
                        "job_id": job_id,
                        "latency_s": round(time.monotonic() - t0, 4),
                        "t_done": round(time.monotonic() - t_start, 4),
                        "service_s": res.get("wall_s"),
                        "cold": bool(res.get("cold")),
                        "kernel_builds": res.get("kernel_builds"),
                        "polished_bp": res.get("polished_bp", 0),
                        "backend": res.get("backend"),
                        "ledger": res.get("ledger"),
                        "client": ci,
                        "tenant": tenant,
                        "priority": priority,
                    }
        except (ServeError, OSError, threading.BrokenBarrierError) as e:
            errors.append(f"client {ci}: {type(e).__name__}: {e}")

    stats_samples: List[dict] = []
    stop_poll = threading.Event()

    def stats_loop() -> None:
        # live-telemetry scrape: the daemon's `stats` op once a second
        # while the clients drive it — queue depths and the telemetry
        # ring under load, not just the end-state.  Polling is
        # observation and must never fail (or silently abandon) the
        # run: errors are tolerated per sample — a slow or restarting
        # daemon costs one data point and a reconnect, not the rest of
        # the series — and the cadence follows a monotonic deadline so
        # slow scrapes do not stretch the sampling interval.
        c: Optional[ServeClient] = None
        next_t = time.monotonic()
        try:
            while not stop_poll.is_set():
                try:
                    if c is None:
                        c = ServeClient(port, timeout=min(timeout, 15.0))
                    resp = c.stats()
                    resp.pop("ok", None)
                    resp["t"] = round(time.monotonic() - t_start, 3)
                    stats_samples.append(resp)  # concurrency: append-only; read after join
                except (ServeError, OSError, ValueError):
                    if c is not None:   # drop the sample, keep the series
                        c.close()
                        c = None
                next_t += 1.0
                delay = next_t - time.monotonic()
                if delay <= 0:
                    next_t = time.monotonic()  # fell behind: re-anchor
                    delay = 0.05
                stop_poll.wait(delay)
        finally:
            if c is not None:
                c.close()

    threads = [threading.Thread(target=client_loop, args=(ci,),
                                name=f"loadtest-c{ci}", daemon=True)
               for ci in range(clients)]
    poller = threading.Thread(target=stats_loop, name="loadtest-stats",
                              daemon=True)
    poller.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    makespan = time.monotonic() - t_start
    stop_poll.set()
    poller.join(timeout=5.0)

    # end-of-run SLO scrape: burn rates + alert state off the daemon's
    # own engine (the `metrics` wire op).  Tolerated failure -> None,
    # so the harness still drives daemons predating the op.
    slo_snap = None
    try:
        with ServeClient(port, timeout=min(timeout, 15.0)) as c:
            slo_snap = c.metrics().get("slo")
    except (ServeError, OSError, ValueError):
        pass

    completed = [r for r in per_job if r is not None]
    if not completed:
        raise RuntimeError("loadtest completed no jobs: "
                           + ("; ".join(errors) or "unknown"))
    lat = [r["latency_s"] for r in completed]
    cold = [r for r in completed if r["cold"]]
    warm = [r for r in completed
            if not r["cold"] and r["service_s"] is not None]
    warm_wall = sum(r["service_s"] for r in warm)
    warm_bp = sum(r["polished_bp"] for r in warm)
    cold_wall = cold[0]["service_s"] if cold else None
    warm_mean = round(warm_wall / len(warm), 4) if warm else None
    summary = {
        "jobs": jobs,
        "clients": clients,
        "tenants": tenants,
        "priority_levels": priority_levels,
        "completed": len(completed),
        "errors": errors,
        "makespan_s": round(makespan, 4),
        "polished_bp": sum(r["polished_bp"] for r in completed),
        "throughput_mbps": round(
            sum(r["polished_bp"] for r in completed) / 1e6 / makespan, 6),
        "latency_s": {
            "p50": percentile(lat, 50),
            "p95": percentile(lat, 95),
            "p99": percentile(lat, 99),
            "mean": round(sum(lat) / len(lat), 4),
            "max": max(lat),
        },
        "service_s": {
            "cold_first_job": cold_wall,
            "warm_mean": warm_mean,
            "cold_warm_delta": (round(cold_wall - warm_mean, 4)
                                if cold_wall is not None
                                and warm_mean is not None else None),
        },
        "warm_mbps": (round(warm_bp / 1e6 / warm_wall, 6)
                      if warm_wall else None),
        "warm_kernel_builds": sum(r["kernel_builds"] or 0 for r in warm),
        # scraped daemon-side telemetry: sample count, the peak queued
        # depth seen across polls, and the final sample (with the
        # daemon's own telemetry ring) — bounded, not the full series
        "daemon_stats": {
            "samples": len(stats_samples),
            "max_queued": max(
                (sum((s.get("queued") or {}).values())
                 for s in stats_samples), default=0),
            "last": stats_samples[-1] if stats_samples else None,
        },
        # elastic pool-size timeline + saturation curve: how worker
        # count, completion rate, and tail latency evolved over the run
        # (pool is None when the daemon ran without a fleet plane)
        "pool": pool_series(stats_samples),
        "curve": saturation_curve(completed, stats_samples, makespan),
        # aggregated latency ledger over the completed jobs (where the
        # wall went, stage by stage) + the daemon's per-tenant SLO
        # snapshot scraped at the end of the run
        "ledger": joblog.summarize(r.get("ledger") for r in completed),
        "slo": slo_snap,
        "per_job": completed,
    }
    return summary


def pool_series(stats_samples: List[dict]) -> Optional[dict]:
    """Elastic-pool timeline from the scraped stats samples: worker
    live/active counts over time plus the plane's own size timeline
    from the final sample.  None when no sample carried a fleet
    snapshot (daemon running without a plane)."""
    fleet = [(s["t"], s["fleet"]) for s in stats_samples
             if isinstance(s.get("fleet"), dict)]
    if not fleet:
        return None
    last = fleet[-1][1]
    return {
        "min": last.get("min_workers"),
        "max": last.get("max_workers"),
        "timeline": last.get("timeline"),
        "samples": [{"t": t,
                     "live": (f.get("workers") or {}).get("live"),
                     "active": (f.get("workers") or {}).get("active"),
                     "chunks_pending": f.get("chunks_pending")}
                    for t, f in fleet[-300:]],
    }


def saturation_curve(completed: List[dict], stats_samples: List[dict],
                     makespan: float, buckets: int = 12) -> List[dict]:
    """Time-bucketed saturation curve over the run: per bucket the
    completion rate (jobs/s), the p99 end-to-end latency of the jobs
    that finished in it, the peak total queued depth, and the peak live
    worker count (None without a fleet plane)."""
    if makespan <= 0 or not completed:
        return []
    buckets = max(1, min(buckets, len(completed)))
    step = makespan / buckets
    curve = []
    for b in range(buckets):
        lo, hi = b * step, (b + 1) * step
        done = [r for r in completed
                if lo <= r["t_done"] < hi or (b == buckets - 1
                                              and r["t_done"] >= lo)]
        in_bucket = [s for s in stats_samples if lo <= s["t"] < hi]
        workers = [((s.get("fleet") or {}).get("workers") or {}).get("live")
                   for s in in_bucket]
        workers = [w for w in workers if w is not None]
        curve.append({
            "t_end_s": round(hi, 3),
            "jobs_done": len(done),
            "jobs_per_s": round(len(done) / step, 4),
            "p99_s": (percentile([r["latency_s"] for r in done], 99)
                      if done else None),
            "max_queued": max(
                (sum((s.get("queued") or {}).values())
                 for s in in_bucket), default=0),
            "workers": max(workers) if workers else None,
        })
    return curve


# -- docs -------------------------------------------------------------------

def render_markdown(summary: dict, workload: str) -> str:
    lat = summary["latency_s"]
    svc = summary["service_s"]
    mix = ""
    if summary.get("tenants", 1) > 1 or summary.get("priority_levels", 1) > 1:
        mix = (f", mixed over {summary['tenants']} tenants / "
               f"{summary['priority_levels']} priority levels")
    lines = [
        DOCS_BEGIN,
        f"Measured by `python -m racon_tpu.serve.loadtest` — {workload}; "
        f"{summary['jobs']} jobs from {summary['clients']} concurrent "
        f"clients against one daemon{mix}:",
        "",
        "| metric | value |",
        "|---|---|",
        f"| throughput (makespan) | "
        f"{summary['throughput_mbps']:.4f} Mbp/s |",
        f"| warm-path throughput | "
        + (f"{summary['warm_mbps']:.4f} Mbp/s |"
           if summary["warm_mbps"] is not None else "n/a |"),
        f"| latency p50 / p95 / p99 | {lat['p50']:.2f} / {lat['p95']:.2f} "
        f"/ {lat['p99']:.2f} s |",
        f"| cold first job (service) | "
        + (f"{svc['cold_first_job']:.2f} s |"
           if svc["cold_first_job"] is not None else "n/a |"),
        f"| warm job mean (service) | "
        + (f"{svc['warm_mean']:.2f} s |"
           if svc["warm_mean"] is not None else "n/a |"),
        f"| cold-vs-warm delta | "
        + (f"{svc['cold_warm_delta']:.2f} s |"
           if svc["cold_warm_delta"] is not None else "n/a |"),
        f"| kernel builds in warm jobs | {summary['warm_kernel_builds']} |",
    ]
    pool = summary.get("pool")
    if pool and pool.get("max") is not None:
        lives = [s["live"] for s in pool.get("samples", [])
                 if s.get("live") is not None]
        lines.append(f"| elastic fleet workers (floor..ceiling) | "
                     f"{pool.get('min')}..{pool.get('max')} |")
        if lives:
            lines.append(f"| worker count seen (min..peak) | "
                         f"{min(lives)}..{max(lives)} |")
    curve = summary.get("curve") or []
    if len(curve) > 1:
        lines += [
            "",
            "Saturation curve (time-bucketed over the makespan — "
            "completion rate, tail latency, queue depth, and elastic "
            "worker count as the run progressed):",
            "",
            "| t (s) | jobs/s | p99 latency (s) | peak queued | workers |",
            "|---|---|---|---|---|",
        ]
        for row in curve:
            p99 = f"{row['p99_s']:.2f}" if row["p99_s"] is not None \
                else "n/a"
            workers = row["workers"] if row["workers"] is not None \
                else "n/a"
            lines.append(
                f"| {row['t_end_s']:.1f} | {row['jobs_per_s']:.2f} | "
                f"{p99} | {row['max_queued']} | {workers} |")
    lines.append(DOCS_END)
    return "\n".join(lines)


def update_docs(doc_path: str, summary: dict, workload: str) -> None:
    """Replace the marked serve-loadtest block in `doc_path` (appends a
    new block if the markers are absent)."""
    block = render_markdown(summary, workload)
    try:
        with open(doc_path) as f:
            text = f.read()
    except OSError:
        text = ""
    if DOCS_BEGIN in text and DOCS_END in text:
        head, rest = text.split(DOCS_BEGIN, 1)
        _, tail = rest.split(DOCS_END, 1)
        text = head + block + tail
    else:
        text = text.rstrip("\n") + "\n\n" + block + "\n"
    with open(doc_path, "w") as f:
        f.write(text)


# -- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="racon-tpu loadtest",
        description="Drive a racon-tpu serve daemon with concurrent "
        "synthetic polish jobs; report throughput + latency percentiles "
        "+ the cold-vs-warm first-job delta.")
    p.add_argument("--jobs", type=int, default=6)
    p.add_argument("--clients", type=int, default=3)
    p.add_argument("--tenants", type=int, default=1,
                   help="round-robin jobs over this many submitter "
                   "identities (exercises tenant fairness + quotas)")
    p.add_argument("--priority-levels", type=int, default=1,
                   help="round-robin jobs over priorities 0..N-1 "
                   "(exercises the priority lanes)")
    p.add_argument("--mix-profiles", action="store_true",
                   help="alternate job shapes (full vs half window "
                   "length) so the load is not uniform")
    p.add_argument("--fleet-max", type=int, default=None,
                   help="spawn the daemon with this elastic-fleet "
                   "worker ceiling (> 0 routes device jobs through "
                   "the chunk-level fleet plane)")
    p.add_argument("--fleet-min", type=int, default=None,
                   help="spawned daemon's fleet worker floor")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="spawned daemon's queued-job admission cap")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="spawned daemon's unfinished-job admission cap")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="spawned daemon's Prometheus-text HTTP port "
                   "(0 disables; lets CI scrape /metrics mid-run)")
    p.add_argument("--port", type=int, default=None,
                   help="drive an already-running daemon on this port "
                   "(default: spawn a fresh one)")
    p.add_argument("--state-dir", default=None,
                   help="state dir for the spawned daemon (default: "
                   "a temporary directory)")
    p.add_argument("--backend", choices=("tpu", "cpu"), default="tpu")
    p.add_argument("--mbp", type=float, default=0.01,
                   help="synthetic workload megabases per job's draft "
                   "(default 0.01)")
    p.add_argument("--coverage", type=int, default=6)
    p.add_argument("-w", "--window-length", type=int, default=500)
    p.add_argument("--json", action="store_true",
                   help="print the full summary JSON (per-job rows "
                   "included) instead of the short text")
    p.add_argument("--docs", metavar="PATH", default=None,
                   help="rewrite the serve-loadtest block in this "
                   "markdown file (docs/benchmarks.md)")
    args = p.parse_args(argv)

    import tempfile

    from ..tools import simulate

    workdir = args.state_dir or tempfile.mkdtemp(prefix="racon_serve_lt.")
    data_dir = os.path.join(workdir, "data")
    paths = simulate.generate(data_dir, mbp=args.mbp,
                              coverage=args.coverage)
    polish_args = {"window_length": args.window_length}
    workload = (f"{args.mbp} Mbp draft x {args.coverage}x coverage, "
                f"-w {args.window_length}, backend {args.backend}")

    extra: List[str] = []
    for flag, val in (("--fleet-max", args.fleet_max),
                      ("--fleet-min", args.fleet_min),
                      ("--queue-depth", args.queue_depth),
                      ("--max-jobs", args.max_jobs),
                      ("--metrics-port", args.metrics_port)):
        if val is not None:
            extra += [flag, str(val)]
    profiles = None
    if args.mix_profiles:
        profiles = [{}, {"window_length": max(50, args.window_length // 2)}]
        workload += ", mixed profiles"
    proc = None
    if args.port is None:
        proc = spawn_daemon(os.path.join(workdir, "state"), args.backend,
                            window_length=args.window_length,
                            extra_args=extra or None)
        with open(os.path.join(workdir, "state", "serve.json")) as f:
            port = json.load(f)["port"]
    else:
        port = args.port
    try:
        summary = run_loadtest(port, paths, args.jobs, args.clients,
                               polish_args=polish_args,
                               tenants=args.tenants,
                               priority_levels=args.priority_levels,
                               profiles=profiles)
    finally:
        if proc is not None:
            try:
                with ServeClient(port, timeout=10.0) as c:
                    c.shutdown()
                proc.wait(timeout=30)
            except (OSError, ServeError, ValueError,
                    subprocess.TimeoutExpired):
                proc.kill()

    if args.docs:
        update_docs(args.docs, summary, workload)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        slim = {k: v for k, v in summary.items() if k != "per_job"}
        print(json.dumps(slim, indent=1))
    return 0 if not summary["errors"] and \
        summary["completed"] == summary["jobs"] else 1


if __name__ == "__main__":
    sys.exit(main())
