"""Measure on-hardware λ device goldens: run golden scenarios through the
TPU backend (fused Pallas kernel) on the real chip and print the exact
accuracy numbers to pin.

The reference pins its accelerator goldens next to the CPU ones for every
scenario (/root/reference/test/racon_test.cpp:297-507 — 10 GPU pins); this
tool produces the numbers pinned the same way in
racon_tpu/tools/golden_scenarios.py (asserted by tests/test_golden.py
under RACON_TPU_HW_TESTS=1).

Usage:  python racon_tpu/tools/pin_device_golden.py [scenario|all]
Scenarios: paf (default) | sam | sam_noq | paf_noq | paf_w1000 | unit
           | kc | kf_fasta | kf_paf | all
"""

import gzip
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import racon_tpu
from racon_tpu import config, native
from racon_tpu.tools import golden_scenarios as gs

# same dataset location + override knob as tests/conftest.py (not imported:
# this tool must not inherit the test suite's CPU-mesh forcing)
DATA = config.get_str("RACON_TPU_TEST_DATA")

# The device pins isolate the CONSENSUS device path: phase 1 runs on the
# host aligner unless the caller overrides. The existing paf=1282 pin was
# measured under the host aligner (2026-07-29, before 'auto' defaulted
# phase 1 to hirschberg-on-TPU); pinning the engine here keeps every
# refresh comparable to it. Hirschberg-phase-1 accuracy is covered by the
# hw_session aligner steps, not these pins.
os.environ.setdefault("RACON_TPU_DEVICE_ALIGNER", "host")

ARGS = gs.ARGS  # single source: the args the asserted pins are defined by

COMP = bytes.maketrans(b"ACGT", b"TGCA")


def revcomp(b: bytes) -> bytes:
    return b.translate(COMP)[::-1]


def run_scenario(name: str, ref: bytes):
    if name in gs.POLISH:
        reads, ovl, tgt, extra = gs.POLISH[name]
        kind = "polish"
    else:
        reads, ovl, tgt, extra = gs.FRAGMENT[name]
        kind = "fragment"
    args = dict(ARGS)
    extra = dict(extra)
    drop = extra.pop("drop", True)
    args.update(extra)
    t0 = time.time()
    p = racon_tpu.create_polisher(DATA + reads, DATA + ovl, DATA + tgt,
                                  backend="tpu", **args)
    p.initialize()
    res = p.polish(drop)
    dt = time.time() - t0
    if kind == "polish":
        assert len(res) == 1, len(res)
        ed = native.edit_distance(revcomp(res[0][1].encode()), ref)
        return f"{name}: device_golden_ed={ed} wall={dt:.1f}s"
    count = len(res)
    total = sum(len(d) for _, d in res)
    return f"{name}: device_golden=({count}, {total}) wall={dt:.1f}s"


def main():
    scenario = sys.argv[1] if len(sys.argv) > 1 else "paf"
    known = list(gs.POLISH) + list(gs.FRAGMENT)
    if scenario != "all" and scenario not in known:
        sys.exit(f"unknown scenario {scenario!r}; one of {known} or 'all'")

    with gzip.open(DATA + "sample_reference.fasta.gz", "rb") as f:
        ref = b"".join(line.strip() for line in f
                       if not line.startswith(b">"))

    import jax
    platform = jax.devices()[0].platform
    if platform != "tpu":
        # a CPU/interpret-mode number must never be mistaken for the
        # hardware golden (the axon tunnel silently falls back when down)
        sys.exit(f"refusing to measure: platform is {platform!r}, not tpu")
    tier = config.get_str("RACON_TPU_POA_KERNEL")
    aligner = config.get_raw("RACON_TPU_DEVICE_ALIGNER")
    print(f"platform={platform} kernel_tier={tier} aligner={aligner}")

    names = known if scenario == "all" else [scenario]
    for name in names:
        print(run_scenario(name, ref), flush=True)


if __name__ == "__main__":
    main()
