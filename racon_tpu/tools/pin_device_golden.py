"""Measure the on-hardware λ device golden: run the PAF+qualities polishing
scenario through the TPU backend (fused Pallas kernel) on the real chip and
print the exact edit distance vs NC_001416.

The reference pins its accelerator goldens next to the CPU ones
(/root/reference/test/racon_test.cpp:316-318, GPU 1385 vs CPU 1312); this
script produces the number we pin the same way in tests/test_golden.py.

Usage:  python racon_tpu/tools/pin_device_golden.py [scenario]
Scenarios: paf (default) | sam | unit
"""

import gzip
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import racon_tpu
from racon_tpu import native

# same dataset location + override knob as tests/conftest.py (not imported:
# this tool must not inherit the test suite's CPU-mesh forcing)
DATA = os.environ.get("RACON_TPU_TEST_DATA", "/root/reference/test/data/")

COMP = bytes.maketrans(b"ACGT", b"TGCA")


def revcomp(b: bytes) -> bytes:
    return b.translate(COMP)[::-1]


def main():
    scenario = sys.argv[1] if len(sys.argv) > 1 else "paf"
    # keep in sync with tests/test_golden.py ARGS — the number this prints
    # is only meaningful as the pin for that test's scenario
    args = dict(window_length=500, quality_threshold=10.0,
                error_threshold=0.3, match=5, mismatch=-4, gap=-8,
                num_threads=1)
    reads, ovl = "sample_reads.fastq.gz", "sample_overlaps.paf.gz"
    if scenario == "sam":
        ovl = "sample_overlaps.sam.gz"
    elif scenario == "unit":
        args.update(match=1, mismatch=-1, gap=-1)

    with gzip.open(DATA + "sample_reference.fasta.gz", "rb") as f:
        ref = b"".join(line.strip() for line in f if not
                       line.startswith(b">"))

    import jax
    platform = jax.devices()[0].platform
    if platform != "tpu":
        # a CPU/interpret-mode number must never be mistaken for the
        # hardware golden (the axon tunnel silently falls back when down)
        sys.exit(f"refusing to measure: platform is {platform!r}, not tpu")

    t0 = time.time()
    p = racon_tpu.create_polisher(DATA + reads, DATA + ovl,
                                  DATA + "sample_layout.fasta.gz",
                                  backend="tpu", **args)
    p.initialize()
    res = p.polish(True)
    dt = time.time() - t0
    assert len(res) == 1, len(res)
    ed = native.edit_distance(revcomp(res[0][1].encode()), ref)
    print(f"platform={platform} scenario={scenario} device_golden_ed={ed} "
          f"wall={dt:.1f}s")


if __name__ == "__main__":
    main()
