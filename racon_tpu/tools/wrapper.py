"""Outer workflow wrapper: subsample reads to a target coverage and/or split
targets into byte-bounded chunks, then polish each chunk — for datasets too
large for one pipeline pass.

Capability parity with the reference wrapper
(/root/reference/scripts/racon_wrapper.py): same flags (--split,
--subsample REF_LEN COV), same work-directory lifecycle, chunks processed
sequentially with results streamed to stdout. Instead of shelling out to a
racon binary it drives the pipeline in-process; on multi-host deployments
each chunk is independent, so chunks can be fanned out across hosts with a
plain ordered gather (no collectives — see SURVEY.md §2.3).
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import time

from . import sampler
from ..polisher import create_polisher


def eprint(*args):
    print(*args, file=sys.stderr, flush=True)


def run(args) -> int:
    work_dir = os.path.join(
        os.getcwd(), f"racon_tpu_work_directory_{time.time()}")
    os.makedirs(work_dir, exist_ok=True)
    try:
        sequences = os.path.abspath(args.sequences)
        if args.subsample is not None:
            eprint("[racon_tpu::wrapper] subsampling sequences")
            ref_len, cov = int(args.subsample[0]), int(args.subsample[1])
            sequences = sampler.subsample(sequences, ref_len, cov, work_dir)

        targets = [os.path.abspath(args.target_sequences)]
        if args.split is not None:
            eprint("[racon_tpu::wrapper] splitting target sequences")
            targets = sampler.split(os.path.abspath(args.target_sequences),
                                    int(args.split), work_dir)
            eprint(f"[racon_tpu::wrapper] total number of splits: "
                   f"{len(targets)}")

        for part in targets:
            eprint("[racon_tpu::wrapper] polishing chunk")
            polisher = create_polisher(
                sequences, os.path.abspath(args.overlaps), part,
                backend="tpu" if args.tpu else "cpu",
                fragment_correction=args.fragment_correction,
                window_length=int(args.window_length),
                quality_threshold=float(args.quality_threshold),
                error_threshold=float(args.error_threshold),
                match=int(args.match), mismatch=int(args.mismatch),
                gap=int(args.gap), num_threads=int(args.threads))
            polisher.initialize()
            for name, data in polisher.polish(not args.include_unpolished):
                sys.stdout.write(f">{name}\n{data}\n")
        return 0
    finally:
        try:
            shutil.rmtree(work_dir)
        except OSError:
            eprint("[racon_tpu::wrapper] warning: unable to clean work "
                   "directory!")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="racon-tpu-wrapper",
        description="racon-tpu with outer subsample/split workflow")
    p.add_argument("sequences")
    p.add_argument("overlaps")
    p.add_argument("target_sequences")
    p.add_argument("--split", help="split target sequences into chunks of "
                   "desired size in bytes")
    p.add_argument("--subsample", nargs=2, metavar=("REF_LEN", "COV"),
                   help="subsample sequences to coverage COV given reference "
                   "length REF_LEN")
    p.add_argument("-u", "--include-unpolished", action="store_true")
    p.add_argument("-f", "--fragment-correction", action="store_true")
    p.add_argument("-w", "--window-length", default=500)
    p.add_argument("-q", "--quality-threshold", default=10.0)
    p.add_argument("-e", "--error-threshold", default=0.3)
    # wrapper score defaults match the reference wrapper (m=5 x=-4 g=-8,
    # scripts/racon_wrapper.py:188-193), not the binary's 3/-5/-4.
    p.add_argument("-m", "--match", default=5)
    p.add_argument("-x", "--mismatch", default=-4)
    p.add_argument("-g", "--gap", default=-8)
    p.add_argument("-t", "--threads", default=1)
    p.add_argument("--tpu", action="store_true")
    return run(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
