"""Outer workflow wrapper: subsample reads to a target coverage and/or split
targets into byte-bounded chunks, then polish each chunk — for datasets too
large for one pipeline pass.

Capability parity with the reference wrapper
(/root/reference/scripts/racon_wrapper.py): same flags (--split,
--subsample REF_LEN COV), same work-directory lifecycle, results streamed to
stdout in chunk order. Beyond the reference: --resume checkpoints, and
--jobs N fans chunks out to N worker processes — the multi-host topology
(chunks are independent; hosts need no collectives, only this ordered
gather over their outputs — SURVEY.md §2.3/§5.8).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import time

from . import sampler
from ..polisher import create_polisher


def eprint(*args):
    print(*args, file=sys.stderr, flush=True)


def _check_resume_stamp(args, work_dir: str) -> None:
    """Refuse to reuse checkpoints produced with different inputs/flags."""
    import json

    def mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return None

    stamp = {
        "sequences": os.path.abspath(args.sequences),
        "sequences_mtime": mtime(args.sequences),
        "overlaps": os.path.abspath(args.overlaps),
        "overlaps_mtime": mtime(args.overlaps),
        "targets": os.path.abspath(args.target_sequences),
        "targets_mtime": mtime(args.target_sequences),
        "split": args.split,
        "subsample": args.subsample,
        "flags": [args.include_unpolished, args.fragment_correction,
                  str(args.window_length), str(args.quality_threshold),
                  str(args.error_threshold), str(args.match),
                  str(args.mismatch), str(args.gap)],
    }
    stamp_path = os.path.join(work_dir, "wrapper_stamp.json")
    if os.path.isfile(stamp_path):
        with open(stamp_path) as f:
            old = json.load(f)
        if old != stamp:
            eprint("[racon_tpu::wrapper] error: resume directory was "
                   "created with different inputs or parameters; clear it "
                   "or choose another --resume directory")
            sys.exit(1)
    else:
        tmp = stamp_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(stamp, f)
        os.replace(tmp, stamp_path)


def run(args) -> int:
    # --resume keeps a persistent work directory with per-chunk outputs:
    # rerunning skips already-polished chunks (coarse checkpoint/restart —
    # the reference offers restartability only by manually re-driving its
    # --split chunks; SURVEY.md §5.4).
    resume = getattr(args, "resume", None)
    if resume:
        work_dir = os.path.abspath(resume)
        os.makedirs(work_dir, exist_ok=True)
    else:
        work_dir = os.path.join(
            os.getcwd(), f"racon_tpu_work_directory_{time.time()}")
        os.makedirs(work_dir, exist_ok=True)
    try:
        sequences = os.path.abspath(args.sequences)
        if resume:
            _check_resume_stamp(args, work_dir)
        if args.subsample is not None:
            ref_len, cov = int(args.subsample[0]), int(args.subsample[1])
            sub_path = sampler.subsample_path(sequences, cov, work_dir)
            if resume and os.path.isfile(sub_path):
                eprint("[racon_tpu::wrapper] reusing subsampled sequences")
                sequences = sub_path
            else:
                eprint("[racon_tpu::wrapper] subsampling sequences")
                sequences = sampler.subsample(sequences, ref_len, cov,
                                              work_dir)

        targets = [os.path.abspath(args.target_sequences)]
        if args.split is not None:
            eprint("[racon_tpu::wrapper] splitting target sequences")
            targets = sampler.split(os.path.abspath(args.target_sequences),
                                    int(args.split), work_dir)
            eprint(f"[racon_tpu::wrapper] total number of splits: "
                   f"{len(targets)}")

        jobs = int(getattr(args, "jobs", 1) or 1)
        if jobs > 1 and len(targets) > 1:
            return _run_distributed(args, sequences, targets, work_dir,
                                    resume, jobs)

        for idx, part in enumerate(targets):
            out_path = os.path.join(work_dir, f"polished_{idx}.fasta")
            if resume and os.path.isfile(out_path):
                eprint(f"[racon_tpu::wrapper] chunk {idx}: reusing "
                       "checkpointed result")
                with open(out_path) as f:
                    shutil.copyfileobj(f, sys.stdout)
                continue

            eprint("[racon_tpu::wrapper] polishing chunk")
            polisher = create_polisher(
                sequences, os.path.abspath(args.overlaps), part,
                backend="tpu" if args.tpu else "cpu",
                fragment_correction=args.fragment_correction,
                window_length=int(args.window_length),
                quality_threshold=float(args.quality_threshold),
                error_threshold=float(args.error_threshold),
                match=int(args.match), mismatch=int(args.mismatch),
                gap=int(args.gap), num_threads=int(args.threads))
            polisher.initialize()
            results = polisher.polish(not args.include_unpolished)
            if resume:
                # Stream into the checkpoint, publish atomically, then echo.
                tmp = out_path + ".tmp"
                with open(tmp, "w") as f:
                    for name, data in results:
                        f.write(f">{name}\n{data}\n")
                os.replace(tmp, out_path)
                with open(out_path) as f:
                    shutil.copyfileobj(f, sys.stdout)
            else:
                for name, data in results:
                    sys.stdout.write(f">{name}\n{data}\n")
        return 0
    finally:
        if not resume:
            try:
                shutil.rmtree(work_dir)
            except OSError:
                eprint("[racon_tpu::wrapper] warning: unable to clean work "
                       "directory!")


def _run_distributed(args, sequences, targets, work_dir, resume,
                     jobs) -> int:
    """Fan chunks out to worker processes (one per simulated host), gather
    their outputs in chunk order. Each worker is a fully independent
    pipeline — the multi-host scale-out needs no collectives."""
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    pending = []
    for idx, part in enumerate(targets):
        out_path = os.path.join(work_dir, f"polished_{idx}.fasta")
        if resume and os.path.isfile(out_path):
            continue
        pending.append((idx, part, out_path))

    running = []

    def launch(idx, part, out_path):
        cmd = [sys.executable, "-m", "racon_tpu.cli",
               "-w", str(args.window_length), "-q",
               str(args.quality_threshold), "-e", str(args.error_threshold),
               "-m", str(args.match), "-x", str(args.mismatch),
               "-g", str(args.gap), "-t", str(args.threads)]
        if args.include_unpolished:
            cmd.append("-u")
        if args.fragment_correction:
            cmd.append("-f")
        if args.tpu:
            cmd.append("--tpu")
        cmd += [sequences, os.path.abspath(args.overlaps), part]
        tmp = out_path + ".tmp"
        eprint(f"[racon_tpu::wrapper] host worker for chunk {idx}")
        return (idx, out_path, tmp, open(tmp, "wb"),
                subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env))

    def finish(entry):
        idx, out_path, tmp, tmp_f, proc = entry
        shutil.copyfileobj(proc.stdout, tmp_f)
        proc.wait()
        tmp_f.close()
        if proc.returncode != 0:
            eprint(f"[racon_tpu::wrapper] error: chunk {idx} worker failed")
            sys.exit(1)
        os.replace(tmp, out_path)

    i = 0
    while i < len(pending) or running:
        while i < len(pending) and len(running) < jobs:
            running.append(launch(*pending[i]))
            i += 1
        finish(running.pop(0))

    # Ordered gather.
    for idx in range(len(targets)):
        out_path = os.path.join(work_dir, f"polished_{idx}.fasta")
        with open(out_path) as f:
            shutil.copyfileobj(f, sys.stdout)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="racon-tpu-wrapper",
        description="racon-tpu with outer subsample/split workflow")
    p.add_argument("sequences")
    p.add_argument("overlaps")
    p.add_argument("target_sequences")
    p.add_argument("--split", help="split target sequences into chunks of "
                   "desired size in bytes")
    p.add_argument("--subsample", nargs=2, metavar=("REF_LEN", "COV"),
                   help="subsample sequences to coverage COV given reference "
                   "length REF_LEN")
    p.add_argument("-u", "--include-unpolished", action="store_true")
    p.add_argument("-f", "--fragment-correction", action="store_true")
    p.add_argument("-w", "--window-length", default=500)
    p.add_argument("-q", "--quality-threshold", default=10.0)
    p.add_argument("-e", "--error-threshold", default=0.3)
    # wrapper score defaults match the reference wrapper (m=5 x=-4 g=-8,
    # scripts/racon_wrapper.py:188-193), not the binary's 3/-5/-4.
    p.add_argument("-m", "--match", default=5)
    p.add_argument("-x", "--mismatch", default=-4)
    p.add_argument("-g", "--gap", default=-8)
    p.add_argument("-t", "--threads", default=1)
    p.add_argument("--tpu", action="store_true")
    p.add_argument("--resume", metavar="DIR",
                   help="persistent work directory with per-chunk "
                   "checkpoints; rerunning skips finished chunks")
    p.add_argument("--jobs", type=int, default=1,
                   help="polish chunks with this many parallel worker "
                   "processes (the multi-host fan-out topology)")
    return run(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
