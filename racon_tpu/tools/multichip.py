"""Multi-device scaling sweep: the MULTICHIP harness's measurement half.

The dryrun gate (``__graft_entry__.dryrun_multichip``) proves the sharded
polish step *works* — compile + run + pallas-vs-XLA-twin byte equality
over an 8-device mesh.  This tool adds the number ROADMAP item 2 actually
asks for: windows/second of the production consensus kernel dispatched
through the partitioner at 1, 2, 4, and 8 mesh shards, so the scaling
curve (near-linear on real chips, flat on forced virtual CPU devices —
they share the same cores) is a committed artifact instead of a claim.

Each device count runs in its OWN bounded subprocess: jax backend init is
one-way, so sweeping mesh widths in-process is impossible.  The sweep
varies ``RACON_TPU_MESH_SHAPE`` (the partitioner under-subscribes the
visible devices), which works identically on a real multi-chip backend
(``--real``) and on the forced virtual-CPU mesh this repo's CI can run —
the same mechanism hw_session's checkpointed ``multichip`` step replays
the moment a healthy tunnel shows up.

Output JSON keeps MULTICHIP_r05's gate keys (``n_devices``/``rc``/``ok``/
``skipped``/``tail``) and adds ``scaling``: one entry per device count
with the measured windows/s, the shard geometry that served it, and the
worker's ``shard.*`` obs counters (per-device row balance evidence).

Usage:
    python racon_tpu/tools/multichip.py --out MULTICHIP_r06.json
    python racon_tpu/tools/multichip.py --real      # ambient backend
    python racon_tpu/tools/multichip.py --counts 1,2 --skip-gate
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_COUNTS = (1, 2, 4, 8)


def _force_cpu_env(base, n_devices):
    """Forced virtual-CPU env for a worker subprocess (same flags the
    dryrun gate forces; loaded from __graft_entry__ by file path so this
    orchestrator never imports jax itself)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_graft_entry_multichip", os.path.join(HERE, "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod._force_cpu_env(base, n_devices)


def _worker_env(base, mesh_n, real, force_host):
    env = dict(base)
    if not real:
        env.update(_force_cpu_env(env, force_host))
    env["RACON_TPU_MESH_SHAPE"] = str(mesh_n)
    # one batch geometry across the whole sweep (the CPU default of 4
    # can't even shard 8 ways); 64 divides every count and satisfies the
    # lockstep kernel's G*m grouping at m=8.  An explicit knob wins.
    env.setdefault("RACON_TPU_BATCH_WINDOWS", "64")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (HERE, base.get("PYTHONPATH")) if p)
    return env


def measure(mesh_n: int, repeats: int) -> dict:
    """Worker body: time `repeats` sharded dispatches of the production
    consensus kernel at the ambient mesh width (RACON_TPU_MESH_SHAPE was
    set by the orchestrator before this process initialized jax).

    Tier choice mirrors the driver's reality: the fused 'ls' pallas
    kernel on a TPU backend, its vmapped XLA twin elsewhere (pallas
    interpret mode is minutes/window on CPU — the gate covers it; a
    timing sweep through it would measure the interpreter).  The first
    dispatch is the compile and is timed separately; the measured loop
    blocks on every batch so windows/s includes device round-trips.
    """
    import numpy as np

    sys.path.insert(0, HERE)
    import __graft_entry__ as g
    import jax

    from racon_tpu import obs
    from racon_tpu.ops import poa, poa_driver
    from racon_tpu.parallel.partitioner import get_partitioner

    obs.configure(metrics=True)
    devs = jax.devices()
    tier = "ls" if devs[0].platform == "tpu" else "xla"
    use_pallas = tier != "xla"
    cfg = poa.PoaConfig(max_nodes=256, max_len=128, max_backbone=128,
                        max_edges=8, depth=4, match=5, mismatch=-4, gap=-8)
    B = poa_driver._device_batch(tier)
    args = g._example_batch(cfg, B, np.random.default_rng(0))
    part = get_partitioner()
    shards = part.batch_axis_size if part.will_shard(B) else 1

    t0 = time.monotonic()
    kern = poa_driver._build_kernel(cfg, B, use_pallas,
                                    tier if use_pallas else "v2")
    res = poa_driver._unpack(poa_driver._submit(kern, args, use_pallas),
                             use_pallas)
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(repeats):
        if shards > 1:
            # same per-dispatch accounting the executor's pad seam emits
            # (B real rows, no padding at this geometry): the committed
            # artifact carries the per-device balance counters
            from racon_tpu.ops.batch_exec import count_shard_rows
            count_shard_rows(B, B, shards)
        res = poa_driver._unpack(
            poa_driver._submit(kern, args, use_pallas), use_pallas)
    wall = time.monotonic() - t0
    assert not res[3].any(), "sweep windows failed on the device kernel"
    snap = obs.snapshot() or {}
    counters = {k: v for k, v in (snap.get("counters") or {}).items()
                if k.startswith("shard.")}
    return {
        "mesh": mesh_n,
        "devices_visible": len(devs),
        "platform": devs[0].platform,
        "tier": tier,
        "batch": B,
        "shards": shards,
        "rows_per_device": B // max(1, shards),
        "repeats": repeats,
        "compile_s": round(compile_s, 3),
        "wall_s": round(wall, 4),
        "windows_per_s": round(B * repeats / wall, 2) if wall > 0 else None,
        "counters": counters,
    }


def _strip_progress(text):
    """Collapse ``\\r``-overwritten progress-bar frames to their final
    state (keep only what follows the last carriage return on each
    line), so the bounded tail captures spend their byte budget on real
    output instead of a hundred redraws of the same bar."""
    return "\n".join(ln.rsplit("\r", 1)[-1]
                     for ln in (text or "").split("\n"))


def _run_worker(mesh_n, repeats, real, force_host, bound_s):
    """One bounded subprocess per device count (backend init is one-way)."""
    cmd = [sys.executable, os.path.abspath(__file__),
           "--worker", str(mesh_n), "--repeats", str(repeats)]
    try:
        r = subprocess.run(
            cmd, cwd=HERE, capture_output=True, text=True, timeout=bound_s,
            env=_worker_env(os.environ, mesh_n, real, force_host))
    except subprocess.TimeoutExpired:
        return {"mesh": mesh_n, "ok": False,
                "error": f"timeout after {bound_s}s"}
    for line in reversed((r.stdout or "").splitlines()):
        if line.startswith("{"):
            try:
                return dict(json.loads(line), ok=r.returncode == 0)
            except ValueError:
                break
    return {"mesh": mesh_n, "ok": False,
            "error": f"rc={r.returncode}",
            "tail": _strip_progress((r.stderr or "")
                                    + (r.stdout or ""))[-800:]}


def sweep(counts=DEFAULT_COUNTS, repeats=3, real=False, force_host=None,
          bound_s=900):
    """Measure windows/s at each device count; returns {count: entry}."""
    force_host = max(counts) if force_host is None else force_host
    out = {}
    for n in counts:
        print(f"[multichip] sweep: {n} device(s)...", file=sys.stderr,
              flush=True)
        out[str(n)] = _run_worker(n, repeats, real, force_host, bound_s)
    return out


def gate(n_devices=8, bound_s=1800):
    """The r05-format dryrun gate: sharded polish step compiles, runs,
    and matches the XLA twin byte-for-byte (plus the 2-process distrib
    fleet rung), in a bounded subprocess."""
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   p for p in (HERE, os.environ.get("PYTHONPATH")) if p))
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             f"import __graft_entry__ as g; g.dryrun_multichip({n_devices})"],
            cwd=HERE, capture_output=True, text=True, timeout=bound_s,
            env=env)
        rc, tail = r.returncode, _strip_progress(
            (r.stderr or "") + (r.stdout or ""))[-2000:]
    except subprocess.TimeoutExpired:
        rc, tail = -1, f"gate timeout after {bound_s}s"
    return {"n_devices": n_devices, "rc": rc, "ok": rc == 0,
            "skipped": False, "tail": tail}


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="multichip.py",
        description="1/2/4/8-device scaling sweep + sharded dryrun gate")
    p.add_argument("--counts", default=",".join(map(str, DEFAULT_COUNTS)),
                   help="device counts to sweep (default 1,2,4,8)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed dispatches per count (default 3)")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="write the harness JSON here (default stdout only)")
    p.add_argument("--real", action="store_true",
                   help="use the ambient backend (silicon); default forces "
                        "a virtual-CPU mesh so a wedged tunnel can't hang "
                        "the sweep")
    p.add_argument("--force-host", type=int, default=None, metavar="N",
                   help="virtual host device count to force (default: "
                        "max of --counts; ignored with --real)")
    p.add_argument("--timeout", type=int, default=900, metavar="S",
                   help="bound per sweep subprocess (default 900)")
    p.add_argument("--gate-devices", type=int, default=8, metavar="N")
    p.add_argument("--skip-gate", action="store_true",
                   help="sweep only; skip the byte-identity dryrun gate")
    p.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.worker is not None:
        print(json.dumps(measure(args.worker, max(1, args.repeats))))
        return 0

    counts = sorted({int(c) for c in args.counts.split(",") if c.strip()})
    doc = gate(args.gate_devices) if not args.skip_gate else \
        {"n_devices": args.gate_devices, "rc": None, "ok": True,
         "skipped": True, "tail": "gate skipped (--skip-gate)"}
    doc["scaling"] = sweep(counts, repeats=args.repeats, real=args.real,
                           force_host=args.force_host,
                           bound_s=args.timeout)
    doc["forced"] = not args.real
    doc["ok"] = bool(doc["ok"]) and all(
        e.get("ok") for e in doc["scaling"].values())
    blob = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    if args.out:
        path = args.out if os.path.isabs(args.out) \
            else os.path.join(HERE, args.out)
        with open(path, "w") as f:
            f.write(blob)
        print(f"[multichip] wrote {path}", file=sys.stderr)
    print(blob, end="")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
