"""Isolate the per-node cost of the fused POA kernel's DP loop on the
current backend (meant for the real TPU).

Builds stripped-down Pallas kernels that run the same shape of
rank-ordered DP loop as poa_pallas.py, adding back one cost component per
mode, and times each:

  mode 0: H-row math only (shift + cummax + write), node index = loop rank
  mode 1: + dynamic node index via the masked `order` load
  mode 2: + base/in_cnt masked loads
  mode 3: + a 2-edge predecessor scan (edge-row load, key check, H row
            reads, running max)
  mode 4: + the has_out masked RMW per edge
  mode 5: mode 0 with the cross-sublane roll steps REMOVED (wrong result,
          right shape) — isolates the cost of pltpu.roll(axis=0)
  mode 6: mode 0 on a flat (1, 8*JW) row layout (lane rolls only, 8x the
          vregs per op) — the v1-style row to compare against
  mode 7: mode 0 with radix-4 lane / radix-8 sublane scans — same work,
          ~half the dependency-chain depth (tests the latency-bound
          hypothesis)
  mode 8: mode 0 on PAIRED rows (2, 8, JW): two independent DP chains per
          iteration in double-width ops — tests pipeline ILP from wider
          vregs (per_node accounts for the 2x rows)
  mode 9: the v3 LANE-LOCKSTEP row shape (poa_pallas_ls.py): (JC, 8, 128)
          rows — window g in sublane g — with lane-radix-4 + chunk-prefix
          cummax and a 128-row VMEM ring write; 8 windows per iteration
          (per_node accounts for the 8x)
  mode 10: mode 9 + a depth-4 delta scan (4 ring-row loads, masked max)
          and 12 exr-style (1,8,128) graph-row loads per rank — the
          ls dp_body's per-rank load traffic
  mode 11: mode 1 under the COLUMN-COMPRESSED while_loop (the v2
          colstep path in poa_pallas.py) on synthetic multiplicity-2
          column keys (key = rank // 2): adjacent same-column ranks
          retire in one iteration, so the serial trip count halves
  mode 12: mode 9 under the ls RANK-PAIR loop (poa_pallas_ls.py
          colstep path): two unconditional dp steps per iteration
  mode 13: the aligner band-loop baseline — a (1, 128) band row carried
          in registers, one scalar query-code load (masked loadn) and
          one shift+select recurrence per DP row
  mode 14: mode 13 PACKED (align_pallas.py pack path): one packed-word
          loadn per iteration, 4 byte-extracted rows scored per step —
          the serial trip count drops to ceil(R / 4)
  mode 15: banded-aligner FLAT baseline — the mode-13 recurrence on a
          full 1024-lane (8, 128) band row; the counter output returns
          IN-LOOP CELLS (lanes scored per DP row), not iterations
  mode 16: mode 15 on the banded 128-lane rung (ops/band.py ladder
          floor), band offset advancing along the diagonal per row —
          8x fewer in-loop cells
  mode 17: banded-POA FLAT baseline — an ls-shape rank row of 13 lane
          chunks (1664 columns) with chunk-prefix cummax and a VMEM
          ring write; counter returns in-loop cells per rank
  mode 18: mode 17 BANDED: only a 4-chunk window around the rank's
          backbone column is read/scored/written (`pl.ds(cb0, CB)`
          windowed ring access) — 13/4 = 3.25x fewer in-loop cells

mode 4 approximates the full v2 dp_body; mode 10 approximates the ls
dp_body. The deltas between modes say which component to attack next;
per-node microseconds are printed for each.

Every kernel also returns a MEASURED in-loop count via a second SMEM
output — serial loop iterations for modes 0-14, scored DP cells for
the banded modes 15-18 — and `--gate` compares the compressed modes
against their baselines on those measured counts, exiting nonzero
unless the ratios clear the floors (11 vs 1 and 12 vs 9: >= 1.5x
steps; 14 vs 13: >= 2x steps; 16 vs 15 and 18 vs 17: >= 3x cells, the
RACON_TPU_BAND acceptance floor for BOTH hot kernels).
Interpret-mode safe: the gate measures counts, not wall time, so CI
runs it on CPU.

Usage: python racon_tpu/tools/dp_cost_probe.py [R] [B] [reps]
       python racon_tpu/tools/dp_cost_probe.py --gate
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from racon_tpu.ops.kernel_cache import device_keyed_cache

NEG = -(1 << 28)


@device_keyed_cache(maxsize=32)
def build(mode: int, R: int, B: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    JW = 128
    NW = 256
    E = 12
    G = -8
    JC = 4       # lane chunks per lockstep row (modes 9/10)
    RING = 128   # lockstep H ring rows (modes 9/10)
    GSLOTS = 16  # lockstep graph-row slots (mode 10 dynamic loads)
    JC2 = 13     # banded-POA flat row chunks, 1664 cols (modes 17/18)
    CB = 4       # banded-POA live window chunks (mode 18)
    RING2 = 8    # banded-POA H ring rows (modes 17/18)

    def kernel(seed_ref, out_ref, steps_ref, H, order, base, key, in_cnt,
               in_src, has_out, gls):
        jlane = jax.lax.broadcasted_iota(jnp.int32, (8, JW), 1)
        jsub = jax.lax.broadcasted_iota(jnp.int32, (8, JW), 0)
        jj = jsub * JW + jlane
        nlane = jax.lax.broadcasted_iota(jnp.int32, (8, NW), 1)
        nsub = jax.lax.broadcasted_iota(jnp.int32, (8, NW), 0)
        nn_i = nsub * NW + nlane
        gvec = jj * G

        def loadn(tile, idx):
            return jnp.sum(jnp.where(nn_i == idx, tile,
                                     jnp.zeros_like(tile)))

        def eload(ref, e, u):
            row = ref[pl.ds(e, 1)][0]
            return jnp.sum(jnp.where(nn_i == u, row, jnp.zeros_like(row)))

        def shift1(x, fill):
            ln = pltpu.roll(x, 1, 1)
            if mode == 5:
                y = ln
            else:
                carry = pltpu.roll(ln, 1, 0)
                y = jnp.where(jlane == 0, carry, ln)
            return jnp.where(jj == 0, fill, y)

        def tree_max(xs):
            while len(xs) > 1:
                nxt = [jnp.maximum(a, b) for a, b in zip(xs[::2], xs[1::2])]
                if len(xs) % 2:
                    nxt.append(xs[-1])
                xs = nxt
            return xs[0]

        def cummaxj(x):
            if mode == 7:
                # radix-4 lane prefix: rounds of 3 independent shifted
                # copies, tree-combined (shallower chain than 7 binary
                # rounds)
                w = 1
                while w < JW:
                    shs = [jnp.where(jlane >= k * w,
                                     pltpu.roll(x, k * w, 1), NEG)
                           for k in (1, 2, 3) if k * w < JW]
                    x = tree_max([x] + shs)
                    w *= 4
            else:
                k = 1
                while k < JW:
                    x = jnp.maximum(
                        x, jnp.where(jlane >= k, pltpu.roll(x, k, 1), NEG))
                    k *= 2
            if mode == 5:
                return x
            tot = jnp.max(x, axis=1, keepdims=True)
            p = jnp.broadcast_to(tot, x.shape)
            if mode == 7:
                # radix-8 sublane exclusive prefix: 7 independent shifted
                # copies, tree-combined (row 0 is NEG by the jsub masks)
                return jnp.maximum(x, tree_max(
                    [jnp.where(jsub >= k, pltpu.roll(p, k, 0), NEG)
                     for k in range(1, 8)]))
            k = 1
            while k < 8:
                p = jnp.maximum(
                    p, jnp.where(jsub >= k, pltpu.roll(p, k, 0), NEG))
                k *= 2
            excl = jnp.where(jsub >= 1, pltpu.roll(p, 1, 0), NEG)
            return jnp.maximum(x, excl)

        FW = 8 * JW

        def shift1_flat(x, fill):
            flane = jax.lax.broadcasted_iota(jnp.int32, (1, FW), 1)
            return jnp.where(flane == 0, fill, pltpu.roll(x, 1, 1))

        def cummax_flat(x):
            flane = jax.lax.broadcasted_iota(jnp.int32, (1, FW), 1)
            k = 1
            while k < FW:
                x = jnp.maximum(
                    x, jnp.where(flane >= k, pltpu.roll(x, k, 1), NEG))
                k *= 2
            return x

        if mode == 6:
            flane = jax.lax.broadcasted_iota(jnp.int32, (1, FW), 1)
            gflat = flane * G
            H[0:1] = (gflat + seed_ref[0, 0, 0]).reshape(1, 1, FW)

            def dp_flat(r, c):
                P = H[pl.ds(r, 1)][0]
                scvec = jnp.where(flane % 4 == 1, 5, -4)
                diag = shift1_flat(P, NEG) + scvec
                up = P + G
                V = jnp.where(diag >= up, diag, up)
                row = cummax_flat(V - gflat) + gflat
                H[pl.ds(r + 1, 1)] = row.reshape(1, 1, FW)
                return c + 1

            steps_ref[0, 0, 0] = jax.lax.fori_loop(0, R, dp_flat, 0)
            out_ref[0, 0, 0] = H[pl.ds(R, 1)][0][0, 0]
            return

        if mode == 8:
            psub = jax.lax.broadcasted_iota(jnp.int32, (2, 8, JW), 1)
            plane = jax.lax.broadcasted_iota(jnp.int32, (2, 8, JW), 2)
            jj2 = psub * JW + plane
            gp = jj2 * G
            H[0:1] = (gp + seed_ref[0, 0, 0]).reshape(1, 2, 8, JW)

            def shift1_pair(x, fill):
                ln = pltpu.roll(x, 1, 2)
                carry = pltpu.roll(ln, 1, 1)
                y = jnp.where(plane == 0, carry, ln)
                return jnp.where(jj2 == 0, fill, y)

            def cummax_pair(x):
                k = 1
                while k < JW:
                    x = jnp.maximum(
                        x, jnp.where(plane >= k, pltpu.roll(x, k, 2), NEG))
                    k *= 2
                tot = jnp.max(x, axis=2, keepdims=True)
                p = jnp.broadcast_to(tot, x.shape)
                k = 1
                while k < 8:
                    p = jnp.maximum(
                        p, jnp.where(psub >= k, pltpu.roll(p, k, 1), NEG))
                    k *= 2
                excl = jnp.where(psub >= 1, pltpu.roll(p, 1, 1), NEG)
                return jnp.maximum(x, excl)

            def dp_pair(r, c):
                P = H[pl.ds(r, 1)][0]                  # (2, 8, JW)
                scvec = jnp.where(jj2 % 4 == 1, 5, -4)
                diag = shift1_pair(P, NEG) + scvec
                up = P + G
                V = jnp.where(diag >= up, diag, up)
                row = cummax_pair(V - gp) + gp
                H[pl.ds(r + 1, 1)] = row.reshape(1, 2, 8, JW)
                return c + 1

            steps_ref[0, 0, 0] = jax.lax.fori_loop(0, R, dp_pair, 0)
            out_ref[0, 0, 0] = H[pl.ds(R, 1)][0][0, 0, 0]
            return

        if mode in (9, 10, 12):
            # v3 lane-lockstep row shape: (JC, 8, 128), window g in
            # sublane g; ring of RING H rows; lane-radix-4 + chunk-prefix
            # cummax (no cross-sublane carries — windows are independent)
            llane = jax.lax.broadcasted_iota(jnp.int32, (JC, 8, 128), 2)
            lchunk = jax.lax.broadcasted_iota(jnp.int32, (JC, 8, 128), 0)
            ljj = lchunk * 128 + llane
            lg = ljj * G
            # the delta scan reads ring rows before the DP has written
            # them (r < RING): every slot must hold defined, seed-derived
            # data, or uninitialized VMEM poisons the chain on real TPU
            # (interpret mode zero-fills and would hide it)
            ring_i = jax.lax.broadcasted_iota(
                jnp.int32, (RING, JC, 8, 128), 0)
            H[:] = lg[None] + seed_ref[0, 0, 0] - ring_i

            def shiftr_ls(x, fill):
                ln = pltpu.roll(x, 1, 2)
                carry = pltpu.roll(ln, 1, 0)
                y = jnp.where(llane == 0, carry, ln)
                return jnp.where(ljj == 0, fill, y)

            def cummax_ls(x):
                w = 1
                while w < 128:
                    shs = [jnp.where(llane >= k * w,
                                     pltpu.roll(x, k * w, 2), NEG)
                           for k in (1, 2, 3) if k * w < 128]
                    x = tree_max([x] + shs)
                    w *= 4
                tot = jnp.max(x, axis=2, keepdims=True)
                p = jnp.broadcast_to(tot, x.shape)
                acc = jnp.full(x.shape, NEG, jnp.int32)
                for k in range(1, JC):
                    acc = jnp.maximum(
                        acc, jnp.where(lchunk >= k, pltpu.roll(p, k, 0),
                                       NEG))
                return jnp.maximum(x, acc)

            # graph-row slots standing in for rk_base/rk_delta[e]/rk_dmax
            # — real (rank-derived) content so the loads cannot fold away
            gl_lane = jax.lax.broadcasted_iota(
                jnp.int32, (GSLOTS, 8, 128), 2)
            gl_slot = jax.lax.broadcasted_iota(
                jnp.int32, (GSLOTS, 8, 128), 0)
            gls[:] = (gl_lane + gl_slot) % 7

            def dp_ls(r):
                P = H[pl.ds(r % RING, 1)][0]           # (JC, 8, 128)
                if mode == 10:
                    # exr-style per-rank graph loads: a DYNAMIC-index
                    # (1,8,128) row slice + lane mask each, like
                    # dp_body's ref[pl.ds(r // 128, 1)] reads
                    lane1p = jax.lax.broadcasted_iota(
                        jnp.int32, (8, 128), 1)
                    acc = jnp.int32(0)
                    for e in range(E):
                        c = gls[pl.ds((r + e) % GSLOTS, 1)][0]
                        acc = acc + jnp.sum(
                            jnp.where(lane1p == (r % 128), c, 0))
                    # depth-4 delta scan: prior ring rows, masked max;
                    # acc (from the loads) feeds both the scan depth and
                    # the row below, so the loads are not eliminable
                    def dscan(d, Pm):
                        prow = H[pl.ds((r - d) % RING, 1)][0]
                        return jnp.where(d <= (acc % 4) + 1,
                                         jnp.maximum(Pm, prow), Pm)
                    P = jax.lax.fori_loop(1, 5, dscan, P)
                    P = P + (acc & 1)
                scvec = jnp.where(ljj % 4 == 1, 5, -4)
                diag = shiftr_ls(P, NEG) + scvec
                up = P + G
                V = jnp.where(diag >= up, diag, up)
                row = cummax_ls(V - lg) + lg
                H[pl.ds((r + 1) % RING, 1)] = row.reshape(1, JC, 8, 128)

            if mode == 12:
                # the ls colstep path: two unconditional ranks per serial
                # iteration (poa_pallas_ls.py pair_body), trailing rank
                # guarded for odd R
                def pair_ls(p, c):
                    r = 2 * p
                    dp_ls(r)

                    @pl.when(r + 1 < R)
                    def _():
                        dp_ls(r + 1)

                    return c + 1

                iters = jax.lax.fori_loop(0, (R + 1) // 2, pair_ls, 0)
            else:
                def one_ls(r, c):
                    dp_ls(r)
                    return c + 1

                iters = jax.lax.fori_loop(0, R, one_ls, 0)
            steps_ref[0, 0, 0] = iters
            hr = H[pl.ds(R % RING, 1)][0]
            out_ref[0, 0, 0] = hr[0, 0, 0] + hr[0, 0, 1]
            return

        if mode in (13, 14):
            # aligner band-loop shape: one (1, 128) band row carried in
            # registers, shift + select recurrence per DP row (the
            # Hirschberg edge kernel's serial chain without its DMA).
            # mode 13 loads one scalar query code per row; mode 14 loads
            # one packed word per iteration and scores 4 byte-extracted
            # rows (align_pallas.py pack path)
            alane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
            row0 = alane * G + seed_ref[0, 0, 0]

            def astep(qc, row):
                scvec = jnp.where(alane % 5 == qc, 5, -4)
                dshift = jnp.where(alane == 0, NEG, pltpu.roll(row, 1, 1))
                diag = dshift + scvec
                up = row + G
                return jnp.where(diag >= up, diag, up)

            if mode == 13:
                base[:] = nn_i % 5         # query codes, one per slot

                def arow(i, c):
                    row, s = c
                    qc = loadn(base[:], i)
                    return (astep(qc, row), s + 1)

                row, iters = jax.lax.fori_loop(
                    0, R, arow, (row0, jnp.int32(0)))
            else:
                # slot w holds codes 4w..4w+3, one byte each (the
                # encoding.pack_bases layout)
                pw = jnp.zeros_like(nn_i)
                for p in range(4):
                    pw = pw + (((4 * nn_i + p) % 5) << (8 * p))
                base[:] = pw

                def arow4(it, c):
                    row, s = c
                    qword = loadn(base[:], it)
                    for p in range(4):
                        i = it * 4 + p
                        qc = (qword >> (8 * p)) & 0xFF
                        row = jnp.where(i < R, astep(qc, row), row)
                    return (row, s + 1)

                row, iters = jax.lax.fori_loop(
                    0, (R + 3) // 4, arow4, (row0, jnp.int32(0)))
            steps_ref[0, 0, 0] = iters
            out_ref[0, 0, 0] = row[0, 0] + row[0, 1]
            return

        if mode in (15, 16):
            # banded-aligner CELL gate (ops/band.py): mode 15 scores a
            # full 1024-lane (8, 128) band row per DP row; mode 16 keeps
            # the 128-lane banded rung, its lane->column mapping
            # advancing one diagonal per row (the Ukkonen band offset).
            # The counter output is IN-LOOP CELLS, not iterations — the
            # serial chain length is identical by construction (banding
            # narrows live lanes per row, it does not shorten the row
            # chain), which is exactly the claim the cost model makes.
            AS = 8 if mode == 15 else 1
            blane = jax.lax.broadcasted_iota(jnp.int32, (AS, 128), 1)
            bsub = jax.lax.broadcasted_iota(jnp.int32, (AS, 128), 0)
            bjj = bsub * 128 + blane
            row0 = bjj * G + seed_ref[0, 0, 0]
            base[:] = nn_i % 5             # query codes, one per slot

            def bstep(r, c):
                row, cells = c
                qc = loadn(base[:], r)
                # mode 16: lane j of the banded row is global column
                # j + r (band advances along the main diagonal)
                col = bjj + (r if mode == 16 else 0)
                scvec = jnp.where(col % 5 == qc, 5, -4)
                ln = pltpu.roll(row, 1, 1)
                if AS > 1:
                    carry = pltpu.roll(ln, 1, 0)
                    ln = jnp.where(blane == 0, carry, ln)
                dshift = jnp.where(bjj == 0, NEG, ln)
                diag = dshift + scvec
                up = row + G
                return (jnp.where(diag >= up, diag, up),
                        cells + AS * 128)

            row, cells = jax.lax.fori_loop(
                0, R, bstep, (row0, jnp.int32(0)))
            steps_ref[0, 0, 0] = cells
            out_ref[0, 0, 0] = row[0, 0] + row[0, 1]
            return

        if mode in (17, 18):
            # banded-POA CELL gate: ls-shape rank rows of JC2 lane
            # chunks (13 * 128 = 1664 columns, the production wl-class).
            # Mode 17 reads/scores/writes all 13 chunks per rank; mode
            # 18 touches only a CB-chunk window around the rank's
            # backbone column via `pl.ds(cb0, CB)` on a flattened
            # (RING2 * JC2, ...) ring — the windowed access pattern of
            # the banded POA kernels.  Counter output is in-loop cells.
            W = JC2 if mode == 17 else CB
            wlane = jax.lax.broadcasted_iota(jnp.int32, (W, 8, 128), 2)
            wchunk = jax.lax.broadcasted_iota(jnp.int32, (W, 8, 128), 0)
            wjj = wchunk * 128 + wlane
            wg = wjj * G
            # every ring slot holds defined, seed-derived data (mode 18
            # reads windows row r+1 never wrote; see modes 9/10 note)
            ring_i = jax.lax.broadcasted_iota(
                jnp.int32, (RING2 * JC2, 8, 128), 0)
            H[:] = ring_i % 97 + seed_ref[0, 0, 0]

            def wshift(x, fill):
                ln = pltpu.roll(x, 1, 2)
                carry = pltpu.roll(ln, 1, 0)
                y = jnp.where(wlane == 0, carry, ln)
                return jnp.where(wjj == 0, fill, y)

            def wcummax(x):
                w = 1
                while w < 128:
                    shs = [jnp.where(wlane >= k * w,
                                     pltpu.roll(x, k * w, 2), NEG)
                           for k in (1, 2, 3) if k * w < 128]
                    x = tree_max([x] + shs)
                    w *= 4
                tot = jnp.max(x, axis=2, keepdims=True)
                p = jnp.broadcast_to(tot, x.shape)
                acc = jnp.full(x.shape, NEG, jnp.int32)
                for k in range(1, W):
                    acc = jnp.maximum(
                        acc, jnp.where(wchunk >= k, pltpu.roll(p, k, 0),
                                       NEG))
                return jnp.maximum(x, acc)

            def wrow(r, cells):
                # window origin tracks the rank's backbone column
                cb0 = jnp.clip(r * JC2 // R - CB // 2, 0, JC2 - W)
                P = H[pl.ds((r % RING2) * JC2 + cb0, W)]
                scvec = jnp.where(wjj % 4 == 1, 5, -4)
                diag = wshift(P, NEG) + scvec
                up = P + G
                V = jnp.where(diag >= up, diag, up)
                row = wcummax(V - wg) + wg
                H[pl.ds(((r + 1) % RING2) * JC2 + cb0, W)] = row
                return cells + W * 128

            cells = jax.lax.fori_loop(0, R, wrow, jnp.int32(0))
            steps_ref[0, 0, 0] = cells
            hr = H[pl.ds((R % RING2) * JC2, 1)][0]
            out_ref[0, 0, 0] = hr[0, 0] + hr[0, 1]
            return

        # graph state init (content irrelevant; loads must be real)
        order[:] = nn_i
        base[:] = nn_i % 4
        # mode 11: synthetic multiplicity-2 column keys — every adjacent
        # rank pair shares a column, so the colstep loop runs at its 2x
        # compression ceiling (the NODE_GROWTH=2.0 expectation)
        key[:] = ((nn_i // 2) if mode == 11 else nn_i).astype(jnp.float32)
        in_cnt[:] = jnp.where(nn_i > 0, 2, 0)
        in_src[:] = jnp.zeros((E, 8, NW), jnp.int32)
        in_src[0:1] = jnp.maximum(nn_i - 1, 0).reshape(1, 8, NW)
        in_src[1:2] = jnp.maximum(nn_i - 2, 0).reshape(1, 8, NW)
        has_out[:] = jnp.zeros((8, NW), jnp.int32)
        # runtime seed keeps XLA from constant-folding the whole call
        H[0:1] = (gvec + seed_ref[0, 0, 0]).reshape(1, 8, JW)

        # modes 5 and 7 are row-math variants of mode 0: no graph-state
        # machinery, or their deltas vs mode 0 would be confounded;
        # mode 11 is mode 1's body under the column-compressed loop
        level = 0 if mode in (5, 7) else 1 if mode == 11 else mode

        def dp_work(r):
            if level >= 1:
                u = loadn(order[:], r)
            else:
                u = r
            if level >= 2:
                ub = loadn(base[:], u)
                cnt = loadn(in_cnt[:], u)
            else:
                ub = jnp.int32(1)
                cnt = jnp.int32(0)

            if level >= 3:
                def pred_scan(e, c):
                    P, any_valid = c
                    src = eload(in_src, e, u)
                    ok = loadn(key[:], jnp.maximum(src, 0)) >= 0.0
                    prow = H[pl.ds(jnp.maximum(src, 0) + 1, 1)][0]
                    better = ok & (prow > P)
                    P = jnp.where(better, prow, P)
                    if level >= 4:
                        @pl.when(ok)
                        def _():
                            has_out[:] = jnp.where(
                                nn_i == jnp.maximum(src, 0), 1, has_out[:])
                    return (P, any_valid | ok)

                P0 = jnp.full((8, JW), NEG, jnp.int32)
                P, any_valid = jax.lax.fori_loop(0, cnt, pred_scan,
                                                 (P0, jnp.bool_(False)))
                # virtual-row fallback, as in the real kernel — without it
                # zero-pred nodes saturate the whole chain to NEG
                P = jnp.where(any_valid, P, H[0:1][0])
            else:
                P = H[pl.ds(jnp.maximum(u, 0), 1)][0]

            scvec = jnp.where(jj % 4 == ub, 5, -4)
            Psh = shift1(P, NEG)
            diag = Psh + scvec
            up = P + G
            V = jnp.where(diag >= up, diag, up)
            row = cummaxj(V - gvec) + gvec
            H[pl.ds(u + 1, 1)] = row.reshape(1, 8, JW)

        if mode == 11:
            # the v2 colstep while_loop (poa_pallas.py): retire rank r,
            # and r+1 too when it shares r's column key
            def col_cond(c):
                return c[0] < R

            def col_body(c):
                r, s = c
                dp_work(r)
                ku = loadn(key[:], loadn(order[:], r))
                k2 = loadn(key[:], loadn(order[:], r + 1))
                pair = (r + 1 < R) & (k2 == ku)

                @pl.when(pair)
                def _():
                    dp_work(r + 1)

                return (r + 1 + pair.astype(jnp.int32), s + 1)

            _, iters = jax.lax.while_loop(
                col_cond, col_body, (jnp.int32(0), jnp.int32(0)))
        else:
            def dp(r, c):
                dp_work(r)
                return c + 1

            iters = jax.lax.fori_loop(0, R, dp, 0)
        steps_ref[0, 0, 0] = iters
        # tap two lanes: a single lane can legitimately saturate to NEG in
        # the stripped-down modes, which would false-positive the seed check
        hr = H[pl.ds(R, 1)][0]
        out_ref[0, 0, 0] = hr[0, 0] + hr[0, 1]

    call = pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[pl.BlockSpec((1, 1, 1), lambda b: (b, 0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec((1, 1, 1), lambda b: (b, 0, 0),
                                memory_space=pltpu.SMEM),
                   pl.BlockSpec((1, 1, 1), lambda b: (b, 0, 0),
                                memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((B, 1, 1), jnp.int32),
                   jax.ShapeDtypeStruct((B, 1, 1), jnp.int32)],
        scratch_shapes=[
            pltpu.VMEM((R + 1, 1, 8 * JW) if mode == 6 else
                       (R + 1, 2, 8, JW) if mode == 8 else
                       (RING, JC, 8, 128) if mode in (9, 10, 12) else
                       # flattened ring: leading dim = ring row * JC2 +
                       # chunk, so the banded window is ONE pl.ds slice
                       (RING2 * JC2, 8, 128) if mode in (17, 18) else
                       (R + 1, 8, JW), jnp.int32),   # H (ring, 9/10/12)
            pltpu.VMEM((8, NW), jnp.int32),          # order
            pltpu.VMEM((8, NW), jnp.int32),          # base
            pltpu.VMEM((8, NW), jnp.float32),        # key
            pltpu.VMEM((8, NW), jnp.int32),          # in_cnt
            pltpu.VMEM((E, 8, NW), jnp.int32),       # in_src
            pltpu.VMEM((8, NW), jnp.int32),          # has_out
            pltpu.VMEM((GSLOTS, 8, 128), jnp.int32),  # gls (modes 9/10)
        ],
        interpret=interpret,
    )
    return jax.jit(lambda seed: call(seed))


def gate(R: int = 32, B: int = 1) -> bool:
    """The CI gate: measured in-loop counts of the compressed modes vs
    their baselines — serial trip counts for the step-compression pairs,
    scored DP cells for the banded pairs (the RACON_TPU_BAND acceptance
    floor: >= 3x fewer cells on BOTH hot kernels).  Runs in interpret
    mode off-TPU (counts, not wall time, are the measurement), prints
    every ratio, returns False if any floor is missed."""
    from racon_tpu.tools import force_cpu_if_requested
    force_cpu_if_requested()
    import jax

    interp = jax.devices()[0].platform != "tpu"
    seed = np.zeros((B, 1, 1), np.int32)

    def steps_of(mode):
        _, steps = build(mode, R, B, interp)(seed)
        jax.block_until_ready(steps)
        return int(np.asarray(steps)[0, 0, 0])

    checks = (("poa-v2 colstep", 1, 11, 1.5, "serial steps"),
              ("poa-ls rank-pair", 9, 12, 1.5, "serial steps"),
              ("align row-pack", 13, 14, 2.0, "serial steps"),
              ("align banded-band", 15, 16, 3.0, "in-loop cells"),
              ("poa banded-window", 17, 18, 3.0, "in-loop cells"))
    ok = True
    for name, base_m, new_m, floor, unit in checks:
        b, n = steps_of(base_m), steps_of(new_m)
        ratio = b / n if n else float("inf")
        good = ratio >= floor
        ok = ok and good
        print(f"{name}: baseline mode {base_m} = {b} {unit}, "
              f"compressed mode {new_m} = {n}, measured ratio "
              f"{ratio:.2f}x (floor {floor}x) "
              f"{'OK' if good else 'FAIL'}")
    return ok


def main():
    if "--gate" in sys.argv[1:]:
        sys.exit(0 if gate() else 1)
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 800
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
    # the masked-load modes index node state by rank: ranks beyond the
    # (8, NW) slot capacity silently resolve to node 0 and break the
    # seed-dependence check below
    assert R <= 8 * 256 - 1, f"R={R} exceeds the 2047 node-slot capacity"

    from racon_tpu.tools import force_cpu_if_requested
    force_cpu_if_requested()
    import jax

    platform = jax.devices()[0].platform
    interp = platform != "tpu"
    print(f"platform={platform} R={R} B={B}")
    prev = 0.0
    for mode in range(19):
        fn = build(mode, R, B, interp)
        seed = np.zeros((B, 1, 1), np.int32)
        t0 = time.time()
        out, steps = fn(seed)
        jax.block_until_ready(out)
        first = time.time() - t0
        # sanity: the result must move with the seed, else the kernel was
        # folded away and the timing is fiction
        o1 = int(np.asarray(out)[0, 0, 0])
        o2 = int(np.asarray(fn(seed + 7)[0])[0, 0, 0])
        st = int(np.asarray(steps)[0, 0, 0])
        best = None
        for i in range(reps):
            t0 = time.time()
            jax.block_until_ready(fn(seed + i + 1))
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        rows = R * B * (2 if mode == 8 else
                        8 if mode in (9, 10, 12, 17, 18) else 1)
        per_node_us = best / rows * 1e6
        folded = " [FOLDED? output ignores seed — timing is fiction]" \
            if o1 == o2 else ""
        print(f"mode={mode} first={first:.2f}s warm={best:.4f}s "
              f"per_node={per_node_us:.3f}us delta={per_node_us - prev:+.3f}"
              f"us steps={st} out(seed0)={o1} out(seed7)={o2}{folded}")
        prev = per_node_us


if __name__ == "__main__":
    main()
