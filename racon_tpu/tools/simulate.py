"""Synthetic long-read polishing workload generator.

Produces a (genome, draft, reads FASTQ, overlaps PAF) quadruple with an
ONT-like error profile so benchmarks and scale tests can run at arbitrary
genome sizes without external data. The draft is a substitution-mutated copy
of the genome (so PAF coordinates transfer 1:1), reads carry
substitution/insertion/deletion errors at configurable rates, and overlaps
are emitted from simulation truth.

Usage:
    python -m racon_tpu.tools.simulate -o OUTDIR --mbp 1.0 --coverage 30
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

BASES = np.frombuffer(b"ACGT", dtype=np.uint8)


_OP_CHARS = np.frombuffer(b"MDI", dtype=np.uint8)


def _cigar_from_ops(ops: np.ndarray, start: int, end: int):
    """RLE an op-code array (0=M, 1=D, 2=I) into a CIGAR string, clipping
    leading/trailing deletion runs (invalid in SAM) by moving the target
    coordinates inward. Returns (cigar, start, end)."""
    # clip boundary D runs
    lo = 0
    while lo < len(ops) and ops[lo] == 1:
        lo += 1
    hi = len(ops)
    while hi > lo and ops[hi - 1] == 1:
        hi -= 1
    start += lo
    end -= len(ops) - hi
    ops = ops[lo:hi]
    if not len(ops):
        return "", start, end
    bounds = np.nonzero(np.diff(ops))[0] + 1
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [len(ops)]])
    cigar = "".join(f"{e - s}{chr(_OP_CHARS[ops[s]])}"
                    for s, e in zip(starts, ends))
    return cigar, start, end


def _mutate_reads(genome: np.ndarray, rng, n_reads: int, mean_len: int,
                  sub: float, ins: float, dele: float):
    """Yield (start, end, strand, read_bytes, fwd_bytes, cigar) tuples.

    fwd_bytes is the read in target orientation (what a SAM record's SEQ
    column carries for a reverse-strand read), cigar the true alignment of
    fwd_bytes to the target — both from simulation ground truth.
    """
    g_len = len(genome)
    comp = np.zeros(256, dtype=np.uint8)
    for a, b in zip(b"ACGT", b"TGCA"):
        comp[a] = b
    for _ in range(n_reads):
        # floor at min(500, mean): the 500 floor suits long-read gammas;
        # short-read profiles (mean 150) would otherwise clamp every
        # read up to 500
        lo = min(500, int(mean_len))
        length = int(np.clip(rng.gamma(4.0, mean_len / 4.0), lo, 40000))
        length = min(length, g_len)
        start = int(rng.integers(0, g_len - length + 1))
        seg = genome[start:start + length]

        r = rng.random(length)
        # substitutions
        sub_mask = r < sub
        seg = seg.copy()
        seg[sub_mask] = BASES[rng.integers(0, 4, int(sub_mask.sum()))]
        # deletions
        keep = rng.random(length) >= dele
        seg = seg[keep]
        # insertions (after random positions)
        ins_mask = rng.random(len(seg)) < ins
        n_ins = int(ins_mask.sum())
        if n_ins:
            out = np.empty(len(seg) + n_ins, dtype=np.uint8)
            pos = np.nonzero(ins_mask)[0]
            out_idx = np.arange(len(seg)) + np.cumsum(ins_mask) - ins_mask
            out[out_idx] = seg
            ins_at = pos + np.arange(1, n_ins + 1)
            out[ins_at] = BASES[rng.integers(0, 4, n_ins)]
            seg = out

        # true op stream in target orientation: M/D per genome position,
        # with each I scattered after its (post-deletion) M
        ops_orig = np.where(keep, 0, 1).astype(np.uint8)
        ins_after = np.zeros(length, dtype=np.int64)
        if n_ins:
            ins_after[np.nonzero(keep)[0]] = ins_mask.astype(np.int64)
        shift = np.concatenate([[0], np.cumsum(ins_after)[:-1]])
        ops = np.full(length + int(ins_after.sum()), 2, dtype=np.uint8)
        ops[np.arange(length) + shift] = ops_orig
        cigar, cg_start, cg_end = _cigar_from_ops(ops, start, start + length)

        strand = bool(rng.integers(0, 2))
        fwd = seg
        if strand:
            seg = comp[seg][::-1]
        yield start, start + length, strand, seg, fwd, (cigar, cg_start,
                                                        cg_end)


def generate(outdir: str, mbp: float = 1.0, coverage: int = 30,
             mean_read: int = 8000, sub: float = 0.05, ins: float = 0.03,
             dele: float = 0.03, draft_error: float = 0.01,
             seed: int = 11, contigs: int = 1) -> dict:
    """`contigs` > 1 splits the genome into that many contiguous draft
    contigs (contig0..contigN-1, per-contig PAF/SAM coordinates, one @SQ
    line each) — the multi-contig shape the phase-pipelined polisher
    chunks on.  The default single-contig output is byte-identical to
    what this generator always produced (name 'contig', same rng
    stream)."""
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(seed)
    g_len = int(mbp * 1e6)

    genome = BASES[rng.integers(0, 4, g_len)]
    draft = genome.copy()
    derr = rng.random(g_len) < draft_error
    draft[derr] = BASES[rng.integers(0, 4, int(derr.sum()))]

    k = max(1, min(int(contigs), g_len))
    bounds = np.linspace(0, g_len, k + 1).astype(int)
    names = ["contig"] if k == 1 else [f"contig{ci}" for ci in range(k)]

    paths = {
        "genome": os.path.join(outdir, "genome.fasta"),
        "draft": os.path.join(outdir, "draft.fasta"),
        "reads": os.path.join(outdir, "reads.fastq"),
        "overlaps": os.path.join(outdir, "overlaps.paf"),
        "overlaps_sam": os.path.join(outdir, "overlaps.sam"),
    }

    with open(paths["genome"], "w") as f:
        f.write(">genome\n")
        f.write(genome.tobytes().decode())
        f.write("\n")
    with open(paths["draft"], "w") as f:
        for ci, name in enumerate(names):
            f.write(f">{name}\n")
            f.write(draft[bounds[ci]:bounds[ci + 1]].tobytes().decode())
            f.write("\n")

    qual_char = chr(33 + 15)
    with open(paths["reads"], "w") as rf, \
            open(paths["overlaps"], "w") as of, \
            open(paths["overlaps_sam"], "w") as sf:
        sf.write("@HD\tVN:1.6\tSO:unsorted\n")
        for ci, name in enumerate(names):
            sf.write(f"@SQ\tSN:{name}\tLN:{bounds[ci + 1] - bounds[ci]}\n")
        i = 0   # read numbering is global across contigs
        for ci, tname in enumerate(names):
            seg_genome = genome[bounds[ci]:bounds[ci + 1]]
            t_len = len(seg_genome)
            n_reads = max(1, int(t_len * coverage / mean_read))
            for start, end, strand, seg, fwd, cg in _mutate_reads(
                    seg_genome, rng, n_reads, mean_read, sub, ins, dele):
                name = f"read{i}"
                i += 1
                rf.write(f"@{name}\n{seg.tobytes().decode()}\n+\n"
                         f"{qual_char * len(seg)}\n")
                of.write(f"{name}\t{len(seg)}\t0\t{len(seg)}\t"
                         f"{'-' if strand else '+'}\t{tname}\t{t_len}\t"
                         f"{start}\t{end}\t{min(len(seg), end - start)}\t"
                         f"{max(len(seg), end - start)}\t60\n")
                # SAM record with the TRUE alignment (what minimap2 -a
                # would approximate): SEQ in target orientation,
                # ground-truth CIGAR
                cigar, cg_start, _cg_end = cg
                flag = 16 if strand else 0
                sf.write(f"{name}\t{flag}\t{tname}\t{cg_start + 1}\t60\t"
                         f"{cigar}\t*\t0\t0\t{fwd.tobytes().decode()}\t"
                         f"{qual_char * len(fwd)}\n")
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="racon-tpu-simulate",
                                description=__doc__.splitlines()[0])
    p.add_argument("-o", "--out-directory", required=True)
    p.add_argument("--mbp", type=float, default=1.0)
    p.add_argument("--coverage", type=int, default=30)
    p.add_argument("--mean-read", type=int, default=8000)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--contigs", type=int, default=1,
                   help="split the genome into this many draft contigs "
                        "(default 1; >1 enables phase-pipelined polishing)")
    args = p.parse_args(argv)
    paths = generate(args.out_directory, mbp=args.mbp,
                     coverage=args.coverage, mean_read=args.mean_read,
                     seed=args.seed, contigs=args.contigs)
    for k, v in paths.items():
        print(f"{k}: {v}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
