"""Workflow tooling around the polisher: sequence subsampling/splitting
(rampler-equivalent), the outer wrapper that chains them with polishing runs,
and paired-end read preprocessing. Capability parity with the reference's
scripts/ + vendored rampler (/root/reference/scripts/racon_wrapper.py,
racon_preprocess.py, vendor/rampler)."""
