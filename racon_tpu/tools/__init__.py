"""Workflow tooling around the polisher: sequence subsampling/splitting
(rampler-equivalent), the outer wrapper that chains them with polishing runs,
and paired-end read preprocessing. Capability parity with the reference's
scripts/ + vendored rampler (/root/reference/scripts/racon_wrapper.py,
racon_preprocess.py, vendor/rampler)."""


def force_cpu_if_requested() -> None:
    """Honor RACON_TPU_FORCE_CPU=1 before any jax backend initializes.

    The axon TPU plugin ignores the JAX_PLATFORMS env var and its backend
    init hangs indefinitely on a wedged tunnel; the config knob is what
    actually wins, and only if it runs before the first jax.devices().
    Measurement tools call this first so they can be pointed at the CPU
    backend while the tunnel is down.
    """
    from .. import config

    if config.get_bool("RACON_TPU_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
