"""The λ-phage golden scenario table, shared by tests/test_golden.py and
pin_device_golden.py so the pinned numbers and the tool that measures them
can never drift apart.

The reference pins accelerator accuracy next to the CPU numbers for every
scenario (/root/reference/test/racon_test.cpp:297-507: 6 polish scenarios
plus fragment-correction kC/kF, 10 GPU pins total); this table carries the
same inventory for the TPU path. HOST pins are asserted unconditionally in
CI; DEVICE pins are asserted on real hardware (RACON_TPU_HW_TESTS=1) and
measured/refreshed with:

    python racon_tpu/tools/pin_device_golden.py <scenario>|all

A device pin of None means "not yet measured on a healthy chip" — the
hardware test reports it as a skip, never a pass.
"""

# base polisher arguments every pin is measured (and asserted) under —
# scenario extra_args override these
ARGS = dict(window_length=500, quality_threshold=10.0, error_threshold=0.3,
            match=5, mismatch=-4, gap=-8, num_threads=1)

# polish scenarios -> (reads, overlaps, target, extra_args)
# edit distance of the revcomp'd single polished contig vs NC_001416
POLISH = {
    "paf": ("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
            "sample_layout.fasta.gz", {}),
    "sam": ("sample_reads.fastq.gz", "sample_overlaps.sam.gz",
            "sample_layout.fasta.gz", {}),
    "sam_noq": ("sample_reads.fasta.gz", "sample_overlaps.sam.gz",
                "sample_layout.fasta.gz", {}),
    "paf_noq": ("sample_reads.fasta.gz", "sample_overlaps.paf.gz",
                "sample_layout.fasta.gz", {}),
    "paf_w1000": ("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                  "sample_layout.fasta.gz", {"window_length": 1000}),
    "unit": ("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
             "sample_layout.fasta.gz",
             {"match": 1, "mismatch": -1, "gap": -1}),
}

# fragment-correction scenarios -> (reads, overlaps, target, extra_args)
# pinned as (record_count, total_corrected_bases)
FRAGMENT = {
    "kc": ("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
           "sample_reads.fastq.gz",
           {"match": 1, "mismatch": -1, "gap": -1}),
    "kf_fasta": ("sample_reads.fasta.gz", "sample_ava_overlaps.paf.gz",
                 "sample_reads.fasta.gz",
                 {"fragment_correction": True, "match": 1, "mismatch": -1,
                  "gap": -1, "drop": False}),
    "kf_paf": ("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
               "sample_reads.fastq.gz",
               {"fragment_correction": True, "match": 1, "mismatch": -1,
                "gap": -1, "drop": False}),
    "kf_mhap": ("sample_reads.fastq.gz", "sample_ava_overlaps.mhap.gz",
                "sample_reads.fastq.gz",
                {"fragment_correction": True, "match": 1, "mismatch": -1,
                 "gap": -1, "drop": False}),
}

# host path (CPU SPOA-parity engine) — asserted in tests/test_golden.py;
# reference CPU numbers in comments for comparison
HOST_POLISH = {
    "paf": 1283,        # reference: 1312
    "sam": 1315,        # reference: 1317
    "sam_noq": 1769,    # reference: 1770
    "paf_noq": 1443,    # reference: 1566
    "paf_w1000": 1304,  # reference: 1289
    "unit": 1338,       # reference: 1321
}
HOST_FRAGMENT = {
    "kc": (40, 401215),            # reference: 40 / 401246
    "kf_fasta": (236, 1662904),    # reference: 236 / 1663982 (GPU 1663732)
    "kf_paf": (236, 1657837),      # reference: 236 / 1658216
    # identical to kf_paf, as in the reference (its MHAP and PAF kF pins
    # are both 1658216, racon_test.cpp:252-258,288-294): the MHAP ordinal
    # transmutation resolves to the same overlaps bit-for-bit
    "kf_mhap": (236, 1657837),     # reference: 236 / 1658216
}

# device path (fused Pallas kernel on a real TPU chip) — refreshed by
# pin_device_golden.py during healthy-tunnel sessions. The reference's GPU
# pins differ from its CPU pins the same way (racon_test.cpp:316-318).
# Pins isolate the consensus device path: phase 1 runs on the HOST aligner
# (pin_device_golden.py pins RACON_TPU_DEVICE_ALIGNER=host; the paf=1282
# measurement predates the hirschberg-on-TPU default and was host-phase-1).
DEVICE_POLISH = {
    "paf": 1282,        # v5e, 2026-07-29: one edit from host's 1283
    "sam": None,
    "sam_noq": None,
    "paf_noq": None,
    "paf_w1000": None,
    "unit": None,
}
DEVICE_FRAGMENT = {
    "kc": None,
    "kf_fasta": None,
    "kf_paf": None,
    "kf_mhap": None,
}
