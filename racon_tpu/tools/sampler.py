"""Sequence subsampler / splitter — CLI-compatible with the vendored rampler
the reference wrapper shells out to (/root/reference/scripts/racon_wrapper.py:
63-64, 88-89; vendor pinned at CMakeLists.txt:114-130):

    racon-tpu-sampler [-o OUTDIR] subsample <sequences> <ref_length> <coverage>
    racon-tpu-sampler [-o OUTDIR] split <sequences> <chunk_size_bytes>

subsample writes <basename>_<coverage>x.<ext>; split writes
<basename>_<i>.<ext> — the exact names the wrapper looks for.
"""

from __future__ import annotations

import argparse
import gzip
import os
import random
import sys


def _open_any(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "rt")


def _fmt(path: str):
    base = path[:-3] if path.endswith(".gz") else path
    for ext in (".fasta", ".fa", ".fna"):
        if base.endswith(ext):
            return "fasta", ".fasta"
    for ext in (".fastq", ".fq"):
        if base.endswith(ext):
            return "fastq", ".fastq"
    print(f"[racon_tpu::sampler] error: unsupported extension in {path}",
          file=sys.stderr)
    sys.exit(1)


def _records(path: str):
    """Yield (header_lines...) record tuples as raw text blocks."""
    fmt, _ = _fmt(path)
    with _open_any(path) as f:
        if fmt == "fasta":
            name, chunks = None, []
            for line in f:
                line = line.rstrip("\n")
                if line.startswith(">"):
                    if name is not None:
                        yield name, "".join(chunks), None
                    name = line
                    chunks = []
                else:
                    chunks.append(line)
            if name is not None:
                yield name, "".join(chunks), None
        else:
            while True:
                header = f.readline().rstrip("\n")
                if not header:
                    return
                data = f.readline().rstrip("\n")
                f.readline()
                qual = f.readline().rstrip("\n")
                yield header, data, qual


def _write_record(out, rec, fmt):
    name, data, qual = rec
    if fmt == "fasta":
        out.write(f"{name}\n{data}\n")
    else:
        out.write(f"{name}\n{data}\n+\n{qual}\n")


def subsample_path(path: str, coverage: int, outdir: str) -> str:
    """Output naming contract shared with the wrapper's resume probing."""
    _, ext = _fmt(path)
    base_name = os.path.basename(path).split(".")[0]
    return os.path.join(outdir, f"{base_name}_{coverage}x{ext}")


def subsample(path: str, ref_length: int, coverage: int, outdir: str,
              seed: int = 42) -> str:
    """Random subsample of whole reads down to coverage * ref_length bases
    (the rampler contract). The output appears atomically (tmp + rename) so
    an interrupted run never leaves a truncated file for --resume to trust."""
    fmt, _ = _fmt(path)
    target_bases = ref_length * coverage

    records = list(_records(path))
    total = sum(len(r[1]) for r in records)
    rng = random.Random(seed)

    out_path = subsample_path(path, coverage, outdir)

    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as out:
        if total <= target_bases:
            for rec in records:
                _write_record(out, rec, fmt)
        else:
            order = list(range(len(records)))
            rng.shuffle(order)
            picked = 0
            chosen = []
            for i in order:
                if picked >= target_bases:
                    break
                chosen.append(i)
                picked += len(records[i][1])
            for i in sorted(chosen):
                _write_record(out, records[i], fmt)
    os.replace(tmp_path, out_path)
    return out_path


def split(path: str, chunk_size: int, outdir: str) -> list:
    """Split into chunks of ~chunk_size bytes of sequence data."""
    fmt, ext = _fmt(path)
    base_name = os.path.basename(path).split(".")[0]
    outputs = []
    out = None
    written = 0
    idx = 0
    for rec in _records(path):
        if out is None or (written >= chunk_size and written > 0):
            if out is not None:
                out.close()
            out_path = os.path.join(outdir, f"{base_name}_{idx}{ext}")
            outputs.append(out_path)
            out = open(out_path, "w")
            written = 0
            idx += 1
        _write_record(out, rec, fmt)
        written += len(rec[1])
    if out is not None:
        out.close()
    return outputs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="racon-tpu-sampler",
        description="sequence subsampler/splitter (rampler-equivalent)")
    p.add_argument("-o", "--out-directory", default=".",
                   help="output directory")
    sub = p.add_subparsers(dest="mode", required=True)
    ps = sub.add_parser("subsample")
    ps.add_argument("sequences")
    ps.add_argument("reference_length", type=int)
    ps.add_argument("coverage", type=int)
    pp = sub.add_parser("split")
    pp.add_argument("sequences")
    pp.add_argument("chunk_size", type=int)

    args = p.parse_args(argv)
    os.makedirs(args.out_directory, exist_ok=True)
    if args.mode == "subsample":
        subsample(args.sequences, args.reference_length, args.coverage,
                  args.out_directory)
    else:
        split(args.sequences, args.chunk_size, args.out_directory)
    return 0


if __name__ == "__main__":
    sys.exit(main())
