"""One-command, self-healing TPU hardware session: run the full
measurement priority list the moment the tunnel is healthy, every step
in a bounded subprocess, and emit a partial-session report no matter
how the tunnel behaves.

The axon tunnel wedges for hours and can die mid-session (round 2: it
wedged between the bench and the golden re-pin; VERDICT.md counts five
rounds lost to it), so the orchestrator assumes failure is the common
case:

* **step-level timeouts** — a step that hangs is killed (whole process
  group) and the session moves on;
* **exponential retry with backoff + jitter** — a step that *fails*
  (non-zero exit: the tunnel flapping, a transient XLA init error) is
  retried up to ``--retries`` times with ``--backoff * 2^k`` seconds
  (+0-25% jitter) between attempts.  Timeouts are NOT retried: the
  bound was already the generous estimate, and re-burning it on a
  wedged tunnel would cost the rest of the session;
* **per-step checkpoint files** — each completed step drops a JSON
  checkpoint under ``--state-dir``; re-running the session (default)
  skips checkpointed steps, so a crashed/killed session resumes where
  it stopped.  ``--fresh`` clears the state first;
* **no abort** — a failed probe no longer exits the session: later
  steps are recorded as ``skipped`` (with the reason) and the session
  still writes its report.  An unhealthy tunnel yields every step that
  did complete plus an honest account of the ones that could not;
* **partial-session report** — ``docs/hw_session_report.json`` lists
  every step's outcome (ok / failed / timeout / cached / skipped),
  attempts, and wall time; a summary line also lands in the durable
  ``docs/hw_session_log.jsonl`` evidence trail;
* **cost-model validation** — each step's obs trace is joined against
  the analytic cost model (``python -m racon_tpu.obs validate``) in a
  bounded subprocess before the trace is discarded, so every measured
  session doubles as a prediction-accuracy data point
  (``cost_model`` in the step entry).

Priorities (unchanged):

  1. probe        — device reachable + tiny matmul (2 min bound)
  2. bench        — python bench.py at the default 0.5 Mbp (45 min)
  3. bench_sam    — SAM input (no alignment phase): consensus ls tier
  4. bench_sam_v2 — same with RACON_TPU_POA_KERNEL=v2
  4a. bench_sam_flat / bench_sam_v2_flat — the same two tiers with
      RACON_TPU_POA_COLSTEP=0 (flat one-rank-per-step loops): the
      compressed-vs-current serial-step A/B on silicon
  4b. bench_sam_xla64 — vmapped XLA kernel at RACON_TPU_BATCH_WINDOWS=64
  4c. bench_sam_sr — short-read profile consensus bench
  5. bench5       — RACON_TPU_BENCH_MBP=5 scale run (90 min)
  6. pin_<scenario> — one bounded pin_device_golden.py run per scenario
  7. aligner      — RACON_TPU_DEVICE_ALIGNER=hirschberg bench
  8. aligner_host — RACON_TPU_DEVICE_ALIGNER=host bench
  9. jobs2        — wrapper --split --jobs 2 --tpu multi-process rehearsal
 10. factor4      — bench with RACON_TPU_NODE_FACTOR=4
 11. multichip    — 1/2/4/8-device scaling sweep + sharded dryrun gate
                    on the real backend (tools/multichip.py; rewrites
                    MULTICHIP_r06.json with the silicon curve)

Usage:
    python racon_tpu/tools/hw_session.py                # full session
    python racon_tpu/tools/hw_session.py bench pins     # a subset
    python racon_tpu/tools/hw_session.py --fresh        # ignore checkpoints
    python racon_tpu/tools/hw_session.py --retries 2 --backoff 30

This orchestrator stays dependency-free on purpose (no racon_tpu
imports): it must run, bound, retry, and report even when the package
itself is broken.  Configuration is therefore CLI flags, not RACON_TPU_*
knobs.
"""

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, HERE)
LOG = os.path.join(HERE, "docs", "hw_session_log.jsonl")
REPORT = os.path.join(HERE, "docs", "hw_session_report.json")
STATE_DIR = "/tmp/racon_tpu_hw_session_state"

PROBE = ("import jax, jax.numpy as jnp; "
         "x = jnp.ones((256, 256)); print(float((x @ x).sum())); "
         "print('devices:', jax.devices())")

STEPS = [
    ("probe", [sys.executable, "-c", PROBE], 120, {}),
    ("bench", [sys.executable, "bench.py"], 2700, {}),
    # banded-DP A/B on silicon: the default bench re-run with the
    # verify-and-widen banding armed — the measured delta against
    # `bench` is the band cell-cut's hardware evidence, and the logged
    # entry carries the cells_banded / band_hit_rate stamps
    # (checkpointed like every step: a wedge mid-pair resumes at the
    # missing half)
    ("bench_banded", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BAND": "1"}),
    # SAM input skips the alignment phase: kernel-vs-kernel consensus
    # comparison, ls tier then v2 — the decisive on-chip tier decision
    ("bench_sam", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam"}),
    ("bench_sam_v2", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_POA_KERNEL": "v2"}),
    # column-compression A/B on silicon: the same two tiers with the
    # compressed stepping disabled (one rank per serial iteration) —
    # the measured delta against bench_sam / bench_sam_v2 is the
    # serial-step cut's hardware evidence (each step checkpoints, so a
    # dropped tunnel resumes at the missing half of the pair)
    ("bench_sam_flat", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_POA_COLSTEP": "0"}),
    ("bench_sam_v2_flat", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_POA_KERNEL": "v2",
      "RACON_TPU_POA_COLSTEP": "0"}),
    # the third consensus tier: the vmapped XLA kernel at a wide batch —
    # the cost model's "decisive alternative" (if XLA lowers the graph
    # gathers well it is bandwidth-bound rather than latency-bound and
    # could beat both hand kernels; docs/benchmarks.md cost-model notes)
    ("bench_sam_xla64", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_PALLAS": "0",
      "RACON_TPU_BATCH_WINDOWS": "64"}),
    # short-read regime (BASELINE config 4's shape): 150 bp reads at ~1%
    # error — NGS windows, ~130 shallow layers/window vs ONT's ~30 long
    ("bench_sam_sr", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_BENCH_PROFILE": "sr"}),
    ("bench5", [sys.executable, "bench.py"], 5400,
     {"RACON_TPU_BENCH_MBP": "5"}),
    ("aligner", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_DEVICE_ALIGNER": "hirschberg"}),
    ("aligner_host", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_DEVICE_ALIGNER": "host"}),
    ("jobs2", [sys.executable, "-c", (
        "import sys, time, subprocess\n"
        "sys.path.insert(0, '.')\n"
        "import bench\n"
        "paths = bench.dataset()\n"
        "t0 = time.monotonic()\n"
        "r = subprocess.run([sys.executable, '-m',"
        " 'racon_tpu.tools.wrapper', paths['reads'], paths['overlaps'],"
        " paths['draft'], '--split', '200000', '--jobs', '2', '--tpu'],"
        " capture_output=True, text=True)\n"
        "dt = time.monotonic() - t0\n"
        "sys.stderr.write(r.stderr[-1500:])\n"
        "bp = sum(len(l.strip()) for l in r.stdout.splitlines()"
        " if not l.startswith('>'))\n"
        "print('jobs2 rc=%d bp=%d wall=%.1fs Mbp/s=%.4f'\n"
        "      % (r.returncode, bp, dt, bp / dt / 1e6))\n"
        "assert r.returncode == 0\n")], 3600, {}),
    ("factor4", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_NODE_FACTOR": "4"}),
    # device-count scaling sweep on the REAL backend (mesh widths 1/2/4/8
    # by under-subscription) + the sharded byte-identity dryrun gate;
    # overwrites the committed forced-CPU MULTICHIP_r06.json with the
    # silicon curve — the one number ROADMAP item 2's near-linear-scaling
    # criterion needs (checkpointed like every step: a wedge mid-sweep
    # resumes here next session)
    ("multichip", [sys.executable, "racon_tpu/tools/multichip.py",
                   "--real", "--out", "MULTICHIP_r06.json"], 3600, {}),
]


def _pin_steps():
    """One bounded step per golden scenario (a wedge mid-scenario must
    not cost the remaining pins); λ is small, so 600 s each is ample.

    golden_scenarios.py is loaded by file path: it has zero imports,
    while importing it as racon_tpu.tools.golden_scenarios would pull the
    whole package (native extension included) into the ORCHESTRATOR
    process, which must stay dependency-free so steps can run bounded
    even when the package itself is broken."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "golden_scenarios",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "golden_scenarios.py"))
    gs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gs)
    return [(f"pin_{name}",
             [sys.executable, "racon_tpu/tools/pin_device_golden.py",
              name], 600, {})
            for name in list(gs.POLISH) + list(gs.FRAGMENT)]


# pins run after the throughput benches, before the aligner measurement
_aligner_i = next(i for i, (n, *_) in enumerate(STEPS) if n == "aligner")
STEPS = STEPS[:_aligner_i] + _pin_steps() + STEPS[_aligner_i:]


def log_step(entry, log_path=LOG):
    entry = dict(entry, utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()))
    try:
        with open(log_path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError as e:
        print(f"[hw_session] WARNING: cannot append {log_path}: {e}",
              file=sys.stderr)


def _checkpoint_path(state_dir, name):
    return os.path.join(state_dir, f"{name}.json")


def _trace_phase_walls(path):
    """Compact per-phase wall-seconds from a step's Chrome-trace file
    (the ``phase.*`` complete events racon_tpu.obs emits).  Returns {}
    when the step wrote no trace or an unparsable one — folding the
    trace into the log entry is evidence enrichment, never a step
    failure."""
    try:
        with open(path) as f:
            doc = json.load(f)
        walls = {}
        for ev in doc.get("traceEvents", []):
            nm = ev.get("name", "")
            if ev.get("ph") == "X" and nm.startswith("phase."):
                walls[nm[6:]] = round(
                    walls.get(nm[6:], 0.0) + ev.get("dur", 0) / 1e6, 3)
        return walls
    except (OSError, ValueError, TypeError, AttributeError):
        return {}


def _trace_cost_validation(trace_path, cwd, timeout_s=120):
    """Predicted-vs-measured cost-model join for a step's trace, run
    through the obs CLI in a bounded subprocess (this orchestrator
    imports nothing from the package, and a broken package must not
    break the session).  Returns the validation dict with the CLI exit
    code attached, or None when the step wrote no trace or the CLI
    failed/hung — evidence enrichment, never a step failure."""
    if not os.path.exists(trace_path):
        return None
    try:
        r = subprocess.run(
            [sys.executable, "-m", "racon_tpu.obs", "validate", "--json",
             trace_path],
            cwd=cwd, capture_output=True, text=True, timeout=timeout_s)
        v = json.loads(r.stdout)
        if not isinstance(v, dict):
            return None
        v["exit_code"] = r.returncode
        return v
    except (subprocess.TimeoutExpired, subprocess.SubprocessError,
            ValueError, OSError):
        return None


def _strip_progress(text):
    """Collapse ``\\r``-overwritten progress-bar frames to their final
    state (keep only what follows the last carriage return on each
    line), so the bounded tail captures spend their byte budget on real
    output instead of a hundred redraws of the same bar."""
    return "\n".join(ln.rsplit("\r", 1)[-1]
                     for ln in (text or "").split("\n"))


def _attempt(name, cmd, bound_s, env, cwd):
    """One bounded attempt.  Returns (outcome, tail, report|None,
    phase_walls, cost_model|None) with outcome in
    {'ok', 'failed', 'timeout'}."""
    # every polish inside the step writes its resilience run report here
    # (last polish wins); read back into the durable log entry so a
    # silently degraded tier is visible in the evidence trail
    report_path = os.path.join("/tmp", f"racon_tpu_report_{name}_"
                               f"{os.getpid()}.json")
    # ...and its obs trace here (same last-polish-wins semantics): the
    # folded per-phase walls tell a wedged-align step apart from a
    # wedged-POA step without shipping the whole trace into the log
    trace_path = os.path.join("/tmp", f"racon_tpu_trace_{name}_"
                              f"{os.getpid()}.json")
    env = dict(env)
    env.setdefault("RACON_TPU_REPORT", report_path)
    env.setdefault("RACON_TPU_TRACE", trace_path)
    # start_new_session: a timeout must kill the step's WHOLE process
    # group — bench.py runs its own probe subprocesses, and an orphaned
    # probe wedged on the tunnel would hold the device and poison every
    # later step
    p = subprocess.Popen(cmd, cwd=cwd, env=env, text=True,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT,
                         start_new_session=True)
    try:
        out, _ = p.communicate(timeout=bound_s)
        outcome = "ok" if p.returncode == 0 else "failed"
        tail = _strip_progress(out)[-2000:]
    except subprocess.TimeoutExpired:
        outcome = "timeout"
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        # keep the partial output: 44 minutes of measured results before a
        # tunnel death ARE the evidence this tool exists to preserve
        out, _ = p.communicate()
        tail = (_strip_progress(out)[-2000:] + f"\nTIMEOUT after {bound_s}s")
    report = None
    try:
        with open(env["RACON_TPU_REPORT"]) as f:
            report = json.load(f)
        if env["RACON_TPU_REPORT"] == report_path:
            os.remove(report_path)
    except (OSError, ValueError):
        pass  # step ran no polish (probe/pins) or died before writing
    phase_walls = _trace_phase_walls(env["RACON_TPU_TRACE"])
    # cost-model validation rides the same trace before it is discarded:
    # every measured session doubles as a prediction-accuracy data point
    cost_model = _trace_cost_validation(env["RACON_TPU_TRACE"], cwd)
    if env["RACON_TPU_TRACE"] == trace_path:
        try:
            os.remove(trace_path)
        except OSError:
            pass
    return outcome, tail, report, phase_walls, cost_model


def run_step(name, cmd, bound_s, extra_env, retries=1, backoff_s=10.0,
             cwd=HERE):
    """Run one step with bounded attempts + exponential backoff.

    Failures (non-zero exit — a flapping tunnel, transient init errors)
    are retried; timeouts are not (the bound was already the generous
    estimate, and a wedged tunnel would burn it again).  Returns the
    step's log/report entry."""
    print(f"[hw_session] === {name} (bound {bound_s}s) ===", flush=True)
    env = dict(os.environ, **extra_env)
    # monotonic: elapsed/backoff accounting must not jump with NTP steps
    t0 = time.monotonic()
    attempts = 0
    outcome, tail, report, phase_walls, cost_model = \
        "failed", "", None, {}, None
    for k in range(retries + 1):
        attempts += 1
        outcome, tail, report, phase_walls, cost_model = _attempt(
            name, cmd, bound_s, env, cwd)
        if outcome != "failed" or k == retries:
            break
        # exponential backoff + jitter: give a flapping tunnel room to
        # settle without stampeding it the moment it comes back
        delay = backoff_s * (2 ** k) * (1.0 + 0.25 * random.random())
        print(f"[hw_session] {name}: attempt {attempts} failed; "
              f"retrying in {delay:.1f}s", flush=True)
        time.sleep(delay)
    dt = time.monotonic() - t0
    print(tail, flush=True)
    print(f"[hw_session] {name}: {outcome.upper()} in {dt:.0f}s "
          f"({attempts} attempt(s))", flush=True)
    entry = {"step": name, "ok": outcome == "ok", "outcome": outcome,
             "attempts": attempts, "wall_s": round(dt, 1),
             "env": extra_env, "tail": tail[-600:]}
    if report is not None:
        entry["report"] = report
    if phase_walls:
        entry["phase_wall"] = phase_walls
    if cost_model is not None:
        entry["cost_model"] = cost_model
    return entry


def resolve_wanted(names, steps=None):
    """Expand the 'pins' alias and validate step names."""
    steps = STEPS if steps is None else steps
    wanted = list(names) or [n for n, *_ in steps]
    if "pins" in wanted:  # convenience alias for all ten pin steps
        i = wanted.index("pins")
        wanted[i:i + 1] = [n for n, *_ in steps if n.startswith("pin_")]
    unknown = set(wanted) - {n for n, *_ in steps}
    if unknown:
        raise SystemExit(
            f"unknown steps {sorted(unknown)}; "
            f"available: {[n for n, *_ in steps]} (or 'pins')")
    return wanted


def run_session(wanted, steps=None, retries=1, backoff_s=10.0,
                state_dir=STATE_DIR, fresh=False, log_path=LOG,
                report_path=REPORT, cwd=HERE):
    """Run the wanted steps; self-heal around a flaky tunnel; always
    return (and write) a session report.

    Healing behavior: completed steps checkpoint into `state_dir` and are
    skipped (`cached`) on a re-run; failed steps retry with backoff; a
    failed/timed-out probe marks every remaining step `skipped` instead
    of aborting, so the report still accounts for the whole session."""
    steps = STEPS if steps is None else steps
    os.makedirs(state_dir, exist_ok=True)
    if fresh:
        for name, *_ in steps:
            try:
                os.remove(_checkpoint_path(state_dir, name))
            except OSError:
                pass
    t0 = time.monotonic()
    outcomes = []
    tunnel_dead = None   # reason string once the probe proves unhealthy
    for name, cmd, bound, extra_env in steps:
        if name not in wanted:
            continue
        ckpt = _checkpoint_path(state_dir, name)
        if os.path.exists(ckpt):
            try:
                with open(ckpt) as f:
                    prev = json.load(f)
            except (OSError, ValueError):
                prev = None
            if prev and prev.get("ok"):
                print(f"[hw_session] === {name}: cached "
                      f"(checkpoint {ckpt}) ===", flush=True)
                entry = {"step": name, "ok": True, "outcome": "cached",
                         "attempts": 0, "wall_s": 0.0, "env": extra_env,
                         "checkpoint": ckpt}
                outcomes.append(entry)
                log_step(entry, log_path)
                continue
        if tunnel_dead is not None:
            entry = {"step": name, "ok": False, "outcome": "skipped",
                     "attempts": 0, "wall_s": 0.0, "env": extra_env,
                     "reason": tunnel_dead}
            print(f"[hw_session] === {name}: skipped ({tunnel_dead}) ===",
                  flush=True)
            outcomes.append(entry)
            log_step(entry, log_path)
            continue
        entry = run_step(name, cmd, bound, extra_env, retries=retries,
                         backoff_s=backoff_s, cwd=cwd)
        outcomes.append(entry)
        log_step(entry, log_path)
        if entry["ok"]:
            try:
                with open(ckpt, "w") as f:
                    json.dump({"step": name, "ok": True,
                               "outcome": entry["outcome"],
                               "wall_s": entry["wall_s"]}, f)
            except OSError as e:
                print(f"[hw_session] WARNING: cannot checkpoint {ckpt}: "
                      f"{e}", file=sys.stderr)
        elif name == "probe":
            # the probe is the tunnel's health check: do NOT abort (the
            # old behavior — it threw away the session report), but do
            # stop feeding a dead tunnel steps that cannot succeed
            tunnel_dead = (f"tunnel unhealthy (probe {entry['outcome']} "
                           f"after {entry['attempts']} attempt(s))")
    counts = {}
    for e in outcomes:
        counts[e["outcome"]] = counts.get(e["outcome"], 0) + 1
    session = {
        "session": {
            "wall_s": round(time.monotonic() - t0, 1),
            "steps_wanted": len(wanted),
            "outcomes": counts,
            "tunnel_dead": tunnel_dead,
            "state_dir": state_dir,
        },
        "steps": [{k: v for k, v in e.items() if k != "tail"}
                  for e in outcomes],
    }
    try:
        with open(report_path, "w") as f:
            json.dump(session, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"[hw_session] report: {report_path}", flush=True)
    except OSError as e:
        print(f"[hw_session] WARNING: cannot write {report_path}: {e}",
              file=sys.stderr)
    log_step({"session_summary": session["session"]}, log_path)
    return session


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="hw_session.py",
        description="self-healing TPU hardware measurement session")
    p.add_argument("steps", nargs="*",
                   help="step names to run (default: all; 'pins' expands "
                        "to every pin_<scenario> step)")
    p.add_argument("--retries", type=int, default=1,
                   help="extra attempts per failed step (default 1; "
                        "timeouts are never retried)")
    p.add_argument("--backoff", type=float, default=10.0, metavar="S",
                   help="base backoff seconds between retries, doubled "
                        "per attempt with +0-25%% jitter (default 10)")
    p.add_argument("--state-dir", default=STATE_DIR,
                   help=f"per-step checkpoint directory (default "
                        f"{STATE_DIR}); completed steps are skipped on "
                        f"re-run")
    p.add_argument("--fresh", action="store_true",
                   help="clear checkpoints first: run every step again")
    p.add_argument("--report", default=REPORT, metavar="PATH",
                   help="session report path (default docs/"
                        "hw_session_report.json)")
    args = p.parse_args(argv)
    wanted = resolve_wanted(args.steps)
    session = run_session(wanted, retries=max(0, args.retries),
                          backoff_s=max(0.0, args.backoff),
                          state_dir=args.state_dir, fresh=args.fresh,
                          report_path=args.report)
    # exit 0 as long as the session produced evidence; 1 only when
    # nothing ran to completion at all
    ok_any = any(e["ok"] for e in session["steps"])
    return 0 if ok_any else 1


if __name__ == "__main__":
    sys.exit(main())
