"""One-command TPU hardware session: run the full measurement priority
list the moment the tunnel is healthy, every step in a bounded subprocess.

The axon tunnel wedges for hours and can die mid-session (round 2: it
wedged between the bench and the golden re-pin), so the priority order
front-loads the headline evidence and every step is independently
time-boxed and durably logged — a step that hangs is killed and the
session moves on. Priorities:

  1. probe        — device reachable + tiny matmul (2 min bound)
  2. bench        — python bench.py at the default 0.5 Mbp; bench.py
                    itself probes pallas tiers, warms geometries, appends
                    to docs/device_bench_log.jsonl, and re-pins the λ
                    golden (45 min)
  3. bench_sam    — SAM input (no alignment phase): isolates the
                    consensus kernel, ls tier (45 min)
  4. bench_sam_v2 — same with RACON_TPU_POA_KERNEL=v2: the on-chip
                    ls-vs-v2 tier decision (45 min)
  4b. bench_sam_xla64 — same through the vmapped XLA kernel at
                    RACON_TPU_BATCH_WINDOWS=64: the cost model's
                    bandwidth-bound alternative to both hand kernels
                    (45 min)
  4c. bench_sam_sr — consensus bench on the short-read profile
                    (150 bp @ ~1% error, BASELINE config-4 regime:
                    NGS windows, deep shallow layers) (45 min)
  5. bench5       — RACON_TPU_BENCH_MBP=5 scale run (90 min)
  6. pin_<scenario> — one bounded pin_device_golden.py run per golden
                    scenario (10 min each; 'pins' expands to all ten —
                    a wedge mid-scenario cannot cost the remaining pins)
  7. aligner      — explicit RACON_TPU_DEVICE_ALIGNER=hirschberg bench
                    at 0.5 Mbp (45 min). Note the default `bench` step
                    already serves phase 1 through hirschberg when its
                    bounded probe passes (align_driver default is `auto`);
                    this step forces it even past a failed probe.
  8. aligner_host — same bench with RACON_TPU_DEVICE_ALIGNER=host: the
                    other half of the phase-1 engine decision, same
                    dataset (45 min)
  9. jobs2        — wrapper --split --jobs 2 --tpu over the bench
                    dataset: the multi-host rehearsal (chunk × process
                    fan-out against one chip — the honest available
                    approximation of BASELINE config 5) (60 min)
 10. factor4      — bench with RACON_TPU_NODE_FACTOR=4: deep-window
                    node capacity (admits the 4 repeat-dense λ windows
                    the default rejects); its golden re-pin rides the
                    bench's opportunistic λ pin (45 min)

Usage:
    python racon_tpu/tools/hw_session.py           # all steps in order
    python racon_tpu/tools/hw_session.py bench pins  # a subset

Output: stdout + one JSON line per step appended to
docs/hw_session_log.jsonl (durable, committed — the evidence trail
survives a tunnel death mid-session).
"""

import json
import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, HERE)
LOG = os.path.join(HERE, "docs", "hw_session_log.jsonl")

PROBE = ("import jax, jax.numpy as jnp; "
         "x = jnp.ones((256, 256)); print(float((x @ x).sum())); "
         "print('devices:', jax.devices())")

STEPS = [
    ("probe", [sys.executable, "-c", PROBE], 120, {}),
    ("bench", [sys.executable, "bench.py"], 2700, {}),
    # SAM input skips the alignment phase: kernel-vs-kernel consensus
    # comparison, ls tier then v2 — the decisive on-chip tier decision
    ("bench_sam", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam"}),
    ("bench_sam_v2", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_POA_KERNEL": "v2"}),
    # the third consensus tier: the vmapped XLA kernel at a wide batch —
    # the cost model's "decisive alternative" (if XLA lowers the graph
    # gathers well it is bandwidth-bound rather than latency-bound and
    # could beat both hand kernels; docs/benchmarks.md cost-model notes)
    ("bench_sam_xla64", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_PALLAS": "0",
      "RACON_TPU_BATCH_WINDOWS": "64"}),
    # short-read regime (BASELINE config 4's shape): 150 bp reads at ~1%
    # error — NGS windows, ~130 shallow layers/window vs ONT's ~30 long
    ("bench_sam_sr", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_BENCH_INPUT": "sam", "RACON_TPU_BENCH_PROFILE": "sr"}),
    ("bench5", [sys.executable, "bench.py"], 5400,
     {"RACON_TPU_BENCH_MBP": "5"}),
    ("aligner", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_DEVICE_ALIGNER": "hirschberg"}),
    ("aligner_host", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_DEVICE_ALIGNER": "host"}),
    ("jobs2", [sys.executable, "-c", (
        "import sys, time, subprocess\n"
        "sys.path.insert(0, '.')\n"
        "import bench\n"
        "paths = bench.dataset()\n"
        "t0 = time.time()\n"
        "r = subprocess.run([sys.executable, '-m',"
        " 'racon_tpu.tools.wrapper', paths['reads'], paths['overlaps'],"
        " paths['draft'], '--split', '200000', '--jobs', '2', '--tpu'],"
        " capture_output=True, text=True)\n"
        "dt = time.time() - t0\n"
        "sys.stderr.write(r.stderr[-1500:])\n"
        "bp = sum(len(l.strip()) for l in r.stdout.splitlines()"
        " if not l.startswith('>'))\n"
        "print('jobs2 rc=%d bp=%d wall=%.1fs Mbp/s=%.4f'\n"
        "      % (r.returncode, bp, dt, bp / dt / 1e6))\n"
        "assert r.returncode == 0\n")], 3600, {}),
    ("factor4", [sys.executable, "bench.py"], 2700,
     {"RACON_TPU_NODE_FACTOR": "4"}),
]


def _pin_steps():
    """One bounded step per golden scenario (a wedge mid-scenario must
    not cost the remaining pins); λ is small, so 600 s each is ample.

    golden_scenarios.py is loaded by file path: it has zero imports,
    while importing it as racon_tpu.tools.golden_scenarios would pull the
    whole package (native extension included) into the ORCHESTRATOR
    process, which must stay dependency-free so steps can run bounded
    even when the package itself is broken."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "golden_scenarios",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "golden_scenarios.py"))
    gs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gs)
    return [(f"pin_{name}",
             [sys.executable, "racon_tpu/tools/pin_device_golden.py",
              name], 600, {})
            for name in list(gs.POLISH) + list(gs.FRAGMENT)]


# pins run after the throughput benches, before the aligner measurement
_aligner_i = next(i for i, (n, *_) in enumerate(STEPS) if n == "aligner")
STEPS = STEPS[:_aligner_i] + _pin_steps() + STEPS[_aligner_i:]


def log_step(entry):
    entry = dict(entry, utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()))
    try:
        with open(LOG, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError as e:
        print(f"[hw_session] WARNING: cannot append {LOG}: {e}",
              file=sys.stderr)


def run_step(name, cmd, bound_s, extra_env):
    print(f"[hw_session] === {name} (bound {bound_s}s) ===", flush=True)
    env = dict(os.environ, **extra_env)
    # every polish inside the step writes its resilience run report here
    # (last polish wins); read back into the durable log entry so a
    # silently degraded tier is visible in the evidence trail
    report_path = os.path.join("/tmp", f"racon_tpu_report_{name}_"
                               f"{os.getpid()}.json")
    env.setdefault("RACON_TPU_REPORT", report_path)
    t0 = time.time()
    # start_new_session: a timeout must kill the step's WHOLE process
    # group — bench.py runs its own probe subprocesses, and an orphaned
    # probe wedged on the tunnel would hold the device and poison every
    # later step
    p = subprocess.Popen(cmd, cwd=HERE, env=env, text=True,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT,
                         start_new_session=True)
    try:
        out, _ = p.communicate(timeout=bound_s)
        ok = p.returncode == 0
        tail = (out or "")[-2000:]
    except subprocess.TimeoutExpired:
        ok = False
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        # keep the partial output: 44 minutes of measured results before a
        # tunnel death ARE the evidence this tool exists to preserve
        out, _ = p.communicate()
        tail = ((out or "")[-2000:] + f"\nTIMEOUT after {bound_s}s")
    dt = time.time() - t0
    print(tail, flush=True)
    print(f"[hw_session] {name}: {'OK' if ok else 'FAILED'} in {dt:.0f}s",
          flush=True)
    entry = {"step": name, "ok": ok, "wall_s": round(dt, 1),
             "env": extra_env, "tail": tail[-600:]}
    try:
        with open(env["RACON_TPU_REPORT"]) as f:
            entry["report"] = json.load(f)
        if env["RACON_TPU_REPORT"] == report_path:
            os.remove(report_path)
    except (OSError, ValueError):
        pass  # step ran no polish (probe/pins) or died before writing
    log_step(entry)
    return ok


def main():
    wanted = sys.argv[1:] or [n for n, *_ in STEPS]
    if "pins" in wanted:  # convenience alias for all ten pin steps
        i = wanted.index("pins")
        wanted[i:i + 1] = [n for n, *_ in STEPS if n.startswith("pin_")]
    unknown = set(wanted) - {n for n, *_ in STEPS}
    if unknown:
        sys.exit(f"unknown steps {sorted(unknown)}; "
                 f"available: {[n for n, *_ in STEPS]} (or 'pins')")
    for name, cmd, bound, env in STEPS:
        if name not in wanted:
            continue
        ok = run_step(name, cmd, bound, env)
        if name == "probe" and not ok:
            sys.exit("[hw_session] tunnel not healthy; aborting (nothing "
                     "else can succeed)")


if __name__ == "__main__":
    main()
