"""Paired-end read preprocessing: rename Illumina read pairs to unique names
(suffix 1/2) so the polisher can treat them single-end.

Capability parity with /root/reference/scripts/racon_preprocess.py (same
suffix scheme, FASTQ validation, one or two input files); also accepts
gzipped input, which the reference script does not.
"""

from __future__ import annotations

import argparse
import gzip
import sys


def _open_any(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "rt")


def parse_file(path: str, read_set: set, out) -> None:
    def emit(name, data, qual):
        if len(name) == 0 or len(data) == 0 or len(data) != len(qual):
            print("File is not in FASTQ format", file=sys.stderr)
            sys.exit(1)
        if name in read_set:
            out.write(f"{name}2\n")
        else:
            read_set.add(name)
            out.write(f"{name}1\n")
        out.write(f"{data}\n+\n{qual}\n")

    line_id = 0
    name, data, qual = "", "", ""
    valid = False
    with _open_any(path) as f:
        for line in f:
            if line_id == 0:
                if valid:
                    emit(name, data, qual)
                    valid = False
                name = line.rstrip().split(" ")[0]
                data = ""
                qual = ""
                line_id = 1
            elif line_id == 1:
                if line[0] == "+":
                    line_id = 2
                else:
                    data += line.rstrip()
            else:
                qual += line.rstrip()
                if len(qual) >= len(data):
                    valid = True
                    line_id = 0
    if valid:
        emit(name, data, qual)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="racon-tpu-preprocess",
        description="rename Illumina paired-end reads to unique names")
    p.add_argument("first", help="file with the first read of a pair or both")
    p.add_argument("second", nargs="?",
                   help="optional file with the second reads of the pairs")
    args = p.parse_args(argv)

    read_set = set()
    parse_file(args.first, read_set, sys.stdout)
    if args.second is not None:
        parse_file(args.second, read_set, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
