"""Micro-benchmark the fused Pallas POA kernel at production geometry on
the current JAX backend (meant for the real TPU; refuses nothing, but
prints the platform so a CPU number can't masquerade as a chip number).

Synthesizes ONT-like windows: 500 bp backbone, `depth` layers at ~11%
error (mix of substitutions/insertions/deletions), which grows the graph
the way real data does — unlike a substitution-only batch, which never
allocates insertion columns.

Usage: python racon_tpu/tools/kernel_bench.py [batch] [depth] [iters]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np


def make_batch(cfg, B, rng, err=0.11):
    bb = np.zeros((B, cfg.max_backbone), dtype=np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), dtype=np.int32)
    bb_len = np.zeros(B, dtype=np.int32)
    n_layers = np.zeros(B, dtype=np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.int32)
    lens = np.zeros((B, cfg.depth), dtype=np.int32)
    begins = np.zeros((B, cfg.depth), dtype=np.int32)
    ends = np.zeros((B, cfg.depth), dtype=np.int32)

    W = 500
    for b in range(B):
        truth = rng.integers(0, 4, W).astype(np.uint8)
        draft = mutate(truth, err, rng)[:min(cfg.max_backbone, W)]
        bb[b, :len(draft)] = draft
        bb_len[b] = len(draft)
        n_layers[b] = cfg.depth
        for li in range(cfg.depth):
            layer = mutate(truth, err, rng)[:cfg.max_len]
            seqs[b, li, :len(layer)] = layer
            ws[b, li, :len(layer)] = rng.integers(1, 30, len(layer))
            lens[b, li] = len(layer)
            begins[b, li] = 0
            ends[b, li] = len(draft) - 1
    return (bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends)


def mutate(seq, rate, rng):
    r = rng.random(len(seq))
    out = []
    for i, c in enumerate(seq):
        if r[i] < rate / 3:
            out.append(rng.integers(0, 4))          # substitution
        elif r[i] < 2 * rate / 3:
            pass                                    # deletion
        elif r[i] < rate:
            out.append(c)
            out.append(rng.integers(0, 4))          # insertion
        else:
            out.append(c)
    return np.array(out, dtype=np.uint8)


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    depth = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 3

    from racon_tpu.tools import force_cpu_if_requested
    force_cpu_if_requested()
    import jax

    from racon_tpu.ops import poa_driver, poa_pallas

    platform = jax.devices()[0].platform
    cfg = poa_driver.make_config(500, depth, 5, -4, -8)
    interp = platform != "tpu"
    fn = poa_pallas.build_pallas_poa_kernel(cfg, interpret=interp)(B)

    rng = np.random.default_rng(0)
    bb, bbw, bl, nl, seqs, ws, lens, bg, en = make_batch(cfg, B, rng)
    args = (bl.reshape(-1, 1), nl.reshape(-1, 1), lens, bg, en,
            bb.astype(np.int32), bbw, seqs.astype(np.int32), ws)

    t0 = time.time()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_and_first = time.time() - t0
    failed = int(np.asarray(out[3]).sum())
    nmax = int(np.asarray(out[4]).max())

    times = []
    for _ in range(iters):
        t0 = time.time()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.time() - t0)
    best = min(times)
    print(f"platform={platform} B={B} depth={depth} "
          f"first={compile_and_first:.2f}s warm={best:.3f}s "
          f"per_window={best / B * 1e3:.2f}ms failed={failed} "
          f"max_nodes_used={nmax}")


if __name__ == "__main__":
    main()
