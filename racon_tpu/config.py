"""Central registry of every ``RACON_TPU_*`` environment knob.

Every environment variable the runtime, tools, benchmarks, or tests read
is declared here — name, default, type, and a docstring — and read
through the typed accessors below.  This file is the ground truth for:

* the ``env-registry`` lint rule (``racon_tpu/analysis``): any
  ``os.environ`` / ``os.getenv`` read of a ``RACON_TPU_*`` name outside
  this module is a violation, so a knob cannot be introduced without a
  registered name and documentation;
* the ``knob-docs`` lint rule: every registered knob must appear in
  README.md's configuration table;
* the run report's stale-knob check (``unknown_env_knobs``): variables
  set in the environment with the ``RACON_TPU_`` prefix but unknown to
  this registry are surfaced in ``Polisher.report`` instead of being
  silently ignored — a typo'd knob is visible, not a no-op.

Only the stdlib is imported so this module is importable from anywhere
(including ``racon_tpu/__init__`` before jax initializes).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional

PREFIX = "RACON_TPU_"


@dataclass(frozen=True)
class Knob:
    """One registered environment knob."""

    name: str          # full variable name, RACON_TPU_… prefix included
    default: Optional[str]  # raw default ('' / None = unset semantics)
    kind: str          # 'str' | 'int' | 'float' | 'bool' — documentation
    doc: str           # one-line effect description (README table text)
    scope: str = "runtime"   # 'runtime' | 'tools' | 'bench' | 'test'
    #: The byte-identity contract, per knob: False declares the knob
    #: *cost-only* — it may change tiers, batching, timing, or memory,
    #: never output bytes — and the determinism taint auditor
    #: (racon_tpu/analysis/determinism, Engine 5) statically rejects
    #: any dataflow path from its read sites into the consensus/CIGAR
    #: install seams (`determinism-leak`).  True declares it
    #: output-affecting: a runtime-scoped True knob must then be
    #: covered by every complete fingerprint composition in
    #: racon_tpu/fingerprint.py (`fingerprint-gap` otherwise).
    affects_output: bool = False


def _k(name: str, default: Optional[str], kind: str, doc: str,
       scope: str = "runtime", affects_output: bool = False) -> Knob:
    assert name.startswith(PREFIX), name
    return Knob(name, default, kind, doc, scope, affects_output)


#: The registry.  Order matters only for documentation output.
KNOBS: Dict[str, Knob] = {k.name: k for k in (
    # -- device-path (production) knobs -----------------------------------
    _k("RACON_TPU_PALLAS", None, "bool",
       "fused Pallas kernels vs the XLA twin (default: 1 on TPU, 0 "
       "elsewhere)"),
    _k("RACON_TPU_POA_KERNEL", "ls", "str",
       "consensus kernel tier: 'ls' (lane-lockstep) or 'v2' (one "
       "window/program)"),
    _k("RACON_TPU_DEVICE_ALIGNER", "auto", "str",
       "phase-1 aligner: auto | hirschberg | 1/xla | 0/host"),
    _k("RACON_TPU_POA_COLSTEP", "1", "bool",
       "column-compressed POA DP stepping: same-column siblings (v2) / "
       "rank pairs (ls) share one serial loop iteration (0 restores the "
       "one-rank-per-step loop; output is byte-identical either way)"),
    _k("RACON_TPU_ALIGN_PACK", "1", "bool",
       "packed Hirschberg DP: 4 query bases per word, 4 DP rows per "
       "serial loop iteration (0 restores one-row-per-step kernels; "
       "output is byte-identical either way)"),
    _k("RACON_TPU_BAND", "0", "bool",
       "banded DP on the hot kernels: Ukkonen-banded Hirschberg "
       "alignment + diagonal-banded POA with verify-and-widen "
       "re-dispatch, falling back to the flat kernels on band-hit "
       "exhaustion (output is byte-identical either way)"),
    _k("RACON_TPU_BAND_SLACK", "32", "int",
       "banded DP initial half-band slack: first band width is the "
       "query/target length delta plus this many diagonals before "
       "bucketing"),
    _k("RACON_TPU_BAND_MAX_WIDENINGS", "2", "int",
       "banded DP widening budget: band-hit jobs double their band this "
       "many times before taking the banded->flat lattice edge"),
    _k("RACON_TPU_BATCH_WINDOWS", None, "int",
       "windows per device batch (default: 64 on TPU, 4 elsewhere)"),
    _k("RACON_TPU_PIPELINE_DEPTH", "2", "int",
       "in-flight device chunks (host packs ahead of execution)"),
    _k("RACON_TPU_PIPELINE_PHASES", None, "bool",
       "overlap alignment and consensus across target chunks: POA for "
       "early contigs starts while late alignment cohorts are in flight "
       "(multi-contig FASTA targets; output stays byte-identical)"),
    _k("RACON_TPU_HANDOFF_DEPTH", "1", "int",
       "phase-pipeline handoff queue depth: aligned target chunks the "
       "worker may buffer ahead of consensus"),
    _k("RACON_TPU_NODE_FACTOR", "3", "int",
       "POA graph node capacity = factor x window length"),
    _k("RACON_TPU_ALIGN_COHORT", None, "int",
       "phase-1 jobs materialized per device cohort (default 64)"),
    _k("RACON_TPU_COMPILE_CACHE", None, "str",
       "persistent XLA compilation cache directory (default: uid-keyed "
       "~/.cache path)"),
    _k("RACON_TPU_SHARD", "1", "bool",
       "shard kernel batches over the device mesh (0 forces "
       "single-device dispatch; output is byte-identical either way)"),
    _k("RACON_TPU_MESH_SHAPE", None, "str",
       "device mesh as 'data[,model]' (e.g. '8' or '4,2'; default: all "
       "devices on the data axis)"),
    _k("RACON_TPU_SHARD_MIN_BATCH", "0", "int",
       "smallest batch worth sharding (0 = one row per mesh shard); "
       "smaller batches dispatch single-device without padding"),
    _k("RACON_TPU_FORCE_CPU", None, "bool",
       "force the virtual-CPU backend before jax initializes (tools)",
       scope="tools"),
    # -- resilience knobs -------------------------------------------------
    _k("RACON_TPU_TIER_RETRIES", "1", "int",
       "extra attempts per kernel tier before bisecting/demoting"),
    _k("RACON_TPU_DEVICE_TIMEOUT", "0", "float",
       "per-device-call watchdog in seconds (0 = off)"),
    _k("RACON_TPU_FAULT", None, "str",
       "deterministic fault injection spec (see resilience/faults.py)"),
    _k("RACON_TPU_REPORT", None, "str",
       "write the JSON run report to this path after every polish"),
    _k("RACON_TPU_WEDGE_LIMIT", "3", "int",
       "consecutive watchdog timeouts before a tier is declared wedged "
       "and demoted without retry (0 = off)"),
    _k("RACON_TPU_JOURNAL", None, "str",
       "crash-safe window journal path; auto-resumes when the input "
       "fingerprint matches (fresh otherwise)"),
    _k("RACON_TPU_JOURNAL_FSYNC", "1", "bool",
       "fsync the journal after every record (0 trades durability for "
       "speed: a crash may lose buffered records)"),
    _k("RACON_TPU_SANITIZE", None, "bool",
       "runtime sanitizer: finite/in-range device-output checks, "
       "sampled host-vs-device parity, guarded driver stats "
       "(diagnostic mode; output stays byte-identical)"),
    _k("RACON_TPU_SANITIZE_PARITY", "8", "int",
       "sanitize mode: host-recompute and byte-compare every Nth "
       "device-served window (0 disables the parity probe)"),
    # -- memory-budget knobs (resilience/budget.py) -----------------------
    _k("RACON_TPU_MEM_BUDGET_MB", "0", "int",
       "peak-RSS budget in MiB: arms the memory watchdog, enables the "
       "streaming input path, and drives the soft/hard watermark "
       "degradations (0 = unbudgeted)"),
    _k("RACON_TPU_MEM_SOFT_FRAC", "0.8", "float",
       "soft watermark as a fraction of the memory budget: above it "
       "backpressure applies (handoff depth shrinks, queued working "
       "sets spill to disk)"),
    _k("RACON_TPU_MEM_HARD_FRAC", "0.95", "float",
       "hard watermark as a fraction of the memory budget: above it the "
       "pressure lattice edges fire (pipelined->sequential, "
       "batched->stream-sequential) and the flight recorder dumps"),
    _k("RACON_TPU_MEM_SPILL_DIR", None, "str",
       "directory for parked chunk working sets under memory pressure "
       "(default: a per-run temp directory)"),
    _k("RACON_TPU_MEM_POLL_MS", "200", "int",
       "memory watchdog sampling interval in milliseconds"),
    _k("RACON_TPU_STREAM_INPUT", None, "bool",
       "stream per-chunk read/overlap working sets instead of handing "
       "the full files to every chunk pipeline (auto-enabled when a "
       "memory budget is set; output is byte-identical either way)"),
    # -- observability knobs ----------------------------------------------
    _k("RACON_TPU_TRACE", None, "str",
       "write a Chrome-trace/Perfetto JSON span timeline of every polish "
       "to this path (CLI --trace overrides; see racon_tpu/obs)"),
    _k("RACON_TPU_METRICS", None, "bool",
       "collect the in-process metrics registry (per-tier counters + "
       "histograms) and embed a snapshot in the run report even without "
       "a trace file"),
    _k("RACON_TPU_TRACE_DEVICE", None, "bool",
       "with tracing armed on a real TPU backend, also capture a "
       "jax.profiler device trace next to the trace file"),
    _k("RACON_TPU_COST_MODEL", "1", "bool",
       "stamp analytic cost predictions into kernel.build spans and "
       "bench entries (obs/costmodel.py; 0 disables)"),
    _k("RACON_TPU_MACHINE_PROFILE", "auto", "str",
       "machine profile for cost-model predictions: auto | cpu-host | "
       "tpu-v4-lite (auto picks by backend platform)"),
    _k("RACON_TPU_FLIGHT", "1", "bool",
       "always-on crash flight recorder: ring of the last N spans/events "
       "per process, dumped to the job dir on faults, TierDead, worker "
       "crash, or SIGTERM (0 disables; see obs/flight.py)"),
    _k("RACON_TPU_FLIGHT_EVENTS", "256", "int",
       "flight-recorder ring capacity: most-recent events kept per "
       "process for the post-mortem dump"),
    _k("RACON_TPU_OBS_SHIP_EVENTS", "1500", "int",
       "span-shipping cap: trace events a distrib worker / serve job "
       "returns with each result for the merged fleet timeline (bounded "
       "so shipments fit the wire's line limit)"),
    _k("RACON_TPU_TELEMETRY_RING", "64", "int",
       "live-telemetry ring capacity: periodic metrics snapshots kept "
       "per process, scraped through the serve/distrib 'stats' verb"),
    # -- SLO / exposition knobs (obs/slo.py, obs/export.py) ---------------
    _k("RACON_TPU_SLO_LATENCY_S", None, "str",
       "per-tenant job-latency SLO targets in seconds: a bare float is "
       "the default target, key=value pairs set per-tenant targets "
       "(e.g. 'default=2.5,tenant-a=1.0'); unset = no latency objective"),
    _k("RACON_TPU_SLO_AVAILABILITY", "0.99", "float",
       "SLO availability objective: the fraction of jobs that must "
       "finish inside their latency target (error budget = 1 - this)"),
    _k("RACON_TPU_SLO_FAST_WINDOW_S", "60", "float",
       "fast burn-rate window in seconds (the reactive half of the "
       "multi-window alert)"),
    _k("RACON_TPU_SLO_SLOW_WINDOW_S", "600", "float",
       "slow burn-rate window in seconds (the confirming half of the "
       "multi-window alert)"),
    _k("RACON_TPU_SLO_BURN_ALERT", "2.0", "float",
       "burn-rate alert threshold: both windows burning past it fires "
       "the slo.alert event and drives the fleet autoscaler (0 disables "
       "SLO alerting)"),
    _k("RACON_TPU_SLO_SHED_BURN", "0", "float",
       "burn-rate shedding threshold: new submissions shed (counted "
       "shed_slo) while both windows burn past it (0 = never shed on "
       "SLO burn)"),
    _k("RACON_TPU_METRICS_PORT", "0", "int",
       "Prometheus exposition HTTP port on the serve daemon (GET "
       "/metrics, localhost only; 0 = disabled, the `metrics` wire op "
       "still serves the same text)"),
    # -- serving knobs ----------------------------------------------------
    _k("RACON_TPU_SERVE_PORT", "0", "int",
       "TCP port for the `racon-tpu serve` daemon (0 = pick a free "
       "ephemeral port, recorded in <state-dir>/serve.json)"),
    _k("RACON_TPU_SERVE_QUEUE_DEPTH", "16", "int",
       "serve admission control: queued (not yet running) jobs beyond "
       "which new submissions are rejected"),
    _k("RACON_TPU_SERVE_MAX_JOBS", "64", "int",
       "serve admission control: total unfinished (queued + running) "
       "jobs the daemon will track at once"),
    _k("RACON_TPU_SERVE_WARMUP", "1", "bool",
       "pre-compile the consensus kernel geometries once at serve "
       "startup so the first job pays no kernel builds (0 disables)"),
    _k("RACON_TPU_SERVE_WINDOW_BUDGET", "0", "int",
       "serve per-job window budget: jobs whose estimated window count "
       "exceeds it are demoted to the host lane instead of occupying "
       "the device queue (0 = unlimited)"),
    # -- distributed-fleet knobs ------------------------------------------
    _k("RACON_TPU_DISTRIB_WORKERS", "2", "int",
       "`racon-tpu distrib` fleet size: chunk-worker processes the "
       "coordinator spawns (CLI --workers overrides)"),
    _k("RACON_TPU_DISTRIB_LEASE_TTL", "10", "float",
       "distrib chunk-lease TTL in seconds: a lease not renewed by a "
       "heartbeat within the TTL expires and the chunk is re-dispatched"),
    _k("RACON_TPU_DISTRIB_HEARTBEAT", None, "float",
       "distrib worker heartbeat interval in seconds (default: lease "
       "TTL / 3)"),
    _k("RACON_TPU_DISTRIB_RETRY_BASE", "0.25", "float",
       "distrib retry backoff base in seconds: attempt N of a chunk "
       "waits base * 2^(N-1) before becoming eligible again"),
    _k("RACON_TPU_DISTRIB_MAX_RETRIES", "3", "int",
       "distrib per-chunk failure budget: a chunk failing more than "
       "this many times falls back to local (in-coordinator) execution"),
    _k("RACON_TPU_DISTRIB_SPECULATE", "2.5", "float",
       "distrib straggler threshold: a running chunk whose elapsed time "
       "exceeds this factor x the median completed-chunk wall gets a "
       "speculative duplicate on an idle worker (0 disables)"),
    _k("RACON_TPU_DISTRIB_FAULT_WORKER", "0", "int",
       "distrib fault scoping: the worker index that inherits "
       "RACON_TPU_FAULT (other workers get it stripped), so chaos tests "
       "kill exactly one worker", scope="test"),
    # -- elastic fleet knobs (racon_tpu/fleet) ----------------------------
    _k("RACON_TPU_FLEET_MIN_WORKERS", "1", "int",
       "elastic fleet floor: worker processes the autoscaling pool "
       "keeps alive even when idle"),
    _k("RACON_TPU_FLEET_MAX_WORKERS", "0", "int",
       "elastic fleet ceiling: worker processes the pool may grow to "
       "under load; in the serve daemon 0 disables the fleet plane "
       "(jobs run in-process as before)"),
    _k("RACON_TPU_FLEET_SCALE_P95_MS", "250", "float",
       "autoscaler trigger: grow the pool when the recent chunk "
       "queueing p95 exceeds this many milliseconds with a backlog "
       "pending"),
    _k("RACON_TPU_FLEET_STEAL", "1", "bool",
       "fleet work stealing: an idle worker whose affinity job has no "
       "eligible chunks takes a chunk from another job (0 pins workers "
       "to their job until it finishes)"),
    _k("RACON_TPU_FLEET_TENANT_QUOTA", "0", "int",
       "per-tenant admission quota: unfinished jobs one submitter may "
       "hold in the scheduler/fleet plane at once (0 = unlimited)"),
    # -- test / bench knobs ----------------------------------------------
    _k("RACON_TPU_HW_TESTS", None, "bool",
       "assert exact on-hardware pins against a real TPU backend",
       scope="test"),
    _k("RACON_TPU_FULL_GOLDEN", None, "bool",
       "run the slow golden scenarios", scope="test"),
    _k("RACON_TPU_TEST_DATA", "/root/reference/test/data/", "str",
       "directory holding the lambda-phage fixture data", scope="test",
       affects_output=True),
    _k("RACON_TPU_BENCH_MBP", "0.5", "float",
       "benchmark workload size in polished megabases", scope="bench",
       affects_output=True),
    _k("RACON_TPU_BENCH_INPUT", "paf", "str",
       "benchmark overlap format: paf | sam", scope="bench",
       affects_output=True),
    _k("RACON_TPU_BENCH_PROFILE", "ont", "str",
       "benchmark read profile: ont | sr", scope="bench",
       affects_output=True),
    _k("RACON_TPU_BENCH_LOG", None, "str",
       "append one bench JSON line per run to this file", scope="bench"),
    _k("RACON_TPU_BENCH_FORCE_DEVICE", None, "bool",
       "treat the current backend as the measured device (CPU rehearsal)",
       scope="bench"),
)}


# --------------------------------------------------------------------------
# typed accessors — the only sanctioned way to READ a RACON_TPU_* variable
# --------------------------------------------------------------------------

def _knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a registered knob; add it to "
            f"racon_tpu/config.py (and README.md)") from None


def get_raw(name: str) -> Optional[str]:
    """The raw environment value, or the registered default (may be
    None).  Exists so call sites with bespoke parsing keep byte-identical
    behavior while still going through the registry."""
    k = _knob(name)
    return os.environ.get(name, k.default)


def get_str(name: str) -> str:
    v = get_raw(name)
    return "" if v is None else v


def get_int(name: str) -> int:
    """int(value); raises ValueError on garbage exactly like the direct
    int(os.environ.get(...)) reads this replaced."""
    v = get_raw(name)
    if v is None:
        raise KeyError(f"{name} has no value and no registered default")
    return int(v)


def get_float(name: str) -> float:
    v = get_raw(name)
    if v is None:
        raise KeyError(f"{name} has no value and no registered default")
    return float(v)


def get_bool(name: str) -> bool:
    """True iff the variable is set to '1' (the repo-wide convention)."""
    return get_raw(name) == "1"


def is_set(name: str) -> bool:
    """Whether the variable is present in the environment at all."""
    _knob(name)
    return name in os.environ


def unknown_env_knobs(environ=None) -> List[str]:
    """RACON_TPU_* variables set in the environment but absent from the
    registry — almost always a typo'd knob that would otherwise be
    silently ignored.  Surfaced in the run report (see
    resilience/report.py)."""
    env = os.environ if environ is None else environ
    return sorted(v for v in env
                  if v.startswith(PREFIX) and v not in KNOBS)
