"""Resilience layer: deterministic fault injection, the unified
degradation lattice, and the machine-readable run report.

The reference racon degrades gracefully when the accelerator rejects work
— failed CUDA batches are re-polished on the host
(/root/reference/src/cuda/cudapolisher.cpp:354-378). This package makes
that posture a tested subsystem instead of scattered try/except blocks:

* `faults`  — named injection points at every device/host seam, driven by
  the `RACON_TPU_FAULT` env spec, so any lattice edge can be triggered
  deterministically on the CPU backend in CI.
* `lattice` — the ordered degradation tiers (ls -> v2 -> xla -> host for
  consensus; hirschberg/xla -> host for alignment) plus the shared
  retry / watchdog / batch-bisection machinery the drivers run through.
* `report`  — per-phase serving/fallback accounting surfaced through
  `Polisher.polish()`, the `--report` CLI flag, `RACON_TPU_REPORT`, and
  `bench.py` / `tools/hw_session.py`.
"""

from . import faults, lattice, report  # noqa: F401
from .faults import InjectedFault, MosaicError, check, parse_spec, reset
from .lattice import (ALIGN_TIERS, CONSENSUS_TIERS, TierDead,
                      WatchdogTimeout, call_with_watchdog, device_timeout,
                      serve_with_bisect, tier_retries)
from .report import PhaseReport, RunReport

__all__ = [
    "faults", "lattice", "report",
    "InjectedFault", "MosaicError", "check", "parse_spec", "reset",
    "ALIGN_TIERS", "CONSENSUS_TIERS", "TierDead", "WatchdogTimeout",
    "call_with_watchdog", "device_timeout", "serve_with_bisect",
    "tier_retries",
    "PhaseReport", "RunReport",
]
