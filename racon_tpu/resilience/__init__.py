"""Resilience layer: deterministic fault injection, the unified
degradation lattice, and the machine-readable run report.

The reference racon degrades gracefully when the accelerator rejects work
— failed CUDA batches are re-polished on the host
(/root/reference/src/cuda/cudapolisher.cpp:354-378). This package makes
that posture a tested subsystem instead of scattered try/except blocks:

* `faults`  — named injection points at every device/host seam, driven by
  the `RACON_TPU_FAULT` env spec, so any lattice edge can be triggered
  deterministically on the CPU backend in CI.
* `lattice` — the ordered degradation tiers (ls -> v2 -> xla -> host for
  consensus; hirschberg/xla -> host for alignment) plus the shared
  retry / watchdog / batch-bisection machinery the drivers run through.
* `watchdog`— the deadline-scoped timer around device dispatch and the
  wedge tracker that classifies repeated timeouts as a wedged tier
  (`TierWedged`) so a hung jit call demotes instead of hanging the run.
* `journal` — the crash-safe, append-only window-result journal behind
  `--journal` / `--resume-journal` / `RACON_TPU_JOURNAL`: a SIGKILLed
  run resumes and reproduces byte-identical output.
* `report`  — per-phase serving/fallback accounting surfaced through
  `Polisher.polish()`, the `--report` CLI flag, `RACON_TPU_REPORT`, and
  `bench.py` / `tools/hw_session.py`.
"""

from . import faults, journal, lattice, report, watchdog  # noqa: F401
from .faults import InjectedFault, MosaicError, check, parse_spec, reset
from .journal import CigarTap, Journal, JournalError, input_fingerprint
from .lattice import (ALIGN_TIERS, CONSENSUS_TIERS, TierDead, TierWedged,
                      WatchdogTimeout, call_with_watchdog, device_timeout,
                      serve_with_bisect, tier_retries)
from .report import PhaseReport, RunReport
from .watchdog import WedgeTracker, wedge_limit

__all__ = [
    "faults", "journal", "lattice", "report", "watchdog",
    "InjectedFault", "MosaicError", "check", "parse_spec", "reset",
    "CigarTap", "Journal", "JournalError", "input_fingerprint",
    "ALIGN_TIERS", "CONSENSUS_TIERS", "TierDead", "TierWedged",
    "WatchdogTimeout", "call_with_watchdog", "device_timeout",
    "serve_with_bisect", "tier_retries",
    "PhaseReport", "RunReport",
    "WedgeTracker", "wedge_limit",
]
