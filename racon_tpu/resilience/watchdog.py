"""Hung-step watchdog: deadline-scoped timing around device dispatch.

The axon tunnel's signature failure is not an exception but silence — a
`jit` call that never returns (VERDICT.md: five rounds of wedged
sessions).  This module owns the two halves of turning that silence into
a routable fault:

* `call_with_watchdog` runs one device call on a daemon thread with a
  deadline (`RACON_TPU_DEVICE_TIMEOUT`); expiry raises
  `WatchdogTimeout`.  A truly hung device op cannot be cancelled from
  Python — the abandoned call keeps its thread, and the caller's job is
  to stop feeding the dead tier.
* `WedgeTracker` classifies *repeated* timeouts: one timeout is a
  transient (the lattice retries at the same tier), but
  `RACON_TPU_WEDGE_LIMIT` consecutive timeouts on one tier mean the tier
  is wedged, and the lattice converts the next failure into
  `TierWedged` (a `TierDead` subtype) so the geometry demotes instead of
  burning a full watchdog deadline per retry forever.

The tracker is process-global per-run state exactly like the fault
plan's counters: `reset()` is called by the polisher constructors so
consecutive runs classify identically.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from .. import config, obs
from . import faults


class WatchdogTimeout(Exception):
    """A device call exceeded the RACON_TPU_DEVICE_TIMEOUT watchdog."""

    def __init__(self, message: str, tier: Optional[str] = None,
                 elapsed: float = 0.0):
        super().__init__(message)
        self.tier = tier
        self.elapsed = elapsed


def device_timeout() -> float:
    """Per-device-call watchdog in seconds; 0 (default) disables it."""
    try:
        return config.get_float("RACON_TPU_DEVICE_TIMEOUT")
    except ValueError:
        return 0.0


def wedge_limit() -> int:
    """Consecutive same-tier watchdog timeouts before the tier is
    declared wedged (default 3; 0 disables wedge classification so every
    timeout stays an ordinary retryable failure)."""
    try:
        return max(0, config.get_int("RACON_TPU_WEDGE_LIMIT"))
    except ValueError:
        return 3


class WedgeTracker:
    """Consecutive-timeout counter per tier.

    A success at a tier clears its streak — a tier that times out, then
    serves, is slow-but-alive, not wedged.  The counter is keyed by tier
    name only (not geometry): a wedged tunnel wedges every geometry, and
    demoting them all at once is the behavior that stops the bleeding.
    """

    def __init__(self):
        self._streak: Dict[str, int] = {}

    def record_timeout(self, tier: str) -> int:
        n = self._streak.get(tier, 0) + 1
        self._streak[tier] = n
        return n

    def record_success(self, tier: str) -> None:
        self._streak.pop(tier, None)

    def streak(self, tier: str) -> int:
        return self._streak.get(tier, 0)

    def is_wedged(self, tier: str) -> bool:
        limit = wedge_limit()
        return limit > 0 and self._streak.get(tier, 0) >= limit

    def reset(self) -> None:
        self._streak.clear()


_TRACKER = WedgeTracker()


def tracker() -> WedgeTracker:
    """The process-wide per-run wedge tracker."""
    return _TRACKER


def reset() -> None:
    """Clear wedge streaks; called by the polisher constructors next to
    `faults.reset()` so consecutive runs classify identically."""
    _TRACKER.reset()


def call_with_watchdog(fn: Callable, timeout: Optional[float] = None,
                       tier: Optional[str] = None):
    """Run fn() under the watchdog.  With no timeout configured this is a
    direct call (no thread).  On expiry raises WatchdogTimeout — and,
    when `tier` is given, feeds the wedge tracker so the lattice can
    distinguish a transient stall from a wedged tier."""
    faults.check("watchdog.call")
    t = device_timeout() if timeout is None else timeout
    if not t or t <= 0:
        return fn()
    box = {}

    def runner():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["error"] = e

    th = threading.Thread(target=runner, daemon=True,
                          name="racon-tpu-watchdog-call")
    th.start()
    th.join(t)
    if th.is_alive():
        if tier is not None:
            _TRACKER.record_timeout(tier)
        obs.event("watchdog.timeout", tier=tier, deadline_s=t,
                  streak=_TRACKER.streak(tier) if tier is not None else 0)
        obs.count(f"watchdog_timeouts.{tier or 'unknown'}")
        raise WatchdogTimeout(
            f"device call exceeded the {t:.3g}s watchdog", tier=tier,
            elapsed=t)
    if "error" in box:
        raise box["error"]
    if tier is not None:
        _TRACKER.record_success(tier)
    return box["result"]
