"""The unified degradation lattice.

Ordered tiers, per-tier bounded retry, a per-device-call watchdog, and
batch bisection — the shared machinery both drivers run their device
calls through.  The reference implements the same posture ad hoc: failed
CUDA batches are re-polished on the host
(/root/reference/src/cuda/cudapolisher.cpp:354-378); here every edge is
explicit and deterministically testable via `resilience.faults`.

Tier orders (best first; a tier's failure demotes to the next):

    consensus:  ls -> v2 -> xla -> host
    alignment:  hirschberg -> host,  xla -> host
                (the entry tier is chosen by RACON_TPU_DEVICE_ALIGNER;
                either device engine degrades straight to the host Myers
                aligner — there is no cross-engine demotion because the
                xla moves-matrix tier only admits small pairs)

Failure taxonomy the drivers map onto this module:

* transient batch failure  -> bounded retry at the same tier
  (`RACON_TPU_TIER_RETRIES`, default 1 extra attempt)
* hung device call         -> watchdog timeout surfaces it as an error
  (`RACON_TPU_DEVICE_TIMEOUT` seconds; 0/unset = disabled)
* wedged tier              -> `RACON_TPU_WEDGE_LIMIT` consecutive
  watchdog timeouts classify the tier as wedged (`TierWedged`, a
  TierDead subtype): demote immediately instead of burning one full
  deadline per retry (see resilience/watchdog.py)
* window-correlated failure-> batch bisection: the failing batch is
  split, halves are probed, and the poisoned window is quarantined to
  the host while the rest of the batch stays on the device
* tier-wide failure        -> `TierDead` (both halves of a bisection
  fail); the caller demotes the whole geometry one tier
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

from .. import config, obs
# the watchdog moved to its own module (resilience/watchdog.py); the
# names stay importable from here — every caller and test uses the
# lattice as the façade
from .watchdog import (WatchdogTimeout, call_with_watchdog,  # noqa: F401
                       device_timeout, tracker)

#: Consensus kernel tiers, best first.  "host" is the floor: windows are
#: re-polished one-by-one by the native SPOA-equivalent engine.
CONSENSUS_TIERS = ("ls", "v2", "xla", "host")

#: Alignment tiers.  hirschberg and xla are alternative entry engines
#: (RACON_TPU_DEVICE_ALIGNER); both degrade straight to the host Myers
#: aligner.
ALIGN_TIERS = ("hirschberg", "xla", "host")


class TierDead(Exception):
    """The current tier fails batch-independently; demote the geometry."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause
        # a dead tier is a crash-adjacent event: dump the flight ring at
        # raise time (covers TierWedged too) so the post-mortem exists
        # even if a caller turns this into a process exit
        from ..obs import flight
        flight.record("lattice.tier_dead", kind="event",
                      error=f"{type(cause).__name__}: {cause}",
                      wedged=isinstance(self, TierWedged))
        flight.dump("tier_dead",
                    error=f"{type(cause).__name__}: {cause}")


class TierWedged(TierDead):
    """The tier kept timing out (RACON_TPU_WEDGE_LIMIT consecutive
    watchdog expiries): a wedged jit call, the axon tunnel's signature
    failure.  A TierDead subtype — callers demote exactly as for any
    dead tier — but distinguishable in reports, and raised *instead of
    retrying* so a wedged tier stops costing one full watchdog deadline
    per attempt."""


def tier_retries() -> int:
    """Extra attempts per tier before bisecting/demoting (default 1)."""
    return max(0, config.get_int("RACON_TPU_TIER_RETRIES"))


def serve_with_bisect(items: Sequence, attempt: Callable,
                      *, tier: str, report=None,
                      retries: Optional[int] = None,
                      cached: Optional[Callable] = None
                      ) -> Tuple[List[Tuple[list, object]],
                                 List[Tuple[object, BaseException]]]:
    """Serve one batch at a fixed tier with bounded retry and bisection.

    items    — one opaque work unit per real window/job in the batch.
    attempt  — attempt(sub_items) -> tier result for that sub-batch
               (pack + submit + block); called under the watchdog.
    cached   — optional zero-arg callable returning the full batch's
               already-dispatched result (the async-pipelined outs);
               tried as attempt #0 so the happy path stays pipelined.

    Returns (pairs, quarantined):
      pairs       — [(sub_items, result)] covering every served unit
      quarantined — [(item, exception)] poisoned units for the host

    Raises TierDead when failures are batch-independent (both halves of
    a bisection fail), i.e. the tier itself is broken for this geometry
    and the caller should demote.  Two poisoned windows landing in
    opposite halves are indistinguishable from a dead tier and demote
    conservatively — correctness is preserved either way (the next tier,
    ultimately the host, serves them).
    """
    n_retries = tier_retries() if retries is None else retries
    if tracker().is_wedged(tier):
        # the tier wedged earlier in this run — do not feed it at all
        raise TierWedged(WatchdogTimeout(
            f"tier {tier!r} is wedged ({tracker().streak(tier)} "
            f"consecutive watchdog timeouts)", tier=tier))

    def timed(fn):
        t0 = time.perf_counter()
        try:
            return call_with_watchdog(fn, tier=tier)
        finally:
            if report is not None:
                report.add_wall(tier, time.perf_counter() - t0)

    def attempts(sub, use_cached):
        last = None
        for a in range(n_retries + 1):
            try:
                if a == 0 and use_cached:
                    return timed(cached)
                return timed(lambda: attempt(sub))
            except Exception as e:  # noqa: BLE001 — lattice boundary
                last = e
                if report is not None:
                    report.record_failure(tier, e)
                    if a < n_retries:
                        report.retries += 1
                if a < n_retries:
                    obs.event("lattice.retry", tier=tier, attempt=a + 1,
                              error=type(e).__name__)
                    obs.count(f"retries.{tier}")
                if (isinstance(e, WatchdogTimeout)
                        and tracker().is_wedged(tier)):
                    # repeated expiry = wedged jit call; each further
                    # attempt would burn a full deadline, so classify
                    # and demote instead of retrying/bisecting
                    raise TierWedged(e) from e
        raise last

    def serve(sub, use_cached):
        try:
            return [(list(sub), attempts(sub, use_cached))], []
        except TierDead:
            raise               # wedge classification — not bisectable
        except Exception as e:  # noqa: BLE001 — lattice boundary
            if len(sub) <= 1:
                return [], [(sub[0], e)]
            if report is not None:
                report.bisections += 1
            obs.event("lattice.bisect", tier=tier, size=len(sub),
                      error=type(e).__name__)
            obs.count(f"bisections.{tier}")
            mid = len(sub) // 2
            probes = []
            for half in (sub[:mid], sub[mid:]):
                try:
                    probes.append((half, timed(lambda h=half: attempt(h))))
                except Exception as he:  # noqa: BLE001
                    if report is not None:
                        report.record_failure(tier, he)
                    probes.append((half, he))
            if all(isinstance(r, BaseException) for _, r in probes):
                raise TierDead(e) from e
            pairs, quarantined = [], []
            for half, r in probes:
                if isinstance(r, BaseException):
                    p, q = serve(half, False)  # TierDead propagates
                    pairs.extend(p)
                    quarantined.extend(q)
                else:
                    pairs.append((list(half), r))
            return pairs, quarantined

    return serve(list(items), cached is not None)


def next_consensus_tier(kind: str) -> str:
    """The tier below `kind` in the consensus lattice ('host' floor)."""
    i = CONSENSUS_TIERS.index(kind)
    return CONSENSUS_TIERS[min(i + 1, len(CONSENSUS_TIERS) - 1)]


def record_band_fallback(report, tier: str, cause=None) -> None:
    """The `banded -> flat` lattice edge, recorded once per job.

    Orthogonal to tier demotion (like the sharded -> single-device
    edge): the job stays at `tier`, only the DP band is dropped — the
    flat kernel is the byte-identity oracle, so the floor of the
    verify-and-widen ladder can never change output.  Shows up in the
    report's degradation list as `<tier>+banded -> <tier>` and in the
    metrics as `band.fallbacks`, so a band that keeps getting hit is
    visible in any trace or run report."""
    exc = cause if isinstance(cause, BaseException) else None
    if report is not None:
        report.record_degrade(f"{tier}+banded", tier, exc)
    obs.count("band.fallbacks")


def record_shard_demotion(report, tier: str, cause) -> None:
    """The `sharded -> single-device` lattice edge, recorded once.

    Orthogonal to tier demotion: the kernel stays at `tier`, only the
    mesh dispatch is dropped (sharding changes where rows compute, never
    what — output stays byte-identical).  Shows up in the report's
    degradation list as `<tier>+sharded -> <tier>` and in the metrics as
    `shard.demotions`, so a silent fallback to one device is visible in
    any trace or run report."""
    exc = cause if isinstance(cause, BaseException) else None
    if report is not None:
        report.record_degrade(f"{tier}+sharded", tier, exc)
    obs.count("shard.demotions")
    import sys
    print(f"[racon-tpu] sharded dispatch failed at tier {tier!r} "
          f"({cause}); demoting to single-device dispatch",
          file=sys.stderr)
