"""Machine-readable run report: who served what, and why it fell back.

Each polishing phase produces a `PhaseReport` (per-tier served counts,
fallback causes, retries, bisections, quarantined window indices, wall
time per tier); the polisher aggregates them into a `RunReport` surfaced
through `TpuPolisher.report`, the CLI `--report PATH` flag, the
`RACON_TPU_REPORT` env var (written at the end of `polish()` — the hook
`bench.py` and `tools/hw_session.py` use), and the one-line bench JSON.

Invariant (regression-tested): a phase's per-tier served counts sum to
its total job/window count, clean or fault-injected.
"""

from __future__ import annotations

import json
import sys
import time
from typing import List, Optional, Tuple

from .. import config, obs

ENV_REPORT = "RACON_TPU_REPORT"

#: Cap per-tier recorded cause strings / quarantined indices so a
#: pathological run cannot balloon the report.
_MAX_CAUSES = 20
_MAX_QUARANTINED = 1000


class PhaseReport:  # concurrency: single-writer accumulator; the coordinator serializes its cross-thread instance under Coordinator._cv
    """Serving/fallback accounting for one phase (alignment/consensus)."""

    def __init__(self, phase: str, tiers: Tuple[str, ...]):
        self.phase = phase
        self.tiers = tuple(tiers)
        self.total = 0
        self.served = {t: 0 for t in self.tiers}
        self.retries = 0
        self.bisections = 0
        self.quarantined: List[int] = []
        self.degradations: List[dict] = []
        self.causes = {}      # tier -> [error strings]
        self.wall_s = {}      # tier -> accumulated seconds
        self.extra = {}       # phase-specific counters (layers_dropped, …)

    # -- recording --------------------------------------------------------
    # The obs hooks below feed the metrics registry from the same calls
    # that mutate the report, so the served-sum invariant between the
    # two (obs.served_sum_check) holds by construction unless some path
    # serves work while bypassing the report — which is the drift the
    # cross-check exists to expose.
    def record_served(self, tier: str, n: int = 1) -> None:
        self.served[tier] = self.served.get(tier, 0) + n
        obs.count(f"served.{self.phase}.{tier}", n)

    def record_failure(self, tier: str, exc: BaseException) -> None:
        lst = self.causes.setdefault(tier, [])
        if len(lst) < _MAX_CAUSES:
            lst.append(f"{type(exc).__name__}: {exc}")
        obs.count(f"failures.{self.phase}.{tier}")

    def record_degrade(self, frm: str, to: str,
                       exc: Optional[BaseException] = None) -> None:
        self.degradations.append({
            "from": frm, "to": to,
            "error": f"{type(exc).__name__}: {exc}" if exc else None})
        obs.event("lattice.demote", phase=self.phase, frm=frm, to=to,
                  error=type(exc).__name__ if exc else None)
        obs.count(f"demotions.{self.phase}.{frm}")

    def record_quarantine(self, index: int,
                          exc: Optional[BaseException] = None) -> None:
        if len(self.quarantined) < _MAX_QUARANTINED:
            self.quarantined.append(int(index))
        if exc is not None:
            self.record_failure("quarantine", exc)
        obs.event("lattice.quarantine", phase=self.phase, index=int(index))
        obs.count(f"quarantined.{self.phase}")

    def add_wall(self, tier: str, seconds: float) -> None:
        self.wall_s[tier] = self.wall_s.get(tier, 0.0) + seconds
        obs.observe(f"wall_s.{self.phase}.{tier}", seconds)

    def merge(self, other: "PhaseReport") -> None:
        """Fold another report for the same phase into this one (the
        pipelined polisher runs one report per target chunk and merges).

        Pure accounting — the obs counters were already fed at record
        time on `other`, so merging does NOT re-feed them; the served-sum
        cross-check stays valid against the merged counts."""
        self.total += other.total
        for t, c in other.served.items():
            self.served[t] = self.served.get(t, 0) + c
        self.retries += other.retries
        self.bisections += other.bisections
        room = _MAX_QUARANTINED - len(self.quarantined)
        if room > 0:
            self.quarantined.extend(other.quarantined[:room])
        self.degradations.extend(other.degradations)
        for t, msgs in other.causes.items():
            lst = self.causes.setdefault(t, [])
            lst.extend(msgs[:max(0, _MAX_CAUSES - len(lst))])
        for t, s in other.wall_s.items():
            self.wall_s[t] = self.wall_s.get(t, 0.0) + s
        for k, v in other.extra.items():
            cur = self.extra.get(k)
            if isinstance(cur, (int, float)) and isinstance(v, (int, float)):
                self.extra[k] = round(cur + v, 6)
            else:
                self.extra[k] = v

    # -- views ------------------------------------------------------------
    def served_total(self) -> int:
        return sum(self.served.values())

    def as_dict(self) -> dict:
        return {
            "phase": self.phase,
            "total": self.total,
            "served": dict(self.served),
            "retries": self.retries,
            "bisections": self.bisections,
            "quarantined": list(self.quarantined),
            "degradations": list(self.degradations),
            "causes": {k: list(v) for k, v in self.causes.items()},
            "wall_s": {k: round(v, 4) for k, v in self.wall_s.items()},
            **({"extra": dict(self.extra)} if self.extra else {}),
        }


class RunReport:
    """Aggregated per-run report (all phases + the armed fault spec)."""

    def __init__(self):
        self.phases = {}
        # monotonic: a wall-clock (time.time) duration goes negative or
        # balloons across an NTP step; the wall-clock lint rule
        # (analysis/rules/clock.py) enforces this repo-wide
        self._t0 = time.monotonic()
        self.wall_s = None
        # flight-recorder dumps swept from the workdir after the run
        # (obs/flight.py `scan` docs) — each entry is one post-mortem
        self.flight: List[dict] = []
        # per-job latency-ledger fragment (obs/ledger.py): the serve
        # session stamps the compute side's stage_s decomposition here
        # so the persisted report carries it; None outside serving
        self.ledger: Optional[dict] = None

    def attach(self, phase_report: Optional[PhaseReport]) -> None:
        if phase_report is not None:
            self.phases[phase_report.phase] = phase_report

    def finalize(self) -> "RunReport":
        self.wall_s = time.monotonic() - self._t0
        return self

    def as_dict(self) -> dict:
        from ..analysis import sanitize
        from .faults import active_spec

        return {
            "phases": {k: v.as_dict() for k, v in self.phases.items()},
            "fault_spec": active_spec(),
            # stale-knob check: RACON_TPU_* vars set in the environment
            # but unknown to the config registry — a typo'd knob surfaces
            # here instead of being silently ignored
            "unknown_knobs": config.unknown_env_knobs(),
            # runtime-sanitizer verdict: armed flag + structured findings
            # (rendered by `python -m racon_tpu.analysis
            # --sanitize-report REPORT.json`)
            "sanitize": {"armed": sanitize.enabled(),
                         "findings": sanitize.as_dicts()},
            # observability snapshot: metrics registry + the served-sum
            # cross-check against the per-phase counts above (racon_tpu/obs)
            "obs": {"armed": obs.enabled(),
                    **({"metrics": obs.snapshot(),
                        "served_sum": obs.served_sum_check(self.phases)}
                       if obs.enabled() else {})},
            # post-mortem references: one compact entry per flight dump
            # found after the run (the dump file holds the full ring)
            "flight": [{"path": d.get("path"), "pid": d.get("pid"),
                        "role": d.get("role"), "reason": d.get("reason"),
                        "events": len(d.get("events") or [])}
                       for d in self.flight],
            "wall_s": round(self.wall_s if self.wall_s is not None
                            else time.monotonic() - self._t0, 3),
            # latency-ledger fragment, present only when serving stamped
            # one (obs/ledger.py) — keys absent rather than null so
            # non-serve reports stay byte-for-byte what they were
            **({"ledger": dict(self.ledger)} if self.ledger else {}),
        }

    def summary(self) -> dict:
        """Compact serving-mix view for logs and the bench JSON line."""
        out = {
            phase: {"total": r.total, "served": dict(r.served),
                    "retries": r.retries, "bisections": r.bisections,
                    "quarantined": len(r.quarantined),
                    "degradations": len(r.degradations),
                    "wall_s": {t: round(s, 4)
                               for t, s in r.wall_s.items()},
                    # pack/kernel wall split and other phase extras ride
                    # along so bench.py can stamp them into log entries
                    **({"extra": dict(r.extra)} if r.extra else {})}
            for phase, r in self.phases.items()
        }
        stale = config.unknown_env_knobs()
        if stale:
            out["unknown_knobs"] = stale
        return out

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.as_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    def write_env(self) -> None:
        """Write to $RACON_TPU_REPORT when set (bench/hw_session hook);
        a write failure warns, it never fails the polish."""
        path = config.get_raw(ENV_REPORT)
        if not path:
            return
        try:
            self.write(path)
        except OSError as e:
            print(f"[racon_tpu::report] WARNING: cannot write {path}: {e}",
                  file=sys.stderr)
