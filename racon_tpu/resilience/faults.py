"""Deterministic fault injection at the device/host seams.

Every place the drivers hand work to (or take results back from) an
accelerator kernel or the native host engine is a named *injection
point*.  The `RACON_TPU_FAULT` environment variable arms one or more of
them:

    RACON_TPU_FAULT="poa.run.ls:raise=MosaicError"
    RACON_TPU_FAULT="poa.run.xla:window=5"
    RACON_TPU_FAULT="align.run:batch=1:count=1,poa.run.v2:hang=2"

Spec grammar (comma-separated specs; colon-separated fields):

    <point>[:batch=N][:window=I][:count=N][:hang=SECONDS][:raise=NAME]
           [:kill=1]

* `point`   — one of KNOWN_POINTS below.  The first field.
* `batch=N` — fire only on the Nth invocation of the point (0-based,
  counted per point per run).  Retries re-invoke the point, so a
  `batch=0:count=1` fault fails the first attempt and lets the retry
  succeed — the deterministic transient fault.
* `window=I`— fire only when window/job index I is in the submitted
  batch (run points pass the batch's indices).  Batch bisection narrows
  such a fault down to the poisoned window, which is quarantined to the
  host while the rest of the batch stays on the device.
* `count=N` — fire at most N times (default: unlimited — the point is
  permanently broken, which is how a whole tier is killed).
* `hang=S`  — sleep S seconds instead of raising (exercises the
  per-device-call watchdog; combine with `RACON_TPU_DEVICE_TIMEOUT`).
* `raise=NAME` — exception class to raise (default `MosaicError`, the
  synthetic stand-in for a Mosaic compile/runtime failure).
* `kill=1`  — SIGKILL the whole process instead of raising: the
  deterministic mid-run crash (no handlers, no flushing — exactly what
  a preemption does).  Combine with `batch=N` on `journal.append` to
  die after exactly N journaled results; the crash-resume tests are
  built on it.

Specs are validated eagerly: a malformed spec raises `ValueError` with a
single-line message (the CLI surfaces it as exit 1, reference-style).
Counters are per-run — `reset()` is called by the polisher constructors
so consecutive runs in one process see identical firing schedules.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .. import config

ENV = "RACON_TPU_FAULT"

#: Every injection point the drivers expose.  Compile points fire when a
#: kernel for that tier is (re)built; run points fire on every batch
#: submitted to that tier; the host seams fire per native call / window
#: export.
KNOWN_POINTS = frozenset({
    "align.compile",     # phase-1 device engine kernel build
    "align.run",         # phase-1 device engine, per cohort
    "align.install",     # phase-1 CIGAR install, per job (after the
                         # lattice: an escape mid-install must not erase
                         # the device-served count — see align_driver)
    "band.hit",          # banded DP verify (ops/band.py): an armed
                         # fault (raise=MosaicError/InjectedFault)
                         # classifies every banded job of the attempt as
                         # a band hit instead of raising — the
                         # deterministic widening-exhaustion drill that
                         # drives the ladder to its flat floor
    "poa.compile.ls",    # lockstep consensus kernel build
    "poa.compile.v2",    # one-window consensus kernel build
    "poa.compile.xla",   # XLA-twin consensus kernel build
    "poa.run.ls",        # lockstep consensus, per submitted batch
    "poa.run.v2",        # one-window consensus, per submitted batch
    "poa.run.xla",       # XLA-twin consensus, per submitted batch
    "native.call",       # host (native) engine calls — the lattice floor
    "window.export",     # per-window export from the native pipeline
    "journal.append",    # durable-journal record write (resilience/journal)
    "journal.replay",    # journal replay on --resume-journal
    "watchdog.call",     # device-dispatch entry under the watchdog
    "sanitize.nan",      # sanitizer: poison the checker's COPY of one
                         # consensus buffer (polish output untouched)
    "sanitize.stats",    # sanitizer: one real cross-thread stats-dict
                         # mutation through the guard
    # distributed seams (racon_tpu/distrib): the coordinator checks
    # worker.spawn before launching each fleet process; a worker checks
    # worker.heartbeat before every lease renewal and worker.result
    # before delivering a finished chunk.  kill=1 on the worker points is
    # a real SIGKILL of that worker mid-chunk — the chaos suite's
    # deterministic worker loss.  Scope the env to one worker with
    # RACON_TPU_DISTRIB_FAULT_WORKER.
    "worker.spawn",      # coordinator, per worker process launched
    "worker.heartbeat",  # worker, before each heartbeat send
    "worker.result",     # worker, before delivering a chunk result
    # elastic control plane seams (racon_tpu/fleet): the pool checks
    # pool.scale_up / pool.scale_down before growing / draining the
    # worker fleet, the plane checks pool.steal before handing a chunk
    # of job A to a worker whose affinity is job B, and every lease
    # reclaim (worker death or drain) checks lease.reclaim before
    # releasing the dead holder's leases.  A raise on these points is
    # absorbed as a modeled control-plane failure (the transition is
    # skipped or proceeds degraded, and counted); kill=1 is the
    # deterministic controller crash mid-transition — the recover()
    # interplay tests are built on pool.scale_up:kill=1.
    "pool.scale_up",     # elastic pool, before spawning a growth worker
    "pool.scale_down",   # elastic pool, before draining a worker
    "pool.steal",        # fleet plane, before a cross-job work steal
    "lease.reclaim",     # lease layer, before reclaiming a dead
                         # holder's leases
    # memory-budget seams (racon_tpu/resilience/budget.py): the budget
    # checks mem.pressure on every synchronous poll — a raise there is
    # absorbed as a forced hard-watermark breach (the deterministic
    # memory-pressure drill: backpressure, spill, and the pressure
    # lattice edges all fire without needing real RSS growth).
    # mem.spill fires before a chunk working set is parked to the spill
    # file — a raise aborts that park and the working set stays in
    # memory (absorbed + counted).  mem.oom fires in the distrib worker
    # before polishing a fetched chunk; kill=1 there is a real
    # OOM-style SIGKILL of that worker mid-chunk (scope with
    # RACON_TPU_DISTRIB_FAULT_WORKER) — the journal/lease machinery
    # resumes the chunk byte-identically.
    "mem.pressure",      # budget poll: forced hard-watermark breach
    "mem.spill",         # before parking a working set to the spill file
    "mem.oom",           # distrib worker, before polishing a chunk
    # SLO seam (racon_tpu/obs/slo.py): the burn-rate engine checks
    # slo.burn on every evaluation — a raise is absorbed as a forced
    # burn (both windows report at least the alert threshold for one
    # fast window, counted as burn_faults).  This is the deterministic
    # injected-slowdown drill: the alert -> autoscale path fires
    # without a real latency regression.
    "slo.burn",          # SLO engine, forced burn-rate breach
})


class InjectedFault(Exception):
    """Base class for synthetic injected failures."""


class MosaicError(InjectedFault):
    """Synthetic stand-in for a Mosaic compile/runtime failure."""


#: Exception classes a spec may name.  Builtins are included so the
#: lattice's broad-Exception handling is exercised with realistic types.
EXCEPTIONS = {
    "MosaicError": MosaicError,
    "InjectedFault": InjectedFault,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
}

_UNLIMITED = -1


@dataclass
class FaultSpec:
    point: str
    batch: Optional[int] = None
    window: Optional[int] = None
    count: int = _UNLIMITED
    hang: float = 0.0
    kill: bool = False
    raise_name: str = "MosaicError"
    fired: int = field(default=0, compare=False)

    def spent(self) -> bool:
        return self.count != _UNLIMITED and self.fired >= self.count

    def describe(self) -> str:
        sel = []
        if self.batch is not None:
            sel.append(f"batch={self.batch}")
        if self.window is not None:
            sel.append(f"window={self.window}")
        return ":".join([self.point, *sel]) or self.point


def parse_spec(text: str) -> list:
    """Parse a RACON_TPU_FAULT value; raises ValueError on any malformed
    field (unknown point, unknown key, non-integer selector, unknown
    exception name) with a single-line message."""
    specs = []
    for part in filter(None, (p.strip() for p in text.split(","))):
        fields = part.split(":")
        point = fields[0]
        if point not in KNOWN_POINTS:
            raise ValueError(
                f"{ENV}: unknown injection point {point!r} "
                f"(valid: {', '.join(sorted(KNOWN_POINTS))})")
        spec = FaultSpec(point)
        for f in fields[1:]:
            key, sep, val = f.partition("=")
            if not sep:
                raise ValueError(f"{ENV}: expected key=value, got {f!r}")
            try:
                if key == "batch":
                    spec.batch = int(val)
                elif key == "window":
                    spec.window = int(val)
                elif key == "count":
                    spec.count = int(val)
                elif key == "hang":
                    spec.hang = float(val)
                elif key == "kill":
                    spec.kill = int(val) != 0
                elif key == "raise":
                    if val not in EXCEPTIONS:
                        raise ValueError(
                            f"{ENV}: unknown exception {val!r} "
                            f"(valid: {', '.join(sorted(EXCEPTIONS))})")
                    spec.raise_name = val
                else:
                    raise ValueError(f"{ENV}: unknown key {key!r} "
                                     f"(valid: batch, window, count, hang, "
                                     f"kill, raise)")
            except ValueError as e:
                if str(e).startswith(ENV):
                    raise
                raise ValueError(
                    f"{ENV}: bad value {val!r} for {key!r}") from None
        specs.append(spec)
    return specs


class FaultPlan:
    """Parsed specs plus per-point invocation counters for one run.

    The plan is process-global shared state: checks come from the main
    thread, serve/distrib/fleet connection handlers and the fleet
    monitor, so invocation counting and spec selection happen under
    ``_LOCK`` — a racing pair of checks must burn two distinct
    invocation indices, or ``batch=N`` selectors stop being
    deterministic.  The *action* (sleep/raise/SIGKILL) runs outside the
    lock so a ``hang=S`` spec stalls only its own thread.
    """

    def __init__(self, specs):
        self.specs = specs
        self.calls = {}

    def check(self, point: str,
              windows: Optional[Sequence[int]] = None) -> None:
        with _LOCK:
            n = self.calls.get(point, 0)
            self.calls[point] = n + 1
            fire = None
            for spec in self.specs:
                if spec.point != point or spec.spent():
                    continue
                if spec.batch is not None and spec.batch != n:
                    continue
                if spec.window is not None:
                    if windows is None or spec.window not in windows:
                        continue
                spec.fired += 1
                fire = spec
                break
        if fire is None:
            return
        from ..obs import flight
        flight.record("fault.fired", point=point, invocation=n,
                      spec=fire.describe())
        if fire.kill:
            # the flight dump is the ONLY artifact this process
            # leaves: it must land before the uncatchable signal
            flight.dump("fault_kill", point=point, invocation=n)
            # the deterministic preemption: no cleanup, no flush —
            # the process is gone mid-append, exactly like a real
            # SIGKILL/OOM/eviction
            os.kill(os.getpid(), signal.SIGKILL)
        if fire.hang:
            time.sleep(fire.hang)
            return
        raise EXCEPTIONS[fire.raise_name](
            f"injected fault at {fire.describe()} (invocation {n})")


# Guards the plan cache and every FaultPlan counter (see
# FaultPlan.check).  Nothing is called while holding it, so it nests
# safely under any control-plane lock (scheduler/coordinator/plane _cv).
_LOCK = threading.Lock()

# cache keyed on the raw env string so monkeypatched environments take
# effect immediately; counters persist while the string is unchanged
# (reset() re-arms them at the start of each polisher run)
_cached_env: Optional[str] = None
_cached_plan: Optional[FaultPlan] = None


def _plan() -> Optional[FaultPlan]:
    global _cached_env, _cached_plan
    env = config.get_str(ENV)
    with _LOCK:
        if env != _cached_env:
            _cached_env = env
            _cached_plan = FaultPlan(parse_spec(env)) if env else None
        return _cached_plan


def active_spec() -> str:
    """The armed spec string ('' when fault injection is off)."""
    return config.get_str(ENV)


def check(point: str, windows: Optional[Sequence[int]] = None) -> None:
    """Fire any armed fault for `point`.  `windows`: the window/job
    indices in the batch being submitted (run points only).  No-op when
    RACON_TPU_FAULT is unset; raises ValueError on a malformed spec."""
    assert point in KNOWN_POINTS, point
    plan = _plan()
    if plan is not None:
        plan.check(point, windows)


def reset() -> None:
    """Re-arm the plan (fresh counters).  Called by the polisher
    constructors so consecutive runs fire deterministically."""
    global _cached_env, _cached_plan
    with _LOCK:
        _cached_env = None
        _cached_plan = None


def validate_env() -> None:
    """Eagerly parse RACON_TPU_FAULT; raises ValueError when malformed.
    The CLI calls this up front so a bad spec is a single-line error."""
    env = config.get_str(ENV)
    if env:
        parse_spec(env)
