"""Memory budget: RSS watermarks, backpressure, and working-set spill.

Every other robustness organ in this package reacts to *events* —
faults, hangs, crashes.  Memory is the resource that kills genome-scale
runs without ever raising: the process just grows until the kernel's
OOM killer takes it.  This module makes memory a first-class budget:

* ``MemoryBudget`` samples the process RSS (``/proc/self/status``
  VmRSS, falling back to ``resource.getrusage``) against
  ``RACON_TPU_MEM_BUDGET_MB`` and classifies it into three levels::

      ok ──▶ soft (RACON_TPU_MEM_SOFT_FRAC × budget) ──▶ hard
                                      (RACON_TPU_MEM_HARD_FRAC × budget)

* the **soft watermark** is backpressure: the streaming polisher stops
  reading ahead and parks materialized chunk working sets to a disk
  spill file (``park_bytes``/``load_spill``) until pressure clears;
* the **hard watermark** is degradation, not death: it latches, the
  flight recorder dumps (``mem_hard_watermark``), and the consumers
  take the pressure lattice edges — the phase pipeline collapses
  (``pipelined→sequential``, polisher.py) and the batch executor drains
  every pack inline (``batched→stream-sequential``, ops/batch_exec.py)
  — both recorded in the RunReport.  Output stays byte-identical: the
  edges only change scheduling, never results.

A registered-role watchdog thread (``mem-watchdog``) samples in the
background so pressure is noticed between synchronous polls; the
synchronous polls (one per chunk / per pack) are the ones that check
the ``mem.pressure`` fault point, so injected-fault invocation counting
stays deterministic.  ``mem.spill`` fires before each park (a raise
aborts that park — the working set simply stays in memory) and
``mem.oom`` sits in the distrib worker's polish path, where ``kill=1``
is a real OOM-style SIGKILL that the lease/journal machinery resumes.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, List, Optional, Tuple

from .. import config
from . import faults

#: Pressure levels, ordered.  ``at_least`` compares against this order.
LEVELS = ("ok", "soft", "hard")


def rss_mb() -> float:
    """Current resident set size in MiB (VmRSS; falls back to the peak
    counter on platforms without /proc)."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        pass
    return peak_rss_mb()


def peak_rss_mb() -> float:
    """Peak resident set size of this process in MiB (ru_maxrss is KiB
    on Linux)."""
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except (ImportError, OSError):
        return 0.0


def at_least(level: str, floor: str) -> bool:
    """True when `level` is at or above `floor` in the pressure order."""
    return LEVELS.index(level) >= LEVELS.index(floor)


class MemoryBudget:
    """RSS watermark tracker for one run.

    ``rss_source`` is injectable (tests drive the watermarks with a fake
    sampler); callbacks fire on upward level *transitions*, outside the
    internal lock, on whichever thread observed the crossing.
    """

    def __init__(self, budget_mb: int, *, soft_frac: float = 0.8,
                 hard_frac: float = 0.95,
                 rss_source: Optional[Callable[[], float]] = None,
                 on_soft: Optional[Callable[[], None]] = None,
                 on_hard: Optional[Callable[[], None]] = None):
        self.budget_mb = max(0, int(budget_mb))
        self.soft_mb = self.budget_mb * soft_frac
        self.hard_mb = self.budget_mb * hard_frac
        self._rss = rss_source or rss_mb
        self._lock = threading.Lock()
        self._level = "ok"
        self._hard_latched = False
        self._peak_mb = 0.0
        self._on_soft: List[Callable[[], None]] = (
            [on_soft] if on_soft else [])
        self._on_hard: List[Callable[[], None]] = (
            [on_hard] if on_hard else [])
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- classification ---------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.budget_mb > 0

    def on_soft(self, cb: Callable[[], None]) -> None:
        with self._lock:
            self._on_soft.append(cb)

    def on_hard(self, cb: Callable[[], None]) -> None:
        with self._lock:
            self._on_hard.append(cb)

    def poll(self, *, fault_check: bool = True) -> str:
        """Sample RSS, classify, and fire watermark callbacks on upward
        transitions.  Synchronous per-chunk/per-pack polls check the
        ``mem.pressure`` fault point (a raise is absorbed as a forced
        hard breach — the deterministic pressure drill); the watchdog
        thread polls with ``fault_check=False`` so fault invocation
        counting stays on the deterministic synchronous schedule."""
        if not self.enabled:
            return "ok"
        forced = None
        if fault_check and faults.active_spec():
            try:
                faults.check("mem.pressure")
            except Exception as e:  # noqa: BLE001 — injected fault =
                # forced hard breach (the deterministic pressure drill)
                forced = e
        cur = float(self._rss())
        with self._lock:
            self._peak_mb = max(self._peak_mb, cur)
            prev = self._level
            if forced is not None or cur >= self.hard_mb:
                level = "hard"
            elif cur >= self.soft_mb:
                level = "soft"
            else:
                level = "ok"
            self._level = level
            newly_hard = level == "hard" and not self._hard_latched
            if newly_hard:
                self._hard_latched = True
            soft_cbs = list(self._on_soft)
            hard_cbs = list(self._on_hard)
        if level != prev and at_least(level, "soft"):
            from .. import obs
            obs.event("mem.pressure", level=level, rss_mb=round(cur, 1),
                      budget_mb=self.budget_mb,
                      forced=bool(forced is not None))
            obs.count(f"mem.{level}_watermark")
        if newly_hard:
            from ..obs import flight
            flight.dump("mem_hard_watermark", rss_mb=round(cur, 1),
                        budget_mb=self.budget_mb,
                        forced=bool(forced is not None))
            for cb in hard_cbs:
                cb()
        if level == "soft" and prev == "ok":
            for cb in soft_cbs:
                cb()
        return level

    def level(self) -> str:
        """Last classified level (no sampling)."""
        with self._lock:
            return self._level

    def hard_latched(self) -> bool:
        """True once the hard watermark has ever been crossed this run."""
        with self._lock:
            return self._hard_latched

    def peak_mb(self) -> float:
        """Highest sampled RSS this run (MiB); 0.0 before the first
        poll.  ``peak_rss_mb()`` is the kernel's authoritative number —
        this one is what the watchdog actually observed."""
        with self._lock:
            return self._peak_mb

    # -- watchdog ---------------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> None:
        """Start the background sampler (no-op when unbudgeted)."""
        if not self.enabled or self._thread is not None:
            return
        if interval_s is None:
            interval_s = max(0.01,
                             config.get_int("RACON_TPU_MEM_POLL_MS") / 1e3)
        self._stop.clear()
        t = threading.Thread(target=self._watch, args=(interval_s,),
                             name="mem-watchdog", daemon=True)
        self._thread = t
        t.start()

    def _watch(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.poll(fault_check=False)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None


# --------------------------------------------------------------------------
# process-global budget (one per run, rebuilt by polisher.reset_run_state)
# --------------------------------------------------------------------------

_LOCK = threading.Lock()
_ACTIVE: Optional[MemoryBudget] = None


def configure() -> MemoryBudget:
    """(Re)build the process budget from the environment and start its
    watchdog.  Called from ``polisher.reset_run_state`` so every run
    starts with fresh watermark state, like ``faults.reset``."""
    global _ACTIVE
    b = MemoryBudget(
        budget_mb=config.get_int("RACON_TPU_MEM_BUDGET_MB"),
        soft_frac=config.get_float("RACON_TPU_MEM_SOFT_FRAC"),
        hard_frac=config.get_float("RACON_TPU_MEM_HARD_FRAC"))
    with _LOCK:
        old, _ACTIVE = _ACTIVE, b
    if old is not None:
        old.stop()
    b.start()
    return b


def active() -> Optional[MemoryBudget]:
    """The current run's budget (None before the first configure)."""
    with _LOCK:
        return _ACTIVE


def poll(*, fault_check: bool = True) -> str:
    """Synchronous pressure poll on the active budget ('ok' when
    unbudgeted)."""
    b = active()
    return b.poll(fault_check=fault_check) if b is not None else "ok"


def level() -> str:
    b = active()
    return b.level() if b is not None else "ok"


def hard_latched() -> bool:
    b = active()
    return b.hard_latched() if b is not None else False


def budget_mb() -> int:
    b = active()
    return b.budget_mb if b is not None else 0


def reset() -> None:
    """Stop the watchdog and drop the budget (tests / teardown)."""
    global _ACTIVE
    with _LOCK:
        old, _ACTIVE = _ACTIVE, None
    if old is not None:
        old.stop()


# --------------------------------------------------------------------------
# working-set spill
# --------------------------------------------------------------------------

def spill_dir(fallback: str) -> str:
    """The directory parked working sets go to:
    ``RACON_TPU_MEM_SPILL_DIR`` or the caller's per-run fallback."""
    return config.get_str("RACON_TPU_MEM_SPILL_DIR") or fallback


def park_bytes(payloads: List[Tuple[str, bytes]], dir_path: str,
               tag: str) -> Optional[str]:
    """Park named byte buffers to one spill file; returns its path, or
    None when the park was aborted (``mem.spill`` fault or I/O error) —
    the caller then simply keeps its in-memory buffers.  The format is a
    JSON header line of ``[name, length]`` pairs followed by the
    concatenated blobs."""
    try:
        faults.check("mem.spill")
    except Exception:  # noqa: BLE001 — injected fault = aborted park;
        # the caller keeps its in-memory buffers
        from .. import obs
        obs.count("mem.spill_faults")
        return None
    path = os.path.join(dir_path, f"spill.{tag}.{os.getpid()}.bin")
    try:
        os.makedirs(dir_path, exist_ok=True)
        header = json.dumps([[name, len(blob)] for name, blob in payloads])
        with open(path, "wb") as f:
            f.write(header.encode() + b"\n")
            for _name, blob in payloads:
                f.write(blob)
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    from .. import obs
    obs.event("mem.spill", tag=tag,
              bytes=sum(len(b) for _n, b in payloads))
    obs.count("mem.spills")
    return path


def load_spill(path: str) -> List[Tuple[str, bytes]]:
    """Load parked buffers back and delete the spill file.  Raises
    OSError/ValueError on a torn spill file — the caller treats that
    like any other torn-input chunk."""
    with open(path, "rb") as f:
        header = json.loads(f.readline().decode())
        out = []
        for name, length in header:
            blob = f.read(int(length))
            if len(blob) != int(length):
                raise ValueError(f"torn spill file {path!r}: "
                                 f"{name} expected {length} bytes, "
                                 f"got {len(blob)}")
            out.append((str(name), blob))
    os.unlink(path)
    return out
