"""Crash-safe, append-only journal of served window/overlap results.

A polishing run that is 90% done must survive a SIGKILL: the drivers
append one JSONL record per served unit as it is installed, so a
`--resume-journal` run replays everything already served and recomputes
only the rest, reproducing byte-identical output (the host and device
paths are deterministic under a fixed environment).

Format (one JSON object per line; keys sorted for stable bytes):

    {"fingerprint": "<sha256>", "kind": "header", "version": 1}
    {"contig": 0, "i": 17, "kind": "window", "payload": "ACGT...",
     "polished": true, "rank": 3, "sha": "<sha256(payload)[:16]>",
     "tier": "ls"}
    {"cigar": "120=1X...", "i": 4, "kind": "cigar", "tier": "hirschberg"}

Durability: every append is flushed and fsynced
(``RACON_TPU_JOURNAL_FSYNC``, default on) so a crash can lose at most
the record being written.  A journal write failure is degradation, not
death: the journal disarms itself with a warning and the polish
continues unjournaled.

Torn-write tolerance: replay scans from the top and stops at the first
incomplete, unparseable, or hash-mismatched line; the file is truncated
back to the last good byte before appending resumes.  A torn tail is
expected (that is what a SIGKILL mid-write produces), never fatal.

Input fingerprint: sha256 over the input files' bytes, the polish
parameters, and the backend.  Replaying records produced from different
inputs or parameters would corrupt output silently, so a mismatched
journal is refused — `--resume-journal` errors out (exit 1), the
`RACON_TPU_JOURNAL` auto-resume path warns and starts fresh.  Thread
count is excluded (it cannot change output); the serving environment
(kernel tiers, batch size, ...) is deliberately excluded too — a resume
may legally mix journaled device windows with recomputed ones, exactly
like an uninterrupted run mixes tiers when the lattice degrades.

Host-side alignment CIGARs are *not* journaled (the native engine has no
per-job getter and recomputes them deterministically); only device-
served CIGARs are.  Consensus records cover every window: device tiers,
host fallback, and backbone passthrough.

The `journal.append` / `journal.replay` fault points make both seams
deterministically testable — including `kill=1`, which turns an armed
append into the mid-run SIGKILL the subsystem exists to survive.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Set

from .. import config, fingerprint, obs
from . import faults

VERSION = fingerprint.JOURNAL_VERSION


class JournalError(RuntimeError):
    """A journal cannot be used for this run (fingerprint mismatch)."""


def _sha16(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()[:16]


def input_fingerprint(paths: Sequence[str], params: dict,
                      backend: str) -> str:
    """Identity of one polishing problem — the `journal` site of the
    unified fingerprint registry (racon_tpu/fingerprint.py), kept under
    its historical name for the drivers and tests that import it."""
    return fingerprint.journal_fingerprint(paths, params, backend)


@dataclass
class WindowRecord:
    payload: bytes
    polished: bool
    tier: str


@dataclass
class CigarRecord:
    cigar: str
    tier: str


class Journal:
    """One run's append handle + whatever a previous run left behind."""

    def __init__(self, path: str, fingerprint: str, *,
                 resume: bool = False, on_mismatch: str = "error"):
        assert on_mismatch in ("error", "fresh")
        self.path = path
        self.fingerprint = fingerprint
        self.resumed = False
        self.dead = False
        self.appended = 0
        self.windows: Dict[int, WindowRecord] = {}
        self.cigars: Dict[int, CigarRecord] = {}
        self._fsync = config.get_raw("RACON_TPU_JOURNAL_FSYNC") != "0"
        self._f = None
        if resume and os.path.exists(path) and os.path.getsize(path) > 0:
            self._open_resume(on_mismatch)
        else:
            self._open_fresh()

    # -- opening -----------------------------------------------------------
    def _open_fresh(self) -> None:
        self._f = open(self.path, "wb")
        header = {"fingerprint": self.fingerprint, "kind": "header",
                  "version": VERSION}
        self._f.write((json.dumps(header, sort_keys=True) + "\n").encode())
        self._f.flush()
        if self._fsync:
            os.fsync(self._f.fileno())

    def _open_resume(self, on_mismatch: str) -> None:
        good_end = 0
        header_ok = False
        with open(self.path, "rb") as f:
            for raw in f:
                if not raw.endswith(b"\n"):
                    break            # torn tail: crash mid-write
                try:
                    rec = json.loads(raw.decode("utf-8"))
                    if not isinstance(rec, dict):
                        break
                    if not header_ok:
                        if (rec.get("kind") != "header"
                                or rec.get("version") != VERSION):
                            break
                        if rec.get("fingerprint") != self.fingerprint:
                            if on_mismatch == "error":
                                raise JournalError(
                                    f"journal {self.path} was written for "
                                    f"different inputs/parameters "
                                    f"(fingerprint "
                                    f"{str(rec.get('fingerprint'))[:12]}… != "
                                    f"{self.fingerprint[:12]}…); refusing "
                                    f"to resume — rerun without "
                                    f"--resume-journal to start fresh")
                            print(f"[racon_tpu::journal] WARNING: "
                                  f"{self.path} belongs to different "
                                  f"inputs/parameters; starting fresh",
                                  file=sys.stderr)
                            self.windows.clear()
                            self.cigars.clear()
                            self._open_fresh()
                            return
                        header_ok = True
                    elif rec.get("kind") == "window":
                        payload = str(rec["payload"]).encode("latin-1")
                        if _sha16(payload) != rec.get("sha"):
                            break    # corrupt record: stop trusting here
                        self.windows[int(rec["i"])] = WindowRecord(
                            payload, bool(rec.get("polished")),
                            str(rec.get("tier", "?")))
                    elif rec.get("kind") == "cigar":
                        self.cigars[int(rec["i"])] = CigarRecord(
                            str(rec["cigar"]), str(rec.get("tier", "?")))
                    # unknown kinds from a newer writer: skip, keep offset
                except JournalError:
                    raise
                except Exception:  # noqa: BLE001 — any undecodable line
                    # ends the trusted prefix (torn/corrupt tail)
                    break
                good_end += len(raw)
        if not header_ok:
            # unreadable or foreign file: refuse to silently clobber it
            # on an explicit resume only if it parsed as a mismatched
            # journal (handled above); an empty/torn header is ours to
            # restart
            self.windows.clear()
            self.cigars.clear()
            self._open_fresh()
            return
        size = os.path.getsize(self.path)
        if good_end < size:
            print(f"[racon_tpu::journal] WARNING: {self.path}: dropping "
                  f"{size - good_end} torn trailing byte(s) "
                  f"(crash mid-append)", file=sys.stderr)
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        self._f = open(self.path, "ab")
        self.resumed = True

    # -- appending ---------------------------------------------------------
    def _append(self, rec: dict) -> None:
        if self.dead or self._f is None:
            return
        try:
            faults.check("journal.append")
            self._f.write(
                (json.dumps(rec, sort_keys=True) + "\n").encode("utf-8"))
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self.appended += 1
        except Exception as e:  # noqa: BLE001 — durability must never
            # fail the polish; a dead journal is a degraded run, not a
            # failed one
            self.dead = True
            print(f"[racon_tpu::journal] WARNING: journal write failed "
                  f"({type(e).__name__}: {e}); continuing without "
                  f"journaling", file=sys.stderr)

    def append_window(self, i: int, contig: int, rank: int, tier: str,
                      consensus: bytes, polished: bool) -> None:
        self._append({"contig": int(contig), "i": int(i), "kind": "window",
                      "payload": consensus.decode("latin-1"),
                      "polished": bool(polished), "rank": int(rank),
                      "sha": _sha16(consensus), "tier": tier})

    def append_cigar(self, job: int, tier: str, cigar: str) -> None:
        self._append({"cigar": cigar, "i": int(job), "kind": "cigar",
                      "tier": tier})

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def __del__(self):
        self.close()


# --------------------------------------------------------------------------
# replay helpers shared by the CPU polisher and the device drivers
# --------------------------------------------------------------------------

def replay_windows(pipeline, journal: Optional[Journal], n: int,
                   report=None) -> Set[int]:
    """Install journaled consensus payloads; returns the replayed window
    indices.  A poisoned replay (the `journal.replay` fault point)
    degrades to recomputing everything — correctness never depends on
    the journal."""
    if journal is None or not journal.windows:
        return set()
    try:
        faults.check("journal.replay", sorted(journal.windows))
    except Exception as e:  # noqa: BLE001 — replay seam: a bad journal
        # must degrade to a fresh computation, not abort the polish
        print(f"[racon_tpu::journal] WARNING: replay failed "
              f"({type(e).__name__}: {e}); recomputing all windows",
              file=sys.stderr)
        if report is not None:
            report.record_failure("journal", e)
        return set()
    done: Set[int] = set()
    with obs.span("journal.replay", kind="windows") as sp:
        for i in sorted(journal.windows):
            if not 0 <= i < n:
                continue         # defensive: fingerprint should prevent
            rec = journal.windows[i]
            # determinism: replayed bytes are journal records
            # fingerprint-matched to this exact run's inputs (see the
            # `journal` site in racon_tpu/fingerprint.py)
            pipeline.set_consensus(i, rec.payload, rec.polished)
            done.add(i)
            if report is not None:
                report.record_served("journal")
        sp.set(replayed=len(done))
    return done


def replay_cigars(pipeline, journal: Optional[Journal], n: int,
                  report=None) -> Set[int]:
    """Install journaled device CIGARs; returns the replayed job
    indices (they are excluded from device batching, and the native
    host pass skips any job whose CIGAR is already set)."""
    if journal is None or not journal.cigars:
        return set()
    try:
        faults.check("journal.replay", sorted(journal.cigars))
    except Exception as e:  # noqa: BLE001 — replay seam (see above)
        print(f"[racon_tpu::journal] WARNING: cigar replay failed "
              f"({type(e).__name__}: {e}); realigning all jobs",
              file=sys.stderr)
        if report is not None:
            report.record_failure("journal", e)
        return set()
    done: Set[int] = set()
    with obs.span("journal.replay", kind="cigars") as sp:
        for job in sorted(journal.cigars):
            if not 0 <= job < n:
                continue
            # determinism: replayed CIGARs are journal records
            # fingerprint-matched to this exact run's inputs (see the
            # `journal` site in racon_tpu/fingerprint.py)
            pipeline.set_job_cigar(job, journal.cigars[job].cigar)
            done.add(job)
            if report is not None:
                report.record_served("journal")
        sp.set(replayed=len(done))
    return done


class CigarTap:
    """Pipeline proxy that journals each CIGAR as an engine installs it.

    The device aligners (`align.run_jobs` / `align_pallas.run_jobs`)
    install results through `pipeline.set_job_cigar`; wrapping the
    pipeline taps that one seam without the engines knowing the journal
    exists.  Everything else delegates untouched."""

    def __init__(self, pipeline, journal: Journal, tier: str):
        self._pipeline = pipeline
        self._journal = journal
        self._tier = tier

    def __getattr__(self, name):
        return getattr(self._pipeline, name)

    def set_job_cigar(self, job: int, cigar: str) -> None:
        self._pipeline.set_job_cigar(job, cigar)
        self._journal.append_cigar(job, self._tier, cigar)
