#!/bin/sh
# Repo check runner: first-party static analysis + generic lint + types +
# native hygiene.  Degrades gracefully: third-party tools that are not
# installed are reported and skipped (the container bakes a fixed
# toolchain; nothing is pip-installed on the fly), so the exit code
# reflects only checks that actually ran.
#
# Usage: tools/check.sh [--fast]
#   --fast   skip the jaxpr audit and the native -Werror gate
set -u

root=$(cd "$(dirname "$0")/.." && pwd)
cd "$root" || exit 1
fast=${1:-}

fail=0
run() {  # run <name> <cmd...>
    name=$1; shift
    echo "== $name"
    if "$@"; then
        echo "   ok"
    else
        echo "   FAIL: $name"
        fail=1
    fi
}

skip() {
    echo "== $1"
    echo "   skipped: $2"
}

# 1. First-party analyzer: repo-specific TPU invariants + jaxpr audit.
if [ "$fast" = "--fast" ]; then
    run "racon_tpu.analysis (lint only)" \
        env JAX_PLATFORMS=cpu python -m racon_tpu.analysis --no-jaxpr
else
    run "racon_tpu.analysis" \
        env JAX_PLATFORMS=cpu python -m racon_tpu.analysis
fi

# 1b. Focused lint over the preemption-tolerance modules: these carry
#     the crash-resume contract (journal/watchdog/hw_session) and the
#     drivers that feed the journal, so their fault points / knob docs /
#     broad-except waivers must stay lint-clean even when a full-tree
#     run is baselined.
run "racon_tpu.analysis (resilience focus)" \
    env JAX_PLATFORMS=cpu python -m racon_tpu.analysis --paths \
        racon_tpu/resilience/journal.py \
        racon_tpu/resilience/watchdog.py \
        racon_tpu/resilience/faults.py \
        racon_tpu/resilience/lattice.py \
        racon_tpu/tools/hw_session.py \
        racon_tpu/ops/poa_driver.py \
        racon_tpu/ops/align_driver.py \
        racon_tpu/polisher.py

# 1c. Focused lint over the observability layer: the tracer must stay on
#     the monotonic clock (wall-clock rule scopes racon_tpu/obs/), its
#     knobs must stay documented, and the instrumented seams
#     (kernel_cache, report) must keep their invariants.
run "racon_tpu.analysis (obs focus)" \
    env JAX_PLATFORMS=cpu python -m racon_tpu.analysis --paths \
        racon_tpu/obs/__init__.py \
        racon_tpu/obs/tracer.py \
        racon_tpu/obs/metrics.py \
        racon_tpu/obs/__main__.py \
        racon_tpu/ops/kernel_cache.py \
        racon_tpu/resilience/report.py

# 1d. Concurrency & contract audits: lock discipline over inferred
#     thread roles, lock-order acyclicity, lattice/fault-point drill
#     coverage, wire-protocol field agreement.  (A full-tree run in 1
#     already includes these; this focused invocation keeps them green
#     even under --fast / a baselined full run.)
run "racon_tpu.analysis (concurrency + contracts)" \
    env JAX_PLATFORMS=cpu python -m racon_tpu.analysis \
        --concurrency --contracts

# 1e. Determinism taint audit: the byte-identity contract (no
#     cost-only knob value may reach the consensus/CIGAR install
#     seams; every complete fingerprint composition covers the
#     output-affecting domain), plus the seeded-mutant self-test —
#     each planted contract bug must be CAUGHT (non-zero exit).
run "racon_tpu.analysis (determinism)" \
    env JAX_PLATFORMS=cpu python -m racon_tpu.analysis --determinism
det_mutants() {
    for m in drop-input-bytes leak-pipeline-depth overkey-tier \
             drop-journal-waiver; do
        if env JAX_PLATFORMS=cpu python -m racon_tpu.analysis \
            --det-mutate "$m" > /dev/null; then
            echo "   determinism mutant $m: MISSED"
            return 1
        fi
    done
    return 0
}
run "racon_tpu.analysis (determinism mutants)" det_mutants

# 2. ruff (style + pyflakes), configured in pyproject.toml.
if command -v ruff >/dev/null 2>&1; then
    run "ruff" ruff check .
else
    skip "ruff" "not installed"
fi

# 3. mypy (type drift in the pure-Python drivers).
if command -v mypy >/dev/null 2>&1; then
    run "mypy" mypy
else
    skip "mypy" "not installed"
fi

# 4. Native hygiene: -Wall -Wextra -Werror syntax gate (+clang-tidy when
#    available; the Makefile handles that probe itself).
if [ "$fast" = "--fast" ]; then
    skip "native lint" "--fast"
else
    run "native lint" make -C racon_tpu/native lint
fi

# 5. Sanitizer matrix: instrumented native builds + the rt_stress race
#    harness under TSan/ASan/UBSan.  Each mode is probed by compiling a
#    trivial program first — a toolchain without that sanitizer runtime
#    (common on minimal images) skips with a notice instead of failing.
san_probe() {  # san_probe <flag>  -> 0 when the toolchain supports it
    probe_dir=$(mktemp -d) || return 1
    printf 'int main(void){return 0;}\n' > "$probe_dir/probe.c"
    ${CXX:-g++} "$1" "$probe_dir/probe.c" -o "$probe_dir/probe" \
        >/dev/null 2>&1
    rc=$?
    rm -rf "$probe_dir"
    return $rc
}

if [ "$fast" = "--fast" ]; then
    skip "sanitizers (asan/tsan/ubsan)" "--fast"
else
    for mode in asan tsan ubsan; do
        case $mode in
            asan)  flag=-fsanitize=address ;;
            tsan)  flag=-fsanitize=thread ;;
            ubsan) flag=-fsanitize=undefined ;;
        esac
        if san_probe "$flag"; then
            run "native $mode (rt_test + rt_stress)" \
                make -C racon_tpu/native "$mode"
        else
            skip "native $mode" "toolchain lacks $flag"
        fi
    done
fi

exit $fail
