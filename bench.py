"""Benchmark: polished Mbp/sec on the device path vs the host oracle path.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Workload: a synthetic ONT-like polishing job (default 0.5 Mbp genome, 30x
reads at ~11% error, PAF overlaps from simulation truth, window=500 — the
shape of BASELINE.json's E. coli config, scaled to this machine; set
RACON_TPU_BENCH_MBP to change the size). value = polished megabases per
second of end-to-end wall time (parse -> polished FASTA) on the accelerated
path; vs_baseline = speedup over the host CPU path measured on the same
machine (the reference's comparison axis: accelerated backend vs its CPU
SPOA path).

RACON_TPU_BENCH_INPUT=sam switches the overlaps to SAM with ground-truth
CIGARs (the reference's SAM scenarios): no alignment phase, so the number
isolates the consensus engines. The recorded default stays PAF.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from racon_tpu import config  # noqa: E402 — central knob registry

MBP = config.get_float("RACON_TPU_BENCH_MBP")
INPUT = config.get_str("RACON_TPU_BENCH_INPUT")
# 'ont' (default): ~8 kb reads at ~11% error — BASELINE config 2's shape.
# 'sr': 150 bp reads at ~1% error — the short-read (chr20-class,
# BASELINE config 4) regime: NGS-type windows (no trim), ~130 shallow
# layers per window instead of ~30 long ones.
PROFILE = config.get_str("RACON_TPU_BENCH_PROFILE")
PROFILES = {
    "ont": dict(mean_read=8000, sub=0.05, ins=0.03, dele=0.03),
    "sr": dict(mean_read=150, sub=0.008, ins=0.001, dele=0.001),
}
COVERAGE = 30
ARGS = dict(window_length=500, quality_threshold=10.0, error_threshold=0.3,
            match=5, mismatch=-4, gap=-8, num_threads=1)

if PROFILE not in PROFILES:
    raise SystemExit(f"RACON_TPU_BENCH_PROFILE must be one of "
                     f"{sorted(PROFILES)}, got {PROFILE!r}")
_WORKLOAD = ("synthetic ONT" if PROFILE == "ont"
             else "synthetic short-read")


def dataset(mbp: float = MBP):
    import hashlib
    import inspect
    import shutil

    from racon_tpu.tools import simulate

    # Cache keyed by size/coverage/profile (name AND parameter values —
    # tuning a PROFILES entry must not silently reuse a dataset generated
    # with the old parameters) plus the generator source, so simulator
    # changes invalidate stale data; built in a temp dir and renamed into
    # place so concurrent bench runs never see half-written files.
    src_tag = hashlib.sha256(
        (inspect.getsource(simulate) +
         repr(sorted(PROFILES[PROFILE].items()))).encode()).hexdigest()[:12]
    ptag = "" if PROFILE == "ont" else f"_{PROFILE}"
    outdir = f"/tmp/racon_tpu_bench_{mbp}mbp_{COVERAGE}x{ptag}_{src_tag}"
    if not os.path.isdir(outdir):
        tmpdir = outdir + f".tmp{os.getpid()}"
        shutil.rmtree(tmpdir, ignore_errors=True)
        paths = simulate.generate(tmpdir, mbp=mbp, coverage=COVERAGE,
                                  **PROFILES[PROFILE])
        try:
            os.rename(tmpdir, outdir)
        except OSError:
            shutil.rmtree(tmpdir, ignore_errors=True)  # another run won
    ovl = "overlaps.sam" if INPUT == "sam" else "overlaps.paf"
    return {k: os.path.join(outdir, f)
            for k, f in (("reads", "reads.fastq"),
                         ("overlaps", ovl),
                         ("draft", "draft.fasta"))}


def observed_window_lengths(draft_path: str, w: int) -> set:
    """Every window length the consensus phase will actually derive —
    now shared with the pipelined polisher's warm-up thread, so the one
    implementation lives next to warm_geometries (ops/poa_driver.py)."""
    from racon_tpu.ops.poa_driver import observed_window_lengths as owl

    return owl(draft_path, w)


def _forced_device() -> bool:
    """RACON_TPU_BENCH_FORCE_DEVICE=1: treat the current backend as the
    device — a CPU-backend dry run of the exact healthy-path flow (probe,
    warm-up, measure, log). Entries logged under the override are marked
    forced and never cited as device evidence."""
    return config.get_bool("RACON_TPU_BENCH_FORCE_DEVICE")


def device_healthy(timeout_s: int = 120) -> bool:
    """The axon TPU tunnel can wedge (device ops then hang forever); probe
    it in a subprocess so a dead tunnel can't hang the benchmark."""
    if _forced_device():
        return True
    probe = ("import jax, jax.numpy as jnp; "
             "x = jnp.ones((128, 128)); print(float((x @ x).sum()))")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, timeout=timeout_s)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def pallas_compiles(timeout_s: int = 900):
    """Bounded probe: compile + run the fused POA kernel at the production
    w=500 geometry in a subprocess. A pathological Mosaic compile would
    otherwise hang the whole bench (and can wedge the tunnel if killed
    mid-flight — hence bounded probes, whose results also warm the
    persistent compilation cache for the real run).

    Mirrors the driver's degrade lattice: returns the first working pallas
    tier ('ls' then 'v2'), or None if neither compiles — the in-process
    lattice handles compile *errors*, but only a subprocess bound can
    handle a compile *hang*."""
    from racon_tpu.ops.poa_driver import _kernel_kind
    requested = _kernel_kind()  # validates RACON_TPU_POA_KERNEL up front
    kinds = ["ls", "v2"] if requested == "ls" else ["v2"]
    for kind in kinds:
        force = ("import sys; sys.path.insert(0, %r)\n"
                 "from __graft_entry__ import _force_cpu; _force_cpu(1)\n"
                 % os.path.dirname(os.path.abspath(__file__))
                 if _forced_device() else "")
        probe = force + (
            "import numpy as np, jax, sys\n"
            "sys.path.insert(0, %r)\n"
            "from racon_tpu.ops import poa, poa_driver\n"
            "import __graft_entry__ as g\n"
            "kind = %r\n"
            "cfg = poa_driver.make_config(500, 8, 5, -4, -8)\n"
            "B = poa_driver._device_batch(kind)\n"
            "fn = poa_driver._build_kernel(cfg, B, True, kind)\n"
            "packed = g._example_batch(cfg, B, np.random.default_rng(0))\n"
            "out = poa_driver._submit(fn, packed, True)\n"
            "jax.block_until_ready(out)\n"
            "cb, cc, cl, fl = poa_driver._unpack(out, True)\n"
            "print('pallas-ok', kind, cl.ravel().tolist())\n"
        ) % (os.path.dirname(os.path.abspath(__file__)), kind)
        try:
            r = subprocess.run([sys.executable, "-c", probe],
                               capture_output=True, timeout=timeout_s,
                               text=True)
            if r.returncode == 0:
                return kind
            print(f"[bench] pallas '{kind}' probe failed:",
                  r.stderr[-500:], file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"[bench] pallas '{kind}' probe exceeded {timeout_s}s",
                  file=sys.stderr)
    print("[bench] no pallas tier compiles; benching the XLA device "
          "kernel instead", file=sys.stderr)
    return None


def aligner_compiles(timeout_s: int = 600):
    """Bounded probe for the phase-1 device aligner (PAF input only).

    With RACON_TPU_DEVICE_ALIGNER=auto the measured run serves alignment
    through the Hirschberg Pallas engine on TPU; its three kernel shapes
    (forward/backward edge, base traceback) have never compiled on real
    hardware, and a Mosaic compile hang inside the measured run would eat
    the healthy-tunnel window. Probe one representative pair in a bounded
    subprocess (same philosophy as pallas_compiles). The engine choice
    (incl. the platform check behind 'auto') resolves INSIDE the probe
    subprocess — the parent must not touch jax.devices() before the probe
    runs, or the parent would hold the chip the probe needs (all this
    file's probes run before any parent-process device op).

    Returns 'hirschberg' when the engine works (or is explicitly forced:
    an explicit RACON_TPU_DEVICE_ALIGNER=hirschberg is honored even past
    a failed probe — the in-process degrade lattice handles errors);
    'host' when the auto-selected engine fails/hangs (caller pins the
    host aligner for the measured run); None when the bench doesn't need
    alignment (SAM input) or the engine resolves to host/xla anyway."""
    if INPUT == "sam":
        return None
    env = config.get_str("RACON_TPU_DEVICE_ALIGNER")
    if _forced_device() or env not in ("auto", "", "hirschberg"):
        return None
    forced = env == "hirschberg"
    probe = (
        "import sys, random\n"
        "sys.path.insert(0, %r)\n"
        "from racon_tpu.ops.align_driver import _engine\n"
        "if _engine() != 'hirschberg':\n"
        "    print('engine-host')\n"
        "    sys.exit(0)\n"
        "import numpy as np\n"
        "from racon_tpu.ops import align_pallas\n"
        "from racon_tpu.ops.encoding import encode\n"
        "rng = random.Random(0)\n"
        "q = bytes(rng.choice(b'ACGT') for _ in range(700))\n"
        "t = bytes(c for c in q if rng.random() > 0.05)\n"
        "enc = lambda s: encode(np.frombuffer(s, np.uint8)).astype(np.int32)\n"
        "ops = align_pallas.align_pairs([(enc(q), enc(t))])\n"
        "assert ops[0] is not None and len(ops[0]) >= len(q)\n"
        "print('hirschberg-ok', len(ops[0]))\n"
    ) % os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, timeout=timeout_s,
                           text=True)
        if r.returncode == 0:
            if "engine-host" in r.stdout:
                return None
            return "hirschberg"
        print("[bench] hirschberg aligner probe failed:",
              r.stderr[-500:], file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"[bench] hirschberg aligner probe exceeded {timeout_s}s",
              file=sys.stderr)
    if forced:
        print("[bench] RACON_TPU_DEVICE_ALIGNER=hirschberg is explicit; "
              "keeping it despite the failed probe", file=sys.stderr)
        return "hirschberg"
    return "host"


def _aligner_log_value(aligner):
    """What actually served phase 1 in the measured run, for the durable
    log: the probe outcome when one ran, else the env-selected engine —
    an explicit xla/1 must not be misrecorded as 'host'."""
    if INPUT == "sam":
        return "n/a"
    if aligner:
        return aligner
    env = config.get_str("RACON_TPU_DEVICE_ALIGNER")
    if env in ("1", "xla"):
        return "xla"
    if env == "hirschberg":
        return "hirschberg"
    return "host"


LOG_PATH = config.get_raw("RACON_TPU_BENCH_LOG") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "docs", "device_bench_log.jsonl")


def log_device_measurement(entry: dict) -> None:
    """Append a successful on-device measurement to the committed log.

    The axon tunnel wedges for hours at a time; without a durable record a
    dead tunnel at measurement time erases real mid-round evidence."""
    try:
        entry = dict(entry, utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime()))
        path = LOG_PATH
        if _forced_device():
            # dry runs never touch the committed device-evidence log
            entry["forced"] = True
            path = LOG_PATH + ".dryrun"
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError as e:
        # An installed/read-only layout must not silently drop the one
        # durable piece of device evidence (set RACON_TPU_BENCH_LOG).
        print(f"[bench] WARNING: could not append device log {LOG_PATH}: "
              f"{e}", file=sys.stderr)


def phase_wall(report_summary) -> dict:
    """Per-phase wall seconds (summed over serving tiers) from a
    RunReport.summary() dict — the bench's compact phase breakdown.
    Entries without per-tier walls (pre-observability writers) yield
    {}."""
    out = {}
    if isinstance(report_summary, dict):
        for phase, rep in report_summary.items():
            if isinstance(rep, dict) and isinstance(rep.get("wall_s"),
                                                    dict):
                out[phase] = round(sum(rep["wall_s"].values()), 4)
    return out


def pack_split(report_summary) -> dict:
    """Per-phase host-pack vs kernel wall split from a RunReport.summary()
    dict — the shared executor (racon_tpu/ops/batch_exec.py) stamps
    `pack_wall_s` / `kernel_wall_s` into each phase's extras.  VERDICT
    #7's feeder criterion (pack time < kernel time) is checkable from
    this stamp alone.  Entries predating the executor yield {}."""
    out = {}
    if isinstance(report_summary, dict):
        for phase, rep in report_summary.items():
            ex = rep.get("extra") if isinstance(rep, dict) else None
            if isinstance(ex, dict) and ("pack_wall_s" in ex
                                         or "kernel_wall_s" in ex):
                out[phase] = {"pack_wall_s": ex.get("pack_wall_s"),
                              "kernel_wall_s": ex.get("kernel_wall_s")}
    return out


def serial_steps_stamp(cm) -> dict:
    """Top-level predicted per-phase serial DP step totals from the
    `cost_model` stamp — the one number the column-compression /
    row-packing work moves, lifted out of the nested stamp so trend
    readers can diff it across log generations.  None when the run
    recorded no cost model (metrics disarmed, serve/distrib lanes,
    pre-cost-model writers)."""
    if not isinstance(cm, dict):
        return None
    out = {ph: row["serial_steps"]
           for ph, row in cm.get("phases", {}).items()
           if isinstance(row, dict) and "serial_steps" in row}
    return out or None


def band_stamp(snap):
    """Banded-DP evidence from the measured run's counter snapshot:
    per-phase banded cell totals and the verify-and-widen hit rate
    (``band.hits / band.jobs``), as a ``(cells_banded, band_hit_rate)``
    pair.  Both None when banding never engaged — RACON_TPU_BAND off,
    metrics disarmed, or no job narrow enough to band — which is a
    different claim from a measured rate of 0.0 (banding on, every
    band verified first try)."""
    c = snap.get("counters") if isinstance(snap, dict) else None
    jobs = (c or {}).get("band.jobs", 0)
    if not jobs:
        return None, None
    cells = {ph: c[key] for ph, key in (("align", "align.cells.banded"),
                                        ("poa", "poa.cells.banded"))
             if c.get(key)}
    return cells or None, round(c.get("band.hits", 0) / jobs, 4)


def mem_stamp(report_summary):
    """``(peak_rss_mb, budget_mb)`` from a RunReport.summary()'s
    ``memory`` phase (the resilience/budget.py accounting stamp);
    ``(None, None)`` when the run carried no memory accounting —
    "not measured", a different claim from a measured 0."""
    if isinstance(report_summary, dict):
        m = report_summary.get("memory")
        ex = m.get("extra") if isinstance(m, dict) else None
        if isinstance(ex, dict):
            return ex.get("peak_rss_mb"), ex.get("budget_mb")
    return None, None


def normalize_entry(e: dict) -> dict:
    """Reader-side honesty backfill for bench JSON entries/log lines.

    Older writers conflated "no device measurement" with "measured
    zero": a dead tunnel emitted ``vs_baseline: 0.0`` next to a
    ``[TPU UNREACHABLE ...]`` metric tag.  Current writers emit
    ``vs_baseline: null`` plus ``device_status: "unreachable"``; this
    helper lifts old entries to the same semantics so both generations
    parse identically downstream.  A measured 0.0 (device reachable,
    ratio genuinely zero) is left untouched.

    Also backfills ``phase_wall`` (per-phase wall seconds) for entries
    whose embedded report already carried per-tier walls but predate the
    explicit stamp, and ``cost_model: null`` for entries written before
    the analytic cost model existed — "no prediction recorded" parses
    the same for every log generation."""
    if not isinstance(e, dict):
        return e
    unreachable = (e.get("device_status") == "unreachable"
                   or "TPU UNREACHABLE" in str(e.get("metric", "")))
    if unreachable:
        e = dict(e, device_status="unreachable")
        if e.get("vs_baseline") == 0.0:
            e["vs_baseline"] = None
    if "phase_wall" not in e:
        pw = phase_wall(e.get("report"))
        if pw:
            e = dict(e, phase_wall=pw)
    if "cost_model" not in e:
        e = dict(e, cost_model=None)
    if "pack_split" not in e:
        # old logs: recover the split from the embedded report when the
        # executor stamped it there, else explicit null ("not measured")
        e = dict(e, pack_split=pack_split(e.get("report")) or None)
    if "serial_steps" not in e:
        # old logs: recover per-phase predicted step totals from the
        # embedded cost-model stamp when it carried them, else explicit
        # null ("not predicted")
        e = dict(e, serial_steps=serial_steps_stamp(e.get("cost_model")))
    if "cells_banded" not in e or "band_hit_rate" not in e:
        # entries written before banded DP existed: explicit nulls ("not
        # measured"), same semantics as a fresh run with banding off
        e = dict(e)
        e.setdefault("cells_banded", None)
        e.setdefault("band_hit_rate", None)
    if ("serve" in e or "distrib" in e) and "fleet" not in e:
        # fleet-lane entries written before the telemetry stamp
        # (per-worker walls, queueing p95, heartbeat staleness):
        # explicit null — "not scraped", same as a run with obs off
        e = dict(e, fleet=None)
    if ("serve" in e or "distrib" in e) and "pool" not in e:
        # entries written before the elastic pool existed: explicit null
        # ("no pool-size timeline"), same as a run with the fleet off
        e = dict(e, pool=None)
    if ("serve" in e or "distrib" in e) and "ledger" not in e:
        # entries written before the per-job latency ledger existed:
        # explicit null ("no stage decomposition recorded")
        e = dict(e, ledger=None)
    if ("serve" in e or "distrib" in e) and "slo" not in e:
        # entries written before the per-tenant SLO engine existed:
        # explicit null ("no burn-rate snapshot scraped")
        e = dict(e, slo=None)
    if "peak_rss_mb" not in e or "budget_mb" not in e:
        # entries written before the memory budget existed: recover the
        # pair from the embedded report's memory phase when the run
        # stamped one, else explicit nulls ("not measured")
        peak, bud = mem_stamp(e.get("report"))
        e = dict(e)
        e.setdefault("peak_rss_mb", peak)
        e.setdefault("budget_mb", bud)
    return e


def degraded_result(mbps_cpu: float, note: str = "") -> dict:
    """Bench JSON for a dead-tunnel run.  `vs_baseline` is null — there
    is NO device measurement — which is a different claim from a
    measured ratio of 0.0; `device_status` carries the machine-readable
    marker so readers don't have to parse the metric string."""
    return {
        "metric": f"polished Mbp/sec ({_WORKLOAD} {MBP} Mbp "
                  f"{COVERAGE}x, {INPUT.upper()}, w=500, end-to-end) "
                  f"[TPU UNREACHABLE: host path only{note}]",
        "value": round(mbps_cpu, 4),
        "unit": "Mbp/s",
        "vs_baseline": None,
        "device_status": "unreachable",
        # no device run: no prediction-vs-measured join, no
        # pack-vs-kernel wall split, no serial-step prediction —
        # explicit nulls keep normalize_entry a fixed point on fresh
        # entries
        "cost_model": None,
        "pack_split": None,
        "serial_steps": None,
        "cells_banded": None,
        "band_hit_rate": None,
        "peak_rss_mb": None,
        "budget_mb": None,
    }


def last_device_measurement():
    """Latest REAL device THROUGHPUT entry — forced dry-run entries and
    accuracy-only entries (golden re-pins, which carry no "value") never
    count; a malformed hand-edited line skips, it does not hide the
    rest."""
    entries = []
    try:
        with open(LOG_PATH) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if not e.get("forced") and "value" in e:
                    entries.append(normalize_entry(e))
    except OSError:
        return None
    return entries[-1] if entries else None


def _backend_platform():
    """Measured backend platform for cost-model profile resolution
    ('auto' -> tpu-v4-lite on tpu, cpu-host otherwise); None when jax
    never initialized (then resolve_profile falls back to cpu-host)."""
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — provenance only
        return None


def run(backend: str, paths):
    import racon_tpu

    t0 = time.time()
    p = racon_tpu.create_polisher(paths["reads"], paths["overlaps"],
                                  paths["draft"], backend=backend, **ARGS)
    p.initialize()
    res = p.polish(True)
    dt = time.time() - t0
    polished_bp = sum(len(d) for _, d in res)
    # compact serving-mix report (who served what, fallback causes) —
    # attached to the bench JSON/log so a silently degraded tier can't
    # masquerade as a device measurement
    return polished_bp, dt, p.report.summary()


def main():
    if _forced_device():
        # dry-run mode: force the CPU backend in THIS process too — with
        # the health probe bypassed, an ambient wedged-TPU backend would
        # otherwise hang the warm-up/measured run unbounded, the exact
        # failure device_healthy() exists to prevent
        from __graft_entry__ import _force_cpu
        _force_cpu(1)
        # ...and rehearse the tier the healthy path actually ships:
        # interpret-mode pallas (~2 s/window at 0.01 Mbp), not the XLA
        # twin (~30 s/window on this box) — the twin is the degraded
        # tier, not the flow under rehearsal
        os.environ.setdefault("RACON_TPU_PALLAS", "1")
    # Arm the in-process metrics registry (counters only — no trace file
    # unless RACON_TPU_TRACE is set) so the measured run counts the
    # per-bucket DP cells the analytic cost model predicts against
    # (racon_tpu/obs/costmodel.py).  setdefault: an explicit =0 wins.
    os.environ.setdefault("RACON_TPU_METRICS", "1")
    paths = dataset()

    degraded = not device_healthy()
    if degraded:
        # Dead tunnel: emulating the device path on the CPU backend is
        # unboundedly slow and measures nothing real, so report the host
        # path only, flagged, with vs_baseline null — NO measurement,
        # deliberately distinct from a measured 0.0 (see
        # normalize_entry, which lifts old 0.0-style logs to the same
        # semantics on the reader side).
        # Real on-device numbers from earlier healthy runs live in the
        # committed log; cite the latest so the evidence isn't erased.
        print("[bench] WARNING: TPU device unreachable; reporting host-path "
              "throughput only", file=sys.stderr)
        prev = last_device_measurement()
        note = ""
        if prev:
            # .get() throughout: the log file is committed and hand-
            # editable; a malformed entry must not crash the degraded path
            tier = "pallas" if prev.get("pallas") else "XLA-fallback"
            note = (f"; last healthy device run {prev.get('utc', '?')} "
                    f"({tier}): {prev.get('value', '?')} Mbp/s, vs_baseline "
                    f"{prev.get('vs_baseline', '?')} on "
                    f"{prev.get('mbp', '?')} Mbp")
        bp_cpu, dt_cpu, _ = run("cpu", paths)
        mbps_cpu = bp_cpu / dt_cpu / 1e6
        print(json.dumps(degraded_result(mbps_cpu, note)))
        print(f"[bench] cpu: {bp_cpu} bp in {dt_cpu:.1f}s", file=sys.stderr)
        return

    pallas_disabled = config.get_raw("RACON_TPU_PALLAS") == "0"
    if pallas_disabled:
        # Explicit XLA-tier measurement (hw_session bench_sam_xla64):
        # skip the Mosaic probes entirely — they'd compile kernels this
        # run has disabled, and a Mosaic hang would starve the one step
        # that doesn't need pallas at all — and label the result as the
        # XLA tier so the durable log keeps the three tiers apart.
        tier = None
        pallas_ok = False
    else:
        tier = pallas_compiles()
        pallas_ok = tier is not None
        if not pallas_ok:
            # Bound the blast radius: the XLA device kernel is the
            # degraded tier; measure it honestly rather than hanging on
            # Mosaic.
            os.environ["RACON_TPU_PALLAS"] = "0"
        else:
            os.environ["RACON_TPU_POA_KERNEL"] = tier
    aligner = aligner_compiles()
    if aligner == "host":
        # probe failed or hung: pin the host aligner so the measured run
        # can't stall in an aligner compile (the in-process degrade
        # lattice handles errors but not hangs)
        os.environ["RACON_TPU_DEVICE_ALIGNER"] = "host"

    # Warm the device path so compile time is not billed as throughput:
    # compile every consensus kernel geometry explicitly (one trivial
    # padded batch per depth bucket) at the window length the measured
    # dataset will actually derive, then run a small end-to-end pass for
    # everything else. The persistent compilation cache keeps both warm
    # across processes — a full-size warm-up pass would triple device wall
    # at multi-Mbp bench scales.
    from racon_tpu.ops import poa_driver
    warm_lens = observed_window_lengths(paths["draft"],
                                        ARGS["window_length"])
    poa_driver.warm_geometries(warm_lens, ARGS["match"],
                               ARGS["mismatch"], ARGS["gap"])
    run("tpu", dataset(mbp=min(MBP, 0.05)))

    bp_tpu, dt_tpu, rep_tpu = run("tpu", paths)
    # The measured run's obs state (cell counters + any trace file) is
    # the cost model's evidence; the CPU oracle run would reset the
    # registry and overwrite the trace, so snapshot now and mute tracing
    # for the oracle pass.
    from racon_tpu import obs
    snap_tpu = obs.snapshot()
    platform = _backend_platform()
    if config.get_raw("RACON_TPU_TRACE"):
        os.environ["RACON_TPU_TRACE"] = ""
    bp_cpu, dt_cpu, _ = run("cpu", paths)

    mbps_tpu = bp_tpu / dt_tpu / 1e6
    mbps_cpu = bp_cpu / dt_cpu / 1e6
    if pallas_disabled:
        kernel_tag = " [XLA kernel: RACON_TPU_PALLAS=0]"
    elif pallas_ok:
        kernel_tag = f" [pallas {tier}]"
    else:
        kernel_tag = " [XLA kernel: pallas compile failed]"
    if _forced_device():
        # the one-line JSON is the bench's documented output: a CPU dry
        # run must be unmistakable there too, not only in the sidecar log
        kernel_tag += " [FORCED DRY-RUN: not device evidence]"
    # numbers measured with the runtime sanitizer armed carry its
    # per-window checking overhead — stamp them so they are never
    # compared against clean-run baselines
    sanitized = config.get_bool("RACON_TPU_SANITIZE")
    # predicted-vs-measured per modeled phase on the run's machine
    # profile; None when metrics were explicitly disarmed
    from racon_tpu.obs import costmodel
    cm = costmodel.bench_cost_model(
        snap_tpu, phase_wall(rep_tpu),
        config.get_str("RACON_TPU_MACHINE_PROFILE") or "auto",
        platform=platform)
    cells_banded, band_hit_rate = band_stamp(snap_tpu)
    peak_rss_mb, budget_mb = mem_stamp(rep_tpu)
    log_device_measurement({
        "mbp": MBP, "input": INPUT, "profile": PROFILE,
        "value": round(mbps_tpu, 4),
        "vs_baseline": round(mbps_tpu / mbps_cpu, 3),
        "pallas": pallas_ok, "kernel": tier or "xla",
        "aligner": _aligner_log_value(aligner),
        "node_factor": config.get_int("RACON_TPU_NODE_FACTOR"),
        "tpu_s": round(dt_tpu, 1), "cpu_s": round(dt_cpu, 1),
        "report": rep_tpu, "phase_wall": phase_wall(rep_tpu),
        "pack_split": pack_split(rep_tpu) or None,
        "cost_model": cm,
        "serial_steps": serial_steps_stamp(cm),
        "cells_banded": cells_banded, "band_hit_rate": band_hit_rate,
        "peak_rss_mb": peak_rss_mb, "budget_mb": budget_mb,
        **({"sanitize": True} if sanitized else {}),
    })
    print(json.dumps({
        "metric": f"polished Mbp/sec ({_WORKLOAD} {MBP} Mbp {COVERAGE}x, "
                  f"{INPUT.upper()}, w=500, end-to-end){kernel_tag}",
        "value": round(mbps_tpu, 4),
        "unit": "Mbp/s",
        "vs_baseline": round(mbps_tpu / mbps_cpu, 3),
        "report": rep_tpu, "phase_wall": phase_wall(rep_tpu),
        "pack_split": pack_split(rep_tpu) or None,
        "cost_model": cm,
        "serial_steps": serial_steps_stamp(cm),
        "cells_banded": cells_banded, "band_hit_rate": band_hit_rate,
        "peak_rss_mb": peak_rss_mb, "budget_mb": budget_mb,
        **({"sanitize": True} if sanitized else {}),
    }))
    print(f"[bench] tpu: {bp_tpu} bp in {dt_tpu:.1f}s | "
          f"cpu: {bp_cpu} bp in {dt_cpu:.1f}s", file=sys.stderr)

    _opportunistic_golden(tier)


def serve_profile(jobs: int = 4, clients: int = 2) -> int:
    """`python bench.py serve`: benchmark the resident daemon path.

    Spawns a `racon-tpu serve` daemon (kernels warmed at startup),
    drives it with concurrent jobs over the standard bench dataset via
    the load-test harness (racon_tpu/serve/loadtest.py), and stamps a
    normalized entry — warm-path Mbp/s as the value, latency percentiles
    and the cold-vs-warm delta under "serve" — so the `obs bench`
    regression gate covers the daemon path.  The `profile:
    serve-<PROFILE>` field keeps it a separate trend series from the
    one-shot bench.  vs_baseline is null: the serve bench has no paired
    oracle run (the byte-identity claim is CI's cmp gate, not a
    throughput ratio)."""
    import tempfile

    from racon_tpu.serve import loadtest

    degraded = not device_healthy()
    backend = "cpu" if degraded else "tpu"
    env = dict(os.environ)
    if _forced_device() and not degraded:
        # dry-run rehearsal: the daemon subprocess gets the forced-CPU
        # env (same reasoning as main(): with the health probe bypassed
        # an ambient wedged backend would hang the warm-up unbounded)
        from __graft_entry__ import _force_cpu_env
        env.update(_force_cpu_env(env, 1))
    paths = dataset()
    # Dry runs (and dead-tunnel host runs) shrink the window: at w=500
    # the XLA-twin consensus runs minutes/window on a CPU backend (same
    # reasoning as CI's pipelined-polish gate), and forced entries are
    # rehearsal, never device evidence.  Healthy device runs measure the
    # production w=500.
    w = ARGS["window_length"] if backend == "tpu" and \
        not _forced_device() else 100
    state = tempfile.mkdtemp(prefix="racon_tpu_bench_serve.")
    proc = loadtest.spawn_daemon(
        state, backend, window_length=w,
        extra_args=["-m", str(ARGS["match"]), "-x", str(ARGS["mismatch"]),
                    "-g", str(ARGS["gap"])],
        env=env)
    with open(os.path.join(state, "serve.json")) as f:
        port = json.load(f)["port"]
    polish_args = {k: ARGS[k] for k in
                   ("quality_threshold", "error_threshold",
                    "match", "mismatch", "gap")}
    polish_args["window_length"] = w
    try:
        summary = loadtest.run_loadtest(port, paths, jobs, clients,
                                        polish_args=polish_args)
    finally:
        try:
            from racon_tpu.serve import ServeClient
            with ServeClient(port, timeout=10.0) as c:
                c.shutdown()
            proc.wait(timeout=30)
        except Exception:  # noqa: BLE001 — teardown must not mask results
            proc.kill()

    value = summary["warm_mbps"]
    if value is None:
        value = summary["throughput_mbps"]
    tag = " [TPU UNREACHABLE: host lane only]" if degraded else ""
    if _forced_device():
        tag += " [FORCED DRY-RUN: not device evidence]"
    serve_stats = {
        "jobs": summary["jobs"], "clients": summary["clients"],
        "throughput_mbps": summary["throughput_mbps"],
        "latency_s": summary["latency_s"],
        "service_s": summary["service_s"],
        "warm_kernel_builds": summary["warm_kernel_builds"],
    }
    entry = {
        "metric": f"serve: warm-path polished Mbp/sec ({_WORKLOAD} {MBP} "
                  f"Mbp {COVERAGE}x, {INPUT.upper()}, w={w}, {jobs} jobs/"
                  f"{clients} clients){tag}",
        "value": round(value, 4),
        "unit": "Mbp/s",
        # no paired oracle run in serve mode — explicit nulls keep
        # normalize_entry a fixed point on fresh entries
        "vs_baseline": None,
        "cost_model": None,
        "pack_split": None,
        "serial_steps": None,
        "cells_banded": None,
        "band_hit_rate": None,
        "peak_rss_mb": None,
        "budget_mb": None,
        "serve": serve_stats,
        # scraped daemon telemetry (stats-op samples during the run)
        "fleet": summary.get("daemon_stats"),
        # elastic pool-size timeline (None: daemon ran without a plane)
        "pool": summary.get("pool"),
        # aggregated per-job latency ledger + end-of-run SLO snapshot
        # (None on daemons predating either; normalize_entry backfills
        # old logs to the same nulls)
        "ledger": summary.get("ledger"),
        "slo": summary.get("slo"),
        **({"device_status": "unreachable"} if degraded else {}),
    }
    assert normalize_entry(dict(entry)) == entry, \
        "serve bench entry must be a normalize_entry fixed point"
    log_device_measurement({
        "mbp": MBP, "input": INPUT, "profile": f"serve-{PROFILE}",
        "value": round(value, 4), "vs_baseline": None,
        "kernel": config.get_str("RACON_TPU_POA_KERNEL") or "ls",
        "serve": serve_stats, "fleet": summary.get("daemon_stats"),
        "pool": summary.get("pool"),
        "ledger": summary.get("ledger"), "slo": summary.get("slo"),
        "cost_model": None, "pack_split": None,
        "serial_steps": None,
        **({"device_status": "unreachable"} if degraded else {}),
    })
    print(json.dumps(entry))
    print(f"[bench] serve: {summary['completed']}/{summary['jobs']} jobs, "
          f"makespan {summary['makespan_s']:.1f}s, errors: "
          f"{summary['errors'] or 'none'}", file=sys.stderr)
    return 0 if summary["completed"] == summary["jobs"] else 1


def distrib_profile(workers: int = 3) -> int:
    """`python bench.py distrib`: benchmark the multi-process chunk
    fleet (racon_tpu/distrib).

    Runs the standard bench dataset through a Coordinator driving
    `workers` localhost worker processes on the cpu backend (the chunk
    workers run the host-oracle path — the fleet's scaling axis is
    processes, not kernels; the device story is serve's), and stamps
    polished Mbp/s over the gathered output plus the fleet accounting —
    chunks, serving mix, re-dispatch / speculation / duplicate /
    journal-resume counts — under "distrib".  The `profile:
    distrib-<PROFILE>` field keeps it its own trend series for the
    `obs bench` regression gate.  vs_baseline is null: byte-identity to
    the serial CLI is CI's cmp gate, not a throughput ratio."""
    import tempfile

    from racon_tpu.distrib import Coordinator

    paths = dataset()
    workdir = tempfile.mkdtemp(prefix="racon_tpu_bench_distrib.")
    out_path = os.path.join(workdir, "polished.fasta")
    t0 = time.monotonic()
    coord = Coordinator(paths["reads"], paths["overlaps"], paths["draft"],
                        workdir, args=dict(ARGS), backend="cpu",
                        workers=workers)
    result = coord.run(out_path, timeout=1800)
    wall = time.monotonic() - t0
    polished_bp = 0
    with open(out_path) as f:
        for line in f:
            if not line.startswith(">"):
                polished_bp += len(line.strip())
    value = polished_bp / 1e6 / wall if wall > 0 else 0.0
    counters = result["counters"]
    from racon_tpu.obs import ledger as joblog
    dist_stage_s = joblog.stage_seconds(result.get("summary"))
    distrib_stats = {
        "workers": workers,
        "chunks": result["chunks"],
        "served": result["served"],
        "dispatches": counters.get("dispatches", 0),
        "redispatches": counters.get("redispatches", 0),
        "speculative": counters.get("speculative", 0),
        "duplicates": counters.get("duplicates", 0),
        "journal_replayed": counters.get("journal_replayed", 0),
        "workers_dead": counters.get("workers_dead", 0),
        "degradations": len(result["degradations"]),
    }
    entry = {
        "metric": f"distrib: polished Mbp/sec ({_WORKLOAD} {MBP} Mbp "
                  f"{COVERAGE}x, {INPUT.upper()}, "
                  f"w={ARGS['window_length']}, {workers} workers/"
                  f"{result['chunks']} chunks, end-to-end)",
        "value": round(value, 4),
        "unit": "Mbp/s",
        # no paired oracle run in distrib mode — explicit nulls keep
        # normalize_entry a fixed point on fresh entries
        "vs_baseline": None,
        "cost_model": None,
        "pack_split": None,
        "serial_steps": None,
        "cells_banded": None,
        "band_hit_rate": None,
        "peak_rss_mb": None,
        "budget_mb": None,
        "distrib": distrib_stats,
        # fleet telemetry from the coordinator: per-worker chunk/kernel
        # walls, dispatch-queue wait p95, heartbeat staleness max
        "fleet": result.get("telemetry"),
        # elastic pool bounds + size timeline (fixed-size here: the
        # distrib bench pins min == max == workers)
        "pool": result.get("pool"),
        # per-stage compute seconds off the gathered run report (the
        # distrib lane has no per-job queueing stamps, and no daemon to
        # scrape an SLO snapshot from — slo stays an explicit null)
        "ledger": (({"stage_s": dist_stage_s} if dist_stage_s else None)),
        "slo": None,
    }
    assert normalize_entry(dict(entry)) == entry, \
        "distrib bench entry must be a normalize_entry fixed point"
    log_device_measurement({
        "mbp": MBP, "input": INPUT, "profile": f"distrib-{PROFILE}",
        "value": round(value, 4), "vs_baseline": None,
        "kernel": "host", "distrib": distrib_stats,
        "fleet": result.get("telemetry"), "pool": result.get("pool"),
        "ledger": ({"stage_s": dist_stage_s} if dist_stage_s else None),
        "slo": None,
        "cost_model": None, "pack_split": None, "serial_steps": None,
    })
    print(json.dumps(entry))
    served_total = sum(result["served"].values())
    print(f"[bench] distrib: {served_total}/{result['chunks']} chunks "
          f"({result['served']}), wall {wall:.1f}s, "
          f"redispatches {distrib_stats['redispatches']}, "
          f"replayed {distrib_stats['journal_replayed']}",
          file=sys.stderr)
    return 0 if served_total == result["chunks"] else 1


def stream_dataset(mbp: float, contigs: int):
    """Multi-contig dataset for the streaming bench, cached like
    dataset() (keyed by size/coverage/contigs + simulator source)."""
    import hashlib
    import inspect
    import shutil

    from racon_tpu.tools import simulate

    src_tag = hashlib.sha256(
        (inspect.getsource(simulate) +
         repr(sorted(PROFILES[PROFILE].items()))).encode()).hexdigest()[:12]
    outdir = (f"/tmp/racon_tpu_bench_stream_{mbp}mbp_{COVERAGE}x_"
              f"{contigs}c_{src_tag}")
    if not os.path.isdir(outdir):
        tmpdir = outdir + f".tmp{os.getpid()}"
        shutil.rmtree(tmpdir, ignore_errors=True)
        simulate.generate(tmpdir, mbp=mbp, coverage=COVERAGE,
                          contigs=contigs, **PROFILES[PROFILE])
        try:
            os.rename(tmpdir, outdir)
        except OSError:
            shutil.rmtree(tmpdir, ignore_errors=True)  # another run won
    ovl = "overlaps.sam" if INPUT == "sam" else "overlaps.paf"
    return {k: os.path.join(outdir, f)
            for k, f in (("reads", "reads.fastq"),
                         ("overlaps", ovl),
                         ("draft", "draft.fasta"))}


def stream_profile(contigs: int = 4) -> int:
    """`python bench.py stream`: the bounded-memory streaming path.

    Polishes a multi-contig draft through a CLI subprocess with the
    streaming input path armed under RACON_TPU_MEM_BUDGET_MB (default
    2048 MiB — override the knob for tighter drills), and stamps Mbp/s
    plus the run's memory accounting: ``peak_rss_mb`` (what the
    watchdog observed) against ``budget_mb``.  The `profile:
    stream-<PROFILE>` field keeps it its own trend series for the
    `obs bench` regression gate.  vs_baseline is null: byte-identity to
    the in-memory path is CI's cmp gate, not a throughput ratio.

    Genome-scale recipe (what the CI-sized default rehearses)::

        RACON_TPU_BENCH_MBP=3000 RACON_TPU_MEM_BUDGET_MB=8192 \\
            python bench.py stream

    — a 3 Gbp human-scale draft polished with peak RSS bounded by the
    chunk working set, not the genome (see docs/benchmarks.md)."""
    import tempfile

    degraded = not device_healthy()
    platform = None
    if not degraded:
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, timeout=120, text=True)
            platform = r.stdout.strip() if r.returncode == 0 else None
        except subprocess.TimeoutExpired:
            platform = None
    budget = config.get_int("RACON_TPU_MEM_BUDGET_MB") or 2048
    paths = stream_dataset(MBP, contigs)
    workdir = tempfile.mkdtemp(prefix="racon_tpu_bench_stream.")
    out_path = os.path.join(workdir, "polished.fasta")
    report_path = os.path.join(workdir, "report.json")
    # the streaming bench measures memory behavior, not kernels: off a
    # real TPU (dead tunnel, cpu backend, dry run) it runs the
    # small-window host fast path — same reasoning as serve_profile: the
    # XLA-twin consensus at w=500 runs minutes/window on a CPU backend
    on_device = platform == "tpu" and not _forced_device()
    w = ARGS["window_length"] if on_device else 100
    env = dict(os.environ)
    env.pop("RACON_TPU_FAULT", None)
    if not on_device:
        env.update(JAX_PLATFORMS="cpu", RACON_TPU_PALLAS="0",
                   RACON_TPU_POA_KERNEL="v2", RACON_TPU_BATCH_WINDOWS="8",
                   RACON_TPU_DEVICE_ALIGNER="xla")
    env["RACON_TPU_MEM_BUDGET_MB"] = str(budget)
    env["RACON_TPU_STREAM_INPUT"] = "1"
    cmd = [sys.executable, "-m", "racon_tpu.cli", "--tpu",
           "-w", str(w), "--report", report_path,
           paths["reads"], paths["overlaps"], paths["draft"]]
    t0 = time.monotonic()
    with open(out_path, "w") as out_f, \
            open(os.path.join(workdir, "stderr.log"), "w") as err_f:
        rc = subprocess.call(cmd, stdout=out_f, stderr=err_f, env=env)
    wall = time.monotonic() - t0
    if rc != 0:
        tail = ""
        try:
            with open(os.path.join(workdir, "stderr.log")) as f:
                tail = f.read()[-500:]
        except OSError:
            pass
        print(f"[bench] stream: CLI exited {rc}: {tail}", file=sys.stderr)
        return 1
    polished_bp = 0
    with open(out_path) as f:
        for line in f:
            if not line.startswith(">"):
                polished_bp += len(line.strip())
    value = polished_bp / 1e6 / wall if wall > 0 else 0.0
    try:
        with open(report_path) as f:
            rep = json.load(f).get("phases", {})
    except (OSError, ValueError):
        rep = {}
    peak_rss_mb, budget_mb = mem_stamp(rep)
    mem = rep.get("memory", {}) if isinstance(rep, dict) else {}
    extra = mem.get("extra", {}) if isinstance(mem, dict) else {}
    stream_stats = {
        "contigs": contigs,
        "streamed": extra.get("streamed"),
        "pressure_level": extra.get("pressure_level"),
        "quarantined": len(mem.get("quarantined", [])
                           if isinstance(mem, dict) else []),
        "degradations": sum(len(p.get("degradations", []))
                            for p in rep.values()
                            if isinstance(p, dict)),
    }
    tag = " [TPU UNREACHABLE: host backend]" if degraded else ""
    if _forced_device():
        tag += " [FORCED DRY-RUN: not device evidence]"
    entry = {
        "metric": f"stream: polished Mbp/sec ({_WORKLOAD} {MBP} Mbp "
                  f"{COVERAGE}x, {INPUT.upper()}, w={w}, {contigs} "
                  f"contigs, budget {budget} MiB, end-to-end){tag}",
        "value": round(value, 4),
        "unit": "Mbp/s",
        # no paired oracle run here — byte-identity is CI's cmp gate;
        # explicit nulls keep normalize_entry a fixed point
        "vs_baseline": None,
        "cost_model": None,
        "pack_split": None,
        "serial_steps": None,
        "cells_banded": None,
        "band_hit_rate": None,
        "peak_rss_mb": peak_rss_mb,
        "budget_mb": budget_mb,
        "stream": stream_stats,
        **({"device_status": "unreachable"} if degraded else {}),
    }
    assert normalize_entry(dict(entry)) == entry, \
        "stream bench entry must be a normalize_entry fixed point"
    log_device_measurement({
        "mbp": MBP, "input": INPUT, "profile": f"stream-{PROFILE}",
        "value": round(value, 4), "vs_baseline": None,
        "kernel": "host" if degraded else
        (config.get_str("RACON_TPU_POA_KERNEL") or "ls"),
        "stream": stream_stats,
        "peak_rss_mb": peak_rss_mb, "budget_mb": budget_mb,
        "cost_model": None, "pack_split": None, "serial_steps": None,
        **({"device_status": "unreachable"} if degraded else {}),
    })
    print(json.dumps(entry))
    print(f"[bench] stream: {polished_bp} bp in {wall:.1f}s, peak RSS "
          f"{peak_rss_mb} MiB / budget {budget_mb} MiB "
          f"(pressure {stream_stats['pressure_level']})", file=sys.stderr)
    return 0


def multichip_profile(counts=(1, 2, 4, 8), repeats: int = 3) -> int:
    """`python bench.py multichip`: the device-count scaling sweep as a
    bench series.

    Runs tools/multichip.py's sweep (one bounded subprocess per mesh
    width; the partitioner under-subscribes the visible devices via
    RACON_TPU_MESH_SHAPE) and stamps windows/s at the widest mesh as the
    value, with every per-count measurement under "multichip" — so the
    `obs bench` regression gate trends the sharded dispatch path.  The
    `profile: multichip-<PROFILE>` field keeps it its own series.  On
    anything but a healthy real TPU the sweep runs on forced virtual CPU
    devices, which share the host's cores: the entry is marked
    `forced` (rehearsal, never device evidence — the silicon curve comes
    from hw_session's checkpointed multichip step).  vs_baseline is
    null: scaling vs the 1-device row IS the metric, not a ratio against
    the CPU oracle."""
    from racon_tpu.tools import multichip as mc

    real = device_healthy() and not _forced_device()
    results = mc.sweep(sorted(set(counts)), repeats=repeats, real=real)
    ok = {n: e for n, e in results.items() if e.get("ok")
          and e.get("windows_per_s")}
    if not ok:
        print("[bench] multichip: every sweep count failed", file=sys.stderr)
        print(json.dumps(results, indent=2), file=sys.stderr)
        return 1
    top = max(ok, key=int)
    value = ok[top]["windows_per_s"]
    tier = ok[top]["tier"]
    tag = "" if real else " [FORCED DRY-RUN: not device evidence]"
    mc_stats = {
        "counts": results,
        "scaling_vs_1": (round(value / ok["1"]["windows_per_s"], 3)
                         if ok.get("1") and ok["1"]["windows_per_s"]
                         else None),
    }
    entry = {
        "metric": f"multichip: sharded consensus windows/sec at {top} "
                  f"device(s) (counts {sorted(map(int, results))}, "
                  f"tier {tier}, batch {ok[top]['batch']}){tag}",
        "value": round(value, 2),
        "unit": "windows/s",
        # no paired oracle run in the sweep — explicit nulls keep
        # normalize_entry a fixed point on fresh entries
        "vs_baseline": None,
        "cost_model": None,
        "pack_split": None,
        "serial_steps": None,
        "cells_banded": None,
        "band_hit_rate": None,
        "peak_rss_mb": None,
        "budget_mb": None,
        "multichip": mc_stats,
        **({"forced": True} if not real else {}),
    }
    assert normalize_entry(dict(entry)) == entry, \
        "multichip bench entry must be a normalize_entry fixed point"
    log_device_measurement({
        "mbp": MBP, "input": INPUT, "profile": f"multichip-{PROFILE}",
        "value": round(value, 2), "vs_baseline": None,
        "kernel": tier, "multichip": mc_stats,
        "cost_model": None, "pack_split": None, "serial_steps": None,
        **({"forced": True} if not real else {}),
    })
    print(json.dumps(entry))
    print(f"[bench] multichip: {len(ok)}/{len(results)} counts measured, "
          f"{top}-device {value:.1f} windows/s "
          f"(x{mc_stats['scaling_vs_1']} vs 1 device)", file=sys.stderr)
    return 0 if len(ok) == len(results) else 1


def _opportunistic_golden(tier, timeout_s: int = 900):
    """Healthy chip in hand: also re-measure the λ device golden, bounded.

    Healthy tunnel windows are scarce and every driver-run bench is a
    chance at accuracy evidence — the measurement rides the same session
    and lands in the durable log itself. Runs AFTER the bench numbers are
    logged and printed so a late tunnel wedge cannot cost the headline
    result; the subprocess bound means it cannot hang the bench either.
    Skipped in forced dry-run mode (λ interpret on CPU takes hours) and
    when the reference fixtures are absent."""
    if _forced_device():
        return
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "racon_tpu", "tools", "pin_device_golden.py")
    data = config.get_str("RACON_TPU_TEST_DATA")
    if not os.path.isdir(data):
        return
    try:
        r = subprocess.run([sys.executable, tool, "paf"],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"[bench] golden re-pin exceeded {timeout_s}s; skipped",
              file=sys.stderr)
        return
    # stdout only — stderr carries routine JAX/runtime warnings that
    # would otherwise be recorded as the "result"
    result = [l for l in r.stdout.strip().splitlines()
              if "device_golden" in l]
    if r.returncode == 0 and result:
        print(f"[bench] golden re-pin: {result[-1]}", file=sys.stderr)
        # record the kernel tier the golden actually ran on: if the
        # pallas probe failed, this number is the XLA tier's accuracy,
        # not the fused kernel's
        log_device_measurement({"golden_paf": result[-1][-200:],
                                "kernel": tier or "xla"})
    else:
        print("[bench] golden re-pin failed: "
              f"{(r.stderr or r.stdout)[-300:]}", file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        sys.exit(serve_profile())
    if len(sys.argv) > 1 and sys.argv[1] == "distrib":
        sys.exit(distrib_profile())
    if len(sys.argv) > 1 and sys.argv[1] == "multichip":
        sys.exit(multichip_profile())
    if len(sys.argv) > 1 and sys.argv[1] == "stream":
        sys.exit(stream_profile())
    main()
