"""Benchmark: polished Mbp/sec on the device path vs the host oracle path.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Dataset: the lambda-phage polishing workload (reads FASTQ + PAF overlaps +
draft layout, window=500, wrapper scores m=5 x=-4 g=-8 — the reference test
suite's standard scenario, /root/reference/test/racon_test.cpp:86-107).
value = polished megabases per second of end-to-end wall time (parse ->
polished FASTA) on the accelerated path; vs_baseline = speedup over the
host CPU path measured on the same machine (the reference's own comparison
axis: accelerated backend vs its CPU SPOA path).
"""

import json
import subprocess
import sys
import time

D = "/root/reference/test/data/"
ARGS = dict(window_length=500, quality_threshold=10.0, error_threshold=0.3,
            match=5, mismatch=-4, gap=-8, num_threads=1)


def device_healthy(timeout_s: int = 120) -> bool:
    """The axon TPU tunnel can wedge (device ops then hang forever); probe
    it in a subprocess so a dead tunnel can't hang the benchmark."""
    probe = ("import jax, jax.numpy as jnp; "
             "x = jnp.ones((128, 128)); print(float((x @ x).sum()))")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, timeout=timeout_s)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run(backend: str):
    import racon_tpu

    t0 = time.time()
    p = racon_tpu.create_polisher(
        D + "sample_reads.fastq.gz", D + "sample_overlaps.paf.gz",
        D + "sample_layout.fasta.gz", backend=backend, **ARGS)
    p.initialize()
    res = p.polish(True)
    dt = time.time() - t0
    polished_bp = sum(len(d) for _, d in res)
    return polished_bp, dt


def main():
    degraded = not device_healthy()
    if degraded:
        # Dead tunnel: measure the device *code path* on the CPU backend so
        # the benchmark still completes (flagged in the metric name); a
        # single unwarmed run keeps the degraded mode bounded.
        print("[bench] WARNING: TPU device unreachable; running the device "
              "path on the CPU backend", file=sys.stderr)
        import jax
        jax.config.update("jax_platforms", "cpu")
        suffix = " [TPU UNREACHABLE: device path on CPU backend]"
    else:
        suffix = ""
        # Warm the device path once so compile time is not billed as
        # throughput (compiled kernels are cached for the steady-state
        # measurement).
        run("tpu")

    bp_tpu, dt_tpu = run("tpu")
    bp_cpu, dt_cpu = run("cpu")

    mbps_tpu = bp_tpu / dt_tpu / 1e6
    mbps_cpu = bp_cpu / dt_cpu / 1e6
    print(json.dumps({
        "metric": "polished Mbp/sec (lambda 47.5kb, PAF+qual, w=500, "
                  "end-to-end)" + suffix,
        "value": round(mbps_tpu, 4),
        "unit": "Mbp/s",
        "vs_baseline": round(mbps_tpu / mbps_cpu, 3),
    }))
    print(f"[bench] tpu: {bp_tpu} bp in {dt_tpu:.1f}s | "
          f"cpu: {bp_cpu} bp in {dt_cpu:.1f}s", file=sys.stderr)


if __name__ == "__main__":
    main()
