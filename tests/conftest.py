"""Test configuration.

JAX runs on a virtual 8-device CPU mesh so multi-chip sharding compiles and
executes in CI without TPU hardware (the driver separately dry-runs the
multi-chip path; see __graft_entry__.py). Must be set before jax imports.

Set RACON_TPU_HW_TESTS=1 to NOT force the CPU mesh and run against the real
TPU backend instead — this enables the exact on-hardware pins (e.g. the λ
device golden in test_golden.py) and is only meant for a machine with a
healthy TPU attached (a wedged tunnel will hang the suite).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HW_TESTS = os.environ.get("RACON_TPU_HW_TESTS") == "1"

if not HW_TESTS:
    from __graft_entry__ import _force_cpu  # noqa: E402 (imports numpy only)

    _force_cpu(8)


def _assert_cpu_mesh():
    # Fail loudly if the forcing didn't take (e.g. a plugin initialized the
    # backend first) — otherwise tests would hit the real TPU tunnel, which
    # can wedge and hang the suite.
    import jax

    devs = jax.devices()
    assert devs[0].platform == "cpu" and len(devs) >= 8, (
        f"expected >=8 virtual CPU devices, got {len(devs)} "
        f"{devs[0].platform} — backend initialized before conftest?")


if not HW_TESTS:
    _assert_cpu_mesh()

import gzip  # noqa: E402

import pytest  # noqa: E402

# The reference lambda-phage dataset; override for CI environments without
# the reference checkout.
DATA = os.environ.get("RACON_TPU_TEST_DATA", "/root/reference/test/data/")

requires_data = pytest.mark.skipif(
    not os.path.isdir(DATA),
    reason=f"lambda test data not found at {DATA} "
           "(set RACON_TPU_TEST_DATA)")

def pytest_collection_modifyitems(config, items):
    if not HW_TESTS:
        return
    skip = pytest.mark.skip(
        reason="RACON_TPU_HW_TESTS=1: virtual 8-device CPU mesh disabled; "
               "multi-device tests need the default (forced-CPU) mode")
    for item in items:
        if "multichip" in item.nodeid or "multidevice" in item.nodeid:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _clear_kernel_cache():
    """poa_driver._build_kernel is memoized (warm-up's compiled kernel is
    the measured run's function object); tests that monkeypatch the
    kernel builders to inject failures must not see another test's real
    cached kernel, so drop the cache after every test."""
    yield
    try:
        from racon_tpu.ops import poa_driver

        poa_driver._build_kernel_cached.cache_clear()
    except Exception:  # noqa: BLE001 — package may not be importable yet
        pass
    try:
        # the memoized Partitioner carries sticky sharded->single-device
        # demotion state; a test that trips it must not demote the rest
        # of the suite
        from racon_tpu.parallel import reset_partitioner

        reset_partitioner()
    except Exception:  # noqa: BLE001
        pass
    try:
        # stop the mem-watchdog and drop latched watermark state so a
        # test that armed a tight RACON_TPU_MEM_BUDGET_MB cannot leave
        # hard-latched pressure (or a sampler thread) for the next test
        from racon_tpu.resilience import budget

        budget.reset()
    except Exception:  # noqa: BLE001
        pass


_COMP = bytes.maketrans(b"ACGT", b"TGCA")


def revcomp(s: bytes) -> bytes:
    return s.translate(_COMP)[::-1]


def read_fasta_gz(path):
    out = []
    name, chunks = None, []
    with gzip.open(path, "rt") as f:
        for line in f:
            line = line.strip()
            if line.startswith(">"):
                if name is not None:
                    out.append((name, "".join(chunks)))
                name = line[1:].split()[0]
                chunks = []
            else:
                chunks.append(line)
    if name is not None:
        out.append((name, "".join(chunks)))
    return out


@pytest.fixture(scope="session")
def lambda_reference() -> bytes:
    recs = read_fasta_gz(DATA + "sample_reference.fasta.gz")
    assert len(recs) == 1
    return recs[0][1].encode()
