"""v3 lane-lockstep Pallas POA kernel differential tests (interpret mode on
the CPU backend; on TPU hardware the same kernel runs compiled — the bench
exercises that).

The kernel (racon_tpu/ops/poa_pallas_ls.py) runs 8 windows per grid step in
sublane lock-step; these tests assert lockstep == XLA twin == host oracle on
one mixed batch covering varying lengths/depths, quality weights, partial
spans, padding windows, and the DMAX rank-distance cap (which must fail the
window to the host path, reproducing the reference's accelerator->CPU
fallback lattice, /root/reference/src/cuda/cudapolisher.cpp:354-378).
"""

import random

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.ops import poa, poa_pallas_ls
from racon_tpu.ops.encoding import decode, encode

from tests.test_pallas import mutate

CFG = poa.PoaConfig(max_nodes=384, max_len=256, max_backbone=128,
                    max_edges=12, depth=8, match=5, mismatch=-4, gap=-8)


def _alloc(B, cfg):
    return dict(
        bb=np.zeros((B, cfg.max_backbone), np.uint8),
        bbw=np.zeros((B, cfg.max_backbone), np.int32),
        bb_len=np.ones(B, np.int32),
        nl=np.zeros(B, np.int32),
        seqs=np.zeros((B, cfg.depth, cfg.max_len), np.uint8),
        ws=np.zeros((B, cfg.depth, cfg.max_len), np.int32),
        lens=np.zeros((B, cfg.depth), np.int32),
        bg=np.zeros((B, cfg.depth), np.int32),
        en=np.zeros((B, cfg.depth), np.int32),
    )


def _set_window(a, b, backbone, layers, weights=None, begins=None,
                ends=None):
    a["bb"][b, :len(backbone)] = encode(np.frombuffer(backbone, np.uint8))
    a["bb_len"][b] = len(backbone)
    a["nl"][b] = len(layers)
    for i, l in enumerate(layers):
        a["seqs"][b, i, :len(l)] = encode(np.frombuffer(l, np.uint8))
        a["ws"][b, i, :len(l)] = 1 if weights is None else weights[i]
        a["lens"][b, i] = len(l)
        a["bg"][b, i] = 0 if begins is None else begins[i]
        a["en"][b, i] = (len(backbone) - 1) if ends is None else ends[i]


def _run_both(a, cfg, B):
    ls_fn = poa_pallas_ls.build_lockstep_poa_kernel(cfg, interpret=True)(B)
    jax_fn = poa.build_poa_kernel(cfg)
    cb, cc, cl, fl, nn = (np.asarray(x) for x in ls_fn(
        a["bb_len"][:, None], a["nl"][:, None], a["lens"], a["bg"],
        a["en"], a["bb"].astype(np.int32), a["bbw"],
        a["seqs"].astype(np.int32), a["ws"]))
    jb, jc, jl, jf, jn = (np.asarray(x) for x in jax_fn(
        a["bb"], a["bbw"], a["bb_len"], a["nl"], a["seqs"], a["ws"],
        a["lens"], a["bg"], a["en"]))
    return (cb, cc, cl, fl, nn), (jb, jc, jl, jf, jn)


def test_lockstep_matches_host_and_jax():
    """One mixed 8-window batch: perfect reads, rising mutation/depth,
    quality weights, partial spans, and a 1-base padding window — each
    asserted against both the XLA twin and the host oracle (consensus,
    coverage, and node count)."""
    rng = random.Random(7)
    B = 8
    a = _alloc(B, CFG)
    cases = {}

    # w0: perfect reads
    truth0 = bytes(rng.choice(b"ACGT") for _ in range(90))
    _set_window(a, 0, truth0, [truth0] * 4)
    cases[0] = (truth0, [truth0] * 4, None, None, None)

    # w1..w4: rising mutation rate and depth, varying lengths
    for b in range(1, 5):
        truth = bytes(rng.choice(b"ACGT") for _ in range(60 + 15 * b))
        backbone = mutate(truth, 0.05 * b, rng)
        layers = [mutate(truth, 0.05 * b, rng) for _ in range(2 + b)]
        _set_window(a, b, backbone, layers)
        cases[b] = (backbone, layers, None, None, None)

    # w5: per-base quality weights (not all-1) — exercises edge-weight
    # accumulation and heaviest-bundle scoring with real magnitudes
    truth5 = bytes(rng.choice(b"ACGT") for _ in range(80))
    backbone5 = mutate(truth5, 0.1, rng)
    layers5 = [mutate(truth5, 0.1, rng) for _ in range(5)]
    w5 = [np.array([rng.randrange(1, 50) for _ in range(len(l))],
                   np.int32) for l in layers5]
    _set_window(a, 5, backbone5, layers5, weights=w5)
    cases[5] = (backbone5, layers5, w5, None, None)

    # w6: partial spans — layers cover only part of the backbone, so the
    # subgraph rule (reference src/window.cpp:88-97) kicks in
    truth6 = bytes(rng.choice(b"ACGT") for _ in range(120))
    backbone6 = mutate(truth6, 0.08, rng)
    half = len(backbone6) // 2
    lay_a = mutate(truth6[:len(truth6) // 2], 0.08, rng)
    lay_b = mutate(truth6[len(truth6) // 2:], 0.08, rng)
    lay_c = mutate(truth6, 0.08, rng)
    layers6 = [lay_c, lay_a, lay_b]
    begins6 = [0, 0, half]
    ends6 = [len(backbone6) - 1, half - 1, len(backbone6) - 1]
    _set_window(a, 6, backbone6, layers6, begins=begins6, ends=ends6)
    cases[6] = (backbone6, layers6, None, begins6, ends6)

    # w7: padding window (1-base backbone, zero layers) — must not crash
    # or flag failure, like the driver's pad-to-B windows

    (cb, cc, cl, fl, nn), (jb, jc, jl, jf, jn) = _run_both(a, CFG, B)

    assert not fl.any(), f"unexpected device failures: {fl[:, 0]}"
    assert not jf.any()
    for b, (backbone, layers, weights, begins, ends) in cases.items():
        ls_cons = decode(cb[b, :cl[b, 0]])
        jax_cons = decode(jb[b, :jl[b]])
        quals = None
        if weights is not None:
            quals = [bytes((w + 33).astype(np.uint8)) for w in weights]
        host_cons, _ = native.window_consensus(
            backbone, [bytes(l) for l in layers], quals=quals,
            begins=begins, ends=ends, trim=False)
        assert ls_cons == jax_cons == host_cons, f"window {b}"
        assert int(nn[b, 0]) == int(jn[b]), f"window {b} node count"
        np.testing.assert_array_equal(cc[b, :cl[b, 0]], jc[b, :jl[b]],
                                      err_msg=f"window {b} coverage")


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_lockstep_differential_fuzz(seed):
    """Seeded random windows — lengths, depths, mutation rates, partial
    spans, per-base layer weights AND backbone weights (the product
    exports PHRED-33 backbone weights, dummy '!' = 0 when the target has
    no quality; rt_capi.cpp rt_pipeline_window_export) — asserted
    lockstep == XLA twin == host oracle."""
    rng = random.Random(seed)
    B = 8
    a = _alloc(B, CFG)
    cases = {}
    for b in range(B):
        L = rng.randrange(40, 110)
        truth = bytes(rng.choice(b"ACGT") for _ in range(L))
        backbone = mutate(truth, rng.uniform(0.02, 0.12), rng)
        nl = rng.randrange(2, CFG.depth + 1)
        layers = [mutate(truth, rng.uniform(0.02, 0.12), rng)
                  for _ in range(nl)]
        bq = np.array([rng.randrange(0, 60) for _ in range(len(backbone))],
                      np.int32)
        w = [np.array([rng.randrange(1, 60) for _ in range(len(l))],
                      np.int32) for l in layers]
        begins = [0] * nl
        ends = [len(backbone) - 1] * nl
        if nl >= 3:  # one partial-span layer per window when depth allows
            begins[nl - 1] = len(backbone) // 3
            ends[nl - 1] = 2 * len(backbone) // 3
            layers[nl - 1] = layers[nl - 1][:max(
                1, len(layers[nl - 1]) // 3)]
            w[nl - 1] = w[nl - 1][:len(layers[nl - 1])]
        _set_window(a, b, backbone, layers, weights=w, begins=begins,
                    ends=ends)
        a["bbw"][b, :len(backbone)] = bq
        cases[b] = (backbone, layers, w, bq, begins, ends)

    (cb, cc, cl, fl, nn), (jb, jc, jl, jf, jn) = _run_both(a, CFG, B)

    assert not fl.any() and not jf.any()
    for b, (backbone, layers, w, bq, begins, ends) in cases.items():
        quals = [bytes((x + 33).astype(np.uint8)) for x in w]
        host, _ = native.window_consensus(
            backbone, [bytes(l) for l in layers],
            backbone_qual=bytes((bq + 33).astype(np.uint8)),
            quals=quals, begins=begins, ends=ends, trim=False)
        ls = decode(cb[b, :cl[b, 0]])
        jx = decode(jb[b, :jl[b]])
        assert ls == jx == host, f"seed {seed} window {b}"


def test_lockstep_ring_spill_at_large_geometry():
    """Windows of 420+ ranks force the 128-row H ring to wrap multiple
    times: DP chunks are DMA'd to the HBM spill buffer under compute and
    streamed back block-descending during traceback (poa_pallas_ls.py
    flush_chunk/tb_load). The small-geometry tests never leave the ring;
    this one crosses ~7 traceback blocks and must still match both the
    XLA twin and the host oracle exactly."""
    rng = random.Random(21)
    big = poa.PoaConfig(max_nodes=768, max_len=640, max_backbone=512,
                        max_edges=12, depth=4, match=5, mismatch=-4,
                        gap=-8)
    B = 8
    a = _alloc(B, big)
    cases = {}
    for b in range(B):
        truth = bytes(rng.choice(b"ACGT") for _ in range(420 + 10 * b))
        backbone = mutate(truth, 0.1, rng)
        layers = [mutate(truth, 0.1, rng) for _ in range(3)]
        _set_window(a, b, backbone, layers)
        cases[b] = (backbone, layers)

    (cb, cc, cl, fl, nn), (jb, jc, jl, jf, jn) = _run_both(a, big, B)

    assert not fl.any() and not jf.any()
    for b, (backbone, layers) in cases.items():
        host, _ = native.window_consensus(
            backbone, [bytes(l) for l in layers], trim=False)
        ls = decode(cb[b, :cl[b, 0]])
        jx = decode(jb[b, :jl[b]])
        assert ls == jx == host, f"window {b}"
        assert int(nn[b, 0]) == int(jn[b]), f"window {b} node count"


def test_lockstep_dmax_cap_fails_window_to_host():
    """A window whose graph grows an in-subgraph edge with rank distance
    beyond DMAX must raise its failed flag (-> driver host fallback), and
    must not poison its batch-mates.

    A long random *insertion* does not produce a long edge — spurious
    matches fragment it during alignment (host telemetry: a 104-base
    insert yields max distance 9). A deletion that CANNOT fragment does:
    the backbone carries a 74-base all-A block while the layers contain
    no A, so the DP is forced into one contiguous deletion and layer 1's
    incorporation adds a single rank-distance-75 edge (> DMAX=64), which
    layer 2's pre-DP distance check must trip."""
    rng = random.Random(11)
    B = 8
    a = _alloc(B, CFG)

    truth = bytes(rng.choice(b"CGT") for _ in range(50))
    backbone = truth[:25] + b"A" * (poa_pallas_ls.DMAX + 10) + truth[25:]
    _set_window(a, 0, backbone, [truth, truth])

    # a healthy batch-mate in another sublane
    mate = mutate(truth, 0.1, rng)
    _set_window(a, 1, truth, [mate, mutate(truth, 0.1, rng)])

    (cb, cc, cl, fl, nn), (jb, jc, jl, jf, jn) = _run_both(a, CFG, B)

    assert fl[0, 0] == 1, "DMAX overflow must fail the window"
    assert not jf[0], "the XLA twin has no DMAX cap and must succeed"
    assert fl[1, 0] == 0, "batch-mate must be unaffected"
    ls_cons = decode(cb[1, :cl[1, 0]])
    jax_cons = decode(jb[1, :jl[1]])
    assert ls_cons == jax_cons


def test_lockstep_driver_path_end_to_end(tmp_path, monkeypatch):
    """Full TpuPolisher flow with the lockstep branch of the consensus
    driver (interpret mode): exercises RACON_TPU_POA_KERNEL=ls dispatch,
    G-multiple batching, padding, marshalling, and unpacking."""
    import random as _r

    import racon_tpu

    rng = _r.Random(5)
    target = "".join(rng.choice("ACGT") for _ in range(240))
    with open(tmp_path / "target.fasta", "w") as f:
        f.write(f">tgt\n{target}\n")
    with open(tmp_path / "reads.fasta", "w") as f:
        for i in range(4):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "ovl.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(4):
            f.write(f"r{i}\t0\ttgt\t1\t60\t240M\t*\t0\t0\t{target}\t*\n")

    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "ls")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "4")  # rounds up to G=8
    p = racon_tpu.TpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ovl.sam"),
                              str(tmp_path / "target.fasta"),
                              window_length=80, quality_threshold=10,
                              error_threshold=0.3, match=5, mismatch=-4,
                              gap=-8, num_threads=1)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    assert res[0][1] == target  # perfect reads -> perfect consensus


def test_lockstep_ls_failure_degrades_to_v2(tmp_path, monkeypatch, capsys):
    """A Mosaic failure in the lockstep kernel must step down to the v2
    pallas kernel (not straight to XLA), preserving the accelerated path."""
    import racon_tpu
    from racon_tpu.ops import poa_driver

    target = "ACGT" * 60
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(4):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(4):
            f.write(f"r{i}\t0\tt\t1\t60\t{len(target)}M\t*\t0\t0\t{target}"
                    f"\t*\n")

    def broken_ls(cfg, interpret=False):
        def make(batch):
            def call(*args):
                raise RuntimeError("synthetic mosaic failure")
            return call
        return make

    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "ls")
    monkeypatch.setattr(
        "racon_tpu.ops.poa_pallas_ls.build_lockstep_poa_kernel", broken_ls)
    p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                              str(tmp_path / "o.sam"),
                              str(tmp_path / "t.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    assert res[0][1] == target
    assert "falling back to the pallas 'v2' kernel" in \
        capsys.readouterr().err
