"""Fixture: kernel-cache-key — lru_cache'd builder with no topology key."""

import functools

import jax


@functools.lru_cache(maxsize=8)
def build_fixture_kernel(cap: int):
    def fn(x):
        return x * 2

    return jax.jit(fn)
