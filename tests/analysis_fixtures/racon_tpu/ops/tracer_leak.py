"""Fixture: tracer-leak — every flavor the rule knows, in one traced fn."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def leaky(x, threshold):
    v = float(x[0])                   # concretizes the tracer
    arr = np.asarray(x)               # host materialization
    s = jnp.sum(x).item()             # device sync
    if x[0] > threshold:              # data-dependent Python branch
        return arr[0] + v + s
    return x
