"""Fixture: env-registry — raw RACON_TPU_* env read outside config.py."""

import os

BATCH = int(os.environ.get("RACON_TPU_FIXTURE_BATCH", "8"))
