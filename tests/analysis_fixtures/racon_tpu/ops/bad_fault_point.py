"""Fixture: fault-point — a check() name missing from KNOWN_POINTS."""

from racon_tpu.resilience import faults


def run(chunk):
    faults.check("poa.run.no_such_tier", chunk)
    return chunk


def journal_typo(chunk):
    # resilience-layer points are registered too: "journal.append" /
    # "journal.replay" are known, this misspelling is not
    faults.check("journal.appendd", chunk)
    return chunk


def watchdog_typo(chunk):
    faults.check("watchdog.calls", chunk)
    return chunk
