"""Fixture: fault-point — a check() name missing from KNOWN_POINTS."""

from racon_tpu.resilience import faults


def run(chunk):
    faults.check("poa.run.no_such_tier", chunk)
    return chunk
