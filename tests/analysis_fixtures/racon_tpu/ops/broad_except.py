"""Fixture: device-except — bare and undocumented broad catches."""


def serve(kernel, batch):
    try:
        return kernel(batch)
    except:  # bare: swallows the lattice's failure signal
        return None


def serve_broad(kernel, batch):
    try:
        return kernel(batch)
    except Exception:
        return None
