"""Fixture: wall-clock — time.time() span stamps in the obs layer."""

import time


def stamp_span(record):
    t0 = time.time()
    record("work")
    return time.time() - t0
