"""Fixture: wall-clock — time.time() deadline in the resilience layer."""

import time


def wait_until_done(poll):
    deadline = time.time() + 30.0
    while time.time() < deadline:
        if poll():
            return True
    return False
