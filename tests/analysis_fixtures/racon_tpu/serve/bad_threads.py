"""Fixture: every flavor of the thread-discipline rule."""

import threading
import time

_LOCK = threading.Lock()


def spawn():
    t = threading.Thread(target=print)               # no daemon, no name
    u = threading.Thread(target=print, daemon=True,
                         name="mystery-worker")      # unregistered role
    t.start()
    u.start()


def nap():
    with _LOCK:
        time.sleep(0.1)                              # sleep under a lock
