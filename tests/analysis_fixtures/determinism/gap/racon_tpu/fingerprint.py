"""Mini fingerprint registry missing the declared-output knob."""

OUTPUT_SOURCES = (
    "input:reads",
)

SITES = {
    "journal": {
        "helper": "journal_fingerprint",
        "complete": True,
        "components": {
            "input_bytes": ("input:reads",),
        },
    },
}
