"""Mini knob registry: one runtime knob declared output-affecting.

The fixture's fingerprint.py does not cover it, so the declared-
complete site must raise exactly one fingerprint-gap."""


def _k(name, default, kind, doc, scope="runtime", affects_output=False):
    return (name, default, kind, doc, scope, affects_output)


KNOBS = {k[0]: k for k in (
    _k("RACON_TPU_SEED", "0", "int",
       "RNG seed baked into output bytes", affects_output=True),
)}
