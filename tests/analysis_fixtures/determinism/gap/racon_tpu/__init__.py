"""fingerprint-gap fixture package root."""
