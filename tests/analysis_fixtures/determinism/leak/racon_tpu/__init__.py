"""determinism-leak fixture package root."""
