"""Mini knob registry: one cost-only knob the code below leaks."""


def _k(name, default, kind, doc, scope="runtime", affects_output=False):
    return (name, default, kind, doc, scope, affects_output)


KNOBS = {k[0]: k for k in (
    _k("RACON_TPU_DEPTH", "2", "int",
       "pipeline depth (declared cost-only)"),
)}


def get_int(name):
    return 2
