"""The seeded contract break: a declared-cost-only knob's value is
concatenated into the installed consensus payload — exactly one
determinism-leak must fire, at the set_consensus call."""

from .. import config


def polish(pipeline, windows):
    depth = config.get_int("RACON_TPU_DEPTH")
    for i, w in enumerate(windows):
        payload = w + str(depth).encode()
        pipeline.set_consensus(i, payload, True)
