"""determinism-leak fixture ops package."""
