"""fingerprint-overkey fixture package root."""
