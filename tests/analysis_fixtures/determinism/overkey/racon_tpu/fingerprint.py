"""Mini fingerprint registry keying a cache on a cost-only knob:
exactly one fingerprint-overkey warning, at the `tier` component."""

OUTPUT_SOURCES = (
    "input:reads",
)

SITES = {
    "cache": {
        "helper": "cache_key",
        "complete": False,
        "components": {
            "args": ("args:builder",),
            "tier": ("knob:RACON_TPU_TIER",),
        },
    },
}
