"""Mini knob registry: one cost-only knob nothing ever leaks."""


def _k(name, default, kind, doc, scope="runtime", affects_output=False):
    return (name, default, kind, doc, scope, affects_output)


KNOBS = {k[0]: k for k in (
    _k("RACON_TPU_TIER", "auto", "str",
       "kernel tier selector (cost-only, taint-clean)"),
)}
