"""Fixture fault registry: knows pool.steal, not pool.warp."""

KNOWN_POINTS = frozenset({
    "pool.steal",
})


def check(point):
    return point
