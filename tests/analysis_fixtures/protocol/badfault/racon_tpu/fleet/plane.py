"""Fixture code site: `_fetch` exists and its injection point is
claimed by the model, so only the unknown point fires."""

from racon_tpu.resilience import faults


def _fetch(worker):
    faults.check("pool.steal")
    return worker
