"""Seeded fixture: one TRANSITIONS entry claims a fault point that is
not in faults.KNOWN_POINTS -> exactly one `model-fault` finding."""

TRANSITIONS = (
    ("steal", "racon_tpu/fleet/plane.py", "_fetch", "pool.steal"),
    ("warp", "racon_tpu/fleet/plane.py", "_fetch", "pool.warp"),
)
