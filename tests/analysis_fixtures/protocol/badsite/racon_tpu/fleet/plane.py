"""Fixture code site: defines `_assign` (live) but not
`_no_such_handler` (the model points at dead code)."""


def _assign(chunk, worker):
    return (chunk, worker)
