"""Seeded fixture: one TRANSITIONS entry points at a callable the site
file no longer defines -> exactly one `model-site` finding.  No
faults.py in this tree, so the fault checks are skipped."""

TRANSITIONS = (
    ("dispatch", "racon_tpu/fleet/plane.py", "_assign", None),
    ("vanish", "racon_tpu/fleet/plane.py", "_no_such_handler", None),
)
