"""Drill stub so fault-drill stays quiet: exercises pool.steal.
(Named drills.py, not test_*.py, so pytest never collects it.)"""

POINT = "pool.steal"
