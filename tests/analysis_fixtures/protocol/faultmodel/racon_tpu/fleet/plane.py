"""Fixture code site for the model's single transition."""


def _assign(chunk, worker):
    return (chunk, worker)
