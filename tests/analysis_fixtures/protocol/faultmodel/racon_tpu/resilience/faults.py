"""Seeded fixture: a fleet-scoped KNOWN_POINTS entry the protocol
model does not claim -> exactly one contracts `fault-model` finding
(the drill/docs stubs below keep the sibling fault rules quiet)."""

KNOWN_POINTS = frozenset({
    "pool.steal",
})


def check(point):
    return point
