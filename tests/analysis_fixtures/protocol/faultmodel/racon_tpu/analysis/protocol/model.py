"""Fixture model: claims no fault point, so the fleet-scoped
pool.steal registry entry is unclaimed."""

TRANSITIONS = (
    ("dispatch", "racon_tpu/fleet/plane.py", "_assign", None),
)
