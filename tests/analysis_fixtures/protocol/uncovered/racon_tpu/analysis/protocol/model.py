"""Seeded fixture: the tree injects a fleet-scoped fault point the
model claims nowhere -> exactly one `model-coverage` finding."""

TRANSITIONS = (
    ("dispatch", "racon_tpu/fleet/plane.py", "_assign", None),
)
