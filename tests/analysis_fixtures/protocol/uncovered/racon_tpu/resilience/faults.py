"""Fixture fault registry for the coverage check."""

KNOWN_POINTS = frozenset({
    "pool.steal",
})


def check(point):
    return point
