"""Fixture code site: a live `faults.check("pool.steal")` injection
the model's TRANSITIONS never claims."""

from racon_tpu.resilience import faults


def _assign(chunk, worker):
    return (chunk, worker)


def _fetch(worker):
    faults.check("pool.steal")
    return worker
