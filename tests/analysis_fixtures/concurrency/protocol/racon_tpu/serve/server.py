class Server:
    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}
