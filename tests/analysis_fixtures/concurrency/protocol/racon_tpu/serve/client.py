class Client:
    def rpc(self, **req) -> dict:
        return req

    def ping(self) -> dict:
        return self.rpc(op="ping", extra=1)  # `extra` is undeclared
