"""Seeded fixture: the client sends a field the declared spec does not
know -> exactly one `protocol-mismatch` finding."""

PROTOCOL = {
    "serve": {
        "ping": {"req": (), "opt": (), "resp": ()},
    },
}
