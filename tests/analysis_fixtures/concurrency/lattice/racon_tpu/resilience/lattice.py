"""Seeded fixture: one lattice edge whose docs row exists but whose
test drill is missing -> exactly one `lattice-drill` finding."""

CONSENSUS_TIERS = ("fast", "slow")
