# Deliberately not a drill: mentions neither tier of the edge.
# (Named drills.py, not test_*.py, so pytest never collects it.)


def unrelated():
    return 1
