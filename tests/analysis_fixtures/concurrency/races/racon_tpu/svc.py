"""Seeded fixture: one shared location mutated from two roles with no
lock held at every site -> exactly one `unguarded-mutation` finding."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        self.n = self.n + 1  # unguarded: neither caller holds _lock


def loop(c: Counter):
    c.bump()


def main():
    c = Counter()
    t = threading.Thread(target=loop, args=(c,), name="serve-conn",
                         daemon=True)
    t.start()
    c.bump()
    t.join()
