"""Seeded fixture: two locks acquired in opposite orders on two paths
-> exactly one `lock-order-cycle` finding."""

import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
