"""Golden end-to-end accuracy tests on the lambda-phage dataset — the same
strategy as the reference suite (/root/reference/test/racon_test.cpp:86-295):
run the full pipeline, pin the exact edit distance of the polished contig
(reverse-complemented) against NC_001416, pin output counts/lengths for
fragment correction.

Our pinned numbers sit next to the reference's for comparison (this
framework's POA/aligner are new implementations, so the numbers differ the
way the reference's own CUDA numbers differ from its CPU numbers):

  scenario                      ours   reference-CPU  reference-GPU
  PAF + qualities               1283   1312           1385
  PAF no qualities              1443   1566           1607
  SAM + qualities               1315   1317           1541
  SAM no qualities              1769   1770           1661
  PAF + qualities, w=1000       1304   1289           4168
  PAF + qualities, unit scores  1338   1321           1361
  fragment kC count/bp          40/401215   40/401246
  fragment kF PAF count/bp      236/1657837 236/1658216
  fragment kF FASTA count/bp    236/1662904 236/1663982
  fragment kF MHAP count/bp     236/1657837 236/1658216

4 of 6 polish scenarios are at-or-better than the reference CPU; the two
worse (w=1000, unit scores) are within 1.3%. The load-bearing semantic:
layer add-order uses unstable std::sort on begin position, mirroring the
reference's sort call (see rt_window.cpp). Like the reference's pins, the
exact values encode the standard library's deterministic-but-unspecified
equal-key permutation (libstdc++ here).

Slow scenarios (host global alignment of every all-vs-all overlap on this
1-core box) are gated behind RACON_TPU_FULL_GOLDEN=1.
"""

import os

import pytest

import racon_tpu
from racon_tpu import native
from racon_tpu.tools import golden_scenarios as gs
from tests.conftest import DATA, revcomp, requires_data

FULL = os.environ.get("RACON_TPU_FULL_GOLDEN") == "1"
HW = os.environ.get("RACON_TPU_HW_TESTS") == "1"

ARGS = gs.ARGS  # single source: the args the pinned numbers are defined by


pytestmark = requires_data

def polish(seqs, ovl, tgt, backend="cpu", drop=True, **kw):
    a = dict(ARGS)
    a.update(kw)
    p = racon_tpu.create_polisher(DATA + seqs, DATA + ovl, DATA + tgt,
                                  backend=backend, **a)
    p.initialize()
    return p.polish(drop)


def run_scenario(name, backend="cpu"):
    """Run one golden_scenarios entry; returns the polish result list."""
    if name in gs.POLISH:
        reads, ovl, tgt, extra = gs.POLISH[name]
    else:
        reads, ovl, tgt, extra = gs.FRAGMENT[name]
    extra = dict(extra)
    drop = extra.pop("drop", True)
    return polish(reads, ovl, tgt, backend=backend, drop=drop, **extra)


def ed_vs_reference(res, lambda_reference):
    assert len(res) == 1
    return native.edit_distance(revcomp(res[0][1].encode()), lambda_reference)


def test_consensus_sam_with_qualities(lambda_reference):
    res = run_scenario("sam")
    assert ed_vs_reference(res, lambda_reference) == \
        gs.HOST_POLISH["sam"]  # reference: 1317


def test_consensus_sam_without_qualities(lambda_reference):
    res = run_scenario("sam_noq")
    assert ed_vs_reference(res, lambda_reference) == \
        gs.HOST_POLISH["sam_noq"]  # reference: 1770


def test_consensus_paf_with_qualities(lambda_reference):
    res = run_scenario("paf")
    assert ed_vs_reference(res, lambda_reference) == \
        gs.HOST_POLISH["paf"]  # reference: 1312


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_consensus_paf_without_qualities(lambda_reference):
    res = run_scenario("paf_noq")
    assert ed_vs_reference(res, lambda_reference) == \
        gs.HOST_POLISH["paf_noq"]  # reference: 1566


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_consensus_paf_larger_window(lambda_reference):
    res = run_scenario("paf_w1000")
    assert ed_vs_reference(res, lambda_reference) == \
        gs.HOST_POLISH["paf_w1000"]  # reference: 1289


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_consensus_paf_unit_scores(lambda_reference):
    res = run_scenario("unit")
    assert ed_vs_reference(res, lambda_reference) == \
        gs.HOST_POLISH["unit"]  # reference: 1321


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kc(lambda_reference):
    res = run_scenario("kc")
    count, total = gs.HOST_FRAGMENT["kc"]  # reference: 40 / 401246
    assert len(res) == count
    assert sum(len(d) for _, d in res) == total


def _on_tpu():
    try:
        import jax
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


@pytest.mark.skipif(not (FULL or HW),
                    reason="slow (device path in interpret/CPU mode); set "
                    "RACON_TPU_FULL_GOLDEN=1, or RACON_TPU_HW_TESTS=1 on "
                    "a TPU machine (fast there, and asserts the exact pin)")
@pytest.mark.parametrize("name", list(gs.POLISH) + list(gs.FRAGMENT))
def test_device_path_golden(name, lambda_reference, monkeypatch):
    """TPU-path accuracy for EVERY golden scenario (the reference pins 10
    accelerator numbers next to the CPU ones, racon_test.cpp:297-507).

    On real TPU hardware each measured pin from golden_scenarios.py is
    asserted EXACTLY; scenarios whose pin is still None skip with a
    pointer to the pin tool (never a silent pass). E.g. 'paf' is pinned
    1282, measured on a v5e (2026-07-29, pin_device_golden.py) — one edit
    from the host path's 1283 (a DP score-tie resolved differently on
    device), better than the reference's CPU 1312 and GPU 1385. The
    hardware branch needs RACON_TPU_HW_TESTS=1 (conftest otherwise forces
    the virtual CPU mesh). On the CPU backend (interpret mode) only the
    historical 'paf' scenario runs — within a small band of the host
    golden; the other 9 would take hours in interpret mode on this box.
    """
    if HW and not _on_tpu():
        # never let a wedged tunnel (JAX silently falls back to CPU) pass
        # the loose band off as a re-verified hardware pin
        pytest.fail("RACON_TPU_HW_TESTS=1 but the JAX platform is not tpu "
                    "— hardware pin not exercised")
    is_polish = name in gs.POLISH
    # the device pins isolate the consensus path: phase 1 on the host
    # aligner, matching pin_device_golden.py's pinned measurement
    # conditions (the hirschberg-on-TPU default postdates the paf pin)
    monkeypatch.setenv("RACON_TPU_DEVICE_ALIGNER", "host")
    if _on_tpu():
        pin = (gs.DEVICE_POLISH if is_polish else gs.DEVICE_FRAGMENT)[name]
        if pin is None:
            pytest.skip(f"device pin for {name!r} not yet measured — run "
                        f"racon_tpu/tools/pin_device_golden.py {name} on a "
                        "healthy chip and record it in golden_scenarios.py")
        res = run_scenario(name, backend="tpu")
        if is_polish:
            assert ed_vs_reference(res, lambda_reference) == pin
        else:
            count, total = pin
            assert len(res) == count
            assert sum(len(d) for _, d in res) == total
    else:
        if name != "paf":
            pytest.skip("interpret-mode device golden runs only the 'paf' "
                        "scenario (hours per scenario on a 1-core host); "
                        "full coverage is the RACON_TPU_HW_TESTS=1 branch")
        # v2 tier: under this suite's 8-virtual-device mesh the ls tier's
        # interpret λ run blows past 25 minutes (64-window sharded chunks),
        # while standalone on one device it takes 197 s and lands on 1282
        # — the exact round-2 hardware pin, 92/96 windows device-served
        # (measured 2026-07-30, docs/benchmarks.md). ls interpret
        # correctness is pinned by tests/test_pallas_ls.py; this branch
        # checks the driver + band.
        monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
        res = run_scenario(name, backend="tpu")
        ed = ed_vs_reference(res, lambda_reference)
        assert abs(ed - gs.HOST_POLISH["paf"]) <= 15, ed


@pytest.mark.skipif(not FULL, reason="very slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kf_fasta(lambda_reference):
    """kF with FASTA reads (no qualities) — reference pins 236/1,663,982
    (test/racon_test.cpp:270-276, GPU 1,663,732)."""
    res = run_scenario("kf_fasta")
    count, total = gs.HOST_FRAGMENT["kf_fasta"]  # reference: 236 / 1663982
    assert len(res) == count
    assert sum(len(d) for _, d in res) == total


@pytest.mark.skipif(not FULL, reason="very slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kf_paf(lambda_reference):
    res = run_scenario("kf_paf")
    count, total = gs.HOST_FRAGMENT["kf_paf"]  # reference: 236 / 1658216
    assert len(res) == count
    assert sum(len(d) for _, d in res) == total


@pytest.mark.skipif(not FULL, reason="very slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kf_mhap(lambda_reference):
    """kF with MHAP overlaps — the reference's 10th pinned scenario
    (test/racon_test.cpp:288-294, 236/1,658,216 == its PAF kF): the MHAP
    ordinal transmutation must resolve to the identical result."""
    res = run_scenario("kf_mhap")
    count, total = gs.HOST_FRAGMENT["kf_mhap"]
    assert len(res) == count
    assert sum(len(d) for _, d in res) == total
    assert (count, total) == gs.HOST_FRAGMENT["kf_paf"]  # format parity
