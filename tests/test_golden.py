"""Golden end-to-end accuracy tests on the lambda-phage dataset — the same
strategy as the reference suite (/root/reference/test/racon_test.cpp:86-295):
run the full pipeline, pin the exact edit distance of the polished contig
(reverse-complemented) against NC_001416, pin output counts/lengths for
fragment correction.

Our pinned numbers sit next to the reference's for comparison (this
framework's POA/aligner are new implementations, so the numbers differ the
way the reference's own CUDA numbers differ from its CPU numbers):

  scenario                      ours   reference-CPU  reference-GPU
  PAF + qualities               1283   1312           1385
  PAF no qualities              1443   1566           1607
  SAM + qualities               1315   1317           1541
  SAM no qualities              1769   1770           1661
  PAF + qualities, w=1000       1304   1289           4168
  PAF + qualities, unit scores  1338   1321           1361
  fragment kC count/bp          40/401215   40/401246
  fragment kF PAF count/bp      236/1657837 236/1658216
  fragment kF FASTA count/bp    236/1662904 236/1663982

4 of 6 polish scenarios are at-or-better than the reference CPU; the two
worse (w=1000, unit scores) are within 1.3%. The load-bearing semantic:
layer add-order uses unstable std::sort on begin position, mirroring the
reference's sort call (see rt_window.cpp). Like the reference's pins, the
exact values encode the standard library's deterministic-but-unspecified
equal-key permutation (libstdc++ here).

Slow scenarios (host global alignment of every all-vs-all overlap on this
1-core box) are gated behind RACON_TPU_FULL_GOLDEN=1.
"""

import os

import pytest

import racon_tpu
from racon_tpu import native
from tests.conftest import DATA, revcomp, requires_data

FULL = os.environ.get("RACON_TPU_FULL_GOLDEN") == "1"
HW = os.environ.get("RACON_TPU_HW_TESTS") == "1"

ARGS = dict(window_length=500, quality_threshold=10.0, error_threshold=0.3,
            match=5, mismatch=-4, gap=-8, num_threads=1)


pytestmark = requires_data

def polish(seqs, ovl, tgt, backend="cpu", drop=True, **kw):
    a = dict(ARGS)
    a.update(kw)
    p = racon_tpu.create_polisher(DATA + seqs, DATA + ovl, DATA + tgt,
                                  backend=backend, **a)
    p.initialize()
    return p.polish(drop)


def ed_vs_reference(res, lambda_reference):
    assert len(res) == 1
    return native.edit_distance(revcomp(res[0][1].encode()), lambda_reference)


def test_consensus_sam_with_qualities(lambda_reference):
    res = polish("sample_reads.fastq.gz", "sample_overlaps.sam.gz",
                 "sample_layout.fasta.gz")
    assert ed_vs_reference(res, lambda_reference) == 1315  # reference: 1317


def test_consensus_sam_without_qualities(lambda_reference):
    res = polish("sample_reads.fasta.gz", "sample_overlaps.sam.gz",
                 "sample_layout.fasta.gz")
    assert ed_vs_reference(res, lambda_reference) == 1769  # reference: 1770


def test_consensus_paf_with_qualities(lambda_reference):
    res = polish("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                 "sample_layout.fasta.gz")
    assert ed_vs_reference(res, lambda_reference) == 1283  # reference: 1312


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_consensus_paf_without_qualities(lambda_reference):
    res = polish("sample_reads.fasta.gz", "sample_overlaps.paf.gz",
                 "sample_layout.fasta.gz")
    assert ed_vs_reference(res, lambda_reference) == 1443  # reference: 1566


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_consensus_paf_larger_window(lambda_reference):
    res = polish("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                 "sample_layout.fasta.gz", window_length=1000)
    assert ed_vs_reference(res, lambda_reference) == 1304  # reference: 1289


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_consensus_paf_unit_scores(lambda_reference):
    res = polish("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                 "sample_layout.fasta.gz", match=1, mismatch=-1, gap=-1)
    assert ed_vs_reference(res, lambda_reference) == 1338  # reference: 1321


@pytest.mark.skipif(not FULL, reason="slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kc(lambda_reference):
    res = polish("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
                 "sample_reads.fastq.gz", match=1, mismatch=-1, gap=-1)
    assert len(res) == 40  # reference: 40
    assert sum(len(d) for _, d in res) == 401215  # reference: 401246


def _on_tpu():
    try:
        import jax
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


@pytest.mark.skipif(not (FULL or HW),
                    reason="slow (device path in interpret/CPU mode); set "
                    "RACON_TPU_FULL_GOLDEN=1, or RACON_TPU_HW_TESTS=1 on "
                    "a TPU machine (fast there, and asserts the exact pin)")
def test_device_path_paf_with_qualities(lambda_reference):
    """TPU-path accuracy (the reference pins exact accelerator numbers next
    to the CPU ones, test/racon_test.cpp:297-318, GPU 1385 vs CPU 1312).

    On real TPU hardware the fused Pallas path is pinned EXACTLY: 1282,
    measured on a v5e (2026-07-29, racon_tpu/tools/pin_device_golden.py) —
    one edit from the host path's 1283 (a DP score-tie resolved differently
    on device), better than the reference's CPU 1312 and GPU 1385. The
    hardware branch needs RACON_TPU_HW_TESTS=1 (conftest otherwise forces
    the virtual CPU mesh). On the CPU backend (interpret mode) the same
    kernel must land within a small band of the host golden."""
    if HW and not _on_tpu():
        # never let a wedged tunnel (JAX silently falls back to CPU) pass
        # the loose band off as a re-verified hardware pin
        pytest.fail("RACON_TPU_HW_TESTS=1 but the JAX platform is not tpu "
                    "— hardware pin not exercised")
    res = polish("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                 "sample_layout.fasta.gz", backend="tpu")
    ed = ed_vs_reference(res, lambda_reference)
    if _on_tpu():
        assert ed == 1282, ed  # hardware pin; host 1283, reference GPU 1385
    else:
        assert abs(ed - 1283) <= 15, ed  # host golden: 1283


@pytest.mark.skipif(not FULL, reason="very slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kf_fasta(lambda_reference):
    """kF with FASTA reads (no qualities) — reference pins 236/1,663,982
    (test/racon_test.cpp:270-276, GPU 1,663,732)."""
    res = polish("sample_reads.fasta.gz", "sample_ava_overlaps.paf.gz",
                 "sample_reads.fasta.gz", fragment_correction=True,
                 match=1, mismatch=-1, gap=-1, drop=False)
    assert len(res) == 236  # reference: 236
    assert sum(len(d) for _, d in res) == 1662904  # reference: 1663982


@pytest.mark.skipif(not FULL, reason="very slow on 1-core host; "
                    "set RACON_TPU_FULL_GOLDEN=1")
def test_fragment_correction_kf_paf(lambda_reference):
    res = polish("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
                 "sample_reads.fastq.gz", fragment_correction=True,
                 match=1, mismatch=-1, gap=-1, drop=False)
    assert len(res) == 236  # reference: 236
    assert sum(len(d) for _, d in res) == 1657837  # reference: 1658216
