"""Memory budget, streaming input, and pressure-driven degradation
(resilience/budget.py + streamio.py + the consumers).

Units: watermark transitions with a fake RSS sampler (ok -> soft ->
hard, latch, callbacks, flight dump), the spill-file round trip (park /
load / torn), the per-chunk byte-range index, and the three ``mem.*``
fault points.  End-to-end: streaming is byte-identical to the in-memory
path; a tight budget forces the hard watermark and the pressure
lattice's degradation edges — the phase pipeline collapses
(pipelined -> sequential) and the batch executor drains inline
(batched -> stream-sequential) — while output stays byte-identical;
``mem.pressure`` / ``mem.spill`` drills are absorbed; a torn input tail
quarantines its chunk, not the run; and ``mem.oom:kill=1`` really
SIGKILLs a fleet worker whose chunk re-dispatches to a byte-identical
finish.  Plus the admission ladder's memory rung, the ``mem.rss``
telemetry surfaces, and the bench ``stream`` entry contract.
"""

import gzip
import json
import os

import pytest

import racon_tpu
from racon_tpu.resilience import budget, faults
from racon_tpu.resilience.budget import MemoryBudget
from racon_tpu.streamio import StreamIndex, WorkingSet

from test_faults import _ARGS, _assert_report_sums, _oracle, _tpu_run, \
    _write_dataset


def _edges(report_dict):
    """Every (from, to) degradation edge across all phase reports."""
    return [(g["from"], g["to"])
            for ph in report_dict["phases"].values()
            for g in ph.get("degradations", []) if isinstance(g, dict)]


# ------------------------------------------------- unit: watermark machine

def test_watermark_transitions_latch_and_callbacks():
    rss = {"v": 10.0}
    softs, hards = [], []
    b = MemoryBudget(100, rss_source=lambda: rss["v"],
                     on_soft=lambda: softs.append(1),
                     on_hard=lambda: hards.append(1))
    assert b.enabled
    assert b.soft_mb == pytest.approx(80.0)
    assert b.hard_mb == pytest.approx(95.0)
    assert b.poll(fault_check=False) == "ok" and not softs
    rss["v"] = 85.0
    assert b.poll(fault_check=False) == "soft"
    assert softs == [1] and not hards
    rss["v"] = 96.0
    assert b.poll(fault_check=False) == "hard"
    assert hards == [1] and b.hard_latched()
    # recovery drops the level but the hard latch is per-run: the
    # consumers' degradations (collapsed pipeline, inline batching)
    # are one-way edges
    rss["v"] = 10.0
    assert b.poll(fault_check=False) == "ok"
    assert b.level() == "ok" and b.hard_latched()
    assert b.peak_mb() == pytest.approx(96.0)
    rss["v"] = 99.0
    b.poll(fault_check=False)
    assert hards == [1]            # the hard callback fires exactly once


def test_unbudgeted_is_disabled():
    b = MemoryBudget(0, rss_source=lambda: 1e9)
    assert not b.enabled
    assert b.poll(fault_check=False) == "ok"
    assert not b.hard_latched()
    assert budget.at_least("hard", "soft")
    assert budget.at_least("soft", "soft")
    assert not budget.at_least("ok", "soft")


def test_hard_watermark_dumps_flight_recorder(monkeypatch):
    from racon_tpu.obs import flight

    dumps = []
    monkeypatch.setattr(
        flight, "dump",
        lambda reason, dir_path=None, **kw: dumps.append((reason, kw)))
    rss = {"v": 10.0}
    b = MemoryBudget(100, rss_source=lambda: rss["v"])
    b.poll(fault_check=False)
    rss["v"] = 99.0
    b.poll(fault_check=False)
    assert dumps == [("mem_hard_watermark",
                      {"rss_mb": 99.0, "budget_mb": 100, "forced": False})]
    rss["v"] = 99.5
    b.poll(fault_check=False)      # latched: one post-mortem per run
    assert len(dumps) == 1


def test_mem_fault_points_registered():
    assert {"mem.pressure", "mem.spill", "mem.oom"} <= faults.KNOWN_POINTS
    specs = faults.parse_spec("mem.oom:kill=1:count=1,mem.spill")
    assert specs[0].point == "mem.oom" and specs[0].kill
    assert specs[1].point == "mem.spill"


def test_mem_pressure_fault_forces_hard_breach(monkeypatch):
    """An injected mem.pressure raise is absorbed as a forced hard
    breach — the deterministic pressure drill — even when real RSS is
    nowhere near the watermarks."""
    monkeypatch.setenv("RACON_TPU_FAULT", "mem.pressure")
    faults.reset()
    b = MemoryBudget(1000, rss_source=lambda: 1.0)
    assert b.poll() == "hard"
    assert b.hard_latched()
    # the watchdog's polls skip the fault point: invocation counting
    # stays on the synchronous per-chunk schedule
    b2 = MemoryBudget(1000, rss_source=lambda: 1.0)
    faults.reset()
    assert b2.poll(fault_check=False) == "ok"
    faults.reset()


# ------------------------------------------------------ unit: spill files

def test_spill_roundtrip_and_unlink(tmp_path):
    payloads = [("seqs", b"ACGT" * 50), ("ovls", b"r0\t0\tt0\n")]
    path = budget.park_bytes(payloads, str(tmp_path), "chunk0")
    assert path is not None and os.path.exists(path)
    assert budget.load_spill(path) == payloads
    assert not os.path.exists(path)          # spill files are one-shot


def test_torn_spill_file_raises(tmp_path):
    path = budget.park_bytes([("seqs", b"A" * 200)], str(tmp_path), "c1")
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-50])
    with pytest.raises(ValueError, match="torn spill"):
        budget.load_spill(path)


def test_working_set_parks_and_realizes_via_spill(tmp_path):
    ws = WorkingSet(2, b">r0\nACGT\n", b"@HD\nr0\t0\tt2\n",
                    "reads.fasta", "ovl.sam")
    assert ws.nbytes() > 0
    assert ws.park(str(tmp_path)) is True
    assert ws.parked() and ws.nbytes() == 0
    seqs_p, ovls_p = ws.realize(str(tmp_path))
    assert open(seqs_p, "rb").read() == b">r0\nACGT\n"
    assert open(ovls_p, "rb").read() == b"@HD\nr0\t0\tt2\n"
    assert not ws.parked()                   # spill consumed on realize


def test_mem_spill_fault_aborts_park_keeps_buffers(tmp_path, monkeypatch):
    monkeypatch.setenv("RACON_TPU_FAULT", "mem.spill")
    faults.reset()
    ws = WorkingSet(0, b"seqbytes", b"ovlbytes", "r.fasta", "o.sam")
    assert ws.park(str(tmp_path)) is False   # park aborted, not the run
    assert not ws.parked() and ws.nbytes() > 0
    seqs_p, ovls_p = ws.realize(str(tmp_path))
    assert open(seqs_p, "rb").read() == b"seqbytes"
    assert open(ovls_p, "rb").read() == b"ovlbytes"
    faults.reset()


# ------------------------------------------------- unit: byte-range index

def test_stream_index_materializes_per_chunk_subsets(tmp_path):
    from racon_tpu.polisher import _split_fasta

    paths = _write_dataset(tmp_path)
    chunks = _split_fasta(paths[2], 3, str(tmp_path))
    assert chunks is not None and len(chunks) == 3
    idx = StreamIndex(paths[0], paths[1], chunks, str(tmp_path))
    assert idx.fmt == "sam"
    assert all(idx.torn(ci) is None for ci in range(3))
    ws = idx.materialize(1)
    seqs_p, ovls_p = ws.realize(str(tmp_path))
    seqs = open(seqs_p, "rb").read()
    ovls = open(ovls_p, "rb").read()
    # the working set is O(chunk): chunk 1 sees only its own records
    assert b">t1r0" in seqs
    assert b">t0r" not in seqs and b">t2r" not in seqs
    assert ovls.startswith(b"@HD")           # headers copied per chunk
    for line in ovls.splitlines()[1:]:
        assert line.split(b"\t")[2] == b"t1"


# ------------------------------------- e2e: streaming polisher (in-process)

def test_streaming_byte_identical_to_in_memory(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    seq_res, _ = _tpu_run(paths, monkeypatch, {})
    stream_res, p = _tpu_run(paths, monkeypatch,
                             {"RACON_TPU_STREAM_INPUT": "1"})
    assert p._stream_index is not None, "3-contig FASTA target must stream"
    assert stream_res == seq_res == oracle
    d = _assert_report_sums(p)
    mem = d["phases"]["memory"]["extra"]
    assert mem["streamed"] is True
    assert mem["budget_mb"] == 0             # streaming forced, unbudgeted
    assert mem["pressure_level"] == "ok"
    assert mem["peak_rss_mb"] > 0
    assert d["phases"]["memory"]["quarantined"] == []


def test_tight_budget_collapses_batched_to_stream_sequential(
        tmp_path, monkeypatch):
    """RACON_TPU_MEM_BUDGET_MB=64 on a JAX-loaded process: the hard
    watermark latches on the first synchronous poll, streaming
    auto-arms, working sets round-trip through the spill file, the
    batch executor takes the batched -> stream-sequential lattice edge
    — and the output is still byte-identical."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_MEM_BUDGET_MB": "64"})
    assert p._stream, "a memory budget must auto-arm streaming input"
    assert res == oracle
    d = _assert_report_sums(p)
    mem = d["phases"]["memory"]["extra"]
    assert mem["budget_mb"] == 64
    assert mem["pressure_level"] == "hard"
    assert mem["peak_rss_mb"] > 64
    assert ("batched", "stream-sequential") in _edges(d)


def test_pipelined_hard_watermark_collapses_to_sequential(
        tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_PIPELINE_PHASES": "1",
                       "RACON_TPU_MEM_BUDGET_MB": "64"})
    assert p._pipelined and p._stream
    assert res == oracle
    d = _assert_report_sums(p)
    # the align worker stopped running ahead of POA and the pipeline
    # degradation was recorded exactly once
    mem_edges = [(g["from"], g["to"])
                 for g in d["phases"]["memory"].get("degradations", [])]
    assert mem_edges.count(("pipelined", "sequential")) == 1


def test_mem_pressure_drill_byte_identical(tmp_path, monkeypatch):
    """The deterministic pressure drill: a huge budget keeps real RSS
    classified ok, the injected mem.pressure raise forces the hard
    breach anyway, and the degraded schedule changes nothing in the
    output."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_MEM_BUDGET_MB": "1000000",
                       "RACON_TPU_FAULT": "mem.pressure"})
    assert res == oracle
    d = _assert_report_sums(p)
    assert ("batched", "stream-sequential") in _edges(d)


def test_mem_spill_drill_byte_identical(tmp_path, monkeypatch):
    """mem.spill aborts every park under a tight budget: the working
    sets just stay in memory, and the run ends byte-identical."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_MEM_BUDGET_MB": "64",
                       "RACON_TPU_FAULT": "mem.spill"})
    assert res == oracle
    d = _assert_report_sums(p)
    assert d["phases"]["memory"]["quarantined"] == []


# --------------------------------------------- e2e: torn-input quarantine

def test_truncated_overlap_tail_quarantines_chunk_not_run(
        tmp_path, monkeypatch):
    """A SAM file torn mid-record: the owning chunk is quarantined in
    the RunReport and polishes from the working set indexed before the
    tear; identical reads make even that output byte-identical."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    data = open(paths[1], "rb").read()
    with open(paths[1], "wb") as f:
        f.write(data[:-30])                  # cut into the last record
    res, p = _tpu_run(paths, monkeypatch, {"RACON_TPU_STREAM_INPUT": "1"})
    assert p._stream_index is not None
    d = _assert_report_sums(p)
    assert d["phases"]["memory"]["quarantined"], \
        "torn overlap tail must quarantine its chunk"
    # chunk 2 kept t2r0..t2r2 (indexed before the tear) — with
    # identical reads every consensus is still exactly the target;
    # only t2's RC:i header tag honestly reports one read fewer
    assert [s for _, s in res] == [s for _, s in oracle]
    assert [n for n, _ in res[:2]] == [n for n, _ in oracle[:2]]
    assert res[2][0] == oracle[2][0].replace("RC:i:4", "RC:i:3")


def test_gzip_corrupt_reads_tail_quarantines_chunk(tmp_path, monkeypatch):
    """A gzip-corrupt reads tail: decompression recovers the prefix,
    the chunk whose referenced read the tear swallowed is quarantined,
    and the run — which the in-memory path would hand straight to the
    native parser — completes."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    raw = open(paths[0], "rb").read()
    cut = raw.rindex(b">t2r3")
    gz = tmp_path / "reads.fasta.gz"
    # a valid member holding everything before t2r3, then a member with
    # a corrupt header: decompression yields exactly the prefix + error
    gz.write_bytes(gzip.compress(raw[:cut]) + b"\x1f\x8b" + b"\x00" * 20)
    paths = (str(gz), paths[1], paths[2])
    res, p = _tpu_run(paths, monkeypatch, {"RACON_TPU_STREAM_INPUT": "1"})
    assert p._stream_index is not None
    d = _assert_report_sums(p)
    assert d["phases"]["memory"]["quarantined"], \
        "swallowed read must quarantine its chunk"
    # every contig still polishes to the exact target; t2's RC:i tag
    # honestly reports the read the tear swallowed
    assert [s for _, s in res] == [s for _, s in oracle]
    assert [n for n, _ in res[:2]] == [n for n, _ in oracle[:2]]
    assert res[2][0] == oracle[2][0].replace("RC:i:4", "RC:i:3")


# ------------------------------------------- e2e: mem.oom fleet OOM-kill

def test_mem_oom_kill_mid_fleet_resumes_byte_identical(
        tmp_path, monkeypatch):
    """mem.oom:kill=1 is a real OOM-style SIGKILL of worker 0 at the
    top of its first chunk polish: the EOF expires the lease, the chunk
    re-dispatches, and the gathered output is byte-identical.  The
    fault fires before the chunk journals anything, so — unlike the
    worker.result drill — resume may legitimately replay zero windows."""
    from racon_tpu.distrib import Coordinator

    paths = _write_dataset(tmp_path, n_targets=6)
    oracle_b = "".join(
        f">{n}\n{s}\n" for n, s in _oracle(paths)).encode()
    monkeypatch.setenv("RACON_TPU_FAULT", "mem.oom:kill=1:count=1")
    monkeypatch.setenv("RACON_TPU_DISTRIB_FAULT_WORKER", "0")
    coord = Coordinator(paths[0], paths[1], paths[2],
                        str(tmp_path / "coord"), args=dict(_ARGS),
                        backend="cpu", workers=3,
                        report_path=str(tmp_path / "report.json"))
    out = str(tmp_path / "polished.fasta")
    result = coord.run(out, timeout=180)
    assert open(out, "rb").read() == oracle_b
    assert result["served"]["fleet"] == result["chunks"]
    assert result["counters"]["workers_dead"] == 1
    assert result["counters"]["redispatches"] >= 1


# ------------------------------------- admission ladder: the memory rung

class _FakeSession:
    backend = "tpu"

    def __init__(self, workdir):
        self.workdir = str(workdir)
        os.makedirs(os.path.join(self.workdir, "jobs"), exist_ok=True)

    def job_dir(self, job_id):
        return os.path.join(self.workdir, "jobs", job_id)

    def stats(self):
        return {}


def _scheduler(tmp_path):
    from racon_tpu.serve import Scheduler

    return Scheduler(_FakeSession(tmp_path / "state"), queue_depth=100,
                     max_jobs=100, window_budget=12, tenant_quota=0)


def test_admission_hard_memory_rejects(tmp_path):
    from racon_tpu.serve import AdmissionError, JobSpec

    paths = _write_dataset(tmp_path)
    sched = _scheduler(tmp_path)
    sched.memory_source = lambda: "hard"     # injectable sampler seam
    with pytest.raises(AdmissionError, match="memory pressure"):
        sched.submit(JobSpec(*paths, args=dict(_ARGS), submitter="acme"))
    assert sched.admission["rejected_memory"] == 1
    assert not sched._queues["device"] and not sched._queues["host"]


def test_admission_soft_memory_sheds_to_host_lane(tmp_path):
    from racon_tpu.serve import JobSpec

    paths = _write_dataset(tmp_path)
    sched = _scheduler(tmp_path)
    sched.memory_source = lambda: "soft"
    sched.submit(JobSpec(*paths, args=dict(_ARGS), submitter="acme"))
    assert len(sched._queues["host"]) == 1
    assert not sched._queues["device"]
    assert sched.admission["shed_memory"] == 1
    job = next(iter(sched._jobs.values()))
    assert job.demotions
    assert job.demotions[0]["cause"].startswith("shed (memory)")


# ------------------------------------------------- telemetry + obs fleet

def test_telemetry_tick_carries_rss_gauge():
    from racon_tpu import obs

    entry = obs.telemetry_tick(queue_depth=3)
    assert entry["queue_depth"] == 3
    assert entry["mem.rss_mb"] > 0.0


def test_obs_fleet_tracks_per_worker_peak_rss():
    from racon_tpu.obs.__main__ import fleet_breakdown

    doc = {"traceEvents": [
        {"name": "mem.rss", "ph": "i", "s": "t", "ts": 0, "pid": 7,
         "tid": 1, "args": {"rss_mb": 123.0, "chunk": 0}},
        {"name": "mem.rss", "ph": "i", "s": "t", "ts": 5, "pid": 7,
         "tid": 1, "args": {"rss_mb": 456.5, "chunk": 1}},
        {"name": "mem.rss", "ph": "i", "s": "t", "ts": 9, "pid": 7,
         "tid": 1, "args": {"rss_mb": "bogus"}},      # ignored, not fatal
    ]}
    b = fleet_breakdown(doc)
    assert not b["violations"]
    (p,) = b["processes"].values()
    assert p["peak_rss_mb"] == 456.5


# --------------------------------------------------- bench stream entry

def test_bench_stream_entry_normalizes_as_fixed_point():
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from bench import mem_stamp, normalize_entry
    finally:
        sys.path.remove(root)
    from racon_tpu.obs import bench_track

    entry = {
        "metric": "stream: polished Mbp/sec (synthetic ONT 0.004 Mbp 6x, "
                  "SAM, w=100, streamed, end-to-end)",
        "value": 0.0005, "unit": "Mbp/s", "vs_baseline": None,
        "cost_model": None, "pack_split": None, "serial_steps": None,
        "cells_banded": None, "band_hit_rate": None,
        "peak_rss_mb": 337.7, "budget_mb": 2048,
        "stream": {"contigs": 4, "streamed": True, "pressure_level": "ok",
                   "quarantined": 0, "degradations": 0},
        "mbp": 0.004, "input": "sam", "profile": "stream-ont",
    }
    assert normalize_entry(dict(entry)) == entry
    # stream entries form their own trend series for the regression gate
    assert (bench_track.series_key(entry)
            != bench_track.series_key(dict(entry, profile="ont")))
    # pre-budget entries recover the stamp from the embedded report...
    legacy = {k: v for k, v in entry.items()
              if k not in ("peak_rss_mb", "budget_mb", "stream")}
    legacy["report"] = {"memory": {"extra": {"peak_rss_mb": 300.5,
                                             "budget_mb": 1024}}}
    n = normalize_entry(legacy)
    assert n["peak_rss_mb"] == 300.5 and n["budget_mb"] == 1024
    # ...and entries with no memory accounting get explicit nulls
    legacy2 = {k: v for k, v in entry.items()
               if k not in ("peak_rss_mb", "budget_mb", "stream")}
    norm = normalize_entry(legacy2)
    assert norm["peak_rss_mb"] is None and norm["budget_mb"] is None
    assert mem_stamp({"memory": {"extra": {"peak_rss_mb": 1.0,
                                           "budget_mb": 2}}}) == (1.0, 2)
    assert mem_stamp(None) == (None, None)
