"""Tier-1 coverage for the static-analysis subsystem.

Three contracts:
* the analyzer keeps the real tree clean (this is the CI gate);
* each lint rule fires on its fixture snippet and nowhere else;
* the jaxpr audit enforces the declared recompile budgets — widening
  the audited grid must fail, the shipped grid must pass.
"""

import os
import subprocess
import sys

import pytest

from racon_tpu import config
from racon_tpu.analysis import jaxpr_audit, lint
from racon_tpu.analysis.__main__ import main as analysis_main
from racon_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXROOT = os.path.join(REPO, "tests", "analysis_fixtures")

#: rule id -> fixture file carrying exactly that violation class
FIXTURES = {
    "tracer-leak": "racon_tpu/ops/tracer_leak.py",
    "kernel-cache-key": "racon_tpu/ops/cache_key.py",
    "env-registry": "racon_tpu/ops/env_read.py",
    "fault-point": "racon_tpu/ops/bad_fault_point.py",
    "device-except": "racon_tpu/ops/broad_except.py",
    "wall-clock": "racon_tpu/resilience/wall_clock.py",
    "thread-discipline": "racon_tpu/serve/bad_threads.py",
}

#: per-file rules (knob-docs is project-level; covered separately)
_FILE_RULES = [r for r in ALL_RULES if r.id != "knob-docs"]


# -------------------------------------------------------------------------
# AST lint: fixtures fire, real tree clean
# -------------------------------------------------------------------------

@pytest.mark.parametrize("rule_id,rel", sorted(FIXTURES.items()))
def test_each_rule_fires_exactly_on_its_fixture(rule_id, rel):
    vs = lint.run_lint(FIXROOT, paths=[rel], rules=_FILE_RULES)
    assert vs, f"{rule_id} did not fire on {rel}"
    assert {v.rule for v in vs} == {rule_id}, (
        f"unexpected rules on {rel}: {[v.render() for v in vs]}")
    assert all(v.path == rel for v in vs)


def test_tracer_leak_fixture_catches_every_flavor():
    vs = lint.run_lint(FIXROOT, paths=[FIXTURES["tracer-leak"]],
                       rules=[RULES_BY_ID["tracer-leak"]])
    text = " ".join(v.message for v in vs)
    for flavor in ("float()", ".item()", "np.asarray", "data-dependent"):
        assert flavor in text, f"missing {flavor}: {text}"


def test_device_except_fixture_catches_bare_and_broad():
    vs = lint.run_lint(FIXROOT, paths=[FIXTURES["device-except"]],
                       rules=[RULES_BY_ID["device-except"]])
    assert len(vs) == 2
    assert any("bare" in v.message for v in vs)
    assert any("BLE001" in v.message for v in vs)


def test_wall_clock_rule_scopes_obs_package():
    # the tracer's monotonic-clock contract: racon_tpu/obs/ is inside
    # the wall-clock scope, so a time.time() span there is a violation
    rel = "racon_tpu/obs/wall_clock_obs.py"
    vs = lint.run_lint(FIXROOT, paths=[rel],
                       rules=[RULES_BY_ID["wall-clock"]])
    assert vs and {v.rule for v in vs} == {"wall-clock"}
    assert all(v.path == rel for v in vs)


def test_knob_docs_rule_fires_when_readme_lacks_knobs():
    # The fixture root's README documents no knobs, so every registered
    # knob is reported undocumented.
    vs = lint.run_lint(FIXROOT, paths=[], rules=[RULES_BY_ID["knob-docs"]])
    assert {v.rule for v in vs} == {"knob-docs"}
    assert len(vs) == len(config.KNOBS)


def test_real_tree_is_clean():
    vs = lint.run_lint(REPO)
    assert vs == [], "\n".join(v.render() for v in vs)


def test_inline_suppression(tmp_path):
    (tmp_path / "snippet.py").write_text(
        "try:\n"
        "    pass\n"
        "except:  # lint: disable=device-except\n"
        "    pass\n")
    rule = [RULES_BY_ID["device-except"]]
    assert lint.run_lint(str(tmp_path), paths=["snippet.py"],
                         rules=rule) == []
    (tmp_path / "snippet.py").write_text(
        "try:\n    pass\nexcept:\n    pass\n")
    assert len(lint.run_lint(str(tmp_path), paths=["snippet.py"],
                             rules=rule)) == 1


# -------------------------------------------------------------------------
# CLI: exit codes + baseline round-trip
# -------------------------------------------------------------------------

def test_cli_exit_zero_on_repo():
    assert analysis_main(["--no-jaxpr", "--repo-root", REPO]) == 0


def test_cli_exit_nonzero_on_fixture_tree():
    assert analysis_main(["--no-jaxpr", "--repo-root", FIXROOT]) == 1


def test_cli_baseline_roundtrip(tmp_path):
    base = str(tmp_path / "baseline.json")
    # accept the fixture tree's violations, then a re-run is clean
    assert analysis_main(["--no-jaxpr", "--repo-root", FIXROOT,
                          "--baseline", base, "--write-baseline"]) == 0
    assert analysis_main(["--no-jaxpr", "--repo-root", FIXROOT,
                          "--baseline", base]) == 0


def test_cli_list_rules(capsys):
    assert analysis_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in list(FIXTURES) + ["knob-docs", "recompile-budget",
                                 "jaxpr-forbidden-primitive"]:
        assert rid in out


def test_cli_subprocess_full_run():
    """The acceptance gate: `python -m racon_tpu.analysis` (both
    engines) exits 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "racon_tpu.analysis"],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -------------------------------------------------------------------------
# jaxpr audit: shipped grid within budget, widened grid rejected
# -------------------------------------------------------------------------

def test_audit_shipped_grids_pass():
    assert jaxpr_audit.run_audit() == []


def test_audit_fails_on_widened_poa_grid():
    vs = jaxpr_audit.audit_poa(window_lengths=(500, 1000, 1500))
    assert any(v.rule == "recompile-budget" for v in vs), \
        [v.render() for v in vs]


def test_audit_fails_on_widened_align_buckets():
    from racon_tpu.ops import align
    widened = tuple(align.BUCKETS) + ((16384, 4096),)
    vs = jaxpr_audit.audit_align(buckets=widened)
    assert any(v.rule == "recompile-budget" for v in vs)


def test_audit_flags_forbidden_primitive():
    import jax

    def cb(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    closed = jax.make_jaxpr(cb)(
        jax.ShapeDtypeStruct((4,), "float32"))
    vs = jaxpr_audit.check_jaxpr(closed, "x.py", "cb")
    assert any(v.rule == "jaxpr-forbidden-primitive" for v in vs)


def test_audit_flags_float64():
    import jax
    import jax.numpy as jnp

    def f64(x):
        return x.astype(jnp.float64) * 2

    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(f64)(
            jax.ShapeDtypeStruct((4,), "float32"))
    vs = jaxpr_audit.check_jaxpr(closed, "x.py", "f64")
    assert any(v.rule == "jaxpr-float64" for v in vs)


# -------------------------------------------------------------------------
# stale-knob surfacing (satellite: typo'd knobs must not vanish)
# -------------------------------------------------------------------------

def test_unknown_env_knobs_detects_typos():
    env = {"RACON_TPU_BOGUS_KNOB": "1", "RACON_TPU_PALLAS": "1",
           "HOME": "/root"}
    assert config.unknown_env_knobs(env) == ["RACON_TPU_BOGUS_KNOB"]
    assert config.unknown_env_knobs({"RACON_TPU_PALLAS": "1"}) == []


def test_run_report_surfaces_stale_knobs(monkeypatch):
    from racon_tpu.resilience.report import RunReport

    monkeypatch.setenv("RACON_TPU_TYPOD_KNOB", "1")
    rep = RunReport().finalize()
    assert "RACON_TPU_TYPOD_KNOB" in rep.as_dict()["unknown_knobs"]
    assert "RACON_TPU_TYPOD_KNOB" in rep.summary()["unknown_knobs"]

    monkeypatch.delenv("RACON_TPU_TYPOD_KNOB")
    rep = RunReport().finalize()
    assert rep.as_dict()["unknown_knobs"] == []
    assert "unknown_knobs" not in rep.summary()
