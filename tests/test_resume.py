"""Preemption tolerance: the crash-safe window journal, SIGKILL resume,
the wedge classifier, and the bulk align-job-lengths FFI.

The headline contract (ISSUE acceptance): a polish killed mid-run with
SIGKILL, resumed via `--resume-journal`, produces byte-identical output
to an uninterrupted run, and the run report counts resumed vs freshly
computed windows.  Everything here runs on the CPU backend in tier-1.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import racon_tpu
from racon_tpu.pipeline import Pipeline
from racon_tpu.resilience import faults, lattice, watchdog
from racon_tpu.resilience.journal import Journal, input_fingerprint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, ROOT)  # for `import bench` (repo-root script)

_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)


def _write_dataset(tmp_path, n_targets=3, n_reads=4):
    """Identical-read PAF dataset (same shape as test_faults.py): w=100
    over 200 bp targets -> 6 windows, all byte-stable across backends."""
    import random
    rng = random.Random(11)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.paf", "w") as of:
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t200\t0\t200\t+\tt{t}\t200\t0\t200"
                         f"\t200\t200\t60\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.paf"),
            str(tmp_path / "targets.fasta"))


def _cli(paths, *extra, env=None, window=100):
    cmd = [sys.executable, "-m", "racon_tpu.cli",
           "-w", str(window), "-q", "10", "-e", "0.3",
           "-m", "5", "-x", "-4", "-g", "-8", *extra, *paths]
    full_env = dict(os.environ, JAX_PLATFORMS="cpu")
    full_env.pop("RACON_TPU_FAULT", None)
    full_env.update(env or {})
    return subprocess.run(cmd, cwd=ROOT, env=full_env, capture_output=True)


# ------------------------------------------------------------ unit: faults

def test_new_fault_points_registered():
    assert {"journal.append", "journal.replay",
            "watchdog.call"} <= faults.KNOWN_POINTS


def test_parse_kill_spec():
    (spec,) = faults.parse_spec("journal.append:batch=3:kill=1")
    assert spec.kill and spec.batch == 3
    (spec,) = faults.parse_spec("journal.append:kill=0")
    assert not spec.kill
    with pytest.raises(ValueError):
        faults.parse_spec("journal.append:kill=x")


# ------------------------------------------------------- unit: fingerprint

def test_fingerprint_sensitivity(tmp_path):
    paths = _write_dataset(tmp_path)
    fp = input_fingerprint(paths, _ARGS, "cpu")
    assert fp == input_fingerprint(paths, _ARGS, "cpu")
    assert fp != input_fingerprint(paths, _ARGS, "tpu")
    assert fp != input_fingerprint(paths, dict(_ARGS, window_length=50),
                                   "cpu")
    # thread count legally varies between the killed and resumed run
    assert fp == input_fingerprint(paths, dict(_ARGS, num_threads=8), "cpu")
    with open(paths[0], "a") as f:
        f.write(">extra\nACGT\n")
    assert fp != input_fingerprint(paths, _ARGS, "cpu")


def test_journal_roundtrip_and_torn_tail(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    j = Journal(jp, "f" * 64)
    j.append_window(0, 0, 3, "xla", b"ACGT", True)
    j.append_cigar(2, "hirschberg", "4=")
    j.close()
    r = Journal(jp, "f" * 64, resume=True)
    assert r.resumed
    assert r.windows[0].payload == b"ACGT" and r.windows[0].polished
    assert r.cigars[2].cigar == "4="
    r.close()
    # chop mid-record: the torn tail is dropped, the prefix survives
    size = os.path.getsize(jp)
    with open(jp, "r+b") as f:
        f.truncate(size - 5)
    t = Journal(jp, "f" * 64, resume=True)
    assert t.windows[0].payload == b"ACGT" and 2 not in t.cigars
    t.close()


def test_journal_fingerprint_mismatch_modes(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    Journal(jp, "a" * 64).close()
    from racon_tpu.resilience.journal import JournalError
    with pytest.raises(JournalError):
        Journal(jp, "b" * 64, resume=True, on_mismatch="error")
    fresh = Journal(jp, "b" * 64, resume=True, on_mismatch="fresh")
    assert not fresh.resumed and not fresh.windows
    fresh.close()


# --------------------------------------------- e2e: SIGKILL -> resume (CLI)

def test_sigkill_mid_polish_resume_byte_identical(tmp_path):
    """The acceptance criterion: kill -9 mid-run, resume, same bytes."""
    paths = _write_dataset(tmp_path)
    baseline = _cli(paths)
    assert baseline.returncode == 0, baseline.stderr.decode()

    jp = str(tmp_path / "run.journal")
    killed = _cli(paths, "--journal", jp,
                  env={"RACON_TPU_FAULT": "journal.append:batch=3:kill=1"})
    assert killed.returncode == -9        # died by SIGKILL, not cleanly
    with open(jp) as f:
        lines = f.read().splitlines()
    assert 1 < len(lines) < 7             # header + a strict subset served

    rp = str(tmp_path / "resume_report.json")
    resumed = _cli(paths, "--resume-journal", jp, "--report", rp)
    assert resumed.returncode == 0, resumed.stderr.decode()
    assert resumed.stdout == baseline.stdout
    with open(rp) as f:
        cons = json.load(f)["phases"]["consensus"]
    assert cons["served"]["journal"] == len(lines) - 1
    assert cons["served"]["journal"] + cons["served"]["host"] == 6


def test_resume_with_torn_last_line(tmp_path):
    paths = _write_dataset(tmp_path)
    baseline = _cli(paths)
    jp = str(tmp_path / "run.journal")
    full = _cli(paths, "--journal", jp)
    assert full.returncode == 0 and full.stdout == baseline.stdout
    size = os.path.getsize(jp)
    with open(jp, "r+b") as f:
        f.truncate(size - 10)             # SIGKILL mid-append simulacrum
    resumed = _cli(paths, "--resume-journal", jp)
    assert resumed.returncode == 0
    assert resumed.stdout == baseline.stdout
    assert b"torn trailing" in resumed.stderr


def test_resume_wrong_params_refused(tmp_path):
    paths = _write_dataset(tmp_path)
    jp = str(tmp_path / "run.journal")
    assert _cli(paths, "--journal", jp).returncode == 0
    r = _cli(paths, "--resume-journal", jp, window=50)
    assert r.returncode == 1
    err = r.stderr.decode()
    assert "refusing to resume" in err
    assert "Traceback" not in err         # single-line contract


# ------------------------------------------ e2e: device-path journal resume

def test_tpu_journal_resume_mixes_tiers(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    for k, v in {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
                 "RACON_TPU_BATCH_WINDOWS": "8"}.items():
        monkeypatch.setenv(k, v)
    jp = str(tmp_path / "run.journal")
    p = racon_tpu.create_polisher(*paths, backend="tpu", journal_path=jp,
                                  **_ARGS)
    p.initialize()
    oracle = p.polish(True)
    assert p.report.as_dict()["phases"]["consensus"]["served"]["xla"] == 6

    # keep header + 3 window records: a run killed mid-batch
    with open(jp) as f:
        lines = f.read().splitlines(keepends=True)
    with open(jp, "w") as f:
        f.writelines(lines[:4])

    p2 = racon_tpu.create_polisher(*paths, backend="tpu", journal_path=jp,
                                   resume_journal=True, **_ARGS)
    p2.initialize()
    assert p2.polish(True) == oracle
    cons = p2.report.as_dict()["phases"]["consensus"]
    assert cons["served"]["journal"] == 3 and cons["served"]["xla"] == 3

    # the resumed journal is now complete: a third run replays everything
    p3 = racon_tpu.create_polisher(*paths, backend="tpu", journal_path=jp,
                                   resume_journal=True, **_ARGS)
    p3.initialize()
    assert p3.polish(True) == oracle
    cons = p3.report.as_dict()["phases"]["consensus"]
    assert cons["served"]["journal"] == 6 and cons["served"]["xla"] == 0


def test_env_knob_arms_autoresume(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    jp = str(tmp_path / "auto.journal")
    monkeypatch.setenv("RACON_TPU_JOURNAL", jp)
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    oracle = p.polish(True)
    with open(jp) as f:
        assert len(f.read().splitlines()) == 7   # header + 6 windows
    p2 = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p2.initialize()
    assert p2.polish(True) == oracle
    cons = p2.report.as_dict()["phases"]["consensus"]
    assert cons["served"]["journal"] == 6 and cons["served"]["host"] == 0


# -------------------------------------------------------------- unit: wedge

def test_wedge_tracker_streaks(monkeypatch):
    monkeypatch.setenv("RACON_TPU_WEDGE_LIMIT", "2")
    t = watchdog.WedgeTracker()
    assert t.record_timeout("xla") == 1 and not t.is_wedged("xla")
    t.record_success("xla")               # slow-but-alive clears the streak
    assert t.streak("xla") == 0
    t.record_timeout("xla")
    t.record_timeout("xla")
    assert t.is_wedged("xla") and not t.is_wedged("ls")
    monkeypatch.setenv("RACON_TPU_WEDGE_LIMIT", "0")
    assert not t.is_wedged("xla")         # 0 disables classification


def test_wedged_tier_short_circuits_lattice(monkeypatch):
    monkeypatch.setenv("RACON_TPU_WEDGE_LIMIT", "2")
    watchdog.reset()
    watchdog.tracker().record_timeout("xla")
    watchdog.tracker().record_timeout("xla")
    calls = []
    with pytest.raises(lattice.TierWedged):
        lattice.serve_with_bisect([1, 2], lambda sub: calls.append(sub),
                                  tier="xla", retries=3)
    assert not calls                      # no deadline burned on a wedge
    watchdog.reset()


def test_wedged_tier_degrades_to_host_e2e(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    p0 = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p0.initialize()
    oracle = p0.polish(True)
    for k, v in {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
                 "RACON_TPU_BATCH_WINDOWS": "8",
                 "RACON_TPU_DEVICE_TIMEOUT": "0.3",
                 "RACON_TPU_WEDGE_LIMIT": "2",
                 # invocation 0 (pipelined submit) fails synchronously so
                 # the lattice's retries run under the watchdog; every
                 # later invocation hangs -> two consecutive timeouts ->
                 # wedged -> demote, instead of one deadline per retry
                 "RACON_TPU_FAULT": ("poa.run.xla:batch=0:count=1,"
                                     "poa.run.xla:hang=1")}.items():
        monkeypatch.setenv(k, v)
    p = racon_tpu.create_polisher(*paths, backend="tpu", **_ARGS)
    p.initialize()
    assert p.polish(True) == oracle
    cons = p.report.as_dict()["phases"]["consensus"]
    assert cons["served"]["host"] == 6
    assert any(d["from"] == "xla" and d["to"] == "host"
               for d in cons["degradations"])
    assert "WatchdogTimeout" in json.dumps(cons["causes"])


# --------------------------------------------------------- unit: bulk FFI

def test_align_job_lengths_bulk_matches_loop(tmp_path):
    paths = _write_dataset(tmp_path)
    p = Pipeline(*paths, **_ARGS)
    p.prepare()
    assert p.num_align_jobs() > 0
    bulk = p.align_job_lengths()
    loop = p._align_job_lengths_loop()
    assert bulk.dtype == np.uint32 and bulk.shape == loop.shape
    assert np.array_equal(bulk, loop)
    assert int(bulk[0, 0]) == 200 and int(bulk[0, 1]) == 200


# ------------------------------------------------------ unit: bench honesty

def test_bench_normalize_entry_backfills_unreachable():
    import bench
    old = {"metric": "Mbp/s [TPU UNREACHABLE: host path only]",
           "value": 0.01, "vs_baseline": 0.0}
    fixed = bench.normalize_entry(old)
    assert fixed["vs_baseline"] is None
    assert fixed["device_status"] == "unreachable"
    assert old["vs_baseline"] == 0.0      # input not mutated
    # a measured zero on a reachable device is a real measurement
    measured = {"metric": "Mbp/s (device)", "value": 0.0,
                "vs_baseline": 0.0}
    assert bench.normalize_entry(measured)["vs_baseline"] == 0.0
    assert "device_status" not in bench.normalize_entry(measured)


def test_bench_degraded_result_is_null_not_zero():
    import bench
    e = bench.degraded_result(1.25, "; note")
    assert e["vs_baseline"] is None
    assert e["device_status"] == "unreachable"
    assert "TPU UNREACHABLE" in e["metric"]
    assert e["cost_model"] is None       # explicit: no prediction joined
    # round-trips through the reader unchanged
    assert bench.normalize_entry(json.loads(json.dumps(e))) == e


def test_bench_normalize_entry_malformed_partial_summaries():
    """The committed log is hand-editable and spans writer generations:
    backfill must cope with entries missing BOTH phase_wall and report,
    and with report summaries whose tier walls are partial/absent."""
    import bench
    # neither phase_wall nor report: no phase_wall invented, cost_model
    # backfills null
    bare = bench.normalize_entry({"value": 0.01})
    assert "phase_wall" not in bare and bare["cost_model"] is None
    # report present but not a dict / summary rows without wall_s: only
    # the well-formed rows yield a backfilled wall
    assert "phase_wall" not in bench.normalize_entry(
        {"value": 0.01, "report": "corrupt"})
    mixed = bench.normalize_entry({"value": 0.01, "report": {
        "alignment": {"served": {"xla": 5}},            # wall_s absent
        "consensus": {"wall_s": {"v2": 1.5, "host": 0.5}},
        "stitch": {"wall_s": "not-a-dict"},
        "parse": 3.0,                                    # not even a dict
    }})
    assert mixed["phase_wall"] == {"consensus": 2.0}
    # an explicit stamp (even {}) is the writer's claim: never overwritten
    stamped = bench.normalize_entry(
        {"value": 0.01, "phase_wall": {},
         "report": {"consensus": {"wall_s": {"v2": 1.0}}}})
    assert stamped["phase_wall"] == {}
    # an existing cost_model stamp survives untouched
    cm = {"profile": "cpu-host", "phases": {}, "ok": True}
    assert bench.normalize_entry(
        {"value": 0.01, "cost_model": cm})["cost_model"] == cm
