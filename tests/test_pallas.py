"""Pallas POA kernel differential test (interpret mode on the CPU backend;
on real TPU hardware the same kernel runs compiled — the bench exercises
that)."""

import random

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.ops import poa, poa_pallas
from racon_tpu.ops.encoding import decode, encode


def mutate(seq, rate, rng):
    out = bytearray()
    for c in seq:
        r = rng.random()
        if r < rate / 3:
            out.append(rng.choice(b"ACGT"))
        elif r < 2 * rate / 3:
            pass
        elif r < rate:
            out.append(c)
            out.append(rng.choice(b"ACGT"))
        else:
            out.append(c)
    return bytes(out)


def test_pallas_driver_path_end_to_end(tmp_path, monkeypatch):
    """Full TpuPolisher flow with the Pallas branch of the consensus driver
    (interpret mode), on a small synthetic dataset: exercises batching,
    padding, argument marshalling, and result unpacking."""
    import random as _r

    import racon_tpu

    rng = _r.Random(5)
    target = "".join(rng.choice("ACGT") for _ in range(240))
    with open(tmp_path / "target.fasta", "w") as f:
        f.write(f">tgt\n{target}\n")
    with open(tmp_path / "reads.fasta", "w") as f:
        for i in range(4):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "ovl.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(4):
            f.write(f"r{i}\t0\ttgt\t1\t60\t240M\t*\t0\t0\t{target}\t*\n")

    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "4")
    p = racon_tpu.TpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ovl.sam"),
                              str(tmp_path / "target.fasta"),
                              window_length=80, quality_threshold=10,
                              error_threshold=0.3, match=5, mismatch=-4,
                              gap=-8, num_threads=1)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    assert res[0][1] == target  # perfect reads -> perfect consensus


def test_pallas_production_geometry_real_window():
    """Production-size config (N=1536, L=768, BB=512) on a real lambda
    window: catches geometry-dependent bugs the small-config differentials
    can't (tiling, padding, order-insert at scale)."""
    import os

    from tests.conftest import DATA
    if not os.path.isdir(DATA):
        pytest.skip(f"lambda test data not found at {DATA} "
                    "(set RACON_TPU_TEST_DATA)")

    import racon_tpu
    from racon_tpu.ops import poa_driver

    pl = racon_tpu.Pipeline(DATA + "sample_reads.fastq.gz",
                            DATA + "sample_overlaps.sam.gz",
                            DATA + "sample_layout.fasta.gz",
                            match=5, mismatch=-4, gap=-8, trim=False)
    pl.initialize()
    target = next((i for i in range(pl.num_windows())
                   if 20 <= pl.window_info(i)[0] - 1 <= 32), None)
    if target is None:
        pytest.skip("no window with 21-32 layers in this dataset")
    wx = pl.export_window(target)

    cfg = poa_driver.make_config(512, 32, 5, -4, -8)
    pk = poa_pallas.build_pallas_poa_kernel(cfg, interpret=True)(1)

    B = 1
    bb = np.zeros((B, cfg.max_backbone), np.int32)
    bbw = np.zeros((B, cfg.max_backbone), np.int32)
    bl = np.zeros((B, 1), np.int32)
    nl = np.zeros((B, 1), np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), np.int32)
    ws = np.zeros((B, cfg.depth, cfg.max_len), np.int32)
    lens = np.zeros((B, cfg.depth), np.int32)
    bg = np.zeros((B, cfg.depth), np.int32)
    en = np.zeros((B, cfg.depth), np.int32)
    L = len(wx.backbone)
    bb[0, :L] = encode(wx.backbone)
    bbw[0, :L] = wx.backbone_weights
    bl[0, 0] = L
    keep = [j for j in range(len(wx.lens))
            if 0 < wx.lens[j] <= cfg.max_len][:cfg.depth]
    nl[0, 0] = len(keep)
    off = np.concatenate([[0], np.cumsum(wx.lens)]).astype(np.int64)
    layers, quals = [], []
    for li, j in enumerate(keep):
        ll = int(wx.lens[j])
        seqs[0, li, :ll] = encode(wx.bases[off[j]:off[j] + ll])
        ws[0, li, :ll] = wx.weights[off[j]:off[j] + ll]
        lens[0, li] = ll
        bg[0, li] = wx.begins[j]
        en[0, li] = wx.ends[j]
        layers.append(wx.bases[off[j]:off[j] + ll].tobytes())
        quals.append((wx.weights[off[j]:off[j] + ll] + 33).astype(
            np.uint8).tobytes())

    cb, cc, cl, fl, nn = (np.asarray(x)
                          for x in pk(bl, nl, lens, bg, en, bb, bbw, seqs,
                                      ws))
    assert not fl[0, 0]
    dev = decode(cb[0, :cl[0, 0]])
    # Compare against the pipeline's own host consensus for the same
    # window: the export is already layer-sorted, and re-sorting through
    # the one-shot hook would permute equal begin keys differently
    # (std::sort is not idempotent on ties).
    pl.consensus_cpu_one(target)
    host = pl.get_consensus(target)
    assert dev == host


def test_pallas_failure_degrades_to_xla_kernel(tmp_path, monkeypatch,
                                               capsys):
    """A Mosaic compile/runtime failure must degrade to the XLA kernel, not
    crash the polish."""
    import racon_tpu
    from racon_tpu.ops import poa_driver

    target = "ACGT" * 60
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(4):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(4):
            f.write(f"r{i}\t0\tt\t1\t60\t{len(target)}M\t*\t0\t0\t{target}"
                    f"\t*\n")

    def broken_kernel(cfg, interpret=False):
        def make(batch):
            def call(*args):
                raise RuntimeError("synthetic mosaic failure")
            return call
        return make

    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
    monkeypatch.setattr("racon_tpu.ops.poa_pallas.build_pallas_poa_kernel",
                        broken_kernel)
    p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                              str(tmp_path / "o.sam"),
                              str(tmp_path / "t.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    assert res[0][1] == target


def test_pallas_runtime_failure_at_drain_degrades(tmp_path, monkeypatch,
                                                  capsys):
    """JAX async dispatch surfaces Mosaic runtime failures at the blocking
    transfer, not at the kernel call — the drain-time recovery must re-run
    the retained packed chunk through the XLA kernel and mark the geometry
    dead."""
    import racon_tpu
    from racon_tpu.ops import poa_driver

    target = "ACGT" * 60
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(4):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(4):
            f.write(f"r{i}\t0\tt\t1\t60\t{len(target)}M\t*\t0\t0\t{target}"
                    f"\t*\n")

    class _LazyFail:
        """Stands in for a device future whose error surfaces on transfer."""

        def __array__(self, *a, **k):
            raise RuntimeError("synthetic async mosaic failure")

    def async_broken_kernel(cfg, interpret=False):
        def make(batch):
            def call(*args):
                return tuple(_LazyFail() for _ in range(5))
            return call
        return make

    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
    monkeypatch.setattr("racon_tpu.ops.poa_pallas.build_pallas_poa_kernel",
                        async_broken_kernel)
    p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                              str(tmp_path / "o.sam"),
                              str(tmp_path / "t.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    assert res[0][1] == target
    assert "falling back to the XLA kernel" in capsys.readouterr().err


def test_pallas_matches_host_and_jax():
    cfg = poa.PoaConfig(max_nodes=384, max_len=256, max_backbone=128,
                        max_edges=12, depth=8, match=5, mismatch=-4, gap=-8)
    pallas_fn = poa_pallas.build_pallas_poa_kernel(cfg, interpret=True)(2)
    jax_fn = poa.build_poa_kernel(cfg)

    rng = random.Random(0)
    truth = bytes(rng.choice(b"ACGT") for _ in range(100))
    backbone = mutate(truth, 0.1, rng)
    layers = [mutate(truth, 0.1, rng) for _ in range(6)]

    B = 2
    bb = np.zeros((B, cfg.max_backbone), np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), np.int32)
    bb_len = np.zeros(B, np.int32)
    nl = np.zeros(B, np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), np.int32)
    lens = np.zeros((B, cfg.depth), np.int32)
    bg = np.zeros((B, cfg.depth), np.int32)
    en = np.zeros((B, cfg.depth), np.int32)
    for b in range(B):
        bb[b, :len(backbone)] = encode(np.frombuffer(backbone, np.uint8))
        bb_len[b] = len(backbone)
        nl[b] = len(layers)
        for i, l in enumerate(layers):
            seqs[b, i, :len(l)] = encode(np.frombuffer(l, np.uint8))
            ws[b, i, :len(l)] = 1
            lens[b, i] = len(l)
            en[b, i] = len(backbone) - 1

    cb, cc, cl, fl, nn = (np.asarray(x) for x in pallas_fn(
        bb_len[:, None], nl[:, None], lens, bg, en, bb.astype(np.int32),
        bbw, seqs.astype(np.int32), ws))
    assert not fl.any()
    pallas_cons = decode(cb[0, :cl[0, 0]])

    jb, jc, jl, jf, jn = (np.asarray(x) for x in jax_fn(
        bb, bbw, bb_len, nl, seqs, ws, lens, bg, en))
    assert not jf.any()
    jax_cons = decode(jb[0, :jl[0]])

    host_cons, _ = native.window_consensus(backbone, layers, trim=False)

    assert pallas_cons == jax_cons == host_cons
    assert int(nn[0, 0]) == int(jn[0])
    # coverages agree too
    np.testing.assert_array_equal(cc[0, :cl[0, 0]], jc[0, :jl[0]])
