"""Per-job latency ledger + per-tenant SLO engine + Prometheus
exposition + critical-path attribution.

Covers the contracts docs/observability.md promises for the
observability control plane: ledger stamps/derived stages and the
explicit ``unattributed_s`` remainder, burn-rate math and multi-window
alerting (a single fast-window spike cannot alert), the ``slo.burn``
injected-slowdown drill CI keys off, exposition-format rendering, and
the critpath analyzer's per-job attribution + exit-code gate.
"""

import json

import pytest

from racon_tpu import obs
from racon_tpu.obs import __main__ as obs_cli
from racon_tpu.obs import critpath, export, ledger, slo
from racon_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _fresh_engine():
    """The SLO engine is process-global (scheduler/plane/exposition all
    read the same one): never leak one test's knobs into the next."""
    slo.reset()
    yield
    slo.reset()
    faults.reset()
    obs.reset()


def _engine(monkeypatch, **knobs):
    for k, v in knobs.items():
        monkeypatch.setenv(k, v)
    return slo.SLOEngine()


# ------------------------------------------------------------ unit: targets

def test_parse_targets_bare_pairs_and_malformed():
    assert slo.parse_targets("2.5") == {"default": 2.5}
    assert slo.parse_targets("default=2.5, gold=0.5") == \
        {"default": 2.5, "gold": 0.5}
    # malformed / non-positive fragments are skipped, never fatal
    assert slo.parse_targets("gold=abc,=1.0x,silver=-3,bronze=4") == \
        {"bronze": 4.0}
    assert slo.parse_targets("") == {}
    assert slo.parse_targets(None) == {}


# --------------------------------------------------------- unit: burn math

def test_burn_rates_and_multiwindow_alert(monkeypatch):
    eng = _engine(monkeypatch,
                  RACON_TPU_SLO_LATENCY_S="default=1.0,gold=0.5",
                  RACON_TPU_SLO_AVAILABILITY="0.9",
                  RACON_TPU_SLO_FAST_WINDOW_S="10",
                  RACON_TPU_SLO_SLOW_WINDOW_S="100",
                  RACON_TPU_SLO_BURN_ALERT="2.0")
    now = 1000.0
    for _ in range(10):
        eng.record("t0", 0.2, ok=True, now=now)
    assert eng.burn_rates("", now=now) == {"fast": 0.0, "slow": 0.0}
    assert not eng.alerting("", now=now)
    # 10 overruns join the window: bad-fraction 0.5 over a 0.1 error
    # budget = burn 5.0 on BOTH windows -> alert (transitions counted
    # once per tenant key: "t0" via record(), "" via alerting())
    for _ in range(10):
        eng.record("t0", 2.0, ok=True, now=now + 5.0)
    rates = eng.burn_rates("", now=now + 5.0)
    assert rates == {"fast": 5.0, "slow": 5.0}
    assert eng.alerting("", now=now + 5.0)
    alerts_after_first = eng.snapshot(now=now + 5.0)["counters"]["alerts"]
    assert eng.alerting("", now=now + 5.0)          # still alerting...
    snap = eng.snapshot(now=now + 5.0)
    assert snap["counters"]["alerts"] == alerts_after_first  # ...not re-counted
    assert snap["overall"]["alerting"] is True
    assert snap["counters"]["observed"] == 20
    assert snap["counters"]["bad"] == 10
    # the bad burst ages out of the fast window: the slow window still
    # burns but multi-window alerting needs BOTH -> alert clears
    later = now + 20.0
    rates = eng.burn_rates("", now=later)
    assert rates["fast"] == 0.0 and rates["slow"] >= 2.0
    assert not eng.alerting("", now=later)


def test_per_tenant_targets_and_failures(monkeypatch):
    eng = _engine(monkeypatch,
                  RACON_TPU_SLO_LATENCY_S="default=1.0,gold=0.5",
                  RACON_TPU_SLO_AVAILABILITY="0.99")
    now = 10.0
    eng.record("gold", 0.7, ok=True, now=now)     # overran gold's 0.5
    eng.record("t1", 0.7, ok=True, now=now)       # within default 1.0
    eng.record("t1", 0.2, ok=False, now=now)      # failed: always bad
    assert eng.target_for("gold") == 0.5
    assert eng.target_for("anyone-else") == 1.0
    assert eng.burn_rates("gold", now=now)["fast"] == 100.0   # 1/1 over 0.01
    assert eng.burn_rates("t1", now=now)["fast"] == 50.0      # 1/2 over 0.01
    snap = eng.snapshot(now=now)
    assert set(snap["tenants"]) == {"gold", "t1"}
    assert snap["tenants"]["gold"]["target_s"] == 0.5


def test_no_targets_means_failures_only(monkeypatch):
    monkeypatch.delenv("RACON_TPU_SLO_LATENCY_S", raising=False)
    eng = slo.SLOEngine()
    eng.record("t0", 999.0, ok=True, now=5.0)      # no target: not bad
    assert eng.burn_rates("", now=5.0)["fast"] == 0.0
    eng.record("t0", 0.1, ok=False, now=5.0)       # failure: still bad
    assert eng.burn_rates("", now=5.0)["fast"] > 0.0


def test_should_shed_gated_by_knob(monkeypatch):
    eng = _engine(monkeypatch,
                  RACON_TPU_SLO_LATENCY_S="0.5",
                  RACON_TPU_SLO_AVAILABILITY="0.9",
                  RACON_TPU_SLO_SHED_BURN="1.0")
    now = 100.0
    for _ in range(4):
        eng.record("t0", 2.0, ok=True, now=now)    # all overruns
    assert eng.should_shed("t0", now=now)
    assert eng.snapshot(now=now)["counters"]["shed"] >= 1
    # shed_burn=0 (the default) disables shedding entirely
    off = _engine(monkeypatch, RACON_TPU_SLO_SHED_BURN="0")
    for _ in range(4):
        off.record("t0", 2.0, ok=False, now=now)
    assert not off.should_shed("t0", now=now)


# -------------------------------------------------- drill: slo.burn fault

def test_slo_burn_drill_forces_alert_then_decays(monkeypatch):
    """The ``slo.burn`` fault point: an armed raise is absorbed as a
    forced burn — both windows report the alert threshold for one fast
    window — so the CI injected-slowdown drill proves the alert ->
    scale-up path deterministically, with zero bad traffic."""
    monkeypatch.setenv("RACON_TPU_SLO_BURN_ALERT", "2.0")
    monkeypatch.setenv("RACON_TPU_SLO_FAST_WINDOW_S", "10")
    monkeypatch.setenv("RACON_TPU_FAULT", "slo.burn")
    faults.reset()
    slo.reset()
    eng = slo.engine()
    now = 50.0
    assert eng.alerting("", now=now)           # forced: no traffic at all
    snap = eng.snapshot(now=now)
    assert snap["forced"] is True
    assert snap["counters"]["burn_faults"] >= 1
    assert snap["counters"]["alerts"] >= 1
    assert snap["overall"]["burn"]["fast"] >= 2.0
    # disarm the fault: the forcing decays after one fast window
    monkeypatch.delenv("RACON_TPU_FAULT")
    faults.reset()
    assert not eng.alerting("", now=now + 11.0)
    assert eng.snapshot(now=now + 11.0)["forced"] is False


# ------------------------------------------------------- unit: job ledger

def test_job_ledger_marks_derived_stages_and_unattributed():
    led = ledger.JobLedger("j1", tenant="t0")
    t0 = led._marks["submit"]
    led.mark("admit", t_ns=t0 + 1_000_000_000)
    led.mark("dispatch", t_ns=t0 + 3_000_000_000)
    led.mark("dispatch", t_ns=t0 + 9_000_000_000)   # idempotent: first wins
    led.add_stage("align", 2.0)
    led.add_stage("align", 0.5)                     # accumulates per chunk
    led.add_stage("poa", -1.0)                      # negative: ignored
    led.add_stage("poa", "garbage")                 # malformed: ignored
    led.merge_stage_s({"poa": 1.0, "kernel_build": 0.25})
    led.merge_stage_s("not a dict")                 # tolerated
    led.mark("finish", t_ns=t0 + 8_000_000_000)
    led.mark("result_ship", t_ns=t0 + 8_500_000_000)
    d = led.as_dict()
    assert d["job"] == "j1" and d["tenant"] == "t0"
    assert d["marks"]["submit"] == 0.0
    assert d["marks"]["admit"] == 1.0 and d["marks"]["dispatch"] == 3.0
    assert d["stage_s"]["queue"] == 2.0             # admit -> dispatch
    assert d["stage_s"]["result_ship"] == 0.5       # finish -> ship
    assert d["stage_s"]["align"] == 2.5
    assert d["wall_s"] == 8.5
    # kernel_build overlaps compute: excluded from the additive sum
    assert d["attributed_s"] == 2.0 + 0.5 + 2.5 + 1.0
    assert d["unattributed_s"] == 2.5               # reported, never hidden
    # stage_s follows the canonical STAGES order
    assert list(d["stage_s"]) == [k for k in ledger.STAGES
                                  if k in d["stage_s"]]


def test_job_ledger_without_ship_mark_falls_back_to_finish():
    led = ledger.JobLedger("j2")
    t0 = led._marks["submit"]
    led.mark("finish", t_ns=t0 + 2_000_000_000)
    d = led.as_dict()
    assert d["wall_s"] == 2.0
    assert "result_ship" not in d["stage_s"]


def test_stage_seconds_sums_per_tier_walls():
    summary = {
        "parse": {"wall_s": {"host": 0.5}},
        "alignment": {"wall_s": {"xla": 1.0, "host": 0.25}},
        "consensus": {"wall_s": 2.0},                 # scalar tolerated
        "stitch": {"wall_s": {"host": "x", "v2": 0.5}},   # garbage skipped
        "memory": {"extra": {"peak_rss_mb": 1}},      # not a ledger stage
        "bogus_phase": {"wall_s": {"host": 9.0}},
    }
    assert ledger.stage_seconds(summary) == \
        {"parse": 0.5, "align": 1.25, "poa": 2.0, "stitch": 0.5}
    assert ledger.stage_seconds(None) == {}
    assert ledger.stage_seconds({"parse": "nope"}) == {}


def test_overlay_seconds_from_metrics_snapshot():
    snap = {"histograms": {
        "span_us.kernel.build": {"sum": 1_500_000.0},
        "span_us.journal.replay": {"sum": 0},             # zero: omitted
        "span_us.phase.poa": {"sum": 9e9},                # not an overlay
    }}
    assert ledger.overlay_seconds(snap) == {"kernel_build": 1.5}
    assert ledger.overlay_seconds(None) == {}
    assert ledger.overlay_seconds({"histograms": "x"}) == {}


def test_summarize_aggregates_and_skips_malformed():
    l1 = {"stage_s": {"align": 1.0, "queue": 0.5},
          "wall_s": 2.0, "unattributed_s": 0.5}
    l2 = {"stage_s": {"align": 2.0}, "wall_s": 3.0, "unattributed_s": 1.0}
    s = ledger.summarize([l1, None, "garbage", {"no": "stage_s"}, l2])
    assert s == {"jobs": 2, "stage_s": {"align": 3.0, "queue": 0.5},
                 "wall_s": 5.0, "unattributed_s": 1.5}
    assert ledger.summarize([]) is None
    assert ledger.summarize(None) is None


# -------------------------------------------------- unit: exposition text

def test_prometheus_text_exposition():
    metrics = {"counters": {"served.poa.fleet": 3},
               "histograms": {"span_us.phase.poa": {
                   "count": 3, "sum": 70.0, "min": 10.0, "max": 40.0,
                   "buckets": {"16": 1, "32": 1, "64": 1}}}}
    slo_snap = {
        "overall": {"burn": {"fast": 1.5, "slow": 0.5}, "alerting": True},
        "tenants": {"t0": {"burn": {"fast": 0.0, "slow": 0.0},
                           "alerting": False}},
        "objectives": {"availability": 0.99, "latency_s": {}},
        "counters": {"alerts": 2},
    }
    text = export.prometheus_text(
        metrics=metrics, slo=slo_snap,
        gauges={"serve_queued_jobs": 4, "fleet_live_workers": None})
    assert text.endswith("\n")
    lines = text.splitlines()
    assert "racon_tpu_served_poa_fleet_total 3" in lines
    # histogram buckets are CUMULATIVE with a closing +Inf
    assert 'racon_tpu_span_us_phase_poa_bucket{le="16"} 1' in lines
    assert 'racon_tpu_span_us_phase_poa_bucket{le="64"} 3' in lines
    assert 'racon_tpu_span_us_phase_poa_bucket{le="+Inf"} 3' in lines
    assert "racon_tpu_span_us_phase_poa_sum 70" in lines
    assert "racon_tpu_span_us_phase_poa_count 3" in lines
    assert "racon_tpu_serve_queued_jobs 4" in lines
    assert not any("fleet_live_workers" in ln for ln in lines)  # None gauge
    assert 'racon_tpu_slo_burn_rate{tenant="",window="fast"} 1.5' in lines
    assert 'racon_tpu_slo_alerting{tenant=""} 1' in lines
    assert 'racon_tpu_slo_alerting{tenant="t0"} 0' in lines
    assert "racon_tpu_slo_availability_objective 0.99" in lines
    assert "racon_tpu_slo_alerts_total 2" in lines
    # a disarmed registry still renders a valid (near-empty) scrape
    assert export.prometheus_text(metrics=None, slo=None) == "\n"


# ------------------------------------------- critpath: attribution + CLI

def _merged_doc():
    """A minimal merged fleet trace: one job, one dispatched chunk with
    phase spans + a kernel.build overlay, scheduler submit/done marks."""
    ab = "ab" * 8
    ev = [
        {"name": "serve.job.submit", "ph": "i", "ts": 0, "pid": 1,
         "tid": 1, "args": {"job": "j1", "tenant": "t0"}},
        {"name": "distrib.dispatch", "ph": "i", "ts": 1000, "pid": 1,
         "tid": 1, "args": {"span_id": "cafe0001", "trace_id": ab,
                            "job": "j1", "worker": 0, "chunk": 0}},
        {"name": "distrib.chunk", "ph": "X", "ts": 2000, "dur": 10000,
         "pid": 2, "tid": 1,
         "args": {"chunk": 0, "parent": "cafe0001", "trace_id": ab}},
        {"name": "phase.align", "ph": "X", "ts": 2500, "dur": 4000,
         "pid": 2, "tid": 1, "args": {}},
        {"name": "kernel.build", "ph": "X", "ts": 2600, "dur": 500,
         "pid": 2, "tid": 1, "args": {}},
        {"name": "phase.poa", "ph": "X", "ts": 6500, "dur": 5000,
         "pid": 2, "tid": 1, "args": {}},
        {"name": "serve.job.done", "ph": "i", "ts": 12500, "pid": 1,
         "tid": 1, "args": {"job": "j1", "state": "done"}},
    ]
    return {"traceEvents": ev}


def test_critpath_attribution_sums_to_wall():
    res = critpath.analyze(_merged_doc())
    assert res["chunks"] == 1
    (job,) = res["jobs"]
    assert job["job"] == "j1" and job["tenant"] == "t0"
    assert job["wall_us"] == 12500.0
    p = job["path_us"]
    assert p["admit_queue"] == 1000.0      # submit -> dispatch
    assert p["queue"] == 1000.0            # dispatch -> chunk start
    assert p["setup"] == 500.0 and p["teardown"] == 500.0
    assert p["align"] == 4000.0 and p["poa"] == 5000.0
    assert p["gather"] == 500.0            # chunk end -> job done
    # overlays are informational, never added to the sum
    assert job["overlay_us"] == {"kernel_build": 500.0}
    assert job["attributed_us"] == 12500.0
    assert job["unattributed_frac"] <= 0.10    # the acceptance bound
    # single job: stage percentiles collapse onto the one sample
    assert res["stages"]["poa"]["p99_us"] == 5000.0
    assert res["wall_p50_us"] == 12500.0


def test_critpath_cli_exit_codes(tmp_path, capsys):
    path = tmp_path / "merged.json"
    path.write_text(json.dumps(_merged_doc()))
    assert obs_cli.main(["critpath", str(path)]) == 0
    assert "OK: every job attributed" in capsys.readouterr().out
    assert obs_cli.main(["critpath", "--json", str(path)]) == 0
    j = json.loads(capsys.readouterr().out)
    assert j["jobs"][0]["job"] == "j1"
    # threshold gate: any unattributed fraction past --max-unattributed
    # is exit 3 (here forced with a negative tolerance)
    assert obs_cli.main(["critpath", str(path),
                         "--max-unattributed", "-0.5"]) == 3
    assert "UNATTRIBUTED" in capsys.readouterr().err
    # unreadable stays exit 2; a chunk-free trace is exit 0 (nothing
    # to attribute is not a failure)
    assert obs_cli.main(["critpath", str(tmp_path / "missing.json")]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    assert obs_cli.main(["critpath", str(empty)]) == 0
    assert "nothing to attribute" in capsys.readouterr().out


def test_critpath_costmodel_crosscheck_reads_merged_counters():
    doc = _merged_doc()
    doc["otherData"] = {"platform": "cpu"}
    doc["racon_tpu"] = {"metrics": {"counters": {
        "align.cells.total": 1_000_000, "poa.cells.d8.c512": 500_000}}}
    res = critpath.analyze(doc, profile="cpu-host")
    cc = res["costmodel"]
    assert cc is not None and cc["profile"] == "cpu-host"
    assert set(cc["phases"]) == {"align", "poa"}
    assert cc["phases"]["poa"]["measured_s"] == 0.005
    assert cc["phases"]["poa"]["predicted_s"] > 0.0


# ------------------------------------------------ ledger end-to-end: serve

class _LedgerSession:
    """Duck-typed session whose run_job ships a pre-aggregated
    ``ledger.stage_s`` fragment, like a fleet-plane result would."""

    backend = "tpu"

    def __init__(self, workdir):
        import os
        self.workdir = str(workdir)
        os.makedirs(os.path.join(self.workdir, "jobs"), exist_ok=True)

    def job_dir(self, job_id):
        import os
        return os.path.join(self.workdir, "jobs", job_id)

    def stats(self):
        return {"jobs_run": 0}

    def run_job(self, spec, cancel_event=None):
        return {"job_id": spec.job_id, "backend": "tpu", "cold": False,
                "wall_s": 0.01, "records": 1, "polished_bp": 1,
                "kernel_builds": 0, "journal_replayed": 0,
                "output": "", "report": "", "trace": "", "summary": None,
                "ledger": {"stage_s": {"align": 0.004, "poa": 0.005}}}


def test_scheduler_finish_feeds_engine_and_persists_ledger(monkeypatch,
                                                           tmp_path):
    """The scheduler's _finish seam end-to-end: the compute-side
    stage_s fragment folds into the job ledger, the persisted
    result.json carries the ledger without result_ship (it cannot time
    its own write), the wire copy is re-finalized with the ship stamp,
    and the completion reaches the process SLO engine."""
    import os

    from racon_tpu.serve.scheduler import Scheduler
    from racon_tpu.serve.session import JobSpec

    monkeypatch.setenv("RACON_TPU_SLO_LATENCY_S", "1000")
    slo.reset()
    paths = []
    for name in ("reads.fasta", "ovl.sam", "targets.fasta"):
        p = tmp_path / name
        p.write_text(">r1\nACGT\n" if name.endswith(".fasta") else "")
        paths.append(str(p))
    ses = _LedgerSession(tmp_path / "state")
    sched = Scheduler(ses, queue_depth=4, max_jobs=4, host_lane=False)
    sched.start()
    try:
        job = sched.submit(JobSpec(paths[0], paths[1], paths[2],
                                   job_id="led1", submitter="tenant0"))
        assert job.done.wait(30)
        assert job.state == "done"
        led = job.result["ledger"]
        assert led["tenant"] == "tenant0"
        assert led["stage_s"]["align"] == 0.004
        assert led["stage_s"]["poa"] == 0.005
        assert {"submit", "admit", "dispatch", "finish", "result_ship"} <= \
            set(led["marks"])
        assert "result_ship" in led["stage_s"]
        assert led["wall_s"] >= led["marks"]["finish"]
        assert led["unattributed_s"] >= 0.0
        # the persisted copy predates the ship stamp by design
        with open(os.path.join(ses.job_dir(job.id), "result.json")) as f:
            persisted = json.load(f)["result"]["ledger"]
        assert "result_ship" not in persisted["stage_s"]
        assert "result_ship" not in persisted["marks"]
        # the completion reached the process SLO engine
        snap = slo.engine().snapshot()
        assert snap["counters"]["observed"] == 1
        assert "tenant0" in snap["tenants"]
    finally:
        sched.shutdown(wait=True, timeout=10)
