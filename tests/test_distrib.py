"""Distributed polishing (racon_tpu/distrib): coordinator/worker fleet.

Covers the wire protocol, lease bookkeeping (expiry, backoff, journal
ownership, speculation, duplicate discard) as units on a Coordinator
that never spawns processes, and the real multi-process paths as
integration tests: 2-process byte-identity vs the serial oracle (the
ROADMAP #2 done-criterion), SIGKILL of a worker mid-chunk with journal
resume on re-dispatch, and fleet collapse degrading to the local rung
with the demotion recorded in the run report.

Datasets follow tests/test_serve.py: identical reads, so every serving
mix reproduces the target exactly and outputs are byte-comparable.
"""

import io
import json
import os
import random
import threading
import time

import pytest

import racon_tpu
from racon_tpu.distrib import Coordinator
from racon_tpu.distrib import common as dcommon
from racon_tpu.distrib import worker as dworker
from racon_tpu.resilience import faults
from racon_tpu.serve.protocol import MAX_LINE, read_message, write_message

_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)


def _write_dataset(tmp_path, n_targets=3, n_reads=4):
    rng = random.Random(11)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                         f"{seq}\t*\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta"))


def _oracle_bytes(paths):
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    return "".join(f">{n}\n{d}\n" for n, d in p.polish(True)).encode()


def _coordinator(paths, tmp_path, **over):
    over.setdefault("args", dict(_ARGS))
    over.setdefault("backend", "cpu")
    return Coordinator(paths[0], paths[1], paths[2],
                       str(tmp_path / "coord"), **over)


# ------------------------------------------------------------ wire protocol

def test_protocol_roundtrip():
    buf = io.BytesIO()
    write_message(buf, {"op": "ping", "n": 1})
    buf.seek(0)
    assert read_message(buf) == {"op": "ping", "n": 1}
    assert read_message(buf) is None                     # clean EOF
    with pytest.raises(ValueError, match="JSON object"):
        read_message(io.BytesIO(b"[1, 2]\n"))
    big = b"x" * (MAX_LINE + 10)
    with pytest.raises((ValueError, json.JSONDecodeError)):
        read_message(io.BytesIO(big))


def test_rpc_raises_on_eof_and_not_ok():
    class _Pipe(io.BytesIO):
        def __init__(self, reply=b""):
            super().__init__(reply)

        def write(self, data):       # request bytes are discarded
            return len(data)

        def flush(self):
            pass

    with pytest.raises(dcommon.WireError, match="closed"):
        dcommon.rpc(_Pipe(), {"op": "fetch"})
    with pytest.raises(dcommon.WireError, match="nope"):
        dcommon.rpc(_Pipe(b'{"ok": false, "error": "nope"}\n'),
                    {"op": "fetch"})


def test_knob_defaults(monkeypatch):
    assert dcommon.distrib_workers() == 2
    assert dcommon.distrib_lease_ttl() == 10.0
    assert dcommon.distrib_heartbeat(9.0) == pytest.approx(3.0)
    monkeypatch.setenv("RACON_TPU_DISTRIB_HEARTBEAT", "0.5")
    assert dcommon.distrib_heartbeat(9.0) == 0.5
    assert dcommon.distrib_retry_base() == 0.25
    assert dcommon.distrib_max_retries() == 3
    assert dcommon.distrib_speculate() == 2.5
    assert dcommon.distrib_fault_worker() == 0


# ------------------------------------------------- coordinator lease units

def test_fault_points_registered():
    assert {"worker.spawn", "worker.heartbeat",
            "worker.result"} <= faults.KNOWN_POINTS
    # the grammar parses the distributed points like any other
    specs = faults.parse_spec("worker.result:kill=1:count=1,"
                              "worker.heartbeat:raise=RuntimeError")
    assert specs[0].point == "worker.result" and specs[0].kill
    assert specs[1].raise_name == "RuntimeError"


def test_assign_expiry_backoff_and_journal_ownership(tmp_path):
    paths = _write_dataset(tmp_path)
    coord = _coordinator(paths, tmp_path, workers=2, lease_ttl=0.01)
    os.makedirs(coord.workdir, exist_ok=True)
    coord._layout()
    assert len(coord.chunks) == 3        # one per contig

    resp = coord._fetch(worker=0)
    a = resp["chunk"]
    c = coord.chunks[a["index"]]
    assert c.state == "running" and c.journal_held
    assert a["journal"] == c.journal     # first attempt holds canonical

    time.sleep(0.05)                     # outlive the 10ms TTL
    coord._expire_leases()
    assert c.state == "pending" and not c.leases
    assert c.journal_held                # holder may still be alive
    assert c.next_eligible > time.monotonic() - 0.01
    assert coord.counters["lease_expired"] == 1
    first_eligible = c.next_eligible

    # a second failure backs off further (exponential)
    with coord._cv:
        coord._fail_chunk(c, RuntimeError("again"))
    assert c.next_eligible >= first_eligible

    # re-dispatch while the journal is held gets a side journal: the
    # TTL-expired holder may still be alive and writing, so two live
    # writers never share a journal file
    c.next_eligible = 0.0
    resp2 = coord._fetch(worker=1)
    a2 = resp2["chunk"]
    assert a2["index"] == a["index"] and a2["journal"] != c.journal
    # death of the SIDE holder does not release the canonical journal
    coord._worker_dead(1, "test")
    assert c.journal_held
    assert c.state == "pending"


def test_worker_death_releases_canonical_journal(tmp_path):
    paths = _write_dataset(tmp_path)
    coord = _coordinator(paths, tmp_path, workers=1)
    os.makedirs(coord.workdir, exist_ok=True)
    coord._layout()
    a = coord._fetch(worker=0)["chunk"]
    c = coord.chunks[a["index"]]
    assert c.journal_held
    # confirmed death (EOF / process exit) frees the canonical journal
    # so the re-dispatch resumes it instead of recomputing
    coord._worker_dead(0, "sigkill")
    assert not c.journal_held
    assert c.state == "pending"
    assert coord.counters["workers_dead"] == 1
    assert coord.counters["lease_expired"] == 1
    c.next_eligible = 0.0      # skip the backoff for the test
    b = coord._fetch(worker=1)
    assert b["chunk"]["index"] == c.index
    assert b["chunk"]["journal"] == c.journal


def test_redispatch_prefers_untried_worker(tmp_path):
    paths = _write_dataset(tmp_path)
    coord = _coordinator(paths, tmp_path, workers=2)
    os.makedirs(coord.workdir, exist_ok=True)
    coord._layout()
    a = coord._fetch(worker=0)["chunk"]
    chunk = coord.chunks[a["index"]]
    with coord._cv:
        chunk.leases.clear()
        coord._fail_chunk(chunk, RuntimeError("boom"))
        chunk.next_eligible = 0.0
    # worker 0 fetching again gets a chunk it has NOT tried first
    b = coord._fetch(worker=0)["chunk"]
    assert b["index"] != a["index"]


def test_first_result_wins_duplicate_discarded(tmp_path):
    paths = _write_dataset(tmp_path)
    coord = _coordinator(paths, tmp_path, workers=2)
    os.makedirs(coord.workdir, exist_ok=True)
    coord._layout()
    a1 = coord._fetch(worker=0)["chunk"]
    c = coord.chunks[a1["index"]]
    c.next_eligible = 0.0
    with coord._cv:
        coord.chunks[a1["index"]].leases.clear()
        c.state = "pending"
    a2 = coord._fetch(worker=1)["chunk"]
    assert a2["index"] == a1["index"]

    r1 = coord._result({"worker": 1, "chunk": a2["index"],
                        "attempt": a2["attempt"], "output": "one.fasta",
                        "stats": {"journal_replayed": 2}})
    assert r1["accepted"] and c.state == "done"
    r2 = coord._result({"worker": 0, "chunk": a1["index"],
                        "attempt": a1["attempt"], "output": "two.fasta",
                        "stats": {}})
    assert not r2["accepted"]
    assert c.output == "one.fasta"       # deterministic: first wins
    assert coord.counters["duplicates"] == 1
    assert coord.counters["journal_replayed"] == 2
    assert coord.phase.served["fleet"] == 1


def test_speculative_dispatch_on_straggler(tmp_path):
    paths = _write_dataset(tmp_path)
    coord = _coordinator(paths, tmp_path, workers=2)
    os.makedirs(coord.workdir, exist_ok=True)
    coord._layout()
    # drain the pending queue onto worker 0
    assigned = [coord._fetch(worker=0)["chunk"] for _ in range(3)]
    assert all("index" in a for a in assigned)
    assert coord._fetch(worker=1).get("wait")    # nothing completed yet

    # complete two chunks quickly; the third becomes the straggler
    for a in assigned[:2]:
        coord._result({"worker": 0, "chunk": a["index"],
                       "attempt": a["attempt"],
                       "output": f"o{a['index']}.fasta", "stats": {}})
    lag = coord.chunks[assigned[2]["index"]]
    for lease in lag.leases.values():
        lease.t_start -= 60.0            # way past factor x median
    spec = coord._fetch(worker=1)
    assert "chunk" in spec and spec["chunk"]["index"] == lag.index
    assert coord.counters["speculative"] == 1
    assert len(lag.leases) == 2
    # worker 1 already tried it now; no third duplicate for worker 1
    assert coord._fetch(worker=1).get("wait")


def test_heartbeat_renews_and_cancels(tmp_path):
    paths = _write_dataset(tmp_path)
    coord = _coordinator(paths, tmp_path, workers=1, lease_ttl=5.0)
    os.makedirs(coord.workdir, exist_ok=True)
    coord._layout()
    a = coord._fetch(worker=0)["chunk"]
    c = coord.chunks[a["index"]]
    old = c.leases[a["attempt"]].deadline
    time.sleep(0.01)
    hb = coord._heartbeat(0, a["index"], a["attempt"])
    assert not hb["cancel"]
    assert c.leases[a["attempt"]].deadline > old
    # a superseded attempt is told to stand down
    assert coord._heartbeat(0, a["index"], a["attempt"] + 7)["cancel"]


def test_heartbeat_fault_stops_renewal(monkeypatch):
    """worker.heartbeat:raise silently ends the renewal loop — the
    heartbeat-loss failure mode, exercised without any socket."""
    monkeypatch.setenv("RACON_TPU_FAULT",
                       "worker.heartbeat:raise=RuntimeError")
    faults.reset()
    stop = threading.Event()
    t0 = time.monotonic()
    # f=None: the injected raise fires before the wire is ever touched
    dworker._heartbeat_loop(None, 0, 0, 1, 0.01, stop)
    assert time.monotonic() - t0 < 5.0
    faults.reset()


def test_bench_distrib_entry_normalizes_as_fixed_point():
    """The distrib bench entry must round-trip normalize_entry unchanged
    and form its own bench-history series (profile distrib-*)."""
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from bench import normalize_entry
    finally:
        sys.path.remove(root)
    from racon_tpu.obs import bench_track

    entry = {
        "metric": "distrib: polished Mbp/sec (synthetic ONT 0.5 Mbp 30x, "
                  "PAF, w=500, 3 workers/6 chunks, end-to-end)",
        "value": 2.34, "unit": "Mbp/s", "vs_baseline": None,
        "cost_model": None, "pack_split": None, "serial_steps": None,
        "cells_banded": None, "band_hit_rate": None,
        "peak_rss_mb": None, "budget_mb": None,
        "distrib": {"workers": 3, "chunks": 6,
                    "served": {"fleet": 6, "local": 0},
                    "redispatches": 1, "journal_replayed": 2},
        "fleet": {"workers": {"0": {"chunks": 6}},
                  "queueing_p95_s": 0.01, "staleness_max_s": 0.2},
        "pool": {"min": 3, "max": 3, "timeline": [[0.0, 3]]},
        "ledger": {"stage_s": {"align": 0.2, "poa": 0.5}},
        "slo": None,
        "mbp": 0.5, "input": "paf", "profile": "distrib-ont",
    }
    assert normalize_entry(dict(entry)) == entry
    plain = dict(entry, profile="ont")
    assert (bench_track.series_key(entry)
            != bench_track.series_key(plain))
    # pre-telemetry distrib entries get the explicit "not scraped" null
    legacy = {k: v for k, v in entry.items() if k != "fleet"}
    assert normalize_entry(legacy)["fleet"] is None
    # pre-elastic-pool entries get the explicit "no timeline" null
    legacy = {k: v for k, v in entry.items() if k != "pool"}
    assert normalize_entry(legacy)["pool"] is None
    # pre-ledger / pre-SLO entries get the explicit nulls too
    legacy = {k: v for k, v in entry.items() if k not in ("ledger", "slo")}
    normalized = normalize_entry(legacy)
    assert normalized["ledger"] is None and normalized["slo"] is None


# ------------------------------------------------ integration: real fleets

def test_two_process_byte_identity(tmp_path):
    """ROADMAP #2 done-criterion: a 2-process localhost fleet produces
    chunk-order-stable output byte-identical to the single-process
    oracle."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle_bytes(paths)
    coord = _coordinator(paths, tmp_path, workers=2,
                         report_path=str(tmp_path / "report.json"))
    out = str(tmp_path / "polished.fasta")
    result = coord.run(out, timeout=180)
    assert open(out, "rb").read() == oracle
    assert result["served"] == {"fleet": 3, "local": 0}
    assert result["counters"].get("workers_dead", 0) == 0
    assert not result["degradations"]
    rep = json.load(open(tmp_path / "report.json"))
    assert rep["phases"]["distrib"]["served"]["fleet"] == 3


def test_worker_sigkill_redispatch_resumes(tmp_path, monkeypatch):
    """The chaos acceptance path: worker 0 is SIGKILLed after its first
    chunk is fully journaled but before the result is delivered
    (worker.result:kill=1).  The EOF expires its lease, the chunk
    re-dispatches to a different worker, the re-run resumes the journal
    (replayed > 0), and the gathered output is still byte-identical.

    Six chunks across three workers so worker 0 is guaranteed to fetch
    one before the fleet drains the queue."""
    paths = _write_dataset(tmp_path, n_targets=6)
    oracle = _oracle_bytes(paths)
    monkeypatch.setenv("RACON_TPU_FAULT", "worker.result:kill=1:count=1")
    monkeypatch.setenv("RACON_TPU_DISTRIB_FAULT_WORKER", "0")
    coord = _coordinator(paths, tmp_path, workers=3,
                         report_path=str(tmp_path / "report.json"))
    out = str(tmp_path / "polished.fasta")
    result = coord.run(out, timeout=180)
    assert open(out, "rb").read() == oracle
    assert result["served"]["fleet"] == result["chunks"]
    assert result["served"]["local"] == 0
    assert result["counters"]["workers_dead"] == 1
    assert result["counters"]["redispatches"] >= 1
    assert result["counters"]["journal_replayed"] > 0
    rep = json.load(open(tmp_path / "report.json"))
    extra = rep["phases"]["distrib"]["extra"]
    assert extra["journal_replayed"] > 0


def test_fleet_collapse_degrades_to_local(tmp_path, monkeypatch):
    """Every spawn fails (worker.spawn armed in the coordinator): the
    fleet is empty, the run degrades to the local rung, finishes, and
    the demotion lands in the RunReport."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle_bytes(paths)
    monkeypatch.setenv("RACON_TPU_FAULT", "worker.spawn:raise=RuntimeError")
    coord = _coordinator(paths, tmp_path, workers=2,
                         report_path=str(tmp_path / "report.json"))
    out = str(tmp_path / "polished.fasta")
    result = coord.run(out, timeout=180)
    assert open(out, "rb").read() == oracle
    assert result["served"] == {"fleet": 0, "local": 3}
    assert len(result["degradations"]) == 1
    assert result["degradations"][0]["from"] == "fleet"
    assert result["degradations"][0]["to"] == "local"
    rep = json.load(open(tmp_path / "report.json"))
    assert rep["phases"]["distrib"]["degradations"][0]["to"] == "local"
    assert rep["phases"]["distrib"]["extra"]["spawn_failures"] == 2


def test_cli_distrib_subcommand(tmp_path):
    """`racon-tpu distrib` end-to-end through the CLI seam: output file,
    trace validated by the obs schema checker, exit 0."""
    import subprocess
    import sys

    paths = _write_dataset(tmp_path)
    oracle = _oracle_bytes(paths)
    out = str(tmp_path / "cli.fasta")
    trace = str(tmp_path / "trace.json")
    rc = subprocess.call(
        [sys.executable, "-m", "racon_tpu.cli", "distrib",
         "-w", "100", "-m", "5", "-x", "-4", "-g", "-8",
         "--workers", "2", "--state-dir", str(tmp_path / "state"),
         "-o", out, "--trace", trace, "--timeout", "180",
         paths[0], paths[1], paths[2]])
    assert rc == 0
    assert open(out, "rb").read() == oracle
    rc = subprocess.call([sys.executable, "-m", "racon_tpu.obs",
                          "--validate", trace])
    assert rc == 0


# --------------------------------------------- fleet tracing + flight

def test_fleet_trace_merges_validates_and_parents(tmp_path):
    """Tentpole acceptance: a traced 3-worker run leaves a coordinator
    trace (with absorbed worker shipments) plus per-chunk worker traces;
    `obs merge` folds them into one timeline that passes `--validate`,
    and `obs fleet` proves every `distrib.chunk` span is parented under
    a coordinator `distrib.dispatch` span via one shared trace id —
    while the fleet served-sum still matches the serial oracle's
    output byte-for-byte."""
    import glob
    import subprocess
    import sys

    paths = _write_dataset(tmp_path)
    oracle = _oracle_bytes(paths)
    trace = str(tmp_path / "coord" / "trace.json")
    coord = _coordinator(paths, tmp_path, workers=3, trace_path=trace)
    out = str(tmp_path / "polished.fasta")
    result = coord.run(out, timeout=180)
    assert open(out, "rb").read() == oracle
    assert sum(result["served"].values()) == result["chunks"]

    # the coordinator absorbed worker span shipments into its own trace
    assert result["counters"].get("obs_events_absorbed", 0) > 0
    # live-telemetry aggregates rode back in the result
    tel = result["telemetry"]
    assert set(tel["workers"]) == {"0", "1", "2"}
    for ws in tel["workers"].values():
        assert ws["chunks"] >= 1
        assert ws["kernel_wall_s"] >= 0.0
    assert tel["queueing_p95_s"] is not None

    worker_traces = sorted(glob.glob(
        str(tmp_path / "coord" / "chunks" / "*" / "trace.a*.json")))
    assert len(worker_traces) == result["chunks"]
    merged = str(tmp_path / "merged.json")
    rc = subprocess.call([sys.executable, "-m", "racon_tpu.obs", "merge",
                          "--out", merged, trace] + worker_traces)
    assert rc == 0
    rc = subprocess.call([sys.executable, "-m", "racon_tpu.obs",
                          "--validate", merged])
    assert rc == 0
    r = subprocess.run([sys.executable, "-m", "racon_tpu.obs", "fleet",
                        merged, "--json"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    b = json.loads(r.stdout)
    assert not b["violations"]
    assert len(b["trace_ids"]) == 1            # one fleet run, one trace
    roles = {p["role"] for p in b["processes"].values()}
    assert "coordinator" in roles
    assert any(r and r.startswith("worker") for r in roles)
    chunks = sum(p["chunks"] for p in b["processes"].values())
    assert chunks >= result["chunks"]          # every chunk span present


def test_fleet_breakdown_flags_dangling_parent(tmp_path):
    """`obs fleet` exit-1 contract: a chunk span whose parent matches no
    dispatch span id is a causality violation, not a rendering quirk."""
    import subprocess
    import sys

    doc = {"traceEvents": [
        {"name": "distrib.dispatch", "ph": "i", "s": "t", "ts": 0,
         "pid": 1, "tid": 1,
         "args": {"span_id": "aabbccdd", "trace_id": "f" * 16}},
        {"name": "distrib.chunk", "ph": "X", "ts": 5, "dur": 10,
         "pid": 2, "tid": 1,
         "args": {"parent": "deadbeef", "trace_id": "f" * 16}},
    ]}
    path = str(tmp_path / "bad.json")
    json.dump(doc, open(path, "w"))
    r = subprocess.run([sys.executable, "-m", "racon_tpu.obs", "fleet",
                        path], capture_output=True, text=True)
    assert r.returncode == 1
    assert "deadbeef" in r.stderr


def test_sigkilled_worker_leaves_flight_dump(tmp_path, monkeypatch):
    """Tentpole acceptance: worker 0 SIGKILLed mid-chunk (worker.result
    kill fault) leaves a parseable flight-recorder dump in its chunk
    directory — written *before* the uncatchable signal — and the
    coordinator's RunReport references it."""
    import glob

    paths = _write_dataset(tmp_path, n_targets=6)
    oracle = _oracle_bytes(paths)
    monkeypatch.setenv("RACON_TPU_FAULT", "worker.result:kill=1:count=1")
    monkeypatch.setenv("RACON_TPU_DISTRIB_FAULT_WORKER", "0")
    coord = _coordinator(paths, tmp_path, workers=3,
                         report_path=str(tmp_path / "report.json"))
    out = str(tmp_path / "polished.fasta")
    result = coord.run(out, timeout=180)
    assert open(out, "rb").read() == oracle
    assert result["counters"]["workers_dead"] == 1

    dumps = glob.glob(str(tmp_path / "coord" / "**" / "flight.*.json"),
                      recursive=True)
    kill_docs = []
    for p in dumps:
        doc = json.load(open(p))            # must parse — tmp+replace
        assert doc["clock"] == "monotonic"
        assert isinstance(doc["events"], list)
        if doc["reason"] == "fault_kill":
            kill_docs.append(doc)
    assert kill_docs, f"no fault_kill dump among {dumps}"
    assert kill_docs[0]["role"] == "worker0"
    # the ring caught the chunk in flight
    names = [e["name"] for e in kill_docs[0]["events"]]
    assert any(n.startswith("distrib.") or n == "fault.fired"
               for n in names)

    # the coordinator swept the dumps into the run report
    assert result["flight"], "coordinator run result references no dumps"
    rep = json.load(open(tmp_path / "report.json"))
    reasons = {d["reason"] for d in rep["flight"]}
    assert "fault_kill" in reasons
    assert all(d["path"] for d in rep["flight"])


def test_fleet_stats_scrapes_live_coordinator(tmp_path):
    """The deepened `stats` wire verb: while a fleet run is in flight, a
    one-shot `fleet_stats` scrape answers with chunk/lease/worker counts
    and the coordinator's telemetry ring."""
    import threading as _threading

    paths = _write_dataset(tmp_path, n_targets=6)
    coord = _coordinator(paths, tmp_path, workers=2)
    out = str(tmp_path / "polished.fasta")
    scraped = []

    def probe():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            port = getattr(coord, "port", None)
            if port:
                try:
                    scraped.append(dcommon.fleet_stats(port, timeout=5.0))
                    return
                except (OSError, dcommon.WireError):
                    pass
            time.sleep(0.05)

    t = _threading.Thread(target=probe, name="loadtest-stats", daemon=True)
    t.start()
    coord.run(out, timeout=180)
    t.join(timeout=10)
    assert scraped, "stats probe never reached the coordinator"
    s = scraped[0]
    assert s["ok"] is True
    assert set(s["chunks"]) == {"pending", "running", "done"}
    assert "workers" in s and "staleness_s" in s
    assert isinstance(s["telemetry"], list)
