"""Engine 5 determinism taint auditor (racon_tpu/analysis/determinism).

Each rule is proven on a seeded fixture mini-tree under
tests/analysis_fixtures/determinism/ (firing exactly once), the real
tree is proven clean (its only knob->sink flows are the documented
journal-replay waivers), and every seeded mutant of the real tree is
caught by the rule that claims it — the acceptance gate CI runs via
`python -m racon_tpu.analysis --determinism` + `--det-mutate`.
"""

import json
import os
import shutil

import pytest

from racon_tpu.analysis import astcache
from racon_tpu.analysis.__main__ import main as analysis_main
from racon_tpu.analysis.determinism import (
    MUTANTS, build_audit, run_determinism, run_mutant)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXROOT = os.path.join(REPO, "tests", "analysis_fixtures", "determinism")


@pytest.fixture(scope="module")
def real_audit():
    """One full-tree audit shared by every real-tree assertion."""
    return build_audit(REPO)


# ------------------------------------------------- seeded fixture trees

def test_leak_fixture_fires_exactly_once():
    res = build_audit(os.path.join(FIXROOT, "leak"))
    assert [v.rule for v in res.violations] == ["determinism-leak"], \
        [v.render() for v in res.violations]
    assert not res.warnings
    v = res.violations[0]
    assert "RACON_TPU_DEPTH" in v.message
    assert v.path == "racon_tpu/ops/code.py"
    assert "set_consensus" in v.message


def test_gap_fixture_fires_exactly_once():
    res = build_audit(os.path.join(FIXROOT, "gap"))
    assert [v.rule for v in res.violations] == ["fingerprint-gap"], \
        [v.render() for v in res.violations]
    assert not res.warnings
    v = res.violations[0]
    assert "knob:RACON_TPU_SEED" in v.message
    assert v.path == "racon_tpu/fingerprint.py"


def test_overkey_fixture_warns_exactly_once():
    res = build_audit(os.path.join(FIXROOT, "overkey"))
    assert not res.violations, [v.render() for v in res.violations]
    assert [v.rule for v in res.warnings] == ["fingerprint-overkey"], \
        [v.render() for v in res.warnings]
    assert "RACON_TPU_TIER" in res.warnings[0].message


def test_fixture_waiver_silences_the_leak(tmp_path):
    """A `# determinism:` waiver above the sink line kills the leak
    finding but the manifest still records the waived flow — the
    documented escape hatch works end to end."""
    src = os.path.join(FIXROOT, "leak")
    tree = tmp_path / "tree"
    shutil.copytree(src, tree)
    code = tree / "racon_tpu" / "ops" / "code.py"
    text = code.read_text()
    code.write_text(text.replace(
        "        pipeline.set_consensus(i, payload, True)",
        "        # determinism: fixture demonstrates a waived flow\n"
        "        pipeline.set_consensus(i, payload, True)"))
    res = build_audit(str(tree))
    assert not res.violations, [v.render() for v in res.violations]
    flows = res.manifest["knobs"]["RACON_TPU_DEPTH"]["sink_flows"]
    assert len(flows) == 1
    assert flows[0]["waived"] == "fixture demonstrates a waived flow"


# ------------------------------------------------- the real tree

def test_real_tree_is_clean(real_audit):
    assert not real_audit.violations, \
        [v.render() for v in real_audit.violations]
    assert not real_audit.warnings, \
        [v.render() for v in real_audit.warnings]


def test_real_tree_journal_flows_are_waived(real_audit):
    """The one intentional knob->sink flow (journal replay installs
    journaled bytes) is present AND waived — the auditor sees the flow
    rather than missing it."""
    flows = real_audit.manifest["knobs"]["RACON_TPU_JOURNAL"][
        "sink_flows"]
    seams = {f["seam"] for f in flows}
    assert seams == {"set_consensus", "set_job_cigar"}, flows
    assert all(f.get("waived") for f in flows), flows


def test_manifest_classifies_every_registered_knob(real_audit):
    from racon_tpu.config import KNOBS
    man = real_audit.manifest
    assert set(KNOBS) <= set(man["knobs"])
    for name, entry in man["knobs"].items():
        assert entry["verdict"] in ("cost-only", "output-affecting"), \
            (name, entry)
    # runtime knobs all honor the byte-identity contract
    for name, knob in KNOBS.items():
        if knob.scope == "runtime":
            assert man["knobs"][name]["affects_output"] is False, name


def test_manifest_lists_every_fingerprint_site(real_audit):
    from racon_tpu import fingerprint
    man = real_audit.manifest
    assert set(man["sites"]) == set(fingerprint.SITES)
    for name, site in man["sites"].items():
        assert site["components"], name
        assert site["expanded_coverage"], name
    # complete sites cover the whole required domain
    domain = set(man["required_domain"])
    for name, site in man["sites"].items():
        if site["complete"]:
            assert domain <= set(site["expanded_coverage"]), name


def test_declared_knob_missing_from_fingerprint_is_a_gap(tmp_path):
    """Registry->domain coupling: declaring any runtime knob
    affects_output=True without extending the fingerprint compositions
    must raise fingerprint-gap on every complete site."""
    tree = tmp_path / "tree"
    (tree / "racon_tpu").parent.mkdir(parents=True, exist_ok=True)
    shutil.copytree(os.path.join(REPO, "racon_tpu"), tree / "racon_tpu")
    cfg = tree / "racon_tpu" / "config.py"
    cfg.write_text(cfg.read_text()
                   + "\n_GAP = _k(\"RACON_TPU_GAP_MUTANT\", \"0\", "
                     "\"int\", \"seeded\", affects_output=True)\n")
    res = build_audit(str(tree))
    gaps = [v for v in res.violations if v.rule == "fingerprint-gap"]
    sites = {v.message.split("`")[1] for v in gaps}
    assert sites == {"journal", "serve_job_dir"}, \
        [v.render() for v in res.violations]
    assert all("knob:RACON_TPU_GAP_MUTANT" in v.message for v in gaps)


# ------------------------------------------------- seeded mutants

@pytest.mark.parametrize("name", [m[0] for m in MUTANTS])
def test_seeded_mutant_is_caught(name):
    mutant, audit, caught = run_mutant(REPO, name)
    assert caught, (
        f"mutant {name} expected {mutant[2]} but audit found only: "
        + "; ".join(v.render()
                    for v in audit.violations + audit.warnings))
    rules = {v.rule for v in audit.violations + audit.warnings}
    assert mutant[2] in rules


def test_unknown_mutant_is_rejected():
    with pytest.raises(ValueError):
        run_mutant(REPO, "no-such-mutant")


# ------------------------------------------------- CLI wiring

def test_cli_determinism_clean_exit_zero(capsys):
    rc = analysis_main(["--determinism", "--repo-root", REPO])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "OK" in out


def test_cli_mutant_exits_nonzero(capsys):
    rc = analysis_main(["--det-mutate", "leak-pipeline-depth",
                        "--repo-root", REPO])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "CAUGHT" in out


def test_cli_list_det_mutations(capsys):
    rc = analysis_main(["--list-det-mutations"])
    out = capsys.readouterr().out
    assert rc == 0
    for m in MUTANTS:
        assert m[0] in out


def test_cli_manifest_round_trip(tmp_path, capsys, real_audit):
    dest = tmp_path / "determinism.json"
    rc = analysis_main(["--determinism", "--emit-manifest", str(dest),
                        "--repo-root", REPO])
    capsys.readouterr()
    assert rc == 0
    loaded = json.loads(dest.read_text())
    assert loaded == real_audit.manifest
    assert loaded["version"] == 1


def test_cli_paths_scoped_run(capsys):
    rc = analysis_main(["--determinism", "--paths",
                        "racon_tpu/resilience/journal.py",
                        "racon_tpu/polisher.py",
                        "--repo-root", REPO])
    out = capsys.readouterr().out
    assert rc == 0, out


def test_run_determinism_entry_point_shape():
    vs = run_determinism(os.path.join(FIXROOT, "leak"))
    assert [v.rule for v in vs] == ["determinism-leak"]
    # warnings never leak through the hard-violation entry point
    assert run_determinism(os.path.join(FIXROOT, "overkey")) == []


# ------------------------------------------------- astcache hardening

def test_astcache_same_size_same_mtime_rewrite_reparses(tmp_path):
    """A same-length rewrite with os.utime-restored mtime must still
    invalidate (ctime/inode guard): no engine may see a stale tree."""
    p = tmp_path / "m.py"
    p.write_text("x = 1\n")
    st = os.stat(p)
    first = astcache.load(str(tmp_path), "m.py")
    assert "x = 1" in first.source
    p.write_text("x = 2\n")            # same size
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
    st2 = os.stat(p)
    assert st2.st_mtime_ns == st.st_mtime_ns
    assert st2.st_size == st.st_size
    second = astcache.load(str(tmp_path), "m.py")
    assert "x = 2" in second.source
