"""Self-healing hardware-session orchestrator: checkpoints, retry with
backoff, step timeouts, and the no-abort partial-session report — all
driven with fake steps in bounded subprocesses (no device needed).
"""

import json
import os
import sys

import pytest

from racon_tpu.tools import hw_session

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _step(name, code, bound=30, env=None):
    return (name, [sys.executable, "-c", code], bound, env or {})


def _session(tmp_path, steps, wanted=None, **kw):
    kw.setdefault("retries", 0)
    kw.setdefault("backoff_s", 0.01)
    return hw_session.run_session(
        wanted if wanted is not None else [n for n, *_ in steps],
        steps=steps,
        state_dir=str(tmp_path / "state"),
        log_path=str(tmp_path / "log.jsonl"),
        report_path=str(tmp_path / "report.json"),
        cwd=str(tmp_path), **kw)


def _outcomes(session):
    return {e["step"]: e["outcome"] for e in session["steps"]}


def test_ok_step_checkpoints_then_caches(tmp_path):
    steps = [_step("a", "print('hi')")]
    s1 = _session(tmp_path, steps)
    assert _outcomes(s1) == {"a": "ok"}
    assert os.path.exists(tmp_path / "state" / "a.json")
    s2 = _session(tmp_path, steps)          # resumed session: skip, don't rerun
    assert _outcomes(s2) == {"a": "cached"}
    s3 = _session(tmp_path, steps, fresh=True)
    assert _outcomes(s3) == {"a": "ok"}
    # the report file accounts for the session either way
    with open(tmp_path / "report.json") as f:
        rep = json.load(f)
    assert rep["session"]["outcomes"] == {"ok": 1}
    assert rep["session"]["tunnel_dead"] is None


def test_flaky_step_retried_with_backoff(tmp_path):
    marker = tmp_path / "flaked"
    code = (f"import os, sys\n"
            f"p = {str(marker)!r}\n"
            f"if os.path.exists(p): sys.exit(0)\n"
            f"open(p, 'w').close(); sys.exit(1)\n")
    s = _session(tmp_path, [_step("flaky", code)], retries=1)
    (entry,) = s["steps"]
    assert entry["outcome"] == "ok" and entry["attempts"] == 2


def test_failed_step_exhausts_retries(tmp_path):
    s = _session(tmp_path, [_step("bad", "import sys; sys.exit(3)")],
                 retries=1)
    (entry,) = s["steps"]
    assert entry["outcome"] == "failed" and entry["attempts"] == 2
    assert not os.path.exists(tmp_path / "state" / "bad.json")


def test_timeout_kills_step_and_is_not_retried(tmp_path):
    s = _session(tmp_path,
                 [_step("wedge", "import time; time.sleep(60)", bound=1)],
                 retries=2)
    (entry,) = s["steps"]
    # the bound was already the generous estimate: one attempt only
    assert entry["outcome"] == "timeout" and entry["attempts"] == 1
    assert entry["wall_s"] < 30


def test_probe_death_skips_rest_but_still_reports(tmp_path):
    steps = [_step("probe", "import sys; sys.exit(1)"),
             _step("bench", "print('never runs')"),
             _step("pins", "print('never runs either')")]
    s = _session(tmp_path, steps)
    assert _outcomes(s) == {"probe": "failed", "bench": "skipped",
                            "pins": "skipped"}
    assert "tunnel unhealthy" in s["session"]["tunnel_dead"]
    for e in s["steps"][1:]:
        assert "tunnel unhealthy" in e["reason"]
    # the partial-session report still lands on disk — the whole point
    with open(tmp_path / "report.json") as f:
        rep = json.load(f)
    assert rep["session"]["outcomes"] == {"failed": 1, "skipped": 2}


def test_cached_probe_does_not_unlock_a_dead_tunnel_twice(tmp_path):
    # checkpointed steps are skipped BEFORE the tunnel_dead gate: a
    # cached success never masks a later probe failure
    steps = [_step("probe", "import sys; sys.exit(1)"),
             _step("b", "print('x')")]
    s1 = _session(tmp_path, steps, wanted=["b"])
    assert _outcomes(s1) == {"b": "ok"}
    s2 = _session(tmp_path, steps)
    assert _outcomes(s2) == {"probe": "failed", "b": "cached"}


def test_resolve_wanted_expands_pins_and_rejects_unknown():
    steps = [("probe", [], 1, {}), ("pin_a", [], 1, {}),
             ("pin_b", [], 1, {}), ("bench", [], 1, {})]
    assert hw_session.resolve_wanted([], steps) == [
        "probe", "pin_a", "pin_b", "bench"]
    assert hw_session.resolve_wanted(["pins", "bench"], steps) == [
        "pin_a", "pin_b", "bench"]
    with pytest.raises(SystemExit):
        hw_session.resolve_wanted(["bogus"], steps)


def test_fault_killed_polish_yields_partial_session_report(tmp_path):
    """ISSUE acceptance: a session whose polish dies under
    RACON_TPU_FAULT still completes and writes a partial report."""
    import random
    rng = random.Random(11)
    with open(tmp_path / "t.fasta", "w") as tf, \
            open(tmp_path / "r.fasta", "w") as rf, \
            open(tmp_path / "ovl.paf", "w") as of:
        seq = "".join(rng.choice("ACGT") for _ in range(200))
        tf.write(f">t0\n{seq}\n")
        for i in range(4):
            rf.write(f">r{i}\n{seq}\n")
            of.write(f"r{i}\t200\t0\t200\t+\tt0\t200\t0\t200\t200\t200\t60\n")
    polish = [sys.executable, "-m", "racon_tpu.cli", "-w", "100",
              "--journal", str(tmp_path / "j.jsonl"),
              str(tmp_path / "r.fasta"), str(tmp_path / "ovl.paf"),
              str(tmp_path / "t.fasta")]
    steps = [("polish", polish, 60,
              {"JAX_PLATFORMS": "cpu",
               "RACON_TPU_FAULT": "journal.append:batch=1:kill=1"}),
             _step("after", "print('still reachable')")]
    s = hw_session.run_session(
        ["polish", "after"], steps=steps, retries=0, backoff_s=0.01,
        state_dir=str(tmp_path / "state"),
        log_path=str(tmp_path / "log.jsonl"),
        report_path=str(tmp_path / "report.json"), cwd=ROOT)
    # SIGKILL mid-append: the step fails, the session neither hangs nor
    # aborts, and the next step still runs
    assert _outcomes(s) == {"polish": "failed", "after": "ok"}
    with open(tmp_path / "report.json") as f:
        rep = json.load(f)
    assert rep["session"]["outcomes"] == {"failed": 1, "ok": 1}
    # the killed run left a resumable journal prefix behind
    with open(tmp_path / "j.jsonl") as f:
        assert len(f.read().splitlines()) >= 1


def test_session_log_is_appended_jsonl(tmp_path):
    _session(tmp_path, [_step("a", "print('x')")])
    with open(tmp_path / "log.jsonl") as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert any(e.get("step") == "a" for e in lines)
    assert any("session_summary" in e for e in lines)
    assert all("utc" in e for e in lines)


def test_strip_progress_collapses_cr_frames():
    """Tail captures keep only the final frame of \r-overwritten
    progress bars (both the session and multichip helpers)."""
    from racon_tpu.tools import multichip

    raw = "start\nbar:  10%\rbar:  55%\rbar: 100%\ndone\n"
    want = "start\nbar: 100%\ndone\n"
    assert hw_session._strip_progress(raw) == want
    assert multichip._strip_progress(raw) == want
    assert hw_session._strip_progress(None) == ""
    assert hw_session._strip_progress("plain\nlines") == "plain\nlines"
