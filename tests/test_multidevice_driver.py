"""Consensus driver over the 8-virtual-device mesh: both kernel flavors must
produce correct polished output with the batch sharded across devices."""

import random

import jax
import pytest

import racon_tpu


def _make_dataset(tmp_path, n_targets=3):
    rng = random.Random(7)
    targets = []
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            targets.append(seq)
            tf.write(f">t{t}\n{seq}\n")
            for i in range(4):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t{seq}\t*\n")
    return targets


@pytest.mark.parametrize("pallas,kind", [("0", "v2"), ("1", "v2"),
                                         ("1", "ls")])
def test_sharded_driver(tmp_path, monkeypatch, capsys, pallas, kind):
    assert len(jax.devices()) == 8
    targets = _make_dataset(tmp_path)
    monkeypatch.setenv("RACON_TPU_PALLAS", pallas)
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", kind)
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "8")
    p = racon_tpu.TpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ovl.sam"),
                              str(tmp_path / "targets.fasta"),
                              window_length=100, quality_threshold=10,
                              error_threshold=0.3, match=5, mismatch=-4,
                              gap=-8, num_threads=1)
    from racon_tpu.ops import poa_driver

    captured = {}
    orig = poa_driver.run_consensus_phase

    def spy(*a, **k):
        stats = orig(*a, **k)
        captured.update(stats)
        return stats

    monkeypatch.setattr(poa_driver, "run_consensus_phase", spy)
    p.initialize()
    res = p.polish(True)
    assert len(res) == len(targets)
    for (name, data), truth in zip(res, targets):
        assert data == truth
    # Correct output via a degrade would mask a broken sharded pallas
    # path: no tier step-down warning, every window served by the
    # device, none re-polished on the host or failed.
    n_windows = 2 * len(targets)  # 200 bp targets, w=100 -> 2 each
    assert captured["device"] == n_windows
    assert captured["host_fallback"] == 0 and captured["failed"] == 0
    if pallas == "1":
        assert "falling back" not in capsys.readouterr().err
