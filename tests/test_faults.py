"""Resilience layer: every degradation-lattice edge driven deterministically
via RACON_TPU_FAULT on the CPU backend, asserting (a) the polished output
stays byte-identical to the CpuPolisher oracle under each fault and (b) the
run report's per-tier served counts sum to the total job/window count.

Edges covered here: xla -> host (tier death), bisect-quarantine (poisoned
window), transient retry, watchdog timeout, window-export quarantine,
hirschberg -> host (engine death mid-phase, served count preserved —
ADVICE.md), and — in a bounded single-device subprocess, where the pallas
tiers can build — ls -> v2 -> xla.
"""

import json
import os
import random
import subprocess
import sys
import time

import pytest

import racon_tpu
from racon_tpu.resilience import faults, lattice, report

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- unit: spec

def test_parse_spec_valid():
    specs = faults.parse_spec(
        "poa.run.ls:batch=2:raise=MosaicError, align.run:window=5:count=1,"
        "poa.run.v2:hang=0.5")
    assert [s.point for s in specs] == ["poa.run.ls", "align.run",
                                       "poa.run.v2"]
    assert specs[0].batch == 2 and specs[0].raise_name == "MosaicError"
    assert specs[1].window == 5 and specs[1].count == 1
    assert specs[2].hang == 0.5


@pytest.mark.parametrize("bad", [
    "bogus.point",
    "poa.run.ls:frobnicate=1",
    "poa.run.ls:batch=x",
    "poa.run.ls:raise=NoSuchError",
    "poa.run.ls:batch",
])
def test_parse_spec_malformed(bad):
    with pytest.raises(ValueError) as ei:
        faults.parse_spec(bad)
    msg = str(ei.value)
    assert msg.startswith("RACON_TPU_FAULT") and "\n" not in msg


def test_check_fires_and_counts(monkeypatch):
    monkeypatch.setenv("RACON_TPU_FAULT", "poa.run.v2:batch=1:count=1")
    faults.reset()
    faults.check("poa.run.v2")                     # invocation 0: no fire
    with pytest.raises(faults.MosaicError):
        faults.check("poa.run.v2")                 # invocation 1: fires
    faults.check("poa.run.v2")                     # spent
    faults.reset()                                 # fresh schedule
    faults.check("poa.run.v2")
    with pytest.raises(faults.MosaicError):
        faults.check("poa.run.v2")


# ------------------------------------------------------------- unit: lattice

def test_watchdog_passthrough_and_timeout():
    assert lattice.call_with_watchdog(lambda: 42) == 42
    assert lattice.call_with_watchdog(lambda: 42, timeout=5) == 42
    with pytest.raises(ValueError):
        lattice.call_with_watchdog(lambda: (_ for _ in ()).throw(
            ValueError("boom")), timeout=5)
    t0 = time.perf_counter()
    with pytest.raises(lattice.WatchdogTimeout):
        lattice.call_with_watchdog(lambda: time.sleep(2), timeout=0.2)
    assert time.perf_counter() - t0 < 1.5


def test_serve_with_bisect_retry_then_success():
    calls = []

    def attempt(sub):
        calls.append(list(sub))
        if len(calls) == 1:
            raise RuntimeError("transient")
        return sum(sub)

    rep = report.PhaseReport("t", ("x",))
    pairs, quarantined = lattice.serve_with_bisect(
        [1, 2, 3], attempt, tier="x", report=rep, retries=1)
    assert pairs == [([1, 2, 3], 6)] and quarantined == []
    assert rep.retries == 1 and rep.bisections == 0


def test_serve_with_bisect_quarantines_poisoned_item():
    def attempt(sub):
        if 3 in sub:
            raise RuntimeError("poisoned")
        return list(sub)

    rep = report.PhaseReport("t", ("x",))
    pairs, quarantined = lattice.serve_with_bisect(
        [1, 2, 3, 4], attempt, tier="x", report=rep, retries=0)
    served = [i for sub, _ in pairs for i in sub]
    assert sorted(served) == [1, 2, 4]
    assert [i for i, _ in quarantined] == [3]
    assert rep.bisections >= 1


def test_serve_with_bisect_tier_dead_when_all_fail():
    def attempt(sub):
        raise RuntimeError("dead tier")

    with pytest.raises(lattice.TierDead):
        lattice.serve_with_bisect([1, 2, 3, 4], attempt, tier="x",
                                  retries=0)


def test_serve_with_bisect_cached_first():
    attempts = []

    def attempt(sub):
        attempts.append(list(sub))
        return "fresh"

    pairs, quarantined = lattice.serve_with_bisect(
        [1, 2], attempt, tier="x", retries=0, cached=lambda: "cached")
    assert pairs == [([1, 2], "cached")] and not attempts


# ------------------------------------------------------------ e2e fixtures

def _write_dataset(tmp_path, overlaps="sam", n_targets=3, n_reads=4):
    """Identical-read dataset: device- and host-served consensus are both
    exactly the target sequence, so polished output is byte-comparable to
    the CpuPolisher oracle under any serving mix."""
    rng = random.Random(11)
    targets = []
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / ("ovl.sam" if overlaps == "sam" else "ovl.paf"),
                 "w") as of:
        if overlaps == "sam":
            of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            targets.append(seq)
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                if overlaps == "sam":
                    of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                             f"{seq}\t*\n")
                else:
                    of.write(f"t{t}r{i}\t200\t0\t200\t+\tt{t}\t200\t0\t200"
                             f"\t200\t200\t60\n")
    ovl = str(tmp_path / ("ovl.sam" if overlaps == "sam" else "ovl.paf"))
    return (str(tmp_path / "reads.fasta"), ovl,
            str(tmp_path / "targets.fasta"))


_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)


def _oracle(paths):
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    return p.polish(True)


def _tpu_run(paths, monkeypatch, env):
    base = {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
            "RACON_TPU_BATCH_WINDOWS": "8"}
    for k, v in {**base, **env}.items():
        monkeypatch.setenv(k, v)
    p = racon_tpu.create_polisher(*paths, backend="tpu", **_ARGS)
    p.initialize()
    res = p.polish(True)
    return res, p


def _assert_report_sums(p):
    d = p.report.as_dict()
    assert d["phases"], "run produced no phase reports"
    for phase in d["phases"].values():
        assert sum(phase["served"].values()) == phase["total"], phase
    json.dumps(d)  # must be JSON-serializable end to end
    return d


# -------------------------------------------------- e2e: consensus lattice

def test_clean_run_report_sums(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {})
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["served"]["xla"] == 6          # 3 targets x 2 windows
    assert cons["served"]["host"] == 0
    assert cons["retries"] == 0 and cons["quarantined"] == []
    assert d["fault_spec"] == ""


def test_xla_tier_death_degrades_to_host(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {"RACON_TPU_FAULT": "poa.run.xla"})
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["served"]["host"] == 6 and cons["served"]["xla"] == 0
    assert any(dg["from"] == "xla" and dg["to"] == "host"
               for dg in cons["degradations"])
    assert "MosaicError" in json.dumps(cons["causes"])


def test_poisoned_window_bisected_and_quarantined(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_FAULT": "poa.run.xla:window=2"})
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    # only the poisoned window reaches the host; the rest stay on device
    assert cons["quarantined"] == [2]
    assert cons["served"]["host"] == 1 and cons["served"]["xla"] == 5
    assert cons["bisections"] >= 1
    assert not cons["degradations"]


def test_transient_fault_retried_at_tier(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_FAULT": "poa.run.xla:batch=0:count=1"})
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["served"]["xla"] == 6 and cons["served"]["host"] == 0
    assert cons["retries"] >= 1
    assert not cons["degradations"] and cons["quarantined"] == []


def test_hung_device_call_hits_watchdog(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        # invocation 0 (pipelined submit) fails synchronously; invocation 1
        # (the lattice's retry attempt) hangs and trips the watchdog;
        # invocation 2 succeeds — all windows still served on device
        "RACON_TPU_FAULT": ("poa.run.xla:batch=0:count=1,"
                            "poa.run.xla:batch=1:count=1:hang=2"),
        "RACON_TPU_DEVICE_TIMEOUT": "0.3",
    })
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["served"]["xla"] == 6
    assert "WatchdogTimeout" in json.dumps(cons["causes"])


def test_window_export_failure_quarantined(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_FAULT": "window.export:window=1"})
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["quarantined"] == [1]
    assert cons["served"]["host"] == 1 and cons["served"]["xla"] == 5


# -------------------------------------------------- e2e: alignment lattice

def test_hirschberg_engine_death_preserves_served_count(tmp_path,
                                                        monkeypatch):
    """The ADVICE.md regression: the engine dies after the first cohort,
    and the phase stats must still report that cohort as device-served
    (the old driver reported device=0, host=n)."""
    paths = _write_dataset(tmp_path, overlaps="paf", n_reads=2)
    oracle = _oracle(paths)
    kill = ",".join(f"align.run:batch={i}" for i in range(1, 12))
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_DEVICE_ALIGNER": "hirschberg",
        "RACON_TPU_ALIGN_COHORT": "2",
        "RACON_TPU_FAULT": kill,
    })
    assert res == oracle
    d = _assert_report_sums(p)
    al = d["phases"]["alignment"]
    assert al["total"] == 6                      # 3 targets x 2 reads
    # cohort 0 (2 jobs) was served before the engine died mid-phase
    assert al["served"]["hirschberg"] == 2
    assert al["served"]["host"] == 4
    assert any(dg["from"] == "hirschberg" and dg["to"] == "host"
               for dg in al["degradations"])


def test_alignment_poisoned_job_quarantined(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path, overlaps="paf", n_reads=2)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_DEVICE_ALIGNER": "hirschberg",
        "RACON_TPU_ALIGN_COHORT": "4",
        "RACON_TPU_FAULT": "align.run:window=3",
    })
    assert res == oracle
    d = _assert_report_sums(p)
    al = d["phases"]["alignment"]
    assert 3 in al["quarantined"]
    assert al["served"]["hirschberg"] == 5 and al["served"]["host"] == 1
    assert al["bisections"] >= 1


def test_align_compile_fault_degrades_to_host(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path, overlaps="paf", n_reads=2)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_DEVICE_ALIGNER": "hirschberg",
        "RACON_TPU_FAULT": "align.compile",
    })
    assert res == oracle
    d = _assert_report_sums(p)
    al = d["phases"]["alignment"]
    assert al["served"]["host"] == 6 and al["served"]["hirschberg"] == 0


def test_poa_compile_fault_degrades_to_host(tmp_path, monkeypatch):
    """poa.compile.xla: the XLA-twin kernel *build* dies (compile seam,
    not the run seam); consensus must degrade xla -> host with output
    still matching the oracle."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_FAULT": "poa.compile.xla"})
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["served"]["host"] == 6 and cons["served"]["xla"] == 0
    assert any(dg["from"] == "xla" and dg["to"] == "host"
               for dg in cons["degradations"])


def test_native_call_fault_surfaces(tmp_path, monkeypatch):
    """native.call: the host (native) engine is the lattice floor — an
    injected fault there has nowhere to degrade to and must surface as
    the injected exception, not as silent corruption."""
    paths = _write_dataset(tmp_path)
    monkeypatch.setenv("RACON_TPU_FAULT", "native.call:count=1")
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    with pytest.raises(faults.InjectedFault):
        p.polish(True)


# ------------------------------------- pallas tiers (single-device subproc)

def test_pallas_chain_ls_v2_xla(tmp_path):
    """ls -> v2 -> xla, in a single-device subprocess (the in-process
    8-virtual-device mesh can't build the sharded pallas kernels here).
    Both pallas run points are killed; the chunk must degrade through v2
    to the XLA twin and the output must match the host oracle."""
    paths = _write_dataset(tmp_path)
    code = f"""
import sys
sys.path.insert(0, {ROOT!r})
from __graft_entry__ import _force_cpu; _force_cpu(1)
import json
import racon_tpu

args = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
            match=5, mismatch=-4, gap=-8, num_threads=1)
paths = {paths!r}
p0 = racon_tpu.create_polisher(*paths, backend="cpu", **args)
p0.initialize()
oracle = p0.polish(True)

import os
os.environ["RACON_TPU_PALLAS"] = "1"
os.environ["RACON_TPU_POA_KERNEL"] = "ls"
os.environ["RACON_TPU_BATCH_WINDOWS"] = "8"
os.environ["RACON_TPU_FAULT"] = "poa.run.ls,poa.run.v2"
p = racon_tpu.create_polisher(*paths, backend="tpu", **args)
p.initialize()
res = p.polish(True)
assert res == oracle, "faulted output diverged from the host oracle"
d = p.report.as_dict()
cons = d["phases"]["consensus"]
assert sum(cons["served"].values()) == cons["total"], cons
edges = {{(dg["from"], dg["to"]) for dg in cons["degradations"]}}
assert ("ls", "v2") in edges, edges
assert ("v2", "xla") in edges, edges
assert cons["served"]["xla"] == cons["total"], cons
print("PALLAS-CHAIN-OK", json.dumps(cons["served"]))
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=570)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "PALLAS-CHAIN-OK" in r.stdout


def test_pallas_compile_faults_chain_to_xla(tmp_path):
    """poa.compile.ls / poa.compile.v2: both pallas kernel *builds* are
    killed at the compile seam; the chunk must degrade ls -> v2 -> xla
    and the output must match the host oracle (compile-seam twins of
    the run-seam chain above)."""
    paths = _write_dataset(tmp_path)
    code = f"""
import sys
sys.path.insert(0, {ROOT!r})
from __graft_entry__ import _force_cpu; _force_cpu(1)
import json
import racon_tpu

args = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
            match=5, mismatch=-4, gap=-8, num_threads=1)
paths = {paths!r}
p0 = racon_tpu.create_polisher(*paths, backend="cpu", **args)
p0.initialize()
oracle = p0.polish(True)

import os
os.environ["RACON_TPU_PALLAS"] = "1"
os.environ["RACON_TPU_POA_KERNEL"] = "ls"
os.environ["RACON_TPU_BATCH_WINDOWS"] = "8"
os.environ["RACON_TPU_FAULT"] = "poa.compile.ls,poa.compile.v2"
p = racon_tpu.create_polisher(*paths, backend="tpu", **args)
p.initialize()
res = p.polish(True)
assert res == oracle, "faulted output diverged from the host oracle"
d = p.report.as_dict()
cons = d["phases"]["consensus"]
assert sum(cons["served"].values()) == cons["total"], cons
edges = {{(dg["from"], dg["to"]) for dg in cons["degradations"]}}
assert ("ls", "v2") in edges, edges
assert ("v2", "xla") in edges, edges
assert cons["served"]["xla"] == cons["total"], cons
print("COMPILE-CHAIN-OK", json.dumps(cons["served"]))
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=570)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "COMPILE-CHAIN-OK" in r.stdout
