"""Analytic cost model + roofline profiler + bench-history tracker.

Covers the contracts docs/benchmarks.md promises: the mirrored kernel
grid constants stay equal to the ops modules' (the stdlib-only obs
package must never drift from the kernels it models), roofline term
selection, the counter -> prediction pipeline (predict_from_counters /
validate_trace), the bench.py `cost_model` stamp, the bench-history
trend gate (including the synthetic-regression self-test CI runs), the
`span_us` histogram quantiles, and the obs CLI subcommand exit codes.
"""

import json

import pytest

from racon_tpu.obs import __main__ as obs_cli
from racon_tpu.obs import bench_track, costmodel
from racon_tpu.obs.metrics import hist_quantile

CPU = costmodel.PROFILES["cpu-host"]
TPU = costmodel.PROFILES["tpu-v4-lite"]


# -------------------------------------------- grid-constant parity (ops)

def test_grid_constants_match_ops_modules():
    """costmodel mirrors the kernel grid so it can stay stdlib-only;
    this pin is the only thing keeping the mirror honest."""
    from racon_tpu.ops import align, align_pallas, poa_driver
    from racon_tpu.ops import poa_pallas_ls

    assert costmodel.DEPTH_BUCKETS == poa_driver.DEPTH_BUCKETS
    assert costmodel.AUDIT_WINDOW_LENGTHS == poa_driver.AUDIT_WINDOW_LENGTHS
    assert costmodel.ALIGN_BUCKETS == align.BUCKETS
    assert costmodel.LS_GROUP == poa_pallas_ls.G
    from racon_tpu.ops import colstep, encoding
    assert costmodel.POA_COLSTEP_PACK == colstep.PACK
    assert costmodel.ALIGN_ROW_PACK == encoding.PACK
    from racon_tpu import config
    from racon_tpu.ops import band
    assert costmodel.BAND_BUCKETS == band.BAND_BUCKETS
    assert costmodel.BAND_SLACK == int(config.KNOBS[
        "RACON_TPU_BAND_SLACK"].default)
    for bb in (1, 100, 128, 129, 500, 1000, 1024):
        assert costmodel.window_class(bb) == poa_driver.window_class(bb)
    # band_need is the `need` inside align_pallas.band_for: the bucket
    # band_for returns is the smallest BANDS entry covering it (0 = host)
    for n, m in ((700, 660), (1000, 1000), (8000, 7000), (50000, 50000)):
        need = costmodel.band_need(n, m)
        expect = next((b for b in align_pallas.BANDS if need <= b), 0)
        assert align_pallas.band_for(n, m) == expect


# ------------------------------------------------------ closed forms

def test_roofline_picks_the_dominant_term():
    flops_heavy = costmodel.CostEstimate(1e12, 1.0, 1.0)
    s, verdict = costmodel.roofline(flops_heavy, CPU)
    assert verdict == "compute-bound" and s == 1e12 / CPU.peak_flops
    bw_heavy = costmodel.CostEstimate(1.0, 1e12, 1.0)
    assert costmodel.roofline(bw_heavy, CPU)[1] == "bandwidth-bound"
    serial_heavy = costmodel.CostEstimate(1.0, 1.0, 1e9)
    assert costmodel.roofline(serial_heavy, CPU)[1] == "serial-step-bound"


def test_ls_tier_amortizes_serial_steps_by_group():
    v2 = costmodel.poa_window_cost(32, 512, "v2")
    ls = costmodel.poa_window_cost(32, 512, "ls")
    assert ls.flops == v2.flops and ls.hbm_bytes == v2.hbm_bytes
    assert v2.serial_steps == ls.serial_steps * costmodel.LS_GROUP


def test_colstep_pack_divides_pallas_tier_serial_steps():
    """Column compression only helps the Pallas loops; the XLA twin
    still retires one rank per scan step."""
    xla = costmodel.poa_window_cost(32, 512, "xla")
    v2 = costmodel.poa_window_cost(32, 512, "v2")
    assert xla.serial_steps == v2.serial_steps * costmodel.POA_COLSTEP_PACK
    assert xla.flops == v2.flops and xla.hbm_bytes == v2.hbm_bytes


def test_row_pack_divides_hirschberg_serial_steps():
    hs = costmodel.align_job_cost(1024, 256, "hirschberg")
    assert hs.serial_steps == 4.0 * 1024 / costmodel.ALIGN_ROW_PACK


def test_banded_closed_forms_cut_cells_not_serial_steps():
    """Banding narrows each DP row's live lanes: the cell/FLOP bill
    divides by the band ratio, the latency-chained step count does not."""
    flat = costmodel.align_job_cost(1024, 256, "hirschberg")
    nar = costmodel.banded_align_job_cost(1024, 128)
    assert nar.serial_steps == flat.serial_steps
    assert nar.flops * 2 == flat.flops
    assert costmodel.banded_cell_ratio("align", band=256, k=128) == 2.0

    pf = costmodel.poa_window_cost(8, 512, "v2")
    pb = costmodel.banded_poa_window_cost(8, 512, 8, "v2")
    assert pb.serial_steps == pf.serial_steps
    assert pb.hbm_bytes == pf.hbm_bytes      # layers stream in either way
    assert pb.flops == pf.flops * 17 / 512   # 2w+1 live columns
    assert costmodel.banded_cell_ratio("poa", wl_class=512, w=8) == 512 / 17
    # a band wider than the class floors at the flat bill
    wide = costmodel.banded_poa_window_cost(8, 512, 10_000, "v2")
    assert wide.flops == pf.flops
    assert costmodel.banded_cell_ratio("poa", wl_class=512, w=10_000) == 1.0


def test_predict_emits_banded_info_rows_without_double_count():
    counters = {"align.cells.hirschberg": 10_000_000,
                "align.cells.banded": 2_500_000,
                "align.cells.total": 10_000_000,
                "poa.cells.d8.c128": 1024,
                "poa.cells.banded": 400_000,
                "served.consensus.v2": 4}
    pred = costmodel.predict_from_counters(counters, CPU)
    banded = [b for b in pred["buckets"] if b["kind"] == "banded"]
    assert {b["phase"] for b in banded} == {"align", "poa"}
    # info rows only: phase totals must equal the banded-counter-free run
    bare = costmodel.predict_from_counters(
        {k: v for k, v in counters.items() if "banded" not in k}, CPU)
    assert pred["phases"] == bare["phases"]


def test_poa_window_cost_scales_with_depth_and_class():
    small = costmodel.poa_window_cost(8, 128, "v2")
    deep = costmodel.poa_window_cost(32, 128, "v2")
    assert deep.flops == pytest.approx(small.flops * 4)
    wide = costmodel.poa_window_cost(8, 256, "v2")
    assert wide.flops == pytest.approx(small.flops * 4)  # ranks x length


def test_tpu_poa_bucket_is_serial_step_bound():
    """The measured 0.188x story: the rank loop's latency chain, not
    FLOPs, dominates on the TPU profile — the prediction that justifies
    ROADMAP's next optimization target."""
    est = costmodel.poa_window_cost(32, 512, "v2")
    _, verdict = costmodel.roofline(est, TPU)
    assert verdict == "serial-step-bound"


def test_model_rows_cover_the_grid():
    rows = costmodel.model_rows(CPU)
    poa_rows = [r for r in rows if r["kind"] == "poa"]
    classes = {costmodel.window_class(w)
               for w in costmodel.AUDIT_WINDOW_LENGTHS}
    assert len(poa_rows) == (len(costmodel.POA_TIERS)
                             * len(costmodel.DEPTH_BUCKETS) * len(classes))
    align_rows = [r for r in rows if r["kind"] == "align"]
    assert len(align_rows) == len(costmodel.ALIGN_BUCKETS)
    for r in rows:
        assert r["predicted_s"] > 0.0 and r["verdict"].endswith("-bound")
        assert r["predicted_cycles"] == pytest.approx(
            r["predicted_s"] * CPU.clock_hz)


def test_profile_lookup_and_auto_resolution():
    assert costmodel.resolve_profile("auto", "tpu") is TPU
    assert costmodel.resolve_profile("auto", "cpu") is CPU
    assert costmodel.resolve_profile("auto", None) is CPU
    assert costmodel.resolve_profile("tpu-v4-lite", "cpu") is TPU
    with pytest.raises(KeyError):
        costmodel.profile("gpu-h100")


# ------------------------------------- counters -> per-phase prediction

def _counters(device=True):
    c = {
        "served.consensus.v2": 90, "served.consensus.host": 10,
        "poa.windows.d32.c512": 100,
        # 100 windows, ~30 admitted layers each, class 512
        "poa.cells.d32.c512": 100 * 30 * 512,
        "served.alignment.xla": 40, "served.alignment.host": 10,
        "align.cells.c1024": 40 * 1024 * 256,
        "align.cells.total": 45 * 1024 * 256,
    }
    if not device:
        c["served.consensus.host"] = 100
        del c["served.consensus.v2"]
    return c


def test_predict_from_counters_builds_phases_and_buckets():
    pred = costmodel.predict_from_counters(_counters(), CPU)
    assert set(pred["phases"]) == {"poa", "align"}
    assert pred["phases"]["poa"]["tier"] == "v2"
    assert pred["phases"]["poa"]["predicted_s"] > 0.0
    kinds = {(b["kind"], b.get("tier")) for b in pred["buckets"]}
    assert ("poa", "v2") in kinds and ("align", "xla") in kinds
    poa_b = next(b for b in pred["buckets"] if b["kind"] == "poa")
    # measured steps at growth 1, scaled by NODE_GROWTH ranks, x class
    assert poa_b["cells"] == pytest.approx(
        100 * 30 * 512 * costmodel.NODE_GROWTH * 512)


def test_predict_flags_host_served_alignment():
    c = _counters()
    del c["align.cells.c1024"]          # no device aligner bucket ran
    c["align.cells.total"] = 10 ** 9
    pred = costmodel.predict_from_counters(c, CPU)
    assert pred["phases"]["align"]["verdict"] == "host-served"
    assert pred["phases"]["align"]["predicted_s"] == pytest.approx(
        10 ** 9 / CPU.host_align_cells_per_s)


# ------------------------------------------------ trace validation join

def _trace_doc(counters, phase_us, extra_events=(), dropped=0):
    events = [{"name": f"phase.{p}", "ph": "X", "ts": 0, "dur": us,
               "pid": 1, "tid": 1} for p, us in phase_us.items()]
    events += list(extra_events)
    return {"traceEvents": events,
            "otherData": {"dropped_events": dropped, "platform": "cpu"},
            "racon_tpu": {"metrics": {"counters": counters,
                                      "histograms": {}}}}


def test_validate_trace_ok_when_prediction_within_bound():
    pred = costmodel.predict_from_counters(_counters(), CPU)
    phase_us = {p: row["predicted_s"] * 1e6            # measured == predicted
                for p, row in pred["phases"].items()}
    v = costmodel.validate_trace(_trace_doc(_counters(), phase_us), CPU)
    assert v["ok"] is True
    for row in v["phases"].values():
        assert row["within_bound"] is True
        assert row["ratio"] == pytest.approx(1.0)


def test_validate_trace_fails_past_declared_bound():
    pred = costmodel.predict_from_counters(_counters(), CPU)
    wrong = {p: row["predicted_s"] * 1e6 * CPU.error_bound_ratio * 4
             for p, row in pred["phases"].items()}
    v = costmodel.validate_trace(_trace_doc(_counters(), wrong), CPU)
    assert v["ok"] is False
    assert any(r["within_bound"] is False for r in v["phases"].values())


def test_validate_trace_ungated_without_measured_walls():
    # counters but no phase spans: reported, not gated — and vice versa
    v = costmodel.validate_trace(_trace_doc(_counters(), {}), CPU)
    assert v["ok"] is True
    assert all(r["within_bound"] is None for r in v["phases"].values())


def test_validate_trace_joins_bucket_spans():
    ev = [{"name": "poa.bucket", "ph": "X", "ts": 0, "dur": 2_000_000,
           "pid": 1, "tid": 1, "args": {"depth": 32, "wl_class": 512,
                                        "windows": 100}}]
    v = costmodel.validate_trace(
        _trace_doc(_counters(), {}, extra_events=ev), CPU)
    poa_b = next(b for b in v["buckets"] if b["kind"] == "poa")
    assert poa_b["measured_s"] == pytest.approx(2.0)
    assert "error_pct" in poa_b


def test_validate_trace_reports_dropped_events():
    v = costmodel.validate_trace(
        _trace_doc(_counters(), {}, dropped=7), CPU)
    assert v["dropped_events"] == 7
    assert "WARNING" in costmodel.render_validation(v)


# ------------------------------------------------- bench.py cost stamp

def test_bench_cost_model_stamp_joins_report_phase_names():
    pred = costmodel.predict_from_counters(_counters(), CPU)
    pw = {"alignment": pred["phases"]["align"]["predicted_s"],
          "consensus": pred["phases"]["poa"]["predicted_s"],
          "stitch": 0.01}
    cm = costmodel.bench_cost_model({"counters": _counters()}, pw,
                                    "cpu-host")
    assert cm["profile"] == "cpu-host" and cm["ok"] is True
    assert set(cm["phases"]) == {"alignment", "consensus"}
    for row in cm["phases"].values():
        assert row["within_bound"] is True and "error_pct" in row


def test_bench_cost_model_none_when_metrics_disarmed():
    assert costmodel.bench_cost_model(None, {}) is None
    assert costmodel.bench_cost_model({}, {}) is None


# -------------------------------------------------- bench-history gate

def _entry(src, value, vs=0.2, pw=None, **kw):
    e = {"mbp": 0.5, "input": "paf", "profile": "ont", "unit": "Mbp/s",
         "value": value, "vs_baseline": vs, "kernel": "v2",
         "_source": src}
    if pw is not None:
        e["phase_wall"] = pw
    e.update(kw)
    return e


def test_trend_clean_series_has_no_regressions():
    r = bench_track.trend([_entry("a", 0.004), _entry("b", 0.0055)])
    assert r["regressions"] == []
    (s,) = r["series"]
    assert s["n"] == 2 and s["deltas"][0]["value_pct"] > 0


def test_trend_gates_value_drop_past_threshold():
    r = bench_track.trend([_entry("a", 0.01), _entry("b", 0.002)])
    assert len(r["regressions"]) == 1
    assert "value" in r["regressions"][0]
    assert "REGRESSION" in bench_track.render(r)


def test_trend_gates_vs_baseline_and_phase_wall():
    a = _entry("a", 0.01, vs=0.2, pw={"consensus": 1.0})
    b = _entry("b", 0.0099, vs=0.05, pw={"consensus": 2.0})
    r = bench_track.trend([a, b])
    kinds = "\n".join(r["regressions"])
    assert "vs_baseline" in kinds and "phase_wall.consensus" in kinds


def test_trend_min_delta_filters_tiny_phase_growth():
    a = _entry("a", 0.01, pw={"stitch": 0.001})
    b = _entry("b", 0.01, pw={"stitch": 0.010})   # +900% but 9 ms
    assert bench_track.trend([a, b])["regressions"] == []


def test_host_only_and_device_entries_never_compared():
    dead = _entry("a", 0.03, vs=None, device_status="unreachable")
    dev = _entry("b", 0.004)            # device run at 13% of host: fine
    r = bench_track.trend([dead, dev])
    assert r["regressions"] == []
    assert len(r["series"]) == 2        # two distinct series


def test_load_history_reads_rounds_log_and_extras(tmp_path):
    (tmp_path / "docs").mkdir()
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"n": 1, "parsed": _entry("x", 0.01)}, f)
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump({"n": 2, "parsed": _entry("x", 0.011)}, f)
    with open(tmp_path / "docs" / "device_bench_log.jsonl", "w") as f:
        f.write(json.dumps(_entry("x", 0.012)) + "\n")
        f.write("not json — hand-edited line skips, not hides\n")
        f.write(json.dumps(_entry("x", 0.013, forced=True)) + "\n")
        f.write(json.dumps({"golden_paf": "ed 1282"}) + "\n")  # no value
    extra = tmp_path / "inject.json"
    with open(extra, "w") as f:
        json.dump(_entry("x", 0.001), f)
    entries, problems = bench_track.load_history(str(tmp_path),
                                                 [str(extra)])
    assert problems == []
    # rounds (2) + one unforced log line + the injected extra
    assert [e["value"] for e in entries] == [0.01, 0.011, 0.012, 0.001]
    assert entries[0]["_source"] == "BENCH_r01.json"
    assert all("cost_model" in e for e in entries)   # normalized backfill
    r = bench_track.trend(entries)
    assert any("value" in s for s in r["regressions"])


def test_load_history_flags_unreadable_round(tmp_path):
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        f.write("{broken")
    _, problems = bench_track.load_history(str(tmp_path))
    assert problems and "BENCH_r01.json" in problems[0]


def test_committed_history_is_clean():
    """The repo's own history must pass its own gate (CI runs this as
    `obs bench` too)."""
    entries, problems = bench_track.load_history()
    assert problems == []
    assert len(entries) >= 5
    assert bench_track.trend(entries)["regressions"] == []


# --------------------------------------------------- histogram quantile

def test_hist_quantile_log2_buckets():
    h = {"count": 4, "sum": 1041.0, "max": 1000.0,
         "buckets": {"1": 1, "8": 2, "1024": 1}}
    # the crossing lands halfway into the (4, 8] bucket: interpolated
    # 6.0, where the old estimator snapped to the upper bound (8.0)
    assert hist_quantile(h, 0.5) == 6.0
    assert hist_quantile(h, 0.99) == 1000.0     # clamped to observed max
    assert hist_quantile({"count": 0, "buckets": {}}, 0.5) is None
    assert hist_quantile({}, 0.5) is None


# --------------------------------------------------------- CLI surface

def test_cli_model_json(capsys):
    assert obs_cli.main(["model", "--json", "--window-length", "500"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["profile"] == "cpu-host"
    assert all(r["class"] == 512 for r in out["rows"]
               if r["kind"] == "poa")


def test_cli_model_rejects_unknown_profile(capsys):
    assert obs_cli.main(["model", "--profile", "abacus"]) == 2


def test_cli_validate_exit_codes(tmp_path, capsys):
    assert obs_cli.main(["validate", str(tmp_path / "missing.json")]) == 2

    pred = costmodel.predict_from_counters(_counters(), CPU)
    good = _trace_doc(_counters(),
                      {p: r["predicted_s"] * 1e6
                       for p, r in pred["phases"].items()})
    p_good = tmp_path / "good.json"
    p_good.write_text(json.dumps(good))
    assert obs_cli.main(["validate", "--json", str(p_good)]) == 0
    v = json.loads(capsys.readouterr().out)
    assert v["ok"] is True and v["profile"] == "cpu-host"

    bad = _trace_doc(_counters(),
                     {p: r["predicted_s"] * 1e6 * 100
                      for p, r in pred["phases"].items()})
    p_bad = tmp_path / "bad.json"
    p_bad.write_text(json.dumps(bad))
    assert obs_cli.main(["validate", str(p_bad)]) == 3
    assert "PAST" in capsys.readouterr().out

    p_schema = tmp_path / "schema.json"
    p_schema.write_text(json.dumps(
        {"traceEvents": [{"name": "x", "ph": "QQ"}]}))
    assert obs_cli.main(["validate", str(p_schema)]) == 1


def test_cli_bench_regression_self_test(tmp_path, capsys):
    (tmp_path / "docs").mkdir()
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"parsed": _entry("x", 0.01)}, f)
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump({"parsed": _entry("x", 0.011)}, f)
    assert obs_cli.main(["bench", "--root", str(tmp_path)]) == 0
    capsys.readouterr()
    inject = tmp_path / "fake_regression.json"
    inject.write_text(json.dumps(_entry("z", 0.001)))
    assert obs_cli.main(["bench", "--root", str(tmp_path),
                         str(inject)]) == 3
    assert "REGRESSION" in capsys.readouterr().out
    assert obs_cli.main(["bench", "--root", str(tmp_path / "empty")]) == 2


def test_cli_legacy_flags_still_dispatch(tmp_path):
    # a trace file literally named "model" must not hijack the
    # subcommand path — subcommand words only dispatch at argv[0]
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"traceEvents": []}))
    assert obs_cli.main(["--validate", str(p)]) == 0


# ------------------------------------------------- ops-side cost hooks

def test_cost_hooks_estimate_maps_builders():
    from racon_tpu.ops import cost_hooks, poa_driver

    cfg = poa_driver.make_config(500, 32, 5, -4, -8)
    est = cost_hooks.estimate("build_poa_kernel", (cfg,), {})
    assert est == costmodel.poa_window_cost(32, cfg.max_backbone, "xla")
    est_ls = cost_hooks.estimate("build_lockstep_poa_kernel", (cfg,), {})
    # xla keeps the one-rank-per-step scan; the ls tier amortizes by
    # LS_GROUP *and* pairs ranks via column compression
    assert (est_ls.serial_steps * costmodel.LS_GROUP
            * costmodel.POA_COLSTEP_PACK == est.serial_steps)
    est_a = cost_hooks.estimate("build_align_kernel", (1024, 256), {})
    assert est_a == costmodel.align_job_cost(1024, 256, "xla")
    assert cost_hooks.estimate("build_mystery_kernel", (1,), {}) is None
    assert cost_hooks.estimate("build_align_kernel", (), {}) is None


def test_cost_hooks_record_build_requires_armed_obs(monkeypatch):
    from racon_tpu import obs
    from racon_tpu.ops import cost_hooks, poa_driver

    cost_hooks.reset()
    obs.reset()
    assert cost_hooks.record_build("build_align_kernel",
                                   (1024, 256), {}) == {}
    monkeypatch.setenv("RACON_TPU_METRICS", "1")
    obs.configure()
    try:
        pred = cost_hooks.record_build("build_align_kernel", (1024, 256),
                                       {})
        assert set(pred) == {"pred_flops", "pred_hbm_bytes",
                             "pred_serial_steps"}
        assert cost_hooks.builds()[-1]["builder"] == "build_align_kernel"
        snap = obs.snapshot()
        assert snap["counters"]["cost_model.builds.build_align_kernel"] == 1
        # the knob kills the stamp even when obs is armed
        monkeypatch.setenv("RACON_TPU_COST_MODEL", "0")
        assert cost_hooks.record_build("build_align_kernel", (1024, 256),
                                       {}) == {}
    finally:
        cost_hooks.reset()
        obs.reset()
