"""Fragment-correction (kF) through the device consensus path on synthetic
all-vs-all overlaps: reads are the targets, dual overlaps drive windows
(reference mode: -f, src/main.cpp:184-186; 'r' provenance tag
src/polisher.cpp:521)."""

import random

import racon_tpu
from racon_tpu import native


def test_fragment_correction_device_path(tmp_path, monkeypatch):
    rng = random.Random(9)
    truth = "".join(rng.choice("ACGT") for _ in range(400))

    def mutate(s, rate):
        out = []
        for c in s:
            r = rng.random()
            if r < rate / 2:
                out.append(rng.choice("ACGT"))
            elif r < rate:
                continue
            else:
                out.append(c)
        return "".join(out)

    reads = [mutate(truth, 0.04) for _ in range(5)]
    with open(tmp_path / "reads.fasta", "w") as f:
        for i, r in enumerate(reads):
            f.write(f">r{i}\n{r}\n")
    with open(tmp_path / "ava.paf", "w") as f:
        for i, a in enumerate(reads):
            for j, b in enumerate(reads):
                if i == j:
                    continue
                f.write(f"r{i}\t{len(a)}\t0\t{len(a)}\t+\tr{j}\t{len(b)}\t"
                        f"0\t{len(b)}\t{min(len(a), len(b))}\t"
                        f"{max(len(a), len(b))}\t60\n")

    from racon_tpu.ops import poa_driver

    captured = {}
    orig = poa_driver.run_consensus_phase

    def spy(*a, **k):
        stats = orig(*a, **k)
        captured.update(stats)
        return stats

    monkeypatch.setattr(poa_driver, "run_consensus_phase", spy)
    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "8")
    p = racon_tpu.TpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ava.paf"),
                              str(tmp_path / "reads.fasta"),
                              fragment_correction=True, window_length=200,
                              match=1, mismatch=-1, gap=-1, num_threads=1)
    p.initialize()
    res = p.polish(False)
    assert len(res) == len(reads)
    for (name, corrected), original in zip(res, reads):
        assert name.startswith("r") and "r LN:i:" in name  # kF 'r' tag
        # corrected read should be closer to truth than the original
        assert (native.edit_distance(corrected.encode(), truth.encode())
                <= native.edit_distance(original.encode(), truth.encode()))
    # the device (default ls tier) must actually have served: a silent
    # per-window host fallback would hide a broken kernel behind correct
    # output
    assert captured["device"] > 0
    assert captured["host_fallback"] == 0 and captured["failed"] == 0
