"""The shared bucketed-batch executor (racon_tpu/ops/batch_exec.py):
every degradation-lattice edge driven deterministically through the
executor itself with a scripted ops object, plus e2e runs proving both
real drivers inherit identical fault semantics from the one seam —
oracle byte-identity and the served-sum invariant intact, including a
kill=1 journal resume.
"""

import json
import os
import random
import subprocess
import sys

import numpy as np

from racon_tpu.ops.batch_exec import BatchExecutor, pipeline_depth
from racon_tpu.resilience.report import PhaseReport

from test_faults import (_assert_report_sums, _oracle, _tpu_run,
                         _write_dataset)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------- scripted ops

class FakeOps:
    """Executor hooks over trivial integer work units.  `fail` maps an
    attempt invocation index to an exception; `dead_tiers` lists tiers
    whose every dispatch/attempt fails (forcing TierDead -> demote)."""

    span_name = "fake.chunk"

    def __init__(self, async_dispatch=True, tiers=("fast", "slow", "host"),
                 fail=None, dead_tiers=(), dispatch_fail=None):
        self.async_dispatch = async_dispatch
        self.tiers = list(tiers)
        self.fail = dict(fail or {})
        self.dead_tiers = set(dead_tiers)
        self.dispatch_fail = set(dispatch_fail or ())
        self.attempts = 0
        self.dispatches = 0
        self.unpacks = 0
        self.installed = []        # (tier, item, result)
        self.surrendered = []      # (item, exported)
        self.quarantined = []      # (item, exc)
        self.demoted = []          # (from, to)
        self.done_chunks = []
        self.tier = self.tiers[0]

    # -- protocol ---------------------------------------------------------
    def live_tier(self, ctx, kind):
        return self.tier

    def export(self, ctx, idxs):
        return [i for i in idxs if i >= 0]

    def pack(self, ctx, chunk):
        return list(chunk)

    def dispatch(self, ctx, kind, packed, chunk):
        self.dispatches += 1
        if kind in self.dead_tiers:
            raise RuntimeError(f"tier {kind} is dead")
        if self.dispatches in self.dispatch_fail:
            raise RuntimeError(f"dispatch {self.dispatches} failed")
        return [x * 10 for x in packed]

    def attempt(self, ctx, kind, sub):
        self.attempts += 1
        if kind in self.dead_tiers:
            raise RuntimeError(f"tier {kind} is dead")
        exc = self.fail.pop(self.attempts, None)
        if exc is not None:
            raise exc
        return [x * 10 for x in sub]

    def unpack(self, ctx, kind, outs):
        self.unpacks += 1
        return list(outs)

    def span_args(self, ctx, chunk, pipelined):
        return {"n": len(chunk), "pipelined": pipelined}

    def install(self, ctx, kind, sub, results):
        for item, r in zip(sub, results):
            self.installed.append((kind, item, r))

    def surrender(self, ctx, items, exported):
        self.surrendered.extend((i, exported) for i in items)

    def quarantine(self, ctx, item, exc):
        self.quarantined.append((item, exc))

    def demote(self, ctx, kind, cause):
        nxt = self.tiers[self.tiers.index(kind) + 1]
        self.demoted.append((kind, nxt))
        self.tier = nxt
        return nxt

    def done(self, ctx, chunk):
        self.done_chunks.append(list(chunk))


def _rep(tiers=("fast", "slow", "host")):
    return PhaseReport("t", tuple(tiers))


# ------------------------------------------------------------- unit tests

def test_depth_pipelined_happy_path_uses_cached_dispatch():
    ops = FakeOps()
    ex = BatchExecutor(ops, depth=2, report=_rep())
    ex.submit(None, [1, 2])
    ex.submit(None, [3, 4])   # depth reached: chunk 1 resolves via cache
    ex.flush()
    assert ops.dispatches == 2
    assert ops.unpacks == 2           # both chunks resolved from futures
    assert ops.attempts == 0          # the lattice never re-packed
    assert [(i, r) for _, i, r in ops.installed] == \
        [(1, 10), (2, 20), (3, 30), (4, 40)]
    assert ops.done_chunks == [[1, 2], [3, 4]]
    assert ex.pack_ns > 0 and ex.kernel_ns > 0


def test_stamp_walls_accumulates_into_report_extra():
    ops = FakeOps()
    rep = _rep()
    ex = BatchExecutor(ops, depth=1, report=rep)
    ex.submit(None, [1])
    ex.flush()
    ex.stamp_walls(rep)
    assert rep.extra["pack_wall_s"] > 0
    assert rep.extra["kernel_wall_s"] > 0
    first = rep.extra["pack_wall_s"]
    ex.stamp_walls(rep)               # accumulating, not overwriting
    assert rep.extra["pack_wall_s"] >= 2 * first
    assert "pack_wall_s" in rep.as_dict()["extra"]


def test_sync_engine_resolves_inline():
    ops = FakeOps(async_dispatch=False)
    ex = BatchExecutor(ops, depth=4, report=_rep())
    ex.submit(None, [1, 2])
    # resolved before flush: host-orchestrated engines never queue
    assert [(i, r) for _, i, r in ops.installed] == [(1, 10), (2, 20)]
    assert ops.dispatches == 0 and ops.unpacks == 0 and ops.attempts == 1
    ex.flush()
    assert len(ops.installed) == 2


def test_dispatch_failure_resolves_through_lattice():
    rep = _rep()
    ops = FakeOps(dispatch_fail={1})
    ex = BatchExecutor(ops, depth=1, report=rep)
    ex.submit(None, [1, 2])
    ex.flush()
    # dispatch blew up synchronously -> recorded as a failure + retry,
    # then the lattice attempt served the chunk at the same tier
    assert [(i, r) for _, i, r in ops.installed] == [(1, 10), (2, 20)]
    assert rep.retries >= 1
    assert rep.causes.get("fast")
    assert ops.attempts >= 1


def test_transient_failure_retried_at_tier(monkeypatch):
    monkeypatch.setenv("RACON_TPU_TIER_RETRIES", "1")
    rep = _rep()
    # sync engine so the FIRST lattice attempt is the serving call
    ops = FakeOps(async_dispatch=False,
                  fail={1: RuntimeError("transient")})
    ex = BatchExecutor(ops, report=rep)
    ex.submit(None, [1, 2, 3])
    ex.flush()
    assert [(i, r) for _, i, r in ops.installed] == \
        [(1, 10), (2, 20), (3, 30)]
    assert rep.retries == 1 and rep.bisections == 0
    assert not ops.demoted


def test_poisoned_item_bisected_and_quarantined(monkeypatch):
    monkeypatch.setenv("RACON_TPU_TIER_RETRIES", "0")

    class PoisonOps(FakeOps):
        def attempt(self, ctx, kind, sub):
            self.attempts += 1
            if 3 in sub:
                raise RuntimeError("poisoned")
            return [x * 10 for x in sub]

    rep = _rep()
    ops = PoisonOps(async_dispatch=False)
    ex = BatchExecutor(ops, report=rep)
    ex.submit(None, [1, 2, 3, 4])
    ex.flush()
    assert sorted(i for _, i, _ in ops.installed) == [1, 2, 4]
    assert [i for i, _ in ops.quarantined] == [3]
    assert rep.bisections >= 1
    assert not ops.demoted


def test_engine_death_demotes_down_to_host(monkeypatch):
    monkeypatch.setenv("RACON_TPU_TIER_RETRIES", "0")
    rep = _rep()
    # every dispatch/attempt at both device tiers fails: fast -> slow ->
    # host, and the chunk surrenders to the host floor (exported=True)
    ops = FakeOps(dead_tiers={"fast", "slow"})
    ex = BatchExecutor(ops, depth=1, report=rep)
    ex.submit(None, [1, 2])
    ex.flush()
    assert ops.demoted == [("fast", "slow"), ("slow", "host")]
    assert ops.surrendered == [(1, True), (2, True)]
    assert not ops.installed
    assert ops.done_chunks == [[1, 2]]   # packed state still released


def test_host_entry_tier_surrenders_unexported():
    ops = FakeOps(tiers=("host",))
    ex = BatchExecutor(ops, report=_rep())
    ex.submit(None, [7, 8])
    ex.flush()
    assert ops.surrendered == [(7, False), (8, False)]
    assert ops.dispatches == 0 and ops.attempts == 0


def test_empty_export_skips_dispatch():
    ops = FakeOps()
    ex = BatchExecutor(ops, report=_rep())
    ex.submit(None, [-1, -2])     # export filters everything out
    ex.flush()
    assert ops.dispatches == 0 and not ops.installed


def test_pipeline_depth_knob(monkeypatch):
    monkeypatch.setenv("RACON_TPU_PIPELINE_DEPTH", "5")
    assert pipeline_depth() == 5
    monkeypatch.setenv("RACON_TPU_PIPELINE_DEPTH", "0")
    assert pipeline_depth() == 1          # floor


# ------------------------------------------ e2e through the real drivers

def test_consensus_driver_full_lattice_chain(tmp_path, monkeypatch):
    """Retry + bisect-quarantine in ONE consensus run, all flowing
    through the shared executor: output byte-identical to the oracle,
    served counts sum, pack/kernel wall split stamped."""
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        # invocation 0 (pipelined dispatch) fails synchronously, so the
        # executor records the failure and re-resolves through the
        # lattice; the window=2 poison then forces a bisect-quarantine
        "RACON_TPU_FAULT": ("poa.run.xla:batch=0:count=1,"
                            "poa.run.xla:window=2"),
    })
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["quarantined"] == [2]
    assert cons["served"]["host"] == 1 and cons["served"]["xla"] == 5
    assert cons["retries"] >= 1 and cons["bisections"] >= 1
    # the executor stamped the feeder's wall split
    assert cons["extra"]["kernel_wall_s"] > 0
    assert cons["extra"]["pack_wall_s"] > 0


def test_xla_align_driver_through_executor(tmp_path, monkeypatch):
    """The moves-matrix aligner now runs on the executor: poisoned job
    quarantined, the rest stay device-served, wall split stamped."""
    paths = _write_dataset(tmp_path, overlaps="paf", n_reads=2)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_DEVICE_ALIGNER": "xla",
        "RACON_TPU_FAULT": "align.run:window=3",
    })
    assert res == oracle
    d = _assert_report_sums(p)
    al = d["phases"]["alignment"]
    assert 3 in al["quarantined"]
    assert al["served"]["xla"] == 5 and al["served"]["host"] == 1
    assert al["bisections"] >= 1
    assert al["extra"]["kernel_wall_s"] > 0


def test_xla_align_engine_death_mid_cohort(monkeypatch):
    """Engine death after the first cohort resolved: already-installed
    CIGARs are kept and counted device-served (the ADVICE.md regression,
    now enforced by the executor's demote/surrender seam)."""
    rng = random.Random(9)
    pairs = []
    for _ in range(6):
        t = bytes(rng.choice(b"ACGT") for _ in range(300))
        pairs.append((t, t))

    class FakePipe:
        def __init__(self, pairs):
            self.pairs = pairs
            self.cigars = {}

        def align_job(self, i):
            q, t = self.pairs[i]
            return (np.frombuffer(q, np.uint8), np.frombuffer(t, np.uint8))

        def set_job_cigar(self, i, c):
            self.cigars[i] = c

    monkeypatch.setenv("RACON_TPU_PIPELINE_DEPTH", "1")
    monkeypatch.setenv("RACON_TPU_TIER_RETRIES", "0")
    monkeypatch.setenv(
        "RACON_TPU_FAULT",
        ",".join(f"align.run:batch={i}" for i in range(1, 12)))
    from racon_tpu.ops import align
    rep = PhaseReport("alignment", ("xla", "host"))
    pipe = FakePipe(pairs)
    served = align.run_jobs(pipe, list(range(6)), batch=2, report=rep)
    # cohort 0 (jobs 0,1) was dispatched AND resolved before the engine
    # died on cohort 1's dispatch; cohorts 1,2 fall to the host
    assert served == 2
    assert sorted(pipe.cigars) == [0, 1]
    assert rep.served.get("xla") == 2
    assert any(d["from"] == "xla" and d["to"] == "host"
               for d in rep.as_dict()["degradations"])


def test_kill_resume_through_executor(tmp_path):
    """kill=1 mid-consensus (inside the executor's dispatch fault
    check), then resume from the journal: already-journaled windows are
    replayed, the rest recomputed, output byte-identical.  Subprocess
    because the fault hard-kills the process."""
    paths = _write_dataset(tmp_path)

    def cli(*extra, env=None):
        cmd = [sys.executable, "-m", "racon_tpu.cli", "--tpu",
               "-w", "100", "-q", "10", "-e", "0.3",
               "-m", "5", "-x", "-4", "-g", "-8", *extra, *paths]
        full_env = dict(os.environ, JAX_PLATFORMS="cpu",
                        RACON_TPU_PALLAS="0", RACON_TPU_POA_KERNEL="v2",
                        RACON_TPU_BATCH_WINDOWS="2")
        full_env.pop("RACON_TPU_FAULT", None)
        # conftest's 8-virtual-device XLA_FLAGS would round the 2-window
        # batches up to one 8-window dispatch and the kill would not fire
        full_env.pop("XLA_FLAGS", None)
        full_env.update(env or {})
        return subprocess.run(cmd, cwd=ROOT, env=full_env,
                              capture_output=True, timeout=540)

    baseline = cli()
    assert baseline.returncode == 0, baseline.stderr.decode()

    jp = str(tmp_path / "run.journal")
    # batch=2 windows/chunk, depth 2: chunk 0 installs (2 windows
    # journaled) when chunk 1 enters the pipe; the third dispatch kills
    killed = cli("--journal", jp,
                 env={"RACON_TPU_FAULT": "poa.run.xla:batch=2:kill=1"})
    assert killed.returncode != 0
    assert os.path.exists(jp)

    rp = str(tmp_path / "resume_report.json")
    resumed = cli("--resume-journal", jp, "--report", rp)
    assert resumed.returncode == 0, resumed.stderr.decode()
    assert resumed.stdout == baseline.stdout
    rep = json.loads(open(rp).read())
    cons = rep["phases"]["consensus"]
    assert sum(cons["served"].values()) == cons["total"]
    assert cons["served"].get("journal", 0) >= 1
