"""The partitioning subsystem: mesh discovery, logical-axis rules, pad
accounting, the will_shard gate, the sharded->single-device lattice edge,
and the jax shard_map version shim — all on the 8-virtual-device mesh the
conftest forces."""

import random
import types

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from racon_tpu import obs
from racon_tpu.parallel import axes, divisible_batch
from racon_tpu.parallel.mesh import resolve_shard_map
from racon_tpu.parallel.partitioner import (Partitioner, build_mesh,
                                            get_partitioner, mesh_shape)
from racon_tpu.resilience import lattice as rl
from racon_tpu.resilience.report import PhaseReport


# -- shard_map version shim (satellite: compat-shim test coverage) ---------

def test_resolve_shard_map_real_jax_runs():
    """Whatever spelling this jax ships, the resolved pair must wrap and
    execute a trivial sharded function over the real device mesh."""
    smap, no_check = resolve_shard_map()
    assert callable(smap)
    assert no_check in ({"check_rep": False}, {"check_vma": False})
    part = get_partitioner()
    spec = part.spec("windows")
    fn = jax.jit(smap(lambda x: x * 2, mesh=part.mesh,
                      in_specs=(spec,), out_specs=spec, **no_check))
    x = np.arange(16, dtype=np.int32).reshape(8, 2)
    np.testing.assert_array_equal(np.asarray(fn(x)), x * 2)


def test_resolve_shard_map_public_branch():
    """jax >= 0.7 spelling: top-level shard_map, check_vma kwarg."""
    sentinel = lambda *a, **k: "public"  # noqa: E731
    fake = types.SimpleNamespace(shard_map=sentinel)
    fn, no_check = resolve_shard_map(fake)
    assert fn is sentinel
    assert no_check == {"check_vma": False}


def test_resolve_shard_map_experimental_branch():
    """jax 0.4.x spelling: jax.experimental.shard_map.shard_map with the
    check_rep kwarg."""
    sentinel = lambda *a, **k: "experimental"  # noqa: E731
    fake = types.SimpleNamespace(
        experimental=types.SimpleNamespace(
            shard_map=types.SimpleNamespace(shard_map=sentinel)))
    fn, no_check = resolve_shard_map(fake)
    assert fn is sentinel
    assert no_check == {"check_rep": False}


def test_resolve_shard_map_experimental_import_fallback():
    """A jax whose `experimental` hasn't loaded the submodule yet: the
    shim must import <mod>.experimental.shard_map by name."""
    fake = types.SimpleNamespace(
        __name__="jax", experimental=types.SimpleNamespace())
    fn, no_check = resolve_shard_map(fake)
    assert callable(fn)
    assert no_check == {"check_rep": False}


# -- logical axis rules ----------------------------------------------------

def test_resolve_spec_default_rules():
    spec = axes.resolve_spec(("windows", "depth", "lane"),
                             axes.DEFAULT_RULES, axes.MESH_AXES)
    assert spec == PartitionSpec("data", "model", None)
    assert axes.resolve_spec((), axes.DEFAULT_RULES,
                             axes.MESH_AXES) == PartitionSpec()
    # None entries and lane dims replicate
    assert axes.resolve_spec(("query", None, "lane"), axes.DEFAULT_RULES,
                             axes.MESH_AXES) == \
        PartitionSpec("data", None, None)


def test_resolve_spec_rejects_unknown_axis():
    with pytest.raises(ValueError, match="unknown logical axis"):
        axes.resolve_spec(("windoes",), axes.DEFAULT_RULES, axes.MESH_AXES)


def test_resolve_spec_rejects_absent_mesh_axis():
    rules = (("windows", "expert"),)
    with pytest.raises(ValueError, match="absent from this mesh"):
        axes.resolve_spec(("windows",), rules, ("data",))


def test_validate_rules_errors():
    with pytest.raises(ValueError, match="unknown logical axis"):
        axes.validate_rules((("bogus", "data"),), axes.MESH_AXES)
    with pytest.raises(ValueError, match="duplicate rule"):
        axes.validate_rules((("windows", "data"), ("windows", None)),
                            axes.MESH_AXES)
    with pytest.raises(ValueError, match="no such axis"):
        axes.validate_rules((("windows", "expert"),), axes.MESH_AXES)


def test_rules_registry_roundtrip(monkeypatch):
    """set_rules changes what get_partitioner memoizes on (rules_key is
    part of the cache key), and a depth-replicated override resolves."""
    override = (("windows", "data"), ("query", "data"),
                ("depth", None), ("lane", None))
    monkeypatch.setattr(axes, "_RULES", override)
    assert axes.get_rules() == override
    assert axes.rules_key() == override
    part = get_partitioner()
    assert part.spec("windows", "depth") == PartitionSpec("data", None)


# -- mesh discovery --------------------------------------------------------

def test_mesh_shape_spellings(monkeypatch):
    monkeypatch.delenv("RACON_TPU_MESH_SHAPE", raising=False)
    assert mesh_shape(8) == (8, 1)
    monkeypatch.setenv("RACON_TPU_MESH_SHAPE", "8")
    assert mesh_shape(8) == (8, 1)
    monkeypatch.setenv("RACON_TPU_MESH_SHAPE", "4,2")
    assert mesh_shape(8) == (4, 2)
    monkeypatch.setenv("RACON_TPU_MESH_SHAPE", "4x2")
    assert mesh_shape(8) == (4, 2)
    monkeypatch.setenv("RACON_TPU_MESH_SHAPE", "2")
    assert mesh_shape(8) == (2, 1)


def test_mesh_shape_invalid_degrades_with_warning(monkeypatch, capsys):
    """Mis-set knobs degrade to the all-devices default, never fail."""
    for bad in ("garbage", "16", "0,4", "2,2,2"):
        monkeypatch.setenv("RACON_TPU_MESH_SHAPE", bad)
        assert mesh_shape(8) == (8, 1)
        assert "RACON_TPU_MESH_SHAPE" in capsys.readouterr().err


def test_build_mesh_flat_and_undersubscribed():
    assert len(jax.devices()) == 8
    full = build_mesh((8, 1))
    assert dict(full.shape) == {"data": 8, "model": 1}
    sub = build_mesh((2, 1))
    assert dict(sub.shape) == {"data": 2, "model": 1}
    assert list(sub.devices.ravel()) == jax.devices()[:2]
    two_d = build_mesh((4, 2))
    assert dict(two_d.shape) == {"data": 4, "model": 2}


# -- pad accounting --------------------------------------------------------

def test_pad_rows_rounds_up():
    part = get_partitioner()
    assert part.batch_axis_size == 8
    assert part.pad_rows(13) == 16
    assert part.pad_rows(8) == 8
    assert part.pad_rows(1) == 8
    assert part.pad_rows(17) == 24


def test_divisible_batch_round_down_regression_pin():
    """The legacy helper rounds DOWN (remainder windows spilled to the
    slow path); the partitioner rounds UP and accounts the pad — the
    satellite this PR fixes, pinned as a visible difference."""
    assert divisible_batch(8, 13) == 8          # 5 windows spilled
    assert get_partitioner().pad_rows(13) == 16  # 3 pad rows, none spilled


def test_pad_packed_repeats_final_row():
    part = get_partitioner()
    a = np.arange(26, dtype=np.int32).reshape(13, 2)
    b = np.arange(13, dtype=np.int32)
    (pa, pb), pad = part.pad_packed((a, b))
    assert pad == 3 and pa.shape == (16, 2) and pb.shape == (16,)
    np.testing.assert_array_equal(pa[13:], np.repeat(a[-1:], 3, axis=0))
    np.testing.assert_array_equal(pb[13:], [12, 12, 12])
    same, none = part.pad_packed((np.zeros((8, 2)),))
    assert none == 0 and same[0].shape == (8, 2)


def test_pad_to_multiple_and_balanced_counters():
    """The executor's one-place pad seam + the balance evidence: after
    padding, every device position counts the same row total (balanced
    to within one batch per device, per the acceptance criterion)."""
    from racon_tpu.ops.batch_exec import count_shard_rows, pad_to_multiple

    obs.configure(metrics=True)
    packed = (np.arange(26, dtype=np.int32).reshape(13, 2),)
    padded, pad = pad_to_multiple(packed, 8)
    assert pad == 3 and padded[0].shape == (16, 2)
    assert count_shard_rows(13, 16, 8) == 3
    snap = obs.snapshot()["counters"]
    per_dev = [snap[f"shard.rows.d{i}"] for i in range(8)]
    assert per_dev == [2] * 8          # balanced: 16 rows / 8 devices
    assert snap["shard.pad_rows"] == 3
    assert snap["shard.chunks"] == 1


# -- the will_shard gate ---------------------------------------------------

def test_will_shard_gating(monkeypatch):
    part = get_partitioner()
    assert part.will_shard(8) and part.will_shard(64)
    assert not part.will_shard(7)     # below one row per shard
    monkeypatch.setenv("RACON_TPU_SHARD_MIN_BATCH", "4")
    assert part.will_shard(4) and not part.will_shard(3)
    monkeypatch.setenv("RACON_TPU_SHARD", "0")
    assert not part.will_shard(64)    # kill switch wins


def test_demote_is_sticky_and_reported_once():
    part = get_partitioner()
    assert part.disabled is None
    assert part.demote("boom") is True     # first demotion: record it
    assert part.demote("again") is False   # sticky: already single-device
    assert not part.will_shard(64)
    assert part.shard_build(lambda b: (lambda x: x), 64, 1, 1) is None
    # the process-wide singleton carries the state
    assert get_partitioner().disabled is not None


def test_record_shard_demotion_lattice_edge():
    """The edge is orthogonal to tier demotion: degradation list shows
    `<tier>+sharded -> <tier>` and the shard.demotions counter ticks."""
    obs.configure(metrics=True)
    rep = PhaseReport("consensus", ("ls", "v2", "xla", "host"))
    rl.record_shard_demotion(rep, "ls", RuntimeError("device lost"))
    assert rep.degradations == [{"from": "ls+sharded", "to": "ls",
                                 "error": "RuntimeError: device lost"}]
    assert obs.snapshot()["counters"]["shard.demotions"] == 1
    rl.record_shard_demotion(None, "xla", "compile failed")  # no report
    assert obs.snapshot()["counters"]["shard.demotions"] == 2


# -- kernel wrapping -------------------------------------------------------

def test_partition_pjit_path_executes():
    part = get_partitioner()
    fn = part.partition(lambda x, y: x + y,
                        in_axes=[("windows", "lane"), ("windows", "lane")],
                        out_axes=("windows", "lane"))
    x = np.arange(32, dtype=np.int32).reshape(16, 2)
    np.testing.assert_array_equal(np.asarray(fn(x, x)), x + x)


def test_shard_build_traces_local_batch():
    """The shard_map path hands each device a kernel built for the LOCAL
    batch size and reassembles the global batch."""
    part = get_partitioner()
    seen = []

    def build_local(b):
        seen.append(b)
        return lambda x: x * 3

    kern = part.shard_build(build_local, 16, 1, 1)
    assert kern is not None and seen == [2]    # 16 rows / 8 shards
    x = np.arange(16, dtype=np.int32).reshape(16, 1)
    np.testing.assert_array_equal(np.asarray(kern(x)), x * 3)


def test_shard_build_declines_bad_batches():
    part = get_partitioner()
    build = lambda b: (lambda x: x)  # noqa: E731
    assert part.shard_build(build, 10, 1, 1) is None   # 10 % 8 != 0
    assert part.shard_build(build, 4, 1, 1) is None    # fewer than shards


# -- end-to-end: byte identity + the demotion edge -------------------------

def _dataset(tmp_path, n_targets=3):
    rng = random.Random(11)
    targets = []
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            targets.append(seq)
            tf.write(f">t{t}\n{seq}\n")
            for i in range(4):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                         f"{seq}\t*\n")
    return targets


def _polish(tmp_path):
    import racon_tpu

    p = racon_tpu.TpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ovl.sam"),
                              str(tmp_path / "targets.fasta"),
                              window_length=100, quality_threshold=10,
                              error_threshold=0.3, match=5, mismatch=-4,
                              gap=-8, num_threads=1)
    p.initialize()
    return p.polish(True), p


def test_sharded_polish_byte_identical_to_single_device(tmp_path,
                                                        monkeypatch):
    """Sharding changes where rows compute, never what: the same polish
    with the mesh on vs RACON_TPU_SHARD=0 must be byte-identical, and the
    sharded run's obs counters must show balanced per-device rows."""
    targets = _dataset(tmp_path)
    monkeypatch.setenv("RACON_TPU_PALLAS", "0")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "8")
    monkeypatch.setenv("RACON_TPU_METRICS", "1")
    sharded, _ = _polish(tmp_path)
    snap = obs.snapshot()["counters"]
    assert snap.get("shard.chunks", 0) >= 1
    rows = [v for k, v in snap.items() if k.startswith("shard.rows.d")]
    assert len(rows) == 8 and max(rows) - min(rows) == 0
    monkeypatch.setenv("RACON_TPU_SHARD", "0")
    single, _ = _polish(tmp_path)
    assert sharded == single
    for (_, got), want in zip(single, targets):
        assert got == want


def test_sharded_build_failure_demotes_never_fails(tmp_path, monkeypatch,
                                                   capsys):
    """The lattice edge end-to-end: a sharded build that dies drops to
    single-device dispatch at the SAME tier, output still correct, the
    demotion recorded (sticky) — the polish never fails."""
    targets = _dataset(tmp_path)
    monkeypatch.setenv("RACON_TPU_PALLAS", "0")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "8")
    monkeypatch.setenv("RACON_TPU_METRICS", "1")

    def broken_partition(self, fn, in_axes, out_axes):
        raise RuntimeError("forced sharded build failure")

    monkeypatch.setattr(Partitioner, "partition", broken_partition)
    monkeypatch.setattr(Partitioner, "shard_build",
                        lambda self, *a, **k: (_ for _ in ()).throw(
                            RuntimeError("forced sharded build failure")))
    res, p = _polish(tmp_path)
    for (_, got), want in zip(res, targets):
        assert got == want
    assert get_partitioner().disabled is not None
    assert obs.snapshot()["counters"].get("shard.demotions", 0) >= 1
    assert "demoting to single-device dispatch" in capsys.readouterr().err
