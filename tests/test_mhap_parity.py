"""MHAP/PAF parity: the same overlaps expressed in MHAP (1-based numeric
ordinals, reference: src/overlap.cpp:15-27) and PAF (names) must produce an
identical polished contig — exercises the id_to_id transmutation path
(reference: src/overlap.cpp:129-177) end-to-end."""

import gzip

import racon_tpu
from tests.conftest import DATA, requires_data

pytestmark = requires_data


def paf_to_mhap(paf_path, reads_order, targets_order, out_path):
    name_to_read_ordinal = {n: i + 1 for i, n in enumerate(reads_order)}
    name_to_target_ordinal = {n: i + 1 for i, n in enumerate(targets_order)}
    with gzip.open(paf_path, "rt") as f, open(out_path, "w") as out:
        for line in f:
            q_name, q_len, q_b, q_e, strand, t_name, t_len, t_b, t_e = \
                line.split("\t")[:9]
            a_rc = 1 if strand == "-" else 0
            out.write(f"{name_to_read_ordinal[q_name]} "
                      f"{name_to_target_ordinal[t_name]} 0.1 0 "
                      f"{a_rc} {q_b} {q_e} {q_len} "
                      f"0 {t_b} {t_e} {t_len}\n")


def fastx_names(path, marker):
    """Record names in file order (multi-line records handled the way the
    native parser handles them)."""
    names = []
    with gzip.open(path, "rt") as f:
        if marker == ">":
            for line in f:
                if line.startswith(">"):
                    names.append(line[1:].split()[0].strip())
            return names
        lines = iter(f)
        while True:
            header = None
            for line in lines:
                if line.startswith("@"):
                    header = line.rstrip("\n")
                    break
            if header is None:
                break
            data = ""
            for line in lines:
                if line.startswith("+"):
                    break
                data += line.rstrip("\n")
            qual = ""
            while len(qual) < len(data):
                qual += next(lines).rstrip("\n")
            names.append(header[1:].split()[0])
    return names


def test_mhap_equals_paf_polish(tmp_path):
    reads_order = fastx_names(DATA + "sample_reads.fastq.gz", "@")
    targets_order = fastx_names(DATA + "sample_layout.fasta.gz", ">")
    mhap = tmp_path / "overlaps.mhap"
    paf_to_mhap(DATA + "sample_overlaps.paf.gz", reads_order, targets_order,
                str(mhap))

    def polish(ovl):
        p = racon_tpu.CpuPolisher(DATA + "sample_reads.fastq.gz", ovl,
                                  DATA + "sample_layout.fasta.gz",
                                  window_length=500, match=5, mismatch=-4,
                                  gap=-8)
        p.initialize()
        return p.polish(True)

    res_paf = polish(DATA + "sample_overlaps.paf.gz")
    res_mhap = polish(str(mhap))
    assert len(res_paf) == len(res_mhap) == 1
    assert res_paf[0][1] == res_mhap[0][1]
    assert res_paf[0][0] == res_mhap[0][0]
