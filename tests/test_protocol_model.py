"""Engine 4: the protocol model checker (racon_tpu/analysis/protocol).

Five contracts:

* the real model is clean AND the bounded default configuration is
  exhausted (a clean verdict from a partial exploration proves
  nothing), comfortably inside the CI time gate;
* every seeded transition-guard mutation is caught by exactly the
  invariant its fixture scenario names — the checker's self-test;
* the declared ``TRANSITIONS`` literal and the runtime ``successors()``
  generator stay in sync, and the conformance pass keeps both pinned
  to the real code (fixture mini-trees fire one drift rule each, the
  real tree is clean);
* counterexample traces compile into ``RACON_TPU_FAULT`` schedules the
  real fault grammar accepts;
* the bridge is real: a compiled witness schedule (worker death +
  lease reclaim) replayed against a live 2-worker fleet shows the
  modeled recovery — death observed, lease reclaimed, byte-identical
  gather.
"""

import glob
import json
import os
import random

import pytest

import racon_tpu
from racon_tpu.analysis.__main__ import main as analysis_main
from racon_tpu.analysis.concurrency import contracts
from racon_tpu.analysis.protocol import checker, conformance, replay
from racon_tpu.analysis.protocol import invariants as inv
from racon_tpu.analysis.protocol.model import (Config, MUTATIONS,
                                               TRANSITIONS, initial,
                                               mutation_entry,
                                               successors,
                                               transition_names)
from racon_tpu.resilience import faults
from racon_tpu.serve import ServeClient, ServeDaemon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXROOT = os.path.join(REPO, "tests", "analysis_fixtures", "protocol")
CONCROOT = os.path.join(REPO, "tests", "analysis_fixtures",
                        "concurrency")

# ------------------------------------------------- the bounded space

#: One exploration of the default config, shared by the verdict test
#: and the transition-coverage test (exhausting it costs ~15s).
_EXPLORED = {}


def _explore():
    if _EXPLORED:
        return _EXPLORED
    cfg = Config()
    res = checker.check(cfg, stop_on_first=False)
    names = set()
    seen = {initial(cfg)}
    frontier = [initial(cfg)]
    # shallow sweep for event-name coverage: every transition shows up
    # within a few BFS levels of the initial state
    for _ in range(12):
        nxt = []
        for s in frontier:
            for ev, ns in successors(cfg, s, None):
                names.add(ev[0])
                if ns not in seen and len(seen) < 60_000:
                    seen.add(ns)
                    nxt.append(ns)
        frontier = nxt
        if len(names) == len(TRANSITIONS):
            break
    _EXPLORED.update(result=res, names=names)
    return _EXPLORED


def test_real_model_clean_and_exhaustive():
    res = _explore()["result"]
    assert res.exhausted, "default config must be fully explorable"
    assert res.ok, "\n".join(v.render() for v in res.violations)
    assert res.elapsed_s < 60, (
        f"bounded config took {res.elapsed_s:.1f}s — CI gate is 60s")


def test_successors_implement_exactly_the_declared_transitions():
    names = _explore()["names"]
    declared = set(transition_names())
    assert names == declared, (
        f"model drift: declared-but-never-fired="
        f"{sorted(declared - names)}, fired-but-undeclared="
        f"{sorted(names - declared)}")


def test_declared_fault_points_cover_every_fleet_scoped_point():
    claimed = {t[3] for t in TRANSITIONS if t[3] is not None}
    fleet = {p for p in faults.KNOWN_POINTS
             if p.startswith(conformance.FLEET_PREFIXES)}
    assert fleet <= claimed, sorted(fleet - claimed)


# ------------------------------------------- seeded-mutant self-test

_SCENARIOS = sorted(glob.glob(os.path.join(FIXROOT, "invariants",
                                           "*.json")))


@pytest.mark.parametrize(
    "path", _SCENARIOS, ids=[os.path.basename(p) for p in _SCENARIOS])
def test_each_invariant_violated_by_its_seeded_mutation(path):
    with open(path) as f:
        scen = json.load(f)
    name, _doc, expected, overrides = mutation_entry(scen["mutation"])
    assert expected == scen["invariant"]
    assert overrides == scen["config"]
    res = checker.check(mutation=name)
    got = {v.invariant for v in res.violations}
    assert got == {scen["invariant"]}, (
        f"{name}: expected {scen['invariant']}, got {got}")
    assert all(v.trace for v in res.violations
               if v.invariant != inv.QUIESCENCE)


def test_every_scenario_file_exists_per_invariant():
    covered = {json.load(open(p))["invariant"] for p in _SCENARIOS}
    assert covered == set(inv.invariant_names())


@pytest.mark.parametrize("mutation", [m[0] for m in MUTATIONS])
def test_every_mutation_is_caught(mutation):
    res = checker.check(mutation=mutation)
    expected = mutation_entry(mutation)[2]
    assert expected in {v.invariant for v in res.violations}, (
        f"checker missed seeded mutation {mutation}")


def test_dfs_fallback_finds_safety_violations():
    res = checker.check(mutation="expiry-releases-journal",
                        strategy="dfs", depth=12)
    assert any(v.invariant == inv.ONE_CANONICAL
               for v in res.violations)


# ------------------------------------------------ conformance fixtures

@pytest.mark.parametrize("tree,rule", [
    ("badsite", "model-site"),
    ("badfault", "model-fault"),
    ("uncovered", "model-coverage"),
])
def test_conformance_fixture_fires_exactly_once(tree, rule):
    vs = conformance.audit(os.path.join(FIXROOT, tree))
    assert [v.rule for v in vs] == [rule], [v.render() for v in vs]


def test_conformance_real_tree_clean():
    assert [v.render() for v in conformance.audit(REPO)] == []


def test_conformance_skips_trees_without_a_model():
    assert conformance.audit(os.path.join(CONCROOT, "races")) == []


def test_contracts_fault_model_fixture_fires_exactly_once():
    vs = contracts.audit(os.path.join(FIXROOT, "faultmodel"))
    assert [v.rule for v in vs] == ["fault-model"], \
        [v.render() for v in vs]
    assert "pool.steal" in vs[0].message


# ------------------------------------------------- schedule compiling

def test_counterexample_compiles_to_valid_fault_schedule():
    res = checker.check(mutation="reclaim-skips-requeue",
                        stop_on_first=True)
    sched = replay.compile_trace(res.violations[0].trace)
    assert sched.spec, "a worker-death trace must inject something"
    assert faults.parse_spec(sched.spec)     # real grammar accepts it
    assert sched.worker is not None
    assert "worker_die" in sched.events


def test_two_worker_scopes_are_unreplayable():
    trace = [("worker_die", (0,)), ("worker_die", (1,))]
    with pytest.raises(replay.Unreplayable):
        replay.compile_trace(trace)


def test_witness_trace_is_schedulable_and_quiescent():
    trace, sched = replay.witness_trace()
    names = [ev[0] for ev in trace]
    assert "worker_die" in names and "lease_reclaim" in names
    assert names[-1] == "gather"
    assert faults.parse_spec(sched.spec)
    assert sched.env()[replay.FAULT_ENV] == sched.spec


# --------------------------------------------------------------- CLI

def test_cli_model_check_small_config_exits_zero(capsys):
    rc = analysis_main(["--model-check", "--repo-root", REPO,
                        "--mc-chunks", "A,A", "--mc-submits", "2"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "exhausted" in out


def test_cli_mutate_exits_nonzero(capsys):
    rc = analysis_main(["--mutate", "split-check-reserve",
                        "--repo-root", REPO])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "protocol-invariant" in out and "budget-capacity" in out


def test_cli_partial_exploration_is_not_a_clean_verdict(capsys):
    rc = analysis_main(["--model-check", "--repo-root", REPO,
                        "--mc-max-states", "50"])
    capsys.readouterr()
    assert rc == 3


def test_cli_emit_schedule(tmp_path, capsys):
    dest = str(tmp_path / "sched.json")
    rc = analysis_main(["--mutate", "expiry-releases-journal",
                        "--repo-root", REPO, "--emit-schedule", dest])
    capsys.readouterr()
    assert rc == 1
    with open(dest) as f:
        payload = json.load(f)
    assert payload["source"] == inv.ONE_CANONICAL
    assert payload["trace"], payload
    # this counterexample needs no injection (pure timing), so the
    # compiled env must be empty rather than an empty spec string
    assert payload["spec"] == "" and payload["env"] == {}


def test_cli_list_mutations(capsys):
    assert analysis_main(["--list-mutations"]) == 0
    out = capsys.readouterr().out
    for name, _doc, expected, _cfg in MUTATIONS:
        assert name in out and expected in out


def test_cli_list_rules_includes_engine4(capsys):
    assert analysis_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("model-site", "model-fault", "model-coverage",
                "fault-model", "protocol-invariant"):
        assert rid in out


# ----------------------------------- satellite: --paths + audit flags

def test_cli_paths_with_explicit_concurrency_runs_the_audit(capsys):
    """Explicit --concurrency wins over the paths-implies-lint-only
    default: the scoped races fixture must actually be audited."""
    rc = analysis_main(["--repo-root", os.path.join(CONCROOT, "races"),
                        "--concurrency", "--paths",
                        "racon_tpu/svc.py"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "unguarded-mutation" in out


def test_cli_paths_without_flags_stays_lint_only(capsys):
    analysis_main(["--repo-root", os.path.join(CONCROOT, "races"),
                   "--paths", "racon_tpu/svc.py"])
    out = capsys.readouterr().out
    # the audit must NOT ride along on a plain --paths run (the races
    # tree would fire unguarded-mutation if it did)
    assert "unguarded-mutation" not in out


def test_cli_paths_contracts_without_anchor_errors_clearly(capsys):
    rc = analysis_main(["--repo-root", REPO, "--contracts",
                        "--paths", "racon_tpu/fleet/plane.py"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "anchor" in err


def test_cli_paths_contracts_with_anchor_runs_scoped(capsys):
    rc = analysis_main(["--repo-root", REPO, "--contracts", "--paths",
                        "racon_tpu/resilience/faults.py"])
    out = capsys.readouterr().out
    assert rc == 0, out


# ------------------------------------------------- e2e replay bridge

_ARGS = dict(window_length=100, quality_threshold=10,
             error_threshold=0.3, match=5, mismatch=-4, gap=-8,
             num_threads=1)


def _write_dataset(tmp_path, n_targets=3, n_reads=4, seed=11):
    rng = random.Random(seed)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                         f"{seq}\t*\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta"))


def _oracle_fasta(paths):
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    return "".join(f">{n}\n{d}\n" for n, d in p.polish(True))


def test_e2e_witness_schedule_replays_on_real_fleet(tmp_path,
                                                    monkeypatch):
    """The model->daemon bridge, end to end: the shortest real-model
    run through worker_die + lease_reclaim compiles to a
    RACON_TPU_FAULT schedule; replaying it against a live 2-worker
    fleet reproduces the modeled interleaving's observable effects —
    the worker dies mid-chunk, its lease is reclaimed, and the job
    still gathers byte-identical output exactly once (the modeled
    recovery rather than an invariant violation, because the real
    model is clean)."""
    trace, sched = replay.witness_trace()
    assert sched.events == ("worker_die",)
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)
    for key, val in sched.env().items():
        monkeypatch.setenv(key, val)
    daemon = ServeDaemon(str(tmp_path / "state"), backend="cpu",
                         port=0, warm=False, fleet_min=1, fleet_max=2)
    daemon.start()
    try:
        with ServeClient(daemon.port, timeout=240) as c:
            jid = c.submit(*paths, args=dict(_ARGS), submitter="replay")
            res = c.wait(jid, timeout=240)
        assert res["state"] == "done"
        assert open(res["result"]["output"]).read() == want
        snap = daemon.plane.snapshot()
        # the modeled worker_die -> lease_reclaim arc, observed live
        assert snap["counters"]["workers_dead"] >= 1
        assert snap["counters"]["lease_reclaimed"] >= 1
    finally:
        daemon.stop(wait=True)
