"""Tool tests: sampler (rampler-equivalent), wrapper, preprocess."""

import gzip
import io
import os
import subprocess
import sys

import pytest

from racon_tpu.tools import preprocess, sampler
from tests.conftest import DATA, read_fasta_gz, requires_data


pytestmark = requires_data

def _write_fasta(path, records):
    with open(path, "w") as f:
        for name, data in records:
            f.write(f">{name}\n{data}\n")


def test_split_chunks(tmp_path):
    recs = [(f"r{i}", "ACGT" * 100) for i in range(10)]  # 400 bp each
    src = tmp_path / "seqs.fasta"
    _write_fasta(src, recs)
    outs = sampler.split(str(src), 1000, str(tmp_path))
    assert len(outs) == 4  # 3 records (1200bp) per chunk
    total = []
    for o in outs:
        assert os.path.basename(o).startswith("seqs_")
        with open(o) as f:
            total += [l for l in f if l.startswith(">")]
    assert len(total) == 10


def test_subsample_respects_target(tmp_path):
    recs = [(f"r{i}", "ACGT" * 250) for i in range(20)]  # 1000 bp each
    src = tmp_path / "reads.fastq"
    with open(src, "w") as f:
        for name, data in recs:
            f.write(f"@{name}\n{data}\n+\n{'I' * len(data)}\n")
    out = sampler.subsample(str(src), 1000, 5, str(tmp_path))
    assert out.endswith("reads_5x.fastq")
    n = sum(1 for l in open(out) if l.startswith("@"))
    assert 5 <= n <= 6  # ~5000 bases at 1000 bp each, one overshoot allowed


def test_subsample_keeps_all_when_under_target(tmp_path):
    recs = [(f"r{i}", "ACGT" * 10) for i in range(3)]
    src = tmp_path / "reads.fasta"
    _write_fasta(src, recs)
    out = sampler.subsample(str(src), 100000, 30, str(tmp_path))
    assert sum(1 for l in open(out) if l.startswith(">")) == 3


def test_preprocess_renames_pairs(tmp_path, capsys):
    fq = tmp_path / "pairs.fastq"
    with open(fq, "w") as f:
        f.write("@read extra\nACGT\n+\nIIII\n@read extra\nTTTT\n+\nIIII\n")
    read_set = set()
    buf = io.StringIO()
    preprocess.parse_file(str(fq), read_set, buf)
    lines = buf.getvalue().splitlines()
    assert lines[0] == "@read1"
    assert lines[4] == "@read2"


def test_wrapper_end_to_end(tmp_path):
    """Wrapper (with --split: splitting is record-granular, so the single
    47.9kb layout record stays one chunk) polishes to the expected contig."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, "-m", "racon_tpu.tools.wrapper",
         "--split", "30000",
         "-m", "5", "-x", "-4", "-g", "-8",
         DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.sam.gz",
         DATA + "sample_layout.fasta.gz"],
        capture_output=True, text=True, timeout=600,
        cwd=str(tmp_path), env=env)
    assert out.returncode == 0, out.stderr
    names = [l for l in out.stdout.splitlines() if l.startswith(">")]
    assert len(names) == 1
    assert names[0].startswith(">utg000001l")
    total = sum(len(l) for l in out.stdout.splitlines()
                if not l.startswith(">"))
    assert 45000 < total < 50000
    # work directory cleaned up
    assert not any(d.startswith("racon_tpu_work_directory")
                   for d in os.listdir(tmp_path))


NATIVE_BIN = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "racon_tpu", "native", "build", "racon_tpu")


def test_native_sampler_split_and_subsample(tmp_path):
    """rampler-compatible subcommands of the native binary."""
    recs = [(f"r{i}", "ACGT" * 100) for i in range(10)]
    src = tmp_path / "seqs.fasta"
    _write_fasta(src, recs)
    out = subprocess.run(
        [NATIVE_BIN, "-o", str(tmp_path / "out"), "split", str(src), "1000"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    chunks = sorted((tmp_path / "out").glob("seqs_*.fasta"))
    assert len(chunks) == 4
    total = sum(sum(1 for l in open(c) if l.startswith(">")) for c in chunks)
    assert total == 10

    out = subprocess.run(
        [NATIVE_BIN, "-o", str(tmp_path / "out"), "subsample", str(src),
         "400", "2"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    sub = tmp_path / "out" / "seqs_2x.fasta"
    n = sum(1 for l in open(sub) if l.startswith(">"))
    assert 2 <= n <= 3  # ~800 bases at 400 bp each, one overshoot allowed


def test_wrapper_parallel_jobs_matches_sequential(tmp_path):
    """--jobs N (multi-host fan-out topology) must gather chunk outputs in
    order, byte-identical to the sequential run."""
    import random
    rng = random.Random(3)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(3):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(4):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t{seq}"
                         f"\t*\n")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    base = [sys.executable, "-m", "racon_tpu.tools.wrapper",
            "--split", "300", "-m", "5", "-x", "-4", "-g", "-8",
            str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta")]
    seq_run = subprocess.run(base, capture_output=True, text=True,
                             timeout=600, cwd=str(tmp_path), env=env)
    assert seq_run.returncode == 0, seq_run.stderr
    par_run = subprocess.run(base + ["--jobs", "2"], capture_output=True,
                             text=True, timeout=600, cwd=str(tmp_path),
                             env=env)
    assert par_run.returncode == 0, par_run.stderr
    assert par_run.stderr.count("host worker for chunk") >= 2
    assert par_run.stdout == seq_run.stdout
    assert seq_run.stdout.count(">") == 3


def test_wrapper_jobs_tpu_path_matches_sequential(tmp_path):
    """The multi-host (DCN) topology with the DEVICE path: two worker
    processes polish disjoint chunks through the accelerator pipeline and
    the ordered gather is byte-identical to one sequential host. Chunks
    are independent, so the only cross-host traffic is this gather —
    SURVEY.md §5.8."""
    import random
    rng = random.Random(7)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(3):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(4):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t{seq}"
                         f"\t*\n")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    base = [sys.executable, "-m", "racon_tpu.tools.wrapper",
            "--split", "300", "--tpu", "-m", "5", "-x", "-4", "-g", "-8",
            str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta")]
    seq_run = subprocess.run(base, capture_output=True, text=True,
                             timeout=600, cwd=str(tmp_path), env=env)
    assert seq_run.returncode == 0, seq_run.stderr
    par_run = subprocess.run(base + ["--jobs", "2"], capture_output=True,
                             text=True, timeout=600, cwd=str(tmp_path),
                             env=env)
    assert par_run.returncode == 0, par_run.stderr
    assert "host worker for chunk" in par_run.stderr  # parallel path taken
    assert par_run.stdout == seq_run.stdout
    assert seq_run.stdout.count(">") == 3


def test_wrapper_resume_checkpoints(tmp_path):
    """--resume persists per-chunk outputs and reuses them on rerun."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    ckpt = tmp_path / "ckpt"
    cmd = [sys.executable, "-m", "racon_tpu.tools.wrapper",
           "--resume", str(ckpt),
           "-m", "5", "-x", "-4", "-g", "-8",
           DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.sam.gz",
           DATA + "sample_layout.fasta.gz"]
    first = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                           cwd=str(tmp_path), env=env)
    assert first.returncode == 0, first.stderr
    assert (ckpt / "polished_0.fasta").is_file()

    second = subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                            cwd=str(tmp_path), env=env)
    assert second.returncode == 0, second.stderr
    assert "reusing checkpointed result" in second.stderr
    assert second.stdout == first.stdout
