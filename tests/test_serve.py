"""Serving layer (racon_tpu/serve): resident PolishSession hot-kernel
reuse, per-job artifact namespacing, scheduler admission/fairness/
demotion, the newline-JSON daemon protocol, preemption + journal resume
across a daemon restart, and the load-test/bench plumbing.

Conventions follow tests/test_faults.py: identical-read datasets (device
and host consensus both reproduce the target exactly, so outputs are
byte-comparable to the CpuPolisher oracle under any serving mix) and the
fast device env (XLA twin, v2 kernel, 8-window batches).
"""

import json
import os
import random
import socket
import subprocess
import sys
import threading
import time

import pytest

import racon_tpu
from racon_tpu.serve import (AdmissionError, JobCancelled, JobSpec,
                             PolishSession, Scheduler, ServeClient,
                             ServeDaemon, ServeError)
from racon_tpu.serve.scheduler import estimate_windows

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)

_FAST_ENV = {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
             "RACON_TPU_BATCH_WINDOWS": "8"}


def _write_dataset(tmp_path, n_targets=3, n_reads=4):
    rng = random.Random(11)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                         f"{seq}\t*\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta"))


def _oracle_fasta(paths):
    """Serial oracle output in the exact byte format the CLI (and the
    session's polished.fasta) emits."""
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    return "".join(f">{n}\n{d}\n" for n, d in p.polish(True))


def _device_env(monkeypatch):
    for k, v in _FAST_ENV.items():
        monkeypatch.setenv(k, v)


def _spec(paths, job_id="", **over):
    return JobSpec(paths[0], paths[1], paths[2], args=dict(_ARGS),
                   job_id=job_id, **over)


def _trace_kernel_builds(trace_path):
    with open(trace_path) as f:
        doc = json.load(f)
    return [e for e in doc["traceEvents"]
            if e.get("name") == "kernel.build"]


# ----------------------------------------------------------- unit: JobSpec

def test_jobspec_validation(tmp_path):
    paths = _write_dataset(tmp_path)
    _spec(paths).validate()   # clean spec passes
    with pytest.raises(ValueError, match="unknown polish arg"):
        JobSpec(*paths, args={"window": 100}).validate()
    with pytest.raises(ValueError, match="unknown backend"):
        JobSpec(*paths, backend="gpu").validate()
    with pytest.raises(ValueError, match="not found"):
        JobSpec(paths[0], paths[1], str(tmp_path / "nope.fa")).validate()
    with pytest.raises(ValueError, match="invalid job id"):
        JobSpec(*paths, job_id="../escape").validate()
    with pytest.raises(ValueError, match="unknown job field"):
        JobSpec.from_dict({"sequences": paths[0], "overlaps": paths[1],
                           "target": paths[2], "frobnicate": 1})
    rt = JobSpec.from_dict(_spec(paths, job_id="j1").as_dict())
    assert rt.as_dict() == _spec(paths, job_id="j1").as_dict()


def test_estimate_windows(tmp_path):
    paths = _write_dataset(tmp_path)          # 3 contigs x 200 bp
    assert estimate_windows(paths[2], 100) == 6
    assert estimate_windows(paths[2], 150) == 6   # ceil(200/150)=2 each
    assert estimate_windows(paths[2], 500) == 3
    assert estimate_windows(str(tmp_path / "missing.fa"), 100) is None
    fq = tmp_path / "reads.fastq"
    fq.write_text("@r1\nACGT\n+\n!!!!\n")
    assert estimate_windows(str(fq), 100) is None


# ------------------------------------------- session: hot kernels, isolation

def test_hot_kernels_across_jobs_and_sessions(tmp_path, monkeypatch):
    """The tentpole invariant: after the first job builds its kernels,
    every later job — same session or a second PolishSession in the same
    process — performs ZERO kernel builds, proven from the per-request
    obs traces (kernel.build span counts) and the per-job counters."""
    _device_env(monkeypatch)
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)

    s1 = PolishSession(str(tmp_path / "s1"), backend="tpu")
    r1 = s1.run_job(_spec(paths, job_id="a"))
    r2 = s1.run_job(_spec(paths, job_id="b"))
    s2 = PolishSession(str(tmp_path / "s2"), backend="tpu")
    r3 = s2.run_job(_spec(paths, job_id="c"))

    assert r1["cold"] and not r2["cold"]
    # no startup warm() here, so job 1 visibly pays the builds...
    assert r1["kernel_builds"] > 0
    assert len(_trace_kernel_builds(r1["trace"])) == r1["kernel_builds"]
    # ...and everyone after it pays none, across session instances too
    for r in (r2, r3):
        assert r["kernel_builds"] == 0, r
        assert _trace_kernel_builds(r["trace"]) == []
    for r in (r1, r2, r3):
        assert open(r["output"]).read() == want


def test_session_warm_precompiles_first_job(tmp_path, monkeypatch):
    """With the startup warm-up, even the COLD job builds nothing."""
    _device_env(monkeypatch)
    paths = _write_dataset(tmp_path)
    s = PolishSession(str(tmp_path / "state"), backend="tpu")
    assert s.warm([100], _ARGS["match"], _ARGS["mismatch"],
                  _ARGS["gap"]) > 0
    r = s.run_job(_spec(paths, job_id="warmed"))
    assert r["cold"] and r["kernel_builds"] == 0
    assert _trace_kernel_builds(r["trace"]) == []


def test_job_artifacts_namespaced_per_job(tmp_path):
    """Satellite regression: concurrent jobs must never clobber each
    other's artifacts — every report/journal/trace/output path is
    namespaced by job id (host backend: no kernels, fast)."""
    paths = _write_dataset(tmp_path)
    s = PolishSession(str(tmp_path / "state"), backend="cpu")
    ra = s.run_job(_spec(paths, job_id="jobA"))
    rb = s.run_job(_spec(paths, job_id="jobB"))
    assert os.path.dirname(ra["output"]) != os.path.dirname(rb["output"])
    for r, jid in ((ra, "jobA"), (rb, "jobB")):
        jd = s.job_dir(jid)
        for key in ("output", "report", "trace"):
            assert r[key].startswith(jd + os.sep), (key, r[key])
            assert os.path.isfile(r[key])
        assert os.path.getsize(os.path.join(jd, "journal.cpu.jsonl")) > 0
        with open(r["report"]) as f:
            assert json.load(f)["job_id"] == jid
    assert open(ra["output"]).read() == open(rb["output"]).read()


def test_session_rerun_resumes_from_journal(tmp_path):
    """Re-running a job id whose journal already holds served windows
    replays them instead of recomputing (the preemption-resume seam the
    daemon's restart recovery builds on)."""
    paths = _write_dataset(tmp_path)
    s = PolishSession(str(tmp_path / "state"), backend="cpu")
    first = s.run_job(_spec(paths, job_id="r"))
    assert first["journal_replayed"] == 0
    again = s.run_job(_spec(paths, job_id="r"))
    assert again["journal_replayed"] == 6          # all 6 windows replayed
    assert open(first["output"]).read() == open(again["output"]).read()


# ------------------------------------------------------ scheduler: fairness

class _FakeSession:
    """Duck-typed session for scheduler unit tests: records execution
    order, optionally blocks the device lane on an event."""

    backend = "tpu"

    def __init__(self, workdir, gate=None):
        self.workdir = str(workdir)
        self.gate = gate
        self.order = []
        os.makedirs(os.path.join(self.workdir, "jobs"), exist_ok=True)

    def job_dir(self, job_id):
        return os.path.join(self.workdir, "jobs", job_id)

    def stats(self):
        return {"jobs_run": len(self.order)}

    def run_job(self, spec, cancel_event=None):
        if self.gate is not None:
            self.gate.wait(timeout=30)
        if cancel_event is not None and cancel_event.is_set():
            raise JobCancelled(spec.job_id)
        self.order.append(spec.job_id)
        return {"job_id": spec.job_id, "backend": "tpu", "cold": False,
                "wall_s": 0.0, "records": 0, "polished_bp": 0,
                "kernel_builds": 0, "journal_replayed": 0,
                "output": "", "report": "", "trace": "", "summary": None}


def _wait_running(sched, job, timeout=10):
    deadline = time.monotonic() + timeout
    while job.state == "queued":
        assert time.monotonic() < deadline, job.as_status()
        time.sleep(0.01)


def test_scheduler_round_robin_and_admission(tmp_path):
    paths = _write_dataset(tmp_path)
    gate = threading.Event()
    ses = _FakeSession(tmp_path / "state", gate=gate)
    sched = Scheduler(ses, queue_depth=4, max_jobs=10, host_lane=False)
    sched.start()
    try:
        blocker = sched.submit(_spec(paths, job_id="blk", submitter="z"))
        _wait_running(sched, blocker)
        jobs = [sched.submit(_spec(paths, job_id=j, submitter=s))
                for j, s in (("a1", "a"), ("a2", "a"), ("a3", "a"),
                             ("b1", "b"))]
        # queue full (depth 4): the fifth queued submission is rejected
        with pytest.raises(AdmissionError, match="queue full"):
            sched.submit(_spec(paths, job_id="a4", submitter="a"))
        gate.set()
        for j in jobs:
            assert j.done.wait(30), j.as_status()
        # round-robin: submitter a cannot run its whole burst before b
        assert ses.order == ["blk", "a1", "b1", "a2", "a3"]
        # per-job persistence: every terminal job wrote its result.json
        for j in jobs:
            with open(os.path.join(ses.job_dir(j.id), "result.json")) as f:
                assert json.load(f)["state"] == "done"
    finally:
        gate.set()
        sched.shutdown(wait=True, timeout=10)


def test_scheduler_max_jobs_and_cancel_queued(tmp_path):
    paths = _write_dataset(tmp_path)
    gate = threading.Event()
    ses = _FakeSession(tmp_path / "state", gate=gate)
    sched = Scheduler(ses, queue_depth=10, max_jobs=2, host_lane=False)
    sched.start()
    try:
        running = sched.submit(_spec(paths, job_id="run", submitter="a"))
        _wait_running(sched, running)
        queued = sched.submit(_spec(paths, job_id="wait", submitter="a"))
        with pytest.raises(AdmissionError, match="at capacity"):
            sched.submit(_spec(paths, job_id="over", submitter="a"))
        st = sched.cancel("wait")
        assert st["state"] == "cancelled"
        assert queued.done.is_set()
        with open(os.path.join(ses.job_dir("wait"), "result.json")) as f:
            assert json.load(f)["state"] == "cancelled"
        gate.set()
        assert running.done.wait(30)
        assert ses.order == ["run"]               # cancelled job never ran
        with pytest.raises(KeyError):
            sched.get("nope")
    finally:
        gate.set()
        sched.shutdown(wait=True, timeout=10)


def test_scheduler_window_budget_demotes_to_host_lane(tmp_path):
    """A job over the window budget runs on the host lane (CLI
    subprocess) with byte-identical output, and records the demotion —
    the degradation lattice extended to whole jobs."""
    paths = _write_dataset(tmp_path)              # 6 windows at w=100
    want = _oracle_fasta(paths)
    ses = PolishSession(str(tmp_path / "state"), backend="tpu")
    sched = Scheduler(ses, queue_depth=4, max_jobs=8, window_budget=5)
    sched.start()
    try:
        job = sched.submit(_spec(paths, job_id="big"))
        assert job.lane == "host"
        assert "window budget" in job.demotions[0]["cause"]
        assert job.done.wait(120), job.as_status()
        assert job.state == "done", job.error
        assert job.result["backend"] == "cpu"
        assert open(job.result["output"]).read() == want
        assert ses.jobs_run == 0                  # device lane untouched
    finally:
        sched.shutdown(wait=True, timeout=10)


def test_scheduler_device_failure_demotes_to_host_lane(tmp_path):
    """A device-lane crash re-queues the job on the host lane instead of
    failing it (and instead of taking the daemon down)."""
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)

    class _WedgedSession(_FakeSession):
        def run_job(self, spec, cancel_event=None):
            raise RuntimeError("synthetic device wedge")

    ses = _WedgedSession(tmp_path / "state")
    sched = Scheduler(ses, queue_depth=4, max_jobs=8)
    sched.start()
    try:
        job = sched.submit(_spec(paths, job_id="dj"))
        assert job.done.wait(120), job.as_status()
        assert job.state == "done", job.error
        assert job.demotions[0]["from"] == "device"
        assert "synthetic device wedge" in job.demotions[0]["cause"]
        assert job.result["backend"] == "cpu"
        assert open(job.result["output"]).read() == want
    finally:
        sched.shutdown(wait=True, timeout=10)


def test_recover_tolerates_torn_spec_and_result(tmp_path):
    """Restart-path regression: a daemon SIGKILLed mid-write can leave
    spec.json or result.json torn in arbitrary ways.  recover() must
    (a) discard a torn result.json and re-queue the job from its good
    spec, (b) mark a job with an unparseable or non-object spec failed
    instead of crashing the restart, and (c) leave finished jobs with
    intact results alone."""
    paths = _write_dataset(tmp_path)
    ses = _FakeSession(tmp_path / "state")
    jobs_root = os.path.join(ses.workdir, "jobs")

    def _job_dir(job_id):
        d = os.path.join(jobs_root, job_id)
        os.makedirs(d, exist_ok=True)
        return d

    # jobA: good spec + result torn mid-write -> unfinished, re-queued
    a = _job_dir("jobA")
    with open(os.path.join(a, "spec.json"), "w") as f:
        json.dump(_spec(paths, job_id="jobA").as_dict(), f)
    with open(os.path.join(a, "result.json"), "w") as f:
        f.write('{"job_id": "jobA", "state": "do')
    # jobB: spec parses but is not an object -> failed, not crashed
    b = _job_dir("jobB")
    with open(os.path.join(b, "spec.json"), "w") as f:
        f.write("null\n")
    # jobC: spec truncated mid-write -> failed, not crashed
    c = _job_dir("jobC")
    with open(os.path.join(c, "spec.json"), "w") as f:
        f.write('{"seq')
    # jobD: intact spec + intact result -> finished, left alone
    d = _job_dir("jobD")
    with open(os.path.join(d, "spec.json"), "w") as f:
        json.dump(_spec(paths, job_id="jobD").as_dict(), f)
    with open(os.path.join(d, "result.json"), "w") as f:
        json.dump({"job_id": "jobD", "state": "done"}, f)

    sched = Scheduler(ses, queue_depth=8, max_jobs=8, host_lane=False)
    recovered = sched.recover()              # must not raise
    assert recovered == ["jobA"]
    assert not os.path.exists(os.path.join(a, "result.json"))
    assert sched.get("jobA").state == "queued"
    for jid in ("jobB", "jobC"):
        j = sched.get(jid)
        assert j.state == "failed", j.as_status()
        assert "recovery failed" in j.error
        with open(os.path.join(jobs_root, jid, "result.json")) as f:
            assert json.load(f)["state"] == "failed"
    with pytest.raises(KeyError):
        sched.get("jobD")                    # finished: not re-queued
    with open(os.path.join(d, "result.json")) as f:
        assert json.load(f)["state"] == "done"


# --------------------------------------------------------- daemon protocol

def test_server_e2e_concurrent_jobs_byte_identical(tmp_path, monkeypatch):
    """Acceptance: N concurrent jobs against one daemon produce output
    byte-identical to serial runs, with jobs 2..N performing zero kernel
    builds (asserted from the per-request traces), and every per-request
    trace passing the obs schema validator."""
    _device_env(monkeypatch)
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)
    daemon = ServeDaemon(str(tmp_path / "state"), backend="tpu", port=0,
                         warm=False)
    daemon.start()
    try:
        with ServeClient(daemon.port) as c1, ServeClient(daemon.port) as c2:
            ids = [c1.submit(*paths, args=dict(_ARGS), submitter="c1"),
                   c2.submit(*paths, args=dict(_ARGS), submitter="c2"),
                   c1.submit(*paths, args=dict(_ARGS), submitter="c1")]
            results = [c1.wait(j, timeout=240)["result"] for j in ids]
        for res in results:
            assert open(res["output"]).read() == want
        builds = [len(_trace_kernel_builds(r["trace"])) for r in results]
        colds = [r["cold"] for r in results]
        assert builds[colds.index(True)] > 0      # first job compiles...
        assert sorted(colds) == [False, False, True]
        for r, b in zip(results, builds):
            if not r["cold"]:
                assert b == 0 and r["kernel_builds"] == 0   # ...others never
        # per-request traces are schema-valid for the obs CLI
        v = subprocess.run([sys.executable, "-m", "racon_tpu.obs",
                            "--validate", results[-1]["trace"]],
                           capture_output=True, text=True, cwd=ROOT)
        assert v.returncode == 0, v.stdout + v.stderr
    finally:
        daemon.stop(wait=True)


def test_server_survives_client_disconnect_midjob(tmp_path):
    """A client that vanishes right after submitting loses only its
    socket: the job completes and stays queryable from new
    connections."""
    paths = _write_dataset(tmp_path)
    daemon = ServeDaemon(str(tmp_path / "state"), backend="cpu", port=0,
                         warm=False)
    daemon.start()
    try:
        c = ServeClient(daemon.port)
        jid = c.submit(*paths, args=dict(_ARGS), submitter="ghost")
        c._sock.close()                           # vanish mid-exchange
        with ServeClient(daemon.port) as c2:
            assert c2.ping()["ok"]
            res = c2.wait(jid, timeout=120)
            assert res["state"] == "done"
            assert os.path.isfile(res["result"]["output"])
    finally:
        daemon.stop(wait=True)


def test_server_protocol_errors_keep_connection_alive(tmp_path):
    paths = _write_dataset(tmp_path)
    daemon = ServeDaemon(str(tmp_path / "state"), backend="cpu", port=0,
                         warm=False)
    daemon.start()
    try:
        sock = socket.create_connection(("127.0.0.1", daemon.port),
                                        timeout=30)
        f = sock.makefile("rwb")

        def rpc(raw):
            f.write(raw + b"\n")
            f.flush()
            return json.loads(f.readline())

        assert rpc(b"this is not json")["ok"] is False
        assert "unknown op" in rpc(b'{"op": "frobnicate"}')["error"]
        bad = rpc(json.dumps({"op": "submit", "sequences": paths[0],
                              "overlaps": paths[1],
                              "target": str(tmp_path / "gone.fa")}).encode())
        assert bad["ok"] is False and "not found" in bad["error"]
        assert "unknown job id" in rpc(
            b'{"op": "status", "job_id": "nope"}')["error"]
        # the same connection still serves good requests after each error
        assert rpc(b'{"op": "ping"}')["ok"] is True
        sock.close()
        with ServeClient(daemon.port) as c:
            with pytest.raises(ServeError, match="unknown polish arg"):
                c.submit(*paths, args={"bogus": 1})
            assert c.stats()["jobs"] == {}
    finally:
        daemon.stop(wait=True)


def test_server_shutdown_op_and_admission_after_stop(tmp_path):
    paths = _write_dataset(tmp_path)
    daemon = ServeDaemon(str(tmp_path / "state"), backend="cpu", port=0,
                         warm=False)
    daemon.start()
    with ServeClient(daemon.port) as c:
        assert c.shutdown()["ok"]
    daemon.scheduler.shutdown(wait=True, timeout=10)
    with pytest.raises(AdmissionError, match="shutting down"):
        daemon.scheduler.submit(_spec(paths, job_id="late"))


# ------------------------------------------- preemption: restart + resume

def _spawn(state, env, *extra):
    from racon_tpu.serve.loadtest import spawn_daemon

    proc = spawn_daemon(str(state), "tpu", window_length=100,
                        extra_args=["--no-warm", *extra], env=env,
                        timeout=120)
    with open(os.path.join(str(state), "serve.json")) as f:
        return proc, json.load(f)["port"]


def test_daemon_killed_midjob_resumes_on_restart(tmp_path):
    """Acceptance: a daemon SIGKILLed mid-job (deterministic
    journal.append fault) is restarted on the same state dir; the job is
    recovered, its journal replays the served prefix, and the output is
    byte-identical to an uninterrupted run."""
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)
    state = tmp_path / "state"
    env = dict(os.environ, JAX_PLATFORMS="cpu", **_FAST_ENV)

    proc1, port1 = _spawn(state, dict(
        env, RACON_TPU_FAULT="journal.append:batch=3:kill=1"))
    try:
        with ServeClient(port1, timeout=30) as c:
            jid = c.submit(*paths, args=dict(_ARGS), job_id="prem")
        assert proc1.wait(timeout=180) == -9      # SIGKILL mid-job
    finally:
        if proc1.poll() is None:
            proc1.kill()
    jd = os.path.join(str(state), "jobs", "prem")
    assert os.path.isfile(os.path.join(jd, "spec.json"))
    assert not os.path.isfile(os.path.join(jd, "result.json"))
    assert os.path.getsize(os.path.join(jd, "journal.tpu.jsonl")) > 0

    proc2, port2 = _spawn(state, env)
    try:
        with ServeClient(port2, timeout=300) as c:
            res = c.wait(jid, timeout=240)
        assert res["state"] == "done"
        assert res["result"]["journal_replayed"] >= 1
        assert open(res["result"]["output"]).read() == want
        with ServeClient(port2, timeout=30) as c:
            c.shutdown()
        proc2.wait(timeout=60)
    finally:
        if proc2.poll() is None:
            proc2.kill()


# -------------------------------------------------- loadtest + bench seams

def test_loadtest_percentile_and_docs_block(tmp_path):
    from racon_tpu.serve import loadtest

    assert loadtest.percentile([1.0], 99) == 1.0
    vals = [float(i) for i in range(1, 101)]
    # linearly interpolated (same estimator as obs critpath): p50 of 1..100
    # sits halfway between the 50th and 51st order statistics.
    assert loadtest.percentile(vals, 50) == 50.5
    assert loadtest.percentile(vals, 95) == 95.05
    assert loadtest.percentile(vals, 99) == 99.01

    summary = {
        "jobs": 4, "clients": 2, "throughput_mbps": 0.5,
        "warm_mbps": 0.75, "warm_kernel_builds": 0,
        "latency_s": {"p50": 1.0, "p95": 2.0, "p99": 2.5,
                      "mean": 1.2, "max": 2.5},
        "service_s": {"cold_first_job": 3.0, "warm_mean": 1.0,
                      "cold_warm_delta": 2.0},
    }
    doc = tmp_path / "bench.md"
    doc.write_text("# Benchmarks\n\nprose stays.\n")
    loadtest.update_docs(str(doc), summary, "toy workload")
    loadtest.update_docs(str(doc), summary, "toy workload")   # idempotent
    text = doc.read_text()
    assert text.count(loadtest.DOCS_BEGIN) == 1
    assert text.count(loadtest.DOCS_END) == 1
    assert "prose stays." in text and "1.00 / 2.00 / 2.50 s" in text


def test_bench_serve_entry_normalizes_as_fixed_point():
    """The serve bench entry must round-trip normalize_entry unchanged
    and form its own bench-history series (profile serve-*)."""
    sys.path.insert(0, ROOT)
    try:
        from bench import normalize_entry
    finally:
        sys.path.remove(ROOT)
    from racon_tpu.obs import bench_track

    entry = {
        "metric": "serve: warm-path polished Mbp/sec (synthetic ONT 0.5 "
                  "Mbp 30x, PAF, w=500, 4 jobs/2 clients)",
        "value": 1.23, "unit": "Mbp/s", "vs_baseline": None,
        "cost_model": None, "pack_split": None, "serial_steps": None,
        "cells_banded": None, "band_hit_rate": None,
        "peak_rss_mb": None, "budget_mb": None,
        "serve": {"jobs": 4, "clients": 2,
                  "latency_s": {"p50": 1, "p95": 2, "p99": 3}},
        "fleet": {"samples": 3, "max_queued": 2, "last": None},
        "pool": {"min": 1, "max": 3, "timeline": [[0.0, 1], [1.5, 3]]},
        "ledger": {"jobs": 4, "stage_s": {"queue": 0.5},
                   "wall_s": 2.0, "unattributed_s": 0.1},
        "slo": {"counters": {"observed": 4, "bad": 0}},
        "mbp": 0.5, "input": "paf", "profile": "serve-ont",
    }
    assert normalize_entry(dict(entry)) == entry
    plain = dict(entry, profile="ont")
    assert (bench_track.series_key(entry)
            != bench_track.series_key(plain))
    # pre-telemetry serve entries get the explicit "not scraped" null
    legacy = {k: v for k, v in entry.items() if k != "fleet"}
    assert normalize_entry(legacy)["fleet"] is None
    # pre-elastic-pool entries get the explicit "no timeline" null
    legacy = {k: v for k, v in entry.items() if k != "pool"}
    assert normalize_entry(legacy)["pool"] is None
    # pre-ledger / pre-SLO entries get the explicit nulls too
    legacy = {k: v for k, v in entry.items() if k not in ("ledger", "slo")}
    normalized = normalize_entry(legacy)
    assert normalized["ledger"] is None and normalized["slo"] is None


def test_cli_serve_subcommand_dispatches():
    r = subprocess.run([sys.executable, "-m", "racon_tpu.cli", "serve",
                        "--help"], capture_output=True, text=True,
                       cwd=ROOT)
    assert r.returncode == 0
    assert "daemon" in r.stdout
    # the polish parser still owns everything that isn't the subcommand
    r2 = subprocess.run([sys.executable, "-m", "racon_tpu.cli",
                        "--version"], capture_output=True, text=True,
                        cwd=ROOT)
    assert r2.returncode == 0
