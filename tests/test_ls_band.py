"""Nightly end-to-end band for the production ls kernel tier.

The shipped consensus default (RACON_TPU_POA_KERNEL=ls, the lane-lockstep
Pallas kernel) must be exercised end to end on real data recurringly —
otherwise a regression in the ls driver plumbing would surface only via
the component differentials (the quick suite's interpret λ band pins the
v2 tier, tests/test_golden.py). Reference analogue: the upstream suite
runs its accelerator path over the same λ goldens as the CPU path
(/root/reference/test/racon_test.cpp:297-507).

The λ polish runs in a FRESH subprocess on a 1-device CPU backend: under
this suite's 8-virtual-device mesh the interpret-mode ls run exceeds
25 minutes, while single-device it takes ~200 s (docs/benchmarks.md —
measured 2026-07-30: edit distance 1282, 92/96 windows device-served).
Gated behind RACON_TPU_FULL_GOLDEN=1, so it rides the nightly
full-golden CI job rather than the per-push quick job.
"""

import json
import os
import subprocess
import sys

import pytest

from tests.conftest import DATA, requires_data

FULL = os.environ.get("RACON_TPU_FULL_GOLDEN") == "1"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = requires_data

_CHILD = """
import json, os, sys
sys.path.insert(0, %(repo)r)
from __graft_entry__ import _force_cpu
_force_cpu(1)                      # 1-device mesh: escapes the suite's 8
os.environ["RACON_TPU_POA_KERNEL"] = "ls"
os.environ["RACON_TPU_PALLAS"] = "1"   # interpret-mode pallas on CPU

import gzip
from racon_tpu import native
from racon_tpu.pipeline import Pipeline
from racon_tpu.ops.align_driver import run_alignment_phase
from racon_tpu.ops.poa_driver import run_consensus_phase
from racon_tpu.tools import golden_scenarios as gs

D = %(data)r
reads, ovl, tgt, extra = gs.POLISH["paf"]
args = dict(gs.ARGS, **extra)
pipe = Pipeline(D + reads, D + ovl, D + tgt, **args)
pipe.prepare()
run_alignment_phase(pipe)
pipe.build_windows()
stats = run_consensus_phase(pipe, match=args["match"],
                            mismatch=args["mismatch"], gap=args["gap"],
                            trim=True)
res = pipe.stitch(True)
assert len(res) == 1, len(res)

ref = b"".join(l.strip().encode()
               for l in gzip.open(D + "sample_reference.fasta.gz", "rt")
               if not l.startswith(">"))
pol = res[0][1].encode()
rc = pol.translate(bytes.maketrans(b"ACGT", b"TGCA"))[::-1]
counters = {k: v for k, v in stats.items() if isinstance(v, int)}
print("RESULT " + json.dumps({"ed": native.edit_distance(rc, ref),
                              "stats": counters}))
"""


@pytest.mark.skipif(not FULL, reason="~200 s single-device interpret run; "
                    "set RACON_TPU_FULL_GOLDEN=1 (nightly band)")
def test_ls_tier_lambda_end_to_end_band():
    child = _CHILD % {"repo": REPO, "data": DATA}
    r = subprocess.run([sys.executable, "-c", child], capture_output=True,
                       text=True, timeout=1800, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, r.stdout[-2000:]
    out = json.loads(line[-1][len("RESULT "):])
    ed, stats = out["ed"], out["stats"]

    # same band the quick suite pins for the v2 tier; the measured ls
    # value is 1282 (host pin 1283)
    assert abs(ed - 1283) <= 15, (ed, stats)
    # the ls tier must actually SERVE: 92/96 windows measured, with 4
    # repeat-dense windows through the per-window host fallback — a
    # silent degrade to host (stats device ~0) must fail here
    assert stats["device"] >= 88, stats
    assert stats["device"] + stats["host_fallback"] + stats["backbone"] \
        >= 96, stats
