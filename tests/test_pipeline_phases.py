"""Cross-phase align/POA pipelining (RACON_TPU_PIPELINE_PHASES): target
chunking, bounded handoff queue, ordered install (byte-identical output),
merged phase reports, span-overlap evidence in traces, and the
pack/kernel wall split surfaced by the shared executor."""

import json
import os
import subprocess
import sys

import pytest

import racon_tpu
from racon_tpu.polisher import TpuPolisher, _split_fasta
from racon_tpu.obs import costmodel
from racon_tpu.resilience.report import PhaseReport
from racon_tpu.tools import simulate

from test_faults import _ARGS, _assert_report_sums, _oracle, _tpu_run, \
    _write_dataset

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------- _split_fasta

def test_split_fasta_balanced_roundtrip(tmp_path):
    src = tmp_path / "t.fasta"
    recs = [(f"c{i}", "ACGT" * (10 * (i + 1))) for i in range(5)]
    src.write_text("".join(f">{n}\n{s}\n" for n, s in recs))
    chunks = _split_fasta(str(src), 3, str(tmp_path))
    assert chunks is not None and len(chunks) == 3
    # verbatim record text, original order, nothing lost or duplicated
    joined = "".join(open(c).read() for c in chunks)
    assert joined == src.read_text()
    for c in chunks:
        assert open(c).read().startswith(">")


def test_split_fasta_chunk_count_capped_by_records(tmp_path):
    src = tmp_path / "t.fasta"
    src.write_text(">a\nACGT\n>b\nGGCC\n")
    chunks = _split_fasta(str(src), 6, str(tmp_path))
    assert chunks is not None and len(chunks) == 2


def test_split_fasta_rejects_unsplittable(tmp_path):
    one = tmp_path / "one.fasta"
    one.write_text(">only\nACGT\n")
    assert _split_fasta(str(one), 3, str(tmp_path)) is None
    junk = tmp_path / "junk.fasta"
    junk.write_text("this is not fasta\n>late\nACGT\n")
    assert _split_fasta(str(junk), 3, str(tmp_path)) is None
    assert _split_fasta(str(tmp_path / "missing.fasta"), 3,
                        str(tmp_path)) is None


def test_split_fasta_gzip(tmp_path):
    import gzip

    src = tmp_path / "t.fasta.gz"
    with gzip.open(src, "wt") as f:
        f.write(">a\nAAAA\n>b\nCCCC\n>c\nGGGG\n")
    chunks = _split_fasta(str(src), 2, str(tmp_path))
    assert chunks is not None and len(chunks) == 2
    assert "".join(open(c).read() for c in chunks) == \
        ">a\nAAAA\n>b\nCCCC\n>c\nGGGG\n"


# ------------------------------------------- pipelined vs sequential

def test_pipelined_byte_identical_to_sequential(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path, overlaps="paf", n_reads=2)
    oracle = _oracle(paths)
    seq_res, seq_p = _tpu_run(paths, monkeypatch, {})
    pipe_res, pipe_p = _tpu_run(paths, monkeypatch,
                                {"RACON_TPU_PIPELINE_PHASES": "1"})
    assert pipe_p._pipelined, "3-contig FASTA target must pipeline"
    assert pipe_res == seq_res == oracle
    # merged per-chunk reports keep the served-sum invariant and the
    # full-run totals
    d = _assert_report_sums(pipe_p)
    ds = _assert_report_sums(seq_p)
    assert d["phases"]["consensus"]["total"] == \
        ds["phases"]["consensus"]["total"] == 6
    assert d["phases"]["alignment"]["total"] == \
        ds["phases"]["alignment"]["total"] == 6


def test_journal_forces_sequential(tmp_path, monkeypatch, capsys):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    monkeypatch.setenv("RACON_TPU_PIPELINE_PHASES", "1")
    for k, v in {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
                 "RACON_TPU_BATCH_WINDOWS": "8"}.items():
        monkeypatch.setenv(k, v)
    p = racon_tpu.create_polisher(*paths, backend="tpu",
                                  journal_path=str(tmp_path / "j.wal"),
                                  **_ARGS)
    assert not p._pipelined       # journal needs run-global window indices
    p.initialize()
    assert p.polish(True) == oracle


def test_non_fasta_extension_forces_sequential(tmp_path, monkeypatch):
    monkeypatch.setenv("RACON_TPU_PIPELINE_PHASES", "1")
    p = TpuPolisher("r.fa", "o.paf", str(tmp_path / "target.txt"), **_ARGS)
    assert p._pipelined
    assert p._split_target() is None


def test_single_contig_forces_sequential(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path, n_targets=1)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_PIPELINE_PHASES": "1"})
    assert not p._pipelined       # fewer than two contigs -> sequential
    assert res == oracle


def test_handoff_depth_floor(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_PIPELINE_PHASES": "1",
        "RACON_TPU_HANDOFF_DEPTH": "0",    # clamped to 1
    })
    assert p._pipelined
    assert res == oracle


# --------------------------------------------------- report merging

def test_phase_report_merge():
    a = PhaseReport("consensus", ("xla", "host"))
    a.total = 4
    a.record_served("xla", 3)
    a.record_served("host", 1)
    a.retries = 1
    a.add_wall("xla", 0.5)
    a.extra["pack_wall_s"] = 0.25
    a.extra["kernel_wall_s"] = 1.0
    b = PhaseReport("consensus", ("xla", "host"))
    b.total = 2
    b.record_served("xla", 2)
    b.bisections = 2
    b.record_quarantine(7, RuntimeError("poison"))
    b.add_wall("xla", 0.25)
    b.extra["pack_wall_s"] = 0.5
    b.extra["note"] = "x"
    a.merge(b)
    assert a.total == 6
    assert a.served == {"xla": 5, "host": 1}
    assert a.retries == 1 and a.bisections == 2
    assert a.quarantined == [7]
    assert a.wall_s["xla"] == 0.75
    assert a.extra["pack_wall_s"] == 0.75       # numeric extras sum
    assert a.extra["kernel_wall_s"] == 1.0
    assert a.extra["note"] == "x"
    assert sum(a.served.values()) == a.total    # invariant survives merge


# ------------------------------------------------ overlap computation

def _doc(*events):
    return {"traceEvents": [
        {"ph": "X", "name": n, "ts": ts, "dur": dur, "pid": 1, "tid": 1}
        for n, ts, dur in events]}


def test_overlap_us_two_pointer():
    doc = _doc(("phase.align", 0, 100), ("phase.align", 300, 100),
               ("phase.poa", 50, 100), ("phase.poa", 500, 50))
    assert costmodel.overlap_us(doc, "phase.align", "phase.poa") == 50
    assert costmodel.overlap_us(doc, "phase.align", "phase.stitch") == 0
    assert costmodel.union_intervals([(0, 10), (5, 20), (30, 40)]) == \
        [(0, 20), (30, 40)]
    assert costmodel.phase_overlaps_us(doc) == {"align+poa": 50.0}


def test_sequential_trace_has_no_phase_overlap():
    doc = _doc(("phase.align", 0, 100), ("phase.poa", 100, 100),
               ("phase.stitch", 200, 10))
    assert costmodel.phase_overlaps_us(doc) == {}
    v = costmodel.validate_trace(doc, costmodel.PROFILES["cpu-host"])
    assert "phase_overlap_s" not in v


def test_validate_trace_stamps_phase_overlap():
    doc = _doc(("phase.align", 0, 1_000_000), ("phase.poa", 500_000,
                                               1_000_000))
    v = costmodel.validate_trace(doc, costmodel.PROFILES["cpu-host"])
    assert v["phase_overlap_s"] == {"align+poa": 0.5}


def test_obs_cli_overlap_exit_codes(tmp_path):
    tr = tmp_path / "trace.json"
    tr.write_text(json.dumps(_doc(("align.cohort", 0, 100),
                                  ("poa.bucket", 50, 100))))
    flat = tmp_path / "flat.json"
    flat.write_text(json.dumps(_doc(("align.cohort", 0, 100),
                                    ("poa.bucket", 200, 100))))

    def run(trace, arg):
        return subprocess.run(
            [sys.executable, "-m", "racon_tpu.obs", str(trace),
             "--overlap", arg, "--json"],
            cwd=ROOT, capture_output=True, text=True)

    ok = run(tr, "align.cohort:poa.bucket")
    assert ok.returncode == 0, ok.stderr
    d = json.loads(ok.stdout)
    assert d["overlap_us"] == 50 and d["spans_a"] == d["spans_b"] == 1
    assert run(flat, "align.cohort:poa.bucket").returncode == 3
    assert run(tr, "malformed-no-colon").returncode == 2


# ------------------------------------------------ bench pack/kernel split

def test_bench_pack_split_and_backfill():
    sys.path.insert(0, ROOT)
    import bench

    # summary() shape: phase-keyed, extras riding along per phase
    rep = {
        "alignment": {"served": {}, "extra": {"pack_wall_s": 0.1,
                                              "kernel_wall_s": 0.9}},
        "consensus": {"served": {}, "extra": {"kernel_wall_s": 2.0}},
        "stitch": {"served": {}},
        "unknown_knobs": ["RACON_TPU_TYPO"],   # non-phase key tolerated
    }
    split = bench.pack_split(rep)
    assert split == {
        "alignment": {"pack_wall_s": 0.1, "kernel_wall_s": 0.9},
        "consensus": {"pack_wall_s": None, "kernel_wall_s": 2.0},
    }
    assert bench.pack_split(None) == {}        # pre-executor entries
    assert bench.pack_split({"x": {"served": {}}}) == {}
    # normalize_entry backfills older log entries (report embedded or not)
    e = bench.normalize_entry({"mbp": 1.0, "report": rep})
    assert e["pack_split"]["alignment"]["kernel_wall_s"] == 0.9
    e2 = bench.normalize_entry({"mbp": 1.0})
    assert e2["pack_split"] is None
    # entries that already carry the field are left alone
    e3 = bench.normalize_entry({"mbp": 1.0, "pack_split": {"k": 1}})
    assert e3["pack_split"] == {"k": 1}


# ------------------------------------------------ simulate --contigs

def test_simulate_multi_contig(tmp_path):
    paths = simulate.generate(str(tmp_path), mbp=0.006, coverage=3,
                              mean_read=900, contigs=3)
    draft = open(paths["draft"]).read()
    names = [ln[1:] for ln in draft.splitlines() if ln.startswith(">")]
    assert names == ["contig0", "contig1", "contig2"]
    seqs = [ln for ln in draft.splitlines() if not ln.startswith(">")]
    assert sum(len(s) for s in seqs) == 6000
    sq = [ln for ln in open(paths["overlaps_sam"]).read().splitlines()
          if ln.startswith("@SQ")]
    assert len(sq) == 3
    for row in open(paths["overlaps"]).read().splitlines():
        cols = row.split("\t")
        tname, t_len, t_start, t_end = (cols[5], int(cols[6]),
                                        int(cols[7]), int(cols[8]))
        assert tname in names
        assert 0 <= t_start < t_end <= t_len == 2000   # local coordinates


def test_simulate_single_contig_unchanged(tmp_path):
    paths = simulate.generate(str(tmp_path / "a"), mbp=0.002, coverage=3,
                              mean_read=500)
    draft = open(paths["draft"]).read()
    assert draft.startswith(">contig\n")
    explicit = simulate.generate(str(tmp_path / "b"), mbp=0.002,
                                 coverage=3, mean_read=500, contigs=1)
    assert open(explicit["draft"]).read() == draft
    assert open(explicit["reads"]).read() == \
        open(paths["reads"]).read()


# --------------------------------- e2e: traced pipelined polish (CLI)

@pytest.mark.slow
def test_traced_pipelined_polish_overlap_and_pack_split(tmp_path):
    """The acceptance run: pipelined and sequential CLI polishes are
    byte-identical; the pipelined trace shows align/POA span overlap
    (asserted through `python -m racon_tpu.obs --overlap`, the same
    check CI runs); the report's phase-1 split shows pack < kernel."""
    data = tmp_path / "data"
    simulate.generate(str(data), mbp=0.004, coverage=6, mean_read=800,
                      contigs=3)
    paths = [str(data / "reads.fastq"), str(data / "overlaps.paf"),
             str(data / "draft.fasta")]

    def cli(tag, env=None):
        trace = str(tmp_path / f"{tag}.trace.json")
        report = str(tmp_path / f"{tag}.report.json")
        # -w 100: small windows keep the per-geometry XLA compiles (the
        # dominant cost on the CPU backend) to seconds instead of minutes
        cmd = [sys.executable, "-m", "racon_tpu.cli", "--tpu",
               "-w", "100", "--trace", trace, "--report", report, *paths]
        full_env = dict(os.environ, JAX_PLATFORMS="cpu",
                        RACON_TPU_PALLAS="0", RACON_TPU_POA_KERNEL="v2",
                        RACON_TPU_BATCH_WINDOWS="8",
                        RACON_TPU_DEVICE_ALIGNER="xla")
        full_env.pop("RACON_TPU_FAULT", None)
        full_env.pop("XLA_FLAGS", None)
        full_env.update(env or {})
        r = subprocess.run(cmd, cwd=ROOT, env=full_env,
                           capture_output=True, timeout=540)
        assert r.returncode == 0, r.stderr.decode()[-3000:]
        return r.stdout, trace, report

    seq_out, seq_trace, _ = cli("seq")
    pipe_out, pipe_trace, pipe_report = cli(
        "pipe", env={"RACON_TPU_PIPELINE_PHASES": "1"})
    assert pipe_out == seq_out and pipe_out.startswith(b">")

    def overlap(trace, pair):
        return subprocess.run(
            [sys.executable, "-m", "racon_tpu.obs", trace,
             "--overlap", pair], cwd=ROOT, capture_output=True)

    # phase spans AND the executors' inner spans ran concurrently
    assert overlap(pipe_trace, "phase.align:phase.poa").returncode == 0
    assert overlap(pipe_trace, "align.cohort:poa.bucket").returncode == 0
    # the sequential trace shows none — exit 3 is the CI failure signal
    assert overlap(seq_trace, "phase.align:phase.poa").returncode == 3
    # the validate join still works on an overlapped trace and stamps
    # the concurrency it found
    doc = json.load(open(pipe_trace))
    v = costmodel.validate_trace(doc, costmodel.PROFILES["cpu-host"])
    assert v["phase_overlap_s"]["align+poa"] > 0
    # phase-1 split: packing is cheaper than the kernels it feeds
    rep = json.load(open(pipe_report))
    al = rep["phases"]["alignment"]["extra"]
    assert 0 < al["pack_wall_s"] < al["kernel_wall_s"]
