"""Device trim rule for windows deeper than DEPTH_CAP: admitted count.

The reference's accelerator path computes the low-coverage end-trim
threshold from seqs_added_per_window_ — the count of sequences actually
incorporated into the GPU group, excluding drops for exceeded size/depth
(src/cuda/cudabatch.cpp:139-163,233) — while the CPU path uses the
window's full sequence count (src/window.cpp:125-146). The device driver
here admits at most DEPTH_CAP=200 layers per window, so for deeper
windows the two counts diverge; this test pins the reference-GPU rule on
the device path. The admitted-count rule is also the only self-consistent
one: device coverage is computed from the admitted layers, so a
full-window threshold would be unattainable above 2*DEPTH_CAP layers
(trim would silently no-op via the chimeric guard) and over-trim between
DEPTH_CAP and 2*DEPTH_CAP.

Scenario (210 layers > DEPTH_CAP): a 100-base backbone where
- 102 layers span positions 0..79  (head + core),
- 108 layers span positions 15..79 (core only),
- positions 80..99 are backbone-only (tail).

Host (full-count) threshold: (211-1)/2 = 105. Host head coverage is
102+1 = 103 < 105 -> host trims the head (and the tail, coverage 1).
Device admits the first 200 layers in layer order (all 102 head + 98
core), threshold (201-1)/2 = 100 <= 103 -> device keeps the head and
trims only the tail. Perfect reads make device and host consensus
base-identical, so the only difference the threshold rule can produce is
exactly the trim extent.
"""

import random

import pytest

import racon_tpu
from racon_tpu.ops.poa_driver import DEPTH_CAP

N_HEAD = 102
N_CORE = 108
HEAD_END = 15   # core region starts here
CORE_END = 80   # head+core reads span [0, CORE_END)


def _write_dataset(tmp_path, truth):
    with open(tmp_path / "target.fasta", "w") as f:
        f.write(f">tgt\n{truth}\n")
    head_core = truth[:CORE_END]
    core = truth[HEAD_END:CORE_END]
    with open(tmp_path / "reads.fasta", "w") as f:
        for i in range(N_HEAD):
            f.write(f">h{i}\n{head_core}\n")
        for i in range(N_CORE):
            f.write(f">c{i}\n{core}\n")
        # trim only applies to TGS windows, chosen when the MEAN read
        # length exceeds 1000 (rt_pipeline.cpp:167-171; reference
        # src/polisher.cpp:277-278) — one long overlap-less read flips
        # the classification without touching the window
        f.write(">dummy_long\n" + "A" * 300000 + "\n")
    with open(tmp_path / "ovl.sam", "w") as f:
        f.write("@HD\tVN:1.6\n@SQ\tSN:tgt\tLN:100\n")
        for i in range(N_HEAD):
            f.write(f"h{i}\t0\ttgt\t1\t60\t{len(head_core)}M\t*\t0\t0\t"
                    f"{head_core}\t*\n")
        for i in range(N_CORE):
            f.write(f"c{i}\t0\ttgt\t{HEAD_END + 1}\t60\t{len(core)}M\t*\t"
                    f"0\t0\t{core}\t*\n")


def _polish(tmp_path, backend, monkeypatch):
    if backend == "tpu":
        monkeypatch.setenv("RACON_TPU_PALLAS", "0")  # XLA twin: fast on CPU
    p = racon_tpu.create_polisher(
        str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
        str(tmp_path / "target.fasta"), backend=backend,
        window_length=100, quality_threshold=10.0, error_threshold=0.9,
        match=5, mismatch=-4, gap=-8, num_threads=1)
    p.initialize()
    return p.polish(True)


def test_depth_over_cap_trim_threshold_uses_admitted_count(tmp_path,
                                                           monkeypatch):
    rng = random.Random(3)
    truth = "".join(rng.choice("ACGT") for _ in range(100))
    _write_dataset(tmp_path, truth)
    assert N_HEAD + N_CORE > DEPTH_CAP  # the scenario's whole point

    host = _polish(tmp_path, "cpu", monkeypatch)
    dev = _polish(tmp_path, "tpu", monkeypatch)

    assert len(host) == 1 and len(dev) == 1
    # host: full-count threshold 105 > head cov 103 -> head trimmed
    assert host[0][1] == truth[HEAD_END:CORE_END]
    # device: admitted-count threshold 100 <= head cov 103 -> head kept,
    # tail (cov 1) trimmed — the reference-GPU seqs_added rule
    assert dev[0][1] == truth[:CORE_END]
