"""Elastic fleet (racon_tpu/fleet): tenant queues, the heartbeat clamp,
atomic window-budget admission under concurrent submits, the FleetPlane
dispatch core (affinity, cross-job stealing, priority, speculation
seams), the four control-plane fault points (pool.scale_up,
pool.scale_down, pool.steal, lease.reclaim), and the chaos acceptance
paths: a worker SIGKILLed mid-chunk recovers in-process, and a daemon
SIGKILLed mid-resize re-queues its unfinished jobs on restart with
journals turning the re-runs into byte-identical resumes.

Conventions follow tests/test_serve.py: identical-read datasets (every
serving mix reproduces the target exactly, so outputs are
byte-comparable to the CpuPolisher oracle) and cpu-backend fleets (the
workers run the host-oracle path — the fleet's scaling axis is
processes, not kernels).
"""

import glob
import json
import os
import random
import subprocess
import sys
import threading
import time

import pytest

import racon_tpu
from racon_tpu.distrib.common import HEARTBEAT_FLOOR, distrib_heartbeat
from racon_tpu.fleet.pool import ElasticPool
from racon_tpu.fleet.queues import TenantQueues
from racon_tpu.serve import (AdmissionError, JobSpec, Scheduler,
                             ServeClient, ServeDaemon)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)

_FAST_ENV = {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
             "RACON_TPU_BATCH_WINDOWS": "8"}


def _write_dataset(tmp_path, n_targets=3, n_reads=4, seed=11):
    rng = random.Random(seed)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                         f"{seq}\t*\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta"))


def _oracle_fasta(paths):
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    return "".join(f">{n}\n{d}\n" for n, d in p.polish(True))


# ------------------------------------------------------ unit: TenantQueues

def test_tenant_queues_rotation_priority_remove():
    q = TenantQueues()
    q.push("a", "a1")
    q.push("a", "a2")
    q.push("b", "b1")
    # round-robin among tenants at the same priority
    assert q.pop() == "a1"
    assert q.pop() == "b1"
    assert q.pop() == "a2"
    assert q.pop() is None
    # a higher priority outranks FIFO order and tenant rotation
    q.push("a", "lo", priority=0)
    q.push("b", "hi", priority=5)
    q.push("a", "hi2", priority=5)
    assert q.pop() == "hi"
    assert q.pop() == "hi2"
    assert q.pop() == "lo"
    # remove() unlinks a queued item (cancellation path)
    q.push("a", "x")
    q.push("a", "y")
    assert q.remove("a", "x") is True
    assert q.remove("a", "x") is False
    assert len(q) == 1 and q.queued_for("a") == 1
    assert q.per_tenant() == {"a": 1, "b": 0}
    assert q.pop() == "y"


# --------------------------------------- satellite: heartbeat floor clamp

def test_heartbeat_clamped_to_floor(monkeypatch):
    """Regression: RACON_TPU_DISTRIB_LEASE_TTL=0.01 must not busy-spin
    the renewal thread — TTL/3 clamps to the floor, and so does an
    explicit tiny RACON_TPU_DISTRIB_HEARTBEAT."""
    monkeypatch.delenv("RACON_TPU_DISTRIB_HEARTBEAT", raising=False)
    assert distrib_heartbeat(0.01) == HEARTBEAT_FLOOR
    assert distrib_heartbeat(3.0) == pytest.approx(1.0)
    monkeypatch.setenv("RACON_TPU_DISTRIB_HEARTBEAT", "0.001")
    assert distrib_heartbeat(0.01) == HEARTBEAT_FLOOR
    monkeypatch.setenv("RACON_TPU_DISTRIB_HEARTBEAT", "0.5")
    assert distrib_heartbeat(0.01) == pytest.approx(0.5)


# ------------------------------- satellite: atomic window-budget admission

class _FakeSession:
    backend = "tpu"

    def __init__(self, workdir):
        self.workdir = str(workdir)
        os.makedirs(os.path.join(self.workdir, "jobs"), exist_ok=True)

    def job_dir(self, job_id):
        return os.path.join(self.workdir, "jobs", job_id)

    def stats(self):
        return {}


def test_concurrent_submits_never_oversubscribe_budget(tmp_path):
    """Many threads race submit() against a device-lane window budget:
    the check-and-reserve under the scheduler lock must admit exactly
    budget//est jobs to the device lane and shed the rest — never two
    winners squeezed into the same headroom."""
    paths = _write_dataset(tmp_path)           # 3 contigs x 200bp: est=6
    sched = Scheduler(_FakeSession(tmp_path / "state"), queue_depth=100,
                      max_jobs=100, window_budget=12, tenant_quota=0)
    errors = []
    barrier = threading.Barrier(10)

    def one(i):
        try:
            barrier.wait()
            sched.submit(JobSpec(*paths, args=dict(_ARGS),
                                 submitter=f"t{i}"))
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # est=6, budget=12: exactly 2 reserve the device lane, 8 shed
    assert sum(sched._reserved.values()) == 12
    assert len(sched._queues["device"]) == 2
    assert len(sched._queues["host"]) == 8
    assert sched.admission["shed"] == 8
    shed_jobs = [j for j in sched._jobs.values() if j.demotions]
    assert len(shed_jobs) == 8
    assert all("shed" in j.demotions[0]["cause"] for j in shed_jobs)


def test_tenant_quota_rejects_flooding_submitter(tmp_path):
    paths = _write_dataset(tmp_path)
    sched = Scheduler(_FakeSession(tmp_path / "state"), queue_depth=100,
                      max_jobs=100, window_budget=0, tenant_quota=1)
    sched.submit(JobSpec(*paths, args=dict(_ARGS), submitter="flood"))
    with pytest.raises(AdmissionError, match="tenant quota"):
        sched.submit(JobSpec(*paths, args=dict(_ARGS), submitter="flood"))
    sched.submit(JobSpec(*paths, args=dict(_ARGS), submitter="other"))
    assert sched.admission["rejected_quota"] == 1


# ------------------------------------- unit: FleetPlane dispatch (no pool)

def _plane(tmp_path, **over):
    """An unstarted plane: no sockets, no processes — _fetch/_result are
    driven directly, exactly what a worker's RPCs would do."""
    from racon_tpu.fleet.plane import FleetPlane
    kw = dict(workdir=str(tmp_path / "plane"), min_workers=0,
              max_workers=2, backend="cpu")
    kw.update(over)
    return FleetPlane(**kw)


def _submit(plane, tmp_path, job_id, tenant="acme", priority=0,
            on_done=None, n_targets=2):
    d = tmp_path / f"data-{job_id}"
    d.mkdir(exist_ok=True)
    paths = _write_dataset(d, n_targets=n_targets)
    wd = str(tmp_path / f"wd-{job_id}")
    return plane.submit_job(job_id, paths[0], paths[1], paths[2],
                            dict(_ARGS), False, "cpu", wd, tenant=tenant,
                            priority=priority, on_done=on_done)


def _deliver(plane, resp, worker=0, body=">x\nACGT\n"):
    """Deliver a fake result for an assignment response."""
    ch = resp["chunk"]
    with open(ch["output"], "w") as f:
        f.write(body)
    return plane._result({"worker": worker, "chunk": ch["index"],
                          "attempt": ch["attempt"], "output": ch["output"],
                          "stats": {}})


def test_plane_affinity_then_steal(tmp_path):
    plane = _plane(tmp_path)
    _submit(plane, tmp_path, "A", tenant="acme")
    _submit(plane, tmp_path, "B", tenant="bcorp")
    # worker 0's first two fetches serve job A (affinity: chunks of the
    # job it last served come first)
    r1 = plane._fetch(0)
    r2 = plane._fetch(0)
    assert {r1["chunk"]["index"], r2["chunk"]["index"]} == {0, 1}
    assert plane.counters.get("steals", 0) == 0
    # job A is live but starved; job B has eligible chunks: the steal
    r3 = plane._fetch(0)
    assert r3["chunk"]["index"] in (2, 3)
    assert plane.counters["steals"] == 1


def test_plane_steal_gate_and_fault(tmp_path, monkeypatch):
    plane = _plane(tmp_path)
    _submit(plane, tmp_path, "A", tenant="acme")
    _submit(plane, tmp_path, "B", tenant="bcorp")
    plane._fetch(0)
    plane._fetch(0)               # job A fully leased to worker 0
    # RACON_TPU_FLEET_STEAL=0 pins the worker to its job
    monkeypatch.setenv("RACON_TPU_FLEET_STEAL", "0")
    assert plane._fetch(0).get("wait") is True
    monkeypatch.delenv("RACON_TPU_FLEET_STEAL")
    # an armed pool.steal fault is absorbed: the fetch waits, the chunk
    # stays eligible, and the fault is counted
    monkeypatch.setenv("RACON_TPU_FAULT", "pool.steal")
    assert plane._fetch(0).get("wait") is True
    assert plane.counters["steal_faults"] == 1
    monkeypatch.delenv("RACON_TPU_FAULT")
    assert "chunk" in plane._fetch(0)      # fault gone: the steal lands
    assert plane.counters["steals"] == 1


def test_plane_priority_orders_cross_tenant_picks(tmp_path):
    plane = _plane(tmp_path)
    _submit(plane, tmp_path, "lo", tenant="acme", priority=0)
    hi = _submit(plane, tmp_path, "hi", tenant="acme", priority=5)
    r = plane._fetch(0)
    assert plane.chunks[r["chunk"]["index"]].job is hi


def test_plane_gather_is_ordered_and_duplicates_counted(tmp_path):
    done = []
    plane = _plane(tmp_path)
    job = _submit(plane, tmp_path, "G", on_done=lambda *a: done.append(a))
    r1 = plane._fetch(0)
    r2 = plane._fetch(0)
    by_index = {r["chunk"]["index"]: r for r in (r1, r2)}
    # deliver out of order; the gather must still be position-ordered
    assert _deliver(plane, by_index[1], body=">c1\nTTTT\n")["accepted"]
    assert _deliver(plane, by_index[0], body=">c0\nAAAA\n")["accepted"]
    assert job.done.wait(10) and job.state == "done"
    assert done and done[0][0] == "done"
    out = open(job.result["output"]).read()
    assert out == ">c0\nAAAA\n>c1\nTTTT\n"
    assert job.result["fleet"]["served"] == {"fleet": 2}
    # a late re-delivery of a finished chunk is a counted duplicate
    assert _deliver(plane, by_index[0])["accepted"] is False
    assert plane.counters["duplicates"] == 1


def test_plane_drain_answer_and_stopping(tmp_path):
    plane = _plane(tmp_path)
    _submit(plane, tmp_path, "D")
    plane.pool._draining.add(7)
    assert plane._fetch(7).get("drain") is True
    with plane._cv:
        plane._stopping = True
    assert plane._fetch(0).get("drain") is True


def test_lease_reclaim_fault_drill_and_requeue(tmp_path, monkeypatch):
    """lease.reclaim: an armed raise is absorbed and counted — the
    reclaim itself always proceeds, releasing the dead holder's
    canonical journal and re-queueing the chunk."""
    plane = _plane(tmp_path)
    _submit(plane, tmp_path, "R")
    r = plane._fetch(0)
    c = plane.chunks[r["chunk"]["index"]]
    assert c.state == "running" and c.journal_held
    monkeypatch.setenv("RACON_TPU_FAULT", "lease.reclaim")
    plane._worker_dead(0, "unit test")
    assert plane.counters["reclaim_faults"] == 1
    assert plane.counters["lease_reclaimed"] == 1
    assert plane.counters["workers_dead"] == 1
    assert c.state == "pending" and not c.leases and not c.journal_held
    assert c.next_eligible > time.monotonic()   # backoff applied


def test_pool_scale_fault_drills(tmp_path, monkeypatch):
    """pool.scale_up / pool.scale_down: an armed raise is absorbed —
    the resize step is skipped (counted), the pool stays safe."""

    class _FakeProc:
        returncode = None

        def poll(self):
            return None

    pool = ElasticPool(logs_dir=str(tmp_path / "logs"), min_workers=0,
                       max_workers=2)
    monkeypatch.setenv("RACON_TPU_FAULT", "pool.scale_up")
    assert pool.scale_up(1, cause="drill") == 0
    assert pool.counters["scale_up_faults"] == 1
    assert pool.live() == 0                      # nothing spawned
    pool._procs[0] = _FakeProc()
    monkeypatch.setenv("RACON_TPU_FAULT", "pool.scale_down")
    assert pool.scale_down(1, cause="drill") == []
    assert pool.counters["scale_down_faults"] == 1
    assert not pool.is_draining(0)
    monkeypatch.delenv("RACON_TPU_FAULT")
    assert pool.scale_down(1, cause="idle") == [0]
    assert pool.is_draining(0)
    assert pool.counters["scale_downs"] == 1


# -------------------------------------------- loadtest telemetry helpers

def test_loadtest_pool_series_and_saturation_curve():
    from racon_tpu.serve.loadtest import pool_series, saturation_curve

    samples = [
        {"t": 0.5, "queued": {"device": 3},
         "fleet": {"workers": {"live": 1, "active": 1}, "min_workers": 1,
                   "max_workers": 4, "chunks_pending": 3,
                   "timeline": [[0.0, 1]]}},
        {"t": 1.5, "queued": {"device": 1},
         "fleet": {"workers": {"live": 3, "active": 3}, "min_workers": 1,
                   "max_workers": 4, "chunks_pending": 1,
                   "timeline": [[0.0, 1], [1.2, 3]]}},
    ]
    pool = pool_series(samples)
    assert pool["min"] == 1 and pool["max"] == 4
    assert pool["timeline"] == [[0.0, 1], [1.2, 3]]
    assert [s["live"] for s in pool["samples"]] == [1, 3]
    assert pool_series([{"t": 0.1}]) is None    # no plane: no series

    completed = [{"t_done": 0.4, "latency_s": 0.4},
                 {"t_done": 1.9, "latency_s": 1.0}]
    curve = saturation_curve(completed, samples, 2.0, buckets=2)
    assert len(curve) == 2
    assert curve[0]["jobs_done"] == 1 and curve[1]["jobs_done"] == 1
    assert curve[0]["workers"] == 1 and curve[1]["workers"] == 3
    assert curve[0]["max_queued"] == 3
    assert saturation_curve([], samples, 2.0) == []


# ------------------------------------------- integration: in-process fleet

def test_fleet_daemon_end_to_end_byte_identity(tmp_path):
    """Two tenants' jobs through a real elastic fleet (cpu workers):
    every chunk served by the fleet, output byte-identical to the
    serial oracle, stats carrying the fleet snapshot + admission
    ledger, and the merged plane trace validating under `obs fleet`."""
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)
    state = str(tmp_path / "state")
    daemon = ServeDaemon(state, backend="cpu", port=0, warm=False,
                         fleet_min=1, fleet_max=2)
    daemon.start()
    try:
        with ServeClient(daemon.port, timeout=180) as c:
            j1 = c.submit(*paths, args=dict(_ARGS), submitter="alice",
                          priority=1)
            j2 = c.submit(*paths, args=dict(_ARGS), submitter="bob")
            r1 = c.wait(j1, timeout=180)
            r2 = c.wait(j2, timeout=180)
            st = c.stats()
        for r in (r1, r2):
            assert r["state"] == "done"
            assert open(r["result"]["output"]).read() == want
            assert r["result"]["fleet"]["served"] == {"fleet": 3}
        assert st["fleet"]["min_workers"] == 1
        assert st["fleet"]["max_workers"] == 2
        assert st["fleet"]["counters"]["jobs_done"] == 2
        assert st["fleet"]["timeline"]          # pool-size samples
        assert "reserved_windows" in st["admission"]
    finally:
        daemon.stop(wait=True)
    fdir = os.path.join(state, "fleet")
    with open(os.path.join(fdir, "report.json")) as f:
        rep = json.load(f)
    assert rep["phases"]["fleet"]["served"]["fleet"] == 6
    r = subprocess.run([sys.executable, "-m", "racon_tpu.obs", "fleet",
                        os.path.join(fdir, "trace.json")],
                       capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "parenting holds" in r.stdout


def test_fleet_worker_killed_midchunk_recovers(tmp_path, monkeypatch):
    """Chaos: worker 0 is SIGKILLed delivering its first result
    (worker.result:kill=1, scoped to worker 0).  EOF reclaims its
    lease, the chunk re-dispatches, the pool respawns capacity, and
    the job still finishes byte-identical."""
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)
    monkeypatch.setenv("RACON_TPU_FAULT", "worker.result:kill=1:count=1")
    monkeypatch.setenv("RACON_TPU_DISTRIB_FAULT_WORKER", "0")
    daemon = ServeDaemon(str(tmp_path / "state"), backend="cpu", port=0,
                         warm=False, fleet_min=1, fleet_max=2)
    daemon.start()
    try:
        with ServeClient(daemon.port, timeout=240) as c:
            jid = c.submit(*paths, args=dict(_ARGS), submitter="chaos")
            res = c.wait(jid, timeout=240)
        assert res["state"] == "done"
        assert open(res["result"]["output"]).read() == want
        snap = daemon.plane.snapshot()
        assert snap["counters"]["workers_dead"] >= 1
        assert snap["counters"]["lease_reclaimed"] >= 1
    finally:
        daemon.stop(wait=True)


# ---------------------------- satellite: daemon SIGKILLed mid-resize

def _spawn_fleet(state, env):
    from racon_tpu.serve.loadtest import spawn_daemon

    proc = spawn_daemon(str(state), "cpu", window_length=100,
                        extra_args=["--no-warm", "--fleet-min", "1",
                                    "--fleet-max", "3"],
                        env=env, timeout=120)
    with open(os.path.join(str(state), "serve.json")) as f:
        return proc, json.load(f)["port"]


def test_daemon_killed_midresize_requeues_and_resumes(tmp_path):
    """Acceptance: pool.scale_up:kill=1 SIGKILLs the daemon mid-resize
    (a hung worker 0 keeps the backlog up so the autoscaler must fire).
    On restart the unfinished jobs re-queue from their specs, chunk
    leases are gone with the dead plane, and the chunk journals written
    before the crash turn the re-runs into byte-identical resumes."""
    paths = _write_dataset(tmp_path)
    want = _oracle_fasta(paths)
    state = tmp_path / "state"
    env = dict(os.environ, JAX_PLATFORMS="cpu", **_FAST_ENV)
    env.pop("RACON_TPU_FAULT", None)

    # worker 0 hangs 5s before each result delivery: the backlog holds,
    # the autoscaler decides to grow, and the armed kill fires mid-resize
    proc1, port1 = _spawn_fleet(state, dict(
        env, RACON_TPU_FAULT="worker.result:hang=5,pool.scale_up:kill=1",
        RACON_TPU_DISTRIB_FAULT_WORKER="0"))
    try:
        with ServeClient(port1, timeout=30) as c:
            c.submit(*paths, args=dict(_ARGS), job_id="ra",
                     submitter="acme")
            c.submit(*paths, args=dict(_ARGS), job_id="rb",
                     submitter="bcorp")
        assert proc1.wait(timeout=120) == -9     # SIGKILL mid-resize
    finally:
        if proc1.poll() is None:
            proc1.kill()
    for jid in ("ra", "rb"):
        jd = os.path.join(str(state), "jobs", jid)
        assert os.path.isfile(os.path.join(jd, "spec.json"))
        assert not os.path.isfile(os.path.join(jd, "result.json"))
    journaled = [p for p in glob.glob(os.path.join(
        str(state), "jobs", "*", "chunks", "*", "journal*.jsonl"))
        if os.path.getsize(p) > 0]

    proc2, port2 = _spawn_fleet(state, env)
    try:
        with ServeClient(port2, timeout=240) as c:
            ra = c.wait("ra", timeout=240)
            rb = c.wait("rb", timeout=240)
        replayed = 0
        for res in (ra, rb):
            assert res["state"] == "done"
            assert open(res["result"]["output"]).read() == want
            replayed += res["result"]["journal_replayed"]
        if journaled:
            # windows journaled before the crash must replay, not re-run
            assert replayed >= 1
        with ServeClient(port2, timeout=30) as c:
            c.shutdown()
        proc2.wait(timeout=60)
    finally:
        if proc2.poll() is None:
            proc2.kill()
