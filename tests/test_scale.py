"""Scale smoke: a simulated 50 kb ONT workload polishes end-to-end with a
substantial error reduction (the bench.py workload shape, small)."""

import numpy as np

import racon_tpu
from racon_tpu import native
from racon_tpu.tools import simulate

# The exact accuracy pins depend on numpy's Generator bit stream, which
# NEP 19 allows to change across feature releases; CI pins numpy==2.0.*.
# On any other numpy, fall back to the (weaker) ratio bound instead of
# failing spuriously.
NUMPY_PINNED = np.__version__.startswith("2.0.")


def test_simulated_workload_polishes(tmp_path):
    paths = simulate.generate(str(tmp_path), mbp=0.05, coverage=20, seed=7)
    genome = b"".join(l.strip().encode() for l in open(paths["genome"])
                      if not l.startswith(">"))
    draft = b"".join(l.strip().encode() for l in open(paths["draft"])
                     if not l.startswith(">"))
    draft_ed = native.edit_distance(draft, genome)
    assert draft_ed > 200  # ~1% draft error

    p = racon_tpu.CpuPolisher(paths["reads"], paths["overlaps"],
                              paths["draft"], window_length=500,
                              match=5, mismatch=-4, gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    polished_ed = native.edit_distance(res[0][1].encode(), genome)
    # Pinned exactly, golden-style: the simulator is seeded and the host
    # engine deterministic, so any drift is a semantic change that must be
    # looked at (the previous < draft_ed/4 bar would have passed sizable
    # regressions silently). Measured 2026-07-29: draft 383 -> polished 95.
    if NUMPY_PINNED:
        assert polished_ed == 95, (draft_ed, polished_ed)
    else:
        assert polished_ed < draft_ed / 4, (draft_ed, polished_ed)


def test_simulated_sam_truth_cigars_polish(tmp_path):
    """The simulator's SAM output carries ground-truth CIGARs: polishing
    from them must skip the alignment phase and land on the same pinned
    accuracy as the PAF path (the true alignment and the banded-Myers
    alignment agree at this scale)."""
    paths = simulate.generate(str(tmp_path), mbp=0.05, coverage=20, seed=7)
    genome = b"".join(l.strip().encode() for l in open(paths["genome"])
                      if not l.startswith(">"))

    p = racon_tpu.CpuPolisher(paths["reads"], paths["overlaps_sam"],
                              paths["draft"], window_length=500,
                              match=5, mismatch=-4, gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    polished_ed = native.edit_distance(res[0][1].encode(), genome)
    if NUMPY_PINNED:
        assert polished_ed == 95, polished_ed
    else:
        assert polished_ed < 120, polished_ed
