"""Short-read (Illumina-style) polishing scenario — the analogue of
BASELINE.json config 4 (short-read polish, SAM input, small windows):
paired-end reads renamed by the preprocess tool, mean read length <= 1000
selects NGS windows (no trim; reference: src/polisher.cpp:277-278,
src/window.cpp:125), window length 200."""

import io
import random

import racon_tpu
from racon_tpu import native
from racon_tpu.tools import preprocess, simulate


def test_bench_sr_profile_dataset_polishes(tmp_path):
    """The bench's short-read profile (150 bp @ ~1% error — the
    hw_session bench_sam_sr workload) must produce a dataset the host
    pipeline actually polishes: reads are short-read-sized, windows are
    NGS-class, and the polished contig lands closer to the genome than
    the draft started."""
    paths = simulate.generate(str(tmp_path / "sr"), mbp=0.02, coverage=30,
                              mean_read=150, sub=0.008, ins=0.001,
                              dele=0.001)
    with open(paths["reads"]) as f:
        lens = [len(line.strip()) for i, line in enumerate(f) if i % 4 == 1]
    assert sum(lens) / len(lens) < 300, "not a short-read profile"

    p = racon_tpu.create_polisher(paths["reads"], paths["overlaps_sam"],
                                  paths["draft"], backend="cpu",
                                  window_length=500,
                                  quality_threshold=10.0,
                                  error_threshold=0.3, match=5,
                                  mismatch=-4, gap=-8, num_threads=1)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    genome = open(paths["genome"]).read().split("\n", 1)[1].replace("\n", "")
    draft = open(paths["draft"]).read().split("\n", 1)[1].replace("\n", "")
    ed_draft = native.edit_distance(draft.encode(), genome.encode())
    ed_pol = native.edit_distance(res[0][1].encode(), genome.encode())
    assert ed_pol < ed_draft / 4, (ed_pol, ed_draft)


def make_dataset(tmp_path, rng, genome_len=2000, read_len=150, coverage=20):
    truth = "".join(rng.choice("ACGT") for _ in range(genome_len))
    # Draft with ~1.5% substitution errors.
    draft = list(truth)
    n_err = int(genome_len * 0.015)
    err_pos = rng.sample(range(genome_len), n_err)
    for pos in err_pos:
        draft[pos] = rng.choice([c for c in "ACGT" if c != draft[pos]])
    draft = "".join(draft)

    with open(tmp_path / "draft.fasta", "w") as f:
        f.write(f">chr\n{draft}\n")

    # Paired reads sharing a name (renamed 1/2 by preprocess), high quality.
    n_reads = genome_len * coverage // read_len
    pairs_fq = io.StringIO()
    records = []
    for i in range(n_reads // 2):
        for _ in range(2):
            start = rng.randint(0, genome_len - read_len)
            seq = truth[start:start + read_len]
            pairs_fq.write(f"@frag{i} extra\n{seq}\n+\n{'I' * read_len}\n")
            records.append((start, seq))

    with open(tmp_path / "pairs.fastq", "w") as f:
        f.write(pairs_fq.getvalue())

    # Rename pairs to unique names (the preprocess contract).
    renamed = io.StringIO()
    preprocess.parse_file(str(tmp_path / "pairs.fastq"), set(), renamed)
    with open(tmp_path / "reads.fastq", "w") as f:
        f.write(renamed.getvalue())
    names = [l[1:].strip() for l in renamed.getvalue().splitlines()[::4]]

    # SAM with exact positions (reads come from truth; the draft's
    # substitutions become the windows' correction work).
    with open(tmp_path / "aln.sam", "w") as f:
        f.write("@HD\tVN:1.6\n@SQ\tSN:chr\tLN:%d\n" % genome_len)
        for name, (start, seq) in zip(names, records):
            f.write(f"{name}\t0\tchr\t{start + 1}\t60\t{read_len}M\t*\t0\t0\t"
                    f"{seq}\t{'I' * read_len}\n")
    return truth, draft


def test_short_read_polish(tmp_path):
    rng = random.Random(17)
    truth, draft = make_dataset(tmp_path, rng)
    assert native.edit_distance(draft.encode(), truth.encode()) > 20

    p = racon_tpu.CpuPolisher(str(tmp_path / "reads.fastq"),
                              str(tmp_path / "aln.sam"),
                              str(tmp_path / "draft.fasta"),
                              window_length=200, quality_threshold=10.0,
                              error_threshold=0.3,
                              match=5, mismatch=-4, gap=-8, num_threads=1)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    polished = res[0][1].encode()
    # Short high-quality reads should correct nearly every draft error.
    ed = native.edit_distance(polished, truth.encode())
    assert ed <= 3, ed


def test_short_read_polish_device_path(tmp_path, monkeypatch):
    rng = random.Random(23)
    truth, draft = make_dataset(tmp_path, rng, genome_len=1000, coverage=16)

    from racon_tpu.ops import poa_driver

    captured = {}
    orig = poa_driver.run_consensus_phase

    def spy(*a, **k):
        stats = orig(*a, **k)
        captured.update(stats)
        return stats

    monkeypatch.setattr(poa_driver, "run_consensus_phase", spy)
    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "8")
    p = racon_tpu.TpuPolisher(str(tmp_path / "reads.fastq"),
                              str(tmp_path / "aln.sam"),
                              str(tmp_path / "draft.fasta"),
                              window_length=200, quality_threshold=10.0,
                              error_threshold=0.3,
                              match=5, mismatch=-4, gap=-8, num_threads=1)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    ed = native.edit_distance(res[0][1].encode(), truth.encode())
    assert ed <= 3, ed
    # the device (default ls tier) must actually have served: a silent
    # per-window host fallback would hide a broken kernel behind correct
    # output
    assert captured["device"] > 0
    assert captured["host_fallback"] == 0 and captured["failed"] == 0
