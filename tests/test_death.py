"""Input-validation death tests — the reference's EXPECT_DEATH strategy
(/root/reference/test/racon_test.cpp:53-84) via subprocess exit codes."""

import os
import subprocess
import sys

from tests.conftest import DATA, requires_data

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN = os.path.join(ROOT, "racon_tpu", "native", "build", "racon_tpu")


def run_bin(*args):
    return subprocess.run([BIN, *args], capture_output=True, text=True,
                          timeout=120)


@requires_data
def test_window_length_error():
    r = run_bin("-w", "0", DATA + "sample_reads.fastq.gz",
                DATA + "sample_overlaps.paf.gz",
                DATA + "sample_layout.fasta.gz")
    assert r.returncode == 1
    assert "invalid window length" in r.stderr


def test_sequences_extension_error():
    r = run_bin("reads.txt", "o.paf", "t.fa")
    assert r.returncode == 1
    assert "unsupported format extension" in r.stderr
    assert ".fasta" in r.stderr


@requires_data
def test_overlaps_extension_error():
    r = run_bin(DATA + "sample_reads.fastq.gz", "o.bed", "t.fa")
    assert r.returncode == 1
    assert ".mhap" in r.stderr


@requires_data
def test_target_extension_error():
    r = run_bin(DATA + "sample_reads.fastq.gz",
                DATA + "sample_overlaps.paf.gz", "t.bed")
    assert r.returncode == 1
    assert "unsupported format extension" in r.stderr


def test_missing_inputs():
    r = run_bin()
    assert r.returncode == 1
    assert "missing input" in r.stderr


@requires_data
def test_missing_file():
    r = run_bin(DATA + "sample_reads.fastq.gz",
                DATA + "sample_overlaps.paf.gz", "/nonexistent/x.fasta")
    assert r.returncode == 1
    assert "unable to open" in r.stderr


def test_bad_kernel_kind_env_clean_error(tmp_path):
    """An invalid RACON_TPU_POA_KERNEL must surface as the reference-style
    single-line error + exit 1 from the Python CLI, not a traceback.
    Self-contained (builds its own inputs): runs even without the
    reference λ fixtures."""
    target = "ACGT" * 30
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(3):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(3):
            f.write(f"r{i}\t0\tt\t1\t60\t{len(target)}M\t*\t0\t0\t{target}"
                    f"\t*\n")
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from __graft_entry__ import _force_cpu; _force_cpu(1); "
        "from racon_tpu.cli import main; "
        "sys.exit(main(['--tpu', %r, %r, %r]))"
    ) % (ROOT, str(tmp_path / "r.fasta"), str(tmp_path / "o.sam"),
         str(tmp_path / "t.fasta"))
    r = subprocess.run([sys.executable, "-c", code],
                       env=dict(os.environ, RACON_TPU_POA_KERNEL="bogus"),
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 1
    assert "RACON_TPU_POA_KERNEL" in r.stderr
    assert "Traceback" not in r.stderr


def test_malformed_fault_spec_clean_error(tmp_path):
    """A malformed RACON_TPU_FAULT spec must surface as a single-line
    error + exit 1 from the CLI (reference-style), not a mid-run
    traceback. Self-contained: builds its own inputs."""
    target = "ACGT" * 30
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(3):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(3):
            f.write(f"r{i}\t0\tt\t1\t60\t{len(target)}M\t*\t0\t0\t{target}"
                    f"\t*\n")
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from __graft_entry__ import _force_cpu; _force_cpu(1); "
        "from racon_tpu.cli import main; "
        "sys.exit(main(['--tpu', %r, %r, %r]))"
    ) % (ROOT, str(tmp_path / "r.fasta"), str(tmp_path / "o.sam"),
         str(tmp_path / "t.fasta"))
    for bad in ("poa.run.bogus", "poa.run.ls:frobnicate=1",
                "poa.run.ls:batch=x"):
        r = subprocess.run([sys.executable, "-c", code],
                           env=dict(os.environ, RACON_TPU_FAULT=bad),
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 1, (bad, r.stderr[-500:])
        assert "RACON_TPU_FAULT" in r.stderr
        assert "Traceback" not in r.stderr
