"""Input-validation death tests — the reference's EXPECT_DEATH strategy
(/root/reference/test/racon_test.cpp:53-84) via subprocess exit codes."""

import os
import subprocess
import sys

from tests.conftest import DATA, requires_data

BIN = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "racon_tpu", "native", "build", "racon_tpu")


pytestmark = requires_data

def run_bin(*args):
    return subprocess.run([BIN, *args], capture_output=True, text=True,
                          timeout=120)


def test_window_length_error():
    r = run_bin("-w", "0", DATA + "sample_reads.fastq.gz",
                DATA + "sample_overlaps.paf.gz",
                DATA + "sample_layout.fasta.gz")
    assert r.returncode == 1
    assert "invalid window length" in r.stderr


def test_sequences_extension_error():
    r = run_bin("reads.txt", "o.paf", "t.fa")
    assert r.returncode == 1
    assert "unsupported format extension" in r.stderr
    assert ".fasta" in r.stderr


def test_overlaps_extension_error():
    r = run_bin(DATA + "sample_reads.fastq.gz", "o.bed", "t.fa")
    assert r.returncode == 1
    assert ".mhap" in r.stderr


def test_target_extension_error():
    r = run_bin(DATA + "sample_reads.fastq.gz",
                DATA + "sample_overlaps.paf.gz", "t.bed")
    assert r.returncode == 1
    assert "unsupported format extension" in r.stderr


def test_missing_inputs():
    r = run_bin()
    assert r.returncode == 1
    assert "missing input" in r.stderr


def test_missing_file():
    r = run_bin(DATA + "sample_reads.fastq.gz",
                DATA + "sample_overlaps.paf.gz", "/nonexistent/x.fasta")
    assert r.returncode == 1
    assert "unable to open" in r.stderr
