"""Column-compressed POA stepping (ops/colstep.py, the colstep paths in
poa_pallas.py / poa_pallas_ls.py) and the packed aligner DP
(encoding.pack_bases, the pack paths in align_pallas.py).

Three layers of coverage, all interpret mode on the CPU backend:

* rank -> column-step mapping unit tests (chain, bubble, branch-heavy
  graphs) against the numpy twin `colstep.pair_schedule`;
* packed-encoding round trips (encoding.pack_bases / unpack_bases);
* byte-identity: the compressed kernels against their flat-loop
  variants and the host oracle across a depth x length grid, the packed
  aligner against the unpacked one, and an end-to-end polish under
  RACON_TPU_FAULT lattice demotion (v2-with-colstep serving most
  windows, the quarantined one demoted to host).

The serial-step GATE (measured loop trip counts, >= 1.5x POA / >= 2x
aligner) runs through racon_tpu/tools/dp_cost_probe.py --gate and is
asserted here so tier-1 CI enforces it.
"""

import random

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.ops import colstep, encoding, poa_pallas, poa_pallas_ls
from racon_tpu.ops.encoding import decode, encode

from tests.test_pallas import mutate
from tests.test_pallas_ls import CFG, _alloc, _set_window


# --------------------------------------------- rank -> column-step map

def test_pair_schedule_chain():
    # linear chain: all keys distinct -> no compression
    keys = [0.0, 1.0, 2.0, 3.0]
    assert colstep.pair_schedule(keys) == [(0, 1), (1, 1), (2, 1), (3, 1)]
    assert colstep.n_column_steps(keys) == 4
    assert colstep.compression(keys) == 1.0


def test_pair_schedule_bubble():
    # SNP bubble: two alternative bases share column 1
    keys = [0.0, 1.0, 1.0, 2.0]
    assert colstep.pair_schedule(keys) == [(0, 1), (1, 2), (3, 1)]
    assert colstep.n_column_steps(keys) == 3


def test_pair_schedule_branch_heavy():
    # multiplicity-3 column takes ceil(3/2) greedy steps; the
    # multiplicity-2 column pairs fully
    keys = [0.0, 1.0, 1.0, 1.0, 2.0, 2.0]
    assert colstep.pair_schedule(keys) == [(0, 1), (1, 2), (3, 1), (4, 2)]
    assert colstep.n_column_steps(keys) == 4
    assert colstep.compression(keys) == pytest.approx(6 / 4)


def test_pair_schedule_pack_ceiling_and_empty():
    keys = [5.0] * 8   # degenerate single column
    assert colstep.n_column_steps(keys) == 4
    assert colstep.compression(keys) == colstep.PACK
    assert colstep.pair_schedule([]) == []
    assert colstep.compression([]) == 1.0


def test_pair_schedule_covers_every_rank_once():
    rng = random.Random(11)
    keys = sorted(rng.choice((0.5, 1.0, 1.5, 2.0, 2.25, 3.0))
                  for _ in range(37))
    seen = []
    for r, take in colstep.pair_schedule(keys):
        seen.extend(range(r, r + take))
    assert seen == list(range(len(keys)))


# --------------------------------------------------- packed encoding

def test_pack_bases_round_trip():
    rng = np.random.default_rng(3)
    for n in (0, 1, 3, 4, 5, 127, 128, 1000):
        codes = rng.integers(0, 5, size=n).astype(np.int32)
        words = encoding.pack_bases(codes)
        assert words.shape[-1] == (n + encoding.PACK - 1) // encoding.PACK
        np.testing.assert_array_equal(encoding.unpack_bases(words, n),
                                      codes)


def test_pack_bases_width_and_batch():
    codes = (np.arange(10, dtype=np.int32) % 5).reshape(2, 5)
    words = encoding.pack_bases(codes, width=128)
    assert words.shape == (2, 128)
    np.testing.assert_array_equal(encoding.unpack_bases(words, 5), codes)


def test_pack_bases_is_lossless_for_code4():
    # why packing is byte-per-code, not 2-bit: code 4 (N) must survive
    codes = np.full(9, 4, np.int32)
    np.testing.assert_array_equal(
        encoding.unpack_bases(encoding.pack_bases(codes), 9), codes)


# --------------------------------- kernel byte-identity (interpret mode)

def _window_batch(rng, B, cfg, depths, lengths, rate=0.1):
    a = _alloc(B, cfg)
    cases = []
    for b in range(B):
        truth = bytes(rng.choice(b"ACGT") for _ in range(lengths[b]))
        backbone = mutate(truth, rate, rng)
        layers = [mutate(truth, rate, rng) for _ in range(depths[b])]
        _set_window(a, b, backbone, layers)
        cases.append((backbone, layers))
    return a, cases


def _call(fn, a):
    return tuple(np.asarray(x) for x in fn(
        a["bb_len"][:, None], a["nl"][:, None], a["lens"], a["bg"],
        a["en"], a["bb"].astype(np.int32), a["bbw"],
        a["seqs"].astype(np.int32), a["ws"]))


def test_v2_colstep_byte_identical_across_grid():
    """Compressed vs flat v2 loop on a depth x length grid: every output
    array identical, and the consensus equals the host oracle."""
    rng = random.Random(19)
    B = 4
    a, cases = _window_batch(rng, B, CFG, depths=[2, 4, 6, 8],
                             lengths=[40, 70, 100, 120])
    on = _call(poa_pallas.build_pallas_poa_kernel(
        CFG, interpret=True, colstep=True)(B), a)
    off = _call(poa_pallas.build_pallas_poa_kernel(
        CFG, interpret=True, colstep=False)(B), a)
    for x, y in zip(on, off):
        np.testing.assert_array_equal(x, y)
    cb, cc, cl, fl, nn = on
    assert not fl.any()
    for b, (backbone, layers) in enumerate(cases):
        host, _ = native.window_consensus(backbone, layers, trim=False)
        assert decode(cb[b, :cl[b, 0]]) == host


def test_ls_colstep_byte_identical_across_grid():
    """Compressed (rank-pair) vs flat lockstep loop on one 8-window
    batch of varying depth/length, including a padding window."""
    rng = random.Random(29)
    B = 8
    a, cases = _window_batch(rng, B, CFG, depths=[2, 3, 4, 5, 6, 4, 3, 2],
                             lengths=[40, 55, 70, 85, 100, 60, 45, 30])
    # w7 -> padding window (1-base backbone, zero layers)
    a["bb"][7] = 0
    a["bb_len"][7] = 1
    a["nl"][7] = 0
    a["lens"][7] = 0
    on = _call(poa_pallas_ls.build_lockstep_poa_kernel(
        CFG, interpret=True, colstep=True)(B), a)
    off = _call(poa_pallas_ls.build_lockstep_poa_kernel(
        CFG, interpret=True, colstep=False)(B), a)
    for x, y in zip(on, off):
        np.testing.assert_array_equal(x, y)
    cb, cc, cl, fl, nn = on
    assert not fl.any()
    for b, (backbone, layers) in enumerate(cases[:7]):
        host, _ = native.window_consensus(backbone, layers, trim=False)
        assert decode(cb[b, :cl[b, 0]]) == host


def test_align_pack_byte_identical(monkeypatch):
    """Packed (4 rows/step) vs unpacked Hirschberg aligner: identical op
    paths on multi-bucket input."""
    from racon_tpu.ops import align_pallas

    rng = random.Random(23)
    pairs = []
    for n in (150, 300, 700):
        q = bytes(rng.choice(b"ACGT") for _ in range(n))
        pairs.append((q, mutate(q, 0.08, rng)))
    enc = [(encode(np.frombuffer(q, np.uint8)).astype(np.int32),
            encode(np.frombuffer(t, np.uint8)).astype(np.int32))
           for q, t in pairs]

    def run(flag):
        monkeypatch.setenv("RACON_TPU_ALIGN_PACK", flag)
        align_pallas._build_edge_kernel.cache_clear()
        align_pallas._build_base_kernel.cache_clear()
        try:
            return align_pallas.align_pairs(enc, interpret=True)
        finally:
            align_pallas._build_edge_kernel.cache_clear()
            align_pallas._build_base_kernel.cache_clear()

    packed = run("1")
    flat = run("0")
    for p_ops, f_ops in zip(packed, flat):
        assert p_ops is not None and f_ops is not None
        np.testing.assert_array_equal(p_ops, f_ops)


def test_colstep_polish_byte_identical_under_fault_demotion(tmp_path,
                                                            monkeypatch):
    """End-to-end polish with the colstep v2 kernel serving, one window
    poisoned via RACON_TPU_FAULT and demoted down the lattice: the
    polished output stays byte-identical to the CPU oracle."""
    from tests.test_faults import (_assert_report_sums, _oracle, _tpu_run,
                                   _write_dataset)

    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_PALLAS": "1",
        "RACON_TPU_FAULT": "poa.run.v2:window=2",
    })
    assert res == oracle
    d = _assert_report_sums(p)
    cons = d["phases"]["consensus"]
    assert cons["served"]["v2"] == 5 and cons["served"]["host"] == 1
    assert cons["quarantined"] == [2]


# ------------------------------------------------ serial-step gate (CI)

def test_probe_serial_step_gate(capsys):
    """The dp_cost_probe gate: measured in-loop counts of the compressed
    modes vs their baselines must clear the floors (>= 1.5x serial steps
    for both POA shapes, >= 2x for the packed aligner, >= 3x in-loop
    cells for the two banded pairs)."""
    from racon_tpu.tools import dp_cost_probe

    assert dp_cost_probe.gate()
    out = capsys.readouterr().out
    assert out.count("OK") == 5 and "FAIL" not in out
    assert out.count("in-loop cells") == 2
    assert "measured ratio" in out
