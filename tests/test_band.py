"""Banded DP + verify-and-widen ladder (ops/band.py) on both hot kernels.

Adversarial fixtures for the exactness contract: banded runs must be
BYTE-IDENTICAL to the flat oracle — boundary-optimum pairs, pairs that
force one widening, pairs that exhaust the ladder through the
``banded -> flat`` lattice edge, and the deterministic ``band.hit``
fault drill — with the band.* counters recording exactly what happened.
"""

import random

import numpy as np
import pytest

from racon_tpu import obs
from racon_tpu.ops import align_pallas, band
from racon_tpu.ops.encoding import encode


def _rand(rng, n):
    return bytes(rng.choice(b"ACGT") for _ in range(n))


def _mut(rng, seq, rate):
    out = bytearray()
    for c in seq:
        r = rng.random()
        if r < rate / 3:
            out.append(rng.choice(b"ACGT"))
        elif r < 2 * rate / 3:
            pass
        elif r < rate:
            out.append(c)
            out.append(rng.choice(b"ACGT"))
        else:
            out.append(c)
    return bytes(out)


def _shifted_pair(rng, n, shift, cut, ins):
    """A pair with net length delta ~0 whose optimal path strays `shift`
    diagonals off the corridor: a `shift`-base block deleted at `cut`
    and a random block inserted at `ins` — w0 (delta + slack) plans a
    narrow band the true path escapes."""
    q = _rand(rng, n)
    t = q[:cut] + q[cut + shift:ins] + _rand(rng, shift) + q[ins:]
    return q, t


def _enc(q, t):
    return (encode(np.frombuffer(q, np.uint8)).astype(np.int32),
            encode(np.frombuffer(t, np.uint8)).astype(np.int32))


class _FakePipe:
    """Duck-typed align pipeline for run_jobs (no lengths table)."""

    def __init__(self, pairs):
        self.pairs = pairs
        self.cigars = {}

    def align_job(self, j):
        q, t = self.pairs[j]
        return (np.frombuffer(q, np.uint8), np.frombuffer(t, np.uint8))

    def set_job_cigar(self, j, c):
        self.cigars[j] = c


def _counters():
    snap = obs.snapshot() or {}
    return snap.get("counters") or {}


# ------------------------------------------------------------ band planning


def test_plan_and_verify_units():
    # w0 = delta + slack, bucketed under the flat band
    assert band.bucket_for(1) == 128
    assert band.bucket_for(128) == 128
    assert band.bucket_for(129) == 256
    assert band.bucket_for(99999) is None
    assert band.plan_align_band(800, 800, 256) == 128
    assert band.plan_align_band(800, 1200, 256) is None   # w0 >= flat band
    assert band.plan_align_band(800, 800, 0) is None      # host-bound pair
    assert band.plan_align_band(2600, 2600, 512, widenings=3) == 256
    # exact Ukkonen certificate: corridor covered, distance within bound
    n = m = 800
    k = 128
    gdmin = min(0, m - n) - (k - 1 - abs(m - n)) // 2
    assert band.ukkonen_ok(n, m, k, gdmin, 10)
    assert not band.ukkonen_ok(n, m, k, gdmin, 2 * k)     # bound exceeded
    assert not band.ukkonen_ok(n, m, k, gdmin, None)      # no distance
    assert not band.ukkonen_ok(800, 1200, k, gdmin, 0)    # corridor escapes


# ------------------------------------------------------- aligner, direct API


def test_align_banded_byte_identity_direct():
    """band_overrides under the exact verify: served pairs are
    byte-identical to the flat oracle; escapes are flagged as hits."""
    rng = random.Random(101)
    pairs = []
    for _ in range(3):
        q = _rand(rng, 800)
        pairs.append((q, _mut(rng, q, 0.03)))
    enc = [_enc(q, t) for q, t in pairs]
    flat = align_pallas.align_pairs(enc, interpret=True)
    hits = set()
    banded = align_pallas.align_pairs(
        enc, interpret=True, band_overrides={i: 128 for i in range(3)},
        hits=hits)
    served = 0
    for i in range(3):
        assert flat[i] is not None
        if i in hits:
            assert banded[i] is None    # hit pairs abort, never mis-serve
            continue
        served += 1
        np.testing.assert_array_equal(banded[i], flat[i])
    assert served >= 1, "3% pairs should mostly verify in-band"


def test_align_boundary_optimum_byte_identity():
    """Boundary-optimum adversarial fixture: a single deletion block
    pushes the optimal path to the band edge — the certificate must
    either serve it byte-identically or flag a hit, never mis-serve."""
    rng = random.Random(7)
    q = _rand(rng, 820)
    t = q[:400] + q[460:]            # 60-base deletion: corridor spans 60
    enc = [_enc(q, t)]
    flat = align_pallas.align_pairs(enc, interpret=True)
    hits = set()
    banded = align_pallas.align_pairs(enc, interpret=True,
                                      band_overrides={0: 128}, hits=hits)
    assert flat[0] is not None
    if 0 in hits:
        assert banded[0] is None
    else:
        np.testing.assert_array_equal(banded[0], flat[0])


def test_align_escape_is_a_hit_not_a_wrong_answer():
    """A path that strays ~100 diagonals off a ±64 band MUST be flagged."""
    rng = random.Random(13)
    q, t = _shifted_pair(rng, 800, 100, 200, 550)
    enc = [_enc(q, t)]
    hits = set()
    banded = align_pallas.align_pairs(enc, interpret=True,
                                      band_overrides={0: 128}, hits=hits)
    assert hits == {0}
    assert banded[0] is None


# --------------------------------------------------- aligner, run_jobs ladder


def test_run_jobs_banded_matches_flat_oracle(monkeypatch):
    """End-to-end verify-and-widen through run_jobs + BatchExecutor: a
    clean pair installs off the narrow band, the escape pair rides the
    banded -> flat lattice edge, and every CIGAR equals the flat run's."""
    rng = random.Random(29)
    qa = _rand(rng, 800)
    pairs = {0: (qa, _mut(rng, qa, 0.03)),
             1: _shifted_pair(rng, 800, 100, 200, 550)}

    flat_pipe = _FakePipe(pairs)
    monkeypatch.setenv("RACON_TPU_BAND", "0")
    served = align_pallas.run_jobs(flat_pipe, list(pairs))
    assert served == 2

    obs.reset()
    obs.configure(metrics=True)
    try:
        band_pipe = _FakePipe(pairs)
        monkeypatch.setenv("RACON_TPU_BAND", "1")
        served = align_pallas.run_jobs(band_pipe, list(pairs))
        assert served == 2
        assert band_pipe.cigars == flat_pipe.cigars   # byte-identical
        c = _counters()
        assert c.get("band.jobs") == 2
        assert c.get("band.hits", 0) >= 1             # the shifted pair
        assert c.get("band.fallbacks", 0) >= 1        # banded -> flat edge
        assert c.get("align.cells.banded", 0) > 0
        # the banded plan iterates fewer cells than the flat band
        assert c["align.cells.banded"] < c["align.cells.hirschberg"]
    finally:
        obs.reset()


def test_run_jobs_fault_drill_exhausts_ladder(monkeypatch):
    """Armed band.hit fault: every banded attempt is classified a hit,
    the ladder drains to its flat floor, output stays byte-identical."""
    rng = random.Random(31)
    qa = _rand(rng, 800)
    pairs = {0: (qa, _mut(rng, qa, 0.03))}

    flat_pipe = _FakePipe(pairs)
    monkeypatch.setenv("RACON_TPU_BAND", "0")
    assert align_pallas.run_jobs(flat_pipe, [0]) == 1

    obs.reset()
    obs.configure(metrics=True)
    try:
        monkeypatch.setenv("RACON_TPU_BAND", "1")
        monkeypatch.setenv("RACON_TPU_FAULT", "band.hit")
        from racon_tpu.resilience import faults
        faults.reset()
        drill_pipe = _FakePipe(pairs)
        assert align_pallas.run_jobs(drill_pipe, [0]) == 1
        assert drill_pipe.cigars == flat_pipe.cigars
        c = _counters()
        assert c.get("band.jobs") == 1
        assert c.get("band.hits", 0) >= 1
        assert c.get("band.fallbacks") == 1
    finally:
        obs.reset()
        faults.reset()


def test_run_jobs_one_widening_rung(monkeypatch):
    """A pair whose flat band is 512 and whose path strays ~100
    diagonals: the 128 rung hits, the 256 rung verifies — exactly one
    widening, no fallback, byte-identical CIGAR."""
    rng = random.Random(37)
    q, t = _shifted_pair(rng, 2600, 100, 900, 1800)
    assert align_pallas.band_for(len(q), len(t)) == 512
    pairs = {0: (q, t)}

    flat_pipe = _FakePipe(pairs)
    monkeypatch.setenv("RACON_TPU_BAND", "0")
    assert align_pallas.run_jobs(flat_pipe, [0]) == 1

    obs.reset()
    obs.configure(metrics=True)
    try:
        band_pipe = _FakePipe(pairs)
        monkeypatch.setenv("RACON_TPU_BAND", "1")
        monkeypatch.setenv("RACON_TPU_BAND_SLACK", "80")
        assert align_pallas.run_jobs(band_pipe, [0]) == 1
        assert band_pipe.cigars == flat_pipe.cigars
        c = _counters()
        assert c.get("band.hits") == 1
        assert c.get("band.widenings") == 1
        assert c.get("band.fallbacks", 0) == 0
    finally:
        obs.reset()


# ----------------------------------------------------------- POA, kernel API


def _poa_batch(cfg, B, seed, roll=0):
    rng = np.random.default_rng(seed)
    L = cfg.max_backbone // 2
    bb = np.zeros((B, cfg.max_backbone), np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), np.int32)
    bl = np.zeros(B, np.int32)
    nl = np.zeros(B, np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), np.int32)
    lens = np.zeros((B, cfg.depth), np.int32)
    bg = np.zeros((B, cfg.depth), np.int32)
    en = np.zeros((B, cfg.depth), np.int32)
    for b in range(B):
        truth = rng.integers(0, 4, L).astype(np.uint8)
        bb[b, :L] = truth
        bl[b] = L
        nl[b] = cfg.depth
        for li in range(cfg.depth):
            layer = truth.copy()
            pos = rng.integers(0, L, 3)
            layer[pos] = (layer[pos] + 1) % 4
            if roll:
                layer[10:] = np.roll(layer[10:], roll)
            seqs[b, li, :L] = layer
            ws[b, li, :L] = 1
            lens[b, li] = L
            bg[b, li] = 0
            en[b, li] = L - 1
    return (bb, bbw, bl, nl, seqs, ws, lens, bg, en)


@pytest.mark.parametrize("kernel", ["v2", "ls"])
def test_poa_banded_kernel_byte_identity(kernel):
    """Both banded POA builds: wband=0 reproduces the flat kernel
    byte-for-byte (the ladder's floor runs through the same compiled
    build), a generous band matches the flat oracle with no hit, and a
    pathologically narrow band on drifted layers raises band_hit."""
    from racon_tpu.ops import poa, poa_driver
    from racon_tpu.ops.poa_pallas import build_pallas_poa_kernel
    from racon_tpu.ops.poa_pallas_ls import build_lockstep_poa_kernel

    cfg = poa.PoaConfig(max_nodes=256, max_len=128, max_backbone=128,
                        max_edges=8, depth=4, match=5, mismatch=-4, gap=-8)
    build = (build_pallas_poa_kernel if kernel == "v2"
             else build_lockstep_poa_kernel)
    B = 8 if kernel == "ls" else 2
    flat = build(cfg, interpret=True)(B)
    banded = build(cfg, interpret=True, band=True)(B)

    def run(kern, packed9, wband):
        is_banded = wband is not None
        w = np.full(B, wband if is_banded else 0, np.int32)
        outs = poa_driver._submit(kern, packed9 + (w,), True, is_banded)
        return poa_driver._unpack(outs, True, is_banded)

    packed9 = _poa_batch(cfg, B, 0)
    fb, fc, fl, ff = run(flat, packed9, None)
    assert not ff.any()

    for w in (0, 8):   # flat floor through the banded build; generous band
        zb, zc, zl, zf, zh = run(banded, packed9, w)
        assert not zf.any() and not zh.any()
        assert (zl == fl).all()
        for b in range(B):
            np.testing.assert_array_equal(zb[b, :zl[b]], fb[b, :fl[b]])
            np.testing.assert_array_equal(zc[b, :zl[b]], fc[b, :fl[b]])

    drift9 = _poa_batch(cfg, B, 1, roll=5)
    nb, nc, nl_, nf, nh = run(banded, drift9, 1)
    assert (nh | nf).any(), "drifted layers at wband=1 must flag a hit"


# -------------------------------------------------------- POA, driver ladder


def _polish_dataset(tmp_path, seed=5, n=240, reads=4):
    rng = random.Random(seed)
    target = "".join(rng.choice("ACGT") for _ in range(n))
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(reads):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(reads):
            f.write(f"r{i}\t0\tt\t1\t60\t{n}M\t*\t0\t0\t{target}\t*\n")
    return target


def _polish(tmp_path):
    import racon_tpu

    p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                              str(tmp_path / "o.sam"),
                              str(tmp_path / "t.fasta"),
                              window_length=80, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    return p.polish(True)


def test_poa_banded_driver_byte_identity(tmp_path, monkeypatch):
    """RACON_TPU_BAND=1 through the full consensus driver (pallas v2,
    interpret): polished output byte-identical to the flat run, banded
    windows counted."""
    target = _polish_dataset(tmp_path)
    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "4")

    monkeypatch.setenv("RACON_TPU_BAND", "0")
    flat = _polish(tmp_path)

    try:
        monkeypatch.setenv("RACON_TPU_BAND", "1")
        monkeypatch.setenv("RACON_TPU_BAND_SLACK", "8")
        # the polisher constructor resets + re-arms obs itself, so the
        # metrics knob (not a direct obs.configure) is what survives
        monkeypatch.setenv("RACON_TPU_METRICS", "1")
        banded = _polish(tmp_path)
        assert [s for _, s in banded] == [s for _, s in flat]
        assert banded[0][1] == target
        c = _counters()
        assert c.get("band.jobs", 0) > 0
        assert c.get("poa.cells.banded", 0) > 0
    finally:
        obs.reset()


def test_poa_banded_fault_drill_exhausts_ladder(tmp_path, monkeypatch):
    """Armed band.hit fault through the consensus driver: every banded
    window widens RACON_TPU_BAND_MAX_WIDENINGS times, takes the
    banded -> flat edge, and still polishes byte-identically."""
    from racon_tpu.resilience import faults

    target = _polish_dataset(tmp_path)
    monkeypatch.setenv("RACON_TPU_PALLAS", "1")
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "4")

    monkeypatch.setenv("RACON_TPU_BAND", "0")
    flat = _polish(tmp_path)

    try:
        monkeypatch.setenv("RACON_TPU_BAND", "1")
        monkeypatch.setenv("RACON_TPU_BAND_SLACK", "8")
        monkeypatch.setenv("RACON_TPU_BAND_MAX_WIDENINGS", "2")
        monkeypatch.setenv("RACON_TPU_FAULT", "band.hit")
        monkeypatch.setenv("RACON_TPU_METRICS", "1")
        faults.reset()
        banded = _polish(tmp_path)
        assert [s for _, s in banded] == [s for _, s in flat]
        c = _counters()
        jobs = c.get("band.jobs", 0)
        assert jobs > 0
        # every banded window: 2 widenings then the fallback edge
        assert c.get("band.widenings") == 2 * jobs
        assert c.get("band.fallbacks") == jobs
        assert c.get("band.hits") == 3 * jobs
    finally:
        obs.reset()
        faults.reset()


# ------------------------------------------------------------ bench stamp


def test_bench_band_stamp_and_normalize_entry():
    """bench.py's banded-evidence stamp: (cells_banded, band_hit_rate)
    from a counter snapshot, explicit double-None when banding never
    engaged; normalize_entry backfills both keys on pre-banding logs."""
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        import bench
    finally:
        sys.path.remove(root)

    snap = {"counters": {"band.jobs": 8, "band.hits": 2,
                         "align.cells.banded": 1000,
                         "poa.cells.banded": 2000}}
    cells, rate = bench.band_stamp(snap)
    assert cells == {"align": 1000, "poa": 2000}
    assert rate == 0.25
    # banding on, zero hits: a measured 0.0, not "not measured"
    assert bench.band_stamp({"counters": {"band.jobs": 3}}) == (None, 0.0)
    assert bench.band_stamp({"counters": {}}) == (None, None)
    assert bench.band_stamp(None) == (None, None)

    old = bench.normalize_entry({"value": 1.0})
    assert old["cells_banded"] is None and old["band_hit_rate"] is None
    fresh = {"value": 1.0, "cells_banded": {"align": 5}, "band_hit_rate": 0.1,
             "cost_model": None, "pack_split": None, "serial_steps": None,
             "peak_rss_mb": None, "budget_mb": None}
    assert bench.normalize_entry(dict(fresh)) == fresh
