"""Feeder tests: the vectorized _pack and the >=2-deep in-flight queue.

The reference fills accelerator batches continuously in C++ while kernels
execute (/root/reference/src/cuda/cudapolisher.cpp:83-145); this driver's
analogue is a numpy gather/scatter pack plus a configurable-depth queue of
async-dispatched chunks. These tests pin the pack against a plain
per-slice loop (the shape the reference's add_window marshalling takes,
src/cuda/cudabatch.cpp:141-198) and run the polisher end-to-end at a
deeper queue setting.
"""

import random

import numpy as np
import pytest

from racon_tpu.ops import poa, poa_driver
from racon_tpu.ops.encoding import encode
from racon_tpu.pipeline import WindowExport


def _naive_pack(chunk, cfg, pad_to):
    """The original per-layer-slice packing loop, kept as the oracle."""
    B = pad_to
    bb = np.zeros((B, cfg.max_backbone), dtype=np.uint8)
    bbw = np.zeros((B, cfg.max_backbone), dtype=np.int32)
    bb_len = np.ones(B, dtype=np.int32)
    n_layers = np.zeros(B, dtype=np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), dtype=np.int32)
    lens = np.zeros((B, cfg.depth), dtype=np.int32)
    begins = np.zeros((B, cfg.depth), dtype=np.int32)
    ends = np.zeros((B, cfg.depth), dtype=np.int32)
    for bi, (i, wx, keep) in enumerate(chunk):
        L = len(wx.backbone)
        bb[bi, :L] = encode(wx.backbone)
        bbw[bi, :L] = wx.backbone_weights
        bb_len[bi] = L
        n_layers[bi] = len(keep)
        offsets = np.concatenate([[0], np.cumsum(wx.lens)]).astype(np.int64)
        for li, j in enumerate(keep):
            ll = int(wx.lens[j])
            seqs[bi, li, :ll] = encode(wx.bases[offsets[j]:offsets[j] + ll])
            ws[bi, li, :ll] = wx.weights[offsets[j]:offsets[j] + ll]
            lens[bi, li] = ll
            begins[bi, li] = wx.begins[j]
            ends[bi, li] = wx.ends[j]
    return (bb, bbw, bb_len, n_layers, seqs, ws, lens, begins, ends)


def _random_export(rng, index, n_layers, bb_len, max_len):
    lens = np.array([rng.randrange(1, max_len + 1) for _ in range(n_layers)],
                    dtype=np.uint32)
    total = int(lens.sum())
    bases = np.frombuffer(
        bytes(rng.choice(b"ACGTN") for _ in range(total)),
        dtype=np.uint8).copy()
    weights = np.array([rng.randrange(0, 60) for _ in range(total)],
                       dtype=np.uint8)
    backbone = np.frombuffer(
        bytes(rng.choice(b"ACGT") for _ in range(bb_len)),
        dtype=np.uint8).copy()
    return WindowExport(
        index=index, rank=0, target_id=0, is_tgs=True,
        backbone=backbone,
        backbone_weights=np.zeros(bb_len, np.uint8),
        lens=lens,
        begins=np.array([rng.randrange(0, bb_len) for _ in range(n_layers)],
                        dtype=np.uint32),
        ends=np.array([bb_len - 1] * n_layers, dtype=np.uint32),
        bases=bases, weights=weights)


def test_vectorized_pack_matches_naive_loop():
    """Mixed chunk: full keeps, dropped (oversized) layers, truncated-at-
    DEPTH_CAP keeps, an empty-keep window, and padding rows."""
    rng = random.Random(13)
    cfg = poa.PoaConfig(max_nodes=384, max_len=64, max_backbone=128,
                        max_edges=12, depth=6, match=5, mismatch=-4, gap=-8)
    chunk = []
    # window 0: all layers kept
    wx = _random_export(rng, 0, 4, 100, cfg.max_len)
    chunk.append((0, wx, list(range(4))))
    # window 1: layer 1 dropped (as if oversized) -> ragged keep indices
    wx = _random_export(rng, 1, 5, 90, cfg.max_len)
    chunk.append((1, wx, [0, 2, 3, 4]))
    # window 2: keep truncated below the layer count (depth cap analogue)
    wx = _random_export(rng, 2, 6, 80, cfg.max_len)
    chunk.append((2, wx, [0, 1, 2, 3, 4, 5][:cfg.depth - 2]))
    # window 3: nothing kept
    wx = _random_export(rng, 3, 3, 70, cfg.max_len)
    chunk.append((3, wx, []))

    got = poa_driver._pack(chunk, cfg, 6)     # 2 padding rows
    want = _naive_pack(chunk, cfg, 6)
    names = ("bb", "bbw", "bb_len", "n_layers", "seqs", "ws", "lens",
             "begins", "ends")
    for name, g, w in zip(names, got, want):
        np.testing.assert_array_equal(g, w, err_msg=name)


@pytest.mark.parametrize("depth", ["1", "3"])
def test_polish_correct_at_any_pipeline_depth(tmp_path, monkeypatch, depth):
    """End-to-end polish with the queue at depth 1 and 3 — results must be
    identical to the single-slot behavior (ordering-independent install).

    The target is long enough (30 windows vs the mesh-rounded batch of 8)
    that the bucket splits into several chunks, so the deque really holds
    multiple in-flight entries at depth 3 — asserted via a dispatch
    counter, not assumed."""
    import racon_tpu

    rng = random.Random(5)
    target = "".join(rng.choice("ACGT") for _ in range(3000))
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{target}\n")
    with open(tmp_path / "r.fasta", "w") as f:
        for i in range(4):
            f.write(f">r{i}\n{target}\n")
    with open(tmp_path / "o.sam", "w") as f:
        f.write("@HD\tVN:1.6\n")
        for i in range(4):
            f.write(f"r{i}\t0\tt\t1\t60\t{len(target)}M\t*\t0\t0\t{target}"
                    f"\t*\n")

    submits = []
    real_submit = poa_driver._submit

    def counting_submit(kernel, packed, use_pallas, banded=False):
        submits.append(1)
        return real_submit(kernel, packed, use_pallas, banded)

    monkeypatch.setenv("RACON_TPU_PALLAS", "0")
    # v2 kind: the ls tier rounds the batch up to G*n_dev=64, which would
    # swallow all 30 windows into a single chunk
    monkeypatch.setenv("RACON_TPU_POA_KERNEL", "v2")
    monkeypatch.setenv("RACON_TPU_PIPELINE_DEPTH", depth)
    monkeypatch.setenv("RACON_TPU_BATCH_WINDOWS", "1")  # several chunks
    monkeypatch.setattr(poa_driver, "_submit", counting_submit)
    p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                              str(tmp_path / "o.sam"),
                              str(tmp_path / "t.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(submits) > int(depth), \
        "scenario too small to exercise the in-flight queue"
    assert len(res) == 1
    assert res[0][1] == target
