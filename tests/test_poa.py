"""Host POA engine tests: consensus recovery on synthetic windows.

Reference behavior model: /root/reference/src/window.cpp:65-149 (POA over a
backbone plus layers, quality weighting, TGS trim)."""

import random

from racon_tpu import native


def mutate(seq: bytes, rate: float, rng: random.Random) -> bytes:
    out = bytearray()
    bases = b"ACGT"
    for c in seq:
        r = rng.random()
        if r < rate / 3:
            out.append(rng.choice(bases))
        elif r < 2 * rate / 3:
            pass
        elif r < rate:
            out.append(c)
            out.append(rng.choice(bases))
        else:
            out.append(c)
    return bytes(out)


def test_fewer_than_two_layers_returns_backbone():
    bb = b"ACGTACGTACGT"
    cons, polished = native.window_consensus(bb, [b"ACGTACGTACGT"])
    assert cons == bb
    assert polished is False


def test_identical_layers_reproduce_truth():
    rng = random.Random(3)
    truth = bytes(rng.choice(b"ACGT") for _ in range(200))
    layers = [truth] * 5
    cons, polished = native.window_consensus(truth, layers, trim=False)
    assert polished is True
    assert cons == truth


def test_noisy_layers_recover_truth():
    rng = random.Random(11)
    truth = bytes(rng.choice(b"ACGT") for _ in range(500))
    backbone = mutate(truth, 0.10, rng)
    layers = [mutate(truth, 0.10, rng) for _ in range(20)]
    cons, polished = native.window_consensus(backbone, layers, trim=False)
    assert polished is True
    # POA consensus over 20 noisy copies should be far closer to the truth
    # than any single 10%-error layer.
    d = native.edit_distance(cons, truth)
    assert d < 0.02 * len(truth), d


def test_quality_weighting_prefers_confident_bases():
    # Two variants at one site; the minority variant carries much higher
    # quality, so weighted consensus should pick it.
    truth_a = b"ACGTACGTGGACGTACGTAA" * 5
    truth_c = truth_a.replace(b"GG", b"CC")
    layers = [truth_a, truth_a, truth_c, truth_c, truth_c]
    quals = [bytes([33 + 1] * len(truth_a))] * 2 + \
        [bytes([33 + 40] * len(truth_c))] * 3
    cons, _ = native.window_consensus(truth_a, layers, quals=quals, trim=False)
    assert b"CC" in cons


def test_tgs_trim_cuts_uncovered_ends():
    rng = random.Random(5)
    truth = bytes(rng.choice(b"ACGT") for _ in range(300))
    # Layers only cover the middle 200 bases.
    mid = truth[50:250]
    layers = [mutate(mid, 0.05, rng) for _ in range(10)]
    begins = [50] * len(layers)
    ends = [249] * len(layers)
    cons_trim, _ = native.window_consensus(
        truth, layers, begins=begins, ends=ends, tgs=True, trim=True)
    cons_notrim, _ = native.window_consensus(
        truth, layers, begins=begins, ends=ends, tgs=True, trim=False)
    assert len(cons_trim) < len(cons_notrim)
    assert len(cons_trim) <= 220
    assert native.edit_distance(cons_trim, mid) < 0.05 * len(mid)
