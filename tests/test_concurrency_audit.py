"""Concurrency & contract auditor (racon_tpu/analysis/concurrency).

Each detector is proven on a seeded fixture mini-tree under
tests/analysis_fixtures/concurrency/ (firing exactly once), and the
real tree is proven clean — the acceptance gate CI runs via
`python -m racon_tpu.analysis --concurrency --contracts`.
"""

import os

from racon_tpu.analysis.__main__ import main as analysis_main
from racon_tpu.analysis.concurrency import contracts, locks
from racon_tpu.analysis.concurrency.model import Model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXROOT = os.path.join(REPO, "tests", "analysis_fixtures", "concurrency")


# ------------------------------------------------- seeded fixture trees

def test_unguarded_mutation_fires_exactly_once():
    vs = locks.audit(os.path.join(FIXROOT, "races"))
    assert [v.rule for v in vs] == ["unguarded-mutation"], \
        [v.render() for v in vs]
    msg = vs[0].message
    assert "Counter.n" in msg
    assert "serve-conn" in msg and "main" in msg


def test_lock_order_cycle_fires_exactly_once():
    vs = locks.audit(os.path.join(FIXROOT, "lockcycle"))
    assert [v.rule for v in vs] == ["lock-order-cycle"], \
        [v.render() for v in vs]
    assert "Pair._a" in vs[0].message and "Pair._b" in vs[0].message


def test_missing_lattice_drill_fires_exactly_once():
    vs = contracts.audit(os.path.join(FIXROOT, "lattice"))
    assert [v.rule for v in vs] == ["lattice-drill"], \
        [v.render() for v in vs]
    assert "fast" in vs[0].message and "slow" in vs[0].message


def test_protocol_field_mismatch_fires_exactly_once():
    vs = contracts.audit(os.path.join(FIXROOT, "protocol"))
    assert [v.rule for v in vs] == ["protocol-mismatch"], \
        [v.render() for v in vs]
    assert "extra" in vs[0].message and "'ping'" in vs[0].message


def test_fixture_waiver_silences_the_finding(tmp_path):
    """A `# concurrency:` waiver on the mutation line kills the races
    finding — the documented escape hatch works end to end."""
    src = os.path.join(FIXROOT, "races", "racon_tpu", "svc.py")
    with open(src) as f:
        text = f.read()
    fixroot = tmp_path / "tree"
    pkg = fixroot / "racon_tpu"
    pkg.mkdir(parents=True)
    (pkg / "svc.py").write_text(text.replace(
        "self.n = self.n + 1  # unguarded",
        "self.n = self.n + 1  # concurrency: test waiver —"))
    assert locks.audit(str(fixroot)) == []


# ------------------------------------------------------ real-tree gates

def test_real_tree_lock_discipline_clean():
    assert [v.render() for v in locks.audit(REPO)] == []


def test_real_tree_contracts_clean():
    assert [v.render() for v in contracts.audit(REPO)] == []


def test_real_tree_lock_order_digraph_acyclic():
    """Stronger than 'no cycle finding': the digraph over serve +
    distrib + polisher locks exists (locks ARE nested somewhere) and
    every SCC is trivial."""
    m = Model.build(REPO)
    assert m.acquires, "no lock acquisitions modeled — model regression?"
    assert locks._lock_order_cycles(m) == []


def test_cli_selected_audits_exit_zero():
    assert analysis_main(["--concurrency", "--contracts",
                          "--repo-root", REPO]) == 0


# -------------------------------------------------- baseline round-trip

def test_fixture_findings_respect_baseline(tmp_path):
    """Audit findings flow through the same fingerprint/baseline gate
    as lint: non-zero without a baseline, zero once accepted."""
    root = os.path.join(FIXROOT, "races")
    base = str(tmp_path / "baseline.json")
    assert analysis_main(["--concurrency", "--repo-root", root]) == 1
    assert analysis_main(["--concurrency", "--repo-root", root,
                          "--write-baseline", "--baseline", base]) == 0
    assert analysis_main(["--concurrency", "--repo-root", root,
                          "--baseline", base]) == 0
