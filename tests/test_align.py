"""Host aligner unit tests: Myers edit distance and banded NW CIGAR against a
naive O(nm) reference DP."""

import random

import pytest

from racon_tpu import native


def naive_edit_distance(q: bytes, t: bytes) -> int:
    n, m = len(q), len(t)
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        for j in range(1, m + 1):
            cur[j] = min(prev[j - 1] + (q[i - 1] != t[j - 1]),
                         prev[j] + 1, cur[j - 1] + 1)
        prev = cur
    return prev[m]


def mutate(seq: bytes, rate: float, rng: random.Random) -> bytes:
    out = bytearray()
    bases = b"ACGT"
    for c in seq:
        r = rng.random()
        if r < rate / 3:
            out.append(rng.choice(bases))  # substitution
        elif r < 2 * rate / 3:
            pass  # deletion
        elif r < rate:
            out.append(c)
            out.append(rng.choice(bases))  # insertion
        else:
            out.append(c)
    return bytes(out)


def cigar_consumed(cigar: str):
    q = t = 0
    num = ""
    for ch in cigar:
        if ch.isdigit():
            num += ch
        else:
            n = int(num)
            num = ""
            if ch in "MI":
                q += n
            if ch in "MD":
                t += n
    return q, t


def cigar_cost_upper_bound(cigar: str, q: bytes, t: bytes) -> int:
    """Edit cost of the alignment path described by the CIGAR."""
    cost = 0
    qi = ti = 0
    num = ""
    for ch in cigar:
        if ch.isdigit():
            num += ch
            continue
        n = int(num)
        num = ""
        if ch == "M":
            for _ in range(n):
                cost += q[qi] != t[ti]
                qi += 1
                ti += 1
        elif ch == "I":
            cost += n
            qi += n
        elif ch == "D":
            cost += n
            ti += n
    return cost


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("rate", [0.05, 0.2, 0.4])
def test_edit_distance_matches_naive(seed, rate):
    rng = random.Random(seed)
    a = bytes(rng.choice(b"ACGT") for _ in range(rng.randint(50, 300)))
    b = mutate(a, rate, rng)
    assert native.edit_distance(a, b) == naive_edit_distance(a, b)


def test_edit_distance_long_multiblock():
    rng = random.Random(7)
    a = bytes(rng.choice(b"ACGT") for _ in range(1000))
    b = mutate(a, 0.15, rng)
    assert native.edit_distance(a, b) == naive_edit_distance(a, b)


def test_edit_distance_trivial():
    assert native.edit_distance(b"", b"ACGT") == 4
    assert native.edit_distance(b"ACGT", b"") == 4
    assert native.edit_distance(b"ACGT", b"ACGT") == 0


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_cigar_is_optimal_path(seed):
    rng = random.Random(seed)
    a = bytes(rng.choice(b"ACGT") for _ in range(rng.randint(100, 500)))
    b = mutate(a, 0.2, rng)
    cigar = native.align_cigar(a, b)
    qc, tc = cigar_consumed(cigar)
    assert qc == len(a) and tc == len(b)
    # The path's cost must equal the optimal edit distance.
    assert cigar_cost_upper_bound(cigar, a, b) == naive_edit_distance(a, b)


def test_cigar_empty_inputs():
    assert native.align_cigar(b"", b"AC") == "2D"
    assert native.align_cigar(b"AC", b"") == "2I"
