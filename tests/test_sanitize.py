"""Runtime invariant sanitizer (racon_tpu/analysis/sanitize.py).

Contracts:
* armed runs are byte-identical to unarmed runs (the sanitizer observes,
  never alters) and a clean tree produces zero findings;
* each detector fires on its injected fault (`sanitize.nan`,
  `sanitize.stats`) with the polished output still untouched;
* the kernel-cache hook keys on device topology (fresh kernel on a
  topology change, stale entries never served);
* `--sanitize-report` renders report JSON with lint-style exit codes.
"""

import json
import random
import threading

import numpy as np
import pytest

import racon_tpu
from racon_tpu.analysis import sanitize
from racon_tpu.analysis.__main__ import main as analysis_main


@pytest.fixture(autouse=True)
def _fresh_findings():
    sanitize.reset()
    yield
    sanitize.reset()


# ------------------------------------------------------------- unit: records

def test_record_dedup_and_cap():
    for _ in range(3):
        sanitize.record("nonfinite", "k[out 0]", "nan")
    fs = sanitize.findings()
    assert len(fs) == 1 and fs[0].count == 3
    for i in range(2 * sanitize._MAX_FINDINGS):
        sanitize.record("parity", f"w{i}", "d")
    assert len(sanitize.findings()) <= sanitize._MAX_FINDINGS + 1
    sanitize.reset()
    assert sanitize.findings() == []


def test_enabled_follows_knob(monkeypatch):
    assert not sanitize.enabled()
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    assert sanitize.enabled()


# ------------------------------------------------------- unit: kernel proxy

def test_wrap_kernel_flags_nonfinite_output():
    def kernel(x):
        return (np.array([1.0, np.nan], dtype=np.float32),
                np.array([3], dtype=np.int32))

    proxied = sanitize.wrap_kernel("build_fake", kernel)
    out = proxied(None)
    assert np.isnan(out[0][1])  # output passes through unchanged
    assert [f.kind for f in sanitize.findings()] == ["nonfinite"]
    assert "build_fake" in sanitize.findings()[0].where


def test_wrap_kernel_transitively_wraps_factories():
    def factory():
        return lambda: np.array([np.inf], dtype=np.float32)

    proxied = sanitize.wrap_kernel("build_factory", factory)
    proxied()()
    assert [f.kind for f in sanitize.findings()] == ["nonfinite"]


def test_wrap_kernel_clean_outputs_record_nothing():
    def kernel():
        return (np.zeros(4, dtype=np.float32), np.zeros(4, dtype=np.uint8))

    sanitize.wrap_kernel("build_ok", kernel)()
    assert sanitize.findings() == []


# ------------------------------------------------------ unit: seam checkers

def test_check_align_outputs_flags_out_of_band_code_on_served_row():
    ops = np.array([[0, 1, 2, 0], [3, 3, 3, 3]], dtype=np.uint8)
    cnt = np.array([4, 4], dtype=np.int32)
    # row 1 carries code 3 but is not served (ok False): legal
    sanitize.check_align_outputs(ops, cnt, np.array([True, False]), "t")
    assert sanitize.findings() == []
    # the same row served: violation
    sanitize.check_align_outputs(ops, cnt, np.array([True, True]), "t")
    assert [f.kind for f in sanitize.findings()] == ["cigar-op-range"]


def test_check_consensus_outputs_flags_bad_rows():
    cons_base = np.array([[0, 1, 2, 3], [0, 9, 0, 0]], dtype=np.int32)
    cons_cov = np.ones_like(cons_base)
    cons_len = np.array([4, 3], dtype=np.int32)
    failed = np.array([0, 0], dtype=np.int32)
    sanitize.check_consensus_outputs(
        (cons_base, cons_cov, cons_len, failed), [0, 1], "t")
    kinds = [f.kind for f in sanitize.findings()]
    assert kinds == ["consensus-range"]  # base code 9 on row 1

    sanitize.reset()
    sanitize.check_consensus_outputs(
        (cons_base, cons_cov, np.array([4, 99]), failed), [0, 1], "t")
    assert any("cons_len" in f.detail for f in sanitize.findings())

    sanitize.reset()
    sanitize.check_consensus_outputs(
        (cons_base[:1], cons_cov[:1], cons_len[:1], failed[:1]), [0], "t")
    assert sanitize.findings() == []


def test_check_consensus_nan_fault_poisons_copy_only(monkeypatch):
    monkeypatch.setenv("RACON_TPU_FAULT", "sanitize.nan")
    from racon_tpu.resilience import faults
    faults.reset()
    cons_base = np.zeros((1, 4), dtype=np.int32)
    sanitize.check_consensus_outputs(
        (cons_base, cons_base, np.array([4]), np.array([0])), [0], "t")
    assert [f.kind for f in sanitize.findings()] == ["nonfinite"]
    assert (cons_base == 0).all()  # the driver's array is untouched


def test_check_parity():
    sanitize.check_parity(b"ACGT", b"ACGT", 0, "t")
    sanitize.check_parity("ACGT", b"ACGT", 1, "t")
    assert sanitize.findings() == []
    sanitize.check_parity(b"ACGT", b"ACGA", 2, "t")
    assert [f.kind for f in sanitize.findings()] == ["parity"]


def test_parity_stride_parses_and_gates(monkeypatch):
    monkeypatch.setenv("RACON_TPU_SANITIZE_PARITY", "4")
    assert sanitize.parity_stride() == 4
    assert sanitize.parity_due(8) and not sanitize.parity_due(9)
    monkeypatch.setenv("RACON_TPU_SANITIZE_PARITY", "0")
    assert not sanitize.parity_due(0)
    monkeypatch.setenv("RACON_TPU_SANITIZE_PARITY", "bogus")
    assert sanitize.parity_stride() == 0


# ------------------------------------------------------- unit: stats guard

def test_guarded_stats_flags_cross_thread_writes():
    g = sanitize.GuardedStats({"device": 0}, "t")
    g["device"] = 1          # owner thread: fine
    assert sanitize.findings() == []
    t = threading.Thread(target=g.__setitem__, args=("device", 2))
    t.start()
    t.join()
    assert g["device"] == 2  # the write itself is never blocked
    assert [f.kind for f in sanitize.findings()] == ["racy-stats"]


def test_guard_stats_passthrough_when_disarmed():
    d = {"x": 1}
    assert sanitize.guard_stats(d, "t") is d


def test_guard_stats_wraps_when_armed(monkeypatch):
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    g = sanitize.guard_stats({"x": 1}, "t")
    assert isinstance(g, sanitize.GuardedStats) and g["x"] == 1


# --------------------------------------------- kernel cache: topology keyed

def test_device_keyed_cache_topology_change_builds_fresh(monkeypatch):
    import jax

    from racon_tpu.ops.kernel_cache import device_keyed_cache

    builds = []

    @device_keyed_cache(maxsize=8)
    def build(cap):
        builds.append(cap)
        return object()  # unique sentinel per build

    class Dev:
        def __init__(self, platform):
            self.platform = platform

    monkeypatch.setattr(jax, "devices", lambda: [Dev("cpu")] * 8)
    k8 = build(100)
    assert build(100) is k8 and builds == [100]

    # fewer devices: a fresh kernel, never the stale 8-device one
    monkeypatch.setattr(jax, "devices", lambda: [Dev("cpu")] * 4)
    k4 = build(100)
    assert k4 is not k8 and len(builds) == 2

    # platform change at the same count: fresh again
    monkeypatch.setattr(jax, "devices", lambda: [Dev("tpu")] * 4)
    kt = build(100)
    assert kt is not k4 and kt is not k8 and len(builds) == 3

    # returning to the original topology serves its cached entry
    monkeypatch.setattr(jax, "devices", lambda: [Dev("cpu")] * 8)
    assert build(100) is k8 and len(builds) == 3


def test_device_keyed_cache_returns_proxy_when_armed(monkeypatch):
    import jax

    from racon_tpu.ops.kernel_cache import device_keyed_cache

    @device_keyed_cache(maxsize=4)
    def build():
        return lambda: np.array([np.nan], dtype=np.float32)

    class Dev:
        platform = "cpu"

    monkeypatch.setattr(jax, "devices", lambda: [Dev()])
    monkeypatch.setenv("RACON_TPU_SANITIZE", "1")
    build()()
    assert [f.kind for f in sanitize.findings()] == ["nonfinite"]


# ----------------------------------------------------------- e2e: polishing

def _write_dataset(tmp_path, n_targets=3, n_reads=4):
    """Identical-read SAM dataset (as in test_faults): every window's
    consensus is exactly the target, so host and device recomputes agree
    and byte-identity is checkable against the CPU oracle."""
    rng = random.Random(11)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.sam", "w") as of:
        of.write("@HD\tVN:1.6\n")
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t0\tt{t}\t1\t60\t200M\t*\t0\t0\t"
                         f"{seq}\t*\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.sam"),
            str(tmp_path / "targets.fasta"))


_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)


def _oracle(paths):
    p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    p.initialize()
    return p.polish(True)


def _tpu_run(paths, monkeypatch, env):
    base = {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
            "RACON_TPU_BATCH_WINDOWS": "8"}
    for k, v in {**base, **env}.items():
        monkeypatch.setenv(k, v)
    p = racon_tpu.create_polisher(*paths, backend="tpu", **_ARGS)
    p.initialize()
    res = p.polish(True)
    return res, p


def test_armed_run_byte_identical_and_clean(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch, {"RACON_TPU_SANITIZE": "1"})
    assert res == oracle
    section = p.report.as_dict()["sanitize"]
    assert section["armed"] is True
    assert section["findings"] == []


def test_armed_run_parity_every_window(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_SANITIZE": "1",
                       "RACON_TPU_SANITIZE_PARITY": "1"})
    assert res == oracle
    assert p.report.as_dict()["sanitize"]["findings"] == []


def test_unarmed_report_says_disarmed(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path, n_targets=1, n_reads=2)
    _, p = _tpu_run(paths, monkeypatch, {})
    section = p.report.as_dict()["sanitize"]
    assert section["armed"] is False and section["findings"] == []


def test_nan_fault_caught_output_untouched(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_SANITIZE": "1",
                       "RACON_TPU_FAULT": "sanitize.nan"})
    assert res == oracle  # detector-only poisoning, polish unaffected
    kinds = {f["kind"] for f in p.report.as_dict()["sanitize"]["findings"]}
    assert "nonfinite" in kinds


def test_stats_fault_caught(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    oracle = _oracle(paths)
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_SANITIZE": "1",
                       "RACON_TPU_FAULT": "sanitize.stats"})
    assert res == oracle
    kinds = {f["kind"] for f in p.report.as_dict()["sanitize"]["findings"]}
    assert "racy-stats" in kinds


# -------------------------------------------------- CLI: --sanitize-report

def _report_json(tmp_path, findings, armed=True):
    path = tmp_path / "report.json"
    path.write_text(json.dumps(
        {"sanitize": {"armed": armed, "findings": findings}}))
    return str(path)


def test_cli_sanitize_report_clean(tmp_path, capsys):
    rc = analysis_main(["--sanitize-report", _report_json(tmp_path, [])])
    assert rc == 0
    assert "SANITIZE OK" in capsys.readouterr().out


def test_cli_sanitize_report_findings_fail(tmp_path, capsys):
    rc = analysis_main(["--sanitize-report", _report_json(tmp_path, [
        {"kind": "parity", "where": "poa._install[xla]",
         "detail": "window 8: device != host", "count": 2}])])
    assert rc == 1
    out = capsys.readouterr().out
    assert "SANITIZE FAIL" in out and "parity" in out and "x2" in out


def test_cli_sanitize_report_json_mode(tmp_path, capsys):
    rc = analysis_main(["--json", "--sanitize-report",
                        _report_json(tmp_path, [])])
    assert rc == 0
    assert json.loads(capsys.readouterr().out) == {"armed": True,
                                                   "findings": []}


def test_cli_sanitize_report_unreadable_or_legacy(tmp_path):
    assert analysis_main(["--sanitize-report",
                          str(tmp_path / "missing.json")]) == 2
    legacy = tmp_path / "legacy.json"
    legacy.write_text("{}")
    assert analysis_main(["--sanitize-report", str(legacy)]) == 2
